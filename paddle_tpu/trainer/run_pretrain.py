"""One-command pretrain driver (VERDICT r4 item 5; ref: PaddleNLP
``llm/run_pretrain.py`` — the north star's named entry point: data ->
hybrid-parallel train loop -> checkpoint, SURVEY §2.4 row 2).

    python -m paddle_tpu.trainer.run_pretrain --config cfg.json

composes the framework's own pieces end to end:
  * text corpus -> in-tree BPE tokenizer (``text.train_bpe``; vocab cached
    beside the checkpoints) -> fixed-length windows, or a pre-tokenized
    ``.npy``/``.npz`` token stream, or seeded synthetic tokens,
  * ``io.DataLoader`` + ``io.DistributedBatchSampler`` (seeded, epoch
    reshuffle; every process draws the IDENTICAL global batch, the
    ``global_device_put`` contract that feeds the dp/sharding axes),
  * ``build_llama_pretrain_step`` over the ``make_hybrid_mesh_for`` mesh
    (dp/mp/pp/sharding/sep from the config's ``parallel`` table — the
    hybrid_configs equivalent),
  * per-step loss + tokens/s + MFU logging (jsonl, resumable-comparable),
  * sharded checkpoint save every ``save_interval`` steps
    (``distributed.checkpoint``: per-shard .npy + reshard-on-load) with
    AUTO-RESUME: restart with the same command and training continues
    from the last checkpoint — data order, optimizer moments and step
    count restored; SIGTERM triggers an emergency checkpoint.

Chip invocation (flagship shard; docs/FLAGSHIP.md has the recipe context):

    python -m paddle_tpu.trainer.run_pretrain --config - <<'JSON'
    {"model": {"preset": "llama3_8b_shard"}, "seq_len": 8192,
     "global_batch": 3, "max_steps": 50, "remat": "none",
     "scan_layers": false, "ce_chunks": 2, "save_interval": 25,
     "output_dir": "/tmp/pretrain_8b"}
    JSON
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import time

import numpy as np

__all__ = ["main", "run"]

DEFAULTS = {
    "model": {"preset": "tiny"},
    "data": {"corpus": None, "vocab_size": 512},
    "seq_len": 128,
    "global_batch": 8,
    "n_microbatches": 1,
    "max_steps": 50,
    "lr": 3e-4,
    "weight_decay": 0.1,
    "grad_clip": 1.0,
    "parallel": {"dp": 1, "mp": 1, "pp": 1, "sharding": 1, "sep": 1},
    "remat": "full",
    "scan_layers": True,
    "ce_chunks": 4,
    "pp_schedule": "compiled",
    "log_interval": 1,
    "save_interval": 50,
    "output_dir": "pretrain_out",
    "seed": 1234,
    # optional predictive OOM gate (auto-tuner trials, SURVEY §2.3 P12):
    # AOT-compile the step and refuse to run if XLA's own memory
    # accounting (args + temps + output, per device) exceeds this budget
    # — the same accounting the TPU runtime uses when it refuses an
    # allocation, surfaced BEFORE burning a trial
    "hbm_budget_bytes": None,
}


def _load_config(path: str) -> dict:
    raw = sys.stdin.read() if path == "-" else open(path).read()
    cfg = dict(DEFAULTS)
    user = json.loads(raw)
    for k, v in user.items():
        if isinstance(v, dict) and isinstance(cfg.get(k), dict):
            cfg[k] = {**cfg[k], **v}
        else:
            cfg[k] = v
    return cfg


def _build_model_config(spec: dict, seq_len: int):
    from ..models.llama import (LlamaConfig, llama3_8b_shard_config,
                                llama_tiny_config)
    spec = dict(spec)
    preset = spec.pop("preset", None)
    if preset == "llama3_8b_shard":
        return llama3_8b_shard_config(mp=8, pp=4,
                                      max_position_embeddings=seq_len,
                                      sequence_parallel=False,
                                      fuse_attention_qkv=True,
                                      fuse_attention_ffn=True, **spec)
    if preset == "tiny":
        spec.setdefault("max_position_embeddings", seq_len)
        return llama_tiny_config(**spec)
    spec.setdefault("max_position_embeddings", seq_len)
    return LlamaConfig(**spec)


def _token_stream(data_cfg: dict, vocab_size_needed: int, out_dir: str,
                  seed: int):
    """Return (tokens int32 1-D numpy, vocab_size). Three sources:
    synthetic (corpus None), pre-tokenized .npy/.npz, or a text file
    tokenized by the in-tree BPE (vocab trained once, cached)."""
    corpus = data_cfg.get("corpus")
    if corpus is None:
        rng = np.random.RandomState(seed)
        n = int(data_cfg.get("synthetic_tokens", 200_000))
        return (rng.randint(0, vocab_size_needed, n).astype(np.int32),
                vocab_size_needed)
    if corpus.endswith((".npy", ".npz")):
        arr = np.load(corpus, mmap_mode="r")
        if hasattr(arr, "files"):
            arr = arr[arr.files[0]]
        return np.asarray(arr, np.int32).reshape(-1), vocab_size_needed
    # text corpus -> BPE; only the COORDINATOR trains/writes the cached
    # vocab (atomic tmp+rename), other ranks wait for it — concurrent
    # writers would race on the shared file
    import jax
    from ..text import BPETokenizer, train_bpe
    vs = int(data_cfg.get("vocab_size", 512))
    cache = os.path.join(out_dir, "bpe_tokenizer.json")
    text = open(corpus, encoding="utf-8").read()
    if not os.path.exists(cache):
        if jax.process_index() == 0:
            vocab, merges = train_bpe([text], vocab_size=vs)
            os.makedirs(out_dir, exist_ok=True)
            with open(cache + ".tmp", "w") as f:
                json.dump({"vocab": vocab, "merges": list(merges)}, f)
            os.replace(cache + ".tmp", cache)
        else:
            deadline = time.time() + 300
            while not os.path.exists(cache):
                if time.time() > deadline:
                    raise TimeoutError(
                        "waiting for the coordinator's bpe_tokenizer.json")
                time.sleep(0.2)
    spec = json.load(open(cache))
    tok = BPETokenizer(spec["vocab"], [tuple(m) for m in spec["merges"]])
    ids = np.asarray(tok.encode(text), np.int32)
    return ids, max(vs, int(ids.max()) + 1)


class _WindowDataset:
    """Fixed-length next-token windows over the token stream."""

    def __init__(self, tokens: np.ndarray, seq_len: int):
        self.tokens = tokens
        self.seq = seq_len
        self.n = max(0, (len(tokens) - 1) // seq_len)

    def __len__(self):
        return self.n

    def __getitem__(self, i):
        s = i * self.seq
        ids = self.tokens[s:s + self.seq]
        labels = self.tokens[s + 1:s + self.seq + 1]
        return np.asarray(ids, np.int32), np.asarray(labels, np.int32)


def _flatten_state(state) -> dict:
    """TrainState -> flat {key: array} for the sharded checkpoint; keys
    come from tree paths so they are stable across rebuilds."""
    import jax
    flat = {}
    for name, tree in (("master", state.master), ("opt", state.opt_state)):
        for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
            key = name + "/" + "/".join(
                str(getattr(p, "key", getattr(p, "idx", p))) for p in path)
            flat[key] = leaf
    flat["step"] = state.step
    return flat


def _restore_state(state, flat: dict, param_dtype):
    """Rebuild a TrainState from the (loaded) flat dict, recomputing the
    compute params (bf16) from the master weights."""
    import jax
    from ..amp import decorate_tree
    from .pretrain import TrainState

    def refill(name, tree):
        paths, treedef = jax.tree_util.tree_flatten_with_path(tree)
        leaves = []
        for path, _ in paths:
            key = name + "/" + "/".join(
                str(getattr(p, "key", getattr(p, "idx", p))) for p in path)
            leaves.append(flat[key])
        return jax.tree_util.tree_unflatten(treedef, leaves)

    master = refill("master", state.master)
    opt = refill("opt", state.opt_state)
    params = decorate_tree(master, param_dtype)
    return TrainState(params, master, opt, flat["step"])


def _peak_flops() -> float:
    """Per-chip peak bf16 FLOP/s for the MFU log line (same table as
    bench.py; CPU smoke runs report against the v5e figure, labeled
    an estimate)."""
    import jax
    table = {"v5e": 197e12, "v5p": 459e12, "v4": 275e12, "v6e": 918e12}
    kind = jax.devices()[0].device_kind.lower()
    for k, v in table.items():
        if k in kind or ("v5 lite" in kind and k == "v5e"):
            return v
    return 197e12


def run(cfg: dict) -> int:
    # JAX_PLATFORMS env is honored by paddle_tpu._bootstrap at import
    # time (the axon PJRT plugin would otherwise outrank it)
    import jax
    import jax.numpy as jnp
    import paddle_tpu as paddle
    from ..distributed import checkpoint as dck
    from ..distributed.mesh import global_device_put
    from ..io import DataLoader, DistributedBatchSampler
    from .pretrain import (PretrainConfig, build_llama_pretrain_step,
                           flops_per_token, make_hybrid_mesh_for)

    out_dir = cfg["output_dir"]
    os.makedirs(out_dir, exist_ok=True)
    paddle.seed(cfg["seed"])
    # multi-process (launcher-driven) runs: every process executes the
    # same SPMD program over the GLOBAL mesh; only the coordinator writes
    # the shared log/pointer files (checkpoint shards are per-process by
    # design — distributed.checkpoint tags files by rank)
    is_coord = jax.process_index() == 0

    mc = _build_model_config(cfg["model"], cfg["seq_len"])
    tokens, data_vocab = _token_stream(cfg["data"], mc.vocab_size, out_dir,
                                       cfg["seed"])
    if data_vocab > mc.vocab_size:
        # XLA's gather CLAMPS out-of-range ids, so oversized token ids
        # would train silently on wrong embeddings — refuse instead
        raise SystemExit(
            f"tokenized corpus needs vocab_size >= {data_vocab} but the "
            f"model has {mc.vocab_size}; raise model.vocab_size (or lower "
            f"data.vocab_size)")
    ds = _WindowDataset(tokens, cfg["seq_len"])
    if len(ds) == 0:
        raise SystemExit("corpus too small for one window")

    par = cfg["parallel"]
    pcfg = PretrainConfig(
        mc, global_batch=cfg["global_batch"], seq_len=cfg["seq_len"],
        n_microbatches=cfg["n_microbatches"], lr=cfg["lr"],
        weight_decay=cfg["weight_decay"], grad_clip=cfg["grad_clip"],
        dp=par.get("dp", 1), mp=par.get("mp", 1), pp=par.get("pp", 1),
        sharding=par.get("sharding", 1), sep=par.get("sep", 1),
        remat=cfg["remat"], scan_layers=cfg["scan_layers"],
        ce_chunks=cfg["ce_chunks"], pp_schedule=cfg["pp_schedule"])
    mesh = make_hybrid_mesh_for(pcfg)
    state, jstep, meta = build_llama_pretrain_step(pcfg, mesh)
    fpt = flops_per_token(mc)

    # SPMD feeding contract: EVERY process draws the identical global
    # batch (num_replicas=1) and global_device_put scatters it onto the
    # dp/sharding submesh — the TPU-native replacement for per-rank NCCL
    # scatter (docs/MULTIHOST_TRAIN.json mechanism note)
    sampler = DistributedBatchSampler(ds, batch_size=cfg["global_batch"],
                                      num_replicas=1, rank=0, shuffle=True,
                                      drop_last=True)
    loader = DataLoader(ds, batch_sampler=sampler,
                        collate_fn=lambda b: (
                            np.stack([x[0] for x in b]),
                            np.stack([x[1] for x in b])))
    steps_per_epoch = len(sampler)
    if steps_per_epoch == 0:
        raise SystemExit("global_batch larger than the dataset")

    if cfg.get("hbm_budget_bytes"):
        spec = jax.ShapeDtypeStruct(
            (cfg["global_batch"], cfg["seq_len"]), jnp.int32,
            sharding=meta["data_sharding"])
        compiled = jstep.lower(state, spec, spec).compile()
        ma = compiled.memory_analysis()
        if ma is not None:
            # XLA's stats are PER-DEVICE (replicated args count at full
            # size on every device, sharded args at their shard size)
            need = (ma.argument_size_in_bytes + ma.temp_size_in_bytes
                    + ma.output_size_in_bytes)
            budget = int(cfg["hbm_budget_bytes"])
            print(f"[run_pretrain] memory estimate {need / 1e6:.1f} MB "
                  f"per device (budget {budget / 1e6:.1f} MB)", flush=True)
            if need > budget:
                raise MemoryError(
                    f"predicted per-device memory {need / 1e6:.1f} MB "
                    f"exceeds hbm_budget_bytes {budget / 1e6:.1f} MB")

    # ---- auto-resume -----------------------------------------------------
    start_step = 0
    latest = os.path.join(out_dir, "latest")
    if os.path.exists(latest):
        ck = open(latest).read().strip()
        flat = _flatten_state(state)
        dck.load_state_dict(flat, os.path.join(out_dir, ck))
        import jax.numpy as _jnp
        pdt = _jnp.bfloat16 if pcfg.param_dtype == "bfloat16" \
            else _jnp.float32
        state = _restore_state(state, flat, pdt)
        start_step = int(jax.device_get(state.step))
        print(f"[run_pretrain] resumed from {ck} at step {start_step}",
              flush=True)

    def save(step: int):
        name = f"ckpt_step{step}"
        dck.save_state_dict(_flatten_state(state),
                            os.path.join(out_dir, name))
        if jax.process_count() > 1:
            # every rank's shard files must be ON DISK before the
            # coordinator commits the pointer — a kill between one rank's
            # save and another's would otherwise publish a checkpoint
            # with missing shards
            from jax.experimental import multihost_utils
            multihost_utils.sync_global_devices(f"ckpt_{step}")
        if is_coord:
            with open(latest + ".tmp", "w") as f:
                f.write(name)
            os.replace(latest + ".tmp", latest)   # atomic pointer flip
            print(f"[run_pretrain] saved {name}", flush=True)

    stop = {"sig": False}
    # single-process: SIGTERM -> emergency checkpoint at the step
    # boundary. Multi-process: a signal may reach only SOME ranks; a
    # partial emergency save would hang in the pointer-flip barrier (the
    # unsignaled peers never join), so those runs exit WITHOUT an extra
    # save and recovery rides the periodic checkpoints + auto-resume —
    # the preemption-aware story of SURVEY §5.3 (the launcher's teardown
    # SIGTERMs every child anyway).
    if jax.process_count() == 1:
        signal.signal(signal.SIGTERM, lambda *_: stop.update(sig=True))

    log_path = os.path.join(out_dir, "losses.jsonl")
    logf = open(log_path, "a") if is_coord else None
    tokens_per_step = cfg["global_batch"] * cfg["seq_len"]
    peak = _peak_flops()

    def batches():
        """Deterministic step->batch mapping that survives restarts: the
        epoch seeds the shuffle, so skipping (start_step % steps_per_
        epoch) batches reproduces the uninterrupted order exactly."""
        epoch = start_step // steps_per_epoch
        skip = start_step % steps_per_epoch
        while True:
            sampler.set_epoch(epoch)
            for i, b in enumerate(loader):
                if skip:
                    skip -= 1
                    continue
                yield b
            epoch += 1

    it = batches()
    t_last = time.time()
    for step in range(start_step, cfg["max_steps"]):
        ids_np, labels_np = next(it)
        ids = global_device_put(jnp.asarray(ids_np),
                                meta["data_sharding"])
        labels = global_device_put(jnp.asarray(labels_np),
                                   meta["data_sharding"])
        state, m = jstep(state, ids, labels)
        loss = float(jax.device_get(m["loss"]))
        now = time.time()
        tok_s = tokens_per_step / max(now - t_last, 1e-9)
        t_last = now
        rec = {"step": step + 1, "loss": round(loss, 6),
               "tokens_per_s": round(tok_s, 1),
               "mfu_6N_est": round(tok_s * fpt / peak, 4)}
        if logf is not None:
            logf.write(json.dumps(rec) + "\n")
            logf.flush()
            if (step + 1) % cfg["log_interval"] == 0:
                print(f"[run_pretrain] {json.dumps(rec)}", flush=True)
        # save_interval <= 0 disables ALL checkpoints (tuner trials)
        if cfg["save_interval"] > 0 and (
                (step + 1) % cfg["save_interval"] == 0
                or (step + 1) == cfg["max_steps"] or stop["sig"]):
            save(step + 1)
        if stop["sig"]:
            print("[run_pretrain] SIGTERM: emergency checkpoint done"
                  if cfg["save_interval"] > 0 else
                  "[run_pretrain] SIGTERM: exiting (checkpoints disabled "
                  "by save_interval<=0 — nothing saved)", flush=True)
            return 0
    print(f"[run_pretrain] done at step {cfg['max_steps']}", flush=True)
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m paddle_tpu.trainer.run_pretrain",
        description=__doc__.split("\n")[0])
    ap.add_argument("--config", required=True,
                    help="JSON config path ('-' reads stdin)")
    ap.add_argument("--max-steps", type=int, default=None)
    ap.add_argument("--output-dir", default=None)
    ap.add_argument("--fault-spec", default=None,
                    help="deterministic fault-injection plan for chaos "
                         "runs (docs/RESILIENCE.md grammar), e.g. "
                         "'seed=3;nan_grad@step=100;preempt@step=500'")
    args = ap.parse_args(argv)
    cfg = _load_config(args.config)
    if args.max_steps is not None:
        cfg["max_steps"] = args.max_steps
    if args.output_dir is not None:
        cfg["output_dir"] = args.output_dir
    if args.fault_spec is not None:
        from .. import resilience as _res
        _res.set_fault_spec(args.fault_spec)
    return run(cfg)


if __name__ == "__main__":
    sys.exit(main())
