"""nn.functional (ref surface: python/paddle/nn/functional/).

Compute is expressed in jnp/lax so XLA owns fusion/layout; the fused-kernel
entry points (flash attention, fused rope, fused rms_norm) route to the Pallas
implementations in paddle_tpu.ops when available, with an XLA reference
fallback — mirroring the reference's fused-op dispatch
(paddle/phi/kernels/fusion/ vs the composite python fallback).
"""

from __future__ import annotations

import math
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ...core.dispatch import apply
from ...core.dtypes import convert_dtype
from ...core.tensor import Tensor
from ...framework.random import next_key

__all__ = [
    # activations
    "relu", "relu6", "gelu", "silu", "swish", "sigmoid", "tanh", "softmax",
    "log_softmax", "leaky_relu", "elu", "selu", "celu", "hardswish",
    "hardsigmoid", "hardtanh", "mish", "softplus", "softsign", "softshrink",
    "hardshrink", "tanhshrink", "thresholded_relu", "prelu", "glu", "swiglu",
    "gumbel_softmax",
    # linear / embedding
    "linear", "embedding", "one_hot", "bilinear",
    # norm
    "layer_norm", "batch_norm", "group_norm", "instance_norm", "rms_norm",
    "local_response_norm", "normalize",
    # conv / pool
    "conv1d", "conv2d", "conv3d", "conv1d_transpose", "conv2d_transpose",
    "max_pool1d", "max_pool2d", "avg_pool1d", "avg_pool2d", "max_pool3d",
    "avg_pool3d", "adaptive_avg_pool1d", "adaptive_avg_pool2d",
    "adaptive_max_pool2d", "unfold", "pixel_shuffle",
    # attention
    "scaled_dot_product_attention", "softmax_mask_fuse",
    # dropout & misc
    "dropout", "dropout2d", "alpha_dropout", "pad", "interpolate", "upsample",
    # losses
    "cross_entropy", "softmax_with_cross_entropy", "binary_cross_entropy",
    "binary_cross_entropy_with_logits", "mse_loss", "l1_loss", "smooth_l1_loss",
    "nll_loss", "kl_div", "margin_ranking_loss", "cosine_similarity",
    "cosine_embedding_loss", "ctc_loss", "rnnt_loss", "hinge_embedding_loss",
    "label_smooth", "square_error_cost", "sequence_mask", "temporal_shift",
]


# ---------------------------------------------------------------------------
# activations
# ---------------------------------------------------------------------------
def relu(x, name=None):
    return apply("relu", jax.nn.relu, [x])


def relu6(x, name=None):
    return apply("relu6", jax.nn.relu6, [x])


def gelu(x, approximate=False, name=None):
    return apply("gelu", lambda a: jax.nn.gelu(a, approximate=approximate), [x])


def silu(x, name=None):
    return apply("silu", jax.nn.silu, [x])


def swish(x, name=None):
    return silu(x)


def sigmoid(x, name=None):
    return apply("sigmoid", jax.nn.sigmoid, [x])


def tanh(x, name=None):
    return apply("tanh", jnp.tanh, [x])


def softmax(x, axis=-1, dtype=None, name=None):
    def impl(a):
        if dtype is not None:
            a = a.astype(convert_dtype(dtype))
        return jax.nn.softmax(a, axis=axis)
    return apply("softmax", impl, [x])


def log_softmax(x, axis=-1, dtype=None, name=None):
    def impl(a):
        if dtype is not None:
            a = a.astype(convert_dtype(dtype))
        return jax.nn.log_softmax(a, axis=axis)
    return apply("log_softmax", impl, [x])


def leaky_relu(x, negative_slope=0.01, name=None):
    return apply("leaky_relu",
                 lambda a: jax.nn.leaky_relu(a, negative_slope), [x])


def elu(x, alpha=1.0, name=None):
    return apply("elu", lambda a: jax.nn.elu(a, alpha), [x])


def selu(x, scale=1.0507009873554805, alpha=1.6732632423543772, name=None):
    return apply("selu",
                 lambda a: scale * jnp.where(a > 0, a, alpha * jnp.expm1(a)),
                 [x])


def celu(x, alpha=1.0, name=None):
    return apply("celu", lambda a: jax.nn.celu(a, alpha), [x])


def hardswish(x, name=None):
    return apply("hardswish", jax.nn.hard_swish, [x])


def hardsigmoid(x, slope=1.0 / 6, offset=0.5, name=None):
    return apply("hardsigmoid",
                 lambda a: jnp.clip(slope * a + offset, 0.0, 1.0), [x])


def hardtanh(x, min=-1.0, max=1.0, name=None):
    return apply("hardtanh", lambda a: jnp.clip(a, min, max), [x])


def mish(x, name=None):
    return apply("mish", lambda a: a * jnp.tanh(jax.nn.softplus(a)), [x])


def softplus(x, beta=1.0, threshold=20.0, name=None):
    return apply("softplus",
                 lambda a: jnp.where(beta * a > threshold, a,
                                     jax.nn.softplus(beta * a) / beta), [x])


def softsign(x, name=None):
    return apply("softsign", jax.nn.soft_sign, [x])


def softshrink(x, threshold=0.5, name=None):
    return apply("softshrink",
                 lambda a: jnp.where(a > threshold, a - threshold,
                                     jnp.where(a < -threshold, a + threshold,
                                               jnp.zeros_like(a))), [x])


def hardshrink(x, threshold=0.5, name=None):
    return apply("hardshrink",
                 lambda a: jnp.where(jnp.abs(a) > threshold, a,
                                     jnp.zeros_like(a)), [x])


def tanhshrink(x, name=None):
    return apply("tanhshrink", lambda a: a - jnp.tanh(a), [x])


def thresholded_relu(x, threshold=1.0, name=None):
    return apply("thresholded_relu",
                 lambda a: jnp.where(a > threshold, a, jnp.zeros_like(a)), [x])


def prelu(x, weight, data_format="NCHW", name=None):
    def impl(a, w):
        if w.size == 1:
            return jnp.where(a > 0, a, w.reshape(()) * a)
        shape = [1] * a.ndim
        ch_axis = 1 if data_format.startswith("NC") else a.ndim - 1
        shape[ch_axis] = w.size
        return jnp.where(a > 0, a, w.reshape(shape) * a)
    return apply("prelu", impl, [x, weight])


def glu(x, axis=-1, name=None):
    def impl(a):
        a1, a2 = jnp.split(a, 2, axis=axis)
        return a1 * jax.nn.sigmoid(a2)
    return apply("glu", impl, [x])


def swiglu(x, y=None, name=None):
    """ref: paddle.incubate.nn.functional.swiglu — silu(x) * y (or split)."""
    if y is None:
        def impl(a):
            a1, a2 = jnp.split(a, 2, axis=-1)
            return jax.nn.silu(a1) * a2
        return apply("swiglu", impl, [x])
    return apply("swiglu", lambda a, b: jax.nn.silu(a) * b, [x, y])


def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1, name=None):
    g = -jnp.log(-jnp.log(
        jax.random.uniform(next_key(), tuple(x.shape)) + 1e-20) + 1e-20)
    def impl(a):
        y = jax.nn.softmax((a + g) / temperature, axis=axis)
        if hard:
            idx = jnp.argmax(y, axis=axis, keepdims=True)
            y_hard = jnp.zeros_like(y)
            y_hard = jnp.put_along_axis(y_hard, idx, 1.0, axis=axis,
                                        inplace=False)
            y = y_hard + y - jax.lax.stop_gradient(y)
        return y
    return apply("gumbel_softmax", impl, [x])


# ---------------------------------------------------------------------------
# linear / embedding
# ---------------------------------------------------------------------------
def linear(x, weight, bias=None, name=None):
    """paddle convention: weight is [in_features, out_features]."""
    if bias is None:
        return apply("linear", lambda a, w: a @ w, [x, weight])
    return apply("linear", lambda a, w, b: a @ w + b, [x, weight, bias])


def embedding(x, weight, padding_idx=None, sparse=False, name=None):
    idx = x._data if isinstance(x, Tensor) else jnp.asarray(x)
    def impl(w):
        out = jnp.take(w, idx, axis=0)
        if padding_idx is not None:
            mask = (idx == padding_idx)[..., None]
            out = jnp.where(mask, jnp.zeros((), out.dtype), out)
        return out
    return apply("embedding", impl, [weight])


def one_hot(x, num_classes, name=None):
    idx = x._data if isinstance(x, Tensor) else jnp.asarray(x)
    return Tensor(jax.nn.one_hot(idx, num_classes))


def bilinear(x1, x2, weight, bias=None, name=None):
    def impl(a, b, w, *rest):
        out = jnp.einsum("bi,oij,bj->bo", a, w, b)
        if rest:
            out = out + rest[0]
        return out
    args = [x1, x2, weight] + ([bias] if bias is not None else [])
    return apply("bilinear", impl, args)


# ---------------------------------------------------------------------------
# normalization
# ---------------------------------------------------------------------------
def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-5,
               name=None):
    if isinstance(normalized_shape, int):
        normalized_shape = [normalized_shape]
    ndim = len(normalized_shape)
    def impl(a, *wb):
        axes = tuple(range(a.ndim - ndim, a.ndim))
        mean = jnp.mean(a, axis=axes, keepdims=True)
        var = jnp.mean(jnp.square(a - mean), axis=axes, keepdims=True)
        out = (a - mean) * jax.lax.rsqrt(var + epsilon)
        i = 0
        if weight is not None:
            out = out * wb[i]; i += 1
        if bias is not None:
            out = out + wb[i]
        return out
    args = [x] + [t for t in (weight, bias) if t is not None]
    return apply("layer_norm", impl, args)


def rms_norm(x, weight=None, epsilon=1e-6, name=None):
    """Fused RMSNorm parity (ref: paddle fused_rms_norm / RmsNormKernel)."""
    def impl(a, *w):
        dt = a.dtype
        a32 = a.astype(jnp.float32)
        var = jnp.mean(jnp.square(a32), axis=-1, keepdims=True)
        out = a32 * jax.lax.rsqrt(var + epsilon)
        out = out.astype(dt)
        if w:
            out = out * w[0]
        return out
    args = [x] + ([weight] if weight is not None else [])
    return apply("rms_norm", impl, args)


def batch_norm(x, running_mean, running_var, weight=None, bias=None,
               training=False, momentum=0.9, epsilon=1e-5,
               data_format="NCHW", name=None):
    ch_axis = 1 if data_format.startswith("NC") else x.ndim - 1
    reduce_axes = tuple(i for i in range(x.ndim) if i != ch_axis)
    shape = [1] * x.ndim
    shape[ch_axis] = x.shape[ch_axis]

    if training:
        def impl(a, *wb):
            mean = jnp.mean(a, axis=reduce_axes)
            var = jnp.var(a, axis=reduce_axes)
            out = (a - mean.reshape(shape)) * jax.lax.rsqrt(
                var.reshape(shape) + epsilon)
            i = 0
            if weight is not None:
                out = out * wb[i].reshape(shape); i += 1
            if bias is not None:
                out = out + wb[i].reshape(shape)
            return out, mean, var
        args = [x] + [t for t in (weight, bias) if t is not None]
        out, mean, var = apply("batch_norm", impl, args)
        # update running stats (host-side state, functional underneath)
        if running_mean is not None and not isinstance(mean._data, jax.core.Tracer):
            running_mean._data = (momentum * running_mean._data
                                  + (1 - momentum) * mean._data)
            running_var._data = (momentum * running_var._data
                                 + (1 - momentum) * var._data)
        elif running_mean is not None:
            running_mean._data = (momentum * running_mean._data
                                  + (1 - momentum) * mean._data)
            running_var._data = (momentum * running_var._data
                                 + (1 - momentum) * var._data)
        return out

    rm = running_mean._data if isinstance(running_mean, Tensor) else running_mean
    rv = running_var._data if isinstance(running_var, Tensor) else running_var
    def impl_eval(a, *wb):
        out = (a - rm.reshape(shape)) * jax.lax.rsqrt(rv.reshape(shape) + epsilon)
        i = 0
        if weight is not None:
            out = out * wb[i].reshape(shape); i += 1
        if bias is not None:
            out = out + wb[i].reshape(shape)
        return out
    args = [x] + [t for t in (weight, bias) if t is not None]
    return apply("batch_norm_eval", impl_eval, args)


def group_norm(x, num_groups, epsilon=1e-5, weight=None, bias=None,
               data_format="NCHW", name=None):
    ch_axis = 1 if data_format.startswith("NC") else x.ndim - 1
    C = x.shape[ch_axis]
    def impl(a, *wb):
        if ch_axis != 1:
            a = jnp.moveaxis(a, ch_axis, 1)
        n = a.shape[0]
        grouped = a.reshape((n, num_groups, C // num_groups) + a.shape[2:])
        axes = tuple(range(2, grouped.ndim))
        mean = jnp.mean(grouped, axis=axes, keepdims=True)
        var = jnp.var(grouped, axis=axes, keepdims=True)
        out = ((grouped - mean) * jax.lax.rsqrt(var + epsilon)).reshape(a.shape)
        shape = [1] * out.ndim
        shape[1] = C
        i = 0
        if weight is not None:
            out = out * wb[i].reshape(shape); i += 1
        if bias is not None:
            out = out + wb[i].reshape(shape)
        if ch_axis != 1:
            out = jnp.moveaxis(out, 1, ch_axis)
        return out
    args = [x] + [t for t in (weight, bias) if t is not None]
    return apply("group_norm", impl, args)


def instance_norm(x, running_mean=None, running_var=None, weight=None,
                  bias=None, use_input_stats=True, momentum=0.9, eps=1e-5,
                  data_format="NCHW", name=None):
    def impl(a, *wb):
        axes = tuple(range(2, a.ndim))
        mean = jnp.mean(a, axis=axes, keepdims=True)
        var = jnp.var(a, axis=axes, keepdims=True)
        out = (a - mean) * jax.lax.rsqrt(var + eps)
        shape = [1] * a.ndim
        shape[1] = a.shape[1]
        i = 0
        if weight is not None:
            out = out * wb[i].reshape(shape); i += 1
        if bias is not None:
            out = out + wb[i].reshape(shape)
        return out
    args = [x] + [t for t in (weight, bias) if t is not None]
    return apply("instance_norm", impl, args)


def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0,
                        data_format="NCHW", name=None):
    def impl(a):
        sq = jnp.square(a)
        half = size // 2
        ch = a.shape[1]
        acc = jnp.zeros_like(a)
        for off in range(-half, half + 1):
            lo = max(0, -off)
            hi = min(ch, ch - off)
            acc = acc.at[:, lo:hi].add(sq[:, lo + off:hi + off])
        return a / jnp.power(k + alpha * acc / size, beta)
    return apply("lrn", impl, [x])


def normalize(x, p=2, axis=1, epsilon=1e-12, name=None):
    def impl(a):
        nrm = jnp.sum(jnp.abs(a) ** p, axis=axis, keepdims=True) ** (1.0 / p)
        return a / jnp.maximum(nrm, epsilon)
    return apply("normalize", impl, [x])


# ---------------------------------------------------------------------------
# conv / pool (paddle weight layout: [out_ch, in_ch/groups, *kernel])
# ---------------------------------------------------------------------------
def _pair(v, n):
    if isinstance(v, (list, tuple)):
        return tuple(int(i) for i in v)
    return (int(v),) * n


def _conv_nd(x, weight, bias, stride, padding, dilation, groups, nd,
             data_format):
    strides = _pair(stride, nd)
    dils = _pair(dilation, nd)
    if isinstance(padding, str):
        pad = padding.upper()  # "SAME"/"VALID"
    else:
        p = _pair(padding, nd) if not (isinstance(padding, (list, tuple))
                                       and isinstance(padding[0], (list, tuple))) \
            else padding
        pad = [(int(pi), int(pi)) for pi in p] if not isinstance(p[0], tuple) \
            else [tuple(pp) for pp in p]

    if data_format.startswith("NC"):
        dn_in = "NC" + "DHW"[3 - nd:]
    else:
        dn_in = "N" + "DHW"[3 - nd:] + "C"
    dn_kernel = "OI" + "DHW"[3 - nd:]
    dn_out = dn_in
    dn = jax.lax.conv_dimension_numbers(
        tuple(x.shape), tuple(weight.shape), (dn_in, dn_kernel, dn_out))

    def impl(a, w, *b):
        out = jax.lax.conv_general_dilated(
            a, w, strides, pad, rhs_dilation=dils, dimension_numbers=dn,
            feature_group_count=groups)
        if b:
            shape = [1] * out.ndim
            ch_axis = 1 if data_format.startswith("NC") else out.ndim - 1
            shape[ch_axis] = b[0].size
            out = out + b[0].reshape(shape)
        return out
    args = [x, weight] + ([bias] if bias is not None else [])
    return apply("conv%dd" % nd, impl, args)


def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCL", name=None):
    return _conv_nd(x, weight, bias, stride, padding, dilation, groups, 1,
                    "NCW" if data_format == "NCL" else "NWC")


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCHW", name=None):
    return _conv_nd(x, weight, bias, stride, padding, dilation, groups, 2,
                    data_format)


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCDHW", name=None):
    return _conv_nd(x, weight, bias, stride, padding, dilation, groups, 3,
                    data_format)


def _conv_transpose_nd(x, weight, bias, stride, padding, output_padding,
                       groups, dilation, nd, data_format):
    """Transpose conv as an input-dilated forward conv (the gradient trick —
    supports groups, dilation, and output_padding uniformly; XLA lowers the
    lhs_dilation form straight onto the MXU).

    Paddle transpose-weight layout: [in, out/groups, *k]. Output size per dim:
    (in-1)*stride - 2*pad + dilation*(k-1) + 1 + output_padding.
    """
    strides = _pair(stride, nd)
    dils = _pair(dilation, nd)
    pads = _pair(padding, nd)
    opads = _pair(output_padding, nd)
    if isinstance(padding, str):
        raise NotImplementedError(
            "string padding for conv_transpose is not supported; pass "
            "explicit integers")
    nc = data_format.startswith("NC")
    dn_in = ("NC" + "DHW"[3 - nd:]) if nc else ("N" + "DHW"[3 - nd:] + "C")
    dn = (dn_in, "OI" + "DHW"[3 - nd:], dn_in)

    def impl(a, w, *b):
        g = groups
        cin = w.shape[0]
        og = w.shape[1]
        k = w.shape[2:]
        # [in, out/g, *k] -> [g, in/g, out/g, *k] -> [g*out/g, in/g, *k]
        wg = w.reshape((g, cin // g, og) + k)
        wg = jnp.moveaxis(wg, 2, 1).reshape((g * og, cin // g) + k)
        wg = jnp.flip(wg, axis=tuple(range(2, 2 + nd)))
        pad_cfg = []
        for i in range(nd):
            k_eff = dils[i] * (k[i] - 1) + 1
            lo = k_eff - 1 - pads[i]
            hi = k_eff - 1 - pads[i] + opads[i]
            pad_cfg.append((lo, hi))
        dnums = jax.lax.conv_dimension_numbers(
            tuple(a.shape), tuple(wg.shape), dn)
        out = jax.lax.conv_general_dilated(
            a, wg, (1,) * nd, pad_cfg, lhs_dilation=strides,
            rhs_dilation=dils, dimension_numbers=dnums,
            feature_group_count=g)
        if b:
            shape = [1] * out.ndim
            ch_axis = 1 if nc else out.ndim - 1
            shape[ch_axis] = b[0].size
            out = out + b[0].reshape(shape)
        return out
    args = [x, weight] + ([bias] if bias is not None else [])
    return apply("conv%dd_transpose" % nd, impl, args)


def conv1d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1,
                     data_format="NCL", name=None):
    return _conv_transpose_nd(x, weight, bias, stride, padding,
                              output_padding, groups, dilation, 1,
                              "NCW" if data_format == "NCL" else "NWC")


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1,
                     data_format="NCHW", name=None):
    return _conv_transpose_nd(x, weight, bias, stride, padding,
                              output_padding, groups, dilation, 2,
                              data_format)


def conv3d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1,
                     data_format="NCDHW", name=None):
    return _conv_transpose_nd(x, weight, bias, stride, padding,
                              output_padding, groups, dilation, 3,
                              data_format)


def _pool_pads(in_sizes, ks, st, pd, ceil_mode):
    """Per-dim (lo, hi) spatial padding; ceil_mode adds extra hi padding so
    the window count is ceil((in + 2p - k)/s) + 1 like the reference."""
    pairs = []
    for insz, k, s, p in zip(in_sizes, ks, st, pd):
        hi = p
        if ceil_mode:
            n_floor = (insz + 2 * p - k) // s
            n_ceil = -((insz + 2 * p - k) // -s)
            hi = p + (n_ceil - n_floor) * s
        pairs.append((p, hi))
    return pairs


def _pool_nd(x, kernel, stride, padding, nd, data_format, reducer, init,
             ceil_mode=False, average=False, exclusive=True):
    ks = _pair(kernel, nd)
    st = _pair(stride if stride is not None else kernel, nd)
    pd = _pair(padding, nd)
    nc = data_format.startswith("NC")
    in_sizes = x.shape[2:] if nc else x.shape[1:-1]
    sp_pairs = _pool_pads(in_sizes, ks, st, pd, ceil_mode)
    if nc:
        window = (1, 1) + ks
        strides = (1, 1) + st
        pads = ((0, 0), (0, 0)) + tuple(sp_pairs)
    else:
        window = (1,) + ks + (1,)
        strides = (1,) + st + (1,)
        pads = ((0, 0),) + tuple(sp_pairs) + ((0, 0),)
    padded = any(lo or hi for lo, hi in sp_pairs)

    def impl(a):
        out = jax.lax.reduce_window(a, init(a.dtype), reducer, window,
                                    strides, pads)
        if average:
            if exclusive and padded:
                ones = jnp.ones_like(a)
                counts = jax.lax.reduce_window(
                    ones, jnp.zeros((), a.dtype), jax.lax.add, window,
                    strides, pads)
                out = out / counts
            else:
                out = out / np.prod(ks)
        return out
    return apply("pool", impl, [x])


def _max_pool_mask(x, ks, st, pd, nd, ceil_mode):
    """Global flat spatial argmax index per window (paddle return_mask
    semantics), via patch extraction — NCHW-family layouts only."""
    in_sizes = x.shape[2:]
    sp_pairs = _pool_pads(in_sizes, ks, st, pd, ceil_mode)

    def impl(a):
        n, c = a.shape[:2]
        neg = jnp.finfo(a.dtype).min if jnp.issubdtype(a.dtype, jnp.floating) \
            else jnp.iinfo(a.dtype).min
        ap = jnp.pad(a, ((0, 0), (0, 0)) + tuple(sp_pairs),
                     constant_values=neg)
        patches = jax.lax.conv_general_dilated_patches(
            ap, ks, st, [(0, 0)] * nd)
        # patches: [N, C*prod(ks), *out_spatial]; local argmax per window
        out_sp = patches.shape[2:]
        pk = int(np.prod(ks))
        patches = patches.reshape((n, c, pk) + out_sp)
        local = jnp.argmax(patches, axis=2)  # [N, C, *out_spatial]
        # local index -> per-dim kernel offsets -> global padded coords ->
        # unpadded global flat index over the input spatial plane
        rem = local
        coords = []
        for d in range(nd - 1, -1, -1):
            coords.insert(0, rem % ks[d])
            rem = rem // ks[d]
        flat = jnp.zeros_like(local)
        for d in range(nd):
            win_start = (jnp.arange(out_sp[d]) * st[d] - sp_pairs[d][0])
            shape = [1] * local.ndim
            shape[2 + d] = out_sp[d]
            g = coords[d] + win_start.reshape(shape)
            flat = flat * in_sizes[d] + g
        return flat.astype(jnp.int32)
    return apply("max_pool_mask", impl, [x])


def _max_pool(x, kernel_size, stride, padding, nd, data_format, ceil_mode,
              return_mask):
    out = _pool_nd(x, kernel_size, stride, padding, nd, data_format,
                   jax.lax.max, lambda dt: jnp.asarray(-jnp.inf, dt)
                   if jnp.issubdtype(dt, jnp.floating)
                   else jnp.asarray(jnp.iinfo(dt).min, dt),
                   ceil_mode=ceil_mode)
    if not return_mask:
        return out
    if not data_format.startswith("NC"):
        raise NotImplementedError("return_mask requires an NC* data_format")
    ks = _pair(kernel_size, nd)
    st = _pair(stride if stride is not None else kernel_size, nd)
    pd = _pair(padding, nd)
    mask = _max_pool_mask(x, ks, st, pd, nd, ceil_mode)
    return out, mask


def max_pool1d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               return_mask=False, name=None):
    return _max_pool(x, kernel_size, stride, padding, 1, "NCW", ceil_mode,
                     return_mask)


def max_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               return_mask=False, data_format="NCHW", name=None):
    return _max_pool(x, kernel_size, stride, padding, 2, data_format,
                     ceil_mode, return_mask)


def max_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               return_mask=False, data_format="NCDHW", name=None):
    return _max_pool(x, kernel_size, stride, padding, 3, data_format,
                     ceil_mode, return_mask)


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, name=None):
    return _pool_nd(x, kernel_size, stride, padding, 1, "NCW",
                    jax.lax.add, lambda dt: jnp.zeros((), dt), average=True,
                    exclusive=exclusive, ceil_mode=ceil_mode)


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCHW",
               name=None):
    return _pool_nd(x, kernel_size, stride, padding, 2, data_format,
                    jax.lax.add, lambda dt: jnp.zeros((), dt), average=True,
                    exclusive=exclusive, ceil_mode=ceil_mode)


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCDHW",
               name=None):
    return _pool_nd(x, kernel_size, stride, padding, 3, data_format,
                    jax.lax.add, lambda dt: jnp.zeros((), dt), average=True,
                    exclusive=exclusive, ceil_mode=ceil_mode)


def adaptive_avg_pool1d(x, output_size, name=None):
    return _adaptive_pool(x, output_size, 1, "avg")


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    return _adaptive_pool(x, output_size, 2, "avg")


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    return _adaptive_pool(x, output_size, 2, "max")


def _adaptive_pool(x, output_size, nd, mode):
    out_sizes = _pair(output_size, nd)
    in_sizes = x.shape[-nd:]
    def impl(a):
        out = a
        for d, (insz, outsz) in enumerate(zip(in_sizes, out_sizes)):
            axis = a.ndim - nd + d
            if insz % outsz == 0:
                # fast path: equal windows → reshape + reduce
                k = insz // outsz
                shape = out.shape[:axis] + (outsz, k) + out.shape[axis + 1:]
                out = out.reshape(shape)
                out = jnp.mean(out, axis=axis + 1) if mode == "avg" \
                    else jnp.max(out, axis=axis + 1)
                continue
            # general paddle/torch windows: [floor(i*in/out), ceil((i+1)*in/out))
            slices = []
            for i in range(outsz):
                lo = (i * insz) // outsz
                hi = -(-((i + 1) * insz) // outsz)  # ceil
                win = jax.lax.slice_in_dim(out, lo, hi, axis=axis)
                red = jnp.mean(win, axis=axis, keepdims=True) \
                    if mode == "avg" else jnp.max(win, axis=axis,
                                                  keepdims=True)
                slices.append(red)
            out = jnp.concatenate(slices, axis=axis)
        return out
    return apply("adaptive_pool", impl, [x])


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    ks = _pair(kernel_sizes, 2)
    st = _pair(strides, 2)
    pd = _pair(paddings, 2)
    dl = _pair(dilations, 2)
    def impl(a):
        n, c, h, w = a.shape
        patches = jax.lax.conv_general_dilated_patches(
            a, ks, st, [(pd[0], pd[0]), (pd[1], pd[1])], rhs_dilation=dl,
            dimension_numbers=jax.lax.conv_dimension_numbers(
                a.shape, (1, 1) + ks, ("NCHW", "OIHW", "NCHW")))
        return patches.reshape(n, c * ks[0] * ks[1], -1)
    return apply("unfold", impl, [x])


def pixel_shuffle(x, upscale_factor, data_format="NCHW", name=None):
    r = upscale_factor
    def impl(a):
        n, c, h, w = a.shape
        a = a.reshape(n, c // (r * r), r, r, h, w)
        a = a.transpose(0, 1, 4, 2, 5, 3)
        return a.reshape(n, c // (r * r), h * r, w * r)
    return apply("pixel_shuffle", impl, [x])


# ---------------------------------------------------------------------------
# attention
# ---------------------------------------------------------------------------
def scaled_dot_product_attention(query, key, value, attn_mask=None,
                                 dropout_p=0.0, is_causal=False,
                                 training=True, name=None):
    """ref: paddle.nn.functional.scaled_dot_product_attention
    (python/paddle/nn/functional/flash_attention.py). Layout [B, S, H, D].
    Routes to the Pallas flash kernel when available (paddle_tpu.ops)."""
    from ...ops import flash_attention as _fa
    mask = attn_mask._data if isinstance(attn_mask, Tensor) else attn_mask
    def impl(q, k, v):
        return _fa.sdpa(q, k, v, mask=mask, causal=is_causal,
                        dropout_p=dropout_p if training else 0.0)
    return apply("sdpa", impl, [query, key, value])


def softmax_mask_fuse(x, mask, name=None):
    m = mask._data if isinstance(mask, Tensor) else mask
    return apply("softmax_mask_fuse",
                 lambda a: jax.nn.softmax(a + m, axis=-1), [x])


# ---------------------------------------------------------------------------
# dropout & shape utilities
# ---------------------------------------------------------------------------
def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train",
            name=None):
    if not training or p == 0.0:
        if mode == "downscale_in_infer" and not training and p > 0.0:
            # legacy paddle mode: train keeps raw mask, infer scales by (1-p)
            return apply("dropout", lambda a: a * (1.0 - p), [x])
        return x if isinstance(x, Tensor) else Tensor(x)
    if p == 1.0:
        return apply("dropout", lambda a: jnp.zeros_like(a), [x])
    shape = tuple(x.shape)
    if axis is not None:
        axes = axis if isinstance(axis, (list, tuple)) else [axis]
        shape = tuple(s if i in axes else 1 for i, s in enumerate(x.shape))
    keep = jax.random.bernoulli(next_key(), 1.0 - p, shape)
    def impl(a):
        if mode == "upscale_in_train":
            return jnp.where(keep, a / (1.0 - p), jnp.zeros((), a.dtype))
        return jnp.where(keep, a, jnp.zeros((), a.dtype))
    return apply("dropout", impl, [x])


def dropout2d(x, p=0.5, training=True, data_format="NCHW", name=None):
    axes = [0, 1] if data_format == "NCHW" else [0, 3]
    return dropout(x, p, axis=axes, training=training)


def alpha_dropout(x, p=0.5, training=True, name=None):
    if not training or p == 0.0:
        return x
    alpha = -1.7580993408473766
    keep = jax.random.bernoulli(next_key(), 1.0 - p, tuple(x.shape))
    q = 1.0 - p
    a_scale = (q + alpha ** 2 * q * p) ** -0.5
    b = -a_scale * p * alpha
    def impl(t):
        return a_scale * jnp.where(keep, t, jnp.asarray(alpha, t.dtype)) + b
    return apply("alpha_dropout", impl, [x])


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):
    from ...tensor.manipulation import pad_nd
    if len(pad) == x.ndim * 2:
        return pad_nd(x, pad, mode, value)
    # paddle semantics: pad applies to spatial dims per data_format
    nd = x.ndim
    if data_format.startswith("NC"):
        spatial = list(range(2, nd))
    else:
        spatial = list(range(1, nd - 1))
    pairs = [(0, 0)] * nd
    half = len(pad) // 2
    if not spatial:
        # low-rank input (no batch/channel dims to skip): pad trailing dims
        spatial = list(range(nd))
    if len(spatial) < half:
        raise ValueError(
            f"pad length {len(pad)} implies {half} spatial dims but input "
            f"rank {nd} with data_format {data_format!r} has {len(spatial)}")
    for i in range(half):
        d = spatial[-(i + 1)]
        pairs[d] = (int(pad[2 * i]), int(pad[2 * i + 1]))
    def impl(a):
        if mode == "constant":
            return jnp.pad(a, pairs, constant_values=value)
        jmode = {"reflect": "reflect", "replicate": "edge",
                 "circular": "wrap"}[mode]
        return jnp.pad(a, pairs, mode=jmode)
    return apply("pad", impl, [x])


def interpolate(x, size=None, scale_factor=None, mode="nearest",
                align_corners=False, data_format="NCHW", name=None):
    nd = x.ndim - 2
    if size is None:
        sf = _pair(scale_factor, nd)
        in_sp = x.shape[2:] if data_format.startswith("NC") else x.shape[1:-1]
        size = [int(s * f) for s, f in zip(in_sp, sf)]
    size = _pair(size, nd)
    nc = data_format.startswith("NC")
    sp_axes = list(range(2, 2 + nd)) if nc else list(range(1, 1 + nd))

    def _axis_linear_align(a, axis, outsz):
        """Separable linear resize with align_corners=True coordinates."""
        insz = a.shape[axis]
        if outsz == 1 or insz == 1:
            idx = jnp.zeros((outsz,), jnp.int32)
            return jnp.take(a, idx, axis=axis)
        pos = jnp.arange(outsz, dtype=jnp.float32) * ((insz - 1) / (outsz - 1))
        lo = jnp.clip(jnp.floor(pos).astype(jnp.int32), 0, insz - 2)
        frac = (pos - lo).astype(a.dtype)
        shape = [1] * a.ndim
        shape[axis] = outsz
        frac = frac.reshape(shape)
        a_lo = jnp.take(a, lo, axis=axis)
        a_hi = jnp.take(a, lo + 1, axis=axis)
        return a_lo * (1 - frac) + a_hi * frac

    def impl(a):
        if nc:
            out_shape = a.shape[:2] + tuple(size)
        else:
            out_shape = (a.shape[0],) + tuple(size) + (a.shape[-1],)
        if mode == "area":
            # area = adaptive average pooling; integer-ratio downscale only
            out = a
            for ax, outsz in zip(sp_axes, size):
                insz = out.shape[ax]
                if insz % outsz != 0:
                    raise NotImplementedError(
                        "mode='area' needs integer downscale ratios on TPU "
                        f"(in={insz}, out={outsz})")
                k = insz // outsz
                shape = out.shape[:ax] + (outsz, k) + out.shape[ax + 1:]
                out = jnp.mean(out.reshape(shape), axis=ax + 1)
            return out
        if align_corners and mode in ("linear", "bilinear", "trilinear"):
            out = a
            for ax, outsz in zip(sp_axes, size):
                out = _axis_linear_align(out, ax, outsz)
            return out
        method = {"nearest": "nearest", "bilinear": "linear",
                  "linear": "linear", "trilinear": "linear",
                  "bicubic": "cubic"}[mode]
        return jax.image.resize(a, out_shape, method=method)
    return apply("interpolate", impl, [x])


def upsample(x, size=None, scale_factor=None, mode="nearest",
             align_corners=False, data_format="NCHW", name=None):
    return interpolate(x, size, scale_factor, mode, align_corners, data_format)


def sequence_mask(lengths, maxlen=None, dtype="int64", name=None):
    l = lengths._data if isinstance(lengths, Tensor) else jnp.asarray(lengths)
    m = int(maxlen) if maxlen is not None else int(np.asarray(l).max())
    mask = jnp.arange(m) < l[..., None]
    return Tensor(mask.astype(convert_dtype(dtype)))


def temporal_shift(x, seg_num, shift_ratio=0.25, data_format="NCHW", name=None):
    def impl(a):
        nt, c, h, w = a.shape
        n = nt // seg_num
        a = a.reshape(n, seg_num, c, h, w)
        fold = int(c * shift_ratio)
        left = jnp.concatenate([a[:, 1:, :fold], jnp.zeros_like(a[:, -1:, :fold])], 1)
        right = jnp.concatenate([jnp.zeros_like(a[:, :1, fold:2 * fold]),
                                 a[:, :-1, fold:2 * fold]], 1)
        rest = a[:, :, 2 * fold:]
        out = jnp.concatenate([left, right, rest], axis=2)
        return out.reshape(nt, c, h, w)
    return apply("temporal_shift", impl, [x])


def label_smooth(label, prior_dist=None, epsilon=0.1, name=None):
    def impl(l):
        k = l.shape[-1]
        if prior_dist is not None:
            pd = prior_dist._data if isinstance(prior_dist, Tensor) else prior_dist
            return (1 - epsilon) * l + epsilon * pd
        return (1 - epsilon) * l + epsilon / k
    return apply("label_smooth", impl, [label])


# ---------------------------------------------------------------------------
# losses
# ---------------------------------------------------------------------------
def _reduce_loss(loss, reduction):
    if reduction == "mean":
        return jnp.mean(loss)
    if reduction == "sum":
        return jnp.sum(loss)
    return loss


def cross_entropy(input, label, weight=None, ignore_index=-100,
                  reduction="mean", soft_label=False, axis=-1,
                  use_softmax=True, label_smoothing=0.0, name=None):
    lab = label._data if isinstance(label, Tensor) else jnp.asarray(label)
    w = weight._data if isinstance(weight, Tensor) else weight
    def impl(logits):
        if use_softmax:
            logp = jax.nn.log_softmax(logits, axis=axis)
        else:
            logp = jnp.log(jnp.clip(logits, 1e-30, None))
        if soft_label:
            target = lab
            if label_smoothing > 0:
                k = logits.shape[axis]
                target = (1 - label_smoothing) * target + label_smoothing / k
            loss = -jnp.sum(target * logp, axis=axis)
        else:
            l = lab
            if l.ndim == logp.ndim:  # trailing 1 dim
                l = l.squeeze(axis)
            k = logits.shape[axis]
            safe = jnp.clip(l, 0, k - 1)
            picked = jnp.take_along_axis(
                logp, jnp.expand_dims(safe, axis).astype(jnp.int32),
                axis=axis).squeeze(axis)
            if label_smoothing > 0:
                smooth = jnp.mean(logp, axis=axis)
                picked = (1 - label_smoothing) * picked + label_smoothing * smooth
            loss = -picked
            mask = (l != ignore_index)
            loss = jnp.where(mask, loss, jnp.zeros((), loss.dtype))
            if w is not None:
                loss = loss * jnp.take(w, safe, axis=0)
            if reduction == "mean":
                denom = jnp.maximum(jnp.sum(mask.astype(loss.dtype)), 1.0) \
                    if w is None else jnp.maximum(
                        jnp.sum(jnp.where(mask, jnp.take(w, safe, 0),
                                          jnp.zeros((), loss.dtype))), 1e-12)
                return jnp.sum(loss) / denom
        return _reduce_loss(loss, reduction)
    return apply("cross_entropy", impl, [input])


def softmax_with_cross_entropy(logits, label, soft_label=False, axis=-1,
                               ignore_index=-100, return_softmax=False,
                               name=None):
    loss = cross_entropy(logits, label, soft_label=soft_label, axis=axis,
                         ignore_index=ignore_index, reduction="none")
    loss = loss.unsqueeze(axis) if loss.ndim < logits.ndim else loss
    if return_softmax:
        return loss, softmax(logits, axis=axis)
    return loss


def binary_cross_entropy(input, label, weight=None, reduction="mean",
                         name=None):
    lab = label._data if isinstance(label, Tensor) else jnp.asarray(label)
    w = weight._data if isinstance(weight, Tensor) else weight
    def impl(p):
        eps = 1e-12
        loss = -(lab * jnp.log(jnp.clip(p, eps, None))
                 + (1 - lab) * jnp.log(jnp.clip(1 - p, eps, None)))
        if w is not None:
            loss = loss * w
        return _reduce_loss(loss, reduction)
    return apply("bce", impl, [input])


def binary_cross_entropy_with_logits(logit, label, weight=None,
                                     reduction="mean", pos_weight=None,
                                     name=None):
    lab = label._data if isinstance(label, Tensor) else jnp.asarray(label)
    w = weight._data if isinstance(weight, Tensor) else weight
    pw = pos_weight._data if isinstance(pos_weight, Tensor) else pos_weight
    def impl(z):
        # numerically stable: max(z,0) - z*y + log(1+exp(-|z|))
        loss = jnp.maximum(z, 0) - z * lab + jnp.log1p(jnp.exp(-jnp.abs(z)))
        if pw is not None:
            loss = loss * (lab * (pw - 1) + 1)
        if w is not None:
            loss = loss * w
        return _reduce_loss(loss, reduction)
    return apply("bce_logits", impl, [logit])


def mse_loss(input, label, reduction="mean", name=None):
    return apply("mse_loss",
                 lambda a, b: _reduce_loss(jnp.square(a - b), reduction),
                 [input, label])


def square_error_cost(input, label, name=None):
    return apply("square_error_cost", lambda a, b: jnp.square(a - b),
                 [input, label])


def l1_loss(input, label, reduction="mean", name=None):
    return apply("l1_loss",
                 lambda a, b: _reduce_loss(jnp.abs(a - b), reduction),
                 [input, label])


def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):
    def impl(a, b):
        d = a - b
        loss = jnp.where(jnp.abs(d) < delta, 0.5 * d * d / delta,
                         jnp.abs(d) - 0.5 * delta)
        return _reduce_loss(loss, reduction)
    return apply("smooth_l1", impl, [input, label])


def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean",
             name=None):
    lab = label._data if isinstance(label, Tensor) else jnp.asarray(label)
    w = weight._data if isinstance(weight, Tensor) else weight
    def impl(logp):
        k = logp.shape[1]
        safe = jnp.clip(lab, 0, k - 1)
        picked = jnp.take_along_axis(logp, safe[:, None].astype(jnp.int32),
                                     axis=1).squeeze(1)
        loss = -picked
        mask = lab != ignore_index
        loss = jnp.where(mask, loss, jnp.zeros((), loss.dtype))
        if w is not None:
            loss = loss * jnp.take(w, safe, 0)
        if reduction == "mean":
            return jnp.sum(loss) / jnp.maximum(
                jnp.sum(mask.astype(loss.dtype)), 1.0)
        return _reduce_loss(loss, reduction)
    return apply("nll_loss", impl, [input])


def kl_div(input, label, reduction="mean", log_target=False, name=None):
    def impl(logp, t):
        tt = jnp.exp(t) if log_target else t
        logt = t if log_target else jnp.log(jnp.clip(t, 1e-12, None))
        loss = tt * (logt - logp)
        if reduction == "batchmean":
            return jnp.sum(loss) / logp.shape[0]
        return _reduce_loss(loss, reduction)
    return apply("kl_div", impl, [input, label])


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean",
                        name=None):
    def impl(a, b, l):
        loss = jnp.maximum(-l * (a - b) + margin, 0.0)
        return _reduce_loss(loss, reduction)
    return apply("margin_ranking", impl, [input, other, label])


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean", name=None):
    def impl(a, l):
        loss = jnp.where(l == 1, a, jnp.maximum(margin - a, 0.0))
        return _reduce_loss(loss, reduction)
    return apply("hinge_embedding", impl, [input, label])


def cosine_similarity(x1, x2, axis=1, eps=1e-8, name=None):
    def impl(a, b):
        dot = jnp.sum(a * b, axis=axis)
        na = jnp.sqrt(jnp.sum(a * a, axis=axis))
        nb = jnp.sqrt(jnp.sum(b * b, axis=axis))
        return dot / jnp.maximum(na * nb, eps)
    return apply("cosine_similarity", impl, [x1, x2])


def cosine_embedding_loss(input1, input2, label, margin=0.0, reduction="mean",
                          name=None):
    def impl(a, b, l):
        cos = jnp.sum(a * b, axis=1) / jnp.maximum(
            jnp.linalg.norm(a, axis=1) * jnp.linalg.norm(b, axis=1), 1e-12)
        loss = jnp.where(l == 1, 1 - cos, jnp.maximum(cos - margin, 0.0))
        return _reduce_loss(loss, reduction)
    return apply("cosine_embedding", impl, [input1, input2, label])


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False, name=None):
    """CTC loss (ref: warpctc external in the reference build; here a native
    XLA forward-algorithm implementation — SURVEY §7.1 L8 warpctc parity).

    log_probs: [T, B, C] (paddle convention), labels: [B, L] padded.
    """
    lab = labels._data if isinstance(labels, Tensor) else jnp.asarray(labels)
    in_len = input_lengths._data if isinstance(input_lengths, Tensor) \
        else jnp.asarray(input_lengths)
    lab_len = label_lengths._data if isinstance(label_lengths, Tensor) \
        else jnp.asarray(label_lengths)

    def impl(lp):
        lp_btc = jnp.transpose(lp, (1, 0, 2))  # [B, T, C]
        lp_btc = jax.nn.log_softmax(lp_btc, axis=-1)
        B, T, C = lp_btc.shape
        L = lab.shape[1]
        S = 2 * L + 1
        # extended label sequence: blank l1 blank l2 ... blank
        ext = jnp.full((B, S), blank, dtype=jnp.int32)
        ext = ext.at[:, 1::2].set(lab.astype(jnp.int32))
        neg_inf = jnp.asarray(-1e30, lp_btc.dtype)

        # allow-transition mask for skip connections (s-2): only when ext
        # labels differ and current is not blank
        skip_ok = jnp.concatenate(
            [jnp.zeros((B, 2), bool),
             (ext[:, 2:] != ext[:, :-2]) & (ext[:, 2:] != blank)], axis=1)

        def step(alpha, lp_t):
            a_prev = alpha
            a1 = jnp.concatenate([jnp.full((B, 1), neg_inf), alpha[:, :-1]], 1)
            a2 = jnp.concatenate([jnp.full((B, 2), neg_inf), alpha[:, :-2]], 1)
            a2 = jnp.where(skip_ok, a2, neg_inf)
            merged = jnp.logaddexp(jnp.logaddexp(a_prev, a1), a2)
            emit = jnp.take_along_axis(lp_t, ext, axis=1)
            return merged + emit, None

        alpha0 = jnp.full((B, S), neg_inf)
        alpha0 = alpha0.at[:, 0].set(lp_btc[:, 0, blank])
        first_emit = jnp.take_along_axis(lp_btc[:, 0], ext[:, 1:2], axis=1)[:, 0]
        alpha0 = alpha0.at[:, 1].set(jnp.where(lab_len > 0, first_emit, neg_inf))

        def scan_body(carry, t):
            alpha, = carry
            new_alpha, _ = step(alpha, lp_btc[:, t])
            # freeze past input_length
            new_alpha = jnp.where((t < in_len)[:, None], new_alpha, alpha)
            return (new_alpha,), None

        (alpha_f,), _ = jax.lax.scan(scan_body, (alpha0,),
                                     jnp.arange(1, T))
        end1 = 2 * lab_len  # final blank position
        end2 = 2 * lab_len - 1
        g1 = jnp.take_along_axis(alpha_f, end1[:, None].astype(jnp.int32), 1)[:, 0]
        g2 = jnp.take_along_axis(alpha_f,
                                 jnp.maximum(end2, 0)[:, None].astype(jnp.int32),
                                 1)[:, 0]
        g2 = jnp.where(lab_len > 0, g2, neg_inf)
        nll = -jnp.logaddexp(g1, g2)
        if reduction == "mean":
            return jnp.mean(nll / jnp.maximum(lab_len.astype(nll.dtype), 1.0))
        return _reduce_loss(nll, reduction)
    return apply("ctc_loss", impl, [log_probs])


def rnnt_loss(logits, labels, logit_lengths, label_lengths, blank=0,
              fastemit_lambda=0.0, reduction="mean", name=None):
    """RNN-Transducer loss (ref: the warprnnt external in the reference
    build — paddle.nn.functional.rnnt_loss; here a native XLA
    forward-algorithm over the (T, U) alignment lattice).

    logits: [B, T, U+1, V] joint-network outputs (T acoustic frames,
    U max label length), labels: [B, U] padded, blank: blank id.
    alpha[t, u] = logaddexp(alpha[t-1, u] + blank(t-1, u),
                            alpha[t, u-1] + emit(t, u-1));
    loss = -(alpha[T-1, U] + blank(T-1, U)), with variable lengths masked.
    """
    lab = labels._data if isinstance(labels, Tensor) else jnp.asarray(labels)
    t_len = logit_lengths._data if isinstance(logit_lengths, Tensor) \
        else jnp.asarray(logit_lengths)
    u_len = label_lengths._data if isinstance(label_lengths, Tensor) \
        else jnp.asarray(label_lengths)

    def impl(lg):
        lp = jax.nn.log_softmax(lg.astype(jnp.float32), axis=-1)
        B, T, U1, V = lp.shape
        U = U1 - 1
        neg_inf = jnp.asarray(-1e30, lp.dtype)
        lb = lp[..., blank]                                   # [B, T, U+1]
        ext = lab.astype(jnp.int32)                           # [B, U]
        emit = jnp.take_along_axis(
            lp[:, :, :U, :], ext[:, None, :, None], axis=3)[..., 0]
        if fastemit_lambda:
            # FastEmit: scale the EMIT-branch gradient by (1+λ) while
            # leaving the forward loss unchanged (the warprnnt/torchaudio
            # kernel semantics) — value-preserving gradient reweighting
            lam = jnp.asarray(fastemit_lambda, lp.dtype)
            emit = (1.0 + lam) * emit - jax.lax.stop_gradient(lam * emit)
        u_idx = jnp.arange(U1)

        def row(alpha_prev, t):
            # horizontal (blank) arrival from the previous frame
            from_blank = alpha_prev + lb[:, t - 1, :]
            # then the in-row emit recurrence: a[u] = logaddexp(
            #   from_blank[u], a[u-1] + emit[t, u-1])
            def cell(carry, u):
                fb = from_blank[:, u]
                em = jnp.where(u > 0, emit[:, t, jnp.maximum(u - 1, 0)],
                               neg_inf)
                a = jnp.logaddexp(fb, carry + em)
                return a, a
            _, cols = jax.lax.scan(cell, jnp.full((B,), neg_inf), u_idx)
            return jnp.transpose(cols), None

        # t = 0 row: only emits along u
        def cell0(carry, u):
            em = jnp.where(u > 0, emit[:, 0, jnp.maximum(u - 1, 0)], neg_inf)
            a = jnp.where(u == 0, jnp.zeros((B,), lp.dtype), carry + em)
            return a, a
        _, cols0 = jax.lax.scan(cell0, jnp.full((B,), neg_inf), u_idx)
        alpha0 = jnp.transpose(cols0)                         # [B, U+1]

        def step(alpha, t):
            nxt, _ = row(alpha, t)
            return nxt, nxt
        _, rows = jax.lax.scan(step, alpha0, jnp.arange(1, T))
        all_rows = jnp.concatenate([alpha0[None], rows], 0)   # [T, B, U+1]

        # terminal: alpha[t_len-1, u_len] + blank(t_len-1, u_len)
        tb = jnp.clip(t_len.astype(jnp.int32) - 1, 0, T - 1)
        ub = jnp.clip(u_len.astype(jnp.int32), 0, U)
        bidx = jnp.arange(B)
        final = all_rows[tb, bidx, ub] + lb[bidx, tb, ub]
        loss = -final
        if reduction == "mean":
            return jnp.mean(loss)
        if reduction == "sum":
            return jnp.sum(loss)
        return loss

    return apply("rnnt_loss", impl, [logits])
