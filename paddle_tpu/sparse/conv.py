"""Sparse 3-D convolutions for point clouds (ref: paddle.sparse.nn.Conv3D /
SubmConv3D over paddle/phi/kernels/sparse/ gpu conv kernels — the
SURVEY §2.1 sparse-kernel row's conv3d gap).

TPU-native mechanism: no CUTLASS gather-scatter kernels. The rulebook is
built with sorted-key lookups (linearized voxel coordinates +
jnp.searchsorted, O(K·n·log n)) and each kernel offset becomes ONE dense
[n, in_c] × [in_c, out_c] matmul on the MXU, masked-accumulated into the
output features. Coordinates are data-dependent, so rulebook construction is
eager-only (dynamic shapes); the feature math itself goes through the
dispatch registry and is differentiable w.r.t. values/weight/bias.

Input layout: SparseCooTensor of shape [N, D, H, W, C] with 4 sparse dims
(batch + 3 spatial) and dense channels. Weight layout: [kd, kh, kw, in_c,
out_c] (paddle parity).
"""

from __future__ import annotations

import math
from typing import Sequence, Tuple

import jax.numpy as jnp
from jax.experimental import sparse as jsparse

from ..core.dispatch import apply
from ..core.tensor import Tensor

__all__ = ["subm_conv3d", "conv3d", "SubmConv3D", "Conv3D"]


def _triple(v) -> Tuple[int, int, int]:
    if isinstance(v, (tuple, list)):
        if len(v) != 3:
            raise ValueError(f"expected 3 values, got {v}")
        return tuple(int(x) for x in v)
    return (int(v),) * 3


def _linearize(coords, spatial: Sequence[int]):
    """[n, 4] (batch, d, h, w) int coords → unique sortable int64-ish key.
    Out-of-bounds coordinates map to -1."""
    D, H, W = spatial
    b, d, h, w = (coords[:, i] for i in range(4))
    valid = ((d >= 0) & (d < D) & (h >= 0) & (h < H)
             & (w >= 0) & (w < W))
    key = ((b * D + d) * H + h) * W + w
    return jnp.where(valid, key, -1), valid


def _offsets(kernel: Tuple[int, int, int]):
    kd, kh, kw = kernel
    out = []
    for a in range(kd):
        for b in range(kh):
            for c in range(kw):
                out.append((a, b, c))
    return out


def _gather_rulebook(in_coords, out_coords, spatial, kernel, stride, padding,
                     subm: bool):
    """For each kernel offset k: (gather_index_into_sorted_inputs, found).

    out[c] = Σ_k W_k · in[c·stride − padding + off_k]   (cross-correlation)
    For subm convs stride=1 and padding=(kernel−1)/2, so the neighbor of the
    center offset is the site itself.
    """
    in_keys, _ = _linearize(in_coords, spatial)
    order = jnp.argsort(in_keys)
    sorted_keys = in_keys[order]
    idxs, founds = [], []
    st = jnp.asarray(stride, jnp.int32)
    pad = jnp.asarray(padding, jnp.int32)
    for off in _offsets(kernel):
        nb = jnp.concatenate(
            [out_coords[:, :1],
             out_coords[:, 1:] * st[None, :] - pad[None, :]
             + jnp.asarray(off, jnp.int32)[None, :]], axis=1)
        nb_keys, valid = _linearize(nb, spatial)
        pos = jnp.searchsorted(sorted_keys, nb_keys)
        pos = jnp.clip(pos, 0, sorted_keys.shape[0] - 1)
        found = (sorted_keys[pos] == nb_keys) & valid & (nb_keys >= 0)
        idxs.append(order[pos])
        founds.append(found)
    return jnp.stack(idxs), jnp.stack(founds)


def _sparse_conv(x, weight, bias, kernel, stride, padding, subm: bool,
                 out_channels: int):
    from . import SparseCooTensor, is_sparse
    if not is_sparse(x):
        raise TypeError("sparse conv expects a SparseCooTensor input")
    b = x._bcoo
    if b.n_sparse != 4 or b.data.ndim != 2:
        raise ValueError("expected [N, D, H, W, C] layout: 4 sparse dims + "
                         "dense channels")
    N, D, H, W, C = b.shape
    import jax as _jax
    x64 = bool(_jax.config.jax_enable_x64)
    if N * D * H * W > 2**31 - 1 and not x64:
        raise ValueError(
            f"voxel key space N*D*H*W = {N * D * H * W} exceeds int32; "
            "enable JAX x64 (JAX_ENABLE_X64=1) for grids this large")
    # keys must be computed in a dtype that actually holds N*D*H*W
    key_dtype = jnp.int64 if x64 else jnp.int32
    spatial = (D, H, W)
    in_coords = b.indices.astype(key_dtype)
    kd, kh, kw = kernel

    if subm:
        out_coords = in_coords
        out_spatial = spatial
    else:
        # output sites: every position some input voxel contributes to
        # (data-dependent → eager-only), out = floor((c + pad − off)/stride)
        st = jnp.asarray(stride, jnp.int32)
        pad = jnp.asarray(padding, jnp.int32)
        cands = []
        for off in _offsets(kernel):
            num = in_coords[:, 1:] + pad[None, :] \
                - jnp.asarray(off, jnp.int32)[None, :]
            ok = (num % st[None, :] == 0).all(axis=1)
            oc = num // st[None, :]
            cands.append((jnp.concatenate([in_coords[:, :1], oc], 1), ok))
        out_spatial = tuple(
            (s + 2 * p - k) // t + 1
            for s, p, k, t in zip(spatial, padding, kernel, stride))
        all_coords = jnp.concatenate([c for c, _ in cands], 0)
        all_ok = jnp.concatenate([o for _, o in cands], 0)
        keys, valid = _linearize(all_coords, out_spatial)
        keys = jnp.where(all_ok & valid, keys, -1)
        uniq = jnp.unique(keys)
        uniq = uniq[uniq >= 0]
        od, oh, ow = out_spatial
        w_ = uniq % ow
        h_ = (uniq // ow) % oh
        d_ = (uniq // (ow * oh)) % od
        b_ = uniq // (ow * oh * od)
        out_coords = jnp.stack([b_, d_, h_, w_], 1).astype(key_dtype)

    gather_idx, found = _gather_rulebook(in_coords, out_coords, spatial,
                                         kernel, stride, padding, subm)
    K = kd * kh * kw

    def impl(values, w, *maybe_bias):
        wk = w.reshape(K, C, out_channels)
        out = jnp.zeros((out_coords.shape[0], out_channels), values.dtype)
        for k in range(K):
            g = values[gather_idx[k]] * found[k][:, None].astype(values.dtype)
            out = out + g @ wk[k]
        if maybe_bias:
            out = out + maybe_bias[0]
        return out

    # x.values() returns the tape-tracked Tensor when a previous sparse op
    # produced it — required for gradients to flow through STACKED convs
    inputs = [x.values(), weight]
    if bias is not None:
        inputs.append(bias)
    out_vals = apply("subm_conv3d" if subm else "sparse_conv3d", impl, inputs)
    out_shape = (N,) + out_spatial + (out_channels,)
    result = SparseCooTensor(jsparse.BCOO((out_vals._data, out_coords),
                                          shape=out_shape))
    result._values_tensor = out_vals  # keep the autograd-tracked values
    return result


def subm_conv3d(x, weight, bias=None, stride=1, padding=None, name=None):
    """Submanifold conv: output sites == input sites (ref:
    paddle.sparse.nn.functional.subm_conv3d). stride must be 1."""
    w = weight._data if isinstance(weight, Tensor) else jnp.asarray(weight)
    kd, kh, kw, ic, oc = w.shape
    if _triple(stride) != (1, 1, 1):
        raise ValueError("subm_conv3d requires stride 1")
    pad = _triple(padding) if padding is not None else \
        ((kd - 1) // 2, (kh - 1) // 2, (kw - 1) // 2)
    return _sparse_conv(x, weight, bias, (kd, kh, kw), (1, 1, 1), pad,
                        subm=True, out_channels=oc)


def conv3d(x, weight, bias=None, stride=1, padding=0, name=None):
    """Standard sparse conv: output sites densify per the kernel footprint
    (ref: paddle.sparse.nn.functional.conv3d)."""
    w = weight._data if isinstance(weight, Tensor) else jnp.asarray(weight)
    kd, kh, kw, ic, oc = w.shape
    return _sparse_conv(x, weight, bias, (kd, kh, kw), _triple(stride),
                        _triple(padding), subm=False, out_channels=oc)


from ..nn import Layer as _Layer  # noqa: E402
from ..nn import initializer as _I  # noqa: E402


class _ConvBase(_Layer):
    """Real nn.Layer so enclosing models see the weights in parameters()
    and state_dict (paddle parity: sparse convs are Layers)."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, bias_attr=None):
        super().__init__()
        self.in_channels = in_channels
        self.out_channels = out_channels
        self.kernel_size = _triple(kernel_size)
        self.stride = _triple(stride)
        self.padding = _triple(padding)
        kd, kh, kw = self.kernel_size
        fan_in = in_channels * kd * kh * kw
        std = math.sqrt(2.0 / fan_in)
        self.weight = self.create_parameter(
            [kd, kh, kw, in_channels, out_channels],
            default_initializer=_I.Normal(0.0, std))
        if bias_attr is not False:
            self.bias = self.create_parameter([out_channels], is_bias=True,
                                              attr=bias_attr)
        else:
            self.bias = None


class SubmConv3D(_ConvBase):
    """paddle.sparse.nn.SubmConv3D parity (point-cloud backbone block).

    Submanifold semantics (spconv/paddle): the kernel is CENTERED on each
    active site and output sites equal input sites; the `padding` argument
    is accepted for signature parity but does not change the neighborhood.
    """

    def forward(self, x):
        return subm_conv3d(x, self.weight, self.bias, stride=1,
                           padding=None)


class Conv3D(_ConvBase):
    """paddle.sparse.nn.Conv3D parity."""

    def forward(self, x):
        return conv3d(x, self.weight, self.bias, stride=self.stride,
                      padding=self.padding)
