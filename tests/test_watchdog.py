"""Collective watchdog & flight recorder (ISSUE 3): ring-buffer
recording at every collective entry, hang detection within
FLAGS_collective_timeout with a JSON post-mortem dump, cross-rank desync
diagnosis through the rendezvous store, merge/first-divergence tooling,
the trainer's emergency-checkpoint path on CollectiveTimeout, and the
watchdog-off overhead gate."""

import json
import os
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu import resilience as res
from paddle_tpu.distributed import collective as coll
from paddle_tpu.distributed import watchdog as wd
from paddle_tpu.flags import flags_guard
from paddle_tpu.io import Dataset
from paddle_tpu.trainer.trainer import Trainer, TrainingArguments


@pytest.fixture(autouse=True)
def _clean_watchdog():
    res.clear_fault_spec()
    wd.reset()
    yield
    res.clear_fault_spec()
    wd.stop_monitor()
    wd.detach_store()
    wd.set_recording(False)
    wd.reset()


def _metric(name: str) -> float:
    snap = wd.metrics().get(name)
    if not snap:
        return 0.0
    return sum(s["value"] for s in snap["series"])


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------
def test_recorder_ring_seq_and_eviction():
    r = wd.FlightRecorder(capacity=3)
    for i in range(5):
        rec = r.start("all_reduce", [[4, 4]], ["float32"], 64, "dp")
        r.finish(rec, "ok")
    recs = r.records()
    assert len(recs) == 3                       # fixed-size ring evicted
    assert [x.seq for x in recs] == [3, 4, 5]   # monotonic seq survives
    assert r.last_completed().seq == 5
    assert all(x.status == "ok" and x.end is not None for x in recs)


def test_recording_off_by_default():
    assert not wd.enabled()                     # FLAGS_collective_timeout=0
    assert wd.start_record("all_reduce") is None
    coll.all_reduce(paddle.to_tensor(np.ones(4, np.float32)))
    assert wd.recorder().records() == []


def test_collective_calls_recorded_with_shapes():
    wd.set_recording(True)
    t = paddle.to_tensor(np.ones((2, 3), np.float32))
    coll.all_reduce(t)
    coll.barrier()
    recs = wd.recorder().records()
    assert [r.op for r in recs] == ["all_reduce", "barrier"]
    ar = recs[0]
    assert ar.shapes == [[2, 3]] and ar.dtypes == ["float32"]
    assert ar.bytes == 2 * 3 * 4
    assert ar.status == "ok" and ar.seq == 1
    assert _metric("watchdog.collectives_recorded") >= 2


def test_injected_error_recorded_as_error():
    wd.set_recording(True)
    res.set_fault_spec("seed=9;collective_error@collective=all_reduce")
    with pytest.raises(res.InjectedFault):
        coll.all_reduce(paddle.to_tensor(np.ones(4, np.float32)))
    rec = wd.recorder().records()[-1]
    assert rec.op == "all_reduce" and rec.status == "error"


def test_dump_format(tmp_path):
    wd.set_recording(True)
    coll.all_reduce(paddle.to_tensor(np.ones(4, np.float32)))
    p = wd.dump_to(str(tmp_path / "flightdump.0.json"))
    d = json.load(open(p))
    assert d["version"] == 1 and d["rank"] == 0
    assert d["last_completed_seq"] == 1
    (rec,) = d["records"]
    assert rec["op"] == "all_reduce" and rec["status"] == "ok"
    assert rec["seq"] == 1 and rec["duration_s"] >= 0
    assert set(rec) >= {"seq", "op", "shapes", "dtypes", "bytes", "axis",
                        "start", "end", "duration_s", "status"}


# ---------------------------------------------------------------------------
# hang detection (tentpole acceptance)
# ---------------------------------------------------------------------------
def test_hang_detected_within_timeout(tmp_path, monkeypatch):
    monkeypatch.setenv("PADDLE_LOG_DIR", str(tmp_path))
    res.set_fault_spec(
        "seed=1;collective_hang@collective=all_reduce:ms=30000")
    before = _metric("watchdog.timeouts")
    with flags_guard(collective_timeout=0.3):
        t0 = time.monotonic()
        with pytest.raises(wd.CollectiveTimeout) as ei:
            coll.all_reduce(paddle.to_tensor(np.ones(4, np.float32)))
        elapsed = time.monotonic() - t0
    # detected within the deadline (not the 30s hang), with the diagnosis
    assert 0.25 <= elapsed < 5.0
    e = ei.value
    assert e.op == "all_reduce" and e.seq == 1
    assert e.elapsed_s >= 0.3
    assert _metric("watchdog.timeouts") >= before + 1
    # the dump landed in the worker log dir and names the hung op
    assert e.dump_path == str(tmp_path / "flightdump.0.json")
    d = json.load(open(e.dump_path))
    assert d["timed_out_seq"] == 1
    assert d["records"][0]["op"] == "all_reduce"
    assert d["records"][0]["status"] == "timeout"


def test_unguarded_hang_is_bounded_by_ms():
    # watchdog off: the injected hang still returns after ms, not forever
    res.set_fault_spec("seed=1;collective_hang@collective=all_reduce:ms=50")
    t0 = time.monotonic()
    coll.all_reduce(paddle.to_tensor(np.ones(4, np.float32)))
    assert 0.04 <= time.monotonic() - t0 < 5.0


def test_barrier_timeout_on_dead_peer(tmp_path, monkeypatch):
    """Satellite bugfix: barrier() must raise CollectiveTimeout instead of
    hanging forever when a peer never completes (block_until_ready
    blocks)."""
    import jax

    class DeadPeerArray:
        def block_until_ready(self):
            time.sleep(10.0)

    monkeypatch.setenv("PADDLE_LOG_DIR", str(tmp_path))
    monkeypatch.setattr(jax, "live_arrays", lambda: [DeadPeerArray()])
    with flags_guard(collective_timeout=0.2):
        t0 = time.monotonic()
        with pytest.raises(wd.CollectiveTimeout, match="barrier"):
            coll.barrier()
        assert time.monotonic() - t0 < 5.0
    rec = wd.recorder().records()[-1]
    assert rec.op == "barrier" and rec.status == "timeout"


# ---------------------------------------------------------------------------
# cross-rank desync
# ---------------------------------------------------------------------------
def test_publish_progress_and_desync_report():
    from paddle_tpu.native import TCPStore
    s = TCPStore(is_master=True, world_size=2)
    try:
        wd.attach_store(s, rank=0, world_size=2, slot=0)
        wd.set_recording(True)
        coll.all_reduce(paddle.to_tensor(np.ones(4, np.float32)))
        coll.all_reduce(paddle.to_tensor(np.ones(4, np.float32)))
        wd.publish_progress()
        # a peer stuck one op behind publishes its own progress
        s.set("flight/1",
              f"{time.time()}|rank=1,seq=1,op=all_reduce,"
              f"inflight=all_gather,inflight_seq=2,status=inflight")
        rep = wd.desync_report(s, world_size=2)
        assert rep["desynced"]
        assert rep["lagging_rank"] == 1
        assert rep["lagging_op"] == "all_gather"
        assert rep["min_seq"] == 1 and rep["max_seq"] == 2
        # the heartbeat payload channel stays parseable by the launcher
        from paddle_tpu.distributed.launch import ElasticManager
        m = ElasticManager(s, node_rank=0, ttl=5.0)
        assert 0 in m.alive_nodes(1)
    finally:
        s.close()


def test_desync_report_names_silent_rank():
    from paddle_tpu.native import TCPStore
    s = TCPStore(is_master=True, world_size=2)
    try:
        s.set("flight/0", f"{time.time()}|rank=0,seq=5,op=all_reduce,"
                          f"inflight=,inflight_seq=0,status=idle")
        rep = wd.desync_report(s, world_size=2)
        # rank 1 never published: it is the laggard by definition
        assert rep["missing"] == [1]
        assert rep["lagging_rank"] == 1 and rep["desynced"]
    finally:
        s.close()


def test_hang_dump_names_lagging_rank(tmp_path, monkeypatch):
    """Acceptance: the flight dump written on timeout carries the
    cross-rank desync report naming the lagging rank."""
    from paddle_tpu.native import TCPStore
    monkeypatch.setenv("PADDLE_LOG_DIR", str(tmp_path))
    s = TCPStore(is_master=True, world_size=2)
    try:
        wd.attach_store(s, rank=0, world_size=2, slot=0)
        # the peer (rank 1) never completed anything: it is the laggard
        # whose absence makes OUR collective hang
        s.set("flight/1", f"{time.time()}|rank=1,seq=0,op=,"
                          f"inflight=all_reduce,inflight_seq=1,"
                          f"status=inflight")
        # hang the 2nd all_reduce (2 candidate sites per call -> n=3):
        # we completed seq 1, the peer completed nothing
        res.set_fault_spec("seed=1;collective_hang@n=3:ms=30000")
        with flags_guard(collective_timeout=0.25):
            coll.all_reduce(paddle.to_tensor(np.ones(4, np.float32)))
            with pytest.raises(wd.CollectiveTimeout) as ei:
                coll.all_reduce(paddle.to_tensor(np.ones(4, np.float32)))
        assert ei.value.lagging_rank == 1
        d = json.load(open(ei.value.dump_path))
        assert d["desync"]["lagging_rank"] == 1
        assert d["desync"]["desynced"]
    finally:
        s.close()


# ---------------------------------------------------------------------------
# post-mortem merge + CLI
# ---------------------------------------------------------------------------
def _dump(rank, records, last=None):
    return {"version": 1, "rank": rank,
            "last_completed_seq": last if last is not None else max(
                (r["seq"] for r in records if r["status"] == "ok"),
                default=0),
            "records": records}


def _rec(seq, op, status="ok", shapes=((4,),)):
    return {"seq": seq, "op": op, "shapes": [list(s) for s in shapes],
            "dtypes": ["float32"], "bytes": 16, "axis": "dp",
            "start": 0.0, "end": 0.1, "duration_s": 0.1, "status": status}


def test_merge_dumps_names_lagging_rank_and_timeout():
    d0 = _dump(0, [_rec(1, "all_reduce"), _rec(2, "all_gather"),
                   _rec(3, "all_reduce", status="timeout")], last=2)
    d1 = _dump(1, [_rec(1, "all_reduce")], last=1)
    m = wd.merge_dumps([d0, d1])
    assert m["world"] == 2 and m["ranks"] == [0, 1]
    assert m["last_completed_seq"] == {0: 2, 1: 1}
    assert m["lagging_rank"] == 1
    fd = m["first_divergence"]
    assert fd["seq"] == 2 and fd["reason"] == "missing_rank"
    assert fd["missing"] == [1]
    # merged records interleave by (seq, rank)
    assert [(r["seq"], r["rank"]) for r in m["records"]] == [
        (1, 0), (1, 1), (2, 0), (3, 0)]


def test_first_divergence_detects_op_mismatch():
    d0 = _dump(0, [_rec(1, "all_reduce"), _rec(2, "all_gather")])
    d1 = _dump(1, [_rec(1, "all_reduce"), _rec(2, "broadcast")])
    fd = wd.first_divergence([d0, d1])
    assert fd["seq"] == 2 and fd["reason"] == "op_mismatch"
    assert fd["ops"] == {0: "all_gather", 1: "broadcast"}


def test_first_divergence_none_when_consistent():
    d0 = _dump(0, [_rec(1, "all_reduce"), _rec(2, "barrier")])
    d1 = _dump(1, [_rec(1, "all_reduce"), _rec(2, "barrier")])
    assert wd.first_divergence([d0, d1]) is None


def _cli():
    import importlib.util
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools", "flight_recorder.py")
    spec = importlib.util.spec_from_file_location("flight_recorder_cli",
                                                  path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_flight_recorder_cli_merge_and_diff(tmp_path, capsys):
    cli = _cli()
    logs = tmp_path / "log"
    logs.mkdir()
    (logs / "flightdump.0.json").write_text(json.dumps(
        _dump(0, [_rec(1, "all_reduce"), _rec(2, "all_gather")], last=2)))
    (logs / "flightdump.1.json").write_text(json.dumps(
        _dump(1, [_rec(1, "all_reduce"),
                  _rec(2, "all_gather", status="timeout")], last=1)))
    out = tmp_path / "report.json"
    rc = cli.main(["merge", str(logs), "-o", str(out)])
    assert rc == 1                              # divergence found
    rep = json.loads(out.read_text())
    assert rep["lagging_rank"] == 1
    assert rep["first_divergence"]["seq"] == 2
    assert rep["first_divergence"]["reason"] == "not_ok"
    rc = cli.main(["diff", str(logs)])
    assert rc == 1
    shown = capsys.readouterr().out
    assert "lagging_rank" in shown and '"seq": 2' in shown
    # consistent dumps -> exit 0
    (logs / "flightdump.1.json").write_text(json.dumps(
        _dump(1, [_rec(1, "all_reduce"), _rec(2, "all_gather")], last=2)))
    assert cli.main(["diff", str(logs)]) == 0


def test_write_watchdog_report(tmp_path):
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools"))
    try:
        import bench_util
    finally:
        sys.path.pop(0)
    wd.set_recording(True)
    coll.all_reduce(paddle.to_tensor(np.ones(4, np.float32)))
    p = str(tmp_path / "wd_report.json")
    rep = bench_util.write_watchdog_report(p, extra={"run": "unit"})
    assert os.path.exists(p)
    assert rep["run"] == "unit"
    assert rep["totals"]["watchdog.collectives_recorded"] >= 1
    assert rep["flight"]["records"][0]["op"] == "all_reduce"


# ---------------------------------------------------------------------------
# trainer integration (acceptance: chaos hang -> emergency ckpt -> resume)
# ---------------------------------------------------------------------------
class ToyDataset(Dataset):
    def __init__(self, n=64, seed=0):
        rng = np.random.RandomState(seed)
        self.x = rng.randn(n, 8).astype(np.float32)
        w = rng.randn(8, 2).astype(np.float32)
        self.y = self.x @ w

    def __len__(self):
        return len(self.x)

    def __getitem__(self, i):
        return self.x[i], self.y[i]


class CollNet(nn.Layer):
    """A net whose forward issues a collective every micro-batch (the
    grad-sync stand-in the hang drill targets)."""

    def __init__(self):
        super().__init__()
        self.fc = nn.Linear(8, 2)

    def forward(self, x, y=None):
        out = self.fc(x)
        coll.all_reduce(paddle.to_tensor(np.ones((1,), np.float32)))
        if y is not None:
            return ((out - y) ** 2).mean(), out
        return out


def _args(tmp_path, **kw):
    base = dict(output_dir=str(tmp_path), per_device_train_batch_size=8,
                learning_rate=5e-2, logging_steps=2, max_steps=10,
                warmup_steps=2, seed=7)
    base.update(kw)
    return TrainingArguments(**base)


def test_chaos_hang_emergency_checkpoint_and_resume(tmp_path, monkeypatch):
    monkeypatch.setenv("PADDLE_LOG_DIR", str(tmp_path / "log"))
    # fault-free reference
    t_ref = Trainer(model=CollNet(), args=_args(tmp_path / "ref"),
                    train_dataset=ToyDataset())
    assert t_ref.train()["global_step"] == 10

    # hang the 5th all_reduce (each call = 2 candidate sites -> n=9),
    # 30s unguarded; the watchdog deadline is 0.3s
    res.set_fault_spec("seed=3;collective_hang@n=9:ms=30000")
    out = tmp_path / "chaos"
    args = _args(out)
    t = Trainer(model=CollNet(), args=args, train_dataset=ToyDataset())
    before = _metric("watchdog.timeouts")
    with flags_guard(collective_timeout=0.3):
        t0 = time.monotonic()
        with pytest.raises(wd.CollectiveTimeout) as ei:
            t.train()
        assert time.monotonic() - t0 < 30.0     # detected, not the hang
    assert ei.value.op == "all_reduce"
    assert _metric("watchdog.timeouts") >= before + 1
    # flight dump names the hung op
    d = json.load(open(ei.value.dump_path))
    assert d["timed_out_seq"] == ei.value.seq
    timed_out = [r for r in d["records"] if r["status"] == "timeout"]
    assert timed_out and timed_out[0]["op"] == "all_reduce"
    # the trainer took the emergency-checkpoint path: step 5's forward
    # hung, so the last applied step (4) was checkpointed
    assert t.state["global_step"] == 4
    emergency = out / "checkpoint-4"
    assert emergency.is_dir()
    entry = next(e for e in t.state["log_history"]
                 if "collective_timeout" in e)
    assert "all_reduce" in entry["collective_timeout"]
    assert entry["emergency_checkpoint"] == str(emergency)

    # clear the fault, resume -> same final step count as fault-free
    res.clear_fault_spec()
    t2 = Trainer(model=CollNet(), args=args, train_dataset=ToyDataset())
    state2 = t2.train(resume_from_checkpoint=str(emergency))
    assert state2["global_step"] == 10


# ---------------------------------------------------------------------------
# overhead gate: watchdog off must not tax the collective hot path
# ---------------------------------------------------------------------------
class TestOverhead:
    def test_disabled_overhead_under_5pct(self):
        assert not wd.enabled()
        a = np.random.RandomState(0).randn(160, 160).astype(np.float32)
        n = 600

        def plain():
            t0 = time.perf_counter()
            for _ in range(n):
                a.dot(a)
            return time.perf_counter() - t0

        def instrumented():
            t0 = time.perf_counter()
            for _ in range(n):
                a.dot(a)
                rec = wd.start_record("all_reduce")
                wd.end_record(rec)
            return time.perf_counter() - t0

        # warm both paths, then interleave rounds and compare the best
        # observation of each (min filters scheduler noise)
        plain()
        instrumented()
        tp, ti = [], []
        for _ in range(7):
            tp.append(plain())
            ti.append(instrumented())
        assert wd.recorder().records() == []    # the gate really gated
        assert min(ti) < min(tp) * 1.05, (
            f"disabled-watchdog loop {min(ti):.4f}s vs plain {min(tp):.4f}s "
            f"(+{(min(ti) / min(tp) - 1) * 100:.1f}%)")
