"""Fused elementwise/norm Pallas kernels: rms_norm, rope, swiglu.

Reference capability (SURVEY §2.1 fused kernels): RmsNormKernel,
FusedRopeKernel, swiglu (paddle/phi/kernels/fusion/gpu/,
python/paddle/incubate/nn/functional/). Here the device kernels are Pallas
TPU kernels (the accepted ".cu analog"); on non-TPU backends they run in
Pallas interpret mode for correctness tests, and each op carries a custom
VJP whose backward is plain XLA math (fused by the compiler).

Kernel design notes (pallas_guide.md):
- blocks keep the last dim = hidden (lane-dim multiple of 128 for real
  models) and tile rows in the sublane dim;
- rms_norm reduces in f32 on the VPU, one HBM round-trip per block;
- rope loads cos/sin once per block (broadcast over batch rows).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["fused_rms_norm", "fused_rope", "swiglu", "fused_layer_norm",
           "fused_bias_residual_layer_norm", "fused_moe_dispatch_combine",
           "fused_rope_append", "fused_append_rows"]


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


# jax renamed TPUCompilerParams -> CompilerParams; accept both
_CompilerParams = getattr(pltpu, "CompilerParams",
                          getattr(pltpu, "TPUCompilerParams", None))


def _row_block(n_rows: int) -> int:
    for b in (256, 128, 64, 32, 16, 8):
        if n_rows % b == 0:
            return b
    return 1


# ---------------------------------------------------------------------------
# rms_norm
# ---------------------------------------------------------------------------

def _rms_kernel(x_ref, w_ref, o_ref, *, eps: float):
    x = x_ref[:].astype(jnp.float32)
    var = jnp.mean(x * x, axis=-1, keepdims=True)
    y = x * jax.lax.rsqrt(var + eps)
    o_ref[:] = (y * w_ref[:].astype(jnp.float32)).astype(o_ref.dtype)


def _rms_forward(x2, w, eps):
    T, H = x2.shape
    bt = _row_block(T)
    return pl.pallas_call(
        functools.partial(_rms_kernel, eps=eps),
        grid=(T // bt,),
        in_specs=[pl.BlockSpec((bt, H), lambda i: (i, 0)),
                  pl.BlockSpec((H,), lambda i: (0,))],
        out_specs=pl.BlockSpec((bt, H), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((T, H), x2.dtype),
        interpret=_interpret(),
    )(x2, w)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def _rms_norm(x2, w, eps):
    return _rms_forward(x2, w, eps)


def _rms_fwd(x2, w, eps):
    return _rms_forward(x2, w, eps), (x2, w)


def _rms_bwd(eps, res, g):
    x2, w = res
    x = x2.astype(jnp.float32)
    gf = g.astype(jnp.float32)
    wf = w.astype(jnp.float32)
    H = x.shape[-1]
    var = jnp.mean(x * x, axis=-1, keepdims=True)
    r = jax.lax.rsqrt(var + eps)
    xhat = x * r
    dw = jnp.sum(gf * xhat, axis=0).astype(w.dtype)
    gw = gf * wf
    dx = r * (gw - xhat * jnp.mean(gw * xhat, axis=-1, keepdims=True))
    return dx.astype(x2.dtype), dw


_rms_norm.defvjp(_rms_fwd, _rms_bwd)


def fused_rms_norm(x, weight, eps: float = 1e-6):
    """x [..., H] * rms-normalized, scaled by weight [H]."""
    shape = x.shape
    x2 = x.reshape(-1, shape[-1])
    out = _rms_norm(x2, weight, float(eps))
    return out.reshape(shape)


# ---------------------------------------------------------------------------
# layer_norm (fused bias+scale)
# ---------------------------------------------------------------------------

def _ln_kernel(x_ref, w_ref, b_ref, o_ref, *, eps: float):
    x = x_ref[:].astype(jnp.float32)
    mu = jnp.mean(x, axis=-1, keepdims=True)
    xc = x - mu
    var = jnp.mean(xc * xc, axis=-1, keepdims=True)
    y = xc * jax.lax.rsqrt(var + eps)
    o_ref[:] = (y * w_ref[:].astype(jnp.float32)
                + b_ref[:].astype(jnp.float32)).astype(o_ref.dtype)


def fused_layer_norm(x, weight, bias, eps: float = 1e-5):
    shape = x.shape
    H = shape[-1]
    x2 = x.reshape(-1, H)
    T = x2.shape[0]
    bt = _row_block(T)
    out = pl.pallas_call(
        functools.partial(_ln_kernel, eps=float(eps)),
        grid=(T // bt,),
        in_specs=[pl.BlockSpec((bt, H), lambda i: (i, 0)),
                  pl.BlockSpec((H,), lambda i: (0,)),
                  pl.BlockSpec((H,), lambda i: (0,))],
        out_specs=pl.BlockSpec((bt, H), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((T, H), x2.dtype),
        interpret=_interpret(),
    )(x2, weight, bias)
    return out.reshape(shape)


# ---------------------------------------------------------------------------
# bias + residual + layer_norm (ref: FusedBiasDropoutResidualLnKernel,
# paddle/phi/kernels/fusion/gpu/fused_bias_dropout_residual_layer_norm*.
# Eval-mode form — dropout is identity; the whole add+add+LN chain runs
# in ONE kernel / one HBM round-trip instead of three.)
# ---------------------------------------------------------------------------

def _brln_kernel(x_ref, r_ref, b_ref, w_ref, lb_ref, o_ref, *, eps: float):
    h = (x_ref[:].astype(jnp.float32) + b_ref[:].astype(jnp.float32)
         + r_ref[:].astype(jnp.float32))
    mu = jnp.mean(h, axis=-1, keepdims=True)
    hc = h - mu
    var = jnp.mean(hc * hc, axis=-1, keepdims=True)
    y = hc * jax.lax.rsqrt(var + eps)
    o_ref[:] = (y * w_ref[:].astype(jnp.float32)
                + lb_ref[:].astype(jnp.float32)).astype(o_ref.dtype)


def _brln_forward(x2, r2, b, w, lb, eps):
    T, H = x2.shape
    bt = _row_block(T)
    return pl.pallas_call(
        functools.partial(_brln_kernel, eps=float(eps)),
        grid=(T // bt,),
        in_specs=[pl.BlockSpec((bt, H), lambda i: (i, 0)),
                  pl.BlockSpec((bt, H), lambda i: (i, 0)),
                  pl.BlockSpec((H,), lambda i: (0,)),
                  pl.BlockSpec((H,), lambda i: (0,)),
                  pl.BlockSpec((H,), lambda i: (0,))],
        out_specs=pl.BlockSpec((bt, H), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((T, H), x2.dtype),
        interpret=_interpret(),
    )(x2, r2, b, w, lb)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5,))
def _brln(x2, r2, b, w, lb, eps):
    return _brln_forward(x2, r2, b, w, lb, eps)


def _brln_fwd(x2, r2, b, w, lb, eps):
    return _brln_forward(x2, r2, b, w, lb, eps), (x2, r2, b, w, lb)


def _brln_bwd(eps, res, g):
    # standard layer-norm backward over h = x + b + r, in plain XLA math
    x2, r2, b, w, lb = res
    h = (x2.astype(jnp.float32) + b.astype(jnp.float32)
         + r2.astype(jnp.float32))
    gf = g.astype(jnp.float32)
    mu = jnp.mean(h, -1, keepdims=True)
    hc = h - mu
    var = jnp.mean(hc * hc, -1, keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    xhat = hc * rstd
    wf = w.astype(jnp.float32)
    dlb = jnp.sum(gf, axis=0).astype(lb.dtype)
    dw = jnp.sum(gf * xhat, axis=0).astype(w.dtype)
    gx = gf * wf
    dh = rstd * (gx - jnp.mean(gx, -1, keepdims=True)
                 - xhat * jnp.mean(gx * xhat, -1, keepdims=True))
    dx = dh.astype(x2.dtype)
    db = jnp.sum(dh, axis=0).astype(b.dtype)
    return dx, dh.astype(r2.dtype), db, dw, dlb


_brln.defvjp(_brln_fwd, _brln_bwd)


def fused_bias_residual_layer_norm(x, residual, bias=None, weight=None,
                                   ln_bias=None, eps: float = 1e-5):
    """layer_norm((x + bias) + residual) in one Pallas kernel (custom
    VJP: plain-XLA LN backward). bias / weight / ln_bias optional
    (zeros/ones substituted)."""
    shape = x.shape
    H = shape[-1]
    x2 = x.reshape(-1, H)
    r2 = residual.reshape(-1, H)
    b = jnp.zeros((H,), x2.dtype) if bias is None else bias
    w = jnp.ones((H,), x2.dtype) if weight is None else weight
    lb = jnp.zeros((H,), x2.dtype) if ln_bias is None else ln_bias
    return _brln(x2, r2, b, w, lb, float(eps)).reshape(shape)


# ---------------------------------------------------------------------------
# MoE dispatch/combine mask build (ref: CINN fusing the GShard gate's
# one-hot/scale/einsum chain — paddle/cinn/operator_fusion; the two
# [T,k,E]x[T,k,C] contractions plus the gate-value scale run in ONE
# kernel, reading keep/one-hot once instead of twice.)
# ---------------------------------------------------------------------------

def _moe_dc_kernel(keep_ref, oh_ref, gv_ref, d_ref, c_ref):
    keep = keep_ref[:].astype(jnp.float32)      # [bt, k, E]
    oh = oh_ref[:].astype(jnp.float32)          # [bt, k, C]
    gv = gv_ref[:].astype(jnp.float32)          # [bt, k]
    disp = jax.lax.dot_general(
        keep, oh, (((1,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32)     # [bt, E, C]
    comb = jax.lax.dot_general(
        keep * gv[..., None], oh, (((1,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32)
    d_ref[:] = disp.astype(d_ref.dtype)
    c_ref[:] = comb.astype(c_ref.dtype)


def _moe_dc_forward(keep, oh_loc, gv):
    T, K, E = keep.shape
    C = oh_loc.shape[-1]
    bt = _row_block(T)
    return pl.pallas_call(
        _moe_dc_kernel,
        grid=(T // bt,),
        in_specs=[pl.BlockSpec((bt, K, E), lambda i: (i, 0, 0)),
                  pl.BlockSpec((bt, K, C), lambda i: (i, 0, 0)),
                  pl.BlockSpec((bt, K), lambda i: (i, 0))],
        out_specs=[pl.BlockSpec((bt, E, C), lambda i: (i, 0, 0)),
                   pl.BlockSpec((bt, E, C), lambda i: (i, 0, 0))],
        out_shape=[jax.ShapeDtypeStruct((T, E, C), keep.dtype),
                   jax.ShapeDtypeStruct((T, E, C), keep.dtype)],
        interpret=_interpret(),
    )(keep, oh_loc, gv)


@jax.custom_vjp
def fused_moe_dispatch_combine(keep, oh_loc, gv):
    """keep [T,k,E], oh_loc [T,k,C], gv [T,k] ->
    (dispatch [T,E,C], combine [T,E,C]) — the GShard gate's final
    einsum pair in one kernel (custom VJP: the pair is bilinear, the
    backward is three small einsums XLA fuses)."""
    return _moe_dc_forward(keep, oh_loc, gv)


def _moe_dc_fwd(keep, oh_loc, gv):
    return _moe_dc_forward(keep, oh_loc, gv), (keep, oh_loc, gv)


def _moe_dc_bwd(res, gs):
    keep, oh, gv = res
    dd, dc = gs
    ddf = dd.astype(jnp.float32)
    dcf = dc.astype(jnp.float32)
    kf = keep.astype(jnp.float32)
    of = oh.astype(jnp.float32)
    gf = gv.astype(jnp.float32)
    kg = kf * gf[..., None]
    dkeep = (jnp.einsum("tec,tkc->tke", ddf, of)
             + gf[..., None] * jnp.einsum("tec,tkc->tke", dcf, of))
    doh = (jnp.einsum("tec,tke->tkc", ddf, kf)
           + jnp.einsum("tec,tke->tkc", dcf, kg))
    dgv = jnp.einsum("tke,tkc,tec->tk", kf, of, dcf)
    return (dkeep.astype(keep.dtype), doh.astype(oh.dtype),
            dgv.astype(gv.dtype))


fused_moe_dispatch_combine.defvjp(_moe_dc_fwd, _moe_dc_bwd)


# ---------------------------------------------------------------------------
# rope
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=())
def _rope(x, cos, sin):
    return _rope_forward(x, cos, sin)


def _rope_pallas_kernel(x_ref, c_ref, s_ref, o_ref):
    # block: [1, bs, H, D] — rotate half (Llama convention)
    x = x_ref[:].astype(jnp.float32)
    c = c_ref[:].astype(jnp.float32)   # [1, bs, 1, D/2]
    s = s_ref[:].astype(jnp.float32)
    d2 = x.shape[-1] // 2
    x1, x2 = x[..., :d2], x[..., d2:]
    o = jnp.concatenate([x1 * c - x2 * s, x2 * c + x1 * s], axis=-1)
    o_ref[:] = o.astype(o_ref.dtype)


def _rope_forward(x, cos, sin):
    """x [B, S, H, D]; cos/sin [S, D/2]."""
    B, S, H, D = x.shape
    bs = _row_block(S)
    c4 = cos[None, :, None, :]
    s4 = sin[None, :, None, :]
    return pl.pallas_call(
        _rope_pallas_kernel,
        grid=(B, S // bs),
        in_specs=[pl.BlockSpec((1, bs, H, D), lambda b, i: (b, i, 0, 0)),
                  pl.BlockSpec((1, bs, 1, D // 2), lambda b, i: (0, i, 0, 0)),
                  pl.BlockSpec((1, bs, 1, D // 2), lambda b, i: (0, i, 0, 0))],
        out_specs=pl.BlockSpec((1, bs, H, D), lambda b, i: (b, i, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((B, S, H, D), x.dtype),
        interpret=_interpret(),
    )(x, c4, s4)


def _rope_fwd(x, cos, sin):
    return _rope_forward(x, cos, sin), (cos, sin)


def _rope_bwd(res, g):
    cos, sin = res
    # inverse rotation = rotation by -theta; cos/sin are non-diff buffers
    d2 = g.shape[-1] // 2
    g1, g2 = g[..., :d2].astype(jnp.float32), g[..., d2:].astype(jnp.float32)
    c = cos[None, :g.shape[1], None, :]
    s = sin[None, :g.shape[1], None, :]
    dx = jnp.concatenate([g1 * c + g2 * s, -g1 * s + g2 * c], axis=-1)
    return dx.astype(g.dtype), None, None


_rope.defvjp(_rope_fwd, _rope_bwd)


def fused_rope(q, k, cos, sin):
    """Fused rotary embedding on q [B,S,Hq,D] and k [B,S,Hk,D]
    (ref: fused_rotary_position_embedding)."""
    return _rope(q, cos, sin), _rope(k, cos, sin)


# ---------------------------------------------------------------------------
# rope + paged-cache append (serving decode path; no VJP — inference only)
# ---------------------------------------------------------------------------

def _rope_append_kernel(pg_ref, off_ref,              # scalar prefetch
                        q_ref, k_ref, v_ref, c_ref, s_ref,
                        kin_ref, vin_ref,
                        qo_ref, kp_ref, vp_ref):
    t = pl.program_id(0)
    c = c_ref[:].astype(jnp.float32)                   # [1, D/2]
    s = s_ref[:].astype(jnp.float32)

    def rot(x):                                        # [h, D] f32
        d2 = x.shape[-1] // 2
        x1, x2 = x[:, :d2], x[:, d2:]
        return jnp.concatenate([x1 * c - x2 * s, x2 * c + x1 * s], -1)

    qo_ref[0] = rot(q_ref[0].astype(jnp.float32)).astype(qo_ref.dtype)
    # first visit of a page seeds the resident output block from the
    # aliased input fetch; consecutive same-page tokens keep the block
    # resident, so their earlier row writes survive (re-seeding would
    # clobber them with the stale pre-launch page)
    prev = pg_ref[jnp.maximum(t - 1, 0)]

    @pl.when((t == 0) | (pg_ref[t] != prev))
    def _seed():
        kp_ref[:] = kin_ref[:]
        vp_ref[:] = vin_ref[:]

    off = off_ref[t]
    kr = rot(k_ref[0].astype(jnp.float32)).astype(kp_ref.dtype)
    kp_ref[:, 0, pl.dslice(off, 1), :] = kr[:, None, :]
    vp_ref[:, 0, pl.dslice(off, 1), :] = \
        v_ref[0].astype(vp_ref.dtype)[:, None, :]


def fused_rope_append(q, k, v, cos, sin, k_pages, v_pages,
                      page_idx, page_off):
    """Rotary embedding (per-TOKEN cos/sin rows) on q and k plus the
    paged-cache K/V row scatter in ONE pallas_call — the serving
    engine's fused rope+append step.

    q [T, Hq, D]; k/v [T, KV, D]; cos/sin [T, D/2]; k/v_pages
    [KV, total_pages, page_size, D]; page_idx/page_off [T] int32 name
    where token t's K/V row lands. Returns (q_roped, k_pages, v_pages)
    with the page pools donated through input_output_aliases (the HBM
    buffers update in place on TPU — callers must use the RETURNED
    pools, never re-read the donated arguments; paddlelint's PF402
    checks the caller side statically, and PE502 proves the kernel
    itself only reads each donated input before its first aliased
    write, so no defensive copy is ever needed here).

    Contract: tokens that share a page are ADJACENT in t (the engine's
    prefill chunk); non-adjacent revisits only happen on the trash page
    (inactive slots), whose content is garbage by design. Identity rope
    (cos=1, sin=0) turns this into a pure fused append for the GPT
    family."""
    T, Hq, D = q.shape
    KV = k.shape[1]
    total, psz = k_pages.shape[1], k_pages.shape[2]
    d2 = D // 2

    def tok_map(t, pg, off):
        return (t, 0, 0)

    def cs_map(t, pg, off):
        return (t, 0)

    def page_map(t, pg, off):
        return (0, jnp.clip(pg[t], 0, total - 1), 0, 0)

    page_spec = pl.BlockSpec((KV, 1, psz, D), page_map)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,                 # page_idx, page_off
        grid=(T,),
        in_specs=[
            pl.BlockSpec((1, Hq, D), tok_map),
            pl.BlockSpec((1, KV, D), tok_map),
            pl.BlockSpec((1, KV, D), tok_map),
            pl.BlockSpec((1, d2), cs_map),
            pl.BlockSpec((1, d2), cs_map),
            page_spec,
            page_spec,
        ],
        out_specs=[pl.BlockSpec((1, Hq, D), tok_map),
                   page_spec, page_spec],
    )
    return pl.pallas_call(
        _rope_append_kernel,
        grid_spec=grid_spec,
        out_shape=[jax.ShapeDtypeStruct((T, Hq, D), q.dtype),
                   jax.ShapeDtypeStruct(k_pages.shape, k_pages.dtype),
                   jax.ShapeDtypeStruct(v_pages.shape, v_pages.dtype)],
        # flat-input indices INCLUDE the scalar-prefetch operands
        input_output_aliases={7: 1, 8: 2},
        compiler_params=_CompilerParams(
            dimension_semantics=("arbitrary",)),
        interpret=_interpret(),
    )(page_idx.astype(jnp.int32), page_off.astype(jnp.int32),
      q, k, v, cos, sin, k_pages, v_pages)


def _append_rows_kernel(pg_ref, off_ref, r_ref, pin_ref, po_ref):
    t = pl.program_id(0)
    prev = pg_ref[jnp.maximum(t - 1, 0)]

    @pl.when((t == 0) | (pg_ref[t] != prev))
    def _seed():
        po_ref[:] = pin_ref[:]

    po_ref[:, 0, pl.dslice(off_ref[t], 1), :] = \
        r_ref[0].astype(po_ref.dtype)[:, None, :]


def fused_append_rows(pages, rows, page_idx, page_off):
    """Scatter per-token cache rows [T, KV, D] into paged pools
    [KV, total_pages, page_size, D] at (page_idx[t], page_off[t]) in one
    pallas_call — the MLA engine's latent-row append (its rope runs on
    split q_pe/k_pe shapes before the rows are concatenated). Same
    adjacency contract as fused_rope_append."""
    T, KV, D = rows.shape
    total, psz = pages.shape[1], pages.shape[2]

    def page_map(t, pg, off):
        return (0, jnp.clip(pg[t], 0, total - 1), 0, 0)

    page_spec = pl.BlockSpec((KV, 1, psz, D), page_map)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(T,),
        in_specs=[pl.BlockSpec((1, KV, D), lambda t, pg, off: (t, 0, 0)),
                  page_spec],
        out_specs=page_spec,
    )
    return pl.pallas_call(
        _append_rows_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(pages.shape, pages.dtype),
        input_output_aliases={3: 0},
        compiler_params=_CompilerParams(
            dimension_semantics=("arbitrary",)),
        interpret=_interpret(),
    )(page_idx.astype(jnp.int32), page_off.astype(jnp.int32),
      rows, pages)


# ---------------------------------------------------------------------------
# swiglu
# ---------------------------------------------------------------------------

def _swiglu_kernel(g_ref, u_ref, o_ref):
    g = g_ref[:].astype(jnp.float32)
    u = u_ref[:].astype(jnp.float32)
    o_ref[:] = (g * jax.lax.logistic(g) * u).astype(o_ref.dtype)


@jax.custom_vjp
def _swiglu(g2, u2):
    return _swiglu_forward(g2, u2)


def _swiglu_forward(g2, u2):
    T, H = g2.shape
    bt = _row_block(T)
    return pl.pallas_call(
        _swiglu_kernel,
        grid=(T // bt,),
        in_specs=[pl.BlockSpec((bt, H), lambda i: (i, 0)),
                  pl.BlockSpec((bt, H), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((bt, H), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((T, H), g2.dtype),
        interpret=_interpret(),
    )(g2, u2)


def _swiglu_fwd(g2, u2):
    return _swiglu_forward(g2, u2), (g2, u2)


def _swiglu_bwd(res, d):
    g, u = res
    gf = g.astype(jnp.float32)
    uf = u.astype(jnp.float32)
    df = d.astype(jnp.float32)
    sig = jax.lax.logistic(gf)
    silu = gf * sig
    dsilu = sig * (1 + gf * (1 - sig))
    return ((df * uf * dsilu).astype(g.dtype),
            (df * silu).astype(u.dtype))


_swiglu.defvjp(_swiglu_fwd, _swiglu_bwd)


def swiglu(gate, up=None):
    """silu(gate) * up (ref: paddle.incubate.nn.functional.swiglu; when `up`
    is None the last dim of `gate` is split in half)."""
    if up is None:
        d = gate.shape[-1] // 2
        gate, up = gate[..., :d], gate[..., d:]
    shape = gate.shape
    g2 = gate.reshape(-1, shape[-1])
    u2 = up.reshape(-1, shape[-1])
    return _swiglu(g2, u2).reshape(shape)


# ---------------------------------------------------------------------------
# certification (ROADMAP item 5 / paddlelint PK105): every kernel entry
# names its XLA oracle and the parity test that pins them together
# ---------------------------------------------------------------------------

from .oracles import register_oracle  # noqa: E402  (registry is leaf-light)

register_oracle(
    "fused_rms_norm", kernel=fused_rms_norm,
    reference="paddle_tpu.ops.references:rms_norm_reference",
    parity_test="tests/test_fused_ops.py::TestRmsNorm")
register_oracle(
    "fused_layer_norm", kernel=fused_layer_norm,
    reference="paddle_tpu.ops.references:layer_norm_reference",
    parity_test="tests/test_fused_ops.py::TestLayerNorm")
register_oracle(
    "fused_bias_residual_layer_norm", kernel=fused_bias_residual_layer_norm,
    reference="paddle_tpu.ops.references:bias_residual_layer_norm_reference",
    parity_test="tests/test_oracles.py::TestOracleParity")
register_oracle(
    "fused_moe_dispatch_combine", kernel=fused_moe_dispatch_combine,
    reference="paddle_tpu.ops.references:moe_dispatch_combine_reference",
    parity_test="tests/test_oracles.py::TestOracleParity")
register_oracle(
    "fused_rope", kernel=fused_rope,
    reference="paddle_tpu.ops.references:rope_reference",
    parity_test="tests/test_fused_ops.py::TestRope")
register_oracle(
    "fused_rope_append", kernel=fused_rope_append,
    reference="paddle_tpu.ops.references:rope_append_reference",
    parity_test="tests/test_ragged_kernel.py::TestFusedRopeAppend")
register_oracle(
    "fused_append_rows", kernel=fused_append_rows,
    reference="paddle_tpu.ops.references:append_rows_reference",
    parity_test="tests/test_oracles.py::TestOracleParity")
register_oracle(
    "swiglu", kernel=swiglu,
    reference="paddle_tpu.ops.references:swiglu_reference",
    parity_test="tests/test_fused_ops.py::TestSwiglu")
