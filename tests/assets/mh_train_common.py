"""Shared build-and-run for the multi-host TRAIN parity test (VERDICT r4
item 1; ref: the launcher -> fleet meta_parallel composition path,
python/paddle/distributed/launch/ + fleet/meta_parallel/, SURVEY §3.5/§5.8).

The SAME routine runs (a) inside each of 2 launched OS processes over the
4+4 = 8-device GLOBAL mesh (collectives cross the process boundary over
the jax.distributed backend) and (b) single-process over the pytest
8-device mesh — the test asserts per-step loss parity between the two,
which is the actual evidence that hybrid-parallel training (not just a
psum) works multi-host."""

import numpy as np

# mesh degrees multiply to 8 (2 processes x 4 devices); both configs put
# at least one collective-carrying axis across the process boundary
CONFIGS = {
    # GSPMD grad psum (dp) + Megatron TP (mp) + ZeRO param/opt sharding
    "dp2mp2zero2": dict(dp=2, mp=2, pp=1, sharding=2, sep=1, n_micro=1,
                        layers=4),
    # compiled-pipeline ppermute (pp) + TP + dp grad psum
    "pp2mp2dp2": dict(dp=2, mp=2, pp=2, sharding=1, sep=1, n_micro=2,
                      layers=4),
}

SEED_PARAMS = 1234
SEED_DATA = 7
BATCH, SEQ = 8, 32


def run_train(name: str, steps: int = 3):
    """Build the hybrid train step for CONFIGS[name] over jax.devices()
    (global — 8 devices whether owned by 1 process or 2) and run
    `steps` steps on seeded data. Returns the per-step losses."""
    import jax
    import jax.numpy as jnp
    import paddle_tpu
    from paddle_tpu.distributed.mesh import global_device_put
    from paddle_tpu.models.llama import llama_tiny_config
    from paddle_tpu.trainer.pretrain import (PretrainConfig,
                                             build_llama_pretrain_step,
                                             make_hybrid_mesh_for)

    c = CONFIGS[name]
    paddle_tpu.seed(SEED_PARAMS)  # identical init on every process
    mc = llama_tiny_config(num_hidden_layers=c["layers"],
                           max_position_embeddings=64)
    cfg = PretrainConfig(mc, global_batch=BATCH, seq_len=SEQ,
                         n_microbatches=c["n_micro"], lr=1e-3,
                         dp=c["dp"], mp=c["mp"], pp=c["pp"],
                         sharding=c["sharding"], sep=c["sep"])
    mesh = make_hybrid_mesh_for(cfg)
    st, step, meta = build_llama_pretrain_step(cfg, mesh)

    rng = np.random.RandomState(SEED_DATA)
    losses = []
    for _ in range(steps):
        ids = jnp.asarray(rng.randint(0, mc.vocab_size, (BATCH, SEQ)),
                          jnp.int32)
        labels = jnp.asarray(rng.randint(0, mc.vocab_size, (BATCH, SEQ)),
                             jnp.int32)
        ids = global_device_put(ids, meta["data_sharding"])
        labels = global_device_put(labels, meta["data_sharding"])
        st, m = step(st, ids, labels)
        losses.append(float(jax.device_get(m["loss"])))
    return losses
