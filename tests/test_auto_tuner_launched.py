"""Launcher-driven auto-tuner trials with OOM survival (VERDICT r4 item
6; ref: python/paddle/distributed/auto_tuner/ — each candidate runs as a
real short launcher subprocess; OOM/crash is recorded, pruned, and tuning
completes with the best feasible config)."""

import math
import os

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class TestLauncherDrivenTuning:
    def test_oom_candidate_survived_best_feasible_picked(self, tmp_path):
        from paddle_tpu.distributed.auto_tuner import AutoTuner

        # two 8-device candidates: dp8 replicates master+opt on every
        # device (~16.5 MB/dev measured); dp2 x mp2 x zero2 shards them
        # (~7.5 MB/dev). A 12 MB predictive-HBM budget OOMs the first.
        space = {"dp_degree": [2, 8], "mp_degree": [1, 2],
                 "pp_degree": [1], "sharding_degree": [1, 2],
                 "sharding_stage": [1], "micro_batch_size": [1],
                 "use_recompute": [False]}
        tuner = AutoTuner(total_devices=8, search_space=space,
                          global_batch=8, num_layers=2, num_heads=4)
        cands = {(c["dp_degree"], c["mp_degree"], c["sharding_degree"])
                 for c in tuner.candidates}
        assert (8, 1, 1) in cands and (2, 2, 2) in cands

        base = {"model": {"preset": "tiny", "num_hidden_layers": 2},
                "data": {"corpus": None},
                "seq_len": 64, "global_batch": 8, "remat": "none",
                "log_interval": 10,
                "hbm_budget_bytes": 12 * 1024 * 1024}
        best, history = tuner.tune_launched(
            base, workdir=str(tmp_path), steps=4, timeout=420,
            env={"JAX_PLATFORMS": "cpu",
                 "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
                 "PYTHONPATH": REPO + os.pathsep
                 + os.environ.get("PYTHONPATH", "")})

        by_key = {(h["dp_degree"], h["mp_degree"], h["sharding_degree"]):
                  h for h in history}
        # the replicated candidate hit the predictive OOM gate and was
        # recorded — not fatal
        assert by_key[(8, 1, 1)]["status"] == "oom", history
        assert by_key[(8, 1, 1)]["metric"] == -math.inf
        # the sharded candidate ran and won
        assert by_key[(2, 2, 2)]["status"] == "ok", history
        assert by_key[(2, 2, 2)]["metric"] > 0
        assert (best["dp_degree"], best["mp_degree"],
                best["sharding_degree"]) == (2, 2, 2)
