"""Optimizers (vs analytic/torch refs), LR schedulers, AMP, DataLoader."""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu import optimizer as opt
from paddle_tpu.io import (BatchSampler, DataLoader, Dataset,
                           DistributedBatchSampler, TensorDataset)


def _quad_problem(optimizer_cls, steps=120, **kw):
    paddle.seed(0)
    w = nn.Parameter(paddle.to_tensor([5.0, -3.0])._data)
    o = optimizer_cls(parameters=[w], **kw)
    for _ in range(steps):
        loss = ((w - paddle.to_tensor([1.0, 2.0])) ** 2).sum()
        loss.backward()
        o.step()
        o.clear_grad()
    return w.numpy()


def test_sgd_converges():
    w = _quad_problem(opt.SGD, learning_rate=0.1)
    np.testing.assert_allclose(w, [1, 2], atol=1e-3)


def test_momentum_converges():
    w = _quad_problem(opt.Momentum, learning_rate=0.05, momentum=0.9)
    np.testing.assert_allclose(w, [1, 2], atol=1e-2)


def test_adam_converges():
    w = _quad_problem(opt.Adam, learning_rate=0.3)
    np.testing.assert_allclose(w, [1, 2], atol=1e-2)


def test_adamw_matches_torch():
    torch = pytest.importorskip("torch")
    w0 = np.array([[0.5, -0.3], [0.2, 0.8]], np.float32)
    g = np.array([[0.1, -0.2], [0.3, 0.05]], np.float32)

    p = nn.Parameter(w0.copy())
    o = opt.AdamW(learning_rate=0.01, parameters=[p], weight_decay=0.1)
    for _ in range(5):
        p.grad = paddle.to_tensor(g)
        o.step()
        o.clear_grad()

    tp = torch.nn.Parameter(torch.tensor(w0.copy()))
    to = torch.optim.AdamW([tp], lr=0.01, weight_decay=0.1, eps=1e-8)
    for _ in range(5):
        tp.grad = torch.tensor(g)
        to.step()
        to.zero_grad()
    np.testing.assert_allclose(p.numpy(), tp.detach().numpy(), rtol=1e-5,
                               atol=1e-6)


def test_multi_precision_master_weights():
    w = nn.Parameter(paddle.ones([4], dtype="bfloat16")._data)
    o = opt.AdamW(learning_rate=1e-3, parameters=[w], multi_precision=True)
    for _ in range(3):
        w.grad = paddle.full([4], 0.001, dtype="bfloat16")
        o.step()
        o.clear_grad()
    assert str(w.dtype) == "bfloat16"
    # master weights moved with f32 resolution (updates smaller than bf16 ulp)
    master = list(o._master.values())[0]
    assert master.dtype == np.float32
    assert not np.allclose(np.asarray(master), 1.0)


def test_grad_clip_global_norm():
    w = nn.Parameter(paddle.zeros([2])._data)
    clip = nn.ClipGradByGlobalNorm(1.0)
    o = opt.SGD(learning_rate=1.0, parameters=[w], grad_clip=clip)
    w.grad = paddle.to_tensor([3.0, 4.0])  # norm 5 → scaled to 1
    o.step()
    np.testing.assert_allclose(np.linalg.norm(w.numpy()), 1.0, rtol=1e-5)


def test_lr_schedulers():
    s = opt.lr.LinearWarmup(0.1, warmup_steps=10, start_lr=0.0, end_lr=0.1)
    lrs = []
    for _ in range(12):
        lrs.append(s())
        s.step()
    assert lrs[0] == 0.0 and abs(lrs[5] - 0.05) < 1e-9 and lrs[11] == 0.1

    c = opt.lr.CosineAnnealingDecay(1.0, T_max=10)
    c.step(10)
    assert c() == pytest.approx(0.0, abs=1e-9)

    w = nn.Parameter(paddle.zeros([1])._data)
    o = opt.SGD(learning_rate=s, parameters=[w])
    assert o.get_lr() == s()


def test_optimizer_state_roundtrip(tmp_path):
    w = nn.Parameter(paddle.ones([2])._data)
    o = opt.Adam(learning_rate=0.1, parameters=[w])
    w.grad = paddle.to_tensor([0.5, 0.5])
    o.step()
    sd = o.state_dict()
    paddle.save(sd, str(tmp_path / "opt.pdopt"))
    loaded = paddle.load(str(tmp_path / "opt.pdopt"))

    w2 = nn.Parameter(paddle.ones([2])._data)
    o2 = opt.Adam(learning_rate=0.1, parameters=[w2])
    o2.set_state_dict(loaded)
    assert o2._step_count == 1
    np.testing.assert_allclose(
        np.asarray(o2._accumulators["moment1"][id(w2)]),
        np.asarray(o._accumulators["moment1"][id(w)]))


def test_amp_auto_cast_o1():
    import paddle_tpu.amp as amp
    a = paddle.rand([4, 4])
    b = paddle.rand([4, 4])
    with amp.auto_cast(level="O1", dtype="bfloat16"):
        c = paddle.matmul(a, b)       # white list → bf16
        d = paddle.exp(a)             # black list → f32
    assert str(c.dtype) == "bfloat16"
    assert d.dtype == np.float32
    e = paddle.matmul(a, b)
    assert e.dtype == np.float32  # outside context


def test_grad_scaler_skips_on_inf():
    import paddle_tpu.amp as amp
    w = nn.Parameter(paddle.ones([1])._data)
    o = opt.SGD(learning_rate=1.0, parameters=[w])
    scaler = amp.GradScaler(init_loss_scaling=2.0)
    w.grad = paddle.to_tensor([float("inf")])
    scaler.step(o)
    np.testing.assert_allclose(w.numpy(), [1.0])  # skipped
    assert scaler.get_loss_scaling() == 1.0  # decreased

    w.grad = paddle.to_tensor([2.0])
    scaler.step(o)
    np.testing.assert_allclose(w.numpy(), [-1.0])  # applied unscaled (2/1)


class _SquareDS(Dataset):
    def __len__(self):
        return 20

    def __getitem__(self, i):
        return np.float32(i), np.float32(i * i)


def test_dataloader_basic():
    dl = DataLoader(_SquareDS(), batch_size=4, drop_last=True)
    batches = list(dl)
    assert len(batches) == 5
    x, y = batches[0]
    assert x.shape == [4]
    np.testing.assert_allclose(y.numpy(), x.numpy() ** 2)


def test_dataloader_shuffle_and_workers():
    paddle.seed(7)
    dl = DataLoader(_SquareDS(), batch_size=5, shuffle=True, num_workers=2)
    xs = np.concatenate([b[0].numpy() for b in dl])
    assert sorted(xs.tolist()) == list(range(20))
    assert xs.tolist() != list(range(20))


def test_distributed_batch_sampler_shards():
    ds = _SquareDS()
    all_idx = []
    for rank in range(4):
        bs = DistributedBatchSampler(ds, batch_size=5, num_replicas=4,
                                     rank=rank)
        idx = [i for batch in bs for i in batch]
        assert len(idx) == 5
        all_idx.extend(idx)
    assert sorted(all_idx) == list(range(20))


def test_tensor_dataset():
    x = paddle.rand([10, 3])
    y = paddle.arange(10)
    ds = TensorDataset([x, y])
    dl = DataLoader(ds, batch_size=5)
    bx, by = next(iter(dl))
    assert bx.shape == [5, 3] and by.shape == [5]


def test_amp_backward_through_cast_boundary():
    """Regression: cast must be inside the vjp'd fn — bf16 linear feeding an
    f32 blacklist op must backprop without dtype mismatch."""
    import paddle_tpu.amp as amp
    net = nn.Linear(8, 4)
    net.to(dtype="bfloat16")
    o = opt.AdamW(learning_rate=1e-2, parameters=net.parameters(),
                  multi_precision=True)
    x = paddle.rand([4, 8])
    with amp.auto_cast(level="O2", dtype="bfloat16"):
        loss = (net(x) ** 2).mean()   # mean is blacklisted → f32
    loss.backward()
    assert str(net.weight.grad.dtype) == "bfloat16"
    o.step()
    o.clear_grad()


def test_optimizer_tail_matches_torch():
    """NAdam/RAdam/Rprop step-for-step vs torch (same update equations;
    RAdam run long enough to cross the rho_t>5 rectification threshold)."""
    import torch
    import jax.numpy as jnp
    import paddle_tpu.optimizer as O

    w0 = np.random.RandomState(0).randn(4, 3).astype(np.float32)
    x = np.random.RandomState(1).randn(8, 4).astype(np.float32)

    def run(make_p, make_t, steps):
        lin = nn.Linear(4, 3, bias_attr=False)
        lin.weight._data = jnp.asarray(w0)
        po = make_p(lin)
        tw = torch.nn.Parameter(torch.tensor(w0))
        to = make_t([tw])
        for _ in range(steps):
            loss = (lin(paddle.to_tensor(x)) ** 2).mean()
            loss.backward(); po.step(); po.clear_grad()
            tl = ((torch.tensor(x) @ tw) ** 2).mean()
            tl.backward(); to.step(); to.zero_grad()
        return np.abs(lin.weight.numpy() - tw.detach().numpy()).max()

    assert run(lambda l: O.NAdam(learning_rate=0.01,
                                 parameters=l.parameters()),
               lambda ps: torch.optim.NAdam(ps, lr=0.01), 5) < 1e-4
    # beta2=0.9 makes rho_inf=19 and rho_t cross 5 within a few steps,
    # covering the rectified branch
    assert run(lambda l: O.RAdam(learning_rate=0.01, beta2=0.9,
                                 parameters=l.parameters()),
               lambda ps: torch.optim.RAdam(ps, lr=0.01,
                                            betas=(0.9, 0.9)), 8) < 1e-4
    assert run(lambda l: O.Rprop(learning_rate=0.01,
                                 parameters=l.parameters()),
               lambda ps: torch.optim.Rprop(ps, lr=0.01), 5) < 1e-5
