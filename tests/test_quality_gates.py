"""Deterministic proxy quality gates (VERDICT r1 item 10; SURVEY §6).

The reference's quality bars (BERT-base SST-2 92-93%, PP-OCRv4 accuracy)
need corpora this environment cannot download, so these gates train the
SAME model/loss/optimizer stacks on bundled synthetic data with fixed
seeds and assert accuracy thresholds — a regression tripwire for the
end-to-end training paths, not a replica of the published numbers
(documented in BASELINE.md rows 4-5).
"""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core.tensor import Tensor


def _sentiment_corpus(n, seed, seq=16):
    """Label = which polarity's words dominate; >=5-token margin keeps
    the task separable for a tiny counting transformer; token 1 = CLS."""
    rng = np.random.RandomState(seed)
    X = np.zeros((n, seq), np.int32)
    y = np.zeros((n,), np.int64)
    for i in range(n):
        while True:
            k = rng.randint(2, seq - 2)
            if abs(2 * k - (seq - 1)) >= 5:
                break
        pos = rng.choice(np.arange(10, 30), k)
        neg = rng.choice(np.arange(30, 50), seq - 1 - k)
        toks = np.concatenate([pos, neg])
        rng.shuffle(toks)
        X[i, 0] = 1
        X[i, 1:] = toks
        y[i] = int(k > (seq - 1 - k))
    return X, y


class TestClassificationGate:
    def test_bert_style_finetune_accuracy(self):
        """The SST-2 fine-tune path (model + CE loss + AdamW + scheduler)
        must reach >= 90% on the separable synthetic dev set."""
        from paddle_tpu.models.bert import (BertForSequenceClassification,
                                            bert_tiny_config)
        paddle.seed(0)
        cfg = bert_tiny_config(vocab_size=64, hidden_size=64,
                               num_hidden_layers=2, num_attention_heads=4,
                               intermediate_size=128,
                               max_position_embeddings=32, num_labels=2)
        model = BertForSequenceClassification(cfg)
        opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                     parameters=list(model.parameters()))
        Xtr, ytr = _sentiment_corpus(512, 0)
        Xdev, ydev = _sentiment_corpus(128, 1)
        B = 32
        for epoch in range(10):
            perm = np.random.RandomState(epoch).permutation(len(Xtr))
            for i in range(0, len(Xtr), B):
                idx = perm[i:i + B]
                loss, _ = model(paddle.to_tensor(Xtr[idx]),
                                labels=paddle.to_tensor(ytr[idx]))
                loss.backward()
                opt.step()
                opt.clear_grad()
        model.eval()
        logits = model(paddle.to_tensor(Xdev))
        pred = np.asarray(logits.numpy()).argmax(-1)
        acc = (pred == ydev).mean()
        assert acc >= 0.92, f"classification gate: dev acc {acc:.3f}"


def _glyph(d):
    """5x3 bitmap font for digits 0-9."""
    F = {
        0: "111101101101111", 1: "010110010010111",
        2: "111001111100111", 3: "111001111001111",
        4: "101101111001001", 5: "111100111001111",
        6: "111100111101111", 7: "111001001001001",
        8: "111101111101111", 9: "111101111001111",
    }
    return np.asarray([int(c) for c in F[d]], np.float32).reshape(5, 3)


def _rec_sample(rng, n_digits, H=32, pitch=16):
    """Render a digit string into a [1, H, W] image at fixed pitch.
    W = n_digits*16 gives the rec backbone (W/2 time axis) T=32 CTC
    steps for 4 labels."""
    W = n_digits * pitch
    img = np.zeros((1, H, W), np.float32)
    label = rng.randint(0, 10, n_digits)
    for i, d in enumerate(label):
        g = np.kron(_glyph(int(d)), np.ones((4, 4), np.float32))  # 20x12
        img[0, 6:26, i * pitch + 2:i * pitch + 14] = g
    return img, label


class TestOCRRecGate:
    def test_ctc_rec_char_accuracy(self):
        """The PP-OCR rec path (rec_mode backbone + CTC head + CTC loss)
        must read >= 80% of characters on the synthetic glyph set."""
        from paddle_tpu.models.ocr import PPOCRRec
        paddle.seed(1)
        n_digits = 4
        model = PPOCRRec(num_classes=11, in_channels=1)  # blank + 10
        opt = paddle.optimizer.AdamW(learning_rate=3e-3,
                                     parameters=list(model.parameters()))
        rng = np.random.RandomState(0)
        B = 16

        def batch():
            imgs, labs = [], []
            for _ in range(B):
                im, lb = _rec_sample(rng, n_digits)
                imgs.append(im)
                labs.append(lb + 1)  # 0 is the CTC blank
            return (np.stack(imgs), np.stack(labs).astype(np.int32),
                    np.full((B,), n_digits, np.int32))

        for step in range(50):
            imgs, labs, lens = batch()
            logits = model(paddle.to_tensor(imgs))
            loss = model.loss(logits, paddle.to_tensor(labs),
                              paddle.to_tensor(lens))
            loss.backward()
            opt.step()
            opt.clear_grad()

        # recalibrate BatchNorm running stats against the FINAL weights
        # (they lag by ~1/(1-momentum) steps on this short schedule; the
        # update_bn pass torch's SWA uses for the same reason)
        from paddle_tpu.core import autograd as ag
        with ag.no_grad():
            for _ in range(15):
                imgs, _, _ = batch()
                model(paddle.to_tensor(imgs))

        # greedy CTC decode on a fresh eval batch
        rng_eval = np.random.RandomState(99)
        imgs, labs = [], []
        for _ in range(B):
            im, lb = _rec_sample(rng_eval, n_digits)
            imgs.append(im)
            labs.append(lb + 1)
        model.eval()
        logits = np.asarray(model(paddle.to_tensor(np.stack(imgs))).numpy())
        total = correct = 0
        for b in range(B):
            path = logits[b].argmax(-1)
            dec = []
            prev = -1
            for p in path:
                if p != prev and p != 0:
                    dec.append(int(p))
                prev = p
            ref = list(labs[b])
            L = min(len(dec), len(ref))
            correct += sum(1 for i in range(L) if dec[i] == ref[i])
            total += len(ref)
        acc = correct / total
        assert acc >= 0.80, f"ocr rec gate: char acc {acc:.3f}"
