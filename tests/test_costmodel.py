"""Analytical cost registry (observability/costmodel.py).

Three contracts:

1. Coverage — every kernel in ops/oracles.py has a registered cost
   function and evaluates to a sane CostEstimate at canonical shapes.
2. BlockSpec consistency — for the paged / ragged / flash families the
   registry's byte formulas EQUAL the transfer sizes the PR-8 kernel
   model derives from the committed grids/BlockSpecs
   (analysis/kernelmodel.py fetch-runs evaluation), so the model and the
   code cannot drift apart silently.
3. Committed pins — the serving rooflines in docs/SERVING_BENCH.json
   and the flagship MFU (docs/FLAGSHIP_data.json + BENCH_REPEATS) are
   reproduced by `decode_step_budget` / `train_mfu`: train and serve
   derive from one cost vocabulary.
"""

import ast
import json
import os

import pytest

import paddle_tpu.analysis.kernelmodel as km
from paddle_tpu.observability import costmodel as cm

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DOCS = os.path.join(REPO, "docs")
OPS = os.path.join(REPO, "paddle_tpu", "ops")

BF16 = 2
I32 = 4

#: canonical evaluation shapes per kernel (kwargs for cm.cost)
SHAPES = {
    "fused_rms_norm": dict(T=8, H=256),
    "fused_layer_norm": dict(T=8, H=256),
    "fused_bias_residual_layer_norm": dict(T=8, H=256),
    "fused_moe_dispatch_combine": dict(T=8, K=2, E=4, C=16),
    "fused_rope": dict(B=2, S=16, H=4, D=64, Hk=1),
    "fused_rope_append": dict(T=8, Hq=4, KV=1, D=64, page_size=16),
    "fused_append_rows": dict(T=8, KV=1, D=64, page_size=16),
    "swiglu": dict(T=8, H=256),
    "flash_sdpa": dict(B=2, H=3, Sq=256, Sk=512, D=64,
                       block_q=128, block_k=128),
    "flashmask_sdpa": dict(B=2, H=3, Sq=256, Sk=512, D=64,
                           block_q=128, block_k=128),
    "paged_decode_attention": dict(B=2, H=4, KV=1, D=128, context=128,
                                   page_size=16),
    "paged_decode_attention_v2": dict(B=2, H=4, KV=1, D=128, context=128,
                                      page_size=16),
    "mla_decode_attention": dict(B=2, nh=16, r=512, dr=64, context=256),
    "ragged_paged_attention": dict(T=8, H=4, KV=1, D=128, S=4,
                                   pages_per_seq=8, page_size=16),
    "gmm": dict(M=64, K=128, N=256, G=4),
    "int4_dequantize": dict(K=128, N=256),
    "weight_only_linear": dict(M=8, K=256, N=512),
    "fused_oproj_norm": dict(T=8, Ko=512, H=512),
    "fused_ffn": dict(T=8, H=512, I=1792),
    "fused_qkv_rope_append": dict(T=8, H=512, Hq=32, KV=8, D=128,
                                  page_size=32),
}


class TestRegistryCoverage:
    def test_all_oracle_kernels_have_costs(self):
        # registration side effects                          # noqa: F401
        from paddle_tpu.ops import (fused, pallas_flash, pallas_flashmask,
                                    pallas_gmm, pallas_megadecode,
                                    pallas_megafront, pallas_mla,
                                    pallas_paged, pallas_ragged, quant)
        from paddle_tpu.ops.oracles import oracles
        names = set(oracles())
        missing = names - set(cm.costs())
        assert not missing, f"kernels without a cost model: {missing}"
        # the canonical shape table covers the same set
        assert set(SHAPES) == names | set(SHAPES)

    @pytest.mark.parametrize("name", sorted(SHAPES))
    def test_estimates_sane(self, name):
        est = cm.cost(name, **SHAPES[name])
        assert est.bytes_read > 0 and est.bytes_written > 0
        assert est.flops >= 0
        assert est.hbm_bytes == est.bytes_read + est.bytes_written
        assert est.arithmetic_intensity >= 0
        # bandwidth-bound time scales down with more bandwidth
        assert est.theoretical_us(819e9) >= est.theoretical_us(2765e9)

    def test_unknown_kernel_raises_with_known_list(self):
        with pytest.raises(KeyError, match="known"):
            cm.cost("no_such_kernel", T=1)

    def test_breakdown_sums_bounded_by_totals(self):
        for name, kw in SHAPES.items():
            est = cm.cost(name, **kw)
            if est.breakdown:
                assert sum(est.breakdown.values()) <= est.hbm_bytes, name


# ---------------------------------------------------------------------------
# BlockSpec consistency: registry bytes == kernel-model fetch accounting
# ---------------------------------------------------------------------------

def _sites():
    files = []
    for f in ("pallas_paged.py", "pallas_ragged.py", "pallas_flash.py"):
        files.append((f"paddle_tpu.ops.{f[:-3]}", os.path.join(OPS, f),
                      os.path.join("paddle_tpu", "ops", f)))
    idx = km.PackageIndex.from_files(files)
    return idx, km.collect_kernel_calls(idx)


@pytest.fixture(scope="module")
def sites():
    return _sites()


def _one(sites, qualname):
    hits = [s for s in sites if s.qualname == qualname]
    assert len(hits) == 1, (qualname, [s.qualname for s in sites])
    return hits[0]


class TestBlockSpecConsistency:
    def test_paged_v1_bytes_match_block_specs(self, sites):
        _, ss = sites
        site = _one(ss, "paged_decode_attention")
        b = dict(B=2, KV=1, rep=4, nj=8, page_size=16, D=128)
        got = km.transfer_bytes(site, b, [BF16] * 3, [BF16])
        assert got is not None and None not in got["in"] + got["out"]
        est = cm.cost("paged_decode_attention", B=2, H=4, KV=1, D=128,
                      context=8 * 16, page_size=16, pages_per_seq=8)
        q, k, v = got["in"]
        assert q + k + v == est.bytes_read
        assert got["out"][0] == est.bytes_written
        assert k + v == est.breakdown["kv"]

    def test_paged_v2_any_specs_opt_out(self, sites):
        # v2 keeps K/V in HBM behind manual DMA (memory_space=ANY): the
        # evaluator must SKIP those specs, which is why the paged cost
        # family is cross-checked against the v1 grid
        _, ss = sites
        site = _one(ss, "paged_decode_attention_v2")
        b = dict(B=2, KV=1, rep=4, page_size=16, D=128,
                 pages_per_group=2, total_pages=8)
        got = km.transfer_bytes(site, b, [BF16] * 3, [BF16])
        assert got is not None
        assert None in got["in"]

    def test_ragged_bytes_match_block_specs(self, sites):
        _, ss = sites
        site = _one(ss, "ragged_paged_attention")
        b = dict(KV=1, S=4, nj=8, T=8, rep=4, psz=16, D=128, total=64)
        got = km.transfer_bytes(site, b, [BF16] * 3, [BF16])
        assert got is not None and None not in got["in"] + got["out"]
        est = cm.cost("ragged_paged_attention", T=8, H=4, KV=1, D=128,
                      S=4, pages_per_seq=8, page_size=16)
        q, k, v = got["in"]
        assert q + k + v == est.bytes_read
        assert got["out"][0] == est.bytes_written
        assert k + v == est.breakdown["kv"]

    def test_flash_fwd_bytes_match_block_specs(self, sites):
        idx, ss = sites
        site = _one(ss, "_flash_fwd_impl")
        mi = idx.modules["paddle_tpu.ops.pallas_flash"]
        fi = mi.functions["_specs"]
        # the in_specs ride through the tuple-unpacked `_specs` helper;
        # rebuild them with the order='qk' branch recorded over the env
        # (Env is flow-insensitive, so the else-branch maps would win)
        env = km.Env(mi, fi)
        branch = next(n for n in ast.walk(fi.node)
                      if isinstance(n, ast.If))
        for stmt in branch.body:
            env._record(stmt)
        ret = next(n for n in ast.walk(fi.node)
                   if isinstance(n, ast.Return))
        spec_calls = ret.value.elts[0].elts
        specs = [km.build_block_spec(c, mi, fi, env) for c in spec_calls]
        assert len(specs) == 5                # seg_q, seg_kv, q, k, v

        B, H, Sq, Sk, D, bq, bk = 2, 3, 256, 512, 64, 128, 128
        nq, nk = Sq // bq, Sk // bk
        grid = [B, H, nq, nk]
        binds = dict(bq=bq, bk=bk, D=D)
        elems = [km.spec_transfer_elems(s, grid, 4, binds) for s in specs]
        assert None not in elems
        seg_q, seg_kv, q, k, v = elems
        read = (seg_q + seg_kv) * I32 + (q + k + v) * BF16

        # out specs: o uses the same tuple-unpacked qmap (rebuild it
        # under the qk env); the lse map is a literal lambda at the site
        o_spec = km.build_block_spec(site.out_specs[0].node, mi, fi, env)
        o = km.spec_transfer_elems(o_spec, grid, 4, binds)
        lse = km.spec_transfer_elems(site.out_specs[1], grid, 4, binds)
        assert o is not None and lse is not None
        written = o * BF16 + lse * 4

        est = cm.cost("flash_sdpa", B=B, H=H, Sq=Sq, Sk=Sk, D=D,
                      block_q=bq, block_k=bk)
        assert read == est.bytes_read
        assert written == est.bytes_written
        # component identities: q once, K/V once per q-block
        assert q * BF16 == B * H * Sq * D * BF16
        assert k * BF16 == B * H * nq * Sk * D * BF16
        assert o * BF16 == B * H * Sq * D * BF16

    def test_grids_evaluate_for_all_three_sites(self, sites):
        _, ss = sites
        v1 = _one(ss, "paged_decode_attention")
        assert km.grid_values(
            v1, dict(B=2, KV=1, nj=8)) == [2, 1, 8]
        rag = _one(ss, "ragged_paged_attention")
        assert km.grid_values(
            rag, dict(KV=1, S=4, nj=8)) == [1, 4, 8]
        fwd = _one(ss, "_flash_fwd_impl")
        assert km.grid_values(
            fwd, dict(B=2, H=3, nq=2, nk=4)) == [2, 3, 2, 4]


# ---------------------------------------------------------------------------
# committed pins: SERVING_BENCH rooflines + flagship MFU from one registry
# ---------------------------------------------------------------------------

def _bench():
    with open(os.path.join(DOCS, "SERVING_BENCH.json")) as f:
        return json.load(f)


#: row -> (family, kv kwargs) for the committed bench configs
ROW_KV = {
    "decode": ("llama", dict(kv_heads=1, head_dim=128)),
    "decode_b1": ("llama", dict(kv_heads=1, head_dim=128)),
    "decode_b16": ("llama", dict(kv_heads=1, head_dim=128)),
    "decode_int8": ("llama", dict(kv_heads=1, head_dim=128)),
    "decode_int4": ("llama", dict(kv_heads=1, head_dim=128)),
    "decode_bf16_ref": ("llama", dict(kv_heads=1, head_dim=128)),
    "moe_decode": ("moe", dict(kv_heads=4, head_dim=128)),
    "moe_decode_int8": ("moe", dict(kv_heads=4, head_dim=128)),
    "mla_decode": ("mla", dict(kv_latent_dim=512 + 64)),
    "mla_decode_int8": ("mla", dict(kv_latent_dim=512 + 64)),
}


class TestCommittedPins:
    @pytest.mark.parametrize("row", sorted(ROW_KV))
    def test_serving_rooflines_reproduced(self, row):
        r = _bench()[row]
        family, kv = ROW_KV[row]
        budget = cm.decode_step_budget(
            family, batch=r["batch"],
            context=r["prefill_len"] + r["new_tokens"] / 2,
            layers=8, weight_bytes=r["weight_bytes"], **kv)
        got = cm.roofline_tokens_per_s(budget, hbm_bw=819e9)
        assert got == pytest.approx(r["roofline_tokens_per_s"], rel=1e-4)
        # the committed fraction is measured/roofline under this budget
        frac = r["decode_tokens_per_s_per_chip"] / got
        assert frac == pytest.approx(r["roofline_fraction"], abs=2e-3)

    def test_headline_band_1p13_to_1p28(self):
        # the ROADMAP's "1.13-1.28x the naive HBM roofline" claim, now
        # derived from costmodel instead of the hand constant
        bench = _bench()
        fracs = []
        for row in ("decode", "decode_b1", "decode_b16", "decode_int8"):
            r = bench[row]
            family, kv = ROW_KV[row]
            budget = cm.decode_step_budget(
                family, batch=r["batch"],
                context=r["prefill_len"] + r["new_tokens"] / 2,
                layers=8, weight_bytes=r["weight_bytes"], **kv)
            fracs.append(r["decode_tokens_per_s_per_chip"]
                         / cm.roofline_tokens_per_s(budget, hbm_bw=819e9))
        assert 1.10 <= min(fracs) and max(fracs) <= 1.31, fracs

    def test_page_granular_budget_never_below_row_granular(self):
        naive = cm.decode_step_budget(
            "llama", batch=8, context=1000, layers=8,
            weight_bytes=7 * 10**8, kv_heads=1, head_dim=128)
        paged = cm.decode_step_budget(
            "llama", batch=8, context=1000, layers=8,
            weight_bytes=7 * 10**8, kv_heads=1, head_dim=128,
            page_size=16)
        assert paged["kv_bytes"] >= naive["kv_bytes"]
        assert paged["kv_bytes"] == 8 * 8 * 1008 * 2 * 128 * 2

    def test_flagship_mfu_reproduced(self):
        with open(os.path.join(DOCS, "FLAGSHIP_data.json")) as f:
            fl = json.load(f)
        with open(os.path.join(DOCS, "BENCH_REPEATS_r5.json")) as f:
            reps = json.load(f)
        tok_s = reps["mean"]
        # the committed trajectory: ~61.4k tokens/s/chip
        assert 58e3 <= tok_s <= 65e3
        n = fl["shard"]["params"]
        # 6N identity between FLAGSHIP's ledger and the registry
        assert 6 * n == fl["shard"]["flops_per_token_6N"]
        mfu = cm.train_mfu(tokens_per_s=tok_s, n_params=n)
        # FLAGSHIP reports 65.5% measured shard MFU
        assert 0.62 <= mfu <= 0.69, mfu

    def test_flops_per_sample_matches_budget(self):
        f = cm.flops_per_sample(n_params=10**8, tokens_per_sample=2048)
        assert f == 6 * 10**8 * 2048
        # attention term engages when the shape is known
        f2 = cm.flops_per_sample(n_params=10**8, tokens_per_sample=2048,
                                 layers=8, hidden=2048)
        assert f2 == (6 * 10**8 + 12 * 8 * 2048 * 2048) * 2048
