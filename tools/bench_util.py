"""Shared measurement helpers for the on-chip bench tools.

block_until_ready is a NO-OP on the axon-tunneled TPU this image exposes
— a host fetch of one element is the only honest barrier. Every bench
must use these helpers so a future barrier fix lands in one place.
"""

from __future__ import annotations

import time

import numpy as np


def fetch(out):
    """Force device completion by fetching one element to the host."""
    leaf = out
    while isinstance(leaf, (tuple, list, dict)):
        leaf = next(iter(leaf.values())) if isinstance(leaf, dict) \
            else leaf[0]
    np.asarray(leaf[(0,) * leaf.ndim])


def timeit(fn, *args, reps: int = 20) -> float:
    """Seconds per call, steady-state (one warmup/compile call first)."""
    out = fn(*args)
    fetch(out)
    t0 = time.time()
    for _ in range(reps):
        out = fn(*args)
    fetch(out)
    return (time.time() - t0) / reps
