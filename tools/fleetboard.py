#!/usr/bin/env python
"""Fleetboard — watch a serving fleet as ONE system (ISSUE 16).

Three things an operator (or CI) does with a fleet, in one tool:

  - ``--selftest``   run the five seeded hostile-traffic scenarios
                     (`paddle_tpu.serving.workloads`) against a tiny
                     fleet, render the scenario table, export ONE
                     stitched chrome trace covering every replica lane,
                     and hold the run to the committed
                     ``docs/FLEET_BENCH.json``: deterministic replay
                     fields must match bit-exactly and the row must
                     clear `tools/perf_gate.py` bands. Writes the
                     artifact when missing (or with ``--write``); CI
                     wires this next to paddlelint/perf_gate in the
                     verify recipe.
  - ``--autopilot``  with ``--selftest``: replay every scenario twice —
                     static config vs the SLO autopilot (ISSUE 18) —
                     and emit paired ``<name>_autopilot`` rows so the
                     gate holds controller-on latency/loss to bands the
                     static run provably misses.
  - ``--federate``   offline metric federation: given per-replica
                     registry snapshot JSONs (``{replica: snapshot}``
                     mappings, or one snapshot per file named by its
                     stem), print the fleet rollup in Prometheus text
                     exposition — counters summed, gauges/histograms
                     re-labeled ``replica=...``.
  - ``--trace OUT``  with ``--selftest``: where to write the stitched
                     chrome trace (default ``/tmp/fleet_trace.json``;
                     open in Perfetto — one process lane per replica,
                     handoffs drawn as flow arrows).

Exit status: 0 = selftest replayed and gated clean, 1 = replay drift or
band failure. Tier-1 runs this on CPU with tiny models in ~30 s.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any, Dict, List, Optional

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

ARTIFACT = os.path.join(REPO, "docs", "FLEET_BENCH.json")

_COLUMNS = (("scenario", "%-22s"), ("requests", "%8s"),
            ("completed", "%9s"), ("zero_loss", "%9s"), ("shed", "%4s"),
            ("handoffs", "%8s"), ("fleet_tokens_per_s", "%9s"),
            ("ttft_p90_steps", "%8s"), ("e2e_p90_steps", "%8s"),
            ("ttft_p90_ms", "%11s"), ("e2e_p90_ms", "%10s"),
            ("handoff_latency_ms", "%10s"),
            ("prefill_skip_rate", "%9s"))
_HEADERS = ("scenario", "requests", "completed", "zero_loss", "shed",
            "handoffs", "tok/s", "ttft p90", "e2e p90", "ttft p90ms",
            "e2e p90ms", "handoff ms", "skip rate")


def render_table(rows: Dict[str, Dict[str, Any]]) -> str:
    """The scenario table, one line per scenario in canonical order."""
    lines = [" ".join(fmt % h for (_, fmt), h
                      in zip(_COLUMNS, _HEADERS))]
    for name in rows:
        row = rows[name]
        cells = []
        for (key, fmt) in _COLUMNS:
            v = row.get(key)
            if isinstance(v, float):
                v = f"{v:.2f}"
            cells.append(fmt % (v if v is not None else "-"))
        lines.append(" ".join(cells))
    return "\n".join(lines)


def _build_model():
    import paddle_tpu as paddle
    from paddle_tpu.models.llama import (LlamaForCausalLM,
                                         llama_tiny_config)
    cfg = llama_tiny_config(num_hidden_layers=1)
    paddle.seed(0)
    m = LlamaForCausalLM(cfg)
    m.eval()
    return m


def selftest(seed: int = 0, write: bool = False,
             trace_path: str = "/tmp/fleet_trace.json",
             autopilot: bool = False) -> int:
    import jax

    from paddle_tpu.observability import fleet as _fleet
    from paddle_tpu.serving import workloads

    model = _build_model()
    rows = workloads.run_all(model, seed=seed)
    if autopilot:
        # paired replay: same plans, SLO autopilot on — `_autopilot`
        # rows land next to their static twins in table and artifact
        rows.update(workloads.run_all(model, seed=seed, autopilot=True))
    print(render_table(rows))
    n_events = _fleet.stitch_chrome_trace(trace_path)
    print(f"fleetboard: stitched trace -> {trace_path} "
          f"({n_events} events)")

    art = {"device": jax.devices()[0].device_kind, "seed": seed,
           "note": "seeded hostile-traffic scenario suite "
                   "(tools/fleetboard.py --selftest); deterministic "
                   "fields replay bit-exactly from the seed, timing "
                   "fields are machine-dependent",
           "scenarios": rows}
    failures: List[str] = []
    committed = None
    if os.path.exists(ARTIFACT) and not write:
        with open(ARTIFACT, encoding="utf-8") as f:
            committed = json.load(f)
        if committed.get("seed") != seed:
            committed = None      # different seed: nothing to replay
    if committed is None:
        os.makedirs(os.path.dirname(ARTIFACT), exist_ok=True)
        with open(ARTIFACT, "w", encoding="utf-8") as f:
            json.dump(art, f, indent=1, sort_keys=True)
            f.write("\n")
        print(f"fleetboard: wrote {os.path.relpath(ARTIFACT, REPO)}")
    else:
        # the replayability gate: this machine, this seed, same story
        want = committed.get("scenarios") or {}
        for name, row in rows.items():
            ref = want.get(name)
            if ref is None:
                failures.append(f"{name}: not in committed artifact "
                                f"(rerun with --write)")
                continue
            for field in workloads.ROW_DETERMINISTIC:
                if row.get(field) != ref.get(field):
                    failures.append(
                        f"{name}.{field}: replayed {row.get(field)!r} "
                        f"vs committed {ref.get(field)!r}")
        if not failures:
            print(f"fleetboard: replay matches "
                  f"{os.path.relpath(ARTIFACT, REPO)} on all "
                  f"deterministic fields")
    # band check through the same gate CI runs
    from perf_gate import check_candidate, fleet_rows
    bands = fleet_rows(REPO)
    cand = {f"fleet.{name}.{field}": float(row[field])
            for name, row in rows.items()
            for field in workloads.ROW_DETERMINISTIC
            if isinstance(row.get(field), (int, float))}
    judged = check_candidate(cand, bands) if bands else []
    for r in judged:
        if not r["ok"]:
            failures.append(f"perf_gate: {r['key']} "
                            f"{r.get('why', 'failed')}")
    if judged:
        print(f"fleetboard: perf_gate accepted "
              f"{sum(r['ok'] for r in judged)}/{len(judged)} "
              f"deterministic rows")
    if failures:
        for f_ in failures:
            print(f"fleetboard: FAIL {f_}", file=sys.stderr)
        return 1
    print("fleetboard: selftest ok")
    return 0


def federate_files(paths: List[str]) -> str:
    """Offline federation: merge snapshot JSONs into the fleet rollup
    and return Prometheus text exposition."""
    import paddle_tpu.observability as obs
    from paddle_tpu.observability import fleet as _fleet
    snaps: Dict[str, Dict[str, Any]] = {}
    for path in paths:
        with open(path, encoding="utf-8") as f:
            d = json.load(f)
        if d and all(isinstance(v, dict) and "kind" in v
                     for v in d.values()):
            # one registry snapshot: replica named by the file stem
            snaps[os.path.splitext(os.path.basename(path))[0]] = d
        else:
            snaps.update(d)
    return obs.to_prometheus(_fleet.federate(snaps))


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--selftest", action="store_true",
                    help="run the seeded scenario suite against the "
                         "committed docs/FLEET_BENCH.json")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--write", action="store_true",
                    help="regenerate docs/FLEET_BENCH.json from this "
                         "run instead of replay-checking against it")
    ap.add_argument("--autopilot", action="store_true",
                    help="with --selftest: also replay every scenario "
                         "with the SLO autopilot on, emitting paired "
                         "`<name>_autopilot` rows")
    ap.add_argument("--trace", default="/tmp/fleet_trace.json",
                    help="stitched chrome-trace output path "
                         "(with --selftest)")
    ap.add_argument("--federate", nargs="+", metavar="SNAP.json",
                    help="merge per-replica snapshot JSONs and print "
                         "the Prometheus rollup")
    args = ap.parse_args(argv)
    if args.federate:
        print(federate_files(args.federate), end="")
        return 0
    if args.selftest:
        return selftest(seed=args.seed, write=args.write,
                        trace_path=args.trace, autopilot=args.autopilot)
    ap.print_help()
    return 0


if __name__ == "__main__":
    sys.exit(main())
