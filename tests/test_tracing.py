"""observability.tracing: percentile-from-cumulative-buckets math (exact
on synthetic distributions), span-event ordering/monotonicity under a
seeded join/leave serving trace, chrome-trace round-trip via
load_profiler_result with host-span correlation, terminal events for
refused/overloaded/timeout requests, the ring buffer + background
exporter, and trainer step-phase spans."""

import json
import threading

import numpy as np
import pytest

from paddle_tpu import serving as srv
from paddle_tpu.observability import Histogram, Registry
from paddle_tpu.observability import tracing as tr
from paddle_tpu.profiler import load_profiler_result


@pytest.fixture(autouse=True)
def _clean_recorder():
    tr.recorder().clear()
    yield
    tr.recorder().clear()
    tr.set_enabled(True)


# ---------------------------------------------------------------- percentiles

class TestPercentile:
    def test_exact_on_bucket_bounds(self):
        # 100 observations at 1.0 and 100 at 2.0 on bounds (1,2,4):
        # p50 interpolates to exactly 1.0, p100 to exactly 2.0
        h = Histogram(buckets=(1.0, 2.0, 4.0))
        for _ in range(100):
            h.observe(1.0)
        for _ in range(100):
            h.observe(2.0)
        assert tr.percentile(h, 50) == pytest.approx(1.0)
        assert tr.percentile(h, 100) == pytest.approx(2.0)
        # p75: target=150 lands mid-bucket (1,2] -> 1 + (150-100)/100
        assert tr.percentile(h, 75) == pytest.approx(1.5)

    def test_uniform_interpolation(self):
        # uniform mass in one bucket: quantiles scale linearly
        h = Histogram(buckets=(0.0, 10.0))
        for _ in range(10):
            h.observe(5.0)
        assert tr.percentile(h, 50) == pytest.approx(5.0)
        assert tr.percentile(h, 90) == pytest.approx(9.0)
        assert tr.percentile(h, 10) == pytest.approx(1.0)

    def test_empty_is_none(self):
        h = Histogram(buckets=(1.0,))
        assert tr.percentile(h, 50) is None
        assert tr.percentiles(h) == {"p50": None, "p90": None, "p99": None}

    def test_inf_bucket_clamps_to_last_finite(self):
        h = Histogram(buckets=(1.0, 2.0))
        h.observe(100.0)   # lands in +Inf bucket
        assert tr.percentile(h, 99) == pytest.approx(2.0)

    def test_invalid_q_raises(self):
        h = Histogram(buckets=(1.0,))
        with pytest.raises(ValueError):
            tr.percentile(h, 101)

    def test_snapshot_series_form(self):
        # the snapshot dict shape ({counts, count}) + explicit buckets
        h = Histogram(buckets=(1.0, 2.0))
        for _ in range(4):
            h.observe(1.0)
        series = {"counts": list(h._counts), "count": h.count}
        assert tr.percentile(series, 100, buckets=h.buckets) == \
            pytest.approx(1.0)
        with pytest.raises(ValueError):
            tr.percentile(series, 50)   # buckets required

    def test_slo_summary_shape(self):
        reg = Registry()
        h = reg.histogram("serving.engine.ttft_seconds", buckets=(1.0, 2.0))
        h.observe(1.0)
        s = tr.slo_summary(["serving.engine.ttft_seconds"], reg=reg)
        row = s["serving.engine.ttft_seconds"]
        assert row["count"] == 1
        assert row["mean"] == pytest.approx(1.0)
        assert set(row) == {"count", "mean", "p50", "p90", "p99"}


class TestSloEdgeCases:
    """ISSUE 11 satellite: slo_summary on degenerate histograms —
    empty, single-bucket, and everything-in-+Inf."""

    def test_empty_histogram_reports_count_zero_none_quantiles(self):
        reg = Registry()
        reg.histogram("slo.empty", buckets=(1.0, 2.0))
        s = tr.slo_summary(("slo.empty",), reg=reg)
        assert s["slo.empty"] == {"count": 0, "mean": None, "p50": None,
                                  "p90": None, "p99": None}

    def test_single_bucket_interpolates_from_zero(self):
        h = Histogram(buckets=(2.0,))
        for _ in range(4):
            h.observe(1.0)
        # one finite bucket: quantiles interpolate linearly from the
        # implicit 0 lower edge to the single bound
        assert tr.percentile(h, 25) == pytest.approx(0.5)
        assert tr.percentile(h, 50) == pytest.approx(1.0)
        assert tr.percentile(h, 100) == pytest.approx(2.0)

    def test_all_observations_in_inf_bucket_clamp(self):
        reg = Registry()
        h = reg.histogram("slo.inf", buckets=(0.1, 1.0))
        for _ in range(3):
            h.observe(9.9)
        s = tr.slo_summary(("slo.inf",), reg=reg)["slo.inf"]
        assert s["count"] == 3
        assert s["mean"] == pytest.approx(9.9)
        # every quantile clamps to the largest finite bound (the
        # Prometheus histogram_quantile convention) — the mean is the
        # only signal the buckets were mis-sized
        assert s["p50"] == s["p90"] == s["p99"] == pytest.approx(1.0)


# ------------------------------------------------------------------ recorder

class TestRecorder:
    def test_event_ordering_monotonic(self):
        rec = tr.TraceRecorder(capacity=4)
        rec.begin("r1")
        for name in ("enqueue", "admit", "token", "token"):
            rec.stamp("r1", name)
        rec.finish("r1", "finish")
        t = rec.trace("r1")
        ts = [e.t_us for e in t.timeline()]
        assert ts == sorted(ts)
        assert [e.name for e in t.timeline()] == \
            ["enqueue", "admit", "token", "token", "finish"]
        assert t.outcome == "finish"

    def test_derived_latencies(self):
        rec = tr.TraceRecorder(capacity=4)
        rec.begin("r")
        rec.stamp("r", "enqueue")
        rec.stamp("r", "admit")
        rec.stamp("r", "token")
        rec.stamp("r", "token")
        rec.stamp("r", "token")
        rec.finish("r", "finish")
        t = rec.trace("r")
        assert t.queue_wait_s() >= 0
        assert t.ttft_s() >= t.queue_wait_s()
        # 3 tokens -> tpot = (last-first)/2
        gap = (t.last("token").t_us - t.first("token").t_us) / 1e6
        assert t.tpot_s() == pytest.approx(gap / 2)
        assert t.e2e_s() >= t.ttft_s()

    def test_unknown_id_stamp_ignored(self):
        rec = tr.TraceRecorder(capacity=4)
        rec.stamp("ghost", "token")
        rec.finish("ghost")
        assert rec.trace("ghost") is None

    def test_ring_eviction_oldest_first(self):
        rec = tr.TraceRecorder(capacity=3)
        for i in range(5):
            rec.begin(i)
            rec.stamp(i, "enqueue")
            rec.finish(i, "finish")
        done = rec.finished()
        assert [t.request_id for t in done] == [2, 3, 4]

    def test_disabled_records_nothing(self):
        rec = tr.TraceRecorder(capacity=4)
        tr.set_enabled(False)
        try:
            assert rec.begin("r") is None
            rec.stamp("r", "enqueue")
            rec.finish("r")
        finally:
            tr.set_enabled(True)
        assert not rec.live() and not rec.finished()

    def test_trace_prefers_live_then_latest_done(self):
        rec = tr.TraceRecorder(capacity=4)
        rec.begin("r")
        rec.stamp("r", "enqueue")
        rec.finish("r", "finish")
        rec.begin("r")           # same id re-submitted
        rec.stamp("r", "enqueue")
        assert rec.trace("r").outcome is None       # the live one
        rec.finish("r", "finish")
        assert rec.trace("r").outcome == "finish"

    def test_background_exporter_jsonl(self, tmp_path):
        rec = tr.TraceRecorder(capacity=16)
        path = str(tmp_path / "traces.jsonl")
        rec.start_exporter(path, interval_s=0.01)
        try:
            for i in range(4):
                rec.begin(i)
                rec.stamp(i, "enqueue")
                rec.stamp(i, "token")
                rec.finish(i, "finish")
        finally:
            rec.stop_exporter()
        lines = [json.loads(ln) for ln in open(path) if ln.strip()]
        assert len(lines) == 4
        assert {r["request_id"] for r in lines} == {0, 1, 2, 3}
        assert all(r["outcome"] == "finish" for r in lines)
        assert all(e["t_us"] for r in lines for e in r["events"])

    def test_exporter_thread_shares_recorder_lock(self):
        # the flush thread must only touch state under the recorder lock
        # (the PT006 discipline): hammer finish() from the main thread
        # while the exporter drains, then verify nothing was lost
        rec = tr.TraceRecorder(capacity=512)
        stop = threading.Event()

        def producer():
            for i in range(200):
                rec.begin(("p", i))
                rec.stamp(("p", i), "enqueue")
                rec.finish(("p", i), "finish")
            stop.set()

        import tempfile
        with tempfile.TemporaryDirectory() as d:
            rec.start_exporter(d + "/t.jsonl", interval_s=0.001)
            th = threading.Thread(target=producer)
            th.start()
            th.join(timeout=10)
            rec.stop_exporter()
            assert stop.is_set()
            lines = [json.loads(ln) for ln in open(d + "/t.jsonl")
                     if ln.strip()]
        assert len(lines) == 200


# ------------------------------------------------- serving-engine integration

def _tiny_engine(**kw):
    from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny_config
    cfg = llama_tiny_config(num_hidden_layers=1)
    kw.setdefault("max_slots", 2)
    kw.setdefault("page_size", 4)
    kw.setdefault("prefill_chunk", 4)
    return srv.ServingEngine(LlamaForCausalLM(cfg), **kw), cfg


@pytest.mark.slow
class TestEngineTracing:
    def test_seeded_join_leave_trace_timeline(self):
        eng, cfg = _tiny_engine()
        rng = np.random.RandomState(0)
        for i in range(3):
            eng.add_request(rng.randint(0, cfg.vocab_size, 5).astype(
                np.int32), max_new_tokens=3, request_id=i)
        eng.run_to_completion()
        done = {t.request_id: t for t in tr.recorder().finished("request")}
        assert set(done) == {0, 1, 2}
        for t in done.values():
            names = [e.name for e in t.timeline()]
            # monotonic timestamps, canonical order, terminal last
            ts = [e.t_us for e in t.timeline()]
            assert ts == sorted(ts)
            assert names[0] == "enqueue" and names[-1] == "finish"
            assert names.index("admit") < names.index("prefill_chunk") \
                < names.index("token")
            assert t.count("token") == 3
            assert t.outcome == "finish"
            # every request produced the full SLO set
            assert t.queue_wait_s() is not None
            assert t.ttft_s() is not None
            assert t.tpot_s() is not None
            assert t.e2e_s() is not None
        # SLO percentiles come out of serving.slo()
        s = srv.slo()
        assert s["serving.engine.ttft_seconds"]["count"] >= 3
        assert s["serving.engine.ttft_seconds"]["p99"] is not None

    def test_chrome_export_round_trip_and_host_correlation(self, tmp_path):
        eng, cfg = _tiny_engine()
        eng.add_request(np.arange(5, dtype=np.int32) % cfg.vocab_size,
                        max_new_tokens=2, request_id="rt")
        eng.run_to_completion()
        path = str(tmp_path / "trace.json")
        n = tr.recorder().export_chrome_trace(path)
        events = load_profiler_result(path)
        assert len(events) == n > 0
        req_trace = tr.recorder().trace("rt")
        # the request's lifetime span carries its span id in the
        # observability.span naming convention
        spans = [e for e in events
                 if e["name"].endswith(f"[span={req_trace.span_id}]")]
        assert len(spans) == 1 and spans[0]["ph"] == "X"
        assert spans[0]["args"]["outcome"] == "finish"
        # phase rows nest inside the lifetime span
        phases = {e["name"] for e in events if e.get("cat") == "phase"}
        assert {"queue", "prefill", "decode"} <= phases
        # token stamps carry the host-profiler span id of their engine
        # step -> joinable against the host chrome trace
        toks = [e for e in events if e["name"] == "token"]
        assert toks and all("host_span" in e["args"] for e in toks)

    def test_refused_request_appears_in_timeline(self):
        from paddle_tpu import resilience as res
        from paddle_tpu.inference import Config
        cfg = Config()
        cfg.set_admission(max_inflight=1, queue_timeout_s=0.0)
        eng, mcfg = _tiny_engine(config=cfg, max_slots=1)
        eng.add_request(np.arange(4, dtype=np.int32) % mcfg.vocab_size,
                        max_new_tokens=2, request_id="a")
        with pytest.raises(res.Overloaded):
            eng.add_request(np.arange(4, dtype=np.int32) % mcfg.vocab_size,
                            max_new_tokens=2, request_id="b")
        t = tr.recorder().trace("b")
        assert t is not None and t.outcome == "refused"
        assert [e.name for e in t.timeline()] == ["enqueue", "refused"]
        eng.run_to_completion()
        assert tr.recorder().trace("a").outcome == "finish"

    def test_queue_timeout_stamps_overloaded(self):
        from paddle_tpu import resilience as res
        from paddle_tpu.inference import Config
        cfg = Config()
        cfg.set_admission(max_inflight=1, queue_timeout_s=1e-4)
        eng, mcfg = _tiny_engine(config=cfg, max_slots=1)
        eng.add_request(np.arange(4, dtype=np.int32) % mcfg.vocab_size,
                        max_new_tokens=4, request_id="x")
        eng.add_request(np.arange(4, dtype=np.int32) % mcfg.vocab_size,
                        max_new_tokens=4, request_id="y")
        import time
        time.sleep(0.01)
        results = eng.run_to_completion()
        assert isinstance(results["y"], res.Overloaded)
        t = tr.recorder().trace("y")
        assert t.outcome == "overloaded"
        assert t.first("token") is None   # never decoded
        assert "waited_s" in t.last("overloaded").meta

    def test_deadline_timeout_stamps_terminal(self):
        from paddle_tpu import resilience as res
        eng, mcfg = _tiny_engine()
        eng.add_request(np.arange(6, dtype=np.int32) % mcfg.vocab_size,
                        max_new_tokens=8, deadline_s=1e-6,
                        request_id="d")
        results = eng.run_to_completion()
        assert isinstance(results["d"], res.TimeoutResult)
        t = tr.recorder().trace("d")
        assert t.outcome == "timeout"

    def test_tracing_off_engine_still_exact(self):
        tr.set_enabled(False)
        try:
            eng, mcfg = _tiny_engine()
            eng.add_request(np.arange(5, dtype=np.int32) % mcfg.vocab_size,
                            max_new_tokens=3, request_id=0)
            results = eng.run_to_completion()
            assert results[0].shape == (3,)
            assert tr.recorder().trace(0) is None
        finally:
            tr.set_enabled(True)


class TestServingStampRoundTrip:
    """PR-10 stamps (prefix_hit, preempted/resumed, draft/verify_accept)
    recorded on a RequestTrace survive the chrome-trace export."""

    def test_recorder_level_roundtrip(self, tmp_path):
        rec = tr.recorder()
        rec.begin("r", prompt_len=12, max_new_tokens=4, priority=2,
                  tenant="acme")
        rec.stamp("r", "enqueue")
        rec.stamp("r", "admit", slot=0)
        rec.stamp("r", "prefix_hit", tokens=8, pages=2)
        rec.stamp("r", "token")
        rec.stamp("r", "preempted", decoded=1)
        rec.stamp("r", "resumed", slot=1, decoded=1)
        rec.stamp("r", "draft", tokens=3)
        rec.stamp("r", "verify_accept", drafted=3, accepted=2)
        rec.stamp("r", "token")
        rec.finish("r", "finish")
        t = rec.trace("r")
        names = [e.name for e in t.timeline()]
        for name in ("prefix_hit", "preempted", "resumed", "draft",
                     "verify_accept"):
            assert name in names
        assert names.index("preempted") < names.index("resumed")
        assert t.first("prefix_hit").meta["tokens"] == 8
        assert t.first("verify_accept").meta == {"drafted": 3,
                                                 "accepted": 2}
        path = str(tmp_path / "trace.json")
        n = rec.export_chrome_trace(path)
        events = load_profiler_result(path)
        assert len(events) == n
        by_name = {e["name"]: e for e in events
                   if e["name"] in ("prefix_hit", "preempted", "resumed",
                                    "draft", "verify_accept")}
        assert set(by_name) == {"prefix_hit", "preempted", "resumed",
                                "draft", "verify_accept"}
        assert by_name["prefix_hit"]["args"]["tokens"] == 8
        assert by_name["verify_accept"]["args"]["accepted"] == 2


class TestCounterTracks:
    """ISSUE 11 satellite: gauge samples become ph:"C" counter events in
    the chrome export (the PR-6 exporter dropped gauges entirely)."""

    def test_counter_roundtrip(self, tmp_path):
        rec = tr.recorder()
        rec.counter("pool.util", 0.25, t_us=100)
        rec.counter("pool.util", 0.75, t_us=200)
        rec.counter("hbm.bytes", 4096, t_us=150)
        assert rec.counters()["pool.util"] == [(100, 0.25), (200, 0.75)]
        path = str(tmp_path / "t.json")
        n = rec.export_chrome_trace(path)
        events = load_profiler_result(path)
        assert len(events) == n == 3
        cs = [e for e in events if e["ph"] == "C"]
        assert {(e["name"], e["ts"], e["args"]["value"]) for e in cs} \
            == {("pool.util", 100, 0.25), ("pool.util", 200, 0.75),
                ("hbm.bytes", 150, 4096.0)}
        assert all(e["cat"] == "counter" for e in cs)

    def test_sample_gauges_reads_registry(self):
        reg = Registry()
        reg.gauge("g.a", "a").set(3.5)
        reg.gauge("g.b", "b").set(7)
        reg.counter("g.c", "not a gauge").inc()
        rec = tr.recorder()
        # missing names and non-gauges are skipped, not errors
        assert rec.sample_gauges(("g.a", "g.b", "g.c", "g.nope"),
                                 reg=reg) == 2
        got = rec.counters()
        assert [v for _, v in got["g.a"]] == [3.5]
        assert [v for _, v in got["g.b"]] == [7.0]
        assert "g.c" not in got and "g.nope" not in got

    def test_counter_disabled_is_noop(self):
        tr.set_enabled(False)
        try:
            rec = tr.recorder()
            rec.counter("x", 1.0)
            assert rec.sample_gauges(("x",)) == 0
            assert rec.counters() == {}
        finally:
            tr.set_enabled(True)

    def test_counter_track_bounded_by_capacity(self):
        rec = tr.TraceRecorder(capacity=4)
        for i in range(10):
            rec.counter("x", float(i), t_us=i)
        assert [v for _, v in rec.counters()["x"]] == [6.0, 7.0, 8.0, 9.0]

    def test_clear_drops_counters(self):
        rec = tr.recorder()
        rec.counter("x", 1.0)
        rec.clear()
        assert rec.counters() == {}


@pytest.mark.slow
class TestEngineCounterTracks:
    def test_engine_step_exports_hbm_counter_tracks(self, tmp_path):
        eng, cfg = _tiny_engine()
        eng.add_request(np.arange(5, dtype=np.int32) % cfg.vocab_size,
                        max_new_tokens=4, request_id="c")
        eng.run_to_completion()
        acct = eng.hbm_accounting()
        assert acct["weights_bytes"] > 0
        assert acct["page_pool_bytes"] > 0
        assert acct["ledger_tokens"] > 0
        # the live ledger and the analytical budget agree well inside
        # the observatory's 25% acceptance band on this seeded trace
        ratio = (acct["bytes_per_token_measured"]
                 / acct["bytes_per_token_model"])
        assert 0.75 < ratio < 1.25
        path = str(tmp_path / "t.json")
        tr.recorder().export_chrome_trace(path)
        events = load_profiler_result(path)
        series = {}
        for e in events:
            if e["ph"] == "C":
                series.setdefault(e["name"], []).append(
                    e["args"]["value"])
        for name in ("serving.engine.pages_used",
                     "serving.engine.page_utilization",
                     "serving.engine.page_fragmentation",
                     "serving.engine.hbm_weights_bytes",
                     "serving.engine.hbm_page_pool_bytes",
                     "serving.engine.bytes_per_token_measured"):
            assert name in series, name
        # one sample per engine step, constant residency throughout
        assert set(series["serving.engine.hbm_weights_bytes"]) \
            == {acct["weights_bytes"]}
        assert set(series["serving.engine.hbm_page_pool_bytes"]) \
            == {acct["page_pool_bytes"]}
        # utilization rises from empty, then drains at finish down to
        # the pages the prefix cache retains for future prompt hits
        util = series["serving.engine.page_utilization"]
        assert max(util) > 0 and util[-1] < max(util)


@pytest.mark.slow
class TestEngineServingStamps:
    def test_prefix_hit_and_spec_stamps(self, tmp_path):
        eng, cfg = _tiny_engine(spec_decode=3, prefix_sharing=False)
        rng = np.random.RandomState(7)
        prompt = rng.randint(0, cfg.vocab_size, 12).astype(np.int32)
        eng.add_request(prompt, max_new_tokens=3, request_id="warm")
        eng.run_to_completion()
        eng.add_request(prompt.copy(), max_new_tokens=3, request_id="hit",
                        tenant="acme")
        eng.run_to_completion()
        t = tr.recorder().trace("hit")
        hit = t.first("prefix_hit")
        assert hit is not None and hit.meta["tokens"] >= 8
        assert t.meta.get("tenant") == "acme"
        # spec decode on a repetitive prompt stamps draft/verify_accept
        rep = np.asarray([5, 9, 5, 9, 5, 9, 5, 9], np.int32)
        eng.add_request(rep, max_new_tokens=6, request_id="spec")
        eng.run_to_completion()
        ts = tr.recorder().trace("spec")
        if ts.first("draft") is not None:       # model-dependent drafts
            assert ts.first("draft").meta["tokens"] >= 1
        # chrome export round-trips every stamped event
        path = str(tmp_path / "t.json")
        n = tr.recorder().export_chrome_trace(path)
        events = load_profiler_result(path)
        assert len(events) == n > 0
        assert any(e["name"] == "prefix_hit" for e in events)

    def test_preempt_resume_stamps(self):
        from paddle_tpu.serving.scheduler import DECODE
        eng, cfg = _tiny_engine(max_slots=1)
        rng = np.random.RandomState(9)
        p1 = rng.randint(0, cfg.vocab_size, 5).astype(np.int32)
        p2 = rng.randint(0, cfg.vocab_size, 5).astype(np.int32)
        r1 = eng.add_request(p1, max_new_tokens=8, request_id="low",
                             priority=0)
        while r1.state != DECODE or len(r1.tokens) < 1:
            eng.step()
        eng.add_request(p2, max_new_tokens=2, request_id="high",
                        priority=3)
        eng.run_to_completion()
        t = tr.recorder().trace("low")
        names = [e.name for e in t.timeline()]
        assert "preempted" in names and "resumed" in names
        assert names.index("preempted") < names.index("resumed")
        assert t.first("preempted").meta["decoded"] >= 1
        # no re-prefill on resume: every prefill_chunk stamp precedes
        # the preemption
        pre = names.index("preempted")
        assert all(i < pre for i, nm in enumerate(names)
                   if nm == "prefill_chunk")
        assert tr.recorder().trace("high").meta.get("priority") == 3


# ---------------------------------------------------------- trainer phases

@pytest.mark.slow
class TestTrainerTracing:
    def test_step_phase_spans(self):
        from paddle_tpu import nn
        from paddle_tpu.trainer.trainer import Trainer, TrainingArguments

        class DS:
            def __len__(self):
                return 4

            def __getitem__(self, i):
                x = np.random.RandomState(i).randn(4).astype("float32")
                return x, x.sum(keepdims=True).astype("float32")

        t = Trainer(model=nn.Linear(4, 1),
                    args=TrainingArguments(
                        max_steps=2, per_device_train_batch_size=2,
                        logging_steps=1),
                    train_dataset=DS(), criterion=nn.MSELoss())
        t.train()
        done = tr.recorder().finished("train")
        assert len(done) == 2
        for st in done:
            names = [e.name for e in st.timeline()]
            assert names == ["data", "fwd", "bwd", "opt", "finish"]
            assert all(e.meta and e.meta.get("dur_us", 0) >= 0
                       for e in st.timeline()[:-1])
            assert st.outcome == "finish"
        assert done[0].meta["step"] == 1
        # train-step traces must NOT pollute the serving SLO histograms
        # (kind guard): export still renders them as chrome rows
        import tempfile
        with tempfile.TemporaryDirectory() as d:
            n = tr.recorder().export_chrome_trace(d + "/t.json")
            evs = load_profiler_result(d + "/t.json")
        assert any(e["name"].startswith("train:train-step-")
                   for e in evs)
        # phase events carry explicit durations -> exported as X spans
        assert any(e["ph"] == "X" and e["name"] == "fwd" for e in evs)
