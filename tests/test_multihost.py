"""Executed multi-host path (VERDICT r3 item 5 + r4 item 1; SURVEY §3.1
bring-up, §3.5 train path, §5.8 DCN half): 2 OS processes x 4 virtual CPU
devices each, through python -m paddle_tpu.distributed.launch -> TCPStore
rendezvous -> init_parallel_env -> jax.distributed.initialize (gloo CPU
collectives) -> (a) a psum across all 8 global devices, (b) a HYBRID
TRAIN STEP (dp x mp x ZeRO and pp x mp x dp tiny-llama) over the global
mesh with per-step loss parity vs the single-process 8-device run. Plus
the elastic relaunch-with-new-ranks flow (ref: ElasticManager scale-in ->
rank regen -> respawn)."""

import os
import socket
import subprocess
import sys
import time

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ASSETS = os.path.join(REPO, "tests", "assets")


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


def _launch_node(node_rank, nnodes, master, script, log_dir, out_dir,
                 extra_env=None):
    env = dict(os.environ)
    env["MH_OUT"] = out_dir
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    if extra_env:
        env.update(extra_env)
    return subprocess.Popen(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nnodes", str(nnodes), "--node_rank", str(node_rank),
         "--nproc_per_node", "1", "--master", master,
         "--log_dir", os.path.join(log_dir, f"node{node_rank}"),
         "--rdzv_timeout", "120", script],
        env=env, cwd=REPO,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT)


def _wait_all(procs, timeout):
    deadline = time.time() + timeout
    outs = []
    for p in procs:
        remaining = max(5.0, deadline - time.time())
        try:
            out, _ = p.communicate(timeout=remaining)
        except subprocess.TimeoutExpired:
            p.kill()
            out, _ = p.communicate()
        outs.append(out.decode(errors="replace"))
    return outs


def _wait_and_assert_ok(procs, tmp_path, timeout, nnodes=2):
    """Wait for all launched nodes, collect workerlogs (launcher names them
    workerlog.{global_rank} under node{r}/), assert zero exit codes."""
    outs = _wait_all(procs, timeout)
    logs = []
    for r in range(nnodes):
        d = tmp_path / f"node{r}" / "workerlog.{}".format(r)
        logs.append(d.read_text(errors="replace") if d.exists() else "")
    assert all(p.returncode == 0 for p in procs), (
        [p.returncode for p in procs], outs, logs)
    return outs, logs


class TestMultiHostPsum:
    def test_two_process_launch_psum_across_8_devices(self, tmp_path):
        master = f"127.0.0.1:{_free_port()}"
        out_dir = str(tmp_path / "out")
        os.makedirs(out_dir)
        procs = [
            _launch_node(r, 2, master, os.path.join(
                ASSETS, "multihost_psum_worker.py"),
                str(tmp_path), out_dir)
            for r in range(2)]
        outs, logs = _wait_and_assert_ok(procs, tmp_path, timeout=420)
        for r in range(2):
            f = os.path.join(out_dir, f"ok.{r}")
            assert os.path.exists(f), (outs, logs)
            # psum over [0..3]+[10..13] across the 8-device global mesh
            assert float(open(f).read()) == 52.0


class TestMultiHostTrain:
    """VERDICT r4 item 1: the actual §3.5 path — launcher -> rendezvous ->
    jax.distributed -> GLOBAL 8-device mesh -> hybrid TRAIN step with
    GSPMD collectives crossing the OS-process boundary -> loss parity
    vs the same routine on the single-process 8-device mesh."""

    @pytest.mark.parametrize("cfg_name", ["dp2mp2zero2", "pp2mp2dp2"])
    def test_two_process_hybrid_train_loss_parity(self, tmp_path, cfg_name):
        import json
        sys.path.insert(0, ASSETS)
        from mh_train_common import run_train

        # baseline: SAME routine, single process, pytest's 8-device mesh
        baseline = run_train(cfg_name)
        assert all(np.isfinite(v) for v in baseline), baseline

        master = f"127.0.0.1:{_free_port()}"
        out_dir = str(tmp_path / "out")
        os.makedirs(out_dir)
        procs = [
            _launch_node(r, 2, master,
                         os.path.join(ASSETS, "multihost_train_worker.py"),
                         str(tmp_path), out_dir,
                         extra_env={"MH_TRAIN_CFG": cfg_name})
            for r in range(2)]
        outs, logs = _wait_and_assert_ok(procs, tmp_path, timeout=420)
        for r in range(2):
            f = os.path.join(out_dir, f"losses.{r}.json")
            assert os.path.exists(f), (outs, logs)
            got = json.load(open(f))
            # per-step loss parity: the 2-process global-mesh program is
            # the same SPMD program; only collective reduction order may
            # differ (gloo ring vs shared-memory)
            assert np.allclose(got, baseline, rtol=1e-5, atol=1e-5), (
                got, baseline)


class TestElasticRelaunch:
    def test_membership_loss_rank_regen_and_relaunch(self, tmp_path):
        from paddle_tpu.native import TCPStore
        from paddle_tpu.distributed.launch.controllers import ElasticManager

        store = TCPStore(host="127.0.0.1", port=0, is_master=True,
                         world_size=1, timeout=30)
        try:
            mgrs = [ElasticManager(store, i, ttl=5.0) for i in range(3)]
            for m in mgrs:
                m.heartbeat()
            assert mgrs[0].alive_nodes(3) == [0, 1, 2]
            assert not mgrs[0].membership_changed(3)
            # node 1 dies: age out its heartbeat
            store.set("heartbeat/1", str(time.time() - 100))
            assert mgrs[0].membership_changed(3)
            ranks = mgrs[0].regenerate_ranks(3)
            assert ranks == {0: 0, 2: 1}
        finally:
            store.close()

        # EXECUTE the relaunch with the regenerated ranks: the survivors
        # come back as a 2-node world with compacted node_ranks
        master = f"127.0.0.1:{_free_port()}"
        out_dir = str(tmp_path / "out")
        os.makedirs(out_dir)
        procs = [
            _launch_node(new_rank, len(ranks), master,
                         os.path.join(ASSETS, "rank_echo_worker.py"),
                         str(tmp_path), out_dir)
            for new_rank in ranks.values()]
        outs = _wait_all(procs, timeout=120)
        assert all(p.returncode == 0 for p in procs), (outs,)
        got = set()
        for r in range(2):
            f = os.path.join(out_dir, f"rank.{r}")
            assert os.path.exists(f), outs
            got.add(open(f).read())
        assert got == {"0/2", "1/2"}
