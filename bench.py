"""Benchmark: Llama pretrain tokens/sec/chip on the local device.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

vs_baseline = measured MFU / 0.40 (the BASELINE.json north-star MFU target;
see BASELINE.md — no published reference throughput exists, so the
hardware-derived 40%-MFU bar is the baseline).
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np


def _peak_flops() -> float:
    """Per-chip peak bf16 FLOP/s for the local device generation."""
    gen = os.environ.get("PALLAS_AXON_TPU_GEN", "").lower()
    table = {"v5e": 197e12, "v5p": 459e12, "v4": 275e12, "v6e": 918e12}
    if gen in table:
        return table[gen]
    import jax
    kind = jax.devices()[0].device_kind.lower()
    for k, v in table.items():
        if k in kind or ("v5 lite" in kind and k == "v5e"):
            return v
    return 197e12  # conservative default


def main() -> None:
    import jax
    import jax.numpy as jnp
    from paddle_tpu.models.llama import LlamaConfig
    from paddle_tpu.trainer.pretrain import (PretrainConfig,
                                             build_llama_pretrain_step,
                                             make_hybrid_mesh_for,
                                             flops_per_token)

    on_tpu = jax.devices()[0].platform != "cpu"
    # tuned recipe: on the full train step the bundled flash kernel is
    # ~0.8% faster on mean with the band CROSSING 1 (same-run interleaved
    # x3: bundled/intree step-time 0.977-1.004, docs/FLASH_RECIPE_AB.json)
    # — i.e. within noise; the recipe keeps the variant that never lost a
    # round, the in-tree kernel stays the default elsewhere and is the
    # only option for configs the bundled kernel refuses
    from paddle_tpu.flags import set_flags
    set_flags({"FLAGS_flash_impl": "bundled"})
    # Headline: the per-chip shard of an mp=8 x pp=4 partitioned
    # Llama-3-8B at the flagship seq 8192 — 8 true-shape decoder layers
    # (4 q-heads of head_dim 128 over the full 4096 residual stream,
    # FFN 14336/8) plus the vocab-parallel CE slice. This measures the
    # MXU efficiency of the flagship's per-chip computation; collectives
    # and pipeline bubbles are accounted in docs/FLAGSHIP.md.
    if on_tpu:
        from paddle_tpu.models.llama import llama3_8b_shard_config
        # fused qkv/gate-up packs: +4 MFU pts on the thin TP-shard
        # matmul shapes (they were neutral on the old square proxy)
        mc = llama3_8b_shard_config(mp=8, pp=4,
                                    max_position_embeddings=8192,
                                    sequence_parallel=False,
                                    fuse_attention_qkv=True,
                                    fuse_attention_ffn=True)
        batch, seq, steps = 3, 8192, 8
    else:  # CI smoke fallback
        mc = LlamaConfig(vocab_size=512, hidden_size=128,
                         intermediate_size=256, num_hidden_layers=2,
                         num_attention_heads=4, num_key_value_heads=2,
                         max_position_embeddings=256,
                         sequence_parallel=False)
        batch, seq, steps = 4, 128, 2

    # remat="none": b3/s8192 residuals fit in HBM next to the f32
    # master+Adam state (flash attention saves only q/k/v/o/lse, never
    # the SxS probs); measured faster than "dots" at every feasible batch
    cfg = PretrainConfig(mc, global_batch=batch, seq_len=seq,
                         n_microbatches=1, param_dtype="bfloat16",
                         scan_layers=False, remat="none",
                         ce_chunks=2 if on_tpu else 4)
    mesh = make_hybrid_mesh_for(cfg, devices=jax.devices()[:1])
    state, train_step, meta = build_llama_pretrain_step(cfg, mesh)

    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(0, mc.vocab_size, (batch, seq)), jnp.int32)
    labels = jnp.asarray(rng.randint(0, mc.vocab_size, (batch, seq)),
                         jnp.int32)

    # Warmup TWO steps: step 1 compiles for the initial arg layouts; because
    # the state is donated, step 2's inputs carry the output layouts and
    # trigger a second compile. Timing must start only after both executables
    # are cached. float() forces a real device round-trip (block_until_ready
    # can return early through the remote-device relay).
    for _ in range(2):
        state, metrics = train_step(state, ids, labels)
        float(metrics["loss"])

    t0 = time.perf_counter()
    for _ in range(steps):
        state, metrics = train_step(state, ids, labels)
    float(metrics["loss"])
    dt = time.perf_counter() - t0

    tokens = batch * seq * steps
    tok_per_sec = tokens / dt
    # 6N fwd+bwd weight FLOPs/token — the conservative model-FLOPs MFU
    # denominator (no attention term; flops_per_token_hw adds it, and
    # docs/FLAGSHIP.md reports both conventions)
    fpt = flops_per_token(mc)
    mfu = tok_per_sec * fpt / _peak_flops()
    print(json.dumps({
        "metric": "llama3_8b_shard_pretrain_tokens_per_sec_per_chip"
                  if on_tpu else "ci_smoke_tokens_per_sec",
        "value": round(tok_per_sec, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": round(mfu / 0.40, 4),
    }))


if __name__ == "__main__":
    # the tunneled device occasionally drops a request mid-run
    # ("read body: response body closed", backend INTERNAL); one retry
    # separates a transient transport hiccup from a real failure
    try:
        main()
    except Exception as e:  # noqa: BLE001
        print(f"bench attempt 1 failed ({type(e).__name__}: {e}); "
              f"retrying once", file=sys.stderr)
        main()
