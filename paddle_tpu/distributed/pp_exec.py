"""Timetable-driven pipeline EXECUTOR: runs pp_schedule.Schedule
(FThenB / 1F1B / ZBH1) as one compiled SPMD program.

Reference parity: python/paddle/distributed/fleet/meta_parallel/
pipeline_parallel.py (1F1B runtime) + distributed/passes/
pipeline_scheduler_pass.py (ZBH1) — SURVEY §2.3 P6. The reference drives
these orders with an actor runtime and NCCL p2p; here the SAME validated
timetable (distributed/pp_schedule.py) is baked into a `lax.scan` over
ticks inside a `shard_map` over the `pp` mesh axis:

  - tick t, stage s executes exactly timeline[s][t]: F (forward one
    microbatch), B (backward-dgrad; at the last stage this also runs the
    loss head and seeds the cotangent), or W (deferred weight-grad — the
    ZBH1 split).
  - activations hop downstream and cotangents upstream via lax.ppermute,
    one message per tick, matching the schedule's 1-tick p2p latency
    model.
  - each stage keeps stage-INPUTS only (remat: B/W recompute the stage
    forward), in a ring buffer whose size is the schedule's peak-liveness
    bound (~n_stages) — NOT the microbatch count. This is 1F1B's memory
    point: GPipe's compiled autodiff stores M stage-inputs per stage, the
    executor stores ≤ bound(s) ≤ S+1.

Because forward and backward INTERLEAVE inside one program, outer
autodiff cannot drive it; `scheduled_pipeline_loss` therefore computes
all gradients in its (custom_vjp) forward pass and replays them, scaled,
in the backward rule — embedding and anything upstream of the pipeline
still differentiate normally through the returned d_microbatches.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from .pipeline import PP_AXIS, _pp_shard_map
from .pp_schedule import Schedule

__all__ = ["scheduled_pipeline_loss", "schedule_buffer_bounds"]

_PHASES = {"F": 1, "B": 2, "W": 3}  # 0 = bubble


def _tables(schedule: Schedule):
    """timeline -> (phase[S,T], mb[S,T], chunk[S,T]) int32 numpy tables."""
    S, T = schedule.n_stages, schedule.n_ticks
    phase = np.zeros((S, T), np.int32)
    mb = np.zeros((S, T), np.int32)
    chunk = np.zeros((S, T), np.int32)
    for s, row in enumerate(schedule.timeline):
        for t, op in enumerate(row):
            if op is not None:
                phase[s, t] = _PHASES[op.phase]
                mb[s, t] = op.mb
                chunk[s, t] = op.chunk
    return phase, mb, chunk


def _stage_intervals(schedule: Schedule):
    """Per-(stage, chunk) liveness intervals derived from the timetable —
    the ONE source both the buffer sizing and the slot-collision guard
    use. Virtual stage v = chunk*S + stage (Megatron ordering); v's F
    input arrives from vstage v-1 (device (s-1) mod S, wrapping chunk),
    its cotangent from vstage v+1. Yields
    (stage, chunk, {"in_buf": [(mb, start, end)], "cot_buf": ...,
    "w_buf": ...})."""
    S, M, C = schedule.n_stages, schedule.n_microbatches, schedule.n_chunks
    V = S * C
    fin: Dict[Tuple[str, int, int], int] = {}
    start: Dict[Tuple[str, int, int], int] = {}
    for s, row in enumerate(schedule.timeline):
        for t, op in enumerate(row):
            if op is not None:
                v = op.chunk * S + s
                fin[(op.phase, v, op.mb)] = t + 1
                start[(op.phase, v, op.mb)] = t
    for s in range(S):
        for c in range(C):
            v = c * S + s
            iv = {"in_buf": [], "cot_buf": [], "w_buf": []}
            for m in range(M):
                arr = fin[("F", v - 1, m)] if v > 0 \
                    else start[("F", v, m)]
                iv["in_buf"].append((m, arr, fin[("B", v, m)]))
                if v < V - 1:
                    iv["cot_buf"].append((m, fin[("B", v + 1, m)],
                                          fin[("B", v, m)]))
                if schedule.split_w:
                    iv["w_buf"].append((m, fin[("B", v, m)],
                                        fin[("W", v, m)]))
            yield s, c, iv


def schedule_buffer_bounds(schedule: Schedule) -> Dict[str, int]:
    """Peak liveness the executor must buffer, derived from the timetable:

    in_buf  — stage inputs: live from the producing stage's F (arrival)
              until this stage's B consumes them;
    cot_buf — cotangents: from downstream B until this stage's B;
    w_buf   — (ZBH1) retained (input, cotangent) pairs from B until W.

    For 1F1B these are O(n_stages); for FThenB in_buf is O(M) — the
    executor allocates what the schedule needs, so the memory claim is
    checkable per schedule. Buffers are PER DEVICE: max over stages.
    """
    def peak(intervals):
        events = []
        for _, a, b in intervals:
            events.append((a, 1))
            events.append((b, -1))
        live = best = 0
        for _, d in sorted(events, key=lambda e: (e[0], -e[1])):
            live += d
            best = max(best, live)
        return best
    out = {"in_buf": 0, "cot_buf": 1, "w_buf": 0}
    for _, _, iv in _stage_intervals(schedule):
        for name in out:
            out[name] = max(out[name], peak(iv[name]))
    if not schedule.split_w:
        out["w_buf"] = 0
    return out


def _check_slots(schedule: Schedule, K: int, KC: int, KW: int) -> None:
    """Simulate ring-buffer occupancy against the timetable: writing slot
    m % K while a DIFFERENT live microbatch occupies it is a hard error
    (would corrupt an activation). Guards the contiguous-window assumption
    the modulo slotting relies on."""
    def check(intervals, nslots, name, stage, chunk):
        occupied: Dict[int, Tuple[int, int]] = {}
        for m, a, b in sorted(intervals, key=lambda iv: iv[1]):
            slot = m % nslots
            if slot in occupied:
                m0, b0 = occupied[slot]
                if a < b0 and m0 != m:
                    raise AssertionError(
                        f"{name} slot collision at stage {stage} chunk "
                        f"{chunk}: mb {m} overwrites live mb {m0} "
                        f"(slots={nslots})")
            occupied[slot] = (m, b)
    sizes = {"in_buf": K, "cot_buf": KC, "w_buf": KW}
    for s, c, iv in _stage_intervals(schedule):
        for name, nslots in sizes.items():
            if name == "w_buf" and not schedule.split_w:
                continue
            check(iv[name], nslots, name, s, c)


def scheduled_pipeline_loss(schedule: Schedule, stage_fn: Callable,
                            head_fn: Callable, mesh: Mesh,
                            stacked_params: Dict[str, Any], head_params,
                            microbatches, labels, extra_args=(),
                            mb_auto_spec: Any = None):
    """Execute `schedule` over the pp axis of `mesh`; returns the SUMMED
    loss (caller normalizes). Differentiable in (stacked_params,
    head_params, microbatches).

    stage_fn(local_params, x, *extra) -> y          (one stage's layers)
    head_fn(head_params, y, labels_mb) -> scalar    (last-stage loss head,
                                                     SUM over tokens)
    stacked_params: {name: [S, L/S, ...]}, dim 0 on pp.
    microbatches: [M, mb, ...] stage-0 inputs (already embedded).
    labels: [M, mb, ...] int labels per microbatch.
    mb_auto_spec: optional PartitionSpec giving ONE microbatch's sharding
      over the AUTO (non-pp) mesh axes, e.g. P(("dp","sharding"), "sep",
      None) for [mb, S, H]. Required when microbatches arrive sharded on
      an auto axis like `sep`: the lax.switch branches each produce
      mb-shaped values (real activations vs. fresh zeros) whose inferred
      shardings differ, and the SPMD partitioner cannot unify branch
      outputs under partial-manual sharding (CHECK at
      spmd_partitioner_util.cc:495). Pinning every mb-shaped value to one
      explicit sharding keeps the branches consistent.
    """
    S = mesh.shape[PP_AXIS]
    M = schedule.n_microbatches
    C = schedule.n_chunks
    if schedule.n_stages != S:
        raise ValueError(f"schedule has {schedule.n_stages} stages, "
                         f"mesh pp={S}")
    if C > 1 and schedule.split_w:
        raise ValueError("chunked (VPP) timetables with split wgrad are "
                         "not supported (upstream VPP is F/B only)")
    if C > 1:
        # interleaved layout contract: {name: [S, C, L/(S*C), ...]}
        for k, v in stacked_params.items():
            if v.ndim < 2 or v.shape[1] != C:
                raise ValueError(
                    f"VPP executor expects stacked_params[{k!r}] with "
                    f"chunk dim {C} at axis 1 (got shape {v.shape}); "
                    f"stack with stack_layer_params_interleaved")
    if S == 1:
        raise ValueError("pp=1 needs no schedule; use spmd_pipeline")

    phase_np, mb_np, chunk_np = _tables(schedule)
    bounds = schedule_buffer_bounds(schedule)
    K = bounds["in_buf"] + 1          # +1: write-before-read margin
    KC = bounds["cot_buf"] + 1
    KW = (bounds["w_buf"] + 1) if schedule.split_w else 1
    _check_slots(schedule, K, KC, KW)
    T = schedule.n_ticks
    phase_tab = jnp.asarray(phase_np)
    mb_tab = jnp.asarray(mb_np)
    chunk_tab = jnp.asarray(chunk_np)
    down = [(i, (i + 1) % S) for i in range(S)]
    up = [((i + 1) % S, i) for i in range(S)]

    cdt = microbatches.dtype
    mb_shape = microbatches.shape[1:]

    def _f32_psum(x):
        return jax.lax.psum(x.astype(jnp.float32), PP_AXIS).astype(x.dtype)

    # with_sharding_constraint inside the pp-manual shard_map needs the
    # pp axis TYPED Manual on the sharding's mesh (vma axes must be
    # Manual); the auto axes keep their Auto type. Legacy jax has no
    # AxisType, and its partitioner CHECK-crashes on any wsc inside a
    # partial-manual region (hlo_sharding_util: sharding.IsManualSubgroup)
    # — there the pins become identity and GSPMD infers the auto-axes
    # sharding on its own.
    try:
        from jax.sharding import AxisType
    except ImportError:
        AxisType = None
    if mb_auto_spec is not None and AxisType is not None:
        from jax.sharding import NamedSharding
        _mesh_mpp = Mesh(
            mesh.devices, mesh.axis_names,
            axis_types=tuple(AxisType.Manual if n == PP_AXIS
                             else AxisType.Auto for n in mesh.axis_names))
        _mb_shd = NamedSharding(_mesh_mpp, mb_auto_spec)

        def _pin(v):
            """Pin an mb-shaped value to the caller's auto-axes sharding."""
            return jax.lax.with_sharding_constraint(v, _mb_shd)

        def _pin_buf(v):
            """Same, for buffers with extra leading (slot/chunk) dims."""
            lead = v.ndim - len(mb_shape)
            shd = NamedSharding(
                _mesh_mpp, P(*([None] * lead), *tuple(mb_auto_spec)))
            return jax.lax.with_sharding_constraint(v, shd)
    else:
        _pin = _pin_buf = lambda v: v

    # COMPOSITION LIMIT (measured, round 3): a NON-batch microbatch dim
    # sharded on an auto axis (seq on `sep`) cannot enter this executor.
    # Attention inside the lax.switch branches then needs seq
    # all-gathers, which XLA lowers to collective-permutes whose CPU
    # rendezvous wants every local device — devices in other branches
    # never arrive (runtime deadlock), and some variants die earlier in
    # the SPMD partitioner (CHECK spmd_partitioner_util.cc:495). Callers
    # must gather such axes at the boundary (trainer/pretrain.py does);
    # in-executor sequence parallelism rides the mp axis (Megatron SP),
    # and ring/Ulysses context parallelism composes with the COMPILED
    # pipeline path instead.
    if mb_auto_spec is not None:
        for _d, _entry in enumerate(tuple(mb_auto_spec)):
            if _d == 0 or _entry is None:
                continue
            for _ax in (_entry if isinstance(_entry, tuple) else (_entry,)):
                if mesh.shape.get(_ax, 1) > 1:
                    raise ValueError(
                        f"mb_auto_spec {mb_auto_spec} shards non-batch "
                        f"dim {_d} on axis {_ax!r}: unsupported inside "
                        f"the timetable executor (in-branch seq "
                        f"collectives deadlock); gather it at the "
                        f"boundary first")

    def per_device(params, head_p, mbs, labels_, *extra):
        # local slice: [L/S, ...] for C==1, [C, L/(S*C), ...] for VPP
        local = {k: v[0] for k, v in params.items()}
        stage = jax.lax.axis_index(PP_AXIS)
        zero_mb = jnp.zeros(mb_shape, cdt)

        def stage_f(p, x):
            return stage_fn(p, x, *extra)

        def chunk_params(ch):
            """The chunk's layer-parameter slice (identity for C==1)."""
            if C == 1:
                return local
            return {k: jax.lax.dynamic_index_in_dim(v_, ch, 0,
                                                    keepdims=False)
                    for k, v_ in local.items()}

        def pv(a):
            """pvary, idempotent: no-op when already device-varying."""
            from ._compat import pvary, vma_of
            return a if PP_AXIS in vma_of(a) else pvary(a, PP_AXIS)
        # CRITICAL: vjp w.r.t. a pp-INVARIANT value makes shard_map insert
        # a psum_invariant collective to re-invariant the cotangent — and
        # a collective inside one lax.switch branch deadlocks devices that
        # took other branches. Mark the replicated head params varying
        # BEFORE any vjp; grads are psum'd once at the end instead.
        head_v = jax.tree.map(pv, head_p)
        # message tuples: (payload, mb, receiver_chunk, valid)
        zmsg = (pv(jnp.zeros((), jnp.int32)), pv(jnp.zeros((), jnp.int32)),
                pv(jnp.zeros((), jnp.bool_)))
        carry0 = dict(
            in_buf=_pin_buf(pv(jnp.zeros((C, K) + mb_shape, cdt))),
            cot_buf=_pin_buf(pv(jnp.zeros((C, KC) + mb_shape, cdt))),
            wx_buf=_pin_buf(pv(jnp.zeros((C, KW) + mb_shape, cdt))),
            wg_buf=_pin_buf(pv(jnp.zeros((C, KW) + mb_shape, cdt))),
            dmbs=_pin_buf(pv(jnp.zeros((M,) + mb_shape, cdt))),
            accp=jax.tree.map(
                lambda v: pv(jnp.zeros(v.shape, jnp.float32)), local),
            acch=jax.tree.map(
                lambda v: pv(jnp.zeros(v.shape, jnp.float32)), head_p),
            loss=pv(jnp.zeros((), jnp.float32)),
            fmsg=(_pin(pv(zero_mb)),) + zmsg,
            bmsg=(_pin(pv(zero_mb)),) + zmsg,
        )

        def tick(carry, t):
            c = dict(carry)
            # 1) deliver last tick's messages (1-tick p2p latency).
            # Sender-side validity decides delivery: the flag rides the
            # same ppermute, so it arrives exactly at the receiver.
            fy, fm, frc, fv = c["fmsg"]
            frc = jnp.clip(frc, 0, C - 1)
            c["in_buf"] = _pin_buf(c["in_buf"].at[frc, fm % K].set(
                jnp.where(fv, fy, c["in_buf"][frc, fm % K])))
            by, bm, brc, bv = c["bmsg"]
            brc = jnp.clip(brc, 0, C - 1)
            c["cot_buf"] = _pin_buf(c["cot_buf"].at[brc, bm % KC].set(
                jnp.where(bv, by, c["cot_buf"][brc, bm % KC])))

            ph = phase_tab[stage, t]
            m = mb_tab[stage, t]
            ch = chunk_tab[stage, t]
            vstage = ch * S + stage
            v_first = vstage == 0           # feeds from mbs, writes dmbs
            v_last = vstage == S * C - 1    # runs the loss head
            # hoist every gather of a (possibly auto-sharded) global
            # buffer OUT of the switch: gathers/reshards of sep-sharded
            # operands inside a branch either trip the SPMD partitioner
            # CHECK or deadlock at the resharding collective (devices in
            # other branches never arrive)
            mbs_m = _pin(mbs[m])
            labels_m = labels_[m]
            local_c = chunk_params(ch)
            no_f = (_pin(pv(zero_mb)),) + zmsg
            no_b = (_pin(pv(zero_mb)),) + zmsg

            def do_idle(c):
                return c, no_f, no_b

            # NOTE: no _pin inside the branches below — a sharding
            # constraint can lower to a collective(-permute), and a
            # collective inside one switch branch deadlocks the devices
            # that took other branches (same rule as the pvary note
            # above). All pins live outside the switch.
            def do_f(c):
                x = jnp.where(v_first, mbs_m, c["in_buf"][ch, m % K])
                c = dict(c)
                c["in_buf"] = c["in_buf"].at[ch, m % K].set(x)
                y = stage_f(local_c, x)
                # receiver = virtual stage vstage+1, on device
                # (stage+1) % S — chunk increments on the S-1 -> 0 hop
                rc = ch + jnp.where(stage == S - 1, 1, 0)
                fmsg = (y, m, rc, vstage < S * C - 1)
                return c, fmsg, no_b

            def do_b(c):
                x = c["in_buf"][ch, m % K]
                last = v_last
                # ONE stage forward, residuals shared with the backward
                # (ZBH1 keeps the x-only vjp so W can be deferred)
                if schedule.split_w:
                    y, vjp_x = jax.vjp(lambda xx: stage_f(local_c, xx), x)
                else:
                    y, vjp_px = jax.vjp(stage_f, local_c, x)
                # the loss head runs ONLY on the last stage (lax.cond is
                # safe here: with head_v pre-pvary'd no branch contains a
                # collective); elsewhere the cotangent arrived upstream

                def head_branch():
                    loss, vjp = jax.vjp(
                        lambda hp_, y_: head_fn(hp_, y_, labels_m),
                        head_v, y)
                    dhp, dy_ = vjp(pv(jnp.ones((), loss.dtype)))
                    return loss.astype(jnp.float32), dy_, dhp

                def skip_branch():
                    return (pv(jnp.zeros((), jnp.float32)),
                            pv(jnp.zeros_like(y)),
                            jax.tree.map(lambda h: pv(jnp.zeros_like(h)),
                                         head_v))
                loss_l, dy_l, dhp_l = jax.lax.cond(last, head_branch,
                                                   skip_branch)
                dy = jnp.where(last, dy_l, c["cot_buf"][ch, m % KC])
                c = dict(c)
                c["loss"] = c["loss"] + loss_l

                def acc_params(acc, dp):
                    """Accumulate the chunk's param grads (full-slice add
                    for C==1, chunk-row scatter-add for VPP)."""
                    if C == 1:
                        return jax.tree.map(
                            lambda a, g: a + g.astype(jnp.float32),
                            acc, dp)
                    return jax.tree.map(
                        lambda a, g: a.at[ch].set(
                            a[ch] + g.astype(jnp.float32)), acc, dp)

                if schedule.split_w:
                    # ZBH1: dgrad now (critical path), wgrad deferred
                    (dx,) = vjp_x(dy)
                    c["wx_buf"] = c["wx_buf"].at[ch, m % KW].set(x)
                    c["wg_buf"] = c["wg_buf"].at[ch, m % KW].set(dy)
                else:
                    dp, dx = vjp_px(dy)
                    c["accp"] = acc_params(c["accp"], dp)
                c["acch"] = jax.tree.map(
                    lambda a, g: a + g.astype(jnp.float32),
                    c["acch"], dhp_l)
                c["dmbs"] = jax.lax.dynamic_update_index_in_dim(
                    c["dmbs"],
                    jnp.where(v_first, dx, c["dmbs"][m]), m, 0)
                # receiver = vstage-1 on device (stage-1) % S — chunk
                # decrements on the 0 -> S-1 hop
                rc = ch - jnp.where(stage == 0, 1, 0)
                bmsg = (dx, m, rc, vstage > 0)
                return c, no_f, bmsg

            def do_w(c):
                x = c["wx_buf"][ch, m % KW]
                dy = c["wg_buf"][ch, m % KW]
                _, vjp_p = jax.vjp(lambda p: stage_f(p, x), local_c)
                (dp,) = vjp_p(dy)
                c = dict(c)
                if C == 1:
                    c["accp"] = jax.tree.map(
                        lambda a, g: a + g.astype(jnp.float32),
                        c["accp"], dp)
                else:
                    c["accp"] = jax.tree.map(
                        lambda a, g: a.at[ch].set(
                            a[ch] + g.astype(jnp.float32)),
                        c["accp"], dp)
                return c, no_f, no_b

            c, fmsg, bmsg = jax.lax.switch(
                ph, [do_idle, do_f, do_b, do_w], c)
            # 3) rotate messages
            c["fmsg"] = tuple(
                (_pin if i == 0 else (lambda z: z))(
                    jax.lax.ppermute(v_, PP_AXIS, down))
                for i, v_ in enumerate(fmsg))
            c["bmsg"] = tuple(
                (_pin if i == 0 else (lambda z: z))(
                    jax.lax.ppermute(v_, PP_AXIS, up))
                for i, v_ in enumerate(bmsg))
            return c, None

        c, _ = jax.lax.scan(tick, carry0, jnp.arange(T))
        loss = jax.lax.psum(c["loss"], PP_AXIS)
        dmbs = _f32_psum(c["dmbs"])
        acch = jax.tree.map(lambda a: jax.lax.psum(a, PP_AXIS), c["acch"])
        accp = jax.tree.map(lambda a: a[None], c["accp"])  # [1, L/S, ...]
        return loss, accp, acch, dmbs

    param_specs = {k: P(PP_AXIS, *([None] * (v.ndim - 1)))
                   for k, v in stacked_params.items()}
    head_specs = jax.tree.map(lambda v: P(*([None] * jnp.ndim(v))),
                              head_params)
    mb_spec = P(*([None] * microbatches.ndim))
    lab_spec = P(*([None] * labels.ndim))
    extra_specs = tuple(P(*([None] * jnp.ndim(e))) for e in extra_args)

    fn = _pp_shard_map(
        per_device, mesh,
        in_specs=(param_specs, head_specs, mb_spec, lab_spec)
        + extra_specs,
        out_specs=(P(), param_specs, head_specs, mb_spec))

    pdt = {k: v.dtype for k, v in stacked_params.items()}
    hdt = jax.tree.map(lambda v: v.dtype, head_params)

    @jax.custom_vjp
    def run(sp, hp, mbs):
        loss, _, _, _ = jax.jit(fn)(sp, hp, mbs, labels, *extra_args)
        return loss

    def run_fwd(sp, hp, mbs):
        loss, accp, acch, dmbs = jax.jit(fn)(sp, hp, mbs, labels,
                                             *extra_args)
        accp = {k: v.astype(pdt[k]) for k, v in accp.items()}
        acch = jax.tree.map(lambda v, d: v.astype(d), acch, hdt)
        return loss, (accp, acch, dmbs)

    def run_bwd(res, g):
        accp, acch, dmbs = res
        scale = lambda v: (g * v.astype(jnp.float32)).astype(v.dtype)
        return (jax.tree.map(scale, accp), jax.tree.map(scale, acch),
                scale(dmbs))

    run.defvjp(run_fwd, run_bwd)
    from .parallel_layers import suppress_sequence_parallel_annotations
    with suppress_sequence_parallel_annotations():
        return run(stacked_params, head_params, microbatches)
