"""One-command pretrain CLI (VERDICT r4 item 5; ref: PaddleNLP
llm/run_pretrain.py). End-to-end on the 8-device CPU mesh: text corpus ->
in-tree BPE -> DistributedBatchSampler -> dp2 x mp2 x zero2 hybrid step ->
MFU/tok-s jsonl logging -> sharded checkpoint; then SIGKILL mid-run and
verify auto-resume reproduces the uninterrupted run's losses exactly."""

import json
import os
import signal
import subprocess
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _cfg(tmp_path, out_name, max_steps=10):
    corpus = tmp_path / "corpus.txt"
    if not corpus.exists():
        import random
        rng = random.Random(0)
        words = ["the", "quick", "brown", "fox", "jumps", "over", "lazy",
                 "dog", "tensor", "mesh", "shard", "chip", "scale", "train"]
        corpus.write_text(" ".join(rng.choice(words)
                                   for _ in range(20000)))
    cfg = {
        "model": {"preset": "tiny", "num_hidden_layers": 2},
        "data": {"corpus": str(corpus), "vocab_size": 280},
        "seq_len": 64, "global_batch": 8, "max_steps": max_steps,
        "parallel": {"dp": 2, "mp": 2, "sharding": 2},
        "save_interval": 4, "log_interval": 10, "remat": "none",
        "output_dir": str(tmp_path / out_name),
    }
    p = tmp_path / f"{out_name}.json"
    p.write_text(json.dumps(cfg))
    return p, cfg


def _env():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    return env


def _cmd(cfg_path):
    return [sys.executable, "-m", "paddle_tpu.trainer.run_pretrain",
            "--config", str(cfg_path)]


def _losses(out_dir):
    path = os.path.join(out_dir, "losses.jsonl")
    res = {}
    with open(path) as f:
        for line in f:
            rec = json.loads(line)
            res[rec["step"]] = rec["loss"]   # resume re-logs: latest wins
    return res


class TestRunPretrainCLI:
    def test_end_to_end_and_kill_resume_loss_continuity(self, tmp_path):
        env = _env()
        # uninterrupted reference
        cfg_ref, ref_cfg = _cfg(tmp_path, "ref")
        r = subprocess.run(_cmd(cfg_ref), env=env, cwd=REPO,
                           capture_output=True, text=True, timeout=420)
        assert r.returncode == 0, (r.stdout, r.stderr)
        ref = _losses(ref_cfg["output_dir"])
        assert len(ref) == 10 and all(v == v for v in ref.values())
        # BPE vocab was trained and cached; MFU/tok-s logged
        assert os.path.exists(os.path.join(ref_cfg["output_dir"],
                                           "bpe_tokenizer.json"))
        first = json.loads(open(os.path.join(
            ref_cfg["output_dir"], "losses.jsonl")).readline())
        assert "tokens_per_s" in first and "mfu_6N_est" in first

        # killed run: SIGKILL once past step 5 (checkpoint exists at 4).
        # max_steps is far larger than the kill point so the run cannot
        # finish before the monitor catches it even on a fast/loaded host
        cfg_k, k_cfg = _cfg(tmp_path, "killed", max_steps=60)
        p = subprocess.Popen(_cmd(cfg_k), env=env, cwd=REPO,
                             stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
        log = os.path.join(k_cfg["output_dir"], "losses.jsonl")
        deadline = time.time() + 360
        killed = False
        while time.time() < deadline:
            if p.poll() is not None:
                break
            if os.path.exists(log):
                lines = open(log).read().strip().splitlines()
                if lines and json.loads(lines[-1])["step"] >= 5:
                    p.send_signal(signal.SIGKILL)
                    killed = True
                    break
            time.sleep(0.25)
        p.wait()
        assert killed, "run finished before the kill window"
        assert os.path.exists(os.path.join(k_cfg["output_dir"], "latest"))

        # resume with the SAME command: must continue from the checkpoint
        r2 = subprocess.run(_cmd(cfg_k), env=env, cwd=REPO,
                            capture_output=True, text=True, timeout=420)
        assert r2.returncode == 0, (r2.stdout, r2.stderr)
        assert "resumed from ckpt_step" in r2.stdout, r2.stdout
        got = _losses(k_cfg["output_dir"])
        # loss continuity: the killed+resumed lineage reproduces the
        # uninterrupted run's losses at every comparable step (state +
        # data order restored exactly), and the resume actually ran on
        # to completion
        for s in range(1, 11):
            assert got[s] == pytest.approx(ref[s], abs=5e-4), \
                (s, got[s], ref[s])
        assert max(got) == 60 and "done at step 60" in r2.stdout
