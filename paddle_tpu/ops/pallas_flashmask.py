"""FlashMask block-skipping attention kernel (splash-attention class).

Reference capability: FlashMask sparse-mask attention — paddle's
flashmask_attention (python/paddle/nn/functional/flash_attention.py,
FlashMask variant of paddle/phi/kernels/gpu/flash_attn_kernel.cu;
SURVEY §5.7 item 1). The mask is encoded per KEY COLUMN as row-index
bands — O(S) memory — and the kernel must never materialize the dense
[B,H,Sq,Sk] mask. This in-tree Pallas kernel (authored, tunable) does
flash attention with:

  - a per-(q_block, k_block) SKIP map computed from block-level min/max
    of the column bands (+ the causal diagonal): fully-masked and
    above-diagonal blocks cost zero MXU work;
  - the exact elementwise band mask applied inside surviving blocks from
    broadcasted iota vs the column bands (VPU-cheap, block-local — the
    dense mask never exists outside one [bq, bk] tile in VMEM);
  - online-softmax forward emitting logsumexp, and flash-style backward
    kernels (dq sweep over k blocks; dkv sweep over q blocks) reusing
    the same skip map.

Band normal form: every paddle startend encoding reduces to two masked
row bands per column, [s1, e1) ∪ [s2, e2); `allow(i, j) =
(causal -> j <= i) and i not in band1(j) and i not in band2(j)`.

Fully-masked query rows produce 0 output (l == 0 guard; the composite
oracle yields an arbitrary uniform average there — such rows are
don't-care by definition).

Block sizes default to 128x128 and are caller-tunable. Runs in Pallas
interpret mode off-TPU so the same kernel logic is covered by the CPU
test suite.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["flashmask_sdpa", "flashmask_block_kinds", "bands_from_startend"]

# jax renamed TPUCompilerParams -> CompilerParams; accept both
_CompilerParams = getattr(pltpu, "CompilerParams",
                          getattr(pltpu, "TPUCompilerParams", None))

_NEG = -1e30

# B/H/outer-block dims are independent; only the innermost dim carries
# the online-softmax / accumulator state (paddlelint PE501: every
# revisited output axis must be declared). Parallel outer dims let
# Mosaic split them across TensorCores (megacore parts), same as flash.
_CPARAMS = _CompilerParams(
    dimension_semantics=("parallel", "parallel", "parallel", "arbitrary"))


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def bands_from_startend(se, Sq: int, Sk: int, causal: bool):
    """paddle startend_row_indices [B, Hm, Sk, C] -> two masked bands
    (s1, e1, s2, e2), each [B, Hm, Sk] int32."""
    C = se.shape[-1]
    se = se.astype(jnp.int32)
    big = jnp.full(se.shape[:-1], Sq, jnp.int32)
    zero = jnp.zeros(se.shape[:-1], jnp.int32)
    if C == 1:
        if not causal:
            raise ValueError("C=1 FlashMask (LTS) requires causal=True")
        return se[..., 0], big, zero, zero          # [start, Sq)
    if C == 2 and causal:
        return se[..., 0], se[..., 1], zero, zero   # [start, end)
    if C == 2:
        # [LTStart, UTEnd]: lower band [lt_start, Sq), upper band [0, ut)
        return se[..., 0], big, zero, se[..., 1]
    if C == 4:
        if causal:
            raise ValueError("C=4 FlashMask requires causal=False")
        return se[..., 0], se[..., 1], se[..., 2], se[..., 3]
    raise ValueError(f"startend_row_indices last dim must be 1, 2 or 4, "
                     f"got {C}")


def flashmask_block_kinds(bands, Sq: int, Sk: int, bq: int, bk: int,
                          causal: bool):
    """[B, Hm, nq, nk] int32 skip map: 0 = block contributes nothing
    (above the causal diagonal, or every column's bands cover the whole
    row range), 1 = compute. Conservative on mixed blocks (computes)."""
    s1, e1, s2, e2 = bands
    nq, nk = Sq // bq, Sk // bk
    q0 = jnp.arange(nq, dtype=jnp.int32)[:, None] * bq        # [nq,1]
    q1 = q0 + bq
    kb = lambda a, red: red(a.reshape(a.shape[:-1] + (nk, bk)), axis=-1)
    s1x, e1n = kb(s1, jnp.max), kb(e1, jnp.min)               # [B,Hm,nk]
    s2x, e2n = kb(s2, jnp.max), kb(e2, jnp.min)
    full1 = jnp.logical_and(s1x[..., None, :] <= q0,
                            e1n[..., None, :] >= q1)          # [B,Hm,nq,nk]
    full2 = jnp.logical_and(s2x[..., None, :] <= q0,
                            e2n[..., None, :] >= q1)
    masked = jnp.logical_or(full1, full2)
    if causal:
        k0 = jnp.arange(nk, dtype=jnp.int32)[None, :] * bk
        above = q1 <= k0                                      # [nq,nk]
        masked = jnp.logical_or(masked, above)
    return jnp.logical_not(masked).astype(jnp.int32)


def _fwd_kernel(kind_ref, s1_ref, e1_ref, s2_ref, e2_ref,
                q_ref, k_ref, v_ref, o_ref, lse_ref,
                acc_ref, m_ref, l_ref, *, scale, bq, bk, causal):
    kj = pl.program_id(3)
    nk = pl.num_programs(3)
    qi = pl.program_id(2)

    @pl.when(kj == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG)
        l_ref[:] = jnp.zeros_like(l_ref)

    @pl.when(kind_ref[0, 0, qi, kj] > 0)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)                   # [bq, D]
        k = k_ref[0, 0].astype(jnp.float32)                   # [bk, D]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale       # [bq, bk]
        rows = qi * bq + jax.lax.broadcasted_iota(
            jnp.int32, (bq, bk), 0)
        band = lambda lo, hi: jnp.logical_and(
            rows >= lo[0, 0][None, :], rows < hi[0, 0][None, :])
        masked = jnp.logical_or(band(s1_ref, e1_ref),
                                band(s2_ref, e2_ref))
        if causal:
            cols = kj * bk + jax.lax.broadcasted_iota(
                jnp.int32, (bq, bk), 1)
            masked = jnp.logical_or(masked, cols > rows)
        s = jnp.where(masked, _NEG, s)
        m_prev = m_ref[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, -1, keepdims=True))
        # exp(_NEG - m) underflows to exactly 0, so fully-masked entries
        # never pollute l; m_new stays at _NEG only when nothing is
        # visible yet, and alpha = exp(0) = 1 keeps that stable
        p = jnp.exp(s - m_new)
        p = jnp.where(masked, 0.0, p)
        alpha = jnp.exp(m_prev - m_new)
        l_ref[:] = l_ref[:] * alpha + jnp.sum(p, -1, keepdims=True)
        v = v_ref[0, 0].astype(jnp.float32)
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[:] = m_new

    @pl.when(kj == nk - 1)
    def _emit():
        l = l_ref[:]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_ref[:] / l_safe).astype(o_ref.dtype)
        # +1e30 sentinel for empty rows: bwd's exp(s - lse) then
        # underflows to 0 instead of exploding on a -inf lse
        lse_ref[0, 0] = jnp.where(
            l == 0.0, -_NEG, m_ref[:] + jnp.log(l_safe))


def _bwd_dq_kernel(kind_ref, s1_ref, e1_ref, s2_ref, e2_ref,
                   q_ref, k_ref, v_ref, do_ref, lse_ref, di_ref, dq_ref,
                   dq_acc, *, scale, bq, bk, causal):
    kj = pl.program_id(3)
    nk = pl.num_programs(3)
    qi = pl.program_id(2)

    @pl.when(kj == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    @pl.when(kind_ref[0, 0, qi, kj] > 0)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)
        k = k_ref[0, 0].astype(jnp.float32)
        v = v_ref[0, 0].astype(jnp.float32)
        do = do_ref[0, 0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        rows = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
        band = lambda lo, hi: jnp.logical_and(
            rows >= lo[0, 0][None, :], rows < hi[0, 0][None, :])
        masked = jnp.logical_or(band(s1_ref, e1_ref),
                                band(s2_ref, e2_ref))
        if causal:
            cols = kj * bk + jax.lax.broadcasted_iota(
                jnp.int32, (bq, bk), 1)
            masked = jnp.logical_or(masked, cols > rows)
        p = jnp.exp(s - lse_ref[0, 0])
        p = jnp.where(masked, 0.0, p)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - di_ref[0, 0]) * scale
        dq_acc[:] = dq_acc[:] + jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(kj == nk - 1)
    def _emit():
        dq_ref[0, 0] = dq_acc[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(kind_ref, s1_ref, e1_ref, s2_ref, e2_ref,
                    q_ref, k_ref, v_ref, do_ref, lse_ref, di_ref,
                    dk_ref, dv_ref, dk_acc, dv_acc, *, scale, bq, bk,
                    causal):
    qi = pl.program_id(3)
    nq = pl.num_programs(3)
    kj = pl.program_id(2)

    @pl.when(qi == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    @pl.when(kind_ref[0, 0, qi, kj] > 0)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)
        k = k_ref[0, 0].astype(jnp.float32)
        v = v_ref[0, 0].astype(jnp.float32)
        do = do_ref[0, 0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale       # [bq, bk]
        rows = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
        band = lambda lo, hi: jnp.logical_and(
            rows >= lo[0, 0][None, :], rows < hi[0, 0][None, :])
        masked = jnp.logical_or(band(s1_ref, e1_ref),
                                band(s2_ref, e2_ref))
        if causal:
            cols = kj * bk + jax.lax.broadcasted_iota(
                jnp.int32, (bq, bk), 1)
            masked = jnp.logical_or(masked, cols > rows)
        p = jnp.exp(s - lse_ref[0, 0])
        p = jnp.where(masked, 0.0, p)
        dv_acc[:] = dv_acc[:] + jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)               # [bk, D]
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)               # [bq, bk]
        ds = p * (dp - di_ref[0, 0]) * scale
        dk_acc[:] = dk_acc[:] + jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)               # [bk, D]

    @pl.when(qi == nq - 1)
    def _emit():
        dk_ref[0, 0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc[:].astype(dv_ref.dtype)


def _specs(B, H, Hm, Sq, Sk, D, bq, bk, order: str):
    """Common in_specs for (kind, s1, e1, s2, e2, q, k, v). order='qk':
    grid (B, H, nq, nk); order='kq': grid (B, H, nk, nq)."""
    nq, nk = Sq // bq, Sk // bk
    hm = (lambda h: h) if Hm > 1 else (lambda h: 0)
    if order == "qk":
        semap = lambda b, h, i, j: (b, hm(h), j)
        qmap = lambda b, h, i, j: (b, h, i, 0)
        kmap = lambda b, h, i, j: (b, h, j, 0)
    else:
        semap = lambda b, h, i, j: (b, hm(h), i)
        qmap = lambda b, h, i, j: (b, h, j, 0)
        kmap = lambda b, h, i, j: (b, h, i, 0)
    se_spec = pl.BlockSpec((1, 1, bk), semap)
    # the skip map is control flow: scalars belong in SMEM. The block
    # keeps the full trailing [nq, nk] table (TPU requires trailing
    # block dims to equal the array dims unless (8,128)-divisible);
    # kernels index it [0, 0, qi, kj] directly.
    kind_spec = pl.BlockSpec((1, 1, nq, nk),
                             lambda b, h, i, j: (b, hm(h), 0, 0),
                             memory_space=pltpu.SMEM)
    return ([kind_spec] + [se_spec] * 4 +
            [pl.BlockSpec((1, 1, bq, D), qmap),
             pl.BlockSpec((1, 1, bk, D), kmap),
             pl.BlockSpec((1, 1, bk, D), kmap)], qmap, kmap)


@functools.partial(jax.custom_vjp, nondiff_argnums=(7, 8, 9, 10))
def _flashmask_core(q, k, v, s1, e1, s2, e2, scale, causal, bq, bk):
    o, _ = _flashmask_fwd_impl(q, k, v, s1, e1, s2, e2, scale, causal,
                               bq, bk)
    return o


def _flashmask_fwd_impl(q, k, v, s1, e1, s2, e2, scale, causal, bq, bk):
    B, H, Sq, D = q.shape
    Sk = k.shape[2]
    Hm = s1.shape[1]
    kinds = flashmask_block_kinds((s1, e1, s2, e2), Sq, Sk, bq, bk,
                                  causal)
    nq, nk = Sq // bq, Sk // bk
    in_specs, qmap, _ = _specs(B, H, Hm, Sq, Sk, D, bq, bk, "qk")
    o, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, scale=scale, bq=bq, bk=bk,
                          causal=causal),
        grid=(B, H, nq, nk),
        in_specs=in_specs,
        out_specs=[pl.BlockSpec((1, 1, bq, D), qmap),
                   pl.BlockSpec((1, 1, bq, 1),
                                lambda b, h, i, j: (b, h, i, 0))],
        out_shape=[jax.ShapeDtypeStruct((B, H, Sq, D), q.dtype),
                   jax.ShapeDtypeStruct((B, H, Sq, 1), jnp.float32)],
        # acc/m/l persist across the sequential innermost (nk) grid dim
        scratch_shapes=[pltpu.VMEM((bq, D), jnp.float32),
                        pltpu.VMEM((bq, 1), jnp.float32),
                        pltpu.VMEM((bq, 1), jnp.float32)],
        compiler_params=_CPARAMS,
        interpret=_interpret(),
    )(kinds, s1, e1, s2, e2, q, k, v)
    return o, (lse, kinds)


def _flashmask_vjp_fwd(q, k, v, s1, e1, s2, e2, scale, causal, bq, bk):
    o, (lse, kinds) = _flashmask_fwd_impl(q, k, v, s1, e1, s2, e2, scale,
                                          causal, bq, bk)
    return o, (q, k, v, s1, e1, s2, e2, o, lse, kinds)


def _flashmask_vjp_bwd(scale, causal, bq, bk, res, do):
    q, k, v, s1, e1, s2, e2, o, lse, kinds = res
    B, H, Sq, D = q.shape
    Sk = k.shape[2]
    Hm = s1.shape[1]
    di = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                 axis=-1, keepdims=True)                     # [B,H,Sq,1]
    nq, nk = Sq // bq, Sk // bk

    in_specs, qmap, kmap = _specs(B, H, Hm, Sq, Sk, D, bq, bk, "qk")
    row_spec = pl.BlockSpec((1, 1, bq, 1),
                            lambda b, h, i, j: (b, h, i, 0))
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, bq=bq, bk=bk,
                          causal=causal),
        grid=(B, H, nq, nk),
        in_specs=in_specs + [pl.BlockSpec((1, 1, bq, D), qmap),
                             row_spec, row_spec],
        out_specs=pl.BlockSpec((1, 1, bq, D), qmap),
        out_shape=jax.ShapeDtypeStruct((B, H, Sq, D), q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, D), jnp.float32)],
        compiler_params=_CPARAMS,
        interpret=_interpret(),
    )(kinds, s1, e1, s2, e2, q, k, v, do, lse, di)

    in_specs2, qmap2, kmap2 = _specs(B, H, Hm, Sq, Sk, D, bq, bk, "kq")
    row_spec2 = pl.BlockSpec((1, 1, bq, 1),
                             lambda b, h, i, j: (b, h, j, 0))
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, bq=bq, bk=bk,
                          causal=causal),
        grid=(B, H, nk, nq),
        in_specs=in_specs2 + [pl.BlockSpec((1, 1, bq, D), qmap2),
                              row_spec2, row_spec2],
        out_specs=[pl.BlockSpec((1, 1, bk, D), kmap2),
                   pl.BlockSpec((1, 1, bk, D), kmap2)],
        out_shape=[jax.ShapeDtypeStruct((B, H, Sk, D), k.dtype),
                   jax.ShapeDtypeStruct((B, H, Sk, D), v.dtype)],
        scratch_shapes=[pltpu.VMEM((bk, D), jnp.float32),
                        pltpu.VMEM((bk, D), jnp.float32)],
        compiler_params=_CPARAMS,
        interpret=_interpret(),
    )(kinds, s1, e1, s2, e2, q, k, v, do, lse, di)
    return dq, dk, dv, None, None, None, None


_flashmask_core.defvjp(_flashmask_vjp_fwd, _flashmask_vjp_bwd)


def flashmask_sdpa(q, k, v, startend_row_indices, causal: bool = True,
                   scale=None, block_q: int = 128, block_k: int = 128):
    """[B,S,H,D] FlashMask attention through the block-skipping kernel.
    startend_row_indices [B, Hm, Sk, C], C in {1,2,4} (paddle encoding).
    Returns [B,Sq,H,D]; differentiable (flash-style bwd kernels)."""
    B, Sq, H, D = q.shape
    Sk = k.shape[1]
    if scale is None:
        scale = D ** -0.5
    bands = bands_from_startend(startend_row_indices, Sq, Sk, causal)
    qh = jnp.swapaxes(q, 1, 2)
    kh = jnp.swapaxes(k, 1, 2)
    vh = jnp.swapaxes(v, 1, 2)
    out = _flashmask_core(qh, kh, vh, *bands, float(scale), bool(causal),
                          block_q, block_k)
    return jnp.swapaxes(out, 1, 2)


def flashmask_kernel_eligible(Sq: int, Sk: int, D: int,
                              block_q: int = 128,
                              block_k: int = 128) -> bool:
    return (Sq % block_q == 0 and Sk % block_k == 0
            and (D % 128 == 0 or (D <= 128 and D % 64 == 0)))


# certification (ROADMAP item 5 / paddlelint PK105): the dense-mask
# composite is the oracle; lazy string — flash_attention imports us
from .oracles import register_oracle  # noqa: E402

register_oracle(
    "flashmask_sdpa", kernel=flashmask_sdpa,
    reference="paddle_tpu.ops.flash_attention:sdpa_reference",
    parity_test="tests/test_flashmask_kernel.py::"
                "test_kernel_matches_dense_oracle")
