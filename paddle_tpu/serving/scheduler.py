"""In-flight (continuous-batching) request scheduler.

Pure host-side state machine — the engine (engine.py) owns the device
work and drives this scheduler once per `step()`:

  - FCFS admission into a FIXED number of decode slots (the jitted
    decode step has a static batch dimension; joining or leaving a slot
    never retraces it — paddlelint PT002);
  - admission backpressure reusing `inference.Config.set_admission`
    semantics: `max_inflight` bounds admitted requests, and with
    `queue_timeout_s == 0` a submit that cannot be admitted is refused
    with `resilience.Overloaded` at the door (the Predictor's
    non-blocking gate); with a positive timeout requests may queue and
    are expired with an `Overloaded` result once they wait longer;
  - per-request deadlines (`inference.Config.set_deadline` or
    `Request(deadline_s=...)`) produce falsy `resilience.TimeoutResult`
    partial results, never hangs;
  - head-of-line order is never bypassed (no skip-ahead admission), so
    a seeded request trace schedules deterministically.
"""

from __future__ import annotations

import itertools
import time
from collections import deque
from typing import List, Optional, Tuple

import numpy as np

from .. import resilience as _res
from ..observability import tracing as _tracing

_TRACE = _tracing.recorder()

__all__ = ["Request", "Scheduler",
           "WAITING", "PREFILL", "DECODE", "FINISHED"]

WAITING = "waiting"
PREFILL = "prefill"
DECODE = "decode"
FINISHED = "finished"

_ids = itertools.count()


class Request:
    """One generation request. `tokens` accumulates greedy output ids;
    after FINISHED, `result` is an int32 array padded to max_new_tokens
    with pad_token_id (the generate_cached row convention), a falsy
    `resilience.TimeoutResult` carrying the partial tokens on a deadline
    miss, or a `resilience.Overloaded` instance if the request timed out
    of the admission queue."""

    def __init__(self, prompt, max_new_tokens: int,
                 eos_token_id: Optional[int] = None,
                 pad_token_id: int = 0,
                 deadline_s: Optional[float] = None,
                 request_id=None):
        self.prompt = np.asarray(prompt, np.int32).reshape(-1)
        if self.prompt.size < 1:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        self.max_new_tokens = int(max_new_tokens)
        self.eos_token_id = eos_token_id
        self.pad_token_id = int(pad_token_id)
        self.deadline_s = deadline_s
        self.request_id = request_id if request_id is not None \
            else next(_ids)
        self.state = WAITING
        self.slot: Optional[int] = None
        self.tokens: List[int] = []
        self.result = None
        self.pending: Optional[int] = None   # last sampled, not yet fed
        self.prefill_pos = 0                 # prompt tokens in cache
        self.shared_tokens = 0               # prefix tokens riding a donor
        self._deadline: Optional[_res.Deadline] = None
        self._enqueued_at: Optional[float] = None

    @property
    def total_tokens(self) -> int:
        return int(self.prompt.size) + self.max_new_tokens

    def start_deadline(self) -> None:
        if self.deadline_s:
            self._deadline = _res.Deadline(self.deadline_s)

    def deadline_expired(self) -> bool:
        return self._deadline is not None and self._deadline.expired()

    def finalize(self) -> None:
        """Pad tokens to max_new_tokens (generate_cached row shape)."""
        out = np.full(self.max_new_tokens, self.pad_token_id, np.int32)
        out[:len(self.tokens)] = self.tokens
        if self._deadline is not None and self._deadline.expired():
            _res.deadline_miss()
            self.result = _res.TimeoutResult(
                kind="serving_engine", budget_s=self._deadline.budget_s,
                elapsed_s=self._deadline.elapsed_s,
                completed=len(self.tokens), partial=out)
        else:
            self.result = out

    def __repr__(self):
        return (f"Request(id={self.request_id}, state={self.state}, "
                f"prompt={self.prompt.size}, out={len(self.tokens)}/"
                f"{self.max_new_tokens})")


class Scheduler:
    """FCFS continuous-batching scheduler over `max_slots` decode slots."""

    def __init__(self, max_slots: int, max_inflight: Optional[int] = None,
                 queue_timeout_s: float = 0.0):
        if max_slots < 1:
            raise ValueError("max_slots must be >= 1")
        self.max_slots = int(max_slots)
        self.max_inflight = min(int(max_inflight), self.max_slots) \
            if max_inflight else self.max_slots
        self.backpressure = max_inflight is not None
        self.queue_timeout_s = float(queue_timeout_s)
        self.waiting: deque = deque()
        self.slots: List[Optional[Request]] = [None] * self.max_slots
        self.finished: List[Request] = []

    # ------------------------------------------------------------- queries
    @property
    def inflight(self) -> int:
        return sum(r is not None for r in self.slots)

    def active(self, state: Optional[str] = None):
        """(slot, request) pairs, optionally filtered by state."""
        return [(i, r) for i, r in enumerate(self.slots)
                if r is not None and (state is None or r.state == state)]

    def has_work(self) -> bool:
        return bool(self.waiting) or self.inflight > 0

    # ----------------------------------------------------------- lifecycle
    def submit(self, req: Request) -> Request:
        """Enqueue FCFS. With backpressure and queue_timeout_s == 0, a
        request that cannot be admitted right now is refused with
        `Overloaded` (the Predictor's non-blocking admission gate)."""
        if self.backpressure and self.queue_timeout_s <= 0 \
                and self.inflight + len(self.waiting) >= self.max_inflight:
            # refused requests still get a (one-event) timeline so the
            # trace shows WHY they never produced tokens
            _TRACE.begin(req.request_id,
                         prompt_len=int(req.prompt.size),
                         max_new_tokens=req.max_new_tokens)
            _TRACE.stamp(req.request_id, "enqueue")
            _TRACE.finish(req.request_id, "refused",
                          inflight=self.max_inflight)
            raise _res.Overloaded(
                f"admission gate full ({self.max_inflight} inflight)")
        req.state = WAITING
        req._enqueued_at = time.monotonic()
        req.start_deadline()
        self.waiting.append(req)
        _TRACE.begin(req.request_id, prompt_len=int(req.prompt.size),
                     max_new_tokens=req.max_new_tokens)
        _TRACE.stamp(req.request_id, "enqueue")
        return req

    def expire_waiting(self) -> List[Request]:
        """Cull queued requests past the admission timeout (and queued
        requests whose own deadline already expired): they finish with
        an Overloaded / TimeoutResult result without touching a slot."""
        expired = []
        keep = deque()
        now = time.monotonic()
        for req in self.waiting:
            timed_out = (self.backpressure and self.queue_timeout_s > 0
                         and now - req._enqueued_at > self.queue_timeout_s)
            if timed_out:
                req.state = FINISHED
                req.result = _res.Overloaded(
                    f"request {req.request_id} waited "
                    f"{now - req._enqueued_at:.3f}s > queue_timeout_s="
                    f"{self.queue_timeout_s}")
                expired.append(req)
                _TRACE.finish(req.request_id, "overloaded",
                              waited_s=now - req._enqueued_at)
            elif req.deadline_expired():
                req.state = FINISHED
                req.finalize()
                expired.append(req)
                _TRACE.finish(req.request_id, "timeout", where="queue")
            else:
                keep.append(req)
        self.waiting = keep
        self.finished.extend(expired)
        return expired

    def next_admittable(self) -> Optional[Request]:
        """Head-of-line request if a slot and an inflight credit are
        free; None otherwise. FCFS: nothing behind the head ever jumps
        it (deterministic under a seeded trace)."""
        if not self.waiting or self.inflight >= self.max_inflight \
                or all(r is not None for r in self.slots):
            return None
        return self.waiting[0]

    def admit(self, req: Request) -> int:
        """Bind the head-of-line request to the lowest free slot."""
        assert self.waiting and self.waiting[0] is req, \
            "admit() must take the head of the FCFS queue"
        slot = next(i for i, r in enumerate(self.slots) if r is None)
        self.waiting.popleft()
        req.state = PREFILL
        req.slot = slot
        self.slots[slot] = req
        _TRACE.stamp(req.request_id, "admit", slot=slot)
        return slot

    def release(self, req: Request) -> None:
        """Free the slot the instant a request finishes — the next
        step() can admit into it (no drain barrier)."""
        if req.slot is not None:
            self.slots[req.slot] = None
            req.slot = None
        req.state = FINISHED
        self.finished.append(req)

    def drain_finished(self) -> List[Request]:
        done, self.finished = self.finished, []
        return done
