"""paddle.incubate.nn.functional parity surface (ref:
python/paddle/incubate/nn/functional/ — SURVEY §2.2 incubate row).

Each name maps onto the Pallas/XLA fused op set in paddle_tpu.ops; Tensor
wrappers go through core.dispatch so autograd/jit see them as single ops.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.dispatch import apply
from ...core.tensor import Tensor
from ...ops.fused import (fused_layer_norm as _ln, fused_rms_norm as _rms,
                          fused_rope as _rope, swiglu as _swiglu)
from ...ops.quant import (weight_only_linear as _wol,
                          weight_quantize as _wq)
from ...ops.paged_attention import paged_attention as _paged

__all__ = ["fused_rms_norm", "fused_layer_norm",
           "fused_rotary_position_embedding", "swiglu",
           "weight_only_linear", "weight_quantize",
           "block_multihead_attention", "fused_linear"]


def _arr(x):
    return x._data if isinstance(x, Tensor) else jnp.asarray(x)


def fused_rms_norm(x, norm_weight, norm_bias=None, epsilon=1e-6,
                   begin_norm_axis=-1):
    def impl(xa, w):
        out = _rms(xa, w, eps=epsilon)
        if norm_bias is not None:
            out = out + _arr(norm_bias)
        return out
    return apply("fused_rms_norm", impl, [x, norm_weight])


def fused_layer_norm(x, norm_weight, norm_bias, epsilon=1e-5,
                     begin_norm_axis=-1):
    def impl(xa, w, b):
        return _ln(xa, w, b, eps=epsilon)
    return apply("fused_layer_norm", impl, [x, norm_weight, norm_bias])


def fused_rotary_position_embedding(q, k, v=None, sin=None, cos=None,
                                    position_ids=None, use_neox_rotary_style=True):
    """ref signature: returns (q, k, v) rotated. cos/sin: [S, D/2] (or
    [S, D] paddle-style — halved here)."""
    ca, sa = _arr(cos), _arr(sin)
    if ca.shape[-1] == _arr(q).shape[-1]:
        ca, sa = ca[..., ::2], sa[..., ::2]

    def impl(qa, ka):
        return _rope(qa, ka, ca, sa)
    qo, ko = apply("fused_rope", impl, [q, k])
    return (qo, ko, v) if v is not None else (qo, ko, None)


def swiglu(x, y=None):
    if y is None:
        return apply("swiglu", lambda a: _swiglu(a), [x])
    return apply("swiglu", lambda a, b: _swiglu(a, b), [x, y])


def weight_quantize(x, algo: str = "weight_only_int8"):
    qw, scale = _wq(_arr(x), algo)
    return Tensor(qw), Tensor(scale)


def weight_only_linear(x, weight, bias=None, weight_scale=None,
                       weight_dtype: str = "int8", arch=None):
    algo = ("weight_only_int4" if "int4" in str(weight_dtype)
            else "weight_only_int8")
    qw, sc = _arr(weight), _arr(weight_scale)
    ba = None if bias is None else _arr(bias)

    def impl(xa):
        return _wol(xa, qw, sc, bias=ba, algo=algo)
    return apply("weight_only_linear", impl, [x])


def block_multihead_attention(q, k_pages, v_pages, seq_lens, block_tables,
                              **kw):
    """ref: block_multihead_attention — paged KV-cache decode attention."""
    kp, vp = _arr(k_pages), _arr(v_pages)
    ln, bt = _arr(seq_lens), _arr(block_tables)

    def impl(qa):
        return _paged(qa, kp, vp, ln, bt)
    return apply("block_multihead_attention", impl, [q])


def fused_linear(x, weight, bias=None, transpose_weight=False):
    def impl(xa, wa, *rest):
        w = wa.T if transpose_weight else wa
        out = xa @ w
        if rest:
            out = out + rest[0]
        return out
    ins = [x, weight] + ([bias] if bias is not None else [])
    return apply("fused_linear", impl, ins)


def fused_bias_dropout_residual_layer_norm(
        x, residual, bias=None, ln_scale=None, ln_bias=None,
        dropout_rate=0.5, ln_epsilon=1e-5, training=True,
        mode="upscale_in_train", name=None):
    """(x + bias) -> dropout -> + residual -> layer_norm, one fused region
    (ref: FusedBiasDropoutResidualLnKernel,
    paddle/phi/kernels/fusion/gpu/fused_bias_dropout_residual_layer_norm*;
    on TPU the chain is a single XLA fusion). Normalization always runs;
    ln_scale/ln_bias are the optional affine params."""
    from ...nn import functional as F

    h = x if bias is None else x + (bias if isinstance(bias, Tensor)
                                    else Tensor(jnp.asarray(bias)))
    if dropout_rate:
        # F.dropout owns the mode semantics incl. downscale_in_infer's
        # eval-time (1-p) scaling — never bypass it on training=False
        h = F.dropout(h, p=dropout_rate, training=training, mode=mode)
    h = h + residual
    return F.layer_norm(h, h.shape[-1:], weight=ln_scale, bias=ln_bias,
                        epsilon=ln_epsilon)


def fused_feedforward(x, linear1_weight, linear2_weight, linear1_bias=None,
                      linear2_bias=None, ln1_scale=None, ln1_bias=None,
                      ln2_scale=None, ln2_bias=None, dropout1_rate=0.5,
                      dropout2_rate=0.5, activation="relu",
                      ln1_epsilon=1e-5, ln2_epsilon=1e-5,
                      pre_layer_norm=False, training=True, name=None):
    """Transformer FFN block as one fused region (ref: FusedFeedForward
    kernel, paddle/phi/kernels/fusion/gpu/fused_feedforward_kernel.cu):
    residual + (pre/post) layer_norm + linear-act-dropout-linear-dropout.
    The layer norm at the active position always runs (affine optional)."""
    from ...nn import functional as F

    residual = x
    h = x
    if pre_layer_norm:
        h = F.layer_norm(h, h.shape[-1:], weight=ln1_scale, bias=ln1_bias,
                         epsilon=ln1_epsilon)
    h = F.linear(h, linear1_weight, linear1_bias)
    h = getattr(F, activation)(h)
    if dropout1_rate:
        h = F.dropout(h, p=dropout1_rate, training=training)
    h = F.linear(h, linear2_weight, linear2_bias)
    if dropout2_rate:
        h = F.dropout(h, p=dropout2_rate, training=training)
    out = residual + h
    if not pre_layer_norm:
        out = F.layer_norm(out, out.shape[-1:], weight=ln2_scale,
                           bias=ln2_bias, epsilon=ln2_epsilon)
    return out


def fused_multi_head_attention(x, qkv_weight, linear_weight,
                               pre_layer_norm=False, pre_ln_scale=None,
                               pre_ln_bias=None, ln_scale=None, ln_bias=None,
                               pre_ln_epsilon=1e-5, qkv_bias=None,
                               linear_bias=None, cache_kv=None,
                               attn_mask=None, dropout_rate=0.5,
                               attn_dropout_rate=0.5, ln_epsilon=1e-5,
                               training=True, num_heads=None, name=None):
    """Full MHA block as one fused region (ref: FusedAttentionKernel,
    paddle/phi/kernels/fusion/gpu/fused_attention_kernel.cu):
    [pre-LN] -> packed qkv proj -> SDPA (flash-routable) -> out proj ->
    dropout -> +residual -> [post-LN].

    qkv_weight: paddle layout [3, num_heads, head_dim, embed_dim].
    With cache_kv ([2, B, H, cache_len, D]) the new keys/values are
    appended and (out, new_cache_kv) is returned (decode semantics).
    """
    from ...nn import functional as F

    residual = x
    h = x
    if pre_layer_norm:
        h = F.layer_norm(h, h.shape[-1:], weight=pre_ln_scale,
                         bias=pre_ln_bias, epsilon=pre_ln_epsilon)

    three, H, D, E = (qkv_weight._data if isinstance(qkv_weight, Tensor)
                      else jnp.asarray(qkv_weight)).shape
    B, S, _ = h.shape
    mask_arr = _arr(attn_mask) if attn_mask is not None else None
    has_bias = qkv_bias is not None
    has_cache = cache_kv is not None

    def impl(hh, wq, *rest):
        rest = list(rest)
        w = wq.reshape(3 * H * D, E).T  # [E, 3*H*D]
        qkv = hh @ w
        if has_bias:
            qkv = qkv + rest.pop(0).reshape(-1)
        qkv = qkv.reshape(B, S, 3, H, D)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        new_cache = None
        if has_cache:
            # append along the cache sequence dim: [2,B,H,L,D] -> L+S;
            # the cache enters through apply() so grads flow into it
            cache = rest.pop(0)
            kc = jnp.concatenate([cache[0], jnp.swapaxes(k, 1, 2)], axis=2)
            vc = jnp.concatenate([cache[1], jnp.swapaxes(v, 1, 2)], axis=2)
            new_cache = jnp.stack([kc, vc])
            k = jnp.swapaxes(kc, 1, 2)
            v = jnp.swapaxes(vc, 1, 2)
        from ...ops.flash_attention import sdpa
        o = sdpa(q, k, v, mask=mask_arr,
                 dropout_p=attn_dropout_rate if training else 0.0)
        o = o.reshape(B, S, H * D)
        return o if new_cache is None else (o, new_cache)
    ins = [h, qkv_weight] + ([qkv_bias] if has_bias else []) \
        + ([cache_kv] if has_cache else [])
    res = apply("fused_multi_head_attention", impl, ins)
    if has_cache:
        o, new_cache = res
    else:
        o, new_cache = res, None
    out = F.linear(o, linear_weight, linear_bias)
    if dropout_rate:
        out = F.dropout(out, p=dropout_rate, training=training)
    out = residual + out
    if not pre_layer_norm:
        out = F.layer_norm(out, out.shape[-1:], weight=ln_scale,
                           bias=ln_bias, epsilon=ln_epsilon)
    return out if new_cache is None else (out, new_cache)


def masked_multihead_attention(x, cache_kv, src_mask=None, bias=None,
                               sequence_lengths=None, rotary_tensor=None,
                               beam_cache_offset=None, seq_len=1,
                               rotary_emb_dims=0, use_neox_rotary_style=False,
                               name=None):
    """Single-token decode attention over an in-place KV cache (ref:
    MaskedMultiheadAttentionKernel, paddle/phi/kernels/fusion/gpu/
    masked_multihead_attention_kernel.cu — the generation-loop kernel).

    x: [B, 3*H*D] packed qkv for the CURRENT step (bias added if given).
    cache_kv: [2, B, H, max_seq, D]; returns (out, new_cache_kv) with the
    step written at `sequence_lengths` (or seq_len-1). src_mask
    ([B, 1, 1, max_seq] additive) masks cached positions. Rotary/beam
    features are not implemented — passing them raises rather than
    silently computing unrotated attention."""
    if rotary_tensor is not None or rotary_emb_dims:
        raise NotImplementedError(
            "masked_multihead_attention: rotary embedding inside the kernel "
            "is not implemented — apply rope to q/k before packing x")
    if beam_cache_offset is not None:
        raise NotImplementedError(
            "masked_multihead_attention: beam_cache_offset not implemented")

    ck = _arr(cache_kv)
    _, B, H, MS, D = ck.shape
    mask_arr = _arr(src_mask) if src_mask is not None else None
    bias_arr = _arr(bias) if bias is not None else None

    def impl(xx, cache):
        if bias_arr is not None:
            xx = xx + bias_arr.reshape(-1)
        qkv = xx.reshape(B, 3, H, D)
        q, k, v = qkv[:, 0], qkv[:, 1], qkv[:, 2]  # [B,H,D]
        if sequence_lengths is not None:
            pos = _arr(sequence_lengths).reshape(B)
        else:
            pos = jnp.full((B,), seq_len - 1, jnp.int32)
        bidx = jnp.arange(B)
        cache = cache.at[0, bidx, :, pos].set(k)
        cache = cache.at[1, bidx, :, pos].set(v)
        keys, vals = cache[0], cache[1]          # [B,H,MS,D]
        logits = jnp.einsum("bhd,bhsd->bhs", q, keys) * (D ** -0.5)
        valid = jnp.arange(MS)[None, None, :] <= pos[:, None, None]
        logits = jnp.where(valid, logits, -1e30)
        if mask_arr is not None:
            logits = logits + mask_arr.reshape(B, 1, MS)
        p = jax.nn.softmax(logits.astype(jnp.float32), -1).astype(q.dtype)
        o = jnp.einsum("bhs,bhsd->bhd", p, vals)
        return o.reshape(B, H * D), cache
    out, new_cache = apply("masked_multihead_attention", impl,
                           [x, cache_kv])
    return out, new_cache


__all__ += ["fused_bias_dropout_residual_layer_norm", "fused_feedforward",
            "fused_multi_head_attention", "masked_multihead_attention"]
