"""Timetable EXECUTOR for 1F1B/ZBH1/FThenB (distributed/pp_exec.py) —
loss/grad parity vs plain sequential autodiff, plus the memory-bound
claims (ref: fleet/meta_parallel/pipeline_parallel.py 1F1B runtime,
pipeline_scheduler_pass.py ZBH1; VERDICT r1 item 2)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.distributed.mesh import build_hybrid_mesh
from paddle_tpu.distributed.pp_exec import (schedule_buffer_bounds,
                                            scheduled_pipeline_loss)
from paddle_tpu.distributed.pp_schedule import (fthenb_schedule,
                                                one_f_one_b_schedule,
                                                zbh1_schedule)

S, LS, H, C = 4, 2, 8, 5   # stages, layers/stage, width, classes
M, MB = 6, 3               # microbatches, microbatch size


def _setup(seed=0):
    rng = np.random.RandomState(seed)
    stacked = {
        "w": jnp.asarray(rng.standard_normal((S, LS, H, H)) * 0.3,
                         jnp.float32),
        "b": jnp.asarray(rng.standard_normal((S, LS, H)) * 0.1,
                         jnp.float32),
    }
    head = {"wout": jnp.asarray(rng.standard_normal((H, C)) * 0.3,
                                jnp.float32)}
    mbs = jnp.asarray(rng.standard_normal((M, MB, H)), jnp.float32)
    labels = jnp.asarray(rng.randint(0, C, (M, MB)), jnp.int32)
    return stacked, head, mbs, labels


def stage_fn(local, x):
    def body(h, lp):
        return jnp.tanh(h @ lp[0] + lp[1]), None
    h, _ = jax.lax.scan(body, x, (local["w"], local["b"]))
    return h


def head_fn(hp, y, lab):
    logits = y @ hp["wout"]
    lse = jax.scipy.special.logsumexp(logits, axis=-1)
    picked = jnp.take_along_axis(logits, lab[..., None], axis=-1)[..., 0]
    return (lse - picked).sum()


def ref_loss(stacked, head, mbs, labels):
    total = 0.0
    for m in range(M):
        x = mbs[m]
        for s in range(S):
            x = stage_fn({"w": stacked["w"][s], "b": stacked["b"][s]}, x)
        total = total + head_fn(head, x, labels[m])
    return total


@pytest.fixture(scope="module")
def mesh():
    return build_hybrid_mesh(pp_degree=S, devices=jax.devices()[:S])


SCHEDULES = {
    "1F1B": lambda: one_f_one_b_schedule(S, M),
    "ZBH1": lambda: zbh1_schedule(S, M),
    "FThenB": lambda: fthenb_schedule(S, M),
}


@pytest.mark.parametrize("name", list(SCHEDULES))
def test_executor_matches_sequential_autodiff(mesh, name):
    schedule = SCHEDULES[name]()
    schedule.validate()
    stacked, head, mbs, labels = _setup()

    ref_l, ref_g = jax.value_and_grad(ref_loss, argnums=(0, 1, 2))(
        stacked, head, mbs, labels)

    def run(sp, hp, xb):
        return scheduled_pipeline_loss(schedule, stage_fn, head_fn, mesh,
                                       sp, hp, xb, labels)
    got_l, got_g = jax.value_and_grad(run, argnums=(0, 1, 2))(
        stacked, head, mbs)

    np.testing.assert_allclose(float(got_l), float(ref_l),
                               rtol=1e-5, atol=1e-5)
    for rg, gg, part in zip(ref_g, got_g, ["stacked", "head", "mbs"]):
        jax.tree.map(lambda a, b: np.testing.assert_allclose(
            np.asarray(b), np.asarray(a), rtol=2e-4, atol=2e-4,
            err_msg=part), rg, gg)


def test_upstream_cotangent_scaling(mesh):
    """The custom_vjp must scale grads by the incoming cotangent (e.g.
    the 1/total_tokens of a mean loss applied OUTSIDE the pipeline)."""
    schedule = one_f_one_b_schedule(S, M)
    stacked, head, mbs, labels = _setup(1)

    def mean_run(sp):
        return scheduled_pipeline_loss(schedule, stage_fn, head_fn, mesh,
                                       sp, head, mbs, labels) / (M * MB)
    def mean_ref(sp):
        return ref_loss(sp, head, mbs, labels) / (M * MB)
    g_run = jax.grad(mean_run)(stacked)
    g_ref = jax.grad(mean_ref)(stacked)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-5), g_run, g_ref)


class TestMemoryBounds:
    def test_1f1b_bounds_are_stage_depth_not_microbatches(self):
        """THE 1F1B claim: executor buffers scale with S, GPipe-order
        (FThenB) buffers scale with M."""
        M_big = 32
        b_1f1b = schedule_buffer_bounds(one_f_one_b_schedule(S, M_big))
        b_fthenb = schedule_buffer_bounds(fthenb_schedule(S, M_big))
        assert b_1f1b["in_buf"] <= S + 1
        assert b_fthenb["in_buf"] >= M_big - S
        # ZBH1 keeps the 1F1B activation class (the H1 memory contract)
        b_zb = schedule_buffer_bounds(zbh1_schedule(S, M_big))
        assert b_zb["in_buf"] <= S + 1
        assert b_zb["w_buf"] <= 2 * S

    def test_zbh1_fills_bubbles(self):
        s_1f1b = one_f_one_b_schedule(S, 8)
        s_zb = zbh1_schedule(S, 8)
        # same F/B work + extra W work in comparable ticks => lower idle
        assert s_zb.bubble_ratio() < s_1f1b.bubble_ratio()


def test_pretrain_step_1f1b_matches_compiled():
    """The flagship train step with pp_schedule='1F1B' (timetable
    executor) must match the compiled GPipe-scan path: same loss every
    step given identical init."""
    import paddle_tpu as paddle
    from paddle_tpu.models.llama import llama_tiny_config
    from paddle_tpu.trainer.pretrain import (PretrainConfig,
                                             build_llama_pretrain_step,
                                             make_hybrid_mesh_for)

    def build(pp_schedule):
        paddle.seed(1234)
        mc = llama_tiny_config(num_hidden_layers=4,
                               max_position_embeddings=64)
        cfg = PretrainConfig(mc, global_batch=4, seq_len=32,
                             n_microbatches=4, dp=1, mp=2, pp=2,
                             sharding=1, sep=1, pp_schedule=pp_schedule)
        mesh = make_hybrid_mesh_for(cfg,
                                    devices=jax.devices()[:4])
        return mc, build_llama_pretrain_step(cfg, mesh)

    mc, (st_a, step_a, meta_a) = build("compiled")
    _, (st_b, step_b, meta_b) = build("1F1B")
    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(0, mc.vocab_size, (4, 32)), jnp.int32)
    labels = jnp.asarray(rng.randint(0, mc.vocab_size, (4, 32)),
                         jnp.int32)
    ids_a = jax.device_put(ids, meta_a["data_sharding"])
    lab_a = jax.device_put(labels, meta_a["data_sharding"])
    ids_b = jax.device_put(ids, meta_b["data_sharding"])
    lab_b = jax.device_put(labels, meta_b["data_sharding"])
    for i in range(2):
        st_a, ma = step_a(st_a, ids_a, lab_a)
        st_b, mb = step_b(st_b, ids_b, lab_b)
        la, lb = float(ma["loss"]), float(mb["loss"])
        np.testing.assert_allclose(lb, la, rtol=5e-4, err_msg=f"step {i}")


def test_pretrain_step_zbh1_runs():
    import paddle_tpu as paddle
    from paddle_tpu.models.llama import llama_tiny_config
    from paddle_tpu.trainer.pretrain import (PretrainConfig,
                                             build_llama_pretrain_step,
                                             make_hybrid_mesh_for)
    paddle.seed(7)
    mc = llama_tiny_config(num_hidden_layers=4,
                           max_position_embeddings=64)
    cfg = PretrainConfig(mc, global_batch=4, seq_len=32,
                         n_microbatches=4, pp=2, mp=2,
                         pp_schedule="ZBH1")
    mesh = make_hybrid_mesh_for(cfg, devices=jax.devices()[:4])
    st, step, meta = build_llama_pretrain_step(cfg, mesh)
    rng = np.random.RandomState(0)
    ids = jax.device_put(jnp.asarray(
        rng.randint(0, mc.vocab_size, (4, 32)), jnp.int32),
        meta["data_sharding"])
    st, m = step(st, ids, ids)
    assert np.isfinite(float(m["loss"]))


# ---------------------------------------------------------------------------
# Chunked (interleaved VPP) timetable executor — VERDICT r2 item 2
# ---------------------------------------------------------------------------
def test_vpp_executor_matches_sequential_autodiff(mesh):
    """Interleaved schedule (n_chunks=2) through the chunked executor:
    loss + grads vs sequential autodiff over the vstage-ordered stack."""
    from paddle_tpu.distributed.pp_schedule import interleaved_1f1b_schedule
    CH = 2
    schedule = interleaved_1f1b_schedule(S, M, CH)
    schedule.validate()
    rng = np.random.RandomState(3)
    # [S, CH, 1, H, H]: vstage v = c*S + s applies stacked[:, c][s]
    stacked = {
        "w": jnp.asarray(rng.standard_normal((S, CH, 1, H, H)) * 0.3,
                         jnp.float32),
        "b": jnp.asarray(rng.standard_normal((S, CH, 1, H)) * 0.1,
                         jnp.float32),
    }
    head = {"wout": jnp.asarray(rng.standard_normal((H, C)) * 0.3,
                                jnp.float32)}
    mbs = jnp.asarray(rng.standard_normal((M, MB, H)), jnp.float32)
    labels = jnp.asarray(rng.randint(0, C, (M, MB)), jnp.int32)

    def ref(sp, hp, xb):
        total = 0.0
        for m in range(M):
            x = xb[m]
            for v in range(S * CH):
                s, c = v % S, v // S
                x = stage_fn({"w": sp["w"][s, c], "b": sp["b"][s, c]}, x)
            total = total + head_fn(hp, x, labels[m])
        return total

    ref_l, ref_g = jax.value_and_grad(ref, argnums=(0, 1, 2))(
        stacked, head, mbs)

    def run(sp, hp, xb):
        return scheduled_pipeline_loss(schedule, stage_fn, head_fn, mesh,
                                       sp, hp, xb, labels)
    got_l, got_g = jax.value_and_grad(run, argnums=(0, 1, 2))(
        stacked, head, mbs)
    np.testing.assert_allclose(float(got_l), float(ref_l),
                               rtol=1e-5, atol=1e-5)
    for rg, gg, part in zip(ref_g, got_g, ["stacked", "head", "mbs"]):
        jax.tree.map(lambda a, b: np.testing.assert_allclose(
            np.asarray(b), np.asarray(a), rtol=2e-4, atol=2e-4,
            err_msg=part), rg, gg)


def test_vpp_schedule_shrinks_warmup_bubble():
    from paddle_tpu.distributed.pp_schedule import interleaved_1f1b_schedule
    s1 = one_f_one_b_schedule(S, 8)
    s2 = interleaved_1f1b_schedule(S, 8, 2)
    assert s2.bubble_ratio() < s1.bubble_ratio()


def test_pretrain_step_vpp_timetable_matches_compiled():
    """pp_schedule='VPP' (chunked timetable executor) vs the compiled
    interleaved pipeline on the flagship step."""
    import paddle_tpu as paddle
    from paddle_tpu.models.llama import llama_tiny_config
    from paddle_tpu.trainer.pretrain import (PretrainConfig,
                                             build_llama_pretrain_step,
                                             make_hybrid_mesh_for)

    def build(pp_schedule):
        paddle.seed(77)
        mc = llama_tiny_config(num_hidden_layers=4,
                               max_position_embeddings=64,
                               sequence_parallel=False)
        cfg = PretrainConfig(mc, global_batch=4, seq_len=32,
                             n_microbatches=4, dp=1, mp=2, pp=2,
                             sharding=1, sep=1, vpp=2,
                             pp_schedule=pp_schedule)
        mesh = make_hybrid_mesh_for(cfg, devices=jax.devices()[:4])
        return mc, build_llama_pretrain_step(cfg, mesh)

    mc, (st_a, step_a, meta_a) = build("compiled")
    _, (st_b, step_b, meta_b) = build("VPP")
    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(0, mc.vocab_size, (4, 32)), jnp.int32)
    ids_a = jax.device_put(ids, meta_a["data_sharding"])
    ids_b = jax.device_put(ids, meta_b["data_sharding"])
    st_a, ma = step_a(st_a, ids_a, ids_a)
    st_b, mb = step_b(st_b, ids_b, ids_b)
    np.testing.assert_allclose(float(mb["loss"]), float(ma["loss"]),
                               rtol=5e-4)


def test_pretrain_step_1f1b_composes_with_sep_axis():
    """1F1B x mp x sep (VERDICT r2 item 2): the timetable executor on a
    mesh WITH a sep axis + Megatron-SP annotations. The executor gathers
    the sep sharding at its boundary (in-branch seq collectives deadlock
    — see pp_exec composition note), so the loss must match the sep-less
    run bit-for-bit-ish."""
    import paddle_tpu as paddle
    from paddle_tpu.models.llama import llama_tiny_config
    from paddle_tpu.trainer.pretrain import (PretrainConfig,
                                             build_llama_pretrain_step,
                                             make_hybrid_mesh_for)

    def build(sep, ndev):
        paddle.seed(55)
        mc = llama_tiny_config(num_hidden_layers=4,
                               max_position_embeddings=64,
                               sequence_parallel=True)
        cfg = PretrainConfig(mc, global_batch=4, seq_len=32,
                             n_microbatches=4, dp=1, mp=2, pp=2,
                             sharding=1, sep=sep, pp_schedule="1F1B")
        mesh = make_hybrid_mesh_for(cfg, devices=jax.devices()[:ndev])
        return mc, build_llama_pretrain_step(cfg, mesh)

    mc, (st_a, step_a, meta_a) = build(1, 4)
    _, (st_b, step_b, meta_b) = build(2, 8)
    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(0, mc.vocab_size, (4, 32)), jnp.int32)
    ids_a = jax.device_put(ids, meta_a["data_sharding"])
    ids_b = jax.device_put(ids, meta_b["data_sharding"])
    st_a, ma = step_a(st_a, ids_a, ids_a)
    st_b, mb = step_b(st_b, ids_b, ids_b)
    np.testing.assert_allclose(float(mb["loss"]), float(ma["loss"]),
                               rtol=1e-4)


def test_seq_sharded_mb_auto_spec_rejected():
    """The composition limit is a loud error, not a hang."""
    from jax.sharding import PartitionSpec as P
    schedule = one_f_one_b_schedule(2, M)
    stacked, head, mbs, labels = _setup()
    stacked = {k: v.reshape((2, 2 * LS) + v.shape[2:])
               for k, v in stacked.items()}
    mesh8 = build_hybrid_mesh(pp_degree=2, sep_degree=2, mp_degree=2,
                              devices=jax.devices()[:8])
    with pytest.raises(ValueError, match="gather it at the boundary"):
        scheduled_pipeline_loss(
            schedule, stage_fn, head_fn, mesh8, stacked, head, mbs,
            labels, mb_auto_spec=P(None, "sep"))
