"""Optimizers (ref: python/paddle/optimizer/).

Paddle semantics: per-param accumulators, multi_precision master weights for
bf16/f16 params, grad_clip objects, LRScheduler integration. All update math
is jnp (traceable), so Optimizer.step() works both eagerly and inside a
traced train step. The per-param python loop is amortized: the jitted Trainer
path traces it once into a single fused XLA update program — the TPU analog
of the reference's fused/multi-tensor optimizer kernels
(paddle/phi/kernels/gpu/adamw_kernel.cu, fused multi_tensor paths).
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional

import jax.numpy as jnp
import numpy as np

from ..core.dtypes import is_floating_dtype
from ..core.tensor import Tensor
from . import lr as lr  # noqa: F401
from .lr import LRScheduler

__all__ = ["Optimizer", "SGD", "Momentum", "Adagrad", "Adadelta", "Adamax",
           "RMSProp", "Adam", "AdamW", "Lamb", "lr"]


def _is_low_precision(dtype) -> bool:
    return dtype in (jnp.float16, jnp.bfloat16) or \
        np.dtype(dtype) in (np.dtype(np.float16),)


class Optimizer:
    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, multi_precision=False, name=None):
        if parameters is None:
            raise ValueError(
                "parameters must be given (eager mode, ref parity)")
        self._param_groups = list(parameters)
        self._lr = learning_rate
        self._weight_decay = 0.0 if weight_decay is None else (
            weight_decay if isinstance(weight_decay, float) else weight_decay)
        self._grad_clip = grad_clip
        self._multi_precision = multi_precision
        self._accumulators: Dict[str, Dict[int, jnp.ndarray]] = {}
        self._master: Dict[int, jnp.ndarray] = {}
        self._step_count = 0

    # -- lr ------------------------------------------------------------------
    def get_lr(self) -> float:
        if isinstance(self._lr, LRScheduler):
            return self._lr()
        return float(self._lr)

    def set_lr(self, value: float) -> None:
        if isinstance(self._lr, LRScheduler):
            raise RuntimeError("cannot set_lr when using an LRScheduler")
        self._lr = float(value)

    @property
    def _learning_rate(self):
        return self._lr

    # -- accumulators --------------------------------------------------------
    def _acc(self, name: str, p: Tensor, init=None):
        store = self._accumulators.setdefault(name, {})
        pid = id(p)
        if pid not in store:
            dt = jnp.float32 if _is_low_precision(p.dtype) else p.dtype
            arr = jnp.zeros(p._data.shape, dt) if init is None else init
            if self._acc_placement is not None:
                arr = self._acc_placement(p, arr)
            store[pid] = arr
        return store[pid]

    # hook: ZeRO optimizer-state sharding installs a placement fn here
    # (ref: DygraphShardingOptimizer — SURVEY §2.3 P2; on TPU the partition
    # is a sharding spec on the accumulator arrays)
    _acc_placement = None

    def _set_acc(self, name: str, p: Tensor, value) -> None:
        self._accumulators[name][id(p)] = value

    def _master_weight(self, p: Tensor):
        pid = id(p)
        if self._multi_precision and _is_low_precision(p.dtype):
            if pid not in self._master:
                mw = p._data.astype(jnp.float32)
                if self._acc_placement is not None:
                    mw = self._acc_placement(p, mw)
                self._master[pid] = mw
            return self._master[pid]
        return p._data

    def _write_param(self, p: Tensor, new_value) -> None:
        pid = id(p)
        if self._multi_precision and _is_low_precision(p.dtype):
            self._master[pid] = new_value
            p._data = new_value.astype(p.dtype)
        else:
            p._data = new_value.astype(p.dtype)

    # -- step ----------------------------------------------------------------
    def step(self) -> None:
        params_grads = [(p, p.grad) for p in self._param_groups
                        if not p.stop_gradient and p.grad is not None]
        if self._grad_clip is not None:
            params_grads = self._grad_clip(params_grads)
        self._step_count += 1
        lr_v = self.get_lr()
        for p, g in params_grads:
            if g is None:
                continue
            gd = g._data
            # a ParamAttr regularizer OVERRIDES the optimizer-level
            # decay for that parameter (paddle priority rule)
            reg = getattr(p, "regularizer", None)
            if reg is not None and hasattr(reg, "grad_term"):
                w = self._master_weight(p)
                gd = gd + reg.grad_term(w).astype(gd.dtype)
                self._wd_skip_param = True
            # per-param lr multiplier (ParamAttr.learning_rate)
            attr = getattr(p, "optimize_attr", None) or {}
            self._update_param(p, gd,
                               lr_v * float(attr.get("learning_rate", 1.0)))
            self._wd_skip_param = False

    def _update_param(self, p: Tensor, grad, lr_v: float) -> None:
        raise NotImplementedError

    def clear_grad(self, set_to_zero: bool = False) -> None:
        for p in self._param_groups:
            p._grad = None

    clear_gradients = clear_grad

    # set transiently by step() when the current param carries its own
    # ParamAttr regularizer (which overrides optimizer-level decay)
    _wd_skip_param = False

    def _decoupled_wd_coeff(self) -> float:
        """The effective decoupled-decay coefficient for the CURRENT
        param: 0 when its ParamAttr regularizer overrides, else the
        float weight_decay or an L1/L2Decay instance's coeff."""
        if self._wd_skip_param:
            return 0.0
        wd = self._weight_decay
        return float(wd) if isinstance(wd, (int, float)) \
            else float(getattr(wd, "coeff", 0.0))

    def _apply_decoupled_wd(self, w, lr_v):
        """AdamW-style decoupled weight decay (float coeff, or the coeff
        of an L2Decay/L1Decay regularizer instance)."""
        coeff = self._decoupled_wd_coeff()
        if coeff:
            return w * (1.0 - lr_v * coeff)
        return w

    def _coupled_wd_grad(self, w, grad):
        """Regularization-style decay added to the gradient: float means
        L2 (wd * w); an L1Decay/L2Decay instance contributes its own
        grad_term (ref: paddle regularizer applied in the optimizer)."""
        if self._wd_skip_param:
            return grad
        wd = self._weight_decay
        if hasattr(wd, "grad_term"):
            return grad + wd.grad_term(w).astype(grad.dtype)
        if isinstance(wd, (int, float)) and wd:
            return grad + wd * w
        return grad

    # -- state dict -----------------------------------------------------------
    def state_dict(self) -> dict:
        # keyed by parameter position (names may repeat across layers)
        pid_to_idx = {id(p): i for i, p in enumerate(self._param_groups)}
        accs = {}
        for name, store in self._accumulators.items():
            accs[name] = {str(pid_to_idx[pid]): Tensor(v)
                          for pid, v in store.items() if pid in pid_to_idx}
        out = {"accumulators": accs, "step": self._step_count,
               "master": {str(pid_to_idx[pid]): Tensor(v)
                          for pid, v in self._master.items()
                          if pid in pid_to_idx}}
        if isinstance(self._lr, LRScheduler):
            out["LR_Scheduler"] = self._lr.state_dict()
        return out

    def set_state_dict(self, state: dict) -> None:
        idx_to_pid = {str(i): id(p) for i, p in enumerate(self._param_groups)}
        self._step_count = state.get("step", 0)
        for name, store in state.get("accumulators", {}).items():
            self._accumulators[name] = {
                idx_to_pid[k]: (v._data if isinstance(v, Tensor) else jnp.asarray(v))
                for k, v in store.items()}
        self._master = {
            idx_to_pid[k]: (v._data if isinstance(v, Tensor) else jnp.asarray(v))
            for k, v in state.get("master", {}).items()}
        if "LR_Scheduler" in state and isinstance(self._lr, LRScheduler):
            self._lr.set_state_dict(state["LR_Scheduler"])


class SGD(Optimizer):
    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision)

    def _update_param(self, p, grad, lr_v):
        w = self._master_weight(p)
        g = self._coupled_wd_grad(w, grad.astype(w.dtype))
        self._write_param(p, w - lr_v * g)


class Momentum(Optimizer):
    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision)
        self._momentum = momentum
        self._nesterov = use_nesterov

    def _update_param(self, p, grad, lr_v):
        w = self._master_weight(p)
        g = self._coupled_wd_grad(w, grad.astype(w.dtype))
        v = self._acc("velocity", p)
        v = self._momentum * v + g
        self._set_acc("velocity", p, v)
        if self._nesterov:
            upd = g + self._momentum * v
        else:
            upd = v
        self._write_param(p, w - lr_v * upd)


class Adagrad(Optimizer):
    def __init__(self, learning_rate=0.001, epsilon=1e-6, parameters=None,
                 weight_decay=None, grad_clip=None, initial_accumulator_value=0.0,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision)
        self._eps = epsilon
        self._init_acc = initial_accumulator_value

    def _update_param(self, p, grad, lr_v):
        w = self._master_weight(p)
        g = self._coupled_wd_grad(w, grad.astype(w.dtype))
        m = self._acc("moment", p,
                      init=jnp.full(p._data.shape, self._init_acc,
                                    jnp.float32 if _is_low_precision(p.dtype)
                                    else p.dtype))
        m = m + jnp.square(g)
        self._set_acc("moment", p, m)
        self._write_param(p, w - lr_v * g / (jnp.sqrt(m) + self._eps))


class Adadelta(Optimizer):
    def __init__(self, learning_rate=0.001, epsilon=1e-6, rho=0.95,
                 parameters=None, weight_decay=None, grad_clip=None,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision)
        self._eps = epsilon
        self._rho = rho

    def _update_param(self, p, grad, lr_v):
        w = self._master_weight(p)
        g = self._coupled_wd_grad(w, grad.astype(w.dtype))
        avg_sq = self._acc("avg_squared_grad", p)
        avg_upd = self._acc("avg_squared_update", p)
        avg_sq = self._rho * avg_sq + (1 - self._rho) * jnp.square(g)
        upd = jnp.sqrt(avg_upd + self._eps) / jnp.sqrt(avg_sq + self._eps) * g
        avg_upd = self._rho * avg_upd + (1 - self._rho) * jnp.square(upd)
        self._set_acc("avg_squared_grad", p, avg_sq)
        self._set_acc("avg_squared_update", p, avg_upd)
        self._write_param(p, w - lr_v * upd)


class RMSProp(Optimizer):
    def __init__(self, learning_rate=0.001, rho=0.95, epsilon=1e-6,
                 momentum=0.0, centered=False, parameters=None,
                 weight_decay=None, grad_clip=None, multi_precision=False,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision)
        self._rho = rho
        self._eps = epsilon
        self._momentum = momentum
        self._centered = centered

    def _update_param(self, p, grad, lr_v):
        w = self._master_weight(p)
        g = self._coupled_wd_grad(w, grad.astype(w.dtype))
        ms = self._acc("mean_square", p)
        ms = self._rho * ms + (1 - self._rho) * jnp.square(g)
        self._set_acc("mean_square", p, ms)
        if self._centered:
            mg = self._acc("mean_grad", p)
            mg = self._rho * mg + (1 - self._rho) * g
            self._set_acc("mean_grad", p, mg)
            denom = jnp.sqrt(ms - jnp.square(mg) + self._eps)
        else:
            denom = jnp.sqrt(ms + self._eps)
        mom = self._acc("momentum", p)
        mom = self._momentum * mom + lr_v * g / denom
        self._set_acc("momentum", p, mom)
        self._write_param(p, w - mom)


class Adam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, lazy_mode=False, multi_precision=False,
                 amsgrad=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision)
        self._b1 = beta1
        self._b2 = beta2
        self._eps = epsilon
        self._amsgrad = amsgrad

    def _decay(self, w, g, lr_v):
        # plain Adam: coupled (L2) decay
        return self._coupled_wd_grad(w, g), w

    def _update_param(self, p, grad, lr_v):
        w = self._master_weight(p)
        g = grad.astype(w.dtype)
        g, w = self._decay(w, g, lr_v)
        m = self._acc("moment1", p)
        v = self._acc("moment2", p)
        t = self._step_count
        m = self._b1 * m + (1 - self._b1) * g
        v = self._b2 * v + (1 - self._b2) * jnp.square(g)
        self._set_acc("moment1", p, m)
        self._set_acc("moment2", p, v)
        mhat = m / (1 - self._b1 ** t)
        vhat = v / (1 - self._b2 ** t)
        if self._amsgrad:
            vmax = self._acc("moment2_max", p)
            vmax = jnp.maximum(vmax, vhat)
            self._set_acc("moment2_max", p, vmax)
            vhat = vmax
        self._write_param(p, w - lr_v * mhat / (jnp.sqrt(vhat) + self._eps))


class AdamW(Adam):
    """Decoupled weight decay (ref: python/paddle/optimizer/adamw.py)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=0.01,
                 lr_ratio=None, apply_decay_param_fun=None, grad_clip=None,
                 lazy_mode=False, multi_precision=False, amsgrad=False,
                 name=None):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         weight_decay, grad_clip, lazy_mode, multi_precision,
                         amsgrad)
        self._apply_decay_fn = apply_decay_param_fun
        self._decay_pids = None

    def _update_param(self, p, grad, lr_v):
        wd = self._decoupled_wd_coeff()
        do_decay = True
        if self._apply_decay_fn is not None:
            do_decay = self._apply_decay_fn(p.name) if p.name else True
        w = self._master_weight(p)
        m = self._acc("moment1", p)
        v = self._acc("moment2", p)
        t = self._step_count
        # the one shared AdamW kernel (also the jitted pretrain path)
        from .functional import adamw_kernel
        if self._amsgrad:
            new_w, m, v, vmax = adamw_kernel(
                w, grad, m, v, t, lr=lr_v, b1=self._b1, b2=self._b2,
                eps=self._eps, weight_decay=wd, do_decay=do_decay,
                vmax=self._acc("moment2_max", p))
            self._set_acc("moment2_max", p, vmax)
        else:
            new_w, m, v = adamw_kernel(
                w, grad, m, v, t, lr=lr_v, b1=self._b1, b2=self._b2,
                eps=self._eps, weight_decay=wd, do_decay=do_decay)
        self._set_acc("moment1", p, m)
        self._set_acc("moment2", p, v)
        self._write_param(p, new_w)


class Adamax(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision)
        self._b1, self._b2, self._eps = beta1, beta2, epsilon

    def _update_param(self, p, grad, lr_v):
        w = self._master_weight(p)
        g = self._coupled_wd_grad(w, grad.astype(w.dtype))
        m = self._acc("moment", p)
        u = self._acc("inf_norm", p)
        t = self._step_count
        m = self._b1 * m + (1 - self._b1) * g
        u = jnp.maximum(self._b2 * u, jnp.abs(g))
        self._set_acc("moment", p, m)
        self._set_acc("inf_norm", p, u)
        self._write_param(p, w - lr_v / (1 - self._b1 ** t) * m / (u + self._eps))


class Lamb(Optimizer):
    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01, beta1=0.9,
                 beta2=0.999, epsilon=1e-6, parameters=None, grad_clip=None,
                 exclude_from_weight_decay_fn=None, multi_precision=False,
                 name=None):
        super().__init__(learning_rate, parameters, lamb_weight_decay,
                         grad_clip, multi_precision)
        self._b1, self._b2, self._eps = beta1, beta2, epsilon
        self._exclude_fn = exclude_from_weight_decay_fn

    def _update_param(self, p, grad, lr_v):
        w = self._master_weight(p)
        g = grad.astype(w.dtype)
        m = self._acc("moment1", p)
        v = self._acc("moment2", p)
        t = self._step_count
        m = self._b1 * m + (1 - self._b1) * g
        v = self._b2 * v + (1 - self._b2) * jnp.square(g)
        self._set_acc("moment1", p, m)
        self._set_acc("moment2", p, v)
        mhat = m / (1 - self._b1 ** t)
        vhat = v / (1 - self._b2 ** t)
        r = mhat / (jnp.sqrt(vhat) + self._eps)
        wd = self._decoupled_wd_coeff()
        if self._exclude_fn is not None and self._exclude_fn(p):
            wd = 0.0
        upd = r + wd * w
        w_norm = jnp.linalg.norm(w)
        u_norm = jnp.linalg.norm(upd)
        trust = jnp.where(jnp.logical_and(w_norm > 0, u_norm > 0),
                          w_norm / u_norm, 1.0)
        self._write_param(p, w - lr_v * trust * upd)


class NAdam(Adam):
    """Nesterov-momentum Adam (ref: python/paddle/optimizer/nadam.py)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, momentum_decay=0.004, parameters=None,
                 weight_decay=None, grad_clip=None, multi_precision=False,
                 name=None):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         weight_decay, grad_clip, False, multi_precision)
        self._psi = momentum_decay

    def _update_param(self, p, grad, lr_v):
        w = self._master_weight(p)
        g = self._coupled_wd_grad(w, grad.astype(w.dtype))
        t = self._step_count
        # momentum schedule mu_t (torch/paddle nadam)
        mu_t = self._b1 * (1.0 - 0.5 * 0.96 ** (t * self._psi))
        mu_t1 = self._b1 * (1.0 - 0.5 * 0.96 ** ((t + 1) * self._psi))
        mu_prod = self._acc("mu_prod", p,
                            init=jnp.ones((), w.dtype)) * mu_t
        self._set_acc("mu_prod", p, mu_prod)
        m = self._acc("moment1", p)
        v = self._acc("moment2", p)
        m = self._b1 * m + (1 - self._b1) * g
        v = self._b2 * v + (1 - self._b2) * jnp.square(g)
        self._set_acc("moment1", p, m)
        self._set_acc("moment2", p, v)
        mhat = (mu_t1 * m / (1 - mu_prod * mu_t1)
                + (1 - mu_t) * g / (1 - mu_prod))
        vhat = v / (1 - self._b2 ** t)
        self._write_param(p, w - lr_v * mhat / (jnp.sqrt(vhat) + self._eps))


class RAdam(Adam):
    """Rectified Adam (ref: python/paddle/optimizer/radam.py)."""

    def _update_param(self, p, grad, lr_v):
        w = self._master_weight(p)
        g = self._coupled_wd_grad(w, grad.astype(w.dtype))
        t = self._step_count
        m = self._acc("moment1", p)
        v = self._acc("moment2", p)
        m = self._b1 * m + (1 - self._b1) * g
        v = self._b2 * v + (1 - self._b2) * jnp.square(g)
        self._set_acc("moment1", p, m)
        self._set_acc("moment2", p, v)
        mhat = m / (1 - self._b1 ** t)
        rho_inf = 2.0 / (1.0 - self._b2) - 1.0
        b2t = self._b2 ** t
        rho_t = rho_inf - 2.0 * t * b2t / (1.0 - b2t)
        if rho_t > 5.0:
            vhat = jnp.sqrt(v / (1 - b2t))
            r = math.sqrt(((rho_t - 4) * (rho_t - 2) * rho_inf)
                          / ((rho_inf - 4) * (rho_inf - 2) * rho_t))
            self._write_param(p, w - lr_v * r * mhat / (vhat + self._eps))
        else:
            self._write_param(p, w - lr_v * mhat)


class Rprop(Optimizer):
    """Resilient backprop (ref: python/paddle/optimizer/rprop.py) —
    full-batch sign-based step-size adaptation."""

    def __init__(self, learning_rate=0.001, learning_rate_range=(1e-5, 50.0),
                 parameters=None, etas=(0.5, 1.2), grad_clip=None,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip,
                         multi_precision)
        self._eta_minus, self._eta_plus = etas
        self._lr_min, self._lr_max = learning_rate_range
        self._init_lr = learning_rate if isinstance(learning_rate, float) \
            else 1e-3

    def _update_param(self, p, grad, lr_v):
        w = self._master_weight(p)
        g = grad.astype(w.dtype)
        prev = self._acc("prev_grad", p)
        step = self._acc("step_size", p,
                         init=jnp.full(w.shape, self._init_lr, w.dtype))
        sign = jnp.sign(g * prev)
        factor = jnp.where(sign > 0, self._eta_plus,
                           jnp.where(sign < 0, self._eta_minus, 1.0))
        step = jnp.clip(step * factor, self._lr_min, self._lr_max)
        # on sign change, zero the gradient (classic Rprop-)
        g_eff = jnp.where(sign < 0, 0.0, g)
        self._set_acc("prev_grad", p, g_eff)
        self._set_acc("step_size", p, step)
        self._write_param(p, w - jnp.sign(g_eff) * step)


class ASGD(Optimizer):
    """Averaged SGD: plain SGD fast weights plus a running average of the
    iterates (Polyak averaging), exposed via averaged_parameters(). NOTE:
    the paddle reference (python/paddle/optimizer/asgd.py) averages the
    last batch_num GRADIENTS instead; that windowed-gradient mode is not
    implemented, so batch_num > 1 raises rather than silently diverging."""

    def __init__(self, learning_rate=0.001, batch_num=1, parameters=None,
                 weight_decay=None, grad_clip=None, multi_precision=False,
                 name=None):
        if batch_num != 1:
            raise NotImplementedError(
                "ASGD batch_num > 1 (gradient-window averaging) is not "
                "implemented; only iterate averaging (batch_num=1)")
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision)

    def _update_param(self, p, grad, lr_v):
        w = self._master_weight(p)
        g = self._coupled_wd_grad(w, grad.astype(w.dtype))
        new_w = w - lr_v * g
        avg = self._acc("averaged_param", p, init=new_w)
        t = self._step_count
        avg = avg + (new_w - avg) / t
        self._set_acc("averaged_param", p, avg)
        self._write_param(p, new_w)

    def averaged_parameters(self):
        store = self._accumulators.get("averaged_param", {})
        return {id(p): store[id(p)] for p in self._param_groups
                if id(p) in store}


__all__ += ["NAdam", "RAdam", "Rprop", "ASGD"]
