"""Ragged mixed prefill+decode paged-attention kernel
(ops/pallas_ragged.py) and the fused rope+append scatter kernels
(ops/fused.fused_rope_append / fused_append_rows). The plain-XLA
ragged_attention_reference is the correctness oracle. Runs in Pallas
interpret mode on CPU: same kernel logic as the TPU path."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.ops.fused import fused_append_rows, fused_rope_append
from paddle_tpu.ops.pallas_ragged import (ragged_attention_reference,
                                          ragged_kernel_eligible,
                                          ragged_paged_attention)


def _setup(T, S, H, KV, D, psz, pps, seed=0, dtype=jnp.float32):
    """Random pools + a ragged batch layout: sequence row spans are
    chosen disjoint inside [0, T); kv_lengths include the new tokens."""
    rng = np.random.RandomState(seed)
    total = S * pps + 1
    q = jnp.asarray(rng.randn(T, H, D), dtype)
    kp = jnp.asarray(rng.randn(KV, total, psz, D), dtype)
    vp = jnp.asarray(rng.randn(KV, total, psz, D), dtype)
    tab = jnp.asarray(1 + rng.permutation(total - 1)[:S * pps]
                      .reshape(S, pps), jnp.int32)
    # carve T rows into S disjoint spans (some possibly empty)
    cuts = np.sort(rng.choice(T + 1, S - 1, replace=False)) \
        if S > 1 else np.array([], np.int64)
    starts = np.concatenate([[0], cuts]).astype(np.int32)
    ends = np.concatenate([cuts, [T]]).astype(np.int32)
    nt = (ends - starts).astype(np.int32)
    kvl = np.zeros(S, np.int32)
    for i in range(S):
        lo = max(int(nt[i]), 1)
        kvl[i] = rng.randint(lo, pps * psz + 1)
    kvl = np.maximum(kvl, nt)
    return (q, kp, vp, jnp.asarray(starts), jnp.asarray(nt),
            jnp.asarray(kvl), tab)


def _check(q, kp, vp, ss, nt, kvl, tab, atol=2e-5, rtol=2e-5):
    out = ragged_paged_attention(q, kp, vp, ss, nt, kvl, tab)
    ref = ragged_attention_reference(q, kp, vp, ss, nt, kvl, tab)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=atol, rtol=rtol)
    return out


class TestRaggedKernelParity:
    @pytest.mark.parametrize("T,S,H,KV,D,psz,pps", [
        (12, 3, 8, 2, 128, 16, 4),   # GQA rep=4, mixed spans
        (9, 4, 4, 1, 64, 16, 2),     # MQA, D=64, non-128-multiple T
        (20, 2, 4, 4, 128, 8, 4),    # MHA rep=1, small pages
    ])
    def test_matches_reference(self, T, S, H, KV, D, psz, pps):
        _check(*_setup(T, S, H, KV, D, psz, pps))

    def test_mixed_prefill_decode_batch(self):
        # the engine's exact shape: decode rows 0..B-1 (1 token each),
        # a prefill chunk on rows B.., kv_lengths include the new rows
        B, C, psz, pps, KV, H, D = 3, 5, 8, 4, 2, 4, 64
        T, S = B + C, B + 1
        rng = np.random.RandomState(1)
        total = S * pps + 1
        q = jnp.asarray(rng.randn(T, H, D), jnp.float32)
        kp = jnp.asarray(rng.randn(KV, total, psz, D), jnp.float32)
        vp = jnp.asarray(rng.randn(KV, total, psz, D), jnp.float32)
        tab = jnp.asarray(1 + rng.permutation(total - 1)[:S * pps]
                          .reshape(S, pps), jnp.int32)
        ss = jnp.asarray(list(range(B)) + [B], jnp.int32)
        nt = jnp.asarray([1, 1, 1, C], jnp.int32)
        kvl = jnp.asarray([7, 19, 1, 6 + C], jnp.int32)
        _check(q, kp, vp, ss, nt, kvl, tab)

    def test_empty_slots_emit_zeros(self):
        # num_tokens=0 rows (idle engine slots) must come back all-zero
        q, kp, vp, ss, nt, kvl, tab = _setup(10, 3, 4, 2, 128, 16, 2,
                                             seed=2)
        nt = nt.at[1].set(0)
        out = _check(q, kp, vp, ss, nt, kvl, tab)
        lo, hi = int(ss[1]), int(ss[1]) + 0
        covered = np.zeros(10, bool)
        ss_np, nt_np = np.asarray(ss), np.asarray(nt)
        for i in range(3):
            covered[ss_np[i]:ss_np[i] + nt_np[i]] = True
        np.testing.assert_array_equal(
            np.asarray(out)[~covered], 0.0)

    def test_sentinel_table_entries(self):
        # dead tail pages marked -1 (allocator sentinel): clamped, never
        # read (kv_length masks them), parity holds
        q, kp, vp, ss, nt, kvl, tab = _setup(8, 2, 4, 2, 64, 16, 4,
                                             seed=3)
        kvl = jnp.minimum(kvl, 16)      # only page 0 of each seq live
        tab = tab.at[:, 1:].set(-1)
        _check(q, kp, vp, ss, nt, kvl, tab)

    def test_single_sequence_whole_buffer(self):
        # degenerate batch: one sequence owns every row (pure prefill)
        T = 16
        q, kp, vp, _, _, _, tab = _setup(T, 1, 8, 2, 128, 16, 4, seed=4)
        ss = jnp.asarray([0], jnp.int32)
        nt = jnp.asarray([T], jnp.int32)
        kvl = jnp.asarray([T + 13], jnp.int32)
        _check(q, kp, vp, ss, nt, kvl, tab)

    def test_causality_within_chunk(self):
        # a token must NOT see later chunk rows: flipping a later row's
        # K/V leaves earlier rows' outputs unchanged
        T, psz, pps = 6, 8, 2
        rng = np.random.RandomState(5)
        q = jnp.asarray(rng.randn(T, 4, 64), jnp.float32)
        kp = jnp.asarray(rng.randn(2, pps + 1, psz, 64), jnp.float32)
        vp = jnp.asarray(rng.randn(2, pps + 1, psz, 64), jnp.float32)
        tab = jnp.asarray([[1, 2]], jnp.int32)
        ss = jnp.asarray([0], jnp.int32)
        nt = jnp.asarray([T], jnp.int32)
        kvl = jnp.asarray([T], jnp.int32)   # chunk starts the sequence
        out1 = ragged_paged_attention(q, kp, vp, ss, nt, kvl, tab)
        # last token's K/V row lives at position T-1 -> page tab[0, .]
        pg, off = (T - 1) // psz, (T - 1) % psz
        kp2 = kp.at[:, tab[0, pg], off].set(99.0)
        vp2 = vp.at[:, tab[0, pg], off].set(-99.0)
        out2 = ragged_paged_attention(q, kp2, vp2, ss, nt, kvl, tab)
        np.testing.assert_array_equal(np.asarray(out1)[:T - 1],
                                      np.asarray(out2)[:T - 1])

    def test_bf16(self):
        q, kp, vp, ss, nt, kvl, tab = _setup(12, 3, 8, 2, 128, 16, 4,
                                             seed=6, dtype=jnp.bfloat16)
        out = ragged_paged_attention(q, kp, vp, ss, nt, kvl, tab)
        ref = ragged_attention_reference(q, kp, vp, ss, nt, kvl, tab)
        assert out.dtype == jnp.bfloat16
        np.testing.assert_allclose(np.asarray(out, np.float32),
                                   np.asarray(ref, np.float32),
                                   atol=3e-2, rtol=3e-2)

    def test_eligibility_mirrors_paged(self):
        assert ragged_kernel_eligible(8, 2, 128, 16)
        assert ragged_kernel_eligible(4, 1, 64, 16)
        assert not ragged_kernel_eligible(4, 1, 24, 16)   # tiny MLA D
        assert not ragged_kernel_eligible(3, 2, 128, 16)  # H % KV != 0


class TestFusedRopeAppend:
    def _setup(self, T, Hq, KV, D, psz, total, seed=0):
        rng = np.random.RandomState(seed)
        q = jnp.asarray(rng.randn(T, Hq, D), jnp.float32)
        k = jnp.asarray(rng.randn(T, KV, D), jnp.float32)
        v = jnp.asarray(rng.randn(T, KV, D), jnp.float32)
        cos = jnp.asarray(rng.randn(T, D // 2), jnp.float32)
        sin = jnp.asarray(rng.randn(T, D // 2), jnp.float32)
        kp = jnp.asarray(rng.randn(KV, total, psz, D), jnp.float32)
        vp = jnp.asarray(rng.randn(KV, total, psz, D), jnp.float32)
        return q, k, v, cos, sin, kp, vp

    @staticmethod
    def _rot(x, c, s):
        d2 = x.shape[-1] // 2
        x1, x2 = x[..., :d2], x[..., d2:]
        cc, ss = c[:, None, :], s[:, None, :]
        return jnp.concatenate([x1 * cc - x2 * ss,
                                x2 * cc + x1 * ss], -1)

    def test_rope_and_scatter(self):
        # engine-shaped page walk: decode rows on distinct pages, then
        # an adjacent prefill run sharing pages, idle rows on trash 0
        T, KV, D, psz, total = 7, 2, 64, 4, 9
        q, k, v, cos, sin, kp, vp = self._setup(T, 4, KV, D, psz, total)
        pg = jnp.asarray([3, 5, 0, 7, 7, 7, 8], jnp.int32)
        off = jnp.asarray([1, 3, 0, 0, 1, 2, 0], jnp.int32)
        qo, kp2, vp2 = fused_rope_append(q, k, v, cos, sin, kp, vp,
                                         pg, off)
        np.testing.assert_allclose(np.asarray(qo),
                                   np.asarray(self._rot(q, cos, sin)),
                                   atol=1e-6)
        kref, vref = np.array(kp), np.array(vp)
        kr = np.asarray(self._rot(k, cos, sin))
        vr = np.asarray(v)
        for t in range(T):
            kref[:, int(pg[t]), int(off[t])] = kr[t]
            vref[:, int(pg[t]), int(off[t])] = vr[t]
        # every page except trash 0 must match exactly (V bitwise; K is
        # roped in f32 in both paths)
        np.testing.assert_array_equal(np.asarray(vp2)[:, 1:],
                                      vref[:, 1:])
        np.testing.assert_allclose(np.asarray(kp2)[:, 1:], kref[:, 1:],
                                   atol=1e-6)

    def test_identity_rope_bitwise(self):
        # cos=1/sin=0 (the GPT family's pure append): bitwise passthrough
        T, KV, D, psz, total = 4, 2, 64, 4, 5
        q, k, v, _, _, kp, vp = self._setup(T, 4, KV, D, psz, total,
                                            seed=1)
        cos = jnp.ones((T, D // 2), jnp.float32)
        sin = jnp.zeros((T, D // 2), jnp.float32)
        pg = jnp.asarray([1, 2, 3, 4], jnp.int32)
        off = jnp.asarray([0, 1, 2, 3], jnp.int32)
        qo, kp2, vp2 = fused_rope_append(q, k, v, cos, sin, kp, vp,
                                         pg, off)
        np.testing.assert_array_equal(np.asarray(qo), np.asarray(q))
        kref, vref = np.array(kp), np.array(vp)
        for t in range(T):
            kref[:, int(pg[t]), int(off[t])] = np.asarray(k)[t]
            vref[:, int(pg[t]), int(off[t])] = np.asarray(v)[t]
        np.testing.assert_array_equal(np.asarray(kp2)[:, 1:],
                                      kref[:, 1:])
        np.testing.assert_array_equal(np.asarray(vp2)[:, 1:],
                                      vref[:, 1:])

    def test_append_rows(self):
        # the MLA latent-row scatter (KV=1 single pool)
        T, D, psz, total = 5, 24, 4, 6
        rng = np.random.RandomState(2)
        rows = jnp.asarray(rng.randn(T, 1, D), jnp.float32)
        pool = jnp.asarray(rng.randn(1, total, psz, D), jnp.float32)
        pg = jnp.asarray([2, 2, 2, 4, 5], jnp.int32)
        off = jnp.asarray([1, 2, 3, 0, 3], jnp.int32)
        out = fused_append_rows(pool, rows, pg, off)
        ref = np.array(pool)
        for t in range(T):
            ref[:, int(pg[t]), int(off[t])] = np.asarray(rows)[t]
        np.testing.assert_array_equal(np.asarray(out)[:, 1:],
                                      ref[:, 1:])


class TestRaggedJit:
    def test_jit_no_retrace_on_data_change(self):
        # the engine's contract: joins/leaves are data changes only
        args1 = _setup(12, 3, 8, 2, 128, 16, 4, seed=7)
        args2 = _setup(12, 3, 8, 2, 128, 16, 4, seed=8)
        f = jax.jit(ragged_paged_attention)
        f(*args1)
        f(*args2)
        assert f._cache_size() == 1
