"""N-gram speculative decoding (serving.spec_decode): drafter and
accept-rule units, engine exactness with batched one-launch
verification, >1 mean accepted tokens per verify step on a repetitive
trace (ISSUE 10 acceptance), and compile-once under spec rows."""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import serving as srv
from paddle_tpu.generation import generate_cached
from paddle_tpu.serving import ServingEngine
from paddle_tpu.serving.spec_decode import accept_length, ngram_draft


def _metric(name):
    fam = srv.metrics().get(name)
    if not fam or not fam["series"]:
        return 0.0
    return fam["series"][0]["value"]


def _solo(model, prompt, max_new):
    out, _ = generate_cached(model, paddle.to_tensor(prompt[None]),
                             max_new_tokens=max_new,
                             decode_strategy="greedy_search")
    return [int(t) for t in out.numpy()[0]]


class TestDrafter:
    def test_recurring_ngram_proposes_followers(self):
        #          [5 6 7] ... [5 6 7] -> propose what followed: 8 9
        ctx = [5, 6, 7, 8, 9, 1, 2, 5, 6, 7]
        assert ngram_draft(ctx, 2) == [8, 9]

    def test_most_recent_occurrence_wins(self):
        # [1 2] occurs twice; the LATER one (followed by 4) is used
        ctx = [1, 2, 3, 0, 1, 2, 4, 9, 1, 2]
        assert ngram_draft(ctx, 1) == [4]

    def test_longest_ngram_tried_first(self):
        # the 1-gram [2] would propose 7, but the 3-gram [9 1 2]
        # (followed by 5) matches and takes precedence
        ctx = [9, 1, 2, 5, 0, 2, 7, 3, 9, 1, 2]
        assert ngram_draft(ctx, 1) == [5]

    def test_self_referential_copy_extends_runs(self):
        # constant tail: the copy source overlaps the drafted tokens
        # (LZ77 style), so a period-1 run drafts all k tokens
        ctx = [3, 1, 4, 7, 7, 7]
        assert ngram_draft(ctx, 4) == [7, 7, 7, 7]
        # period-2 cycle continues the alternation
        ctx2 = [9, 5, 8, 5, 8, 5, 8]
        assert ngram_draft(ctx2, 4) == [5, 8, 5, 8]

    def test_no_match_or_degenerate_returns_empty(self):
        assert ngram_draft([1, 2, 3, 4], 3) == []    # nothing recurs
        assert ngram_draft([1, 2, 3], 0) == []       # k = 0
        assert ngram_draft([], 3) == []
        assert ngram_draft([4], 3) == []

    def test_accept_length_prefix_rule(self):
        assert accept_length([7, 8, 9], [7, 8, 9]) == 3
        assert accept_length([7, 8, 9], [7, 8, 1]) == 2
        assert accept_length([7, 8, 9], [1, 8, 9]) == 0
        assert accept_length([], [5]) == 0


class TestEngineSpecDecode:
    @pytest.fixture(scope="class")
    def model(self):
        from paddle_tpu.models.llama import (LlamaForCausalLM,
                                             llama_tiny_config)
        paddle.seed(0)
        m = LlamaForCausalLM(llama_tiny_config(num_hidden_layers=1))
        m.eval()
        return m

    def _repetitive_prompt(self, model):
        """A prompt whose greedy continuation is repetitive: extend a
        seed prompt with its own greedy output up into the cyclic tail
        tiny greedy models converge to."""
        base = np.asarray([251, 195, 359, 9, 211], np.int32)
        cont = _solo(model, base, 16)
        return np.concatenate([base, np.asarray(cont[:10], np.int32)])

    def test_spec_decode_exact_and_accepts_over_one(self, model):
        # acceptance: > 1 mean accepted tokens per verify step on a
        # repetitive-text trace, output exactly equal to solo greedy
        prompt = self._repetitive_prompt(model)
        ref = _solo(model, prompt, 12)
        base = {k: _metric(f"serving.spec_decode.{k}")
                for k in ("draft_tokens", "accepted_tokens",
                          "verify_steps")}
        eng = ServingEngine(model, max_slots=1, page_size=4,
                            prefill_chunk=4, spec_decode=4)
        r = eng.add_request(prompt, max_new_tokens=12)
        steps = 0
        while eng.has_work():
            eng.step()
            steps += 1
        out = eng.collect()[r.request_id]
        assert [int(t) for t in out] == ref
        drafted = _metric("serving.spec_decode.draft_tokens") \
            - base["draft_tokens"]
        accepted = _metric("serving.spec_decode.accepted_tokens") \
            - base["accepted_tokens"]
        vsteps = _metric("serving.spec_decode.verify_steps") \
            - base["verify_steps"]
        assert vsteps >= 1 and drafted >= accepted
        assert accepted / vsteps > 1.0
        # accepted drafts emit multiple tokens per launch: fewer engine
        # steps than a token-at-a-time decode would need
        assert steps < len(prompt) // 4 + 12
        assert all(v == 1 for v in eng.program_cache_sizes().values())

    def test_spec_decode_exact_on_mixed_batch(self, model):
        # spec rows coexist with plain decode + chunked prefill in the
        # same ragged launch; every stream stays exact
        V = model.config.vocab_size
        rng = np.random.RandomState(21)
        prompts = [self._repetitive_prompt(model)] + \
            [rng.randint(0, V, rng.randint(4, 9)).astype(np.int32)
             for _ in range(3)]
        eng = ServingEngine(model, max_slots=2, page_size=4,
                            prefill_chunk=4, spec_decode=3)
        reqs = [eng.add_request(p, max_new_tokens=5) for p in prompts]
        out = eng.run_to_completion()
        for p, r in zip(prompts, reqs):
            assert [int(t) for t in out[r.request_id]] \
                == _solo(model, p, 5)
        assert all(v == 1 for v in eng.program_cache_sizes().values())

    def test_rollback_rewrites_rejected_kv(self, model):
        # force drafts that mostly get rejected (cyclic prompt, but the
        # model breaks the cycle): rolled-back KV slots are rewritten
        # and the output still exact-matches
        V = model.config.vocab_size
        rng = np.random.RandomState(33)
        for _ in range(3):
            p = rng.randint(0, V, 6).astype(np.int32)
            prompt = np.concatenate([p, p])       # repetitive PROMPT
            eng = ServingEngine(model, max_slots=1, page_size=4,
                                prefill_chunk=4, spec_decode=4)
            r = eng.add_request(prompt, max_new_tokens=8)
            out = eng.run_to_completion()[r.request_id]
            assert [int(t) for t in out] == _solo(model, prompt, 8)

    def test_spec_zero_is_plain_decode(self, model):
        V = model.config.vocab_size
        rng = np.random.RandomState(44)
        prompt = rng.randint(0, V, 7).astype(np.int32)
        base_drafted = _metric("serving.spec_decode.draft_tokens")
        eng = ServingEngine(model, max_slots=1, page_size=4,
                            prefill_chunk=4, spec_decode=0)
        r = eng.add_request(prompt, max_new_tokens=4)
        out = eng.run_to_completion()[r.request_id]
        assert [int(t) for t in out] == _solo(model, prompt, 4)
        assert _metric("serving.spec_decode.draft_tokens") == base_drafted

    def test_negative_spec_rejected(self, model):
        with pytest.raises(ValueError):
            ServingEngine(model, max_slots=1, spec_decode=-1)
