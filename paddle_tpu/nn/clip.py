"""Gradient clipping (ref: python/paddle/nn/clip.py — ClipGradByGlobalNorm is
the one the LLM recipes depend on; the hybrid-parallel cross-mesh-axis variant
lives in paddle_tpu.distributed.fleet)."""

from __future__ import annotations

from typing import List, Tuple

import jax.numpy as jnp

from ..core.tensor import Tensor

__all__ = ["ClipGradByValue", "ClipGradByNorm", "ClipGradByGlobalNorm",
           "clip_grad_norm_"]


class ClipGradBase:
    def __call__(self, params_grads: List[Tuple[Tensor, Tensor]]):
        raise NotImplementedError


class ClipGradByValue(ClipGradBase):
    def __init__(self, max, min=None):
        self.max = float(max)
        self.min = float(min) if min is not None else -float(max)

    def __call__(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None:
                out.append((p, g))
                continue
            out.append((p, Tensor(jnp.clip(g._data, self.min, self.max))))
        return out


class ClipGradByNorm(ClipGradBase):
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def __call__(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None:
                out.append((p, g))
                continue
            norm = jnp.sqrt(jnp.sum(jnp.square(g._data.astype(jnp.float32))))
            scale = jnp.minimum(self.clip_norm / jnp.maximum(norm, 1e-12), 1.0)
            out.append((p, Tensor((g._data.astype(jnp.float32) * scale
                                   ).astype(g._data.dtype))))
        return out


class ClipGradByGlobalNorm(ClipGradBase):
    def __init__(self, clip_norm=1.0, group_name="default_group",
                 auto_skip_clip=False):
        self.clip_norm = float(clip_norm)

    def __call__(self, params_grads):
        # ParamAttr(need_clip=False) excludes a param from both the
        # global norm and the rescale (paddle semantics)
        def clippable(p):
            return getattr(p, "need_clip", True)
        sq = None
        for p, g in params_grads:
            if g is None or not clippable(p):
                continue
            s = jnp.sum(jnp.square(g._data.astype(jnp.float32)))
            sq = s if sq is None else sq + s
        if sq is None:
            return params_grads
        global_norm = jnp.sqrt(sq)
        scale = self.clip_norm / jnp.maximum(global_norm, self.clip_norm)
        out = []
        for p, g in params_grads:
            if g is None or not clippable(p):
                out.append((p, g))
                continue
            out.append((p, Tensor((g._data.astype(jnp.float32) * scale
                                   ).astype(g._data.dtype))))
        return out


def clip_grad_norm_(parameters, max_norm, norm_type=2.0,
                    error_if_nonfinite=False):
    params = [p for p in parameters if p.grad is not None]
    if not params:
        return Tensor(jnp.zeros(()))
    if norm_type == float("inf"):
        total = jnp.max(jnp.stack(
            [jnp.max(jnp.abs(p.grad._data)) for p in params]))
    else:
        total = jnp.sum(jnp.stack(
            [jnp.sum(jnp.abs(p.grad._data.astype(jnp.float32)) ** norm_type)
             for p in params])) ** (1.0 / norm_type)
    scale = max_norm / jnp.maximum(total, 1e-6)
    scale = jnp.minimum(scale, 1.0)
    for p in params:
        p.grad._data = (p.grad._data.astype(jnp.float32) * scale).astype(
            p.grad._data.dtype)
    return Tensor(total)
