"""Worker for the executed multi-host TRAIN test (VERDICT r4 item 1):
launched by python -m paddle_tpu.distributed.launch on 2 simulated hosts;
after mh_bootstrap the GLOBAL mesh spans 8 devices and the hybrid train
step's collectives (grad psum / TP all-reduce / pipeline ppermute / ZeRO
all-gather) cross the OS-process boundary."""
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import mh_bootstrap  # noqa: F401  (env + jax.distributed init, pre-jax)
from mh_train_common import run_train  # noqa: E402

losses = run_train(os.environ["MH_TRAIN_CFG"])
with open(os.path.join(os.environ["MH_OUT"],
                       f"losses.{os.environ['PADDLE_TRAINER_ID']}.json"),
          "w") as f:
    json.dump(losses, f)
print("TRAIN OK", losses, flush=True)
