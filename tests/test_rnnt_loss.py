"""RNN-Transducer loss (ref: warprnnt external / paddle.nn.functional
rnnt_loss). Oracle: hand-rolled numpy forward algorithm over the (T, U)
lattice."""

import numpy as np

import paddle_tpu as paddle
from paddle_tpu.nn import functional as F


def _np_rnnt(logits, labels, t_len, u_len, blank=0):
    """Reference forward algorithm, one sequence at a time."""
    B = logits.shape[0]
    losses = []
    for b in range(B):
        T, U = int(t_len[b]), int(u_len[b])
        lp = logits[b] - np.log(
            np.exp(logits[b]).sum(-1, keepdims=True))  # log softmax
        alpha = np.full((T, U + 1), -np.inf)
        alpha[0, 0] = 0.0
        for u in range(1, U + 1):
            alpha[0, u] = alpha[0, u - 1] + lp[0, u - 1, labels[b, u - 1]]
        for t in range(1, T):
            alpha[t, 0] = alpha[t - 1, 0] + lp[t - 1, 0, blank]
            for u in range(1, U + 1):
                a = alpha[t - 1, u] + lp[t - 1, u, blank]
                bterm = alpha[t, u - 1] + lp[t, u - 1, labels[b, u - 1]]
                alpha[t, u] = np.logaddexp(a, bterm)
        losses.append(-(alpha[T - 1, U] + lp[T - 1, U, blank]))
    return np.asarray(losses, np.float32)


def _case(B=2, T=5, U=3, V=6, seed=0):
    rng = np.random.RandomState(seed)
    logits = rng.randn(B, T, U + 1, V).astype(np.float32)
    labels = rng.randint(1, V, (B, U)).astype(np.int32)
    t_len = np.array([T] * B, np.int32)
    u_len = np.array([U] * B, np.int32)
    return logits, labels, t_len, u_len


def test_matches_numpy_forward():
    logits, labels, t_len, u_len = _case()
    ref = _np_rnnt(logits, labels, t_len, u_len)
    got = F.rnnt_loss(paddle.to_tensor(logits), paddle.to_tensor(labels),
                      paddle.to_tensor(t_len), paddle.to_tensor(u_len),
                      reduction="none")
    np.testing.assert_allclose(got.numpy(), ref, rtol=1e-4, atol=1e-4)
    mean = F.rnnt_loss(paddle.to_tensor(logits), paddle.to_tensor(labels),
                       paddle.to_tensor(t_len), paddle.to_tensor(u_len))
    np.testing.assert_allclose(float(mean.numpy()), ref.mean(), rtol=1e-4)


def test_variable_lengths():
    logits, labels, t_len, u_len = _case(B=3, T=6, U=4, seed=1)
    t_len = np.array([6, 4, 5], np.int32)
    u_len = np.array([4, 2, 3], np.int32)
    ref = _np_rnnt(logits, labels, t_len, u_len)
    got = F.rnnt_loss(paddle.to_tensor(logits), paddle.to_tensor(labels),
                      paddle.to_tensor(t_len), paddle.to_tensor(u_len),
                      reduction="none")
    np.testing.assert_allclose(got.numpy(), ref, rtol=1e-4, atol=1e-4)


def test_gradient_finite_difference():
    logits, labels, t_len, u_len = _case(B=1, T=3, U=2, V=4, seed=2)
    lt = paddle.to_tensor(logits)
    lt.stop_gradient = False
    loss = F.rnnt_loss(lt, paddle.to_tensor(labels),
                       paddle.to_tensor(t_len), paddle.to_tensor(u_len),
                       reduction="sum")
    loss.backward()
    g = lt.grad.numpy()
    eps = 1e-3
    rng = np.random.RandomState(3)
    for _ in range(5):
        i = tuple(rng.randint(0, s) for s in logits.shape)
        lp = logits.copy(); lp[i] += eps
        lm = logits.copy(); lm[i] -= eps
        fd = (_np_rnnt(lp, labels, t_len, u_len).sum()
              - _np_rnnt(lm, labels, t_len, u_len).sum()) / (2 * eps)
        np.testing.assert_allclose(g[i], fd, rtol=2e-2, atol=2e-3)


def test_perfect_alignment_low_loss():
    """Logits hugely favoring the correct emit/blank path → loss ≈ 0."""
    B, T, U, V = 1, 4, 2, 5
    labels = np.array([[2, 3]], np.int32)
    logits = np.zeros((B, T, U + 1, V), np.float32)
    big = 20.0
    # emit the two labels at t=0, then blanks to the end
    logits[0, 0, 0, 2] = big
    logits[0, 0, 1, 3] = big
    for t in range(T):
        logits[0, t, 2, 0] = big
    logits[0, 1, 2, 0] = big
    loss = F.rnnt_loss(paddle.to_tensor(logits),
                       paddle.to_tensor(labels),
                       paddle.to_tensor(np.array([T], np.int32)),
                       paddle.to_tensor(np.array([U], np.int32)))
    assert float(loss.numpy()) < 0.5


def test_fastemit_scales_gradients_not_loss():
    """Review regression: fastemit_lambda reweights EMIT gradients by
    (1+lambda) and leaves the forward loss unchanged."""
    logits, labels, t_len, u_len = _case(B=1, T=4, U=2, V=5, seed=5)

    def run(lam):
        lt = paddle.to_tensor(logits)
        lt.stop_gradient = False
        loss = F.rnnt_loss(lt, paddle.to_tensor(labels),
                           paddle.to_tensor(t_len),
                           paddle.to_tensor(u_len),
                           fastemit_lambda=lam, reduction="sum")
        loss.backward()
        return float(loss.numpy()), lt.grad.numpy()

    l0, g0 = run(0.0)
    l1, g1 = run(0.5)
    np.testing.assert_allclose(l1, l0, rtol=1e-6)   # loss unchanged
    assert np.abs(g1 - g0).max() > 1e-5             # gradients changed
