"""Tensor creation ops (ref surface: python/paddle/tensor/creation.py)."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dispatch import apply
from ..core.dtypes import convert_dtype, get_default_dtype
from ..core.tensor import Tensor, to_tensor

__all__ = [
    "to_tensor", "zeros", "ones", "full", "empty", "zeros_like", "ones_like",
    "full_like", "empty_like", "arange", "linspace", "logspace", "eye",
    "meshgrid", "diag", "diagflat", "tril", "triu", "assign", "clone",
    "tril_indices", "triu_indices", "complex",
]


def _shape(shape):
    if isinstance(shape, Tensor):
        return tuple(int(x) for x in np.asarray(shape._data))
    if isinstance(shape, int):
        return (shape,)
    return tuple(int(s) for s in shape)


def _dt(dtype, default=None):
    d = convert_dtype(dtype)
    if d is None:
        d = default if default is not None else get_default_dtype()
    return d


def zeros(shape, dtype=None, name=None) -> Tensor:
    return Tensor(jnp.zeros(_shape(shape), _dt(dtype)))


def ones(shape, dtype=None, name=None) -> Tensor:
    return Tensor(jnp.ones(_shape(shape), _dt(dtype)))


def full(shape, fill_value, dtype=None, name=None) -> Tensor:
    if isinstance(fill_value, Tensor):
        fill_value = fill_value._data
    if dtype is None and isinstance(fill_value, bool):
        dtype = "bool"
    elif dtype is None and isinstance(fill_value, int):
        dtype = "int64"
    return Tensor(jnp.full(_shape(shape), fill_value, _dt(dtype)))


def empty(shape, dtype=None, name=None) -> Tensor:
    # XLA has no uninitialized alloc; zeros is the TPU-native equivalent
    return zeros(shape, dtype, name)


def zeros_like(x, dtype=None, name=None) -> Tensor:
    return Tensor(jnp.zeros_like(x._data, dtype=convert_dtype(dtype)))


def ones_like(x, dtype=None, name=None) -> Tensor:
    return Tensor(jnp.ones_like(x._data, dtype=convert_dtype(dtype)))


def full_like(x, fill_value, dtype=None, name=None) -> Tensor:
    return Tensor(jnp.full_like(x._data, fill_value, dtype=convert_dtype(dtype)))


def empty_like(x, dtype=None, name=None) -> Tensor:
    return zeros_like(x, dtype, name)


def arange(start=0, end=None, step=1, dtype=None, name=None) -> Tensor:
    def _v(v):
        return v._data if isinstance(v, Tensor) else v
    start, end, step = _v(start), _v(end), _v(step)
    if end is None:
        start, end = 0, start
    if dtype is None:
        vals = [v for v in (start, end, step)]
        dtype = "float32" if any(isinstance(v, float) for v in vals) else "int64"
    return Tensor(jnp.arange(start, end, step, dtype=convert_dtype(dtype)))


def linspace(start, stop, num, dtype=None, name=None) -> Tensor:
    return Tensor(jnp.linspace(
        start._data if isinstance(start, Tensor) else start,
        stop._data if isinstance(stop, Tensor) else stop,
        int(num), dtype=_dt(dtype)))


def logspace(start, stop, num, base=10.0, dtype=None, name=None) -> Tensor:
    return Tensor(jnp.logspace(
        start._data if isinstance(start, Tensor) else start,
        stop._data if isinstance(stop, Tensor) else stop,
        int(num), base=base, dtype=_dt(dtype)))


def eye(num_rows, num_columns=None, dtype=None, name=None) -> Tensor:
    return Tensor(jnp.eye(int(num_rows),
                          None if num_columns is None else int(num_columns),
                          dtype=_dt(dtype)))


def meshgrid(*args, **kwargs):
    tensors = args[0] if len(args) == 1 and isinstance(args[0], (list, tuple)) else args
    outs = jnp.meshgrid(*[t._data for t in tensors], indexing="ij")
    return [Tensor(o) for o in outs]


def diag(x, offset=0, padding_value=0, name=None) -> Tensor:
    def impl(a):
        if a.ndim == 1:
            out = jnp.diag(a, k=offset)
            if padding_value != 0:
                mask = jnp.diag(jnp.ones_like(a, dtype=bool), k=offset)
                out = jnp.where(mask, out, jnp.asarray(padding_value, a.dtype))
            return out
        return jnp.diagonal(a, offset=offset)
    return apply("diag", impl, [x])


def diagflat(x, offset=0, name=None) -> Tensor:
    return apply("diagflat", lambda a: jnp.diagflat(a, k=offset), [x])


def tril(x, diagonal=0, name=None) -> Tensor:
    return apply("tril", lambda a: jnp.tril(a, k=diagonal), [x])


def triu(x, diagonal=0, name=None) -> Tensor:
    return apply("triu", lambda a: jnp.triu(a, k=diagonal), [x])


def tril_indices(row, col=None, offset=0, dtype="int64", name=None) -> Tensor:
    col = row if col is None else col
    r, c = np.tril_indices(row, offset, col)
    return Tensor(jnp.asarray(np.stack([r, c]), dtype=convert_dtype(dtype)))


def triu_indices(row, col=None, offset=0, dtype="int64", name=None) -> Tensor:
    col = row if col is None else col
    r, c = np.triu_indices(row, offset, col)
    return Tensor(jnp.asarray(np.stack([r, c]), dtype=convert_dtype(dtype)))


def assign(x, output=None) -> Tensor:
    src = x if isinstance(x, Tensor) else Tensor(jnp.asarray(x))
    if output is None:
        return apply("assign", lambda a: a + jnp.zeros((), a.dtype), [src])
    output.set_value(src)
    return output


def clone(x, name=None) -> Tensor:
    return x.clone()


def complex(real, imag, name=None) -> Tensor:
    return apply("complex", lambda r, i: jax.lax.complex(r, i), [real, imag])
