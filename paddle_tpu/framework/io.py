"""paddle.save / paddle.load parity (ref: python/paddle/framework/io.py).

Pickle-protocol state dicts with tensors converted to numpy on save and
restored as device tensors on load; nested containers and >4GB tensors are
handled by pickle protocol 4. Sharding-aware distributed checkpointing lives
in paddle_tpu.distributed.checkpoint (orbax/tensorstore-backed).
"""

from __future__ import annotations

import json
import os
import pickle
from typing import Any, Optional

import jax.numpy as jnp
import numpy as np

from .. import resilience as _res
from ..core.tensor import Tensor

__all__ = ["save", "load", "verify"]

_PROTOCOL = 4
_META_SUFFIX = ".meta.json"


class _TensorPayload:
    """Tag wrapper so load() knows which ndarrays were Tensors."""

    __slots__ = ("array", "stop_gradient")

    def __init__(self, array: np.ndarray, stop_gradient: bool):
        self.array = array
        self.stop_gradient = stop_gradient


def _pack(obj: Any) -> Any:
    if isinstance(obj, Tensor):
        a = np.asarray(obj._data)
        # bfloat16 has no numpy pickle support everywhere; view as uint16
        if obj._data.dtype == jnp.bfloat16:
            return _TensorPayload(a.view(np.uint16), obj.stop_gradient), "bf16"
        return _TensorPayload(a, obj.stop_gradient)
    if isinstance(obj, dict):
        return {k: _pack(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = type(obj)
        return t(_pack(v) for v in obj)
    return obj


def _unpack(obj: Any) -> Any:
    if isinstance(obj, tuple) and len(obj) == 2 and isinstance(obj[0], _TensorPayload) \
            and obj[1] == "bf16":
        payload = obj[0]
        return Tensor(jnp.asarray(payload.array).view(jnp.bfloat16),
                      stop_gradient=payload.stop_gradient)
    if isinstance(obj, _TensorPayload):
        return Tensor(jnp.asarray(obj.array), stop_gradient=obj.stop_gradient)
    if isinstance(obj, dict):
        return {k: _unpack(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = type(obj)
        return t(_unpack(v) for v in obj)
    return obj


def save(obj: Any, path: str, protocol: int = _PROTOCOL,
         retries: Optional[int] = None,
         backoff: Optional[float] = None) -> None:
    """Atomic, integrity-tracked save: the pickle is written via
    temp-file + os.replace (a crash mid-save never truncates an existing
    checkpoint), its crc32 is recorded in a ``<path>.meta.json`` sidecar
    that load() verifies, and write failures are retried with bounded
    backoff (FLAGS_ckpt_retries / FLAGS_ckpt_retry_backoff)."""
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    payload = pickle.dumps(_pack(obj), protocol=protocol)
    meta = json.dumps({"crc32": _res.crc32_bytes(payload),
                       "bytes": len(payload)}).encode()

    def _attempt():
        rule = _res.inject("ckpt_write_fail", path=os.path.basename(path))
        if rule is not None:
            raise _res.InjectedFault(
                f"ckpt_write_fail injected for {path}", rule)
        _res.atomic_write(path, payload)
        _res.atomic_write(path + _META_SUFFIX, meta)

    _res.retry_io(_attempt, what=f"save({path})", retries=retries,
                  backoff=backoff)


def verify(path: str) -> bool:
    """True when `path` matches its integrity sidecar (or has no sidecar
    — legacy checkpoints verify vacuously); False on mismatch."""
    meta_path = path + _META_SUFFIX
    if not os.path.exists(meta_path):
        return os.path.exists(path)
    try:
        with open(meta_path) as f:
            meta = json.load(f)
        return _res.crc32_file(path) == int(meta["crc32"])
    except (OSError, ValueError, KeyError):
        return False


def load(path: str, return_numpy: bool = False,
         verify_integrity: bool = True) -> Any:
    with open(path, "rb") as f:
        data = f.read()
    meta_path = path + _META_SUFFIX
    if verify_integrity and os.path.exists(meta_path):
        with open(meta_path) as f:
            meta = json.load(f)
        injected = _res.inject("ckpt_read_corrupt",
                               path=os.path.basename(path)) is not None
        if injected or _res.crc32_bytes(data) != int(meta["crc32"]):
            raise _res.CheckpointCorrupt(
                f"{path}: checksum mismatch vs {meta_path}"
                + (" (injected)" if injected else ""))
    obj = pickle.loads(data)
    out = _unpack(obj)
    if return_numpy:
        def to_np(o):
            if isinstance(o, Tensor):
                return o.numpy()
            if isinstance(o, dict):
                return {k: to_np(v) for k, v in o.items()}
            if isinstance(o, (list, tuple)):
                return type(o)(to_np(v) for v in o)
            return o
        return to_np(out)
    return out
