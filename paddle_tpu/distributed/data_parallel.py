"""paddle.DataParallel parity (ref: python/paddle/base/dygraph/parallel.py
DataParallel — the dygraph DP wrapper).

TPU-native reading: inside a jitted step on a mesh with a `dp` axis,
gradient synchronization is GSPMD's job (the batch dim is sharded and
XLA inserts the grad psum). This wrapper therefore (a) delegates forward
to the wrapped layers, (b) replicates parameters onto the current mesh,
and (c) for the EAGER path offers the reference's scale_loss /
apply_collective_grads pair built on the eager shard_map collectives
(distributed.collective.all_reduce)."""

from __future__ import annotations

from typing import Optional

from ..nn import Layer
from .mesh import get_mesh

__all__ = ["DataParallel"]


class DataParallel(Layer):
    def __init__(self, layers: Layer, strategy=None, comm_buffer_size=25,
                 last_comm_buffer_size=1, find_unused_parameters=False,
                 group=None):
        super().__init__()
        self._layers = layers
        self._group = group
        self.find_unused_parameters = find_unused_parameters

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def _dp_world(self) -> int:
        mesh = get_mesh()
        if mesh is not None and "dp" in mesh.axis_names:
            return int(mesh.shape["dp"])
        import jax
        return jax.process_count()

    def scale_loss(self, loss):
        """Divide the loss by the DP world size so summed grads average
        (ref: DataParallel.scale_loss)."""
        n = self._dp_world()
        return loss if n <= 1 else loss / float(n)

    def apply_collective_grads(self):
        """All-reduce every parameter gradient over the dp axis (eager
        path; the jitted path gets this from GSPMD automatically)."""
        n = self._dp_world()
        if n <= 1:
            return
        from .collective import all_reduce
        for p in self._layers.parameters():
            if p.grad is not None:
                all_reduce(p.grad, group="dp")

    # passthroughs (paddle API surface)
    def state_dict(self, *args, **kwargs):
        return self._layers.state_dict(*args, **kwargs)

    def set_state_dict(self, state_dict, *args, **kwargs):
        return self._layers.set_state_dict(state_dict, *args, **kwargs)

    def parameters(self, include_sublayers: bool = True):
        return self._layers.parameters(include_sublayers)
