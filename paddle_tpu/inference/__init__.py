"""Inference engine — paddle_infer parity (ref: paddle/fluid/inference/
api/analysis_predictor.cc + paddle/fluid/inference/api/paddle_inference_api.h,
SURVEY §2.1 'Inference engine' row and §3.6).

TPU-native substitution: the reference's AnalysisPredictor loads a
ProgramDesc, runs ~200 IR fusion passes, and optionally offloads subgraphs
to TensorRT. Here the saved artifact is a `jax.export` serialized program
(StableHLO under the hood): XLA IS the analysis/fusion pipeline, and the
compiled executable is cached by PJRT. The Config/Predictor/Tensor-handle
API surface is preserved so deployment code ports directly.

Artifact format (written by `paddle_tpu.jit.save(layer, path, input_spec)`):
  path.pdparams       — weights (paddle.save format)
  path.jaxexport      — serialized jax.export program (weights baked in)
  path.stablehlo.txt  — human-readable StableHLO (debug / judge parity)
"""

from __future__ import annotations

import os
import time
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .. import observability as _obs
from .. import resilience as _res

__all__ = ["Config", "Predictor", "create_predictor", "PredictorTensor"]

# serving-engine metrics (ISSUE 1): queue wait is the staging-to-execution
# gap — the time between the FIRST copy_from_cpu of a request's inputs and
# the run() that consumes them (the paddle_infer feed/run protocol)
_Q_WAIT = _obs.registry().histogram(
    "pt_serving_queue_wait_seconds",
    "staging (copy_from_cpu) to run() latency per request")
_RUN_S = _obs.registry().histogram(
    "pt_serving_run_seconds", "Predictor.run wall time")
_RUN_BATCH = _obs.registry().histogram(
    "pt_serving_run_batch_size", "leading input dim per Predictor.run",
    buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256, 512))
_RUN_TOTAL = _obs.registry().counter(
    "pt_serving_run_total", "Predictor.run calls")


class Config:
    """paddle_infer.Config parity (the knobs that are meaningful on TPU;
    GPU/TensorRT/oneDNN toggles are accepted and recorded but are no-ops —
    XLA owns graph optimization)."""

    def __init__(self, prog_file: Optional[str] = None,
                 params_file: Optional[str] = None):
        # paddle accepts Config(model_dir) or Config(prog, params); we take
        # the artifact prefix written by jit.save
        self._model_prefix = prog_file
        self._device = "tpu"
        self._device_id = 0
        self._enable_memory_optim = True
        self._switches: Dict[str, bool] = {}
        self._deadline_s: Optional[float] = None
        self._admission: Optional[tuple] = None
        self._prefix_cache: Optional[bool] = None

    def set_deadline(self, seconds: Optional[float]):
        """Per-request wall-clock budget for Predictor.run: an expired
        budget yields a typed resilience.TimeoutResult (never a hang)."""
        self._deadline_s = float(seconds) if seconds else None

    def set_admission(self, max_inflight: int, queue_timeout_s: float = 0.0):
        """Queue-admission backpressure: at most max_inflight run() calls
        execute concurrently (shared across clone()s); a request that
        cannot get a slot within queue_timeout_s raises
        resilience.Overloaded instead of queueing unboundedly."""
        self._admission = (int(max_inflight), float(queue_timeout_s))

    def set_prefix_cache(self, enabled: bool):
        """Toggle the serving engine's global radix prefix cache
        (cross-request KV reuse of identical prompt prefixes). Default
        on; exactness is unaffected either way — the cache only skips
        recomputing KV that is bit-identical by construction."""
        self._prefix_cache = bool(enabled)

    def set_prog_file(self, path: str):
        self._model_prefix = path

    def prog_file(self) -> Optional[str]:
        return self._model_prefix

    def enable_use_gpu(self, memory_pool_init_size_mb=100, device_id=0):
        # accepted for API compat; the device is whatever jax.devices() is
        self._device = "gpu"
        self._device_id = device_id

    def disable_gpu(self):
        self._device = "cpu"

    def enable_xpu(self, *a, **k):
        self._device = "xpu"

    def use_gpu(self) -> bool:
        return self._device == "gpu"

    def enable_memory_optim(self):
        self._enable_memory_optim = True

    def switch_ir_optim(self, flag: bool = True):
        self._switches["ir_optim"] = flag

    def enable_tensorrt_engine(self, *a, **k):
        # documented non-goal: TensorRT is NVIDIA tech; XLA compiles the
        # whole program on TPU (docs/PARITY.md inference row)
        self._switches["tensorrt"] = False

    def summary(self) -> str:
        return (f"Config(model={self._model_prefix!r}, device={self._device},"
                f" switches={self._switches})")


class PredictorTensor:
    """Zero-copy-style IO handle (paddle_infer.Tensor parity):
    copy_from_cpu / copy_to_cpu / shape / reshape."""

    def __init__(self, name: str, spec: jax.ShapeDtypeStruct):
        self.name = name
        self._spec = spec
        self._value: Optional[jnp.ndarray] = None
        self._staged_ts: Optional[float] = None

    def reshape(self, shape: Sequence[int]):
        self._spec = jax.ShapeDtypeStruct(tuple(shape), self._spec.dtype)

    def shape(self) -> List[int]:
        src = self._value if self._value is not None else self._spec
        return list(src.shape)

    def copy_from_cpu(self, data: np.ndarray):
        arr = jnp.asarray(data)
        if arr.dtype != self._spec.dtype:
            arr = arr.astype(self._spec.dtype)
        self._value = arr
        if _obs.enabled():
            self._staged_ts = time.perf_counter()

    def copy_to_cpu(self) -> np.ndarray:
        if self._value is None:
            raise RuntimeError(f"output {self.name!r} not computed yet — "
                               f"call predictor.run() first")
        return np.asarray(self._value)

    # numpy-protocol sugar
    def numpy(self) -> np.ndarray:
        return self.copy_to_cpu()


class Predictor:
    """paddle_infer.Predictor parity over a jax.export artifact."""

    def __init__(self, config: Config):
        self._config = config
        self._gate = _res.AdmissionGate(*config._admission) \
            if config._admission else None
        prefix = config.prog_file()
        if prefix is None:
            raise ValueError("Config needs the jit.save artifact prefix")
        path = prefix + ".jaxexport"
        if not os.path.exists(path):
            raise FileNotFoundError(
                f"{path} not found — export with paddle_tpu.jit.save("
                f"layer, {prefix!r}, input_spec=[...])")
        from ..jit import _deserialize_exported
        self._exported = _deserialize_exported(path)
        self._in_specs = [jax.ShapeDtypeStruct(s.shape, s.dtype)
                          for s in self._exported.in_avals]
        self._input_names = [f"x{i}" for i in range(len(self._in_specs))]
        self._inputs = {n: PredictorTensor(n, s)
                        for n, s in zip(self._input_names, self._in_specs)}
        n_out = len(self._exported.out_avals)
        self._output_names = [f"out{i}" for i in range(n_out)]
        self._outputs = {
            n: PredictorTensor(n, jax.ShapeDtypeStruct(s.shape, s.dtype))
            for n, s in zip(self._output_names, self._exported.out_avals)}
        self._call = jax.jit(self._exported.call)
        # FLAGS_use_fusion_compiler: run the program through the C++
        # StableHLO fusion pass pipeline (jit/fusion_cc.py — the CINN
        # ApplyCinnPass analog on the inference path); falls back to the
        # plain jit path when nothing fuses or the pass is unavailable
        from ..flags import get_flags
        if get_flags("FLAGS_use_fusion_compiler")[
                "FLAGS_use_fusion_compiler"]:
            try:
                from ..jit import fusion_cc
                # ShapeDtypeStructs: lowering needs no device buffers
                fused = fusion_cc.fuse_compile(self._exported.call,
                                               *self._in_specs)
                if fused.n_fused:
                    self._call = fused
            except Exception as e:  # explicit opt-in -> observable fallback
                import warnings
                warnings.warn(
                    f"FLAGS_use_fusion_compiler: C++ fusion pipeline "
                    f"unavailable ({type(e).__name__}: {e}); running the "
                    f"plain jit path", RuntimeWarning)

    # --- paddle_infer API surface ---
    def get_input_names(self) -> List[str]:
        return list(self._input_names)

    def get_input_handle(self, name: str) -> PredictorTensor:
        return self._inputs[name]

    def get_output_names(self) -> List[str]:
        return list(self._output_names)

    def get_output_handle(self, name: str) -> PredictorTensor:
        return self._outputs[name]

    def run(self, inputs: Optional[Sequence[np.ndarray]] = None,
            deadline_s: Optional[float] = None):
        """Execute. Either feed via get_input_handle().copy_from_cpu()
        then run(), or pass arrays positionally (newer paddle_infer.run).

        Degradation contract (ISSUE 2): with a deadline (per-call
        ``deadline_s`` or Config.set_deadline) an over-budget request
        returns a falsy resilience.TimeoutResult instead of hanging —
        the executable dispatch is atomic, so the budget is enforced at
        the dispatch boundaries; with Config.set_admission, a request
        that cannot get an execution slot raises resilience.Overloaded."""
        budget = deadline_s if deadline_s is not None \
            else self._config._deadline_s
        dl = _res.Deadline(budget) if budget else None
        if self._gate is None:
            return self._run_inner(inputs, dl)
        with self._gate.admit():
            return self._run_inner(inputs, dl)

    def _run_inner(self, inputs, dl):
        if dl is not None and dl.expired():
            # spent the whole budget queueing — don't dispatch at all
            _res.deadline_miss()
            return _res.TimeoutResult(kind="predictor",
                                      budget_s=dl.budget_s,
                                      elapsed_s=dl.elapsed_s)
        if inputs is not None:
            if len(inputs) != len(self._input_names):
                raise ValueError(
                    f"run() got {len(inputs)} inputs; the exported program "
                    f"takes {len(self._input_names)}")
            for n, a in zip(self._input_names, inputs):
                self._inputs[n].copy_from_cpu(np.asarray(a))
        args = []
        for n in self._input_names:
            v = self._inputs[n]._value
            if v is None:
                raise RuntimeError(f"input {n!r} not set")
            args.append(v)
        mx = _obs.enabled()
        if mx:
            _RUN_TOTAL.inc()
            staged = [self._inputs[n]._staged_ts for n in self._input_names]
            staged = [s for s in staged if s is not None]
            t_run = time.perf_counter()
            if staged:
                _Q_WAIT.observe(t_run - min(staged))
                for n in self._input_names:
                    self._inputs[n]._staged_ts = None
            if args and getattr(args[0], "ndim", 0):
                _RUN_BATCH.observe(int(args[0].shape[0]))
        outs = self._call(*args)
        if mx:
            _RUN_S.observe(time.perf_counter() - t_run)
        if not isinstance(outs, (tuple, list)):
            outs = (outs,)
        flat = jax.tree_util.tree_leaves(outs)
        for n, o in zip(self._output_names, flat):
            self._outputs[n]._value = o
        result = [np.asarray(o) for o in flat] if inputs is not None else None
        if dl is not None and dl.expired():
            # the dispatch finished but blew the budget: typed miss with
            # the full outputs attached (handles are populated either way)
            _res.deadline_miss()
            return _res.TimeoutResult(kind="predictor",
                                      budget_s=dl.budget_s,
                                      elapsed_s=dl.elapsed_s,
                                      completed=len(flat), partial=result)
        return result

    def clone(self) -> "Predictor":
        """Independent predictor over the same compiled program (the
        paddle_infer pattern for per-thread serving): shares the executable
        AND the admission gate (concurrency is a process-wide budget),
        gets fresh input AND output handles."""
        new = object.__new__(Predictor)
        new.__dict__ = dict(self.__dict__)
        new._inputs = {n: PredictorTensor(n, s) for n, s in
                       zip(self._input_names, self._in_specs)}
        new._outputs = {
            n: PredictorTensor(n, jax.ShapeDtypeStruct(s.shape, s.dtype))
            for n, s in zip(self._output_names, self._exported.out_avals)}
        return new


def create_predictor(config: Config) -> Predictor:
    return Predictor(config)
