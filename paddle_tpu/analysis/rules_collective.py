"""PC201: collective-order divergence inside ``shard_map`` regions.

A collective (``psum``/``all_gather``/...) is a *program-order* rendezvous:
every rank must issue the same collectives in the same order or the mesh
deadlocks — the exact failure PR 3's runtime watchdog can only catch
after the fact. The static shape of that bug is a collective issued under
a branch inside a function that runs as a ``shard_map`` body (or anything
it calls): a Python ``if``/``while`` around a collective, or a collective
inside a ``lax.cond``/``switch`` branch function, makes the issue order
data-dependent.

The region is built exactly like the traced region: functions passed to
``shard_map(...)`` plus everything reachable from them through the call
graph. All shipped collective wrappers in ``distributed/collective.py``
keep their ``fn`` bodies straight-line — which is the contract this rule
enforces.
"""

from __future__ import annotations

import ast
from typing import List, Set

from .callgraph import PackageIndex, _last_name, walk_shallow
from .model import Config, Finding, register_rule

register_rule("PC201", "collective issued under a branch inside a "
                       "shard_map region (cross-rank deadlock shape)",
              severity="error", module=__name__)

#: communicating primitives — axis_index etc. are local and excluded
COLLECTIVES = {"psum", "pmax", "pmin", "pmean", "all_gather",
               "psum_scatter", "all_to_all", "ppermute", "pshuffle",
               "pbroadcast", "reduce_scatter_p", "all_gather_invariant"}

_BRANCH_COMBINATORS = {"cond", "switch"}


def _unparse(node: ast.AST, limit: int = 60) -> str:
    try:
        s = ast.unparse(node)
    except Exception:  # pragma: no cover
        s = type(node).__name__
    s = " ".join(s.split())
    return s if len(s) <= limit else s[: limit - 3] + "..."


def _shard_map_region(index: PackageIndex) -> Set[str]:
    roots: Set[str] = set()
    for mi in index.modules.values():
        for fi_or_none, call in index._all_calls(mi):
            if _last_name(call.func) != "shard_map":
                continue
            for arg in list(call.args) + [kw.value for kw in call.keywords
                                          if kw.arg in (None, "f")]:
                roots |= index._direct_func_keys(mi, fi_or_none, arg)
    return index.reachable_from(roots)


def _branch_fn_keys(index: PackageIndex, region: Set[str]) -> Set[str]:
    """Functions passed as branches to lax.cond/lax.switch from inside
    the region — their whole body is conditionally executed."""
    out: Set[str] = set()
    for key in region:
        fi = index.functions.get(key)
        if fi is None:
            continue
        mi = index.modules[fi.modname]
        for _, bare, call in fi.calls:
            if bare not in _BRANCH_COMBINATORS:
                continue
            for arg in list(call.args[1:]) + [kw.value
                                              for kw in call.keywords]:
                out |= index._direct_func_keys(mi, fi, arg)
    return out


def _collective_calls(node: ast.AST) -> List[ast.Call]:
    return [n for n in walk_shallow(node)
            if isinstance(n, ast.Call)
            and _last_name(n.func) in COLLECTIVES]


def run(index: PackageIndex, cfg: Config) -> List[Finding]:
    findings: List[Finding] = []
    if not cfg.wants("PC201"):
        return findings
    region = _shard_map_region(index)
    branch_fns = _branch_fn_keys(index, region)

    def report(fi, mi, call: ast.Call, how: str) -> None:
        name = _last_name(call.func)
        findings.append(Finding(
            "PC201", "error", mi.rel, call.lineno, call.col_offset,
            fi.qualname,
            f"collective `{name}` issued {how} inside a shard_map "
            f"region — ranks that take a different path skip the "
            f"rendezvous and the mesh deadlocks",
            hint="hoist the collective out of the branch (compute a "
                 "masked/neutral operand instead), or branch on a "
                 "value provably uniform across ranks",
            detail=f"branch-collective:{name}:{_unparse(call, 40)}"))

    for key in sorted(branch_fns):
        fi = index.functions.get(key)
        if fi is None:
            continue
        mi = index.modules[fi.modname]
        node = (ast.Module(body=[ast.Expr(fi.node.body)], type_ignores=[])
                if isinstance(fi.node, ast.Lambda) else fi.node)
        for call in _collective_calls(node):
            report(fi, mi, call, "from a lax.cond/switch branch function")

    for key in sorted(region - branch_fns):
        fi = index.functions.get(key)
        if fi is None or isinstance(fi.node, ast.Lambda):
            continue
        mi = index.modules[fi.modname]

        def visit(node: ast.AST, in_branch: bool) -> None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda, ast.ClassDef)):
                return  # nested scope: its own FunctionInfo
            if isinstance(node, ast.Call) and in_branch \
                    and _last_name(node.func) in COLLECTIVES:
                report(fi, mi, node, "under a Python branch")
            if isinstance(node, (ast.If, ast.While)):
                # the test itself executes unconditionally on every rank;
                # the bodies do not
                visit(node.test, in_branch)
                for part in node.body + node.orelse:
                    visit(part, True)
                return
            for child in ast.iter_child_nodes(node):
                visit(child, in_branch)

        for stmt in fi.node.body:
            visit(stmt, False)
    return findings
