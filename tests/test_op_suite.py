"""Per-op correctness sweep over the OpTest triangle (SURVEY §4.1).

Mirrors the reference's test/legacy_test/test_*_op.py files: each entry
declares inputs + a NumPy reference; the harness checks output parity,
finite-difference gradients, and eager-vs-traced equality.
"""

import numpy as np
import pytest
from scipy import special as sps

import paddle_tpu as paddle
from op_test import OpCase, run_case

R = np.random.RandomState(42)


def _softmax_np(x, axis=-1):
    e = np.exp(x - x.max(axis=axis, keepdims=True))
    return e / e.sum(axis=axis, keepdims=True)


CASES = [
    # ---- math: unary ----
    OpCase("exp", paddle.exp, np.exp, [R.randn(3, 4).astype(np.float32)],
           extra_dtypes=("float16",)),
    OpCase("log", paddle.log, np.log,
           [R.uniform(0.5, 2.0, (3, 4)).astype(np.float32)]),
    OpCase("sqrt", paddle.sqrt, np.sqrt,
           [R.uniform(0.1, 4.0, (3, 4)).astype(np.float32)]),
    OpCase("rsqrt", paddle.rsqrt, lambda x: 1 / np.sqrt(x),
           [R.uniform(0.5, 4.0, (3, 4)).astype(np.float32)]),
    OpCase("abs", paddle.abs, np.abs, [R.randn(3, 4).astype(np.float32)],
           check_grad=False),  # |x| kink: fd unreliable at 0
    OpCase("tanh", paddle.tanh, np.tanh, [R.randn(3, 4).astype(np.float32)]),
    OpCase("sigmoid", paddle.nn.functional.sigmoid, sps.expit,
           [R.randn(3, 4).astype(np.float32)]),
    OpCase("erf", paddle.erf, sps.erf, [R.randn(3, 4).astype(np.float32)]),
    OpCase("sin", paddle.sin, np.sin, [R.randn(3, 4).astype(np.float32)]),
    OpCase("cos", paddle.cos, np.cos, [R.randn(3, 4).astype(np.float32)]),
    OpCase("floor", paddle.floor, np.floor,
           [R.randn(3, 4).astype(np.float32) * 3], check_grad=False),
    OpCase("round", paddle.round, np.round,
           [R.randn(3, 4).astype(np.float32) * 3], check_grad=False),
    OpCase("reciprocal", paddle.reciprocal, lambda x: 1 / x,
           [R.uniform(0.5, 2.0, (3, 4)).astype(np.float32)]),
    OpCase("expm1", paddle.expm1, np.expm1,
           [R.randn(3, 4).astype(np.float32)]),
    OpCase("log1p", paddle.log1p, np.log1p,
           [R.uniform(-0.5, 2.0, (3, 4)).astype(np.float32)]),
    OpCase("silu", paddle.nn.functional.silu, lambda x: x * sps.expit(x),
           [R.randn(3, 4).astype(np.float32)]),
    OpCase("gelu", paddle.nn.functional.gelu,
           lambda x: x * 0.5 * (1 + sps.erf(x / np.sqrt(2))),
           [R.randn(3, 4).astype(np.float32)], grad_rtol=8e-2),
    OpCase("relu", paddle.nn.functional.relu,
           lambda x: np.maximum(x, 0),
           [R.randn(3, 4).astype(np.float32) + 0.3], grad_rtol=8e-2),
    OpCase("softplus", paddle.nn.functional.softplus,
           lambda x: np.log1p(np.exp(-np.abs(x))) + np.maximum(x, 0),
           [R.randn(3, 4).astype(np.float32)]),

    # ---- math: binary + broadcast ----
    OpCase("add_bcast", paddle.add, np.add,
           [R.randn(3, 4).astype(np.float32),
            R.randn(4).astype(np.float32)]),
    OpCase("subtract", paddle.subtract, np.subtract,
           [R.randn(2, 3, 4).astype(np.float32),
            R.randn(3, 1).astype(np.float32)]),
    OpCase("multiply", paddle.multiply, np.multiply,
           [R.randn(3, 4).astype(np.float32),
            R.randn(3, 4).astype(np.float32)]),
    OpCase("divide", paddle.divide, np.divide,
           [R.randn(3, 4).astype(np.float32),
            R.uniform(0.5, 2.0, (3, 4)).astype(np.float32)]),
    OpCase("maximum", paddle.maximum, np.maximum,
           [R.randn(3, 4).astype(np.float32),
            R.randn(3, 4).astype(np.float32)], check_grad=False),
    OpCase("minimum", paddle.minimum, np.minimum,
           [R.randn(3, 4).astype(np.float32),
            R.randn(3, 4).astype(np.float32)], check_grad=False),
    OpCase("pow", paddle.pow, np.power,
           [R.uniform(0.5, 2.0, (3, 4)).astype(np.float32),
            np.float32(2.5)], grad_inputs=[0]),
    OpCase("fmod", paddle.mod, np.mod,
           [R.uniform(1, 10, (3, 4)).astype(np.float32),
            R.uniform(1, 3, (3, 4)).astype(np.float32)], check_grad=False),
    OpCase("atan2", paddle.atan2, np.arctan2,
           [R.randn(3, 4).astype(np.float32),
            R.uniform(0.5, 2, (3, 4)).astype(np.float32)]),

    # ---- reductions ----
    OpCase("sum_axis", lambda x: paddle.sum(x, axis=1),
           lambda x: np.sum(x, axis=1), [R.randn(3, 4, 5).astype(np.float32)]),
    OpCase("mean_keepdim", lambda x: paddle.mean(x, axis=[0, 2], keepdim=True),
           lambda x: np.mean(x, axis=(0, 2), keepdims=True),
           [R.randn(3, 4, 5).astype(np.float32)]),
    OpCase("max_red", lambda x: paddle.max(x, axis=1),
           lambda x: np.max(x, axis=1),
           [R.randn(3, 7).astype(np.float32)], check_grad=False),
    OpCase("prod", lambda x: paddle.prod(x, axis=1),
           lambda x: np.prod(x, axis=1),
           [R.uniform(0.5, 1.5, (3, 4)).astype(np.float32)]),
    OpCase("logsumexp", lambda x: paddle.logsumexp(x, axis=-1),
           lambda x: sps.logsumexp(x, axis=-1),
           [R.randn(3, 6).astype(np.float32)]),
    OpCase("cumsum", lambda x: paddle.cumsum(x, axis=1),
           lambda x: np.cumsum(x, axis=1),
           [R.randn(3, 5).astype(np.float32)]),
    OpCase("cumprod", lambda x: paddle.cumprod(x, dim=1),
           lambda x: np.cumprod(x, axis=1),
           [R.uniform(0.5, 1.5, (3, 5)).astype(np.float32)]),

    # ---- linalg ----
    OpCase("matmul", paddle.matmul, np.matmul,
           [R.randn(3, 4).astype(np.float32),
            R.randn(4, 5).astype(np.float32)], rtol=1e-4, atol=1e-5),
    OpCase("matmul_batch_T",
           lambda a, b: paddle.matmul(a, b, transpose_y=True),
           lambda a, b: a @ np.swapaxes(b, -1, -2),
           [R.randn(2, 3, 4).astype(np.float32),
            R.randn(2, 5, 4).astype(np.float32)], rtol=1e-4, atol=1e-5),
    OpCase("einsum_ij,jk",
           lambda a, b: paddle.einsum("ij,jk->ik", a, b),
           lambda a, b: np.einsum("ij,jk->ik", a, b),
           [R.randn(3, 4).astype(np.float32),
            R.randn(4, 5).astype(np.float32)], rtol=1e-4, atol=1e-5),
    OpCase("norm_fro", lambda x: paddle.linalg.norm(x),
           lambda x: np.linalg.norm(x), [R.randn(3, 4).astype(np.float32)]),

    # ---- manipulation ----
    OpCase("transpose", lambda x: paddle.transpose(x, [2, 0, 1]),
           lambda x: np.transpose(x, (2, 0, 1)),
           [R.randn(2, 3, 4).astype(np.float32)]),
    OpCase("reshape", lambda x: paddle.reshape(x, [4, 6]),
           lambda x: np.reshape(x, (4, 6)),
           [R.randn(2, 3, 4).astype(np.float32)]),
    OpCase("concat", lambda a, b: paddle.concat([a, b], axis=1),
           lambda a, b: np.concatenate([a, b], axis=1),
           [R.randn(2, 3).astype(np.float32),
            R.randn(2, 4).astype(np.float32)]),
    OpCase("stack", lambda a, b: paddle.stack([a, b], axis=1),
           lambda a, b: np.stack([a, b], axis=1),
           [R.randn(2, 3).astype(np.float32),
            R.randn(2, 3).astype(np.float32)]),
    OpCase("tile", lambda x: paddle.tile(x, [2, 3]),
           lambda x: np.tile(x, (2, 3)), [R.randn(2, 3).astype(np.float32)]),
    OpCase("flip", lambda x: paddle.flip(x, axis=[1]),
           lambda x: np.flip(x, axis=1), [R.randn(2, 5).astype(np.float32)]),
    OpCase("roll", lambda x: paddle.roll(x, 2, axis=1),
           lambda x: np.roll(x, 2, axis=1),
           [R.randn(2, 5).astype(np.float32)]),
    OpCase("pad2d", lambda x: paddle.nn.functional.pad(x, [1, 2], value=0.5),
           lambda x: np.pad(x, [(0, 0), (1, 2)], constant_values=0.5),
           [R.randn(2, 5).astype(np.float32)], check_grad=False),
    OpCase("gather", lambda x, i: paddle.gather(x, i, axis=0),
           lambda x, i: np.take(x, i, axis=0),
           [R.randn(5, 3).astype(np.float32),
            np.array([0, 3, 1], np.int32)]),
    OpCase("index_select", lambda x, i: paddle.index_select(x, i, axis=1),
           lambda x, i: np.take(x, i, axis=1),
           [R.randn(3, 5).astype(np.float32),
            np.array([4, 0, 2], np.int32)]),
    OpCase("squeeze", lambda x: paddle.squeeze(x, axis=1),
           lambda x: np.squeeze(x, axis=1),
           [R.randn(3, 1, 4).astype(np.float32)]),
    OpCase("expand", lambda x: paddle.expand(x, [3, 2, 4]),
           lambda x: np.broadcast_to(x, (3, 2, 4)),
           [R.randn(2, 4).astype(np.float32)], check_grad=False),
    OpCase("split_get1",
           lambda x: paddle.split(x, 2, axis=1)[1],
           lambda x: np.split(x, 2, axis=1)[1],
           [R.randn(3, 6).astype(np.float32)]),
    OpCase("where", paddle.where,
           lambda c, a, b: np.where(c, a, b),
           [R.randn(3, 4) > 0, R.randn(3, 4).astype(np.float32),
            R.randn(3, 4).astype(np.float32)]),

    # ---- softmax / norm / loss ----
    OpCase("softmax", lambda x: paddle.nn.functional.softmax(x, axis=-1),
           _softmax_np, [R.randn(3, 6).astype(np.float32)]),
    OpCase("log_softmax",
           lambda x: paddle.nn.functional.log_softmax(x, axis=-1),
           lambda x: np.log(_softmax_np(x)),
           [R.randn(3, 6).astype(np.float32)]),
    OpCase("layer_norm",
           lambda x, w, b: paddle.nn.functional.layer_norm(
               x, x.shape[-1:], weight=w, bias=b),
           lambda x, w, b: ((x - x.mean(-1, keepdims=True))
                            / np.sqrt(x.var(-1, keepdims=True) + 1e-5)
                            * w + b),
           [R.randn(4, 8).astype(np.float32),
            R.uniform(0.5, 1.5, 8).astype(np.float32),
            R.randn(8).astype(np.float32)], grad_rtol=8e-2),
    OpCase("cross_entropy",
           lambda x, t: paddle.nn.functional.cross_entropy(x, t),
           lambda x, t: -np.mean(
               np.log(_softmax_np(x))[np.arange(len(t)), t]),
           [R.randn(6, 5).astype(np.float32),
            R.randint(0, 5, 6).astype(np.int64)], grad_inputs=[0]),
    OpCase("mse_loss",
           lambda x, y: paddle.nn.functional.mse_loss(x, y),
           lambda x, y: np.mean((x - y) ** 2),
           [R.randn(4, 3).astype(np.float32),
            R.randn(4, 3).astype(np.float32)]),

    # ---- search / logic ----
    OpCase("argmax", lambda x: paddle.argmax(x, axis=1),
           lambda x: np.argmax(x, axis=1),
           [R.randn(3, 7).astype(np.float32)], check_grad=False),
    OpCase("sort", lambda x: paddle.sort(x, axis=1),
           lambda x: np.sort(x, axis=1),
           [R.randn(3, 7).astype(np.float32)], check_grad=False),
    OpCase("argsort", lambda x: paddle.argsort(x, axis=1),
           lambda x: np.argsort(x, axis=1, kind="stable"),
           [R.randn(3, 7).astype(np.float32)], check_grad=False),
    OpCase("topk_values", lambda x: paddle.topk(x, 3, axis=1)[0],
           lambda x: -np.sort(-x, axis=1)[:, :3],
           [R.randn(3, 7).astype(np.float32)], check_grad=False),
    OpCase("equal", paddle.equal, np.equal,
           [np.array([1, 2, 3], np.int32), np.array([1, 5, 3], np.int32)],
           check_grad=False),
    OpCase("isclose", paddle.isclose, np.isclose,
           [np.array([1.0, 2.0], np.float32),
            np.array([1.0, 2.1], np.float32)], check_grad=False),
    OpCase("clip", lambda x: paddle.clip(x, -0.5, 0.5),
           lambda x: np.clip(x, -0.5, 0.5),
           [R.randn(3, 4).astype(np.float32)], check_grad=False),
]


@pytest.mark.parametrize("case", CASES, ids=lambda c: c.name)
def test_op(case):
    run_case(case)
