"""paddle.geometric parity (ref: python/paddle/geometric/ — graph segment
ops + message passing; SURVEY §2.2 misc numerics). XLA segment primitives
replace the CUDA scatter kernels."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.dispatch import apply
from ..core.tensor import Tensor

__all__ = ["segment_sum", "segment_mean", "segment_max", "segment_min",
           "send_u_recv", "send_ue_recv"]


def _arr(x):
    return x._data if isinstance(x, Tensor) else jnp.asarray(x)


def _segment(name, reducer, x, segment_ids, num_segments=None):
    ids = _arr(segment_ids).astype(jnp.int32)
    n = int(num_segments) if num_segments is not None else \
        int(jnp.max(ids)) + 1

    def impl(a):
        return reducer(a, ids, n)
    return apply(name, impl, [x])


def segment_sum(data, segment_ids, name=None):
    return _segment("segment_sum", lambda a, i, n:
                    jax.ops.segment_sum(a, i, n), data, segment_ids)


def _segment_count(a, ids, n):
    return jax.ops.segment_sum(
        jnp.ones((a.shape[0],) + (1,) * (a.ndim - 1), a.dtype), ids, n)


def _segment_mean(a, i, n):
    s = jax.ops.segment_sum(a, i, n)
    return s / jnp.maximum(_segment_count(a, i, n), 1)


def segment_mean(data, segment_ids, name=None):
    return _segment("segment_mean", _segment_mean, data, segment_ids)


def _masked_extremum(reducer):
    """Reference fills EMPTY segments with 0, not the ±inf identity."""
    def red(a, i, n):
        out = reducer(a, i, n)
        return jnp.where(_segment_count(a, i, n) > 0, out,
                         jnp.zeros((), a.dtype))
    return red


def segment_max(data, segment_ids, name=None):
    return _segment("segment_max", _masked_extremum(jax.ops.segment_max),
                    data, segment_ids)


def segment_min(data, segment_ids, name=None):
    return _segment("segment_min", _masked_extremum(jax.ops.segment_min),
                    data, segment_ids)


def _reducer(reduce_op: str):
    try:
        return {"sum": jax.ops.segment_sum,
                "mean": _segment_mean,
                "max": _masked_extremum(jax.ops.segment_max),
                "min": _masked_extremum(jax.ops.segment_min)}[reduce_op]
    except KeyError:
        raise ValueError(f"unknown reduce_op {reduce_op!r}") from None


def send_u_recv(x, src_index, dst_index, reduce_op: str = "sum",
                out_size=None, name=None):
    """Graph message passing (ref: paddle.geometric.send_u_recv): gather
    x[src], segment-reduce onto dst."""
    src = _arr(src_index).astype(jnp.int32)
    dst = _arr(dst_index).astype(jnp.int32)
    xa = _arr(x)
    n = int(out_size) if out_size is not None else xa.shape[0]
    red = _reducer(reduce_op)

    def impl(a):
        return red(a[src], dst, n)
    return apply("send_u_recv", impl, [x])


def send_ue_recv(x, y, src_index, dst_index, message_op: str = "add",
                 reduce_op: str = "sum", out_size=None, name=None):
    """Messages combine node features x[src] with edge features y."""
    src = _arr(src_index).astype(jnp.int32)
    dst = _arr(dst_index).astype(jnp.int32)
    xa = _arr(x)
    n = int(out_size) if out_size is not None else xa.shape[0]
    red = _reducer(reduce_op)

    def impl(a, e):
        m = a[src]
        m = m + e if message_op == "add" else m * e
        return red(m, dst, n)
    return apply("send_ue_recv", impl, [x, y])


def sample_neighbors(row, colptr, input_nodes, sample_size=-1,
                     eids=None, return_eids=False, perm_buffer=None,
                     name=None):
    """ref: paddle.geometric.sample_neighbors — uniform neighbor sampling
    over a CSC graph (row: concatenated in-neighbors, colptr: [N+1] offsets).
    Returns (out_neighbors, out_count[, out_eids]). Output sizes are
    data-dependent → eager-only (same restriction as the reference's
    dynamic-shape GPU kernel under CINN).
    """
    import numpy as np
    from ..framework.random import next_key

    if return_eids and eids is None:
        raise ValueError("return_eids=True requires eids")
    row_np = np.asarray(_arr(row))
    colptr_np = np.asarray(_arr(colptr))
    nodes = np.asarray(_arr(input_nodes))
    eids_np = None if eids is None else np.asarray(_arr(eids))
    rng = None  # lazily seeded: full-neighborhood calls use no randomness
    neigh, counts, out_eids = [], [], []
    for n in nodes.reshape(-1):
        s, e = int(colptr_np[n]), int(colptr_np[n + 1])
        deg = e - s
        if sample_size < 0 or deg <= sample_size:
            idx = np.arange(s, e)
        else:
            if rng is None:
                seed = int(jax.random.randint(next_key(), (), 0,
                                              2**31 - 1))
                rng = np.random.RandomState(seed)
            idx = s + rng.choice(deg, size=sample_size, replace=False)
        neigh.append(row_np[idx])
        counts.append(len(idx))
        if eids_np is not None:
            out_eids.append(eids_np[idx])
    out = (Tensor(jnp.asarray(np.concatenate(neigh)
                              if neigh else np.zeros(0, row_np.dtype))),
           Tensor(jnp.asarray(np.asarray(counts, np.int32))))
    if return_eids:
        out += (Tensor(jnp.asarray(
            np.concatenate(out_eids) if out_eids
            else np.zeros(0, eids_np.dtype))),)
    return out


def reindex_graph(x, neighbors, count, value_buffer=None, index_buffer=None,
                  name=None):
    """ref: paddle.geometric.reindex_graph — compact (x ∪ neighbors) to
    local ids; returns (reindexed_src, reindexed_dst, out_nodes)."""
    import numpy as np

    x_np = np.asarray(_arr(x)).reshape(-1)
    nb = np.asarray(_arr(neighbors)).reshape(-1)
    cnt = np.asarray(_arr(count)).reshape(-1)
    mapping = {}
    for v in x_np.tolist():
        mapping.setdefault(int(v), len(mapping))
    for v in nb.tolist():
        mapping.setdefault(int(v), len(mapping))
    idt = x_np.dtype  # preserve the caller's node-id dtype (ref parity)
    src = np.asarray([mapping[int(v)] for v in nb], idt)
    dst = np.repeat(np.arange(len(x_np)), cnt).astype(idt)
    # insertion order == id order: no sort needed
    out_nodes = np.fromiter(mapping, idt, len(mapping))
    return (Tensor(jnp.asarray(src)), Tensor(jnp.asarray(dst)),
            Tensor(jnp.asarray(out_nodes)))


__all__ += ["sample_neighbors", "reindex_graph"]
