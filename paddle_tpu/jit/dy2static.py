"""dy2static — AST control-flow capture for ``to_static`` (ref:
python/paddle/jit/dy2static/ — IfElseTransformer / LoopTransformer /
convert_ifelse / convert_while_loop rewrite python ``if``/``while``/``for``
into cond/while ops on the captured program; SURVEY §2.2 jit row).

TPU-native rework: the reference needs a full source-to-source translator
because its static graph has no Python execution at all. Here the traced
program IS Python execution, so the transform is far smaller: every
``if``/``while``/``for range()`` statement is rewritten into a call to a
runtime dispatcher (``_jst.run_if`` / ``run_while`` / ``run_for_range``)
that checks the predicate at run time —

* concrete predicate → execute the original Python branch/loop (identical
  semantics, taken path only, exact tape autograd),
* traced predicate (under ``jit``/``to_static``) → lower through
  ``paddle_tpu.static.nn.cond`` / ``while_loop`` so XLA compiles a real
  conditional/while region instead of the trace failing.

This runtime dual-dispatch replaces the reference's static analysis: no
type inference is needed because the decision is made on the live value
(the same trick as convert_ifelse's ``paddle.jit.dy2static.convert_*``
wrappers, which also dispatch on Variable-ness at run time).

``break``/``continue`` in WHILE bodies are captured via the reference's
flag rewrite (BreakContinueTransformer): the statement becomes a flag
assignment, skipped statements are guarded by ``loop_guard``, and the
loop test gains ``not brk`` — all through the same recursive pass, so a
break under a tensor-if lowers to lax correctly. A predicate that BECOMES
traced mid-loop (a break flag turned cond output) hands the remaining
iterations to the lax lowering.

Early returns are captured by the reference ReturnTransformer's
normalization: ``if p: return a`` followed by REST folds into
``if p: return a else: REST``, every Return becomes an assignment to a
single return variable, and the tail-position fold carries ONLY that
variable out of the branches — so tensor-predicated early returns and
elif-return chains lower to lax.cond. Applies when every path explicitly
returns and no Return hides in a loop/try.

``for`` over ITERABLES (r5, convert_for_iter/convert_enumerate parity):
``for x in tensor``, ``for i, x in enumerate(seq[, start])`` and
``for a, b in zip(...)`` route through ``run_for_iter`` — concrete
iterables run the original python iteration (generators, dicts, any
protocol), and when a component is a traced Tensor the loop lowers to a
bounded differentiable scan over the STATIC leading axis (zip stops at
the min length, python semantics; mixed tensor+python zips raise a clear
TypeError under trace). ``enumerate``/``zip`` are treated structurally
only when not shadowed by a local binding.

``while``/``for`` ELSE clauses are captured (r5): without a loop-level
break the else body simply follows the loop; with one, an ``_elseok``
flag cleared on every loop-level break guards the else, so a TRACED
break predicate turns the else into a lax.cond. Exact python semantics
on both paths, all loop forms (while / for-range / for-iterable).

``nonlocal``/``global`` are contained PER-SITE (r5): names written
through a cell or the module dict anywhere in the function make only the
statements that would THREAD those names fall back (threading by value
could not observe a mid-statement cell write); every other statement
still converts, and branch-fn reads of such names stay live via closure.

Scope (documented limitations, each falls back to the untransformed
statement, which still works for concrete predicates):
* ``return`` inside a LOOP body or try-block is not captured (branch
  returns are — see above); functions with fall-off-the-end paths keep
  their original form,
* ``break``/``continue`` nested inside ``try``/``match`` blocks are not
  captured (while and for-range bodies are — for-range desugars to the
  canonical while, counter advanced before the body so continue keeps
  python semantics); for-over-ITERABLE bodies with loop-level
  break/continue fall back,
* a body reassignment of a for-over-iterable TARGET is visible inside
  the loop but the post-loop target value is the last iteration's
  element (the one documented deviation on the traced path),
* a loop temp FIRST assigned after a continue-guard needs a pre-loop
  initial value under trace (clear NameError says so); initialized
  temps are promoted into the lax carry at runtime, so post-loop reads
  see the last-iteration value exactly like python,
* in-place Tensor mutation of closure variables inside a traced branch is
  dropped (branch outputs must flow through the returned loop/branch vars),
* loops with a traced predicate are forward-only unless
  ``FLAGS_dy2static_max_iter`` is set (bounded differentiable scan).
"""

from __future__ import annotations

import ast
import functools
import inspect
import textwrap
import types
from typing import Any, Callable, List, Optional, Sequence, Set

import jax

from ..core.tensor import Tensor
from ..flags import define_flag, flag

try:
    define_flag("FLAGS_dy2static_max_iter", 0,
                "if >0, tensor-dependent loops converted by dy2static lower "
                "to a bounded differentiable scan of this length instead of "
                "a forward-only lax.while_loop")
except ValueError:
    pass

__all__ = ["convert", "Undefined", "run_if", "run_while", "run_for_range",
           "run_for_iter", "ld"]


# ---------------------------------------------------------------------------
# runtime dispatchers (the convert_* ops of the reference)
# ---------------------------------------------------------------------------

class Undefined:
    """Sentinel for a name unbound at the control-flow statement. Any use
    raises the NameError python would have raised."""

    __slots__ = ("name",)

    def __init__(self, name: str):
        self.name = name

    def _raise(self, *a, **k):
        raise NameError(
            f"variable '{self.name}' is not defined on every path through a "
            "dy2static-converted control-flow statement")

    __bool__ = __call__ = __add__ = __radd__ = __mul__ = _raise
    __sub__ = __truediv__ = __getitem__ = __iter__ = __len__ = _raise
    __eq__ = __ne__ = __lt__ = __le__ = __gt__ = __ge__ = _raise
    __neg__ = __rsub__ = __rmul__ = __rtruediv__ = __mod__ = _raise
    __hash__ = object.__hash__

    def __getattr__(self, name):
        # dunder probes (getattr(v, "__closure__", None), pickling, etc.)
        # must see a plain AttributeError, not the use-error
        if name.startswith("__") and name.endswith("__"):
            raise AttributeError(name)
        self._raise()

    def __repr__(self):
        return f"<undefined '{self.name}'>"


def loop_not(v):
    """Boolean NOT that works for python values AND traced Tensors (the
    break-flag guard in converted loops; `not tensor` would trace-fail)."""
    if isinstance(v, Tensor):
        from ..tensor.logic import logical_not
        return logical_not(v)
    return not v


def loop_and(a, b):
    """Non-short-circuit AND over python values / traced Tensors (the
    rewritten loop test `not brk and test`)."""
    if isinstance(a, Tensor) or isinstance(b, Tensor):
        from ..tensor.logic import logical_and
        return logical_and(a, b)
    return a and b


def loop_test(brk, test_thunk):
    """The rewritten while test: short-circuits on a CONCRETE break flag —
    python never re-evaluates the test after ``break``, and the test may
    only be safe while the loop is live (e.g. an index bound the break
    protects). A traced flag can't short-circuit (lax evaluates the cond
    region with the final carry once more); that requires the test itself
    to be trace-safe, which the traced regime requires anyway."""
    if isinstance(brk, Tensor):
        if _is_traced(brk):
            return loop_and(loop_not(brk), test_thunk())
        if bool(brk._data):
            return False
        return test_thunk()
    if brk:
        return False
    return test_thunk()


def loop_guard(*flags):
    """True when NO break/continue flag is set — the guard condition for
    statements a python break/continue would have skipped."""
    acc = flags[0]
    for f in flags[1:]:
        if isinstance(acc, Tensor) or isinstance(f, Tensor):
            from ..tensor.logic import logical_or
            acc = logical_or(acc, f)
        else:
            acc = acc or f
    return loop_not(acc)


def range_cond(i, stop, step):
    """The while-test of a desugared for-range: direction follows the
    (static) step sign; works for python and traced values. Keeps
    range()'s own argument validation (zero step, non-integer bounds)."""
    import numpy as _np
    if isinstance(step, Tensor):
        raise ValueError(
            "dy2static for-range: step must be a python int when the "
            "bounds are tensors (XLA needs the loop direction statically)")
    if not isinstance(step, (int, _np.integer)):
        raise TypeError(f"'{type(step).__name__}' object cannot be "
                        "interpreted as an integer")
    if step == 0:
        raise ValueError("range() arg 3 must not be zero")
    for v in (i, stop):
        if not isinstance(v, Tensor) and not isinstance(
                v, (int, _np.integer)):
            raise TypeError(f"'{type(v).__name__}' object cannot be "
                            "interpreted as an integer")
    return (i < stop) if step > 0 else (i > stop)


def is_undef(v) -> bool:
    """Runtime check used by generated scrub guards: a loop temp whose
    post-loop value is unavailable under trace is DELETED after the loop,
    so any later read raises UnboundLocalError (python semantics for an
    unbound name) instead of silently passing the sentinel through a
    return/argument position."""
    return isinstance(v, Undefined)


def ld(thunk: Callable, name: str):
    """Safe load of a possibly-unbound local for threading into branch fns."""
    try:
        return thunk()
    except (NameError, UnboundLocalError):
        return Undefined(name)


def _is_traced(x) -> bool:
    arr = x._data if isinstance(x, Tensor) else x
    return isinstance(arr, jax.core.Tracer)


def _truthy(p) -> bool:
    if isinstance(p, Tensor):
        return bool(p._data)
    return bool(p)


def _check_defined(cur: Sequence[Any], what: str):
    for v in cur:
        if isinstance(v, Undefined):
            raise NameError(
                f"dy2static: variable '{v.name}' must be assigned before a "
                f"tensor-dependent {what} (every branch/loop variable needs "
                "an initial value to lower to lax control flow)")


def run_if(test_thunk: Callable, true_fn: Callable, false_fn: Callable,
           cur: tuple):
    """Dispatcher for a converted ``if`` statement. ``true_fn``/``false_fn``
    take and return the tuple of written names. A name need not exist
    before the ``if`` as long as BOTH branches assign it (reference
    semantics: conditional_block output vars)."""
    pred = test_thunk()
    if _is_traced(pred):
        from ..static import control_flow as cf

        def _chk(vals, branch):
            for v in vals:
                if isinstance(v, Undefined):
                    raise NameError(
                        f"dy2static: variable '{v.name}' is assigned in only "
                        f"one branch of a tensor-dependent if (missing in the "
                        f"{branch} branch); assign it in both branches or "
                        "before the if to lower to lax.cond")
            return vals

        out = cf.cond(pred, lambda: _chk(tuple(true_fn(*cur)), "true"),
                      lambda: _chk(tuple(false_fn(*cur)), "false"))
        return tuple(out)
    return tuple(true_fn(*cur)) if _truthy(pred) else tuple(false_fn(*cur))


def run_while(cond_fn: Callable, body_fn: Callable, cur: tuple,
              names: tuple = (), n_carried: Optional[int] = None):
    """Dispatcher for a converted ``while`` statement. ``cur`` is ordered
    carried-variables-first; ``cur[n_carried:]`` are loop temps (assigned
    before read each iteration — the reference LoopTransformer's
    create-in-loop vars) which are NOT threaded through the lax carry. A
    temp's post-loop value under trace is Undefined (reads raise; python
    path returns the real last value)."""
    if n_carried is None:
        n_carried = len(cur)
    first = cond_fn(*cur)
    if _is_traced(first):
        from ..static import control_flow as cf
        carried, temps = list(cur[:n_carried]), list(cur[n_carried:])
        _check_defined(carried, "while loop")
        # RUNTIME temp promotion: a temp that HAS a jax-carryable pre-loop
        # value rides the lax carry, so its post-loop value is the
        # last-iteration one (python semantics for `acc = acc + tmp` after
        # the loop); uninitialized or non-numeric temps (strings, lists —
        # lax carries reject them) stay closure-side and scrub to
        # Undefined after the loop
        def _carryable(v):
            if isinstance(v, Undefined):
                return False
            if isinstance(v, (Tensor, bool, int, float, complex)):
                return True
            import numpy as _np
            return (hasattr(v, "dtype") and hasattr(v, "shape")
                    and _np.issubdtype(getattr(v, "dtype"), _np.number)
                    or (hasattr(v, "dtype")
                        and getattr(v, "dtype") == bool))

        promote = [i for i, v in enumerate(temps) if _carryable(v)]
        keep = [i for i in range(len(temps)) if i not in promote]

        def remap(args2):
            c = args2[:n_carried]
            pr = args2[n_carried:]
            t = [None] * len(temps)
            for j, i in enumerate(promote):
                t[i] = pr[j]
            for i in keep:
                t[i] = temps[i]
            return tuple(c) + tuple(t)

        sel = list(range(n_carried)) + [n_carried + i for i in promote]
        mx = flag("FLAGS_dy2static_max_iter") or None
        out = cf.while_loop(
            lambda *a: cond_fn(*remap(a)),
            lambda *a: tuple(tuple(body_fn(*remap(a)))[k] for k in sel),
            carried + [temps[i] for i in promote], max_iter=mx)
        out = tuple(out)
        full_t = [None] * len(temps)
        for j, i in enumerate(promote):
            full_t[i] = out[n_carried + j]
        for i in keep:
            full_t[i] = Undefined(names[n_carried + i] if names
                                  else "<temp>")
        return out[:n_carried] + tuple(full_t)
    vals = cur
    while True:
        if _is_traced(first):
            # the predicate BECAME traced mid-loop (e.g. a break flag
            # assigned under a tensor-if turned into a cond output):
            # the concrete iterations already ran as the prefix — hand
            # the current state to the lax lowering for the rest
            return run_while(cond_fn, body_fn, vals, names, n_carried)
        if not _truthy(first):
            break
        vals = tuple(body_fn(*vals))
        first = cond_fn(*vals)
    return vals


def run_for_range(range_thunk: Callable, body_fn: Callable, cur: tuple,
                  names: tuple = (), n_carried: Optional[int] = None):
    """Dispatcher for a converted ``for <name> in range(...)`` statement.
    ``cur[0]`` is the prior value of the index name (possibly Undefined);
    ``body_fn(i, *vars) -> (i, *vars)`` with vars ordered carried-first
    (see :func:`run_while`). Traced-bound loops lower to while_loop; the
    returned index is the python last-iteration value (``start - step``
    for a dynamically zero-trip traced loop)."""
    args = range_thunk()
    prior_i, rest = cur[0], tuple(cur[1:])
    if n_carried is None:
        n_carried = len(rest)
    if any(_is_traced(a) for a in args):
        from ..static import control_flow as cf
        import jax.numpy as jnp
        carried, temps = rest[:n_carried], rest[n_carried:]
        _check_defined(carried, "for loop")
        if len(args) == 1:
            start, stop, step = 0, args[0], 1
        elif len(args) == 2:
            (start, stop), step = args, 1
        else:
            start, stop, step = args
        if isinstance(step, Tensor):
            raise ValueError(
                "dy2static for-range: step must be a python int when the "
                "bounds are tensors (XLA needs the loop direction "
                "statically)")
        step = int(step)
        if step == 0:
            raise ValueError("range() arg 3 must not be zero")
        i0 = start if isinstance(start, Tensor) else Tensor(jnp.asarray(start))
        stop_t = stop if isinstance(stop, Tensor) else Tensor(jnp.asarray(stop))

        def cnd(i, _s, *vs):
            return (i < _s) if step > 0 else (i > _s)

        def body(i, _s, *vs):
            out = body_fn(i, *vs, *temps)
            # python rebinds the index from the iterator each pass — a body
            # assignment to it must not change the iteration count
            return (i + step, _s) + tuple(out[1:1 + n_carried])

        mx = flag("FLAGS_dy2static_max_iter") or None
        out = cf.while_loop(cnd, body, [i0, stop_t] + list(carried),
                            max_iter=mx)
        tail = tuple(Undefined(names[1 + n_carried + j] if names else "<temp>")
                     for j in range(len(temps)))
        return (out[0] - step,) + tuple(out[2:]) + tail
    vals = rest
    i = prior_i
    for i in range(*[int(a) if isinstance(a, Tensor) else a for a in args]):
        out = body_fn(i, *vals)
        i, vals = out[0], tuple(out[1:])
    return (i,) + vals


def run_for_iter(iter_thunk: Callable, body_fn: Callable, cur: tuple,
                 names: tuple = (), n_carried: Optional[int] = None,
                 n_targets: int = 1):
    """Dispatcher for a converted ``for <targets> in <iterable>`` statement
    (ref: convert_operators.py convert_for_iter / convert_enumerate /
    convert_zip). ``iter_thunk() -> (kind, components, start)`` where kind
    is 'plain' | 'enumerate' | 'zip' and components are the evaluated
    iterable expressions (1 for plain/enumerate, k for zip).

    Concrete components -> the original python iteration, exact semantics
    for ANY iterable (generators included). Any component a traced Tensor
    -> every component must be a Tensor; the loop lowers to a bounded
    differentiable scan over the STATIC leading-axis length (min across
    zip components, python semantics), with elements gathered per step.
    Post-loop target values are the last iteration's ELEMENTS (a body
    reassignment of the loop target is visible inside the loop but not in
    its post-loop value — the one documented deviation)."""
    kind, comps, start = iter_thunk()
    comps = tuple(comps)
    prior_t, rest = tuple(cur[:n_targets]), tuple(cur[n_targets:])
    if n_carried is None:
        n_carried = len(rest)
    if not any(_is_traced(c) for c in comps):
        if kind == "enumerate":
            it = enumerate(comps[0], start if start is not None else 0)
        elif kind == "zip":
            it = zip(*comps)
        else:
            it = comps[0]
        tvals, vals = prior_t, rest
        for item in it:
            if n_targets == 1:
                tg = (item,)
            else:
                tg = tuple(item)
                if len(tg) != n_targets:
                    raise ValueError(
                        f"cannot unpack {len(tg)} values into "
                        f"{n_targets} for-loop targets")
            out = body_fn(*tg, *vals)
            tvals, vals = tuple(out[:n_targets]), tuple(out[n_targets:])
        return tvals + vals

    from ..static import control_flow as cf
    carried, temps = rest[:n_carried], rest[n_carried:]
    _check_defined(carried, "for loop")
    for c in comps:
        if not isinstance(c, Tensor):
            raise TypeError(
                "dy2static for-over-iterable: when any component is a "
                "traced Tensor, every zip/enumerate component must be a "
                f"Tensor (got {type(c).__name__}); stack python sequences "
                "into a Tensor before the loop")
        if len(c.shape) == 0:
            raise TypeError("cannot iterate over a 0-d Tensor")
    L = min(int(c.shape[0]) for c in comps)

    def elems(i):
        base = tuple(c[i] for c in comps)
        if kind == "enumerate":
            base = ((0 if start is None else start) + i,) + base
        if n_targets == 1:
            return (base[0],) if kind == "plain" else (base,)
        if kind == "plain":
            # `for a, b in pairs` — unpack the row (static width check)
            row = base[0]
            if len(row.shape) == 0 or int(row.shape[0]) != n_targets:
                raise ValueError(
                    f"cannot unpack a {tuple(row.shape)} Tensor row into "
                    f"{n_targets} for-loop targets")
            return tuple(row[j] for j in range(n_targets))
        if len(base) != n_targets:
            raise ValueError(
                f"cannot unpack {len(base)} values into {n_targets} "
                f"for-loop targets")
        return base

    tail = tuple(Undefined(names[n_targets + n_carried + j]
                           if names else "<temp>")
                 for j in range(len(temps)))
    if L == 0:
        return prior_t + tuple(carried) + tail

    def cnd(i, *vs):
        return i < L

    def body(i, *vs):
        out = body_fn(*elems(i), *vs, *temps)
        return (i + 1,) + tuple(out[n_targets:n_targets + n_carried])

    # the counter must be TRACED or while_loop's concrete-predicate path
    # unrolls all L iterations at trace time; derive a traced zero from a
    # traced component (int cast before the reduce so inf/NaN data cannot
    # leak into the index — int wraparound times zero is exactly zero)
    seed = next(c for c in comps if _is_traced(c))
    i0 = seed.astype("int32").sum() * 0
    # the trip count is STATIC (leading axis), so the loop always lowers
    # to the bounded masked scan — reverse-differentiable, unlike a
    # dynamically-bounded while
    out = cf.while_loop(cnd, body, [i0] + list(carried), max_iter=L)
    return tuple(elems(L - 1)) + tuple(out[1:]) + tail


# ---------------------------------------------------------------------------
# written-name analysis
# ---------------------------------------------------------------------------

def _written_names(stmts: Sequence[ast.stmt]) -> Set[str]:
    """Names bound by the statements, at this function's scope (does not
    descend into nested function/class/lambda/comprehension scopes)."""
    out: Set[str] = set()

    class V(ast.NodeVisitor):
        def visit_Name(self, node):
            if isinstance(node.ctx, (ast.Store, ast.Del)):
                out.add(node.id)

        def visit_NamedExpr(self, node):
            if isinstance(node.target, ast.Name):
                out.add(node.target.id)
            self.visit(node.value)

        def visit_FunctionDef(self, node):
            out.add(node.name)

        visit_AsyncFunctionDef = visit_FunctionDef

        def visit_ClassDef(self, node):
            out.add(node.name)

        def visit_Lambda(self, node):
            pass

        def _comp(self, node):
            # py3 comprehensions have their own scope; only the walrus leaks
            for gen in node.generators:
                self.visit(gen.iter)

        visit_ListComp = visit_SetComp = visit_DictComp = _comp
        visit_GeneratorExp = _comp

        def visit_Import(self, node):
            for alias in node.names:
                out.add((alias.asname or alias.name).split(".")[0])

        visit_ImportFrom = visit_Import

    for s in stmts:
        V().visit(s)
    return {n for n in out
            if not n.startswith(("_pt_", "__pt_")) and n != "_jst"}


def _stored_names(targets) -> Set[str]:
    out: Set[str] = set()
    for t in targets:
        for sub in ast.walk(t):
            if isinstance(sub, ast.Name) and isinstance(sub.ctx, ast.Store):
                out.add(sub.id)
    return out


def _carried_names(test: Optional[ast.expr], body: Sequence[ast.stmt],
                   written: Set[str], pre_assigned: Set[str] = frozenset()) \
        -> Set[str]:
    """Subset of ``written`` whose value may flow across loop iterations:
    read by the loop test, or possibly read before (re)assignment inside one
    iteration. The complement — names always assigned before read — are
    loop temps (the reference LoopTransformer's create-in-loop vars) and
    stay out of the lax carry. Conservative: unknown constructs count their
    loads as reads."""
    reads: Set[str] = set()

    def expr(node, assigned, skip: Set[str] = frozenset()):
        if node is None:
            return
        for sub in ast.walk(node):
            if (isinstance(sub, ast.Name) and isinstance(sub.ctx, ast.Load)
                    and sub.id in written and sub.id not in assigned
                    and sub.id not in skip):
                reads.add(sub.id)

    def block(stmts, assigned):
        for s in stmts:
            stmt(s, assigned)

    def stmt(s, assigned):
        if isinstance(s, ast.Assign):
            expr(s.value, assigned)
            for t in s.targets:
                if not isinstance(t, ast.Name):
                    expr(t, assigned)          # subscript/attribute bases
            assigned |= _stored_names(s.targets)
        elif isinstance(s, ast.AugAssign):
            expr(s.value, assigned)
            expr(s.target, assigned | set())   # target is read too
            if isinstance(s.target, ast.Name):
                if s.target.id in written and s.target.id not in assigned:
                    reads.add(s.target.id)
                assigned.add(s.target.id)
        elif isinstance(s, ast.AnnAssign):
            expr(s.value, assigned)
            if isinstance(s.target, ast.Name) and s.value is not None:
                assigned.add(s.target.id)
        elif isinstance(s, ast.If):
            expr(s.test, assigned)
            a1, a2 = set(assigned), set(assigned)
            block(s.body, a1)
            block(s.orelse, a2)
            assigned |= (a1 & a2)
        elif isinstance(s, ast.While):
            expr(s.test, assigned)
            a1 = set(assigned)
            block(s.body, a1)                  # may run zero times
            block(s.orelse, assigned)
        elif isinstance(s, ast.For):
            expr(s.iter, assigned)
            a1 = set(assigned) | _stored_names([s.target])
            block(s.body, a1)
            block(s.orelse, assigned)
        elif isinstance(s, ast.With):
            for item in s.items:
                expr(item.context_expr, assigned)
                if item.optional_vars is not None:
                    assigned |= _stored_names([item.optional_vars])
            block(s.body, assigned)
        elif isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef)):
            params = {a.arg for a in (s.args.posonlyargs + s.args.args
                                      + s.args.kwonlyargs)}
            for sub in s.body:
                expr(sub, assigned, skip=params)
            assigned.add(s.name)
        elif isinstance(s, (ast.Expr, ast.Return, ast.Raise, ast.Assert,
                            ast.Delete)):
            expr(s, assigned)
        else:
            # Try, Match, imports, ...: conservative — all loads are reads,
            # nothing definitely assigned
            expr(s, assigned)
    expr(test, set(pre_assigned))
    block(list(body), set(pre_assigned))
    return reads & written


class _Disallowed(ast.NodeVisitor):
    """Detects constructs the v1 transform can't capture inside a branch or
    loop body: return, break/continue that target the statement being
    transformed (or an enclosing loop), del, global/nonlocal. Nested
    function scopes own their returns; fully-nested loops own their
    breaks."""

    def __init__(self, is_loop_body: bool):
        self.bad = False
        self._base = 1 if is_loop_body else 0
        self._loop_depth = self._base

    def visit_Return(self, node):
        self.bad = True

    def visit_Yield(self, node):
        self.bad = True

    visit_YieldFrom = visit_Await = visit_Yield

    def visit_If(self, node):
        if getattr(node, "_pt_scrub", False):
            return                    # generated Undefined-scrub guard
        self.generic_visit(node)

    def visit_Delete(self, node):
        self.bad = True

    def visit_Global(self, node):
        self.bad = True

    visit_Nonlocal = visit_Global

    def visit_Break(self, node):
        if self._loop_depth <= self._base:
            self.bad = True

    visit_Continue = visit_Break

    def visit_While(self, node):
        self._loop_depth += 1
        self.generic_visit(node)
        self._loop_depth -= 1

    visit_For = visit_While

    def visit_FunctionDef(self, node):
        pass                      # nested scopes own their returns

    visit_AsyncFunctionDef = visit_FunctionDef
    visit_Lambda = visit_FunctionDef


def _branch_ok(stmts, is_loop_body=False) -> bool:
    d = _Disallowed(is_loop_body)
    for s in stmts:
        d.visit(s)
    return not d.bad


# ---------------------------------------------------------------------------
# the transformer
# ---------------------------------------------------------------------------

def _n(name):
    return ast.Name(id=name, ctx=ast.Load())


def _ns(name):
    return ast.Name(id=name, ctx=ast.Store())


def _jst_attr(name):
    return ast.Attribute(value=_n("__pt_jst__"), attr=name, ctx=ast.Load())


def _lambda0(body_expr):
    return ast.Lambda(
        args=ast.arguments(posonlyargs=[], args=[], kwonlyargs=[],
                           kw_defaults=[], defaults=[]),
        body=body_expr)


def _ld_tuple(names):
    """( _jst.ld(lambda: w, 'w'), ... )"""
    return ast.Tuple(
        elts=[ast.Call(func=_jst_attr("ld"),
                       args=[_lambda0(_n(w)), ast.Constant(w)], keywords=[])
              for w in names],
        ctx=ast.Load())


def _fn_def(name, argnames, body):
    # ld-wrap ONLY the names a generated scrub guard inside this body can
    # del (their read would otherwise raise UnboundLocalError from
    # synthesized code); plain names return bare — the concrete loop path
    # runs this body every iteration and need not pay N lambdas
    scrubbed = set()
    for s in body:
        for n in ast.walk(s):
            if getattr(n, "_pt_scrub", False):
                scrubbed.add(n.body[0].targets[0].id)

    def _ret_elt(a):
        if a in scrubbed:
            return ast.Call(func=_jst_attr("ld"),
                            args=[_lambda0(_n(a)), ast.Constant(a)],
                            keywords=[])
        return _n(a)

    ret = ast.Return(value=ast.Tuple(
        elts=[_ret_elt(a) for a in argnames], ctx=ast.Load()))
    return ast.FunctionDef(
        name=name,
        args=ast.arguments(
            posonlyargs=[], args=[ast.arg(arg=a) for a in argnames],
            kwonlyargs=[], kw_defaults=[], defaults=[]),
        body=list(body) + [ret],
        decorator_list=[], type_params=[])


def _unpack(names, call):
    """w1, ..., wk = call   (or a bare expression statement when k == 0)"""
    if not names:
        return ast.Expr(value=call)
    target = ast.Tuple(elts=[_ns(w) for w in names], ctx=ast.Store())
    return ast.Assign(targets=[target], value=call)


def _scrub_guards(names):
    """One `if __pt_jst__.is_undef(w): del w` per name: an Undefined loop
    temp must not leak through pass-through positions (return, argument,
    container) — deleting it makes any later read raise, matching the
    documented 'reads raise' contract."""
    out = []
    for w in names:
        guard = ast.If(
            test=ast.Call(func=_jst_attr("is_undef"), args=[_n(w)],
                          keywords=[]),
            body=[ast.Delete(targets=[ast.Name(id=w, ctx=ast.Del())])],
            orelse=[])
        # generated construct: its `del` must not disqualify an ENCLOSING
        # loop/branch from conversion (_Disallowed skips marked nodes)
        guard._pt_scrub = True
        out.append(guard)
    return out


def _stmt_may_flag(s) -> bool:
    """Does this statement contain a loop-LEVEL break/continue (not one
    belonging to a nested loop / function)?"""
    d = _Disallowed(is_loop_body=True)
    d.visit(s)
    return d.bad


def _rewrite_break_continue(node: ast.While, uid: int):
    """The reference BreakContinueTransformer (dy2static/break_continue_
    transformer.py), TPU-sized: loop-level ``break``/``continue`` become
    flag assignments; the statements python would have skipped are wrapped
    in ``if __pt_jst__.loop_guard(flags):`` (which the recursive pass then
    lowers like any other if); the loop test becomes
    ``loop_and(loop_not(brk), test)``. Returns (pre_stmts, node, used) —
    used is False when the body has no loop-level break/continue."""
    # NOTE: these are USER-scope variables (threaded through the loop as
    # carried state), so they must not carry the _pt_ prefix that the
    # written-name analysis filters out
    brk = f"_loopbrk_{uid}"
    cont = f"_loopcont_{uid}"
    used = {"b": False, "c": False}

    class R(ast.NodeTransformer):
        def __init__(self):
            self._loop_depth = 0

        def visit_Break(self, n):
            if self._loop_depth == 0:
                used["b"] = True
                return ast.Assign(targets=[_ns(brk)],
                                  value=ast.Constant(True))
            return n

        def visit_Continue(self, n):
            if self._loop_depth == 0:
                used["c"] = True
                return ast.Assign(targets=[_ns(cont)],
                                  value=ast.Constant(True))
            return n

        def visit_While(self, n):
            self._loop_depth += 1
            self.generic_visit(n)
            self._loop_depth -= 1
            return n

        visit_For = visit_While

        def visit_FunctionDef(self, n):
            return n                    # nested scopes own their breaks

        visit_AsyncFunctionDef = visit_FunctionDef
        visit_Lambda = visit_FunctionDef

    def guard_block(stmts):
        """Rewrite one statement list: after any statement that may set a
        flag, the remaining statements run only under the guard."""
        out = []
        for i, s in enumerate(stmts):
            may = _stmt_may_flag(s)
            if isinstance(s, ast.If):
                s = ast.If(test=s.test, body=guard_block(s.body),
                           orelse=guard_block(s.orelse))
            elif isinstance(s, ast.With):
                s = ast.With(items=s.items, body=guard_block(s.body))
            s = R().visit(s)
            out.append(s)
            rest = stmts[i + 1:]
            if may and rest:
                guard = ast.Call(func=_jst_attr("loop_guard"),
                                 args=[_n(brk), _n(cont)], keywords=[])
                out.append(ast.If(test=guard, body=guard_block(rest),
                                  orelse=[]))
                return out
        return out

    # a loop-level break/continue inside a construct guard_block can't
    # guard (Try/Match) would leave its trailing statements unguarded —
    # silently wrong on BOTH paths; bail and leave the loop untransformed
    class _InUnsupported(ast.NodeVisitor):
        def __init__(self):
            self.bad = False
            self._loop = 0
            self._try = 0

        def visit_Break(self, n):
            if self._loop == 0 and self._try > 0:
                self.bad = True

        visit_Continue = visit_Break

        def visit_Try(self, n):
            self._try += 1
            self.generic_visit(n)
            self._try -= 1

        def visit_While(self, n):
            self._loop += 1
            self.generic_visit(n)
            self._loop -= 1

        visit_For = visit_While

        def visit_FunctionDef(self, n):
            pass

        visit_AsyncFunctionDef = visit_FunctionDef
        visit_Lambda = visit_FunctionDef
        if hasattr(ast, "Match"):
            visit_Match = visit_Try

    chk = _InUnsupported()
    for s in node.body:
        chk.visit(s)
    if chk.bad:
        return [], node, False

    new_body = guard_block(list(node.body))
    if not (used["b"] or used["c"]):
        return [], node, False
    # reset the continue flag at the top of every iteration
    new_body = [ast.Assign(targets=[_ns(cont)],
                           value=ast.Constant(False))] + new_body
    # short-circuiting test (see loop_test): `not brk and <test>` with
    # python semantics on concrete flags
    new_test = ast.Call(
        func=_jst_attr("loop_test"),
        args=[_n(brk), _lambda0(node.test)],
        keywords=[])
    new_node = ast.While(test=new_test, body=new_body, orelse=[])
    pre = [ast.Assign(targets=[_ns(brk)], value=ast.Constant(False)),
           ast.Assign(targets=[_ns(cont)], value=ast.Constant(False))]
    return pre, new_node, True


def _has_loop_level_break(stmts) -> bool:
    class V(ast.NodeVisitor):
        found = False

        def __init__(self):
            self._depth = 0

        def visit_Break(self, n):
            if self._depth == 0:
                self.found = True

        def visit_While(self, n):
            # the body belongs to the INNER loop, but a break in the
            # else clause targets the ENCLOSING loop (python scoping)
            self._depth += 1
            for s in n.body:
                self.visit(s)
            self._depth -= 1
            for s in n.orelse:
                self.visit(s)

        visit_For = visit_While

        def visit_FunctionDef(self, n):
            pass

        visit_AsyncFunctionDef = visit_FunctionDef
        visit_Lambda = visit_FunctionDef

    v = V()
    for s in stmts:
        v.visit(s)
    return v.found


def _rewrite_breaks_clear_flag(stmts, flag: str):
    """Prefix every loop-LEVEL break with ``<flag> = False`` (nested
    loops own their body breaks — but a break in a nested loop's ELSE
    clause targets the enclosing loop, python scoping)."""
    class B(ast.NodeTransformer):
        def __init__(self):
            self._depth = 0

        def _block(self, stmts_):
            out = []
            for s in stmts_:
                r = self.visit(s)
                out.extend(r if isinstance(r, list) else [r])
            return out

        def visit_Break(self, n):
            if self._depth == 0:
                return [ast.Assign(targets=[_ns(flag)],
                                   value=ast.Constant(False)),
                        ast.Break()]
            return n

        def visit_While(self, n):
            self._depth += 1
            n.body = self._block(n.body)
            self._depth -= 1
            n.orelse = self._block(n.orelse)
            return n

        visit_For = visit_While

        def visit_FunctionDef(self, n):
            return n

        visit_AsyncFunctionDef = visit_FunctionDef
        visit_Lambda = visit_FunctionDef

    return B()._block(stmts)


class _ControlFlowTransformer(ast.NodeTransformer):
    def __init__(self):
        self.counter = 0
        self.applied = 0
        # names written through a cell or the module dict somewhere in the
        # function tree (nonlocal/global declarations); per-site fallback
        # below, instead of the old whole-function bail
        self._contaminated: Set[str] = frozenset()
        # names assigned anywhere in the current function scope — used to
        # rule out locally-shadowed `enumerate`/`zip` before treating a
        # for-iter syntactically
        self._assigned: Set[str] = frozenset()

    def _uid(self):
        self.counter += 1
        return self.counter

    def _threads_contaminated(self, names) -> bool:
        """Per-site nonlocal/global containment (VERDICT r4 item 4): a
        converted statement threads its written names BY VALUE through
        generated function parameters; if one of those names is written
        through a cell (`nonlocal`) or the module dict (`global`) anywhere
        in this function tree, a mutation by a call inside the statement
        could not be observed and the conversion would silently diverge —
        that statement falls back, the rest of the function still
        converts. (Reads of such names are safe: non-parameter reads
        resolve lexically through the live cell.)"""
        return bool(set(names) & self._contaminated)

    # NESTED defs get the full conversion too (the reference converts
    # called functions via convert_call): their scopes are independent, so
    # the same per-function pipeline — return capture then statement
    # transforms — runs on each body. Generated _pt_* helpers are left
    # alone (nested only — a USER function may carry any name). Lambdas
    # and async defs stay untouched.
    def visit_FunctionDef(self, node, top: bool = False):
        if not top and node.name.startswith(("_pt_", "__pt_")):
            return node
        outer_contam, outer_assigned = self._contaminated, self._assigned
        self._contaminated = outer_contam | {
            name for n in ast.walk(node)
            if isinstance(n, (ast.Nonlocal, ast.Global))
            for name in n.names}
        a = node.args
        # UNION with the enclosing scope's assignments: a name this scope
        # does not assign resolves lexically, so an outer shadow of
        # range/enumerate/zip must also disable the structural treatment
        # inside nested defs
        self._assigned = (outer_assigned
                          | _written_names(node.body)
                          | {x.arg for x in a.args + a.posonlyargs
                             + a.kwonlyargs}
                          | ({a.vararg.arg} if a.vararg else set())
                          | ({a.kwarg.arg} if a.kwarg else set()))
        try:
            # return-capture threads only the generated _retval_N name —
            # never a user name — so it is contamination-safe by design
            node.body = _rewrite_returns(node.body, self._uid())
            new_body = []
            for s in node.body:
                r = self.visit(s)  # dispatches nested/async defs correctly
                new_body.extend(r if isinstance(r, list) else [r])
            node.body = new_body
        finally:
            self._contaminated, self._assigned = outer_contam, outer_assigned
        return node

    def visit_AsyncFunctionDef(self, node):
        return node

    def visit_Lambda(self, node):
        return node

    def visit_If(self, node: ast.If):
        node = self.generic_visit(node)
        if _has_walrus(node.test):
            # a walrus in the test binds in the enclosing scope; moving the
            # test into a lambda would silently change that — leave as is
            return node
        if not (_branch_ok(node.body) and _branch_ok(node.orelse)):
            return node
        written = sorted(_written_names(node.body) |
                         _written_names(node.orelse))
        # contamination must be judged on the FULL written set, BEFORE the
        # live-out filter: a cell-written name assigned in a tail-folded
        # branch would otherwise be filtered out of `written`, convert,
        # and bind a plain local instead of the cell
        if self._threads_contaminated(written):
            return node
        live_out = getattr(node, "_pt_live_out", None)
        if live_out is not None:
            written = sorted(set(written) & live_out)
        k = self._uid()
        tname, fname = f"_pt_true_{k}", f"_pt_false_{k}"
        tdef = _fn_def(tname, written, node.body)
        fdef = _fn_def(fname, written, node.orelse or [ast.Pass()])
        call = ast.Call(
            func=_jst_attr("run_if"),
            args=[_lambda0(node.test), _n(tname), _n(fname),
                  _ld_tuple(written)],
            keywords=[])
        self.applied += 1
        return [tdef, fdef, _unpack(written, call)]

    def _desugar_loop_orelse(self, node):
        """``while``/``for`` with an ``else`` clause (r5, reference
        LoopTransformer parity): python runs the else body iff the loop
        exits through its condition/iterator rather than a break. Without
        a loop-level break the else body simply follows the loop; with
        one, an ``_elseok`` flag is cleared on every loop-level break and
        guards the else — the pieces then convert like any other loop +
        if (the flag becomes carried state, so a TRACED break flag makes
        the else a lax.cond). Exact python semantics either way."""
        core = (ast.While(test=node.test, body=list(node.body), orelse=[])
                if isinstance(node, ast.While)
                else ast.For(target=node.target, iter=node.iter,
                             body=list(node.body), orelse=[]))
        if not _has_loop_level_break(node.body):
            return [core] + list(node.orelse)
        flag = f"_elseok_{self._uid()}"
        core.body = _rewrite_breaks_clear_flag(core.body, flag)
        return [ast.Assign(targets=[_ns(flag)], value=ast.Constant(True)),
                core,
                ast.If(test=_n(flag), body=list(node.orelse), orelse=[])]

    def _visit_desugared(self, stmts):
        out = []
        for s in stmts:
            r = self.visit(s)
            out.extend(r if isinstance(r, list) else [r])
        return out

    def visit_While(self, node: ast.While):
        if node.orelse:
            return self._visit_desugared(self._desugar_loop_orelse(node))
        pre = []
        if not _has_walrus(node.test):
            # loop-level break/continue -> flag rewrite (reference
            # BreakContinueTransformer) BEFORE the recursive pass, so the
            # generated guard ifs get converted like any other
            pre, node, _ = _rewrite_break_continue(node, self._uid())
        node = self.generic_visit(node)
        if (_has_walrus(node.test)
                or not _branch_ok(node.body, is_loop_body=True)):
            return pre + [node] if pre else node
        written = _written_names(node.body)
        carried = sorted(_carried_names(node.test, node.body, written))
        temps = sorted(written - set(carried))
        ordered = carried + temps
        if self._threads_contaminated(ordered):
            return pre + [node] if pre else node
        k = self._uid()
        cname, bname = f"_pt_wcond_{k}", f"_pt_wbody_{k}"
        cdef = ast.FunctionDef(
            name=cname,
            args=ast.arguments(
                posonlyargs=[], args=[ast.arg(arg=a) for a in ordered],
                kwonlyargs=[], kw_defaults=[], defaults=[]),
            body=[ast.Return(value=node.test)],
            decorator_list=[], type_params=[])
        bdef = _fn_def(bname, ordered, node.body)
        call = ast.Call(
            func=_jst_attr("run_while"),
            args=[_n(cname), _n(bname), _ld_tuple(ordered),
                  ast.Constant(tuple(ordered)), ast.Constant(len(carried))],
            keywords=[])
        self.applied += 1
        return (pre + [cdef, bdef, _unpack(ordered, call)]
                + _scrub_guards(temps))

    def _desugar_for_range_with_break(self, node: ast.For):
        """for <name> in range(...) whose body has loop-level break/
        continue: desugar to the canonical while so the flag rewrite and
        all while machinery apply. Concrete-path python semantics are
        exact (target rebound from the counter each iteration, unbound on
        zero-trip). Under a TRACED predicate the usual promotion rule
        applies: a post-loop read of the target needs a pre-loop initial
        value (clear NameError says so), like any other loop temp."""
        k = self._uid()
        cnt, stop, step = f"_fori_{k}", f"_fstop_{k}", f"_fstep_{k}"
        args = list(node.iter.args)
        if len(args) == 1:
            start_e, stop_e, step_e = ast.Constant(0), args[0], \
                ast.Constant(1)
        elif len(args) == 2:
            start_e, stop_e = args
            step_e = ast.Constant(1)
        else:
            start_e, stop_e, step_e = args[:3]
        pre = [ast.Assign(targets=[_ns(cnt)], value=start_e),
               ast.Assign(targets=[_ns(stop)], value=stop_e),
               ast.Assign(targets=[_ns(step)], value=step_e)]
        test = ast.Call(func=_jst_attr("range_cond"),
                        args=[_n(cnt), _n(stop), _n(step)], keywords=[])
        # increment BEFORE the body: a converted `continue` must still
        # advance the counter (python's for advances the iterator first)
        body = ([ast.Assign(targets=[ast.Name(id=node.target.id,
                                              ctx=ast.Store())],
                            value=_n(cnt)),
                 ast.Assign(targets=[_ns(cnt)],
                            value=ast.BinOp(left=_n(cnt), op=ast.Add(),
                                            right=_n(step)))]
                + list(node.body))
        return pre + [ast.While(test=test, body=body, orelse=[])]

    def _is_builtin_range_for(self, node: ast.For) -> bool:
        """``for <Name> in range(1..3 plain args)`` with `range` not
        locally shadowed — the ONE predicate both the desugar path and
        the plain run_for_range path must agree on."""
        return (isinstance(node.target, ast.Name)
                and isinstance(node.iter, ast.Call)
                and isinstance(node.iter.func, ast.Name)
                and node.iter.func.id == "range"
                and "range" not in self._assigned
                and not node.iter.keywords
                and len(node.iter.args) in (1, 2, 3)
                and not any(isinstance(a, ast.Starred)
                            for a in node.iter.args))

    def visit_For(self, node: ast.For):
        if node.orelse:
            return self._visit_desugared(self._desugar_loop_orelse(node))
        if (not _has_walrus(node.iter)
                and self._is_builtin_range_for(node)
                and any(_stmt_may_flag(s) for s in node.body)
                and not _return_in_unsupported([node])):
            # loop-level break/continue -> desugar to while and recurse
            stmts = self._desugar_for_range_with_break(node)
            out = []
            for s in stmts:
                r = self.visit(s)
                out.extend(r if isinstance(r, list) else [r])
            return out
        node = self.generic_visit(node)
        if (_has_walrus(node.iter)
                or not _branch_ok(node.body, is_loop_body=True)):
            return node
        if self._is_builtin_range_for(node):
            idx = node.target.id
            written = _written_names(node.body) - {idx}
            carried = sorted(_carried_names(None, node.body, written,
                                            pre_assigned={idx}))
            temps = sorted(written - set(carried))
            ordered = carried + temps
            if self._threads_contaminated([idx] + ordered):
                return node
            k = self._uid()
            bname = f"_pt_fbody_{k}"
            bdef = _fn_def(bname, [idx] + ordered, node.body)
            range_args = ast.Tuple(elts=list(node.iter.args), ctx=ast.Load())
            call = ast.Call(
                func=_jst_attr("run_for_range"),
                args=[_lambda0(range_args), _n(bname),
                      _ld_tuple([idx] + ordered),
                      ast.Constant(tuple([idx] + ordered)),
                      ast.Constant(len(carried))],
                keywords=[])
            self.applied += 1
            return ([bdef, _unpack([idx] + ordered, call)]
                    + _scrub_guards(temps))
        return self._convert_for_iter(node)

    def _convert_for_iter(self, node: ast.For):
        """``for <targets> in <iterable>`` capture (ref convert_for_iter /
        convert_enumerate parity): plain iterables, ``enumerate(E[,
        start])`` and ``zip(E1, ..)`` are routed through run_for_iter —
        exact python semantics on concrete iterables, bounded-scan
        lowering over the static leading axis when a component is a traced
        Tensor. enumerate/zip are only treated structurally when the name
        is not shadowed by a local assignment."""
        if isinstance(node.target, ast.Name):
            targets = [node.target.id]
        elif (isinstance(node.target, ast.Tuple)
              and node.target.elts
              and all(isinstance(e, ast.Name) for e in node.target.elts)):
            targets = [e.id for e in node.target.elts]
        else:
            return node

        kind, comp_exprs = "plain", [node.iter]
        start_expr = ast.Constant(None)
        it = node.iter
        if (isinstance(it, ast.Call) and isinstance(it.func, ast.Name)
                and it.func.id in ("enumerate", "zip")
                and it.func.id not in self._assigned
                and not any(isinstance(a, ast.Starred) for a in it.args)):
            if (it.func.id == "enumerate" and 1 <= len(it.args) <= 2
                    and len(it.keywords) <= 1
                    and all(kw.arg == "start" for kw in it.keywords)
                    and not (len(it.args) == 2 and it.keywords)):
                kind, comp_exprs = "enumerate", [it.args[0]]
                start_expr = (it.args[1] if len(it.args) == 2
                              else (it.keywords[0].value if it.keywords
                                    else ast.Constant(0)))
            elif it.func.id == "zip" and not it.keywords and it.args:
                kind, comp_exprs = "zip", list(it.args)

        written = _written_names(node.body) - set(targets)
        carried = sorted(_carried_names(None, node.body, written,
                                        pre_assigned=set(targets)))
        temps = sorted(written - set(carried))
        ordered = carried + temps
        if self._threads_contaminated(targets + ordered):
            return node
        k = self._uid()
        bname = f"_pt_ibody_{k}"
        bdef = _fn_def(bname, targets + ordered, node.body)
        thunk = _lambda0(ast.Tuple(elts=[
            ast.Constant(kind),
            ast.Tuple(elts=comp_exprs, ctx=ast.Load()),
            start_expr], ctx=ast.Load()))
        call = ast.Call(
            func=_jst_attr("run_for_iter"),
            args=[thunk, _n(bname), _ld_tuple(targets + ordered),
                  ast.Constant(tuple(targets + ordered)),
                  ast.Constant(len(carried)),
                  ast.Constant(len(targets))],
            keywords=[])
        self.applied += 1
        return ([bdef, _unpack(targets + ordered, call)]
                + _scrub_guards(temps))


# ---------------------------------------------------------------------------
# convert()
# ---------------------------------------------------------------------------

def _walk_same_scope(node):
    """ast.walk that does NOT descend into nested function/lambda scopes
    (their returns/names belong to them, not the function under rewrite).
    The scope nodes themselves are yielded; their interiors never are —
    including when ``node`` itself is one (callers pass STATEMENTS; a def
    statement owns its returns)."""
    stack = [node]
    while stack:
        n = stack.pop()
        yield n
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(n))


def _always_returns(stmts, allow_raise: bool = True) -> bool:
    """Every path through this statement list ends in an explicit Return
    (or raise). ``with`` blocks are transparent for RETURN (no context
    manager can suppress one) but NOT for raise — ``__exit__`` may
    swallow the exception and fall through (contextlib.suppress)."""
    if not stmts:
        return False
    last = stmts[-1]
    if isinstance(last, ast.Return):
        return True
    if isinstance(last, ast.Raise):
        return allow_raise
    if isinstance(last, ast.If):
        return (_always_returns(last.body, allow_raise) and last.orelse
                and _always_returns(last.orelse, allow_raise))
    if isinstance(last, ast.With):
        return _always_returns(last.body, allow_raise=False)
    return False


def _return_in_unsupported(stmts) -> bool:
    """Is any function-level Return nested in a loop/try (the v1
    return-capture can't fold those)?"""
    class V(ast.NodeVisitor):
        bad = False

        def __init__(self):
            self._depth = 0

        def visit_Return(self, n):
            if self._depth > 0:
                self.bad = True

        def _enter(self, n):
            self._depth += 1
            self.generic_visit(n)
            self._depth -= 1

        visit_While = visit_For = visit_Try = _enter

        def visit_FunctionDef(self, n):
            pass

        visit_AsyncFunctionDef = visit_FunctionDef
        visit_Lambda = visit_FunctionDef

    v = V()
    for s in stmts:
        v.visit(s)
    return v.bad


def _returns_are_leaf_only(stmts, tail=True) -> bool:
    """After folding, EVERY Return must sit in a terminal leaf position:
    last statement of its block, with every enclosing construct an If that
    is itself the last statement of its block, up to the function body. A
    Return anywhere else (inside With/Try, or mid-body) would become an
    assignment that silently falls through — refuse the rewrite."""
    for i, s in enumerate(stmts):
        last = i == len(stmts) - 1
        if isinstance(s, ast.Return):
            if not (tail and last):
                return False
        elif isinstance(s, ast.If):
            if not _returns_are_leaf_only(s.body, tail and last):
                return False
            if not _returns_are_leaf_only(s.orelse, tail and last):
                return False
        elif isinstance(s, ast.With):
            # transparent for control flow; terminal only in tail position
            if not _returns_are_leaf_only(s.body, tail and last):
                return False
        else:
            for n in _walk_same_scope(s):
                if isinstance(n, ast.Return):
                    return False
    return True


def _fold_early_returns(stmts):
    """Normalize early returns (the reference ReturnTransformer's core
    move): ``if p: return a`` followed by REST becomes ``if p: return a
    else: REST`` — after which every Return sits on an else-paired leaf
    and the ordinary if-capture handles a tensor-valued ``p``."""
    out = []
    for i, s in enumerate(stmts):
        if isinstance(s, ast.If):
            body = _fold_early_returns(s.body)
            orelse = _fold_early_returns(s.orelse)
            rest = stmts[i + 1:]
            if (_always_returns(body) and not orelse and rest):
                folded = ast.If(test=s.test, body=body,
                                orelse=_fold_early_returns(rest))
                folded._pt_folded = True
                return out + [folded]
            if (orelse and _always_returns(orelse)
                    and not _always_returns(body) and rest):
                # mirrored: else-branch returns, fall-through continues
                folded = ast.If(
                    test=s.test,
                    body=body + _fold_early_returns(rest),
                    orelse=orelse)
                folded._pt_folded = True
                return out + [folded]
            s = ast.If(test=s.test, body=body, orelse=orelse)
        elif isinstance(s, ast.With):
            s = ast.With(items=s.items,
                         body=_fold_early_returns(s.body))
        out.append(s)
    return out


class _ReturnToAssign(ast.NodeTransformer):
    """Replace function-level Return nodes with ``_retv_N = value`` (the
    epilogue returns it). Runs AFTER folding, so every Return is a leaf."""

    def __init__(self, retv: str):
        self.retv = retv

    def visit_Return(self, node):
        val = node.value if node.value is not None else ast.Constant(None)
        return ast.Assign(targets=[_ns(self.retv)], value=val)

    def visit_FunctionDef(self, node):
        return node

    visit_AsyncFunctionDef = visit_FunctionDef
    visit_Lambda = visit_FunctionDef

    def visit_While(self, node):
        return node        # bailed earlier if returns live in loops

    visit_For = visit_While


def _rewrite_returns(body, uid: int):
    """Capture early returns: fold trailing code into else-branches so
    every Return is an else-paired leaf, convert Returns to assignments of
    ``_retv_N``, and append the single real return. Applies only when
    every path explicitly returns and no Return hides in a loop/try —
    otherwise the body is returned unchanged (concrete predicates keep
    working via the plain python path)."""
    n_returns = sum(isinstance(n, ast.Return)
                    for s in body for n in _walk_same_scope(s))
    trailing_only = (n_returns == 1 and isinstance(body[-1], ast.Return))
    if n_returns == 0 or trailing_only:
        return body
    if _return_in_unsupported(body):
        return body
    folded = _fold_early_returns(body)
    if not _always_returns(folded):
        return body            # fall-off-the-end path: leave untouched
    if not _returns_are_leaf_only(folded):
        # a Return the fold could not move to a terminal position (With/
        # nested-in-non-returning-branch): converting it would silently
        # fall through — leave the function untouched
        return body
    retv = f"_retval_{uid}"
    tr = _ReturnToAssign(retv)
    new = [tr.visit(s) for s in folded]
    # a folded if sits in TAIL position: the only name live after it is
    # the return variable — mark it so the if-capture does not thread the
    # tail's branch-local temps as outputs (they'd need both-branch
    # assignment for no reason)
    for s in new:
        for n in ast.walk(s):
            if getattr(n, "_pt_folded", False):
                n._pt_live_out = {retv}
    return new + [ast.Return(value=_n(retv))]


def _has_nonlocal_or_global(tree) -> bool:
    return any(isinstance(n, (ast.Nonlocal, ast.Global))
               for n in ast.walk(tree))


def _has_walrus(node) -> bool:
    return node is not None and any(
        isinstance(n, ast.NamedExpr) for n in ast.walk(node))


def convert(fn: Callable) -> Callable:
    """Return ``fn`` with python control flow rewritten to the runtime
    dispatchers, or ``fn`` unchanged if the source is unavailable or the
    transform does not apply. Bound methods are converted and re-bound."""
    if isinstance(fn, types.MethodType):
        inner = convert(fn.__func__)
        if inner is fn.__func__:
            return fn
        return types.MethodType(inner, fn.__self__)
    if getattr(fn, "__pt_dy2static__", False):
        return fn
    # wrapper callables (functools.lru_cache, partial, C functions) have no
    # __code__/__globals__ — leave them for StaticFunction to trace directly
    if (getattr(fn, "__code__", None) is None
            or getattr(fn, "__globals__", None) is None):
        return fn
    try:
        src = textwrap.dedent(inspect.getsource(fn))
        tree = ast.parse(src)
    except (OSError, TypeError, IndentationError, SyntaxError):
        return fn
    fndef = next((n for n in tree.body
                  if isinstance(n, ast.FunctionDef)
                  and n.name == fn.__name__), None)
    if fndef is None:
        return fn
    # nonlocal/global no longer bail the whole function: the transformer
    # contains them per-site (statements threading a cell/global-written
    # name fall back individually; see _threads_contaminated)

    tr = _ControlFlowTransformer()
    # visit_FunctionDef runs the whole per-function pipeline (early-return
    # capture, then the statement transforms) and recurses into nested
    # defs, which the reference converts via convert_call
    fndef = tr.visit_FunctionDef(fndef, top=True)
    if tr.applied == 0:
        return fn
    fndef.decorator_list = []

    freevars = fn.__code__.co_freevars
    module = ast.Module(body=[fndef], type_ignores=[])
    if freevars:
        outer = ast.FunctionDef(
            name="_pt_make",
            args=ast.arguments(
                posonlyargs=[], args=[ast.arg(arg=v) for v in freevars],
                kwonlyargs=[], kw_defaults=[], defaults=[]),
            body=[fndef, ast.Return(value=_n(fndef.name))],
            decorator_list=[], type_params=[])
        module = ast.Module(body=[outer], type_ignores=[])
    ast.fix_missing_locations(module)

    # execute against the LIVE module globals (a later rebinding of a global
    # the function reads must stay visible, exactly as in the original fn);
    # only the reserved dispatcher name is injected
    from . import dy2static as _self
    glob = fn.__globals__
    if glob.get("__pt_jst__", _self) is not _self:
        glob = dict(fn.__globals__)       # unlikely collision: fall back
    glob["__pt_jst__"] = _self
    fname = f"<dy2static {getattr(fn, '__module__', '?')}." \
            f"{fn.__qualname__}>"
    try:
        code = compile(module, filename=fname, mode="exec")
        ns: dict = {}
        exec(code, glob, ns)
        if freevars:
            # rebuild with the ORIGINAL closure cells so later rebindings of
            # the enclosing scope's variables stay visible
            make = ns["_pt_make"]
            inner_code = next(
                c for c in make.__code__.co_consts
                if isinstance(c, types.CodeType) and c.co_name == fndef.name)
            cellmap = dict(zip(fn.__code__.co_freevars, fn.__closure__ or ()))
            closure = tuple(cellmap[v] for v in inner_code.co_freevars)
            new_fn = types.FunctionType(inner_code, glob, fn.__name__,
                                        fn.__defaults__, closure)
        else:
            new_fn = ns[fndef.name]
    except Exception:
        return fn
    new_fn.__defaults__ = fn.__defaults__
    new_fn.__kwdefaults__ = fn.__kwdefaults__
    functools.update_wrapper(new_fn, fn, updated=[])
    new_fn.__pt_dy2static__ = True
    return new_fn
