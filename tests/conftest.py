"""Test configuration: run the suite on a simulated 8-device CPU mesh.

SURVEY §4.2 build lesson: the reference tests distributed logic single-host
(Gloo fake, subprocess ranks); the TPU-native equivalent is
xla_force_host_platform_device_count so sharding/collective tests execute a
real 8-way SPMD program without hardware. Must run before jax import.
"""

import os

# force CPU even though the session profile exports JAX_PLATFORMS=axon (the
# real chip): the 8-device simulated mesh only exists on the cpu platform
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    flags = (flags + " --xla_force_host_platform_device_count=8").strip()
# 8 virtual devices share one physical core: a lagging device thread can
# miss XLA-CPU's default 40s collective rendezvous kill on a busy host


def _xla_knows(flag_name: str) -> bool:
    """True when the installed jaxlib's XLA recognizes `flag_name`. Older
    XLA builds hard-abort the process on any unknown flag in XLA_FLAGS
    (parse_flags_from_env), so probe the binary before opting in."""
    try:
        import mmap
        import jaxlib
        so = os.path.join(os.path.dirname(jaxlib.__file__),
                          "xla_extension.so")
        with open(so, "rb") as f:
            with mmap.mmap(f.fileno(), 0, access=mmap.ACCESS_READ) as m:
                return m.find(flag_name.encode()) != -1
    except Exception:
        return False


if "xla_cpu_collective_call_terminate_timeout_seconds" not in flags and \
        _xla_knows("xla_cpu_collective_call_terminate_timeout_seconds"):
    flags += (" --xla_cpu_collective_call_terminate_timeout_seconds=900"
              " --xla_cpu_collective_call_warn_stuck_timeout_seconds=300")
os.environ["XLA_FLAGS"] = flags
# keep CI deterministic and quiet
os.environ.setdefault("JAX_ENABLE_X64", "0")

import jax  # noqa: E402

# env var alone loses to the preinstalled axon PJRT plugin in this image; the
# config update is authoritative
jax.config.update("jax_platforms", "cpu")

# numerics tests compare against f32 references; the TPU-idiomatic low default
# (bf16 MXU passes) is exercised explicitly by the kernel/perf tests instead
jax.config.update("jax_default_matmul_precision", "highest")

# persistent compilation cache: the suite is compile-bound; cached XLA
# executables cut full-suite time from ~20min to a few minutes on reruns
jax.config.update("jax_compilation_cache_dir", "/tmp/paddle_tpu_jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)


# ---------------------------------------------------------------------------
# global-state hygiene: tests that fleet.init() a hybrid mesh must not leak
# it into later tests (the ambient mesh changes eager-collective routing)
# ---------------------------------------------------------------------------
import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _restore_global_mesh():
    from paddle_tpu.distributed.mesh import get_mesh, set_mesh
    from paddle_tpu.distributed import fleet
    prev = get_mesh()
    prev_fleet = dict(fleet._fleet_state)
    yield
    set_mesh(prev)
    fleet._fleet_state.clear()
    fleet._fleet_state.update(prev_fleet)


# ---------------------------------------------------------------------------
# XLA/jax capability probes: legacy installs (jax 0.4.x) cannot run two
# feature sets the pipeline/multihost tests depend on. Probe the actual
# capability (never the version string) and skip the known dependents with
# an explicit reason instead of letting them fail.
# ---------------------------------------------------------------------------
_probe_cache: dict = {}


def _partial_auto_axis_index_ok() -> bool:
    """True when a PARTIAL-AUTO shard_map body may call lax.axis_index:
    on legacy jax the combination lowers to a PartitionId instruction
    XLA's SPMD partitioner rejects (UNIMPLEMENTED) — the exact pattern
    the pipeline-parallel schedules use for stage identity."""
    if "partial_auto" not in _probe_cache:
        try:
            import numpy as np
            import jax.numpy as jnp
            from jax.sharding import Mesh, PartitionSpec as P
            from paddle_tpu.distributed._compat import shard_map

            devs = jax.devices()
            if len(devs) < 4:
                # can't build a non-trivial auto axis — don't skip
                _probe_cache["partial_auto"] = True
                return True
            # the auto axis must be >1 wide: with a trivial auto axis
            # the partitioner never emits the ambiguous PartitionId
            mesh = Mesh(np.array(devs[:4]).reshape(2, 2),
                        ("_pa", "_pb"))

            def body(x):
                return x + jax.lax.axis_index("_pa").astype(jnp.float32)

            f = shard_map(body, mesh=mesh, in_specs=P("_pa"),
                          out_specs=P("_pa"), axis_names={"_pa"})
            jax.jit(f)(jnp.zeros((2,), jnp.float32)).block_until_ready()
            _probe_cache["partial_auto"] = True
        except Exception:
            _probe_cache["partial_auto"] = False
    return _probe_cache["partial_auto"]


def _multihost_workers_ok() -> bool:
    """The multihost tests launch subprocess workers that call
    jax.distributed.is_initialized — absent on legacy jax."""
    if "multihost" not in _probe_cache:
        _probe_cache["multihost"] = hasattr(jax.distributed,
                                            "is_initialized")
    return _probe_cache["multihost"]


# (file basename, test-name prefixes) — prefixes cover parametrized ids
_PARTIAL_AUTO_DEPENDENTS = {
    "test_pipeline.py": ("test_gpipe_matches_sequential",
                         "test_vpp_matches_sequential",
                         "test_vpp_grad_flows"),
    "test_pipeline_bf16.py": ("test_bf16_pipeline_matches_f32",),
    "test_pp_exec.py": ("test_pretrain_step_1f1b_matches_compiled",
                        "test_pretrain_step_zbh1_runs",
                        "test_pretrain_step_vpp_timetable_matches_compiled",
                        "test_pretrain_step_1f1b_composes_with_sep_axis"),
}
_MULTIHOST_DEPENDENTS = {
    "test_multihost.py": ("test_two_process_launch_psum_across_8_devices",
                          "test_two_process_hybrid_train_loss_parity",
                          "test_launcher_driven_cli_loss_parity"),
}


def _match(item, table) -> bool:
    prefixes = table.get(item.fspath.basename)
    return bool(prefixes) and item.name.startswith(prefixes)


def pytest_collection_modifyitems(config, items):
    pa_mark = mh_mark = None
    for item in items:
        if _match(item, _PARTIAL_AUTO_DEPENDENTS):
            if _partial_auto_axis_index_ok():
                continue
            if pa_mark is None:
                pa_mark = pytest.mark.skip(reason=(
                    "legacy jax: partial-auto shard_map + axis_index "
                    "lowers to PartitionId, unimplemented in this XLA's "
                    "SPMD partitioner (capability probe)"))
            item.add_marker(pa_mark)
        elif _match(item, _MULTIHOST_DEPENDENTS):
            if _multihost_workers_ok():
                continue
            if mh_mark is None:
                mh_mark = pytest.mark.skip(reason=(
                    "legacy jax: jax.distributed.is_initialized missing "
                    "— subprocess workers cannot join (capability probe)"))
            item.add_marker(mh_mark)
