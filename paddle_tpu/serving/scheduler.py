"""In-flight (continuous-batching) request scheduler.

Pure host-side state machine — the engine (engine.py) owns the device
work and drives this scheduler once per `step()`:

  - FCFS admission into a FIXED number of decode slots (the jitted
    decode step has a static batch dimension; joining or leaving a slot
    never retraces it — paddlelint PT002);
  - admission backpressure reusing `inference.Config.set_admission`
    semantics: `max_inflight` bounds admitted requests, and with
    `queue_timeout_s == 0` a submit that cannot be admitted is refused
    with `resilience.Overloaded` at the door (the Predictor's
    non-blocking gate); with a positive timeout requests may queue and
    are expired with an `Overloaded` result once they wait longer;
  - per-request deadlines (`inference.Config.set_deadline` or
    `Request(deadline_s=...)`) produce falsy `resilience.TimeoutResult`
    partial results, never hangs;
  - priority / fair-share classes: `Request(priority=..., tenant=...)`
    plus per-tenant in-flight token budgets (`tenant_budgets`) on the
    admission gate. Admission picks the highest-priority, oldest
    budget-eligible request; over-budget tenants are skipped (their
    requests wait, others flow). With the defaults — every request at
    priority 0, no budgets — this reduces exactly to the original FCFS
    head-of-line order, so seeded traces stay deterministic;
  - preemption: a DECODE-state victim of strictly lower priority can be
    re-queued (`preempt()`) to make room for a higher-priority arrival.
    The victim keeps its allocator sequence — pages and reservation
    intact — and is re-admitted straight into DECODE without any
    re-prefill, so engine output is unchanged, only its latency.
"""

from __future__ import annotations

import itertools
import time
from collections import deque
from typing import List, Optional, Tuple

import numpy as np

from .. import resilience as _res
from ..observability import tracing as _tracing

_TRACE = _tracing.recorder()

__all__ = ["Request", "Scheduler",
           "WAITING", "PREFILL", "DECODE", "FINISHED"]

WAITING = "waiting"
PREFILL = "prefill"
DECODE = "decode"
FINISHED = "finished"

_ids = itertools.count()


class Request:
    """One generation request. `tokens` accumulates greedy output ids;
    after FINISHED, `result` is an int32 array padded to max_new_tokens
    with pad_token_id (the generate_cached row convention), a falsy
    `resilience.TimeoutResult` carrying the partial tokens on a deadline
    miss, or a `resilience.Overloaded` instance if the request timed out
    of the admission queue."""

    def __init__(self, prompt, max_new_tokens: int,
                 eos_token_id: Optional[int] = None,
                 pad_token_id: int = 0,
                 deadline_s: Optional[float] = None,
                 request_id=None,
                 priority: int = 0,
                 tenant: Optional[str] = None):
        self.prompt = np.asarray(prompt, np.int32).reshape(-1)
        if self.prompt.size < 1:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        self.max_new_tokens = int(max_new_tokens)
        self.eos_token_id = eos_token_id
        self.pad_token_id = int(pad_token_id)
        self.deadline_s = deadline_s
        self.request_id = request_id if request_id is not None \
            else next(_ids)
        self.state = WAITING
        self.slot: Optional[int] = None
        self.tokens: List[int] = []
        self.result = None
        self.pending: Optional[int] = None   # last sampled, not yet fed
        self.prefill_pos = 0                 # prompt tokens in cache
        self.shared_tokens = 0               # prefix tokens riding a donor
        self.priority = int(priority)        # higher = more urgent
        self.tenant = tenant                 # fair-share accounting key
        self.preempted = False               # mid-decode, pages intact
        self._seq: int = 0                   # submit order (set by submit)
        self._share_source = None            # "cache" | "donor" | None
        self._share_meta: dict = {}
        self._deadline: Optional[_res.Deadline] = None
        self._enqueued_at: Optional[float] = None

    @property
    def total_tokens(self) -> int:
        return int(self.prompt.size) + self.max_new_tokens

    def start_deadline(self) -> None:
        if self.deadline_s:
            self._deadline = _res.Deadline(self.deadline_s)

    def deadline_expired(self) -> bool:
        return self._deadline is not None and self._deadline.expired()

    def finalize(self) -> None:
        """Pad tokens to max_new_tokens (generate_cached row shape)."""
        out = np.full(self.max_new_tokens, self.pad_token_id, np.int32)
        out[:len(self.tokens)] = self.tokens
        if self._deadline is not None and self._deadline.expired():
            _res.deadline_miss()
            self.result = _res.TimeoutResult(
                kind="serving_engine", budget_s=self._deadline.budget_s,
                elapsed_s=self._deadline.elapsed_s,
                completed=len(self.tokens), partial=out)
        else:
            self.result = out

    def __repr__(self):
        return (f"Request(id={self.request_id}, state={self.state}, "
                f"prompt={self.prompt.size}, out={len(self.tokens)}/"
                f"{self.max_new_tokens})")


class Scheduler:
    """Continuous-batching scheduler over `max_slots` decode slots:
    FCFS within a priority class, per-tenant token budgets across
    classes, optional preemption of lower-priority decodes."""

    def __init__(self, max_slots: int, max_inflight: Optional[int] = None,
                 queue_timeout_s: float = 0.0,
                 tenant_budgets: Optional[dict] = None):
        if max_slots < 1:
            raise ValueError("max_slots must be >= 1")
        self.max_slots = int(max_slots)
        self.max_inflight = min(int(max_inflight), self.max_slots) \
            if max_inflight else self.max_slots
        self.backpressure = max_inflight is not None
        self.queue_timeout_s = float(queue_timeout_s)
        # tenant -> max in-flight total_tokens. A tenant at zero usage
        # always gets one request through even if it alone exceeds the
        # budget (progress guarantee — budgets shape, never starve).
        self.tenant_budgets = dict(tenant_budgets or {})
        self._tenant_tokens: dict = {}
        # graduated load shedding (the SLO autopilot's level-2 gate):
        # requests with priority < shed_below_priority are refused at
        # the door with `resilience.Shed`; shed_measurement is the
        # controller's triggering measurement, stamped on the terminal
        # `shed` trace event so the timeline answers "why was I shed"
        self.shed_below_priority: Optional[int] = None
        self.shed_measurement: dict = {}
        self.waiting: deque = deque()
        self.slots: List[Optional[Request]] = [None] * self.max_slots
        self.finished: List[Request] = []
        self._submit_seq = itertools.count()

    # ------------------------------------------------------------- queries
    @property
    def inflight(self) -> int:
        return sum(r is not None for r in self.slots)

    def active(self, state: Optional[str] = None):
        """(slot, request) pairs, optionally filtered by state."""
        return [(i, r) for i, r in enumerate(self.slots)
                if r is not None and (state is None or r.state == state)]

    def has_work(self) -> bool:
        return bool(self.waiting) or self.inflight > 0

    # ----------------------------------------------------------- lifecycle
    def submit(self, req: Request) -> Request:
        """Enqueue FCFS. With backpressure and queue_timeout_s == 0, a
        request that cannot be admitted right now is refused with
        `Overloaded` (the Predictor's non-blocking admission gate).
        With the controller's shed gate armed, a request below the
        priority floor is refused with `Shed` — a DISTINCT terminal
        trace outcome from `refused` (gate full) and `overloaded`
        (queue timeout), carrying the triggering measurement."""
        if self.shed_below_priority is not None \
                and req.priority < self.shed_below_priority:
            _TRACE.begin(req.request_id,
                         prompt_len=int(req.prompt.size),
                         max_new_tokens=req.max_new_tokens)
            _TRACE.stamp(req.request_id, "enqueue")
            _TRACE.finish(req.request_id, "shed",
                          priority=req.priority,
                          floor=self.shed_below_priority,
                          **self.shed_measurement)
            raise _res.Shed(
                f"priority {req.priority} < shed floor "
                f"{self.shed_below_priority}",
                measurement=self.shed_measurement)
        if self.backpressure and self.queue_timeout_s <= 0 \
                and self.inflight + len(self.waiting) >= self.max_inflight:
            # refused requests still get a (one-event) timeline so the
            # trace shows WHY they never produced tokens
            _TRACE.begin(req.request_id,
                         prompt_len=int(req.prompt.size),
                         max_new_tokens=req.max_new_tokens)
            _TRACE.stamp(req.request_id, "enqueue")
            _TRACE.finish(req.request_id, "refused",
                          inflight=self.max_inflight)
            raise _res.Overloaded(
                f"admission gate full ({self.max_inflight} inflight)")
        req.state = WAITING
        req._seq = next(self._submit_seq)
        req._enqueued_at = time.monotonic()
        req.start_deadline()
        self.waiting.append(req)
        meta = {}
        if req.priority:
            meta["priority"] = req.priority
        if req.tenant is not None:
            meta["tenant"] = req.tenant
        if req.preempted and _TRACE.is_live(req.request_id):
            # a handed-off / resumed request keeps its source timeline:
            # the cross-replica story (routed → admit → prefill_chunk →
            # handoff_export → handoff_import → resumed) stays ONE trace
            # instead of the re-submit clobbering the earlier events
            _TRACE.stamp(req.request_id, "enqueue", resume=True, **meta)
        else:
            _TRACE.begin(req.request_id, prompt_len=int(req.prompt.size),
                         max_new_tokens=req.max_new_tokens, **meta)
            _TRACE.stamp(req.request_id, "enqueue")
        return req

    def expire_waiting(self) -> List[Request]:
        """Cull queued requests past the admission timeout (and queued
        requests whose own deadline already expired): they finish with
        an Overloaded / TimeoutResult result without touching a slot."""
        expired = []
        keep = deque()
        now = time.monotonic()
        for req in self.waiting:
            # preempted requests were already admitted once: the
            # admission-queue timeout no longer applies (their deadline
            # still does, producing a partial TimeoutResult)
            timed_out = (not req.preempted
                         and self.backpressure and self.queue_timeout_s > 0
                         and now - req._enqueued_at > self.queue_timeout_s)
            if timed_out:
                req.state = FINISHED
                req.result = _res.Overloaded(
                    f"request {req.request_id} waited "
                    f"{now - req._enqueued_at:.3f}s > queue_timeout_s="
                    f"{self.queue_timeout_s}")
                expired.append(req)
                _TRACE.finish(req.request_id, "overloaded",
                              waited_s=now - req._enqueued_at)
            elif req.deadline_expired():
                req.state = FINISHED
                req.finalize()
                expired.append(req)
                _TRACE.finish(req.request_id, "timeout", where="queue")
            else:
                keep.append(req)
        self.waiting = keep
        self.finished.extend(expired)
        return expired

    def _budget_ok(self, req: Request) -> bool:
        budget = self.tenant_budgets.get(req.tenant)
        if budget is None:
            return True
        used = self._tenant_tokens.get(req.tenant, 0)
        return used == 0 or used + req.total_tokens <= budget

    def next_candidate(self) -> Optional[Request]:
        """Highest-priority, oldest budget-eligible waiting request —
        ignoring slot availability (the preemption path asks this)."""
        best = None
        for req in self.waiting:
            if not self._budget_ok(req):
                continue
            if best is None or (req.priority, -req._seq) \
                    > (best.priority, -best._seq):
                best = req
        return best

    def next_admittable(self) -> Optional[Request]:
        """The request `admit()` would take if a slot and an inflight
        credit are free; None otherwise. With all-default priorities
        and no budgets this is exactly the old FCFS head of line —
        nothing behind the head ever jumps it (deterministic under a
        seeded trace)."""
        if not self.waiting or self.inflight >= self.max_inflight \
                or all(r is not None for r in self.slots):
            return None
        return self.next_candidate()

    def admit(self, req: Request) -> int:
        """Bind the chosen waiting request to the lowest free slot. A
        preempted request resumes straight into DECODE — its KV pages
        never left the allocator, so there is nothing to re-prefill."""
        self.waiting.remove(req)
        slot = next(i for i, r in enumerate(self.slots) if r is None)
        req.state = DECODE if req.preempted else PREFILL
        req.slot = slot
        self.slots[slot] = req
        if req.tenant is not None:
            self._tenant_tokens[req.tenant] = \
                self._tenant_tokens.get(req.tenant, 0) + req.total_tokens
        if req.preempted:
            req.preempted = False
            _TRACE.stamp(req.request_id, "resumed", slot=slot,
                         decoded=len(req.tokens))
        else:
            _TRACE.stamp(req.request_id, "admit", slot=slot)
        return slot

    def pick_victim(self, priority: int) -> Optional[Request]:
        """Lowest-priority DECODE-state request strictly below
        `priority` (youngest on ties) — the page-intact preemption
        victim. PREFILL requests are never preempted (their chunk
        bookkeeping is mid-flight)."""
        victim = None
        for _, req in self.active(DECODE):
            if req.priority >= priority:
                continue
            if victim is None or (req.priority, -req._seq) \
                    < (victim.priority, -victim._seq):
                victim = req
        return victim

    def preempt(self, req: Request) -> None:
        """Re-queue a running decode with its allocator sequence —
        pages, length, reservation — intact. Only the slot is given
        up; `admit()` later resumes it without re-prefill."""
        assert req.slot is not None and req.state == DECODE
        self.slots[req.slot] = None
        req.slot = None
        req.state = WAITING
        req.preempted = True
        if req.tenant is not None:
            self._tenant_tokens[req.tenant] = \
                self._tenant_tokens.get(req.tenant, 0) - req.total_tokens
        self.waiting.append(req)
        _TRACE.stamp(req.request_id, "preempted",
                     decoded=len(req.tokens))

    def detach(self, req: Request) -> None:
        """Unbind an in-flight (or preempted-waiting) request from this
        scheduler entirely — the cross-replica handoff path. Unlike
        `preempt()` the request does NOT re-enter the waiting queue: it
        continues on another replica's scheduler, so only the slot (or
        queue position) and the tenant accounting are given up here."""
        if req.slot is not None:
            self.slots[req.slot] = None
            req.slot = None
            if req.tenant is not None:
                self._tenant_tokens[req.tenant] = \
                    self._tenant_tokens.get(req.tenant, 0) \
                    - req.total_tokens
        elif req in self.waiting:
            self.waiting.remove(req)
        _TRACE.stamp(req.request_id, "detached",
                     decoded=len(req.tokens))

    def release(self, req: Request) -> None:
        """Free the slot the instant a request finishes — the next
        step() can admit into it (no drain barrier)."""
        if req.slot is not None:
            self.slots[req.slot] = None
            req.slot = None
            if req.tenant is not None:
                self._tenant_tokens[req.tenant] = \
                    self._tenant_tokens.get(req.tenant, 0) \
                    - req.total_tokens
        req.state = FINISHED
        self.finished.append(req)

    def drain_finished(self) -> List[Request]:
        done, self.finished = self.finished, []
        return done
