"""In-tree fused MLA (multi-head latent attention) decode kernel.

Reference capability: DeepSeek-V2 absorbed-latent decode (PaddleNLP
deepseek_v2 modeling, SURVEY §2.4 row 5; the fused masked-MHA decode
kernels under paddle/phi/kernels/fusion/gpu/ are the CUDA analogue).

Absorbed MLA decode is structurally MULTI-QUERY attention: every q head
attends to the SAME latent stream — K[t] = (c_lat[t] ⊕ c_pe[t]) with
dim r+dr and V[t] = c_lat[t] with dim r. The XLA einsum path reads the
latent cache TWICE per step (score einsum, then output einsum after the
softmax barrier — XLA cannot fuse across it), which is exactly the
~0.09 roofline residual recorded in docs/SERVING_BENCH.json r5. This
kernel streams each cache byte ONCE: one pass over time-blocks with
online-softmax accumulators, scores and the weighted latent sum computed
from the same VMEM tile.

Machinery mirrors ops/pallas_paged.py v1: grid (B, T-blocks), innermost
sequential with m/l/acc scratch; lengths ride as scalar prefetch and the
c_lat/c_pe index maps CLAMP dead trailing blocks onto the last live one
(their compute is pl.when-skipped); f32 accumulation; decode-only (no
backward — serving path); interpret mode off-TPU so the CPU suite covers
the kernel logic.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

__all__ = ["mla_decode_attention", "mla_kernel_eligible"]

_NEG = -1e30


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def mla_kernel_eligible(nh: int, r: int, dr: int) -> bool:
    """Lane-dim friendliness: the latent rank r is the contracting AND
    output lane dim (wants 128-multiples); dr only contracts (8 ok)."""
    return r % 128 == 0 and dr % 8 == 0 and nh >= 1


def _kernel(lens_ref, qe_ref, qp_ref, cl_ref, cp_ref, o_ref,
            acc_ref, m_ref, l_ref, *, block_t, scale):
    b = pl.program_id(0)
    j = pl.program_id(1)
    nj = pl.num_programs(1)

    @pl.when(j == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG)
        l_ref[:] = jnp.zeros_like(l_ref)

    seq = lens_ref[b]

    @pl.when(j * block_t < seq)
    def _compute():
        qe = qe_ref[0]                                 # [nh, r]
        qp = qp_ref[0]                                 # [nh, dr]
        cl = cl_ref[0]                                 # [Tb, r]
        cp = cp_ref[0]                                 # [Tb, dr]
        s = (jax.lax.dot_general(
                qe, cl, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)
             + jax.lax.dot_general(
                qp, cp, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)) * scale   # [nh, Tb]
        pos = j * block_t + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 1)
        masked = pos >= seq
        s = jnp.where(masked, _NEG, s)
        m_prev = m_ref[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, -1, keepdims=True))
        p = jnp.exp(s - m_new)
        p = jnp.where(masked, 0.0, p)
        alpha = jnp.exp(m_prev - m_new)
        l_ref[:] = l_ref[:] * alpha + jnp.sum(p, -1, keepdims=True)
        # the SAME cl tile feeds the output accumulation — this is the
        # single-read fusion the XLA path cannot express. Rows past seq
        # must be ZEROED, not just given p=0: a tail block that overruns
        # T holds uninitialized data, and 0 * NaN would poison the dot.
        rowdead = (j * block_t + jax.lax.broadcasted_iota(
            jnp.int32, (cl.shape[0], 1), 0)) >= seq
        cl_v = jnp.where(rowdead, jnp.zeros_like(cl), cl)
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p.astype(cl.dtype), cl_v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[:] = m_new

    @pl.when(j == nj - 1)
    def _emit():
        l = l_ref[:]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_ref[:] / l_safe).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("scale", "block_t"))
def mla_decode_attention(q_eff, q_pe, c_lat, c_pe, lengths, *,
                         scale: float, block_t: int = 1024):
    """One MLA decode step over the absorbed latent cache.

    q_eff  [B, nh, r]  — q_nope with W_uk absorbed (latent-space query)
    q_pe   [B, nh, dr] — rope-rotated positional query
    c_lat  [B, T, r]   — normalized latent cache (doubles as K-nope & V)
    c_pe   [B, T, dr]  — rope key cache (shared across heads)
    lengths[B] int32   — valid tokens per sequence (mask + block clamp)
    Returns the softmax-weighted latent read-out, [B, nh, r].
    """
    B, nh, r = q_eff.shape
    dr = q_pe.shape[-1]
    T = c_lat.shape[1]
    block_t = min(block_t, T)
    nj = -(-T // block_t)
    lens = lengths.astype(jnp.int32)

    def live_map(b, j, lens_ref):
        # clamp trailing dead blocks onto the last live one — their DMA
        # re-reads hot data instead of dead cache, compute is skipped
        last = jnp.maximum((lens_ref[b] + block_t - 1) // block_t - 1, 0)
        return (b, jnp.minimum(j, last), 0)

    from jax.experimental.pallas import tpu as pltpu
    return pl.pallas_call(
        functools.partial(_kernel, block_t=block_t, scale=scale),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(B, nj),
            in_specs=[
                pl.BlockSpec((1, nh, r), lambda b, j, L: (b, 0, 0)),
                pl.BlockSpec((1, nh, dr), lambda b, j, L: (b, 0, 0)),
                pl.BlockSpec((1, block_t, r), live_map),
                pl.BlockSpec((1, block_t, dr), live_map),
            ],
            out_specs=pl.BlockSpec((1, nh, r), lambda b, j, L: (b, 0, 0)),
            scratch_shapes=[
                pltpu.VMEM((nh, r), jnp.float32),
                pltpu.VMEM((nh, 1), jnp.float32),
                pltpu.VMEM((nh, 1), jnp.float32),
            ]),
        out_shape=jax.ShapeDtypeStruct((B, nh, r), c_lat.dtype),
        # jax renamed TPUCompilerParams -> CompilerParams; accept both
        compiler_params=getattr(pltpu, "CompilerParams",
                                getattr(pltpu, "TPUCompilerParams", None))(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=_interpret(),
    )(lens, q_eff, q_pe, c_lat, c_pe)


# certification (ROADMAP item 5 / paddlelint PK105)
from .oracles import register_oracle  # noqa: E402

register_oracle(
    "mla_decode_attention", kernel=mla_decode_attention,
    reference="paddle_tpu.ops.references:mla_decode_reference",
    parity_test="tests/test_pallas_mla.py::TestKernelParity")
