"""Control-flow capture tests: static.nn.cond/while_loop/case/switch_case +
the dy2static AST pass (ref test strategy: test_cond.py / test_while_loop.py
/ dy2static unit tests under test/dygraph_to_static — SURVEY §4).

Each op is exercised on all three paths: concrete predicate (dygraph),
traced predicate under to_static (lax lowering), and — where the reference
supports it — backward through the captured region.
"""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import static
from paddle_tpu.jit import dy2static

import jax.numpy as jnp


# ---------------------------------------------------------------------------
# static.nn.cond
# ---------------------------------------------------------------------------

class TestCond:
    def test_eager_concrete_pred_runs_taken_branch(self):
        x = paddle.to_tensor([2.0])
        x.stop_gradient = False
        out = static.nn.cond(paddle.to_tensor(True),
                             lambda: x * 3, lambda: x * 5)
        out.backward()
        assert float(out.sum()) == 6.0
        assert float(x.grad.sum()) == 3.0

    def test_eager_false_branch(self):
        x = paddle.to_tensor([2.0])
        out = static.nn.cond(paddle.to_tensor(False),
                             lambda: x * 3, lambda: x * 5)
        assert float(out.sum()) == 10.0

    def test_traced_both_branches_and_grads(self):
        lin = paddle.nn.Linear(4, 4)

        @paddle.jit.to_static
        def f(a):
            pred = a.sum() > 0
            y = static.nn.cond(pred, lambda: lin(a) * 2.0,
                               lambda: lin(a) * 0.5)
            loss = y.sum()
            loss.backward()
            return loss

        a = paddle.to_tensor(np.ones((2, 4), np.float32))
        l_pos = float(f(a))
        g_pos = lin.weight.grad.numpy().copy()
        l_neg = float(f(paddle.to_tensor(-np.ones((2, 4), np.float32))))
        # pos branch: 2*(aW+b); d/dW = 2 * 2(rows) = 4 per entry
        np.testing.assert_allclose(g_pos, np.full((4, 4), 4.0), rtol=1e-6)
        # eager reference
        y_ref = lin(paddle.to_tensor(np.ones((2, 4), np.float32)))
        assert l_pos == pytest.approx(float(y_ref.sum()) * 2.0, rel=1e-5)
        assert l_neg != pytest.approx(l_pos)

    def test_traced_multi_output_structure(self):
        @paddle.jit.to_static
        def f(a):
            return static.nn.cond(a.sum() > 0,
                                  lambda: (a + 1, a * 2),
                                  lambda: (a - 1, a / 2))

        u, v = f(paddle.to_tensor([1.0, 2.0]))
        np.testing.assert_allclose(u.numpy(), [2.0, 3.0])
        np.testing.assert_allclose(v.numpy(), [2.0, 4.0])

    def test_none_fns(self):
        assert static.nn.cond(paddle.to_tensor(True)) is None


# ---------------------------------------------------------------------------
# static.nn.while_loop
# ---------------------------------------------------------------------------

class TestWhileLoop:
    def test_eager_python_loop(self):
        i = paddle.to_tensor(0)
        s = paddle.to_tensor(0.0)
        i, s = static.nn.while_loop(lambda i, s: i < 5,
                                    lambda i, s: (i + 1, s + 2.0), [i, s])
        assert int(i) == 5 and float(s) == 10.0

    def test_eager_tape_gradient(self):
        x = paddle.to_tensor([1.5])
        x.stop_gradient = False
        i = paddle.to_tensor(0)
        _, v = static.nn.while_loop(lambda i, v: i < 3,
                                    lambda i, v: (i + 1, v * 2.0), [i, x])
        v.sum().backward()
        assert float(x.grad.sum()) == 8.0

    def test_traced_while(self):
        @paddle.jit.to_static
        def g(n):
            with paddle.no_grad():
                i = paddle.to_tensor(0)
                s = paddle.zeros([1])
                i, s = static.nn.while_loop(
                    lambda i, s: i < n, lambda i, s: (i + 1, s + 2.0), [i, s])
            return s

        assert float(g(paddle.to_tensor(7)).sum()) == 14.0
        # new trip count without retrace-breaking
        assert float(g(paddle.to_tensor(3)).sum()) == 6.0

    def test_traced_bounded_differentiable(self):
        lin = paddle.nn.Linear(4, 1)

        @paddle.jit.to_static
        def h(x):
            i = paddle.to_tensor(0)
            v = lin(x)
            i, v = static.nn.while_loop(
                lambda i, v: i < 3, lambda i, v: (i + 1, v * 2.0), [i, v],
                max_iter=8)
            loss = v.sum()
            loss.backward()
            return loss

        x = paddle.to_tensor(np.ones((2, 4), np.float32))
        loss = float(h(x))
        # d(8*sum(lin(x)))/dW = 8 * 2 rows = 16 per entry
        np.testing.assert_allclose(lin.weight.grad.numpy().ravel(),
                                   np.full(4, 16.0), rtol=1e-6)
        y_ref = float(lin(x).sum())
        assert loss == pytest.approx(8.0 * y_ref, rel=1e-5)

    def test_traced_unbounded_backward_raises(self):
        lin = paddle.nn.Linear(2, 2)

        @paddle.jit.to_static
        def bad(x):
            i = paddle.to_tensor(0)
            v = lin(x)
            i, v = static.nn.while_loop(
                lambda i, v: i < 3, lambda i, v: (i + 1, v * 2.0), [i, v])
            loss = v.sum()
            loss.backward()
            return loss

        with pytest.raises(RuntimeError, match="max_iter"):
            bad(paddle.to_tensor(np.ones((1, 2), np.float32)))

    def test_bad_loop_vars_type(self):
        with pytest.raises(TypeError):
            static.nn.while_loop(lambda x: x < 1, lambda x: x + 1,
                                 paddle.to_tensor(0))


# ---------------------------------------------------------------------------
# case / switch_case
# ---------------------------------------------------------------------------

class TestCaseSwitch:
    def test_case_eager_first_true_wins(self):
        r = static.nn.case(
            [(paddle.to_tensor(False), lambda: paddle.to_tensor(1.0)),
             (paddle.to_tensor(True), lambda: paddle.to_tensor(2.0)),
             (paddle.to_tensor(True), lambda: paddle.to_tensor(9.0))],
            default=lambda: paddle.to_tensor(3.0))
        assert float(r) == 2.0

    def test_case_default(self):
        r = static.nn.case(
            [(paddle.to_tensor(False), lambda: paddle.to_tensor(1.0))],
            default=lambda: paddle.to_tensor(3.0))
        assert float(r) == 3.0

    def test_case_traced(self):
        @paddle.jit.to_static
        def cs(a):
            return static.nn.case(
                [(a.sum() > 10, lambda: a * 100),
                 (a.sum() > 0, lambda: a * 10)],
                default=lambda: a)

        assert float(cs(paddle.to_tensor([1.0])).sum()) == 10.0
        assert float(cs(paddle.to_tensor([20.0])).sum()) == 2000.0
        assert float(cs(paddle.to_tensor([-1.0])).sum()) == -1.0

    def test_switch_case_eager(self):
        a = paddle.to_tensor([2.0])
        r = static.nn.switch_case(paddle.to_tensor(1),
                                  {1: lambda: a + 1, 3: lambda: a * 10},
                                  default=lambda: a * 0)
        assert float(r.sum()) == 3.0

    def test_switch_case_traced_with_default(self):
        @paddle.jit.to_static
        def sw(k, a):
            return static.nn.switch_case(
                k, {1: lambda: a + 1, 3: lambda: a * 10},
                default=lambda: a * 0)

        a = paddle.to_tensor([2.0])
        assert float(sw(paddle.to_tensor(3), a).sum()) == 20.0
        assert float(sw(paddle.to_tensor(1), a).sum()) == 3.0
        assert float(sw(paddle.to_tensor(9), a).sum()) == 0.0

    def test_switch_case_list_fns(self):
        r = static.nn.switch_case(paddle.to_tensor(1),
                                  [lambda: paddle.to_tensor(10.0),
                                   lambda: paddle.to_tensor(20.0)])
        assert float(r) == 20.0

    def test_duplicate_keys_raise(self):
        with pytest.raises(ValueError):
            static.nn.switch_case(paddle.to_tensor(0),
                                  [(0, lambda: 1), (0, lambda: 2)])


# ---------------------------------------------------------------------------
# dy2static AST pass
# ---------------------------------------------------------------------------

def _make_branchy():
    lin = paddle.nn.Linear(4, 4)

    def f(a):
        y = lin(a)
        if y.sum() > 0:
            out = y * 2.0
        else:
            out = y * 0.5
        return out.sum()

    return f, lin


class TestDy2Static:
    def test_if_parity_both_branches(self):
        f, lin = _make_branchy()
        sf = paddle.jit.to_static(f)
        for sign in (1.0, -1.0):
            a = paddle.to_tensor(sign * np.ones((2, 4), np.float32))
            assert float(sf(a)) == pytest.approx(float(f(a)), rel=1e-5)

    def test_if_gradients(self):
        lin = paddle.nn.Linear(4, 4)

        def f(a):
            y = lin(a)
            if y.sum() > 0:
                out = y * 2.0
            else:
                out = y * 0.5
            loss = out.sum()
            loss.backward()
            return loss

        sf = paddle.jit.to_static(f)
        a = paddle.to_tensor(np.ones((2, 4), np.float32))
        sf(a)
        g_static = lin.weight.grad.numpy().copy()
        lin.weight._grad = None
        f(a)   # eager reference
        np.testing.assert_allclose(g_static, lin.weight.grad.numpy(),
                                   rtol=1e-5)

    def test_while_accumulator(self):
        def g(n):
            with paddle.no_grad():
                i = paddle.to_tensor(0)
                s = paddle.to_tensor(0.0)
                while i < n:
                    s = s + 2.0
                    i = i + 1
            return s

        sg = paddle.jit.to_static(g)
        assert float(sg(paddle.to_tensor(6))) == 12.0
        assert float(sg(paddle.to_tensor(2))) == 4.0

    def test_for_range_tensor_bound(self):
        def h(n, x):
            with paddle.no_grad():
                acc = x
                for i in range(n):
                    acc = acc * 2.0
            return acc

        sh = paddle.jit.to_static(h)
        assert float(sh(paddle.to_tensor(3), paddle.to_tensor([1.0])).sum()) == 8.0

    def test_python_control_flow_unchanged(self):
        def k(x, flg=True):
            total = paddle.to_tensor(0.0)
            for i in range(4):
                total = total + x.sum() * float(i)
            if flg:
                total = total * 2.0
            return total

        sk = paddle.jit.to_static(k)
        assert float(sk(paddle.to_tensor([1.0])).sum()) == 12.0

    def test_nested_if_in_while(self):
        def f(n):
            with paddle.no_grad():
                i = paddle.to_tensor(0)
                s = paddle.to_tensor(0.0)
                while i < n:
                    if s > 4.0:
                        s = s + 1.0
                    else:
                        s = s + 3.0
                    i = i + 1
            return s

        sf = paddle.jit.to_static(f)
        # 0->3->6 then +1 each: 3,6,7,8,9
        assert float(sf(paddle.to_tensor(5))) == 9.0
        assert float(f(paddle.to_tensor(5))) == 9.0

    def test_var_defined_in_one_branch_errors_clearly(self):
        def f(a):
            if a.sum() > 0:
                z = a * 2
            return a

        sf = paddle.jit.to_static(f)
        with pytest.raises(NameError, match="only one branch|not assigned"):
            sf(paddle.to_tensor([1.0]))

    def test_undefined_sentinel_raises_on_use(self):
        u = dy2static.Undefined("zzz")
        with pytest.raises(NameError, match="zzz"):
            bool(u)
        with pytest.raises(NameError):
            u + 1

    def test_loop_carry_dtype_promotion(self):
        # python-int init whose body produces floats: the carry is promoted,
        # not silently truncated (review fix)
        def f(n):
            with paddle.no_grad():
                i = paddle.to_tensor(0)
                s = paddle.to_tensor(0)
                while i < n:
                    s = s + 0.5
                    i = i + 1
            return s

        sf = paddle.jit.to_static(f)
        assert float(sf(paddle.to_tensor(4))) == pytest.approx(
            float(f(paddle.to_tensor(4)))) == 2.0

    def test_global_rebinding_stays_visible(self):
        def f(x):
            if x.sum() > 0:
                y = x * _CF_SCALE
            else:
                y = x * 0.0
            return y.sum()

        f.__globals__["_CF_SCALE"] = 1.0
        c = dy2static.convert(f)
        assert c is not f
        x = paddle.to_tensor([2.0])
        assert float(c(x)) == 2.0
        # rebinding the global must stay visible to the converted fn
        f.__globals__["_CF_SCALE"] = 3.0
        assert float(c(x)) == 6.0

    def test_closure_rebinding_stays_visible(self):
        def outer():
            scale = 1.0

            def f(x):
                if x.sum() > 0:
                    y = x * scale
                else:
                    y = x * 0.0
                return y.sum()

            def set_scale(v):
                nonlocal scale
                scale = v

            return f, set_scale

        f, set_scale = outer()
        c = dy2static.convert(f)
        assert c is not f
        x = paddle.to_tensor([2.0])
        assert float(c(x)) == 2.0
        set_scale(5.0)
        assert float(c(x)) == 10.0

    def test_walrus_test_left_untransformed(self):
        def f(x):
            n = 3
            acc = paddle.to_tensor(0.0)
            while (n := n - 1) >= 0:
                acc = acc + float(n)
            return acc

        # concrete predicate: untransformed python while still runs
        sf = paddle.jit.to_static(f)
        assert float(sf(paddle.to_tensor([1.0]))) == 3.0

    def test_undefined_comparison_raises(self):
        u = dy2static.Undefined("q")
        with pytest.raises(NameError, match="q"):
            u == 3
        with pytest.raises(NameError, match="q"):
            u < 3

    def test_body_dtype_instability_errors_clearly(self):
        # a genuinely type-unstable body (dtype depends on iteration) can't
        # be promoted; the error must name the dtypes
        @paddle.jit.to_static
        def f(n):
            with paddle.no_grad():
                i = paddle.to_tensor(0)
                v = paddle.to_tensor([1.0])
                i, v = static.nn.while_loop(
                    lambda i, v: i < n,
                    lambda i, v: (i + 1, v.astype("float64")
                                  if False else v * 2),
                    [i, v])
            return v

        # this body is stable after promotion — just confirm it runs
        assert float(f(paddle.to_tensor(2)).sum()) == 4.0

    def test_for_body_assigning_index_keeps_trip_count(self):
        def f(n):
            with paddle.no_grad():
                s = paddle.to_tensor(0.0)
                for i in range(n):
                    i = i + 5
                    s = s + 1.0
            return s

        sf = paddle.jit.to_static(f)
        assert float(sf(paddle.to_tensor(6))) == 6.0 == \
            float(f(paddle.to_tensor(6)))

    def test_generator_with_branch_yield_untransformed(self):
        def g(x):
            if x > 0:
                yield x
            yield -1

        c = dy2static.convert(g)
        assert list(c(5)) == [5, -1]

    def test_wrapper_without_code_passes_through(self):
        import functools

        @functools.lru_cache(maxsize=None)
        def cached(n):
            if n > 0:
                return n
            return 0

        assert dy2static.convert(cached) is cached

    def test_loop_temp_read_after_traced_loop_raises(self):
        # a temp (assigned-before-read each iteration) has no post-loop
        # value under lax lowering; reading it after the loop must raise,
        # not silently return the Undefined sentinel (review fix)
        def f(n):
            with paddle.no_grad():
                i = paddle.to_tensor(0)
                y = paddle.to_tensor(1.0)
                while i < n:
                    t = y * 2.0
                    y = t - 1.0
                    i = i + 1
            return t

        sf = paddle.jit.to_static(f)
        with pytest.raises(Exception) as ei:
            sf(paddle.to_tensor(3))
        assert isinstance(ei.value, (NameError, UnboundLocalError))
        # python semantics preserved for the untraced fn
        assert float(f(3)) == 2.0

    def test_nested_def_global_tensor_captured(self):
        # a branch fn touching a global Tensor only via an inner def must
        # still thread it through the traced cond (review fix)
        from paddle_tpu.static.control_flow import _captured_tensors
        t = paddle.to_tensor([1.0])
        glob = {"_CF_W": t}

        src = "def branch():\n    def inner():\n        return _CF_W * 2\n" \
              "    return inner()\n"
        ns = {}
        exec(compile(src, "<t>", "exec"), glob, ns)
        caps = _captured_tensors([ns["branch"]])
        assert any(c is t for c in caps)

    def test_convert_noop_without_control_flow(self):
        def plain(x):
            return x + 1

        assert dy2static.convert(plain) is plain

    def test_convert_marks_and_idempotent(self):
        f, _ = _make_branchy()
        c1 = dy2static.convert(f)
        assert c1 is not f and getattr(c1, "__pt_dy2static__", False)
        assert dy2static.convert(c1) is c1


# ---------------------------------------------------------------------------
# the canonical acceptance case: while-until-EOS generate under to_static
# ---------------------------------------------------------------------------

class TinyLM(paddle.nn.Layer):
    """3-token LM whose next token is (cur + 1) % 3 by construction, with
    token 2 as EOS."""

    def __init__(self):
        super().__init__()
        self.emb = paddle.nn.Embedding(3, 8)
        self.head = paddle.nn.Linear(8, 3)

    def forward(self, tok):
        return self.head(self.emb(tok))


class TestGenerateUnderToStatic:
    def test_while_until_eos(self):
        lm = TinyLM()

        def generate(first):
            with paddle.no_grad():
                tok = first
                steps = paddle.to_tensor(0)
                while paddle.logical_and(tok != 2, steps < 16):
                    logits = lm(tok)
                    tok = paddle.argmax(logits, axis=-1).astype("int64")
                    steps = steps + 1
            return tok, steps

        eager_tok, eager_steps = generate(paddle.to_tensor(0, dtype="int64"))
        sgen = paddle.jit.to_static(generate)
        st_tok, st_steps = sgen(paddle.to_tensor(0, dtype="int64"))
        assert int(st_tok) == int(eager_tok)
        assert int(st_steps) == int(eager_steps)
        # and the loop really runs a data-dependent number of steps
        st_tok2, st_steps2 = sgen(paddle.to_tensor(2, dtype="int64"))
        assert int(st_steps2) == 0 and int(st_tok2) == 2


# ---------------------------------------------------------------------------
# Assert
# ---------------------------------------------------------------------------

class TestAssert:
    def test_pass(self):
        static.nn.Assert(paddle.to_tensor(True))

    def test_fail(self):
        with pytest.raises(AssertionError):
            static.nn.Assert(paddle.to_tensor(False),
                             data=[paddle.to_tensor([1.0])])


class TestBreakContinueCapture:
    """Loop-level break/continue in while bodies: the reference
    BreakContinueTransformer flag rewrite (round-4)."""

    def test_break_under_tensor_if(self):
        def f(n):
            with paddle.no_grad():
                i = paddle.to_tensor(0)
                s = paddle.to_tensor(0.0)
                while i < n:
                    if s > 5.0:
                        break
                    s = s + 2.0
                    i = i + 1
            return s

        sf = paddle.jit.to_static(f)
        assert float(sf(paddle.to_tensor(10))) == 6.0 == \
            float(f(paddle.to_tensor(10)))

    def test_continue_skips_rest_of_iteration(self):
        def g(n):
            with paddle.no_grad():
                i = paddle.to_tensor(0)
                s = paddle.to_tensor(0.0)
                while i < n:
                    i = i + 1
                    if paddle.equal(paddle.mod(i, paddle.to_tensor(2)),
                                    paddle.to_tensor(0)):
                        continue
                    s = s + 1.0
            return s

        sg = paddle.jit.to_static(g)
        assert float(sg(paddle.to_tensor(7))) == 4.0 == \
            float(g(paddle.to_tensor(7)))

    def test_mixed_break_continue(self):
        def h(n):
            with paddle.no_grad():
                i = paddle.to_tensor(0)
                s = paddle.to_tensor(0.0)
                while i < n:
                    i = i + 1
                    if i > 5:
                        break
                    if paddle.equal(paddle.mod(i, paddle.to_tensor(2)),
                                    paddle.to_tensor(1)):
                        continue
                    s = s + i.astype("float32")
            return s, i

        sh = paddle.jit.to_static(h)
        se, ie = h(paddle.to_tensor(20))
        st, it = sh(paddle.to_tensor(20))
        assert float(st) == float(se) == 6.0
        assert int(it) == int(ie) == 6

    def test_predicate_becomes_traced_mid_loop(self):
        # `while True` with a break whose flag turns into a cond output:
        # the concrete prefix runs as python, the rest lowers to lax
        def k(m):
            with paddle.no_grad():
                tot = paddle.to_tensor(0.0)
                while True:
                    tot = tot + 1.0
                    if tot > m:
                        break
            return tot

        ck = paddle.jit.to_static(k)
        assert float(ck(paddle.to_tensor(3.0))) == 4.0

    def test_break_in_nested_loop_stays_inner(self):
        def f(n):
            total = paddle.to_tensor(0.0)
            with paddle.no_grad():
                i = paddle.to_tensor(0)
                while i < n:
                    j = 0
                    while j < 10:       # python inner loop
                        j += 1
                        if j >= 2:
                            break       # belongs to the INNER loop
                    total = total + float(j)
                    i = i + 1
            return total

        sf = paddle.jit.to_static(f)
        assert float(sf(paddle.to_tensor(3))) == 6.0 == \
            float(f(paddle.to_tensor(3)))


class TestBreakContinueReviewCases:
    """Round-4 review repros: Try containment, short-circuit test,
    nested-temp carry promotion, guard-temp error clarity."""

    def test_break_inside_try_left_untransformed(self):
        # guard_block can't guard Try internals: the rewrite bails and the
        # concrete python path stays exactly correct
        def t1(n=10):
            i = 0
            s = paddle.to_tensor(0.0)
            while i < n:
                try:
                    if i > 2:
                        break
                    s = s + 1.0
                except ValueError:
                    pass
                i = i + 1
            return s, i

        st = paddle.jit.to_static(t1)
        se, ie = t1()
        ste, sti = st()
        assert float(ste) == float(se) == 3.0 and int(sti) == int(ie) == 3

    def test_rewritten_test_short_circuits_after_break(self):
        # python never re-evaluates the test after break; the test here is
        # only safe while the break's index guard holds
        def t2():
            vals = [1.0, 2.0, 3.0]
            i = 0
            s = 0.0
            while vals[i] < 10.0:
                s += vals[i]
                i += 1
                if i >= len(vals):
                    break
            return paddle.to_tensor(s)

        assert float(paddle.jit.to_static(t2)()) == 6.0

    def test_initialized_inner_temp_promoted_to_carry(self):
        # tmp is an inner-loop temp read AFTER the inner loop: because it
        # has a pre-loop value it rides the lax carry and the post-loop
        # read sees the last-iteration value, matching python exactly
        def t3(n):
            with paddle.no_grad():
                tmp = paddle.to_tensor(0.0)
                i = paddle.to_tensor(0)
                acc = paddle.to_tensor(0.0)
                while i < n:
                    j = paddle.to_tensor(0)
                    while j < 2:
                        tmp = acc + 1.0
                        j = j + 1
                    acc = acc + tmp
                    i = i + 1
            return acc

        st = paddle.jit.to_static(t3)
        assert float(st(paddle.to_tensor(3))) == \
            float(t3(paddle.to_tensor(3))) == 7.0

    def test_uninitialized_guard_temp_errors_clearly(self):
        def t4(n):
            with paddle.no_grad():
                i = paddle.to_tensor(0)
                s = paddle.to_tensor(0.0)
                while i < n:
                    i = i + 1
                    if paddle.equal(paddle.mod(i, paddle.to_tensor(2)),
                                    paddle.to_tensor(0)):
                        continue
                    delta = i.astype("float32") * 2.0
                    s = s + delta
            return s

        with pytest.raises(NameError, match="delta.*assigned before"):
            paddle.jit.to_static(t4)(paddle.to_tensor(5))

    def test_guard_temp_with_init_runs(self):
        # the error's suggested fix works: initialize the temp pre-loop
        def t5(n):
            with paddle.no_grad():
                i = paddle.to_tensor(0)
                s = paddle.to_tensor(0.0)
                delta = paddle.to_tensor(0.0)
                while i < n:
                    i = i + 1
                    if paddle.equal(paddle.mod(i, paddle.to_tensor(2)),
                                    paddle.to_tensor(0)):
                        continue
                    delta = i.astype("float32") * 2.0
                    s = s + delta
            return s

        st = paddle.jit.to_static(t5)
        assert float(st(paddle.to_tensor(5))) == \
            float(t5(paddle.to_tensor(5))) == 18.0


class TestReturnCapture:
    """Early-return capture (reference ReturnTransformer): folding
    trailing code into else-branches so tensor-predicated returns lower
    to lax.cond (round-4)."""

    def test_early_return_tensor_pred(self):
        def f(x):
            if x.sum() > 0:
                return x * 2.0
            return x * -1.0

        sf = paddle.jit.to_static(f)
        assert float(sf(paddle.to_tensor([3.0])).sum()) == 6.0
        assert float(sf(paddle.to_tensor([-3.0])).sum()) == 3.0

    def test_elif_chain_all_return(self):
        def g(x):
            if x.sum() > 10.0:
                return x * 100.0
            elif x.sum() > 0:
                return x * 10.0
            else:
                return x

        sg = paddle.jit.to_static(g)
        assert float(sg(paddle.to_tensor([20.0])).sum()) == 2000.0
        assert float(sg(paddle.to_tensor([1.0])).sum()) == 10.0
        assert float(sg(paddle.to_tensor([-1.0])).sum()) == -1.0

    def test_tail_temps_stay_branch_local(self):
        # z is only live inside the folded tail: it must NOT become a
        # cond output needing both-branch assignment
        def h(x):
            y = x + 1.0
            if y.sum() > 5.0:
                return y * 2.0
            z = y * 3.0
            return z + 1.0

        sh = paddle.jit.to_static(h)
        for v in (10.0, 1.0):
            assert float(sh(paddle.to_tensor([v])).sum()) == \
                float(h(paddle.to_tensor([v])).sum())

    def test_fall_off_end_untouched(self):
        def k(x, flag=False):
            if flag:
                return x * 2.0

        assert paddle.jit.to_static(k)(paddle.to_tensor([1.0])) is None

    def test_return_in_loop_untouched(self):
        # v1 scope: returns inside loops stay python (concrete path ok)
        def m(n=4):
            s = paddle.to_tensor(0.0)
            for i in range(n):
                s = s + 1.0
                if i == 2:
                    return s
            return s

        assert float(paddle.jit.to_static(m)()) == 3.0 == float(m())


class TestReturnCaptureReviewCases:
    """Round-4 review repros for the return capture + temp promotion."""

    def test_return_inside_with_bails(self):
        # the fold can't move a Return out of a With: the rewrite must
        # bail entirely (silent fall-through would be wrong)
        def g(x, flag=True):
            with paddle.no_grad():
                if flag:
                    return x * 2.0
            return x

        c = dy2static.convert(g)
        assert float(c(paddle.to_tensor([5.0])).sum()) == 10.0

    def test_fold_inside_non_folding_parent_bails(self):
        def f(x, big=False):
            y = paddle.to_tensor(0.0)
            if x is not None:
                if big:
                    return paddle.to_tensor(-1.0)
                y = paddle.to_tensor(1.0)
            z = y + 2.0
            return z

        c = dy2static.convert(f)
        assert float(c(paddle.to_tensor([1.0])).sum()) == 3.0
        assert float(c(paddle.to_tensor([1.0]), big=True).sum()) == -1.0

    def test_string_temp_not_promoted_into_carry(self):
        def h(n):
            with paddle.no_grad():
                msg = ""
                i = paddle.to_tensor(0)
                s = paddle.to_tensor(0.0)
                while i < n:
                    msg = "iter"
                    s = s + 1.0
                    i = i + 1
            return s

        sh = paddle.jit.to_static(h)
        assert float(sh(paddle.to_tensor(4))) == 4.0


class TestSuppressedRaiseUnderWith:
    def test_raise_in_suppress_with_not_counted_terminal(self):
        # contextlib.suppress can swallow the raise and fall through: the
        # fold must NOT treat the With body's Raise as terminal
        import contextlib

        def f(x, p=True, q=True):
            if p:
                with contextlib.suppress(ValueError):
                    raise ValueError()
            if q:
                return x
            return x + 1.0

        c = dy2static.convert(f)
        # original: raise suppressed, falls through, returns x
        assert float(c(paddle.to_tensor([10.0])).sum()) == 10.0
        assert float(c(paddle.to_tensor([10.0]), q=False).sum()) == 11.0


class TestLoopElse:
    """r5: while/for ... else capture (LoopTransformer parity). Without a
    loop-level break the else body follows the loop; with one, an
    _elseok flag guards it — under a traced break predicate the guard
    lowers to lax.cond with the flag as carried state."""

    def test_while_else_no_break(self):
        def f(n):
            with paddle.no_grad():
                i = paddle.to_tensor(0)
                s = paddle.to_tensor(0.0)
                while i < n:
                    i = i + 1
                    s = s + 2.0
                else:
                    s = s + 100.0
            return s

        sf = paddle.jit.to_static(f)
        n = paddle.to_tensor(3)
        assert float(sf(n)) == float(f(n)) == 106.0

    def test_while_else_skipped_on_tensor_break(self):
        def f(n):
            with paddle.no_grad():
                i = paddle.to_tensor(0)
                s = paddle.to_tensor(0.0)
                while i < n:
                    i = i + 1
                    s = s + 1.0
                    if s > 2.0:      # tensor predicate -> traced break
                        break
                else:
                    s = s + 100.0
            return s

        sf = paddle.jit.to_static(f)
        # breaks at s=3 -> else skipped
        n = paddle.to_tensor(10)
        assert float(sf(n)) == float(f(n)) == 3.0
        # loop exhausts at s=2 -> else runs
        n2 = paddle.to_tensor(2)
        assert float(sf(n2)) == float(f(n2)) == 102.0

    def test_for_range_else_with_break(self):
        def f(x):
            total = x * 0.0
            for i in range(5):
                total = total + 1.0
                if total.sum() > 2.5:    # tensor predicate -> traced break
                    break
            else:
                total = total + 100.0
            return total

        sf = paddle.jit.to_static(f)
        a = paddle.to_tensor([0.0])
        assert float(sf(a).sum()) == float(f(a).sum()) == 3.0

    def test_for_iter_else(self):
        def f(t):
            acc = paddle.to_tensor(0.0)
            for row in t:
                acc = acc + row.sum()
            else:
                acc = acc + 100.0
            return acc

        t = paddle.to_tensor(np.ones((3, 2), np.float32))
        sf = paddle.jit.to_static(f)
        assert float(sf(t)) == float(f(t)) == 106.0

    def test_break_in_inner_loop_else_targets_outer(self):
        # review r5: a break inside an INNER loop's else clause belongs
        # to the OUTER loop (python scoping) — the outer else must be
        # guarded by it
        def f(x):
            s = x * 0.0
            i = 0
            while i < 3:
                i = i + 1
                s = s + 1.0
                for j in range(2):
                    s = s + 0.0
                else:
                    break
            else:
                s = s + 100.0
            return s

        sf = paddle.jit.to_static(f)
        a = paddle.to_tensor([0.0])
        assert float(sf(a).sum()) == float(f(a).sum()) == 1.0

    def test_bare_loop_level_break_with_else(self):
        # review r5: a break as a DIRECT body statement must not produce
        # a nested-list AST (silent conversion fallback)
        def f(n):
            with paddle.no_grad():
                i = paddle.to_tensor(0)
                s = paddle.to_tensor(0.0)
                while i < n:
                    i = i + 1
                    s = s + 1.0
                    break
                else:
                    s = s + 100.0
            return s

        sf = paddle.jit.to_static(f)
        n = paddle.to_tensor(5)
        assert float(sf(n)) == float(f(n)) == 1.0
        z = paddle.to_tensor(0)
        assert float(sf(z)) == float(f(z)) == 100.0

    def test_for_list_else_break_concrete(self):
        def f(x):
            acc = x * 0.0
            k = 0
            for v in [1.0, 2.0, 3.0]:
                acc = acc + v
                k = k + 1
                if k > 2:    # python predicate: concrete even under trace
                    break
            else:
                acc = acc + 100.0
            return acc

        sf = paddle.jit.to_static(f)
        a = paddle.to_tensor([0.0])
        assert float(sf(a).sum()) == float(f(a).sum()) == 6.0


class TestForRangeBreakContinue:
    """for-range bodies with break/continue: desugared to the canonical
    while so the flag rewrite + lax lowering apply (round-4)."""

    def test_for_break_tensor_pred(self):
        def f(n):
            with paddle.no_grad():
                s = paddle.to_tensor(0.0)
                for i in range(n):
                    if s > 4.0:
                        break
                    s = s + 2.0
            return s

        sf = paddle.jit.to_static(f)
        assert float(sf(paddle.to_tensor(10))) == 6.0
        # concrete path identical
        assert float(f(10)) == 6.0
        assert float(sf(paddle.to_tensor(1))) == 2.0

    def test_for_continue_advances_counter(self):
        def g(n):
            with paddle.no_grad():
                s = paddle.to_tensor(0.0)
                for i in range(n):
                    if paddle.equal(paddle.mod(paddle.to_tensor(i)
                                               if isinstance(i, int)
                                               else i,
                                               paddle.to_tensor(2)),
                                    paddle.to_tensor(0)):
                        continue
                    s = s + 1.0
            return s

        sg = paddle.jit.to_static(g)
        # odd i in [0, 7): 1,3,5 -> 3
        assert float(sg(paddle.to_tensor(7))) == 3.0 == float(g(7))

    def test_for_break_concrete_bound(self):
        def h():
            s = paddle.to_tensor(0.0)
            for i in range(100):
                s = s + 1.0
                if i >= 4:
                    break
            return s

        assert float(paddle.jit.to_static(h)()) == 5.0 == float(h())

    def test_target_last_value_after_break(self):
        def k(n=10):
            last = -1
            for i in range(n):
                last = i
                if i >= 3:
                    break
            return paddle.to_tensor(float(last))

        assert float(paddle.jit.to_static(k)()) == 3.0 == float(k())


class TestForRangeDesugarEdgeCases:
    """Round-4 review: desugar gate robustness."""

    def test_starred_range_args_left_alone(self):
        def f(bounds=(0, 5)):
            s = paddle.to_tensor(0.0)
            for i in range(*bounds):
                s = s + 1.0
                if i >= 2:
                    break
            return s

        c = dy2static.convert(f)
        assert float(c()) == 3.0 == float(f())

    def test_zero_step_raises_like_range(self):
        def f(n=5):
            s = paddle.to_tensor(0.0)
            step = 0
            for i in range(10, 0, step):
                s = s + 1.0
                if s > 3.0:
                    break
            return s

        c = dy2static.convert(f)
        with pytest.raises(ValueError, match="must not be zero"):
            c()

    def test_float_bound_raises_like_range(self):
        def f():
            s = paddle.to_tensor(0.0)
            stop = 2.5
            for i in range(stop):
                s = s + 1.0
                if s > 1.0:
                    break
            return s

        c = dy2static.convert(f)
        with pytest.raises(TypeError, match="interpreted as an integer"):
            c()

    def test_del_body_not_desugared(self):
        def f():
            cache = {0: "a", 1: "b"}
            s = paddle.to_tensor(0.0)
            for i in range(2):
                del cache[i]
                s = s + 1.0
            return s, cache

        c = dy2static.convert(f)
        s, cache = c()
        assert float(s) == 2.0 and cache == {}

    def test_nested_def_with_return_in_body_still_converts(self):
        def f(n=4):
            s = paddle.to_tensor(0.0)
            for i in range(n):
                def pick(v):
                    return v + 1
                s = s + float(pick(i))
                if i >= 2:
                    break
            return s

        c = dy2static.convert(f)
        assert float(c()) == 6.0 == float(f())


class TestNestedFunctionConversion:
    """Nested defs get the full conversion too (reference convert_call)."""

    def test_inner_def_tensor_if_converts(self):
        def outer(x):
            def head(v):
                if v.sum() > 0:
                    return v * 2.0
                return v * -1.0

            a = head(x)
            b = head(-x)
            return a + b

        so = paddle.jit.to_static(outer)
        got = float(so(paddle.to_tensor([3.0])).sum())
        want = float(outer(paddle.to_tensor([3.0])).sum())
        assert got == want == 9.0

    def test_inner_def_while_break(self):
        def outer(n):
            def count(lim):
                with paddle.no_grad():
                    i = paddle.to_tensor(0)
                    while True:
                        i = i + 1
                        if i >= lim:
                            break
                return i

            return count(n) + count(n + 1)

        so = paddle.jit.to_static(outer)
        assert int(so(paddle.to_tensor(3))) == \
            int(outer(paddle.to_tensor(3))) == 7

    def test_nonlocal_inner_def_untouched(self):
        def outer(x):
            state = [0.0]

            def bump():
                state[0] += 1.0

            bump()
            bump()
            if x.sum() > 0:
                return paddle.to_tensor(state[0]) + x.sum()
            return paddle.to_tensor(state[0])

        so = paddle.jit.to_static(outer)
        assert float(so(paddle.to_tensor([1.0]))) == \
            float(outer(paddle.to_tensor([1.0]))) == 3.0


class TestNestedDefReviewCases:
    def test_outer_return_capture_despite_inner_returns(self):
        # a nested def's returns must not disable the OUTER fold
        def outer(x):
            def head(v):
                return v + 1.0

            if x.sum() > 0:
                return head(x) * 2.0
            return head(x) * -1.0

        so = paddle.jit.to_static(outer)
        assert float(so(paddle.to_tensor([3.0])).sum()) == 8.0
        assert float(so(paddle.to_tensor([-3.0])).sum()) == 2.0

    def test_pt_prefixed_user_function_converts(self):
        def _pt_step(x):
            if x.sum() > 0:
                return x * 2.0
            return x * -1.0

        c = dy2static.convert(_pt_step)
        assert c is not _pt_step
        assert float(paddle.jit.to_static(_pt_step)(
            paddle.to_tensor([2.0])).sum()) == 4.0

    def test_true_nonlocal_contained_per_site(self):
        # r5: nonlocal no longer bails the whole function — it is
        # contained per-site. Here the if threads NO names (the branch
        # only calls bump()), so conversion is sound: the branch fn is a
        # closure over the live frame and the cell mutation stays
        # visible. Statements that WOULD thread `n` fall back
        # individually (tests/test_for_iter.py::TestNonlocalContainment).
        def outer(x):
            n = 0

            def bump():
                nonlocal n
                n += 1

            bump()
            if float(x.sum()) > 0:
                bump()
            return paddle.to_tensor(float(n)) + x.sum()

        co = dy2static.convert(outer)
        assert float(co(paddle.to_tensor([1.0]))) == 3.0
        assert float(co(paddle.to_tensor([-1.0]))) == 0.0
        assert float(outer(paddle.to_tensor([1.0]))) == 3.0
