"""paddle_tpu.observability.tracing — per-request span timelines and SLO
histograms (ISSUE 6 tentpole).

The metrics registry (``observability``) answers "how much / how fast in
aggregate"; this module answers "what happened to request 17": a
`TraceRecorder` keyed by request id collects monotonic `TraceEvent`
stamps from the serving path (enqueue → admit → prefill chunks → one
`token` event per decode step → finish/timeout/overloaded/refused, plus
copy-on-write page events) and from the trainer (data/fwd/bwd/opt phase
events per optimizer step), so one timeline covers both workloads.

Terminal events derive the serving SLOs a serving tier is operated by
and observe them into registry histograms:

  - ``serving.engine.queue_wait_seconds``  (enqueue → admit)
  - ``serving.engine.ttft_seconds``        (enqueue → first token)
  - ``serving.engine.tpot_seconds``        (inter-token, steady decode)
  - ``serving.engine.e2e_seconds``         (enqueue → completion)

`percentile()` / `percentiles()` compute p50/p90/p99 from the cumulative
bucket counts (linear interpolation within the landing bucket — exact
whenever observations sit on bucket bounds), and `slo_summary()` renders
the standard serving table. `TraceRecorder.export_chrome_trace` writes
the timelines as chrome-trace JSON whose span ids share the namespace
(and the ``name[span=<pid>-<seq>]`` convention) of the host-profiler
events `observability.span` emits, so request rows and host-profiler
spans correlate in one viewer; each stamp taken inside an engine step
additionally carries the step's host span id in its args.

Overhead contract (same as the metrics layer): every entry point checks
the cached ``FLAGS_request_tracing`` flag object FIRST, so with tracing
off a stamp costs one function call + one attribute test. Gated at <5%
alongside the metrics gate in tests/test_observability.py::TestOverhead.

Thread discipline (paddlelint PT006): all recorder state — the live
table, the finished-trace ring, the exporter file handle — is touched
only under ``self._lock``; the optional background flush thread
(`start_exporter`) shares exactly that state and that lock.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Mapping, Optional, Sequence, Tuple, Union

from .. import flags as _flags
from . import DEFAULT_BUCKETS, Histogram, _span_seq, registry

__all__ = ["TraceEvent", "RequestTrace", "TraceRecorder", "recorder",
           "enabled", "set_enabled", "percentile", "percentiles",
           "slo_summary", "SLO_METRICS"]

_FLAG = _flags._registry["FLAGS_request_tracing"]


def enabled() -> bool:
    """Whether trace stamps are recorded (FLAGS_request_tracing)."""
    return _FLAG.value


def set_enabled(on: bool) -> None:
    _flags.set_flags({"FLAGS_request_tracing": bool(on)})


def _now_us() -> int:
    # same clock family as the host profiler's pure-python fallback
    # (perf_counter_ns // 1000), so exported timelines share an epoch
    return time.perf_counter_ns() // 1000


# the four serving SLO histograms; registered here so importing the
# tracing module is what creates them (engine/scheduler only stamp)
SLO_METRICS: Tuple[str, ...] = (
    "serving.engine.queue_wait_seconds",
    "serving.engine.ttft_seconds",
    "serving.engine.tpot_seconds",
    "serving.engine.e2e_seconds",
)
_H_QWAIT = registry().histogram(
    "serving.engine.queue_wait_seconds",
    "enqueue -> admit wait per request", buckets=DEFAULT_BUCKETS)
_H_TTFT = registry().histogram(
    "serving.engine.ttft_seconds",
    "enqueue -> first generated token per request",
    buckets=DEFAULT_BUCKETS)
_H_TPOT = registry().histogram(
    "serving.engine.tpot_seconds",
    "steady-state inter-token latency per request "
    "((last - first token) / (tokens - 1))", buckets=DEFAULT_BUCKETS)
_H_E2E = registry().histogram(
    "serving.engine.e2e_seconds",
    "enqueue -> completion per finished request", buckets=DEFAULT_BUCKETS)


class TraceEvent:
    """One monotonic stamp: name, microsecond timestamp, optional meta
    (token index, chunk size, host-profiler span id, explicit dur_us)."""

    __slots__ = ("name", "t_us", "meta")

    def __init__(self, name: str, t_us: int,
                 meta: Optional[Dict[str, Any]] = None):
        self.name = name
        self.t_us = int(t_us)
        self.meta = meta

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {"name": self.name, "t_us": self.t_us}
        if self.meta:
            d.update(self.meta)
        return d

    def __repr__(self):
        return f"TraceEvent({self.name!r}, t_us={self.t_us})"


class RequestTrace:
    """The event timeline of one request (or one train step).

    Events are appended by the owning `TraceRecorder` under its lock;
    readers get copies via `timeline()`. Derived latencies return None
    until the required events exist.
    """

    __slots__ = ("request_id", "kind", "span_id", "outcome", "meta",
                 "_events")

    def __init__(self, request_id, kind: str = "request",
                 meta: Optional[Dict[str, Any]] = None):
        self.request_id = request_id
        self.kind = kind
        # same namespace + format as observability.span host spans
        self.span_id = f"{os.getpid()}-{next(_span_seq)}"
        self.outcome: Optional[str] = None
        self.meta = dict(meta) if meta else {}
        self._events: List[TraceEvent] = []

    # -- queries -----------------------------------------------------------
    def timeline(self) -> List[TraceEvent]:
        return list(self._events)

    def first(self, name: str) -> Optional[TraceEvent]:
        for e in self._events:
            if e.name == name:
                return e
        return None

    def last(self, name: str) -> Optional[TraceEvent]:
        for e in reversed(self._events):
            if e.name == name:
                return e
        return None

    def count(self, name: str) -> int:
        return sum(e.name == name for e in self._events)

    # -- derived SLOs ------------------------------------------------------
    def _gap_s(self, a: Optional[TraceEvent],
               b: Optional[TraceEvent]) -> Optional[float]:
        if a is None or b is None:
            return None
        return (b.t_us - a.t_us) / 1e6

    def queue_wait_s(self) -> Optional[float]:
        return self._gap_s(self.first("enqueue"), self.first("admit"))

    def ttft_s(self) -> Optional[float]:
        return self._gap_s(self.first("enqueue"), self.first("token"))

    def tpot_s(self) -> Optional[float]:
        n = self.count("token")
        if n < 2:
            return None
        gap = self._gap_s(self.first("token"), self.last("token"))
        return gap / (n - 1) if gap is not None else None

    def e2e_s(self) -> Optional[float]:
        if not self._events:
            return None
        return self._gap_s(self.first("enqueue"), self._events[-1])

    def to_dict(self) -> Dict[str, Any]:
        return {"request_id": self.request_id, "kind": self.kind,
                "span_id": self.span_id, "outcome": self.outcome,
                "meta": self.meta,
                "queue_wait_s": self.queue_wait_s(),
                "ttft_s": self.ttft_s(), "tpot_s": self.tpot_s(),
                "e2e_s": self.e2e_s(),
                "events": [e.to_dict() for e in self._events]}

    def __repr__(self):
        return (f"RequestTrace(id={self.request_id!r}, kind={self.kind}, "
                f"events={len(self._events)}, outcome={self.outcome})")


_TERMINAL_OBSERVES_E2E = ("finish",)


class TraceRecorder:
    """Process-wide request/step timeline recorder.

    All mutation goes through `begin` / `stamp` / `finish`, each gated on
    FLAGS_request_tracing first. Finished traces move to a bounded ring
    (FLAGS_trace_ring_size, oldest evicted) so a long-lived serving
    process cannot grow without bound. An optional background exporter
    thread drains finished traces to JSONL; it shares the same lock as
    every other accessor (paddlelint PT006 discipline).
    """

    def __init__(self, capacity: Optional[int] = None):
        if capacity is None:
            capacity = int(_flags.flag("FLAGS_trace_ring_size"))
        self._lock = threading.Lock()
        self._live: Dict[Any, RequestTrace] = {}
        self._done: deque = deque(maxlen=int(capacity))
        self._capacity = int(capacity)
        self._counters: Dict[str, deque] = {}
        self._host_span: Optional[str] = None
        self._replica: Optional[str] = None
        self._export_f = None
        self._export_thread: Optional[threading.Thread] = None
        self._export_stop: Optional[threading.Event] = None
        self._pending_export: deque = deque()

    # ------------------------------------------------------------ recording
    def begin(self, request_id, kind: str = "request",
              **meta) -> Optional[RequestTrace]:
        """Open a trace for `request_id` (replacing any live one) and
        stamp nothing; returns None with tracing off."""
        if not _FLAG.value:
            return None
        tr = RequestTrace(request_id, kind=kind, meta=meta or None)
        with self._lock:
            self._live[request_id] = tr
        return tr

    def stamp(self, request_id, name: str, **meta) -> None:
        """Append one monotonic event to the live trace of `request_id`;
        silently ignored when tracing is off or the id is unknown (a
        request admitted before tracing was switched on)."""
        if not _FLAG.value:
            return
        t = _now_us()
        with self._lock:
            tr = self._live.get(request_id)
            if tr is None:
                return
            hs = self._host_span
            if hs is not None and "host_span" not in meta:
                meta["host_span"] = hs
            rp = self._replica
            if rp is not None and "replica" not in meta:
                meta["replica"] = rp
            tr._events.append(TraceEvent(name, t, meta or None))

    def finish(self, request_id, outcome: str = "finish", **meta) -> None:
        """Stamp the terminal event, derive the SLOs into the registry
        histograms, and move the trace to the finished ring. Overloaded /
        Timeout / refused requests go through here too — they appear in
        the timeline instead of vanishing."""
        if not _FLAG.value:
            return
        self.stamp(request_id, outcome, **meta)
        with self._lock:
            tr = self._live.pop(request_id, None)
            if tr is None:
                return
            tr.outcome = outcome
            self._done.append(tr)
            if self._export_f is not None:
                self._pending_export.append(tr)
        if tr.kind != "request":
            return
        qw, ttft, tpot = (tr.queue_wait_s(), tr.ttft_s(), tr.tpot_s())
        if qw is not None:
            _H_QWAIT.observe(qw)
        if ttft is not None:
            _H_TTFT.observe(ttft)
        if tpot is not None:
            _H_TPOT.observe(tpot)
        if outcome in _TERMINAL_OBSERVES_E2E:
            e2e = tr.e2e_s()
            if e2e is not None:
                _H_E2E.observe(e2e)

    def set_host_span(self, span_id: Optional[str]) -> None:
        """Record the host-profiler span id of the engine step currently
        executing; subsequent stamps carry it for trace correlation."""
        if not _FLAG.value:
            return
        with self._lock:
            self._host_span = span_id

    def set_replica_context(self, name: Optional[str]) -> None:
        """Record which fleet replica is currently stamping; subsequent
        stamps carry ``replica=<name>`` in their meta so the fleet
        stitcher (`observability.fleet`) can split one cross-replica
        timeline into per-replica chrome-trace lanes. The serving engine
        sets this at the top of every method that stamps (and clears it
        with None for solo engines)."""
        if not _FLAG.value:
            return
        with self._lock:
            self._replica = name

    # ---------------------------------------------- cross-replica handoff
    def export_context(self, request_id) -> Optional[Dict[str, Any]]:
        """Portable trace context for a request leaving this process
        with a `KVPageHandoff`: request id, span lineage, accumulated
        events. `adopt()` on the importing replica's recorder continues
        the SAME logical timeline. Returns None with tracing off or for
        an unknown id."""
        if not _FLAG.value:
            return None
        with self._lock:
            tr = self._live.get(request_id)
            if tr is None:
                return None
            return {
                "request_id": tr.request_id, "kind": tr.kind,
                "span_id": tr.span_id, "meta": dict(tr.meta),
                "events": [{"name": e.name, "t_us": e.t_us,
                            "meta": dict(e.meta) if e.meta else None}
                           for e in tr._events],
            }

    def adopt(self, request_id, ctx: Optional[Dict[str, Any]]) -> None:
        """Continue a timeline exported by another replica's recorder
        (`export_context` travelling on the handoff). In-process fleets
        share ONE recorder, so a request that is still live here keeps
        its existing trace untouched; on a real fleet the importing
        process rebuilds the carried events — same span id, same
        lineage — and the scheduler's resume path appends to it."""
        if not _FLAG.value or not ctx:
            return
        with self._lock:
            if request_id in self._live:
                return
            tr = RequestTrace(request_id, kind=ctx.get("kind", "request"),
                              meta=ctx.get("meta") or None)
            if ctx.get("span_id"):
                tr.span_id = ctx["span_id"]
            for e in ctx.get("events", ()):
                tr._events.append(TraceEvent(e["name"], e["t_us"],
                                             e.get("meta") or None))
            self._live[request_id] = tr

    def counter(self, name: str, value, t_us: Optional[int] = None) -> None:
        """Record one sample on a named counter track — a (t, value)
        point rendered as a chrome-trace ``ph:"C"`` counter series on
        the same timeline as the request spans (the live HBM accounting
        view ISSUE 11 adds: weights / page pool / draft state /
        utilization). Bounded per series by the ring capacity."""
        if not _FLAG.value:
            return
        t = _now_us() if t_us is None else int(t_us)
        with self._lock:
            series = self._counters.get(name)
            if series is None:
                series = self._counters[name] = deque(
                    maxlen=self._capacity)
            series.append((t, float(value)))

    def sample_gauges(self, names: Sequence[str], reg=None) -> int:
        """Sample current registry gauge values onto counter tracks (one
        `counter()` point per gauge that exists). The engine calls this
        at the end of every step, so the exporter's counter tracks move
        in lockstep with the span timeline. Returns the sampled count."""
        if not _FLAG.value:
            return 0
        reg = reg or registry()
        n = 0
        for name in names:
            m = reg._metrics.get(name)
            if m is None or m.kind != "gauge":
                continue
            self.counter(name, m.value)
            n += 1
        return n

    # -------------------------------------------------------------- queries
    def counters(self) -> Dict[str, List[Tuple[int, float]]]:
        """Snapshot of every counter track: {name: [(t_us, value), ...]}."""
        with self._lock:
            return {k: list(v) for k, v in self._counters.items()}

    def trace(self, request_id) -> Optional[RequestTrace]:
        """Most recent trace for `request_id`: live first, then the
        newest matching finished one."""
        with self._lock:
            tr = self._live.get(request_id)
            if tr is not None:
                return tr
            for t in reversed(self._done):
                if t.request_id == request_id:
                    return t
        return None

    def live(self) -> List[RequestTrace]:
        with self._lock:
            return list(self._live.values())

    def is_live(self, request_id) -> bool:
        with self._lock:
            return request_id in self._live

    def finished(self, kind: Optional[str] = None) -> List[RequestTrace]:
        with self._lock:
            done = list(self._done)
        return [t for t in done if kind is None or t.kind == kind]

    def clear(self) -> None:
        with self._lock:
            self._live.clear()
            self._done.clear()
            self._counters.clear()
            self._pending_export.clear()
            self._host_span = None
            self._replica = None

    # ------------------------------------------------------- chrome export
    def export_chrome_trace(self, path: str,
                            include_live: bool = True) -> int:
        """Write every trace as chrome-trace JSON: one `tid` row per
        request/step, an enclosing lifetime span named
        ``<kind>:<id>[span=<span_id>]`` (the observability.span naming
        convention, so ids join against host-profiler exports), phase
        spans (queue / prefill / decode or the trainer phases), an
        instant per point event, and one ``ph:"C"`` counter event per
        counter-track sample (gauge series — page-pool utilization,
        HBM accounting — rendered by Perfetto as value-over-time tracks
        on the same clock). Returns the event count; the file
        round-trips through `profiler.load_profiler_result`."""
        with self._lock:
            traces = list(self._done) + \
                (list(self._live.values()) if include_live else [])
            counters = {k: list(v) for k, v in self._counters.items()}
        pid = os.getpid()
        events: List[Dict[str, Any]] = []
        for tid, tr in enumerate(traces, start=1):
            evs = tr.timeline()
            if not evs:
                continue
            t0, t1 = evs[0].t_us, evs[-1].t_us
            args = {"span_id": tr.span_id, "outcome": tr.outcome}
            args.update(tr.meta)
            events.append({
                "name": f"{tr.kind}:{tr.request_id}[span={tr.span_id}]",
                "ph": "X", "pid": pid, "tid": tid, "ts": t0,
                "dur": max(t1 - t0, 1), "cat": tr.kind, "args": args})
            events.extend(self._phase_events(tr, evs, pid, tid))
            for e in evs:
                rec = {"name": e.name, "ph": "i", "pid": pid, "tid": tid,
                       "ts": e.t_us, "s": "t", "cat": "event"}
                if e.meta:
                    rec["args"] = dict(e.meta)
                    dur = e.meta.get("dur_us")
                    if dur:
                        rec.update(ph="X", dur=int(dur),
                                   ts=e.t_us - int(dur), cat="phase")
                events.append(rec)
        for name, series in sorted(counters.items()):
            for t, v in series:
                events.append({"name": name, "ph": "C", "pid": pid,
                               "ts": t, "cat": "counter",
                               "args": {"value": v}})
        parent = os.path.dirname(os.path.abspath(path))
        os.makedirs(parent, exist_ok=True)
        with open(path, "w", encoding="utf-8") as f:
            json.dump({"traceEvents": events}, f)
        return len(events)

    @staticmethod
    def _phase_events(tr: RequestTrace, evs: List[TraceEvent], pid: int,
                      tid: int) -> List[Dict[str, Any]]:
        """Queue / prefill / decode phase spans for request traces (the
        trainer stamps its phases with explicit dur_us instead)."""
        if tr.kind != "request":
            return []
        out = []
        enq, adm = tr.first("enqueue"), tr.first("admit")
        tok1, tokn = tr.first("token"), tr.last("token")
        spans = [("queue", enq, adm or (evs[-1] if enq else None)),
                 ("prefill", adm, tok1), ("decode", tok1, tokn)]
        for name, a, b in spans:
            if a is None or b is None or b.t_us < a.t_us:
                continue
            out.append({"name": name, "ph": "X", "pid": pid, "tid": tid,
                        "ts": a.t_us, "dur": max(b.t_us - a.t_us, 1),
                        "cat": "phase",
                        "args": {"span_id": tr.span_id}})
        return out

    # -------------------------------------------------- background export
    def start_exporter(self, path: str,
                       interval_s: float = 1.0) -> None:
        """Start the background flush thread: finished traces are
        appended to `path` as JSONL (one trace per line). Idempotent per
        recorder; `stop_exporter` joins the thread and closes the file."""
        with self._lock:
            if self._export_thread is not None:
                return
            parent = os.path.dirname(os.path.abspath(path))
            os.makedirs(parent, exist_ok=True)
            self._export_f = open(path, "a", encoding="utf-8")
            self._export_stop = threading.Event()
            stop = self._export_stop
            t = threading.Thread(
                target=self._export_loop, args=(stop, float(interval_s)),
                name="trace-exporter", daemon=True)
            self._export_thread = t
        t.start()

    def _export_loop(self, stop: threading.Event,
                     interval_s: float) -> None:
        while not stop.wait(interval_s):
            self._flush_pending()
        self._flush_pending()

    def _flush_pending(self) -> None:
        # drain + write under the one recorder lock: the flush thread
        # touches no state outside it (paddlelint PT006)
        with self._lock:
            if self._export_f is None:
                return
            while self._pending_export:
                tr = self._pending_export.popleft()
                self._export_f.write(json.dumps(tr.to_dict()) + "\n")
            self._export_f.flush()

    def stop_exporter(self) -> None:
        with self._lock:
            t, stop = self._export_thread, self._export_stop
            self._export_thread = self._export_stop = None
        if t is None:
            return
        stop.set()
        t.join(timeout=5.0)
        self._flush_pending()
        with self._lock:
            if self._export_f is not None:
                self._export_f.close()
                self._export_f = None


_default_recorder = TraceRecorder()


def recorder() -> TraceRecorder:
    """The process-wide recorder the serving engine and trainer stamp
    into (module-level singleton, assigned once at import — readers
    never mutate the binding)."""
    return _default_recorder


# ---------------------------------------------------------------------------
# percentiles from cumulative buckets
# ---------------------------------------------------------------------------

def _hist_state(h: Union[Histogram, Mapping[str, Any]],
                buckets: Optional[Sequence[float]] = None):
    """(bounds, per-bucket counts, total) from a Histogram or a snapshot
    series dict ({'counts': [...], 'count': n} + buckets argument)."""
    if isinstance(h, Histogram):
        with h._lock:
            return h.buckets, list(h._counts), h._count
    if buckets is None:
        raise ValueError("snapshot series needs explicit buckets")
    return tuple(buckets), list(h["counts"]), int(h["count"])


def percentile(h: Union[Histogram, Mapping[str, Any]], q: float,
               buckets: Optional[Sequence[float]] = None
               ) -> Optional[float]:
    """q-th percentile (0..100) from cumulative bucket counts.

    Linear interpolation inside the landing bucket (the first bucket's
    lower edge is 0) — exact whenever observations sit on bucket bounds.
    Returns None on an empty histogram; a percentile landing in the +Inf
    bucket clamps to the largest finite bound (the Prometheus
    `histogram_quantile` convention)."""
    if not 0 <= q <= 100:
        raise ValueError(f"percentile q must be in [0, 100], got {q}")
    bounds, counts, total = _hist_state(h, buckets)
    if total == 0:
        return None
    target = q / 100.0 * total
    cum = 0.0
    for i, c in enumerate(counts):
        if cum + c >= target and c > 0:
            if i >= len(bounds):          # +Inf bucket: clamp
                return float(bounds[-1])
            lo = 0.0 if i == 0 else float(bounds[i - 1])
            hi = float(bounds[i])
            return lo + (hi - lo) * (target - cum) / c
        cum += c
    return float(bounds[-1])


def percentiles(h: Union[Histogram, Mapping[str, Any]],
                qs: Sequence[float] = (50, 90, 99),
                buckets: Optional[Sequence[float]] = None
                ) -> Dict[str, Optional[float]]:
    return {f"p{g:g}": percentile(h, g, buckets=buckets) for g in qs}


def slo_summary(names: Sequence[str] = SLO_METRICS, reg=None,
                qs: Sequence[float] = (50, 90, 99)) -> Dict[str, Any]:
    """{metric: {count, mean, p50, p90, p99}} for the serving SLO
    histograms (or any histogram names passed); metrics that never
    observed report count 0 and None quantiles."""
    reg = reg or registry()
    out: Dict[str, Any] = {}
    for name in names:
        h = reg._metrics.get(name) if name in reg._metrics else None
        if h is None or h.kind != "histogram":
            continue
        with h._lock:
            count, total = h._count, h._sum
        row: Dict[str, Any] = {
            "count": count,
            "mean": (total / count) if count else None}
        row.update(percentiles(h, qs))
        out[name] = row
    return out
