"""MoE / expert-parallel tests (SURVEY §2.3 P7; §4.2 simulated-mesh method)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import paddle_tpu as paddle
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.incubate.moe import (MoELayer, SwitchMoELayer, top_k_gating,
                                     router_z_loss)
from paddle_tpu.ops.grouped_gemm import grouped_gemm, sort_by_group, \
    unsort_by_group


def _rand(*shape, seed=0, scale=0.1):
    rng = np.random.RandomState(seed)
    return (rng.randn(*shape) * scale).astype(np.float32)


class TestGating:
    def test_topk_dispatch_shapes_and_capacity(self):
        T, E, k, C = 16, 4, 2, 8
        gates = jax.nn.softmax(jnp.asarray(_rand(T, E, seed=1, scale=1.0)))
        dispatch, combine, aux = top_k_gating(gates, k, C)
        assert dispatch.shape == (T, E, C)
        # no expert bucket slot used twice
        per_slot = np.asarray(dispatch).sum(axis=0)  # [E, C]
        assert per_slot.max() <= 1.0 + 1e-6
        # every token goes to at most k slots
        per_tok = np.asarray(dispatch).sum(axis=(1, 2))
        assert per_tok.max() <= k + 1e-6
        assert float(aux) > 0

    def test_combine_renormalized_sums_to_one(self):
        T, E, k = 8, 4, 2
        C = T  # no drops
        gates = jax.nn.softmax(jnp.asarray(_rand(T, E, seed=2, scale=1.0)))
        _, combine, _ = top_k_gating(gates, k, C, renormalize=True)
        s = np.asarray(combine).sum(axis=(1, 2))
        np.testing.assert_allclose(s, np.ones(T), rtol=1e-5)

    def test_z_loss_positive(self):
        logits = jnp.asarray(_rand(8, 4, scale=2.0))
        assert float(router_z_loss(logits)) > 0


class TestGroupedGemm:
    def test_matches_dense_loop(self):
        M, K, N, G = 12, 8, 6, 3
        lhs = jnp.asarray(_rand(M, K, seed=3))
        rhs = jnp.asarray(_rand(G, K, N, seed=4))
        sizes = jnp.asarray([5, 4, 3], jnp.int32)
        out = grouped_gemm(lhs, rhs, sizes)
        ref = np.zeros((M, N), np.float32)
        start = 0
        for g, s in enumerate([5, 4, 3]):
            ref[start:start + s] = np.asarray(lhs)[start:start + s] @ \
                np.asarray(rhs)[g]
            start += s
        np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-4, atol=1e-5)

    def test_fallback_matches_ragged(self):
        M, K, N, G = 10, 4, 4, 2
        lhs = jnp.asarray(_rand(M, K, seed=5))
        rhs = jnp.asarray(_rand(G, K, N, seed=6))
        sizes = jnp.asarray([7, 3], jnp.int32)
        a = grouped_gemm(lhs, rhs, sizes, prefer_ragged=True)
        b = grouped_gemm(lhs, rhs, sizes, prefer_ragged=False)
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)

    def test_sort_unsort_roundtrip(self):
        x = jnp.asarray(_rand(9, 3, seed=7))
        gid = jnp.asarray([2, 0, 1, 1, 0, 2, 2, 0, 1])
        srt, sizes, inv = sort_by_group(x, gid, 3)
        assert list(np.asarray(sizes)) == [3, 3, 3]
        np.testing.assert_allclose(np.asarray(unsort_by_group(srt, inv)),
                                   np.asarray(x))


class TestMoELayer:
    def test_single_expert_equals_dense_ffn(self):
        """E=1, k=1, ample capacity → exactly a dense swiglu FFN."""
        H, I = 16, 32
        layer = MoELayer(H, I, num_experts=1, top_k=1, capacity_factor=64.0)
        x = Tensor(jnp.asarray(_rand(2, 6, H, seed=8)))
        out = layer(x)
        wg = layer.w_gate._data[0]
        wu = layer.w_up._data[0]
        wd = layer.w_down._data[0]
        xa = x._data
        ref = (jax.nn.silu(xa @ wg) * (xa @ wu)) @ wd
        np.testing.assert_allclose(np.asarray(out._data), np.asarray(ref),
                                   rtol=1e-4, atol=1e-5)
        assert layer.l_aux is not None


class TestMoELayer2:
    def _mk(self, dropless, seed=11):
        H, I, E = 8, 16, 4
        rng = np.random.RandomState(seed)
        # capacity_factor=E/k makes capacity == T (provably no drops)
        layer = MoELayer(H, I, E, top_k=2, capacity_factor=E / 2.0,
                         dropless=dropless, renormalize=True)
        # deterministic weights shared between instances
        for p, nm in ((layer.gate_weight, "g"), (layer.w_gate, "wg"),
                      (layer.w_up, "wu"), (layer.w_down, "wd")):
            p._data = jnp.asarray(
                np.random.RandomState(abs(hash(nm)) % 2**31)
                .randn(*p.shape).astype(np.float32) * 0.1)
        return layer

    def test_dropless_matches_capacity_when_no_drops(self):
        a = self._mk(dropless=False)
        b = self._mk(dropless=True)
        x = Tensor(jnp.asarray(_rand(2, 4, 8, seed=12)))
        oa, ob = a(x), b(x)
        np.testing.assert_allclose(np.asarray(oa._data), np.asarray(ob._data),
                                   rtol=1e-3, atol=1e-4)

    def test_gradients_flow_to_experts(self):
        layer = self._mk(dropless=False)
        for p in layer.parameters():
            p.stop_gradient = False
        x = Tensor(jnp.asarray(_rand(2, 4, 8, seed=13)))
        out = layer(x)
        loss = (out * out).mean() + layer.l_aux * 0.01
        loss.backward()
        g = layer.w_up.grad
        assert g is not None and float(jnp.abs(g._data).max()) > 0
        assert layer.gate_weight.grad is not None

    def test_switch_layer_runs(self):
        layer = SwitchMoELayer(8, 16, 4)
        x = Tensor(jnp.asarray(_rand(2, 4, 8, seed=14)))
        out = layer(x)
        assert tuple(out.shape) == (2, 4, 8)
        assert np.isfinite(np.asarray(out._data)).all()


class TestExpertParallel:
    def test_ep_sharded_forward_matches_single_device(self):
        from paddle_tpu.distributed.mesh import build_hybrid_mesh, \
            mesh_context
        from paddle_tpu.distributed import fleet
        layer = TestMoELayer2()._mk(dropless=False)
        x = Tensor(jnp.asarray(_rand(2, 8, 8, seed=15)))
        ref = np.asarray(layer(x)._data)

        mesh = build_hybrid_mesh(dp_degree=2, ep_degree=4)
        with mesh_context(mesh):
            from jax.sharding import NamedSharding, PartitionSpec as P
            from paddle_tpu.distributed.mesh import sanitize_spec
            for p in layer.parameters():
                spec = sanitize_spec(mesh,
                                     getattr(p, "_sharding_spec", None))
                p._data = jax.device_put(p._data, NamedSharding(mesh, spec))
            out = layer(x)
        np.testing.assert_allclose(np.asarray(out._data), ref,
                                   rtol=1e-3, atol=1e-4)

    def test_moe_lm_loss_and_aux(self):
        from paddle_tpu.models.moe_llm import (MoEForCausalLM,
                                               qwen2_moe_tiny_config)
        cfg = qwen2_moe_tiny_config(sequence_parallel=False)
        model = MoEForCausalLM(cfg)
        rng = np.random.RandomState(0)
        ids = Tensor(jnp.asarray(rng.randint(0, cfg.vocab_size, (2, 16)),
                                 jnp.int32))
        labels = Tensor(jnp.asarray(rng.randint(0, cfg.vocab_size, (2, 16)),
                                    jnp.int32))
        loss, logits = model(ids, labels=labels)
        assert np.isfinite(float(loss))
        aux = model.model.aux_loss()
        assert aux is not None and float(aux) > 0
