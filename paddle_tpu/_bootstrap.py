"""Early jax.distributed bootstrap (ref: the reference initialises its
collective context from the PADDLE_* env at import/bring-up time —
SURVEY §3.1). MUST be the first import in paddle_tpu/__init__.py: package
import builds jnp values, which initialises the XLA backend, after which
``jax.distributed.initialize`` refuses to run. The launcher
(distributed/launch) exports COORDINATOR_ADDRESS (the jax coordination
port published through the TCPStore rendezvous) + PADDLE_TRAINERS_NUM /
PADDLE_TRAINER_ID; any worker that imports paddle_tpu joins the pod
automatically. ``init_parallel_env()`` stays the explicit-API parity
surface and is a no-op when this already ran."""

from __future__ import annotations

import os


def maybe_initialize() -> bool:
    """Join the jax distributed pod if the launcher env says we are one of
    N>1 processes. Idempotent. Returns True if this process is (now)
    initialized as part of a multi-process pod."""
    n = os.environ.get("PADDLE_TRAINERS_NUM", "1")
    # ONLY the launcher-published coordinator endpoint triggers the join:
    # PADDLE_MASTER is the TCPStore's port, and the jax coordination
    # service can never share it (rank 0 would fail to bind / everyone
    # else would hang talking the wrong protocol) — so it must not be
    # used as a fallback here
    coord = os.environ.get("COORDINATOR_ADDRESS")
    if n == "1" or not coord:
        return False
    # a worker's own subprocesses (dataloader workers, helpers) inherit the
    # launcher env; they must NOT join the pod as a duplicate of the
    # parent's rank — the marker records which pid actually joined
    joined_pid = os.environ.get("PADDLE_DIST_JOINED_PID")
    if joined_pid is not None and joined_pid != str(os.getpid()):
        return False
    import jax
    if jax.distributed.is_initialized():
        return True
    if os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
        # cross-process CPU collectives need gloo (the simulated
        # multi-host path; TPU pods ride ICI/DCN natively)
        try:
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        except Exception:
            pass
    jax.distributed.initialize(
        coordinator_address=coord,
        num_processes=int(n),
        process_id=int(os.environ.get("PADDLE_TRAINER_ID", 0)))
    os.environ["PADDLE_DIST_JOINED_PID"] = str(os.getpid())
    return True


def _honor_jax_platforms_env() -> None:
    """Pin jax's platform choice to the JAX_PLATFORMS env var at import
    time. On images with a preinstalled PJRT plugin (axon TPU) the plugin
    outranks the env var, so ``JAX_PLATFORMS=cpu python -m
    paddle_tpu...`` would silently land on the TPU; mirroring the env
    into jax.config BEFORE the backend initialises makes the env contract
    hold for every entry point (run_pretrain, launch workers, tools)."""
    plats = os.environ.get("JAX_PLATFORMS")
    if not plats:
        return
    try:
        import jax
        jax.config.update("jax_platforms", plats)
    except Exception:  # pragma: no cover - never block package import
        pass


_honor_jax_platforms_env()
maybe_initialize()
