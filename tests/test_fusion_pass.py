"""CINN-parity fusion pass (SURVEY §2.1 'CINN fusion compiler' row):
jaxpr pattern matching + fused-kernel substitution, flag-gated like
FLAGS_use_cinn."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.jit.fusion import fuse, match_sdpa_patterns

R = np.random.RandomState(0)
B, H, S, D = 2, 2, 16, 8


def naive_sdpa(q, k, v):
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k) * (D ** -0.5)
    p = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v)


def _qkv(dtype=np.float32):
    return tuple(jnp.asarray(R.randn(B, H, S, D).astype(np.float32) * 0.3)
                 .astype(dtype) for _ in range(3))


def test_matcher_finds_sdpa_chain():
    q, k, v = _qkv()
    closed = jax.make_jaxpr(naive_sdpa)(q, k, v)
    ms = match_sdpa_patterns(closed.jaxpr)
    assert len(ms) == 1
    assert ms[0]["scale"] == pytest.approx(D ** -0.5)
    assert len(ms[0]["chain"]) >= 8  # interior softmax chain eliminated


def test_matcher_finds_bf16_chain_through_converts():
    q, k, v = _qkv(jnp.bfloat16)
    closed = jax.make_jaxpr(naive_sdpa)(q, k, v)
    assert len(match_sdpa_patterns(closed.jaxpr)) == 1


def test_matcher_ignores_non_sdpa():
    def plain(a, b):
        return jax.nn.softmax(a @ b, axis=-1).sum()
    a = jnp.zeros((4, 4))
    closed = jax.make_jaxpr(plain)(a, a)
    assert match_sdpa_patterns(closed.jaxpr) == []


def test_externally_used_interiors_disable_fusion():
    """If the probs are ALSO returned, the whole chain must execute anyway
    — fusing would only ADD work, so the matcher declines (no
    pessimization) and outputs stay exact."""
    def sdpa_and_probs(q, k, v):
        logits = jnp.einsum("bhqd,bhkd->bhqk", q, k) * 0.5
        p = jax.nn.softmax(logits, axis=-1)
        return jnp.einsum("bhqk,bhkd->bhqd", p, v), p
    q, k, v = _qkv()
    closed = jax.make_jaxpr(sdpa_and_probs)(q, k, v)
    assert match_sdpa_patterns(closed.jaxpr) == []
    out, probs = fuse(sdpa_and_probs)(q, k, v)
    ref_out, ref_p = sdpa_and_probs(q, k, v)
    np.testing.assert_allclose(np.asarray(probs), np.asarray(ref_p),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref_out),
                               rtol=1e-4, atol=1e-5)


def test_fused_matches_naive_numerics():
    q, k, v = _qkv()
    ref = naive_sdpa(q, k, v)
    out = fuse(naive_sdpa)(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-5)


def test_fused_under_jit_and_grad():
    q, k, v = _qkv()
    out = jax.jit(fuse(naive_sdpa))(q, k, v)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(naive_sdpa(q, k, v)),
                               rtol=1e-4, atol=1e-5)
    g = jax.grad(lambda q: fuse(naive_sdpa)(q, k, v).sum())(q)
    gref = jax.grad(lambda q: naive_sdpa(q, k, v).sum())(q)
    np.testing.assert_allclose(np.asarray(g), np.asarray(gref),
                               rtol=1e-3, atol=1e-4)


def test_surrounding_ops_preserved():
    """The pass must only touch the matched region."""
    def model(x, q, k, v):
        h = jnp.tanh(x)
        a = naive_sdpa(q, k, v)
        return (h.sum() + a.sum()) * 2.0
    q, k, v = _qkv()
    x = jnp.asarray(R.randn(3, 3).astype(np.float32))
    np.testing.assert_allclose(float(fuse(model)(x, q, k, v)),
                               float(model(x, q, k, v)), rtol=1e-5)


def test_flag_gated_in_to_static():
    """FLAGS_use_fusion_compiler routes to_static through the pass
    (FLAGS_use_cinn parity) without changing results."""
    from paddle_tpu import jit, nn

    class Attn(nn.Layer):
        def forward(self, q, k, v):
            return paddle.Tensor(naive_sdpa(q._data, k._data, v._data))

    q, k, v = (paddle.to_tensor(np.asarray(t)) for t in _qkv())
    ref = Attn()(q, k, v).numpy()
    paddle.set_flags({"FLAGS_use_fusion_compiler": True})
    try:
        m = jit.to_static(Attn())
        out = m(q, k, v).numpy()
    finally:
        paddle.set_flags({"FLAGS_use_fusion_compiler": False})
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)


# ---------------------------------------------------------------------------
# pattern-table patterns beyond SDPA (VERDICT r1 item 5; ref:
# paddle/cinn/operator_fusion/ pattern registry)
# ---------------------------------------------------------------------------
class TestRmsNormPattern:
    @staticmethod
    def _rms(x, w, eps=1e-6):
        x32 = x.astype(jnp.float32)
        var = jnp.mean(jnp.square(x32), -1, keepdims=True)
        return (x32 * jax.lax.rsqrt(var + eps)).astype(x.dtype) * w

    def test_matches_and_substitutes(self):
        from paddle_tpu.jit.fusion import match_rmsnorm_patterns
        x = jnp.asarray(np.random.RandomState(0)
                        .standard_normal((4, 128)), jnp.float32)
        w = jnp.asarray(np.random.RandomState(1)
                        .standard_normal((128,)), jnp.float32)
        closed = jax.make_jaxpr(self._rms)(x, w)
        ms = match_rmsnorm_patterns(closed.jaxpr)
        assert len(ms) == 1 and ms[0]["pattern"] == "rmsnorm"
        assert abs(ms[0]["eps"] - 1e-6) < 1e-9
        # the chain must swallow the variance reduction — otherwise the
        # "fused" kernel runs NEXT TO the original math
        skipped = {closed.jaxpr.eqns[i].primitive.name
                   for i in ms[0]["chain"]}
        assert {"reduce_sum", "square", "rsqrt", "add",
                "div"} <= skipped, skipped
        fused_out = fuse(self._rms)(x, w)
        np.testing.assert_allclose(np.asarray(fused_out),
                                   np.asarray(self._rms(x, w)),
                                   rtol=2e-5, atol=2e-5)

    def test_bf16_chain_with_converts(self):
        from paddle_tpu.jit.fusion import match_rmsnorm_patterns
        x = jnp.asarray(np.random.RandomState(0)
                        .standard_normal((4, 128)), jnp.bfloat16)
        w = jnp.ones((128,), jnp.bfloat16)
        closed = jax.make_jaxpr(self._rms)(x, w)
        assert len(match_rmsnorm_patterns(closed.jaxpr)) == 1
        fused_out = fuse(self._rms)(x, w)
        np.testing.assert_allclose(
            np.asarray(fused_out, np.float32),
            np.asarray(self._rms(x, w), np.float32), rtol=2e-2, atol=2e-2)

    def test_wrong_divisor_not_matched(self):
        from paddle_tpu.jit.fusion import match_rmsnorm_patterns

        def not_rms(x, w):
            x32 = x.astype(jnp.float32)
            var = jnp.sum(jnp.square(x32), -1, keepdims=True) / 7.0
            return (x32 * jax.lax.rsqrt(var + 1e-6)).astype(x.dtype) * w
        x = jnp.ones((4, 128), jnp.float32)
        w = jnp.ones((128,))
        closed = jax.make_jaxpr(not_rms)(x, w)
        assert match_rmsnorm_patterns(closed.jaxpr) == []


class TestSwigluPattern:
    @staticmethod
    def _ffn(x, wg, wu):
        return jax.nn.silu(x @ wg) * (x @ wu)

    def test_matches_and_substitutes(self):
        from paddle_tpu.jit.fusion import match_swiglu_patterns
        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.standard_normal((4, 64)), jnp.float32)
        wg = jnp.asarray(rng.standard_normal((64, 128)), jnp.float32)
        wu = jnp.asarray(rng.standard_normal((64, 128)), jnp.float32)
        closed = jax.make_jaxpr(self._ffn)(x, wg, wu)
        ms = match_swiglu_patterns(closed.jaxpr)
        assert len(ms) == 1 and ms[0]["pattern"] == "swiglu"
        out = fuse(self._ffn)(x, wg, wu)
        np.testing.assert_allclose(np.asarray(out),
                                   np.asarray(self._ffn(x, wg, wu)),
                                   rtol=2e-5, atol=2e-5)

    def test_silu_alone_not_matched(self):
        from paddle_tpu.jit.fusion import match_swiglu_patterns
        closed = jax.make_jaxpr(jax.nn.silu)(jnp.ones((4, 8)))
        assert match_swiglu_patterns(closed.jaxpr) == []


def test_full_block_fuses_all_three_patterns():
    """A naive transformer block (inline rmsnorm + sdpa-composite +
    swiglu FFN) gets all three rewrites in one pass."""
    from paddle_tpu.jit.fusion import PATTERNS
    rng = np.random.RandomState(0)
    B, H, S, D, F = 2, 2, 128, 64, 256

    def rms(x, w):
        x32 = x.astype(jnp.float32)
        var = jnp.mean(jnp.square(x32), -1, keepdims=True)
        return (x32 * jax.lax.rsqrt(var + 1e-6)).astype(x.dtype) * w

    def block(x, w1, wq, wk, wv, w2, wg, wu):
        h = rms(x, w1)                                  # [B,S,HD]
        q = (h @ wq).reshape(B, S, H, D).transpose(0, 2, 1, 3)
        k = (h @ wk).reshape(B, S, H, D).transpose(0, 2, 1, 3)
        v = (h @ wv).reshape(B, S, H, D).transpose(0, 2, 1, 3)
        logits = jnp.einsum("bhqd,bhkd->bhqk", q, k) * (D ** -0.5)
        probs = jax.nn.softmax(logits, -1)
        o = jnp.einsum("bhqk,bhkd->bhqd", probs, v)
        o = o.transpose(0, 2, 1, 3).reshape(B, S, H * D)
        x = x + o
        h2 = rms(x, w2)
        return x + jax.nn.silu(h2 @ wg) * (h2 @ wu) @ jnp.eye(F)[:, :H * D]

    HD = H * D
    args = (jnp.asarray(rng.standard_normal((B, S, HD)), jnp.float32),
            jnp.ones((HD,), jnp.float32),
            *(jnp.asarray(rng.standard_normal((HD, HD)) * 0.1,
                          jnp.float32) for _ in range(3)),
            jnp.ones((HD,), jnp.float32),
            jnp.asarray(rng.standard_normal((HD, F)) * 0.1, jnp.float32),
            jnp.asarray(rng.standard_normal((HD, F)) * 0.1, jnp.float32))
    closed = jax.make_jaxpr(block)(*args)
    found = {name for name, (matcher, _, _) in PATTERNS.items()
             if matcher(closed.jaxpr)}
    assert found == {"sdpa", "rmsnorm", "swiglu"}, found
    out = fuse(block)(*args)
    np.testing.assert_allclose(np.asarray(out), np.asarray(block(*args)),
                               rtol=3e-4, atol=3e-4)


class TestBiasResidualLnPattern:
    """VERDICT r2 item 4: bias+dropout+residual+LN chain (eval form)."""

    def _ref(self, x, r, b, w, lb):
        h = x + b[None, :] + r
        mu = jnp.mean(h, -1, keepdims=True)
        var = jnp.mean(jnp.square(h - mu), -1, keepdims=True)
        return (h - mu) * jax.lax.rsqrt(var + 1e-5) * w[None, :] \
            + lb[None, :]

    def test_matches_and_substitutes(self):
        from paddle_tpu.jit.fusion import (fuse,
                                           match_bias_residual_ln_patterns)
        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.standard_normal((8, 256)), jnp.float32)
        r = jnp.asarray(rng.standard_normal((8, 256)), jnp.float32)
        b, w, lb = (jnp.asarray(rng.standard_normal((256,)), jnp.float32)
                    for _ in range(3))
        jx = jax.make_jaxpr(self._ref)(x, r, b, w, lb)
        ms = match_bias_residual_ln_patterns(jx.jaxpr)
        assert [m["pattern"] for m in ms] == ["bias_residual_ln"]
        assert ms[0]["bias"] is not None and ms[0]["w"] is not None
        got = fuse(self._ref)(x, r, b, w, lb)
        np.testing.assert_allclose(np.asarray(got),
                                   np.asarray(self._ref(x, r, b, w, lb)),
                                   rtol=2e-5, atol=2e-5)

    def test_residual_only_form(self):
        from paddle_tpu.jit.fusion import (fuse,
                                           match_bias_residual_ln_patterns)

        def rln(x, r):
            h = x + r
            mu = jnp.mean(h, -1, keepdims=True)
            var = jnp.mean(jnp.square(h - mu), -1, keepdims=True)
            return (h - mu) * jax.lax.rsqrt(var + 1e-5)
        rng = np.random.RandomState(1)
        x = jnp.asarray(rng.standard_normal((8, 128)), jnp.float32)
        r = jnp.asarray(rng.standard_normal((8, 128)), jnp.float32)
        jx = jax.make_jaxpr(rln)(x, r)
        assert len(match_bias_residual_ln_patterns(jx.jaxpr)) == 1
        np.testing.assert_allclose(np.asarray(fuse(rln)(x, r)),
                                   np.asarray(rln(x, r)),
                                   rtol=2e-5, atol=2e-5)

    def test_plain_ln_without_residual_not_matched(self):
        from paddle_tpu.jit.fusion import match_bias_residual_ln_patterns

        def ln(x):
            mu = jnp.mean(x, -1, keepdims=True)
            var = jnp.mean(jnp.square(x - mu), -1, keepdims=True)
            return (x - mu) * jax.lax.rsqrt(var + 1e-5)
        x = jnp.ones((8, 128), jnp.float32)
        jx = jax.make_jaxpr(ln)(x)
        assert match_bias_residual_ln_patterns(jx.jaxpr) == []

    def test_incubate_functional_fuses(self):
        """The incubate fused_bias_dropout_residual_layer_norm eval path
        is exactly this pattern."""
        import paddle_tpu as paddle
        from paddle_tpu.core.tensor import Tensor
        from paddle_tpu.incubate.nn import functional as IF
        from paddle_tpu.jit.fusion import match_bias_residual_ln_patterns

        def f(xa, ra, ba, wa, la):
            return IF.fused_bias_dropout_residual_layer_norm(
                Tensor(xa), Tensor(ra), bias=Tensor(ba),
                ln_scale=Tensor(wa), ln_bias=Tensor(la),
                dropout_rate=0.0, training=False)._data
        x = jnp.ones((4, 256), jnp.float32)
        v = jnp.ones((256,), jnp.float32)
        jx = jax.make_jaxpr(f)(x, x, v, v, v)
        assert len(match_bias_residual_ln_patterns(jx.jaxpr)) == 1


class TestMoeDispatchPattern:
    """VERDICT r2 item 4: the GShard gate's dispatch/combine einsum pair
    fuses into one two-output kernel."""

    def test_matches_gate_and_numerics(self):
        from paddle_tpu.incubate.moe import top_k_gating
        from paddle_tpu.jit.fusion import (fuse,
                                           match_moe_dispatch_patterns)
        rng = np.random.RandomState(2)
        g = jax.nn.softmax(
            jnp.asarray(rng.standard_normal((16, 8)), jnp.float32), -1)

        def gate(g):
            return top_k_gating(g, 2, 4)
        jx = jax.make_jaxpr(gate)(g)
        ms = match_moe_dispatch_patterns(jx.jaxpr)
        assert len(ms) == 1
        assert len(ms[0]["finals"]) == 2
        d_ref, c_ref, aux_ref = gate(g)
        d_f, c_f, aux_f = fuse(gate)(g)
        np.testing.assert_allclose(np.asarray(d_f), np.asarray(d_ref),
                                   rtol=1e-6)
        np.testing.assert_allclose(np.asarray(c_f), np.asarray(c_ref),
                                   rtol=1e-6)
        np.testing.assert_allclose(float(aux_f), float(aux_ref),
                                   rtol=1e-6)

    def test_unrelated_dot_pair_not_matched(self):
        from paddle_tpu.jit.fusion import match_moe_dispatch_patterns

        def f(a, b):
            return jnp.einsum("tke,tkc->tec", a, b)
        a = jnp.ones((4, 2, 8), jnp.float32)
        b = jnp.ones((4, 2, 6), jnp.float32)
        jx = jax.make_jaxpr(f)(a, b)
        assert match_moe_dispatch_patterns(jx.jaxpr) == []


def test_brln_matcher_survives_scalar_literals():
    """Round-3 review regression: a scalar-Literal consumer next to the
    LN chain must not crash the matcher (jcore.Literal is unhashable)."""
    from paddle_tpu.jit.fusion import fuse

    def f(x, r):
        h = x + r
        mu = jnp.mean(h, -1, keepdims=True)
        var = jnp.mean(jnp.square(h - mu), -1, keepdims=True)
        return (h - mu) * jax.lax.rsqrt(var + 1e-5) * 2.0
    x = jnp.ones((4, 128), jnp.float32)
    out = fuse(f)(x, x)   # must not raise
    np.testing.assert_allclose(np.asarray(out), np.asarray(f(x, x)),
                               rtol=2e-5, atol=2e-5)


def test_new_patterns_differentiate():
    """Grads must flow through the round-3 fused kernels (custom VJPs):
    brln vs plain-XLA LN backward, moe pair vs einsum backward."""
    from paddle_tpu.jit.fusion import fuse

    def brln(x, r, w):
        h = x + r
        mu = jnp.mean(h, -1, keepdims=True)
        var = jnp.mean(jnp.square(h - mu), -1, keepdims=True)
        return ((h - mu) * jax.lax.rsqrt(var + 1e-5) * w).sum()
    rng = np.random.RandomState(5)
    x = jnp.asarray(rng.standard_normal((8, 256)), jnp.float32)
    w = jnp.asarray(rng.standard_normal((256,)), jnp.float32)
    g_ref = jax.grad(brln, argnums=(0, 2))(x, x, w)
    g_fus = jax.grad(fuse(brln), argnums=(0, 2))(x, x, w)
    for a, b in zip(g_fus, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)

    from paddle_tpu.incubate.moe import top_k_gating

    def gate_loss(g):
        d, c, _ = top_k_gating(g, 2, 4)
        return (d * 0.5 + c).sum()
    gg = jax.nn.softmax(
        jnp.asarray(rng.standard_normal((16, 8)), jnp.float32), -1)
    np.testing.assert_allclose(
        np.asarray(jax.grad(fuse(gate_loss))(gg)),
        np.asarray(jax.grad(gate_loss)(gg)), rtol=1e-4, atol=1e-5)
