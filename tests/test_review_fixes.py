"""Regression tests for the round-1 code-review findings (torch CPU is the
numerical reference for the functional ops, mirroring the reference's OpTest
check_output-vs-reference triangle, SURVEY §4.1)."""

import numpy as np
import pytest
import torch
import torch.nn.functional as TF

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.nn import functional as F


def t2n(t):
    return t.detach().numpy()


# -- conv transpose: output_padding + groups --------------------------------
@pytest.mark.parametrize("groups,output_padding,stride,pad,dil", [
    (1, 0, 2, 1, 1),
    (1, 1, 2, 1, 1),
    (2, 0, 2, 0, 1),
    (2, 1, 3, 1, 2),
])
def test_conv2d_transpose_matches_torch(groups, output_padding, stride, pad,
                                        dil):
    rng = np.random.RandomState(0)
    x = rng.randn(2, 4, 7, 7).astype(np.float32)
    w = rng.randn(4, 6 // groups, 3, 3).astype(np.float32)  # [in, out/g, k, k]
    b = rng.randn(6).astype(np.float32)
    ref = TF.conv_transpose2d(torch.tensor(x), torch.tensor(w),
                              torch.tensor(b), stride=stride, padding=pad,
                              output_padding=output_padding, groups=groups,
                              dilation=dil)
    out = F.conv2d_transpose(paddle.to_tensor(x), paddle.to_tensor(w),
                             paddle.to_tensor(b), stride=stride, padding=pad,
                             output_padding=output_padding, groups=groups,
                             dilation=dil)
    assert tuple(out.shape) == tuple(ref.shape)
    np.testing.assert_allclose(out.numpy(), t2n(ref), rtol=2e-4, atol=2e-4)


def test_conv1d_transpose_matches_torch():
    rng = np.random.RandomState(1)
    x = rng.randn(2, 4, 9).astype(np.float32)
    w = rng.randn(4, 3, 5).astype(np.float32)
    ref = TF.conv_transpose1d(torch.tensor(x), torch.tensor(w), stride=2,
                              padding=2, output_padding=1)
    out = F.conv1d_transpose(paddle.to_tensor(x), paddle.to_tensor(w),
                             stride=2, padding=2, output_padding=1)
    assert tuple(out.shape) == tuple(ref.shape)
    np.testing.assert_allclose(out.numpy(), t2n(ref), rtol=2e-4, atol=2e-4)


# -- max_pool: return_mask + ceil_mode --------------------------------------
@pytest.mark.parametrize("ceil_mode", [False, True])
def test_max_pool2d_mask_and_ceil(ceil_mode):
    rng = np.random.RandomState(2)
    x = rng.randn(2, 3, 7, 7).astype(np.float32)
    ref, ref_idx = TF.max_pool2d(torch.tensor(x), 3, stride=2, padding=1,
                                 ceil_mode=ceil_mode, return_indices=True)
    out, mask = F.max_pool2d(paddle.to_tensor(x), 3, stride=2, padding=1,
                             ceil_mode=ceil_mode, return_mask=True)
    assert tuple(out.shape) == tuple(ref.shape)
    np.testing.assert_allclose(out.numpy(), t2n(ref), rtol=1e-6)
    np.testing.assert_array_equal(mask.numpy(), t2n(ref_idx))


def test_avg_pool2d_ceil_mode_shape():
    x = paddle.rand([1, 2, 7, 7])
    out = F.avg_pool2d(x, 3, stride=2, padding=0, ceil_mode=True)
    ref = TF.avg_pool2d(torch.tensor(x.numpy()), 3, stride=2, padding=0,
                        ceil_mode=True)
    assert tuple(out.shape) == tuple(ref.shape)
    np.testing.assert_allclose(out.numpy(), t2n(ref), rtol=1e-5)


# -- interpolate: align_corners + area --------------------------------------
def test_interpolate_align_corners_matches_torch():
    rng = np.random.RandomState(3)
    x = rng.randn(1, 2, 5, 5).astype(np.float32)
    ref = TF.interpolate(torch.tensor(x), size=(9, 11), mode="bilinear",
                         align_corners=True)
    out = F.interpolate(paddle.to_tensor(x), size=(9, 11), mode="bilinear",
                        align_corners=True)
    np.testing.assert_allclose(out.numpy(), t2n(ref), rtol=1e-4, atol=1e-5)


def test_interpolate_area_matches_torch():
    rng = np.random.RandomState(4)
    x = rng.randn(1, 2, 8, 8).astype(np.float32)
    ref = TF.interpolate(torch.tensor(x), size=(4, 4), mode="area")
    out = F.interpolate(paddle.to_tensor(x), size=(4, 4), mode="area")
    np.testing.assert_allclose(out.numpy(), t2n(ref), rtol=1e-5)


# -- dropout downscale_in_infer ---------------------------------------------
def test_dropout_downscale_in_infer():
    x = paddle.ones([4, 4])
    out = F.dropout(x, p=0.5, training=False, mode="downscale_in_infer")
    np.testing.assert_allclose(out.numpy(), 0.5 * np.ones((4, 4)), rtol=1e-6)
    # train path keeps surviving values unscaled
    out_t = F.dropout(x, p=0.5, training=True, mode="downscale_in_infer")
    vals = set(np.unique(out_t.numpy()).tolist())
    assert vals <= {0.0, 1.0}


# -- GradScaler unscale-then-step -------------------------------------------
def test_grad_scaler_no_double_unscale():
    from paddle_tpu.amp import GradScaler
    from paddle_tpu.optimizer import SGD
    net = nn.Linear(4, 4)
    opt = SGD(learning_rate=0.0, parameters=net.parameters())
    scaler = GradScaler(init_loss_scaling=1024.0)
    x = paddle.ones([2, 4])
    loss = net(x).sum()
    scaler.scale(loss).backward()
    scaler.unscale_(opt)
    g1 = net.weight.grad.numpy().copy()
    scaler.step(opt)          # must NOT unscale a second time
    scaler.update()
    np.testing.assert_allclose(g1, np.full((4, 4), 2.0), rtol=1e-6)
    # next step unscales again after update() reset
    for p in net.parameters():
        p.clear_grad()
    loss = net(x).sum()
    scaler.scale(loss).backward()
    scaler.unscale_(opt)
    np.testing.assert_allclose(net.weight.grad.numpy(), g1, rtol=1e-6)


# -- amp.decorate single model ----------------------------------------------
def test_amp_decorate_returns_single_model():
    from paddle_tpu.amp import decorate
    from paddle_tpu.optimizer import SGD
    net = nn.Linear(2, 2)
    o1 = SGD(learning_rate=0.1, parameters=net.parameters())
    o2 = SGD(learning_rate=0.1, parameters=net.parameters())
    m, opts = decorate(net, [o1, o2], level="O1")
    assert m is net
    assert opts == [o1, o2]
    m2, o = decorate(net, o1, level="O1")
    assert m2 is net and o is o1
    assert decorate(net, level="O1") is net


# -- buffer reassignment stays registered -----------------------------------
def test_buffer_reassignment_keeps_registration():
    layer = nn.Linear(2, 2)
    layer.register_buffer("steps", paddle.to_tensor(np.zeros(1, np.float32)))
    layer.steps = paddle.to_tensor(np.ones(1, np.float32))
    assert "steps" in dict(layer.named_buffers())
    assert "steps" in layer.state_dict()
    np.testing.assert_allclose(layer.state_dict()["steps"].numpy(), [1.0])


# -- LayerList out-of-range raises ------------------------------------------
def test_layerlist_index_error():
    ll = nn.LayerList([nn.Linear(2, 2) for _ in range(3)])
    assert ll[-1] is ll[2]
    with pytest.raises(IndexError):
        ll[5]
    with pytest.raises(IndexError):
        ll[-4]
