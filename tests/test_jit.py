"""to_static / functional_call: traced == eager, compile caching, export."""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu import jit


class Net(nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc1 = nn.Linear(4, 16)
        self.fc2 = nn.Linear(16, 2)

    def forward(self, x):
        return self.fc2(paddle.tanh(self.fc1(x)))


def test_traced_equals_eager():
    paddle.seed(0)
    net = Net()
    x = paddle.rand([3, 4])
    eager = net(x).numpy()
    snet = jit.to_static(net)
    traced = snet(x).numpy()
    np.testing.assert_allclose(traced, eager, rtol=1e-5, atol=1e-6)


def test_functional_call_pure():
    net = Net()
    x = paddle.rand([2, 4])
    state = jit.extract_state(net)
    out1 = jit.functional_call(net, state, x)
    out2 = net(x)
    np.testing.assert_allclose(out1.numpy(), out2.numpy(), rtol=1e-6)


def test_to_static_fn_with_closure():
    net = Net()
    x = paddle.rand([2, 4])

    @jit.to_static
    def step(inp):
        return net(inp).sum()

    v1 = step(x)
    v2 = net(x).sum()
    assert v1.item() == pytest.approx(v2.item(), rel=1e-5)


def test_traced_training_with_tape():
    """Whole train step (forward+backward+sgd) traced as one XLA program."""
    paddle.seed(1)
    net = Net()
    lr = 0.1

    @jit.to_static
    def train_step(x, y):
        out = net(x)
        loss = ((out - y) ** 2).mean()
        loss.backward()
        with paddle.no_grad():
            for p in net.parameters():
                p._data = p._data - lr * p.grad._data
                p._grad = None
        return loss

    x = paddle.rand([8, 4])
    y = paddle.rand([8, 2])
    losses = [train_step(x, y).item() for _ in range(5)]
    assert losses[-1] < losses[0]


def test_param_update_visible_after_trace():
    net = Net()
    before = net.fc1.weight.numpy().copy()

    @jit.to_static
    def mutate():
        net.fc1.weight._data = net.fc1.weight._data + 1.0
        return paddle.to_tensor(0.0)

    mutate()
    np.testing.assert_allclose(net.fc1.weight.numpy(), before + 1.0, rtol=1e-6)


def test_dropout_under_trace_differs_per_call():
    drop = nn.Dropout(0.5)

    @jit.to_static
    def f(x):
        return drop(x)

    x = paddle.ones([1000])
    a = f(x).numpy()
    b = f(x).numpy()
    assert (a == 0).any() and (b == 0).any()
    assert not np.array_equal(a, b)  # per-call rng folding


def test_jit_save_exports_stablehlo(tmp_path):
    net = Net()
    net.eval()
    p = str(tmp_path / "model")
    jit.save(net, p, input_spec=[([1, 4], np.float32)])
    import os
    assert os.path.exists(p + ".pdparams")
    text = open(p + ".stablehlo.txt").read()
    assert "stablehlo" in text or "func.func" in text


def test_dynamic_shape_op_raises_under_trace():
    net = Net()

    @jit.to_static
    def bad(x):
        return paddle.nonzero(x)

    with pytest.raises(NotImplementedError):
        bad(paddle.rand([4]))


def test_global_layer_discovered(tmp_path):
    """Layers referenced as module globals (not closures) are found."""
    import textwrap, subprocess, sys, os
    script = tmp_path / "g.py"
    script.write_text(textwrap.dedent("""
        import os
        os.environ["JAX_PLATFORMS"] = "cpu"
        import numpy as np
        import paddle_tpu as paddle
        import paddle_tpu.nn as nn
        from paddle_tpu import jit

        net = nn.Linear(4, 2)

        @jit.to_static
        def step(x):
            loss = net(x).sum()
            loss.backward()
            return loss

        step(paddle.rand([3, 4]))
        import jax
        assert isinstance(net.weight.grad._data, jax.Array), "grad leaked tracer"
        print("GLOBAL-OK")
    """))
    env = dict(os.environ, PYTHONPATH=os.getcwd())
    out = subprocess.run([sys.executable, str(script)], capture_output=True,
                         text=True, env=env)
    assert "GLOBAL-OK" in out.stdout, out.stderr


def test_mode_switch_retraces():
    net = nn.Sequential(nn.Linear(8, 8), nn.Dropout(0.9))
    snet = jit.to_static(net)
    x = paddle.ones([4, 8])
    net.train()
    a = snet(x).numpy()
    net.eval()
    b = snet(x).numpy()
    assert (a == 0).any() and not (b == 0).any()


def test_grad_accumulation_across_traced_calls():
    net = Net()
    snet = jit.to_static(lambda x: _loss(net, x))
    x = paddle.rand([2, 4])
    g1 = None
    snet(x)
    g1 = net.fc1.weight.grad.numpy().copy()
    snet(x)  # second call accumulates
    np.testing.assert_allclose(net.fc1.weight.grad.numpy(), 2 * g1, rtol=1e-4)


def _loss(net, x):
    l = net(x).sum()
    l.backward()
    return l


def test_static_save_load_inference_model(tmp_path):
    """ref: paddle.static.save/load_inference_model round trip
    (python/paddle/static/io.py — VERDICT r1 missing item 8): ported
    reference deployment code must run unchanged."""
    import numpy as np
    import paddle_tpu.static as static

    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [4, 8], "float32")
        lin = nn.Linear(8, 3)
        y = lin(x)
        out = paddle.nn.functional.softmax(y)
    exe = static.Executor()
    arr = np.random.RandomState(0).standard_normal((4, 8)).astype(
        np.float32)
    ref, = exe.run(main, feed={"x": arr}, fetch_list=[out])

    prefix = str(tmp_path / "infer")
    static.save_inference_model(prefix, [x], [out], exe, program=main)
    prog, feed_names, fetch_targets = static.load_inference_model(
        prefix, exe)
    assert feed_names == ["x"]
    got, = exe.run(prog, feed={"x": arr}, fetch_list=fetch_targets)
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)


def test_static_save_dynamic_batch(tmp_path):
    import numpy as np
    import paddle_tpu.static as static

    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [-1, 6], "float32")
        w = paddle.to_tensor(np.ones((6, 2), np.float32))
        y = paddle.matmul(x, w)
    exe = static.Executor()
    prefix = str(tmp_path / "dyn")
    static.save_inference_model(prefix, [x], [y], exe, program=main)
    prog, feeds, fetches = static.load_inference_model(prefix, exe)
    for b in (2, 5):
        arr = np.ones((b, 6), np.float32)
        got, = exe.run(prog, feed={"x": arr}, fetch_list=fetches)
        np.testing.assert_allclose(got, np.full((b, 2), 6.0))
