"""paddle_tpu.resilience — fault injection and recovery substrate.

Long pretrain jobs on preemptible TPU slices treat worker loss, corrupt
checkpoint shards and numeric blowups as routine (ISSUE 2). This module
provides the shared machinery every recovery path builds on:

  - a deterministic, seedable fault-injection framework (``FaultPlan``)
    driven by ``FLAGS_fault_spec`` so chaos tests and CLI runs exercise
    the exact same failure schedule (same seed -> same schedule);
  - atomic file I/O (temp-file + ``os.replace``) and bounded
    retry-with-backoff for checkpoint writes;
  - per-request ``Deadline`` budgets and an ``AdmissionGate`` for
    queue-admission backpressure in serving, with typed
    ``TimeoutResult`` / ``Overloaded`` outcomes instead of hangs;
  - the ``resilience.*`` counters every fault/recovery event reports
    into (paddle_tpu.observability), so a metrics snapshot shows what
    was injected and what was absorbed.

Fault spec grammar (full reference: docs/RESILIENCE.md)::

    spec    := clause (';' clause)*
    clause  := 'seed=' INT | kind '@' site (':' opt)*
    site    := key '=' value        # step=3, n=1, p=0.25, collective=all_reduce
    opt     := key '=' value        # times=2, ms=50, scale=100

Each injection point is a *candidate event*; ``n=K`` matches the K-th
candidate of that kind, context keys (``step=``, ``batch=``,
``collective=``) match what the call site reports, and ``p=`` draws from
a per-kind RNG stream seeded by the plan seed (deterministic given call
order). Every rule fires at most ``times`` times (default 1).
"""

from __future__ import annotations

import contextlib
import dataclasses
import os
import threading
import time
import zlib
from typing import Any, Dict, List, Mapping, Optional, Sequence, Tuple

import numpy as np

from .. import flags as _flags
from .. import observability as _obs

__all__ = [
    "FaultRule", "FaultPlan", "parse_fault_spec", "active_plan", "inject",
    "set_fault_spec", "clear_fault_spec",
    "InjectedFault", "CheckpointCorrupt", "DeadlineExceeded", "Overloaded",
    "Shed",
    "Deadline", "TimeoutResult", "AdmissionGate",
    "atomic_write", "retry_io", "crc32_bytes", "crc32_file",
    "list_checkpoints", "metrics",
]

# ---------------------------------------------------------------------------
# metrics (ISSUE 2 names these exactly; dots are fine for the JSON
# snapshot consumers — bench_util.write_resilience_report keys off the
# "resilience." prefix)
# ---------------------------------------------------------------------------
_M_FAULTS = _obs.registry().counter(
    "resilience.faults_injected", "faults fired by the active FaultPlan",
    labels=("kind",))
_M_SKIPPED = _obs.registry().counter(
    "resilience.steps_skipped", "optimizer steps skipped by trainer guards")
_M_ROLLBACKS = _obs.registry().counter(
    "resilience.rollbacks", "rollbacks to last-good trainer state")
_M_CKPT_RETRIES = _obs.registry().counter(
    "resilience.ckpt_retries", "checkpoint write attempts retried")
_M_CKPT_FALLBACKS = _obs.registry().counter(
    "resilience.ckpt_fallbacks",
    "loads redirected to a previous known-good checkpoint")
_M_DEADLINE = _obs.registry().counter(
    "resilience.deadline_misses", "serving requests past their deadline")
_M_REJECTS = _obs.registry().counter(
    "resilience.admission_rejects",
    "serving requests refused by queue-admission backpressure")
_M_LOADER_RETRIES = _obs.registry().counter(
    "resilience.loader_retries", "dataloader batches retried after a "
    "worker raise")
_M_EMERGENCY = _obs.registry().counter(
    "resilience.emergency_checkpoints",
    "emergency checkpoints written on preemption")


def metrics() -> Dict[str, Any]:
    """The resilience.* slice of the registry snapshot."""
    return {k: v for k, v in _obs.registry().snapshot().items()
            if k.startswith("resilience.")}


# ---------------------------------------------------------------------------
# typed failure outcomes
# ---------------------------------------------------------------------------
class InjectedFault(RuntimeError):
    """Raised (or used as a cause) at sites where the active FaultPlan
    fired a raising fault."""

    def __init__(self, msg: str, rule: Optional["FaultRule"] = None):
        super().__init__(msg)
        self.rule = rule


class CheckpointCorrupt(IOError):
    """Checkpoint payload failed its checksum / integrity verification."""


class DeadlineExceeded(TimeoutError):
    """A request ran past its deadline where no partial result makes
    sense (gate acquisition paths return TimeoutResult instead)."""


class Overloaded(RuntimeError):
    """Queue admission refused the request (backpressure, not failure):
    retry later or shed load upstream."""


class Shed(Overloaded):
    """The serving controller deliberately dropped this request to
    protect the SLO of higher-priority traffic (graduated load
    shedding). Subclasses `Overloaded` so every retry/backpressure
    handler keeps working, but carries its own trace outcome (`shed`)
    and the measurement that triggered the shed decision."""

    def __init__(self, msg: str, measurement: Optional[dict] = None):
        super().__init__(msg)
        self.measurement = dict(measurement or {})


@dataclasses.dataclass
class TimeoutResult:
    """Typed deadline-expiry outcome. Falsy on purpose: callers that
    treat the return as success-ish data can gate on truthiness, while
    `isinstance(r, TimeoutResult)` keeps the explicit protocol."""

    kind: str                 # "generate" | "predictor" | ...
    budget_s: float
    elapsed_s: float
    completed: int = 0        # units of work done (decode steps, ...)
    partial: Any = None       # partial outputs when they exist

    def __bool__(self) -> bool:
        return False


# ---------------------------------------------------------------------------
# fault spec parsing
# ---------------------------------------------------------------------------
_RAISING_KINDS = frozenset({
    "nan_loss", "inf_loss", "spike_loss", "nan_grad", "inf_grad",
    "ckpt_write_fail", "ckpt_read_corrupt", "loader_raise",
    "collective_delay", "collective_hang", "collective_error", "preempt",
})


def _parse_val(raw: str) -> Any:
    try:
        return int(raw)
    except ValueError:
        pass
    try:
        return float(raw)
    except ValueError:
        return raw


class FaultRule:
    """One clause of a fault spec: kind + site match + options."""

    __slots__ = ("kind", "when", "p", "times", "opts", "fired")

    def __init__(self, kind: str, when: Mapping[str, Any],
                 p: Optional[float], times: int, opts: Mapping[str, Any]):
        self.kind = kind
        self.when = dict(when)
        self.p = p
        self.times = times
        self.opts = dict(opts)
        self.fired = 0

    def __repr__(self):
        site = f"p={self.p}" if self.p is not None else \
            ",".join(f"{k}={v}" for k, v in self.when.items())
        return (f"FaultRule({self.kind}@{site}, times={self.times}, "
                f"fired={self.fired}, opts={self.opts})")


class FaultPlan:
    """A parsed, stateful fault schedule. `should_fire` is called once
    per candidate event; probabilistic rules draw from a per-kind RNG
    stream seeded by the plan seed, so two plans parsed from the same
    spec fire identically over the same event sequence."""

    def __init__(self, rules: Sequence[FaultRule], seed: int = 0,
                 spec: str = ""):
        self.rules = list(rules)
        self.seed = int(seed)
        self.spec = spec
        self._seen: Dict[str, int] = {}
        self._rng: Dict[str, np.random.RandomState] = {}
        self._lock = threading.Lock()

    def _rng_for(self, kind: str) -> np.random.RandomState:
        rng = self._rng.get(kind)
        if rng is None:
            rng = np.random.RandomState(
                (self.seed ^ zlib.crc32(kind.encode())) & 0x7FFFFFFF)
            self._rng[kind] = rng
        return rng

    def should_fire(self, kind: str, **ctx: Any) -> Optional[FaultRule]:
        """Register one candidate event of `kind`; return the first rule
        that fires (and record the fire), else None."""
        with self._lock:
            n = self._seen.get(kind, 0) + 1
            self._seen[kind] = n
            hit: Optional[FaultRule] = None
            for r in self.rules:
                if r.kind != kind:
                    continue
                if r.p is not None:
                    # draw unconditionally so the stream stays aligned
                    # with the candidate sequence even after exhaustion
                    draw = float(self._rng_for(kind).random_sample())
                    if hit is None and r.fired < r.times and draw < r.p:
                        hit = r
                    continue
                if hit is not None or r.fired >= r.times:
                    continue
                matched = True
                for k, v in r.when.items():
                    have = n if k == "n" else ctx.get(k)
                    if have != v and str(have) != str(v):
                        matched = False
                        break
                if matched:
                    hit = r
            if hit is not None:
                hit.fired += 1
                _M_FAULTS.labels(kind=kind).inc()
            return hit

    def reset(self) -> None:
        """Forget fire counts, candidate counters and RNG streams (the
        schedule replays identically afterwards)."""
        with self._lock:
            for r in self.rules:
                r.fired = 0
            self._seen.clear()
            self._rng.clear()


def parse_fault_spec(spec: str) -> FaultPlan:
    """Parse ``FLAGS_fault_spec`` grammar into a FaultPlan. Raises
    ValueError on malformed clauses or unknown fault kinds."""
    rules: List[FaultRule] = []
    seed = 0
    for clause in spec.split(";"):
        clause = clause.strip()
        if not clause:
            continue
        if clause.startswith("seed="):
            seed = int(clause[len("seed="):])
            continue
        if "@" not in clause:
            raise ValueError(
                f"fault clause {clause!r}: expected 'kind@site[:opt=..]' "
                f"(or 'seed=N')")
        kind, _, rest = clause.partition("@")
        kind = kind.strip()
        if kind not in _RAISING_KINDS:
            raise ValueError(f"unknown fault kind {kind!r}; known kinds: "
                             f"{sorted(_RAISING_KINDS)}")
        parts = rest.split(":")
        when: Dict[str, Any] = {}
        p: Optional[float] = None
        times = 1
        opts: Dict[str, Any] = {}
        for i, part in enumerate(parts):
            part = part.strip()
            if not part:
                continue
            if "=" not in part:
                raise ValueError(f"fault clause {clause!r}: bad "
                                 f"'{part}' (expected key=value)")
            k, _, v = part.partition("=")
            k, val = k.strip(), _parse_val(v.strip())
            if k == "p":
                p = float(val)
                if not 0.0 <= p <= 1.0:
                    raise ValueError(f"fault clause {clause!r}: p must be "
                                     f"in [0, 1]")
            elif k == "times":
                times = int(val)
            elif i == 0:
                when[k] = val
            else:
                opts[k] = val
        if p is None and not when:
            raise ValueError(f"fault clause {clause!r}: needs a site "
                             f"(key=value or p=prob)")
        rules.append(FaultRule(kind, when, p, times, opts))
    return FaultPlan(rules, seed=seed, spec=spec)


# the plan is cached on the spec string: re-reading the flag each call
# keeps env/CLI/set_flags control, while an unchanged spec keeps its
# stateful counters (times=1 means once per process, not once per call)
_FAULT_FLAG = _flags._registry["FLAGS_fault_spec"]
_plan_lock = threading.Lock()
_plan_cache: Tuple[str, Optional[FaultPlan]] = ("", None)


def active_plan() -> Optional[FaultPlan]:
    """The FaultPlan for the current FLAGS_fault_spec ('' -> None).
    The empty-spec fast path is one attribute read + one compare."""
    global _plan_cache
    spec = _FAULT_FLAG.value
    if not spec:
        if _plan_cache[0]:
            with _plan_lock:
                _plan_cache = ("", None)
        return None
    if _plan_cache[0] != spec:
        with _plan_lock:
            if _plan_cache[0] != spec:
                _plan_cache = (spec, parse_fault_spec(spec))
    return _plan_cache[1]


def set_fault_spec(spec: str) -> Optional[FaultPlan]:
    """Set FLAGS_fault_spec and force a FRESH plan (counters reset) even
    when the spec string is unchanged — the test-facing entry point."""
    global _plan_cache
    _flags.set_flags({"FLAGS_fault_spec": spec})
    with _plan_lock:
        _plan_cache = (spec, parse_fault_spec(spec) if spec else None)
    return _plan_cache[1]


def clear_fault_spec() -> None:
    set_fault_spec("")


def inject(kind: str, **ctx: Any) -> Optional[FaultRule]:
    """The hook call sites use: no-op (None) without an active plan."""
    plan = active_plan()
    if plan is None:
        return None
    return plan.should_fire(kind, **ctx)


# ---------------------------------------------------------------------------
# atomic I/O + bounded retry
# ---------------------------------------------------------------------------
def crc32_bytes(data: bytes) -> int:
    return zlib.crc32(data) & 0xFFFFFFFF


def crc32_file(path: str, chunk: int = 1 << 20) -> int:
    crc = 0
    with open(path, "rb") as f:
        while True:
            block = f.read(chunk)
            if not block:
                break
            crc = zlib.crc32(block, crc)
    return crc & 0xFFFFFFFF


def atomic_write(path: str, data: bytes) -> None:
    """Write bytes via temp-file + fsync + os.replace in the target's
    directory: a crash mid-write never truncates an existing file."""
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    tmp = os.path.join(d, f".{os.path.basename(path)}.tmp.{os.getpid()}."
                          f"{threading.get_ident()}")
    try:
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        with contextlib.suppress(OSError):
            os.unlink(tmp)
        raise


def retry_io(fn, what: str = "checkpoint write",
             retries: Optional[int] = None,
             backoff: Optional[float] = None):
    """Run `fn` with a bounded retry-with-backoff budget. OSError and
    InjectedFault are retryable; each retry bumps resilience.ckpt_retries.
    The final failure re-raises the last error."""
    if retries is None:
        retries = _flags.flag("FLAGS_ckpt_retries")
    if backoff is None:
        backoff = _flags.flag("FLAGS_ckpt_retry_backoff")
    attempt = 0
    while True:
        try:
            return fn()
        except (OSError, InjectedFault) as e:
            if attempt >= retries:
                raise
            _M_CKPT_RETRIES.inc()
            if backoff:
                time.sleep(backoff * (2 ** attempt))
            attempt += 1


def list_checkpoints(output_dir: str) -> List[Tuple[int, str]]:
    """(step, path) for every checkpoint-<step> dir under output_dir,
    ascending by step — the fallback scan order source."""
    out: List[Tuple[int, str]] = []
    try:
        names = os.listdir(output_dir)
    except OSError:
        return out
    for name in names:
        if name.startswith("checkpoint-"):
            suffix = name[len("checkpoint-"):]
            if suffix.isdigit():
                full = os.path.join(output_dir, name)
                if os.path.isdir(full):
                    out.append((int(suffix), full))
    out.sort()
    return out


# ---------------------------------------------------------------------------
# deadlines + admission backpressure (serving degradation)
# ---------------------------------------------------------------------------
class Deadline:
    """A wall-clock budget. Cheap to poll between decode steps."""

    __slots__ = ("budget_s", "_t0")

    def __init__(self, budget_s: float):
        self.budget_s = float(budget_s)
        self._t0 = time.monotonic()

    @property
    def elapsed_s(self) -> float:
        return time.monotonic() - self._t0

    def remaining_s(self) -> float:
        return self.budget_s - self.elapsed_s

    def expired(self) -> bool:
        return self.remaining_s() <= 0.0


def deadline_miss() -> None:
    _M_DEADLINE.inc()


class AdmissionGate:
    """Queue-admission backpressure: at most `max_inflight` requests
    execute; a further request waits up to `queue_timeout_s` for a slot
    and is then refused with the typed `Overloaded` error (never an
    unbounded hang)."""

    def __init__(self, max_inflight: int = 1, queue_timeout_s: float = 0.0):
        if max_inflight < 1:
            raise ValueError("max_inflight must be >= 1")
        self.max_inflight = int(max_inflight)
        self.queue_timeout_s = float(queue_timeout_s)
        self._sem = threading.BoundedSemaphore(self.max_inflight)

    def try_acquire(self, timeout_s: Optional[float] = None) -> bool:
        t = self.queue_timeout_s if timeout_s is None else float(timeout_s)
        if t > 0:
            return self._sem.acquire(timeout=t)
        return self._sem.acquire(blocking=False)

    def release(self) -> None:
        self._sem.release()

    @contextlib.contextmanager
    def admit(self, timeout_s: Optional[float] = None):
        if not self.try_acquire(timeout_s):
            _M_REJECTS.inc()
            raise Overloaded(
                f"admission gate full ({self.max_inflight} inflight); "
                f"queue wait exceeded "
                f"{self.queue_timeout_s if timeout_s is None else timeout_s:.3f}s")
        try:
            yield self
        finally:
            self.release()


# internal counters the wired subsystems report through (keeps the
# metric objects private to this module)
def _count_skip() -> None:
    _M_SKIPPED.inc()


def _count_rollback() -> None:
    _M_ROLLBACKS.inc()


def _count_fallback() -> None:
    _M_CKPT_FALLBACKS.inc()


def _count_loader_retry() -> None:
    _M_LOADER_RETRIES.inc()


def _count_emergency() -> None:
    _M_EMERGENCY.inc()
