"""paddle_tpu.jit — trace/compile bridge (ref: python/paddle/jit — @to_static
via AST transform + SOT bytecode capture; SURVEY §3.4).

TPU-native rework: because Tensor is a jax pytree and every op is
jax-traceable, *tracing the eager code directly under jax.jit* replaces both
the AST rewriter and the CPython frame-eval (SOT) machinery. `to_static(fn)`:

1. pulls the parameters/buffers out of the bound Layers (functional_call),
2. traces fn once per (shapes, dtypes) signature — guards are the jit cache
   key, the analog of SOT's guard system,
3. returns compiled XLA executables with donated buffers on later calls.

Control flow: the dy2static AST pass (jit/dy2static.py) rewrites python
``if``/``while``/``for range()`` into runtime dispatchers that execute
plain python under concrete predicates and lower through
``static.nn.cond``/``while_loop`` (lax control flow) under traced ones —
the reference's IfElse/Loop transformer, TPU-sized. What genuinely can't
capture (dynamic-shape ops, break/return inside a traced branch) raises a
clear error naming the eager fallback — the honest TPU equivalent of
SOT's silent subgraph fallback, which would hide 10-100x performance
cliffs here.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp

from .. import observability as _obs
from ..core.tensor import Tensor
from ..nn.layer.layers import Layer

# jit-cache visibility (ISSUE 1): traces happen once per new signature
# (jax.jit cache miss = a compile), calls happen every invocation; the
# hit rate is (calls - traces) / calls
_JIT_TRACE = _obs.registry().counter(
    "pt_jit_trace_total", "to_static retraces (jit-cache misses)",
    labels=("kind",))
_JIT_CALL = _obs.registry().counter(
    "pt_jit_call_total", "to_static compiled-wrapper invocations",
    labels=("kind",))

__all__ = ["to_static", "jit", "functional_call", "extract_state",
           "bind_state", "save", "load", "TracedLayer", "TranslatedLayer",
           "not_to_static"]


def extract_state(layer: Layer) -> Dict[str, jnp.ndarray]:
    """Layer → flat {name: raw array} state (params + persistable buffers)."""
    return {k: v._data for k, v in layer.state_dict().items()}


def bind_state(layer: Layer, state: Dict[str, jnp.ndarray]) -> None:
    """Write raw arrays (or tracers) back into the layer's tensors in place."""
    sd = layer.state_dict()
    for k, v in state.items():
        sd[k]._data = v


def extract_grads(layer: Layer) -> Dict[str, jnp.ndarray]:
    """Flat {name: grad array} for state tensors that currently hold a grad."""
    return {k: t._grad._data for k, t in layer.state_dict().items()
            if t._grad is not None}


def bind_grads(layer: Layer, grads: Dict[str, Any]) -> None:
    sd = layer.state_dict()
    for k, g in grads.items():
        sd[k]._grad = g if isinstance(g, Tensor) else Tensor(g)


class _StateSwap:
    """Temporarily substitute layer state (values AND grads) with tracer
    arrays during trace; restore the concrete tensors on exit."""

    def __init__(self, layers: List[Layer]):
        self.layers = layers

    def __enter__(self):
        self._saved = [extract_state(l) for l in self.layers]
        self._saved_grads = [
            {k: t._grad for k, t in l.state_dict().items()}
            for l in self.layers]
        return self

    def __exit__(self, *exc):
        for l, s, gs in zip(self.layers, self._saved, self._saved_grads):
            bind_state(l, s)
            sd = l.state_dict()
            for k, g in gs.items():
                sd[k]._grad = g
        return False


def functional_call(layer: Layer, state: Dict[str, jnp.ndarray], *args,
                    **kwargs):
    """Run layer.forward as a pure function of (state, inputs)."""
    with _StateSwap([layer]):
        bind_state(layer, state)
        out = layer(*args, **kwargs)
    return out


def _find_layers(fn) -> List[Layer]:
    """Discover the Layers whose state the traced fn reads: bound method
    target, closure cells, and module globals the code names (the SOT-guard
    analog — what the reference finds via frame inspection)."""
    layers: List[Layer] = []

    def add(obj):
        if isinstance(obj, Layer) and not any(obj is l for l in layers):
            layers.append(obj)

    if isinstance(fn, Layer):
        add(fn)
    add(getattr(fn, "__self__", None))
    code = getattr(fn, "__code__", None)
    for cell in (getattr(fn, "__closure__", None) or ()):
        try:
            add(cell.cell_contents)
        except ValueError:  # empty cell
            pass
    if code is not None:
        g = getattr(fn, "__globals__", {})
        # walk nested code objects too (lambdas / inner defs reference
        # globals through their OWN co_names — e.g. cond/while_loop branch
        # closures naming a module-level Layer)
        stack, names = [code], set()
        while stack:
            c = stack.pop()
            names.update(c.co_names)
            stack.extend(k for k in c.co_consts if isinstance(k, type(code)))
        for name in names:
            add(g.get(name))
    return layers


class StaticFunction:
    """The compiled wrapper returned by to_static (ref: dy2static
    StaticFunction + program cache)."""

    def __init__(self, fn: Callable, layers: Optional[List[Layer]] = None,
                 donate_state: bool = False, static_argnums=()):
        self._fn = fn
        self._layers = layers if layers is not None else _find_layers(fn)
        self._static_argnums = static_argnums
        self._compiled = None
        self._donate = donate_state
        functools.update_wrapper(self, fn, updated=[])

    @staticmethod
    def _is_static_leaf(x) -> bool:
        """Outputs jit can't return (Layers, arbitrary objects) are carried
        around the trace as static values instead of through it. Containers
        and registered pytrees (incl. Tensor) must recurse, so only default-
        registry leaves can be static."""
        import numpy as _np
        if not jax.tree_util.all_leaves([x]):
            return False
        if isinstance(x, (jnp.ndarray, _np.ndarray, int, float, bool,
                          complex, bytes)) or x is None:
            return False
        return not hasattr(x, "__jax_array__")

    def _build(self):
        fn = self._fn
        layers = self._layers
        aux = self._aux = {}

        def pure(mode_sig, states, grads, rng_state, args, kwargs):
            # mode_sig is static: a train()/eval() flip retraces (the guard
            # the reference's SOT records on mutable layer attributes)
            del mode_sig
            from ..framework.random import rng_key_guard
            with _StateSwap(layers):
                for l, s, g in zip(layers, states, grads):
                    bind_state(l, s)
                    sd = l.state_dict()
                    for t in sd.values():
                        t._grad = None
                    bind_grads(l, g)
                with rng_key_guard(rng_state):
                    out = fn(*args, **kwargs)
                new_states = [extract_state(l) for l in layers]
                # grads created/accumulated inside the trace (loss.backward())
                # must cross the jit boundary as outputs, or they leak tracers
                new_grads = [extract_grads(l) for l in layers]
            # split static (non-jax) output leaves out of the traced result;
            # recorded at trace time, re-inserted in __call__ (structure is
            # assumed stable across signatures, like SOT's guard assumption)
            leaves, treedef = jax.tree_util.tree_flatten(
                out, is_leaf=StaticFunction._is_static_leaf)
            statics = {i: v for i, v in enumerate(leaves)
                       if StaticFunction._is_static_leaf(v)}
            aux["treedef"], aux["statics"] = treedef, statics
            dyn = [v for i, v in enumerate(leaves) if i not in statics]
            return dyn, new_states, new_grads

        # CINN-parity pass (FLAGS_use_cinn analog): rewrite SDPA chains in
        # the traced program into the fused attention kernel. The flag is
        # re-read at every retrace (flags contract: "read once per trace"),
        # so toggling it takes effect on the next recompile.
        pure_dyn = pure

        def pure(mode_sig, *rest):
            # this wrapper only runs while jax.jit TRACES (a cache miss),
            # so the increment counts compiles, not steady-state calls
            _JIT_TRACE.labels(kind="to_static").inc()
            from ..flags import get_flags
            if get_flags("FLAGS_use_fusion_compiler")[
                    "FLAGS_use_fusion_compiler"]:
                from .fusion import fuse
                return fuse(functools.partial(pure_dyn, mode_sig))(*rest)
            return pure_dyn(mode_sig, *rest)
        self._compiled = jax.jit(pure, static_argnums=(0,))

    def _mode_signature(self):
        return tuple(l.training for lay in self._layers
                     for l in lay.sublayers(include_self=True))

    def __call__(self, *args, **kwargs):
        if _obs.enabled():
            _JIT_CALL.labels(kind="to_static").inc()
        if self._compiled is None:
            self._build()
        from ..framework.random import default_generator
        states = [extract_state(l) for l in self._layers]
        grads = [extract_grads(l) for l in self._layers]
        key = default_generator.next_key()
        dyn, new_states, new_grads = self._compiled(
            self._mode_signature(), states, grads, key, args, kwargs)
        for l, s, g in zip(self._layers, new_states, new_grads):
            bind_state(l, s)  # buffers (e.g. BN running stats) updated in trace
            sd = l.state_dict()
            for t in sd.values():
                t._grad = None
            bind_grads(l, g)
        treedef, statics = self._aux["treedef"], self._aux["statics"]
        n_leaves = treedef.num_leaves
        leaves, it = [], iter(dyn)
        for i in range(n_leaves):
            leaves.append(statics[i] if i in statics else next(it))
        return jax.tree_util.tree_unflatten(treedef, leaves)

    @property
    def code(self) -> str:
        """Traced program text (ref parity: StaticFunction.code shows the
        transformed source; here the jaxpr is the program)."""
        import inspect
        return inspect.getsource(self._fn)

    def lower_text(self, *args, **kwargs) -> str:
        """StableHLO text of the traced program for the given args."""
        if self._compiled is None:
            self._build()
        states = [extract_state(l) for l in self._layers]
        grads = [extract_grads(l) for l in self._layers]
        from ..framework.random import default_generator
        key = default_generator._key
        return self._compiled.lower(self._mode_signature(), states, grads,
                                    key, args, kwargs).as_text()


def to_static(function=None, input_spec=None, build_strategy=None,
              backend=None, **kwargs):
    """@paddle.jit.to_static parity decorator. Python ``if``/``while``/
    ``for range()`` on traced tensors are captured by the dy2static AST
    pass (``jit/dy2static.py``) into lax control flow before tracing."""
    def wrap(fn):
        from . import dy2static
        if isinstance(fn, Layer):
            sf = StaticFunction(dy2static.convert(fn.forward), layers=[fn])
            fn.forward = sf
            return fn
        return StaticFunction(dy2static.convert(fn))
    if function is not None:
        return wrap(function)
    return wrap


jit = to_static


def not_to_static(fn):
    fn.__not_to_static__ = True
    return fn


class TracedLayer:
    """Result of paddle_tpu.jit.save/load — a compiled inference callable."""

    def __init__(self, layer: Layer):
        self._layer = layer
        self._fn = StaticFunction(layer.forward, layers=[layer])

    def __call__(self, *args, **kwargs):
        return self._fn(*args, **kwargs)


def _make_infer_fn(layer: Layer):
    """Pure inference fn (weights baked as constants) for export — the
    TPU-native 'inference program' (ref: the pruned forward ProgramDesc
    paddle.jit.save writes)."""
    state = extract_state(layer)

    def infer(*xs):
        from ..core import autograd as ag
        with ag.no_grad():
            out = functional_call(layer, state, *[Tensor(x) for x in xs])
        return jax.tree_util.tree_map(
            lambda t: t._data if isinstance(t, Tensor) else t, out,
            is_leaf=lambda t: isinstance(t, Tensor))
    return infer


def save(layer: Layer, path: str, input_spec=None, **config) -> None:
    """Export three artifacts (ref: paddle.jit.save producing the inference
    program consumed by AnalysisPredictor):
      path.pdparams      — weights (paddle.save format)
      path.jaxexport     — serialized jax.export program, weights baked in
                           (the servable; paddle_tpu.inference loads this)
      path.stablehlo.txt — readable StableHLO program text (debugging)
    """
    from ..framework.io import save as _save
    _save(layer.state_dict(), path + ".pdparams")
    if input_spec:
        from ..core.dtypes import convert_dtype
        specs = []
        for s in input_spec:
            if isinstance(s, Tensor):
                specs.append(jax.ShapeDtypeStruct(tuple(s.shape), s.dtype))
            elif hasattr(s, "shape") and hasattr(s, "dtype"):
                # static.InputSpec (paddle signature) — dynamic (-1) dims
                # are not exportable without shape polymorphism; concrete
                # shapes only
                shp = tuple(s.shape)
                if any(d is None or d < 0 for d in shp):
                    raise ValueError(
                        f"jit.save needs concrete dims in InputSpec, got "
                        f"{shp}")
                specs.append(jax.ShapeDtypeStruct(
                    shp, convert_dtype(s.dtype) or s.dtype))
            else:
                specs.append(jax.ShapeDtypeStruct(tuple(s[0]), s[1]))
        # remember EVERY sublayer's mode: a blanket layer.train() on restore
        # would clobber deliberately-frozen sublayers (e.g. frozen BN)
        modes = [(l, l.training) for l in layer.sublayers(include_self=True)]
        layer.eval()
        try:
            from jax import export as jexport
            infer = jax.jit(_make_infer_fn(layer))
            exported = jexport.export(infer)(*specs)
            with open(path + ".jaxexport", "wb") as f:
                f.write(exported.serialize())
            with open(path + ".stablehlo.txt", "w") as f:
                f.write(str(exported.mlir_module()))
        finally:
            for l, was in modes:
                l.training = was


class TranslatedLayer:
    """paddle.jit.load result parity: a callable inference layer backed by
    the deserialized exported program."""

    def __init__(self, exported):
        self._exported = exported
        self._call = jax.jit(exported.call)

    def __call__(self, *args):
        raw = [a._data if isinstance(a, Tensor) else jnp.asarray(a)
               for a in args]
        out = self._call(*raw)
        return jax.tree_util.tree_map(Tensor, out)

    def eval(self):
        return self

    def train(self):
        raise RuntimeError("TranslatedLayer is inference-only (the exported "
                           "program has no training graph)")


def _deserialize_exported(path: str):
    """Single loader for .jaxexport artifacts (shared by jit.load and
    inference.Predictor so format changes live in one place)."""
    from jax import export as jexport
    with open(path, "rb") as f:
        return jexport.deserialize(f.read())


def load(path: str, **config) -> TranslatedLayer:
    """Load a jit.save artifact as an inference-only callable."""
    return TranslatedLayer(_deserialize_exported(path + ".jaxexport"))
