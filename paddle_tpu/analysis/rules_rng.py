"""PT004: PRNG hygiene.

Two hazards:

1. **Key reuse without split** — the same PRNG key fed to two or more
   ``jax.random.*`` samplers produces *correlated* draws (identical, for
   the same sampler+shape). Flow: track names bound from ``PRNGKey(...)``
   / ``split(...)`` / ``fold_in(...)``; the second consumption of a key
   name without an intervening rebind is an error.
2. **Host RNG in traced code** — ``np.random.*`` / stdlib ``random.*``
   inside a traced body executes once at trace time and bakes a constant
   into the compiled program: every step "samples" the same numbers.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set

from .callgraph import PackageIndex, FunctionInfo, _dotted, _last_name
from .model import Config, Finding, register_rule

register_rule("PT004", "PRNG hygiene: key reuse without split, host RNG "
                       "in traced code", severity="error", module=__name__)

_KEY_MAKERS = {"PRNGKey", "key", "split", "fold_in", "clone"}
# jax.random samplers that consume a key as their first argument
_CONSUMERS = {"normal", "uniform", "bernoulli", "randint", "categorical",
              "truncated_normal", "gumbel", "permutation", "shuffle",
              "choice", "bits", "exponential", "gamma", "beta", "poisson",
              "laplace", "cauchy", "dirichlet", "multivariate_normal",
              "rademacher", "ball", "orthogonal", "t"}
_HOST_RNG_PREFIXES = ("np.random.", "numpy.random.", "random.",
                      "onp.random.")


def _key_name(node: ast.AST) -> Optional[str]:
    """Name (or name of an attribute chain root like self.key) used as a
    key argument."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        root = _dotted(node)
        return root
    if isinstance(node, ast.Subscript) and isinstance(node.value, ast.Name):
        # keys[i] — treat each subscript expr as distinct enough; use text
        try:
            return ast.unparse(node)
        except Exception:  # pragma: no cover
            return None
    return None


def _check_key_reuse(fi: FunctionInfo, mi, findings: List[Finding]) -> None:
    if isinstance(fi.node, ast.Lambda):
        return
    key_vars: Set[str] = {p for p in fi.params
                          if p in ("key", "rng", "prng_key", "rng_key",
                                   "seed_key")}
    consumed: Set[str] = set()

    def _targets(assign: ast.Assign) -> Set[str]:
        out: Set[str] = set()
        for t in assign.targets:
            for n in ast.walk(t):
                if isinstance(n, ast.Name):
                    out.add(n.id)
        return out

    def _is_key_expr(value: ast.AST) -> bool:
        for n in ast.walk(value):
            if isinstance(n, ast.Call) \
                    and _last_name(n.func) in _KEY_MAKERS:
                return True
        return False

    def visit(node) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda, ast.ClassDef)):
            return
        if isinstance(node, ast.Assign):
            visit(node.value)  # consumption inside the RHS happens first
            rebound = _targets(node)
            if _is_key_expr(node.value):
                key_vars.update(rebound)
            for name in rebound:
                consumed.discard(name)
            return
        if isinstance(node, ast.Call):
            name = _last_name(node.func)
            if name in _CONSUMERS and node.args:
                k = _key_name(node.args[0])
                if k is not None and (k in key_vars
                                      or k.endswith("key")
                                      or k == "rng"):
                    if k in consumed:
                        findings.append(Finding(
                            "PT004", "error", mi.rel, node.lineno,
                            node.col_offset, fi.qualname,
                            f"PRNG key `{k}` consumed again without a "
                            f"`split` — draws are correlated",
                            hint="key, sub = jax.random.split(key) "
                                 "before each consumption",
                            detail=f"key-reuse:{k}"))
                    consumed.add(k)
        for child in ast.iter_child_nodes(node):
            visit(child)

    for stmt in fi.node.body:
        visit(stmt)


def _check_host_rng(fi: FunctionInfo, mi, findings: List[Finding]) -> None:
    nodes = (ast.walk(fi.node.body) if isinstance(fi.node, ast.Lambda)
             else ast.walk(fi.node))
    for node in nodes:
        if not isinstance(node, ast.Call):
            continue
        dotted = _dotted(node.func) or ""
        if dotted.startswith(_HOST_RNG_PREFIXES):
            findings.append(Finding(
                "PT004", "error", mi.rel, node.lineno, node.col_offset,
                fi.qualname,
                f"host RNG `{dotted}` inside traced code — it runs once at "
                f"trace time, so every compiled call reuses the same draw",
                hint="thread a jax.random key through the traced function",
                detail=f"host-rng:{dotted}"))


def run(index: PackageIndex, cfg: Config) -> List[Finding]:
    if not cfg.wants("PT004"):
        return []
    findings: List[Finding] = []
    for mi in index.modules.values():
        for fi in mi.functions.values():
            _check_key_reuse(fi, mi, findings)
    for key in sorted(index.traced):
        fi = index.functions.get(key)
        if fi is None:
            continue
        _check_host_rng(fi, index.modules[fi.modname], findings)
    return findings
