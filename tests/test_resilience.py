"""Fault-tolerance layer (ISSUE 2): deterministic fault injection,
checkpoint integrity + atomic I/O with retry/fallback, trainer NaN/spike
guards with skip-vs-rollback policies, dataloader crash recovery, and
deadline-bounded serving — all observable through resilience.* metrics."""

import json
import os

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu import resilience as res
from paddle_tpu.io import DataLoader, Dataset
from paddle_tpu.trainer.trainer import Trainer, TrainingArguments


@pytest.fixture(autouse=True)
def _clean_plan():
    res.clear_fault_spec()
    yield
    res.clear_fault_spec()


def _metric(name: str) -> float:
    snap = res.metrics().get(name)
    if not snap:
        return 0.0
    return sum(s["value"] for s in snap["series"])


# ---------------------------------------------------------------------------
# fault-spec parsing + deterministic schedules
# ---------------------------------------------------------------------------
def test_parse_fault_spec_grammar():
    plan = res.parse_fault_spec(
        "seed=11;nan_grad@step=3;ckpt_write_fail@n=1:times=2;"
        "collective_delay@collective=all_reduce:ms=5")
    assert plan.seed == 11
    kinds = [r.kind for r in plan.rules]
    assert kinds == ["nan_grad", "ckpt_write_fail", "collective_delay"]
    assert plan.rules[0].when == {"step": 3}
    assert plan.rules[1].times == 2
    assert plan.rules[2].opts["ms"] == 5


@pytest.mark.parametrize("bad", [
    "frobnicate@step=1",          # unknown kind
    "nan_grad",                   # no site
    "nan_grad@p=1.5",             # p out of range
])
def test_parse_fault_spec_rejects(bad):
    with pytest.raises(ValueError):
        res.parse_fault_spec(bad)


def test_probabilistic_schedule_is_seed_deterministic():
    def schedule(seed):
        plan = res.parse_fault_spec(f"seed={seed};loader_raise@p=0.3:times=100")
        return [plan.should_fire("loader_raise") is not None
                for _ in range(64)]

    a, b = schedule(42), schedule(42)
    assert a == b                       # same seed -> same schedule
    assert any(a) and not all(a)        # actually probabilistic
    assert schedule(43) != a            # different seed -> different


def test_rule_fires_limited_times():
    plan = res.parse_fault_spec("seed=1;nan_loss@step=5")
    assert plan.should_fire("nan_loss", step=5) is not None
    # a rolled-back/re-executed step must NOT re-fire (times defaults 1)
    assert plan.should_fire("nan_loss", step=5) is None
    assert plan.should_fire("nan_loss", step=6) is None


# ---------------------------------------------------------------------------
# atomic I/O + integrity + retry + fallback
# ---------------------------------------------------------------------------
def test_atomic_save_writes_sidecar_and_verifies(tmp_path):
    p = str(tmp_path / "m.pdparams")
    paddle.save({"w": paddle.to_tensor(np.arange(4.0, dtype=np.float32))}, p)
    assert os.path.exists(p + ".meta.json")
    assert paddle.framework.io.verify(p)
    out = paddle.load(p)
    np.testing.assert_allclose(out["w"].numpy(), np.arange(4.0))
    # no stray temp files
    assert not [f for f in os.listdir(tmp_path) if ".tmp." in f]


def test_corrupt_file_detected_on_load(tmp_path):
    p = str(tmp_path / "m.pdparams")
    paddle.save({"w": paddle.to_tensor(np.ones(3, np.float32))}, p)
    with open(p, "r+b") as f:
        f.seek(8)
        f.write(b"\xff\xff\xff")
    assert not paddle.framework.io.verify(p)
    with pytest.raises(res.CheckpointCorrupt):
        paddle.load(p)


def test_injected_write_failure_is_retried(tmp_path):
    before = _metric("resilience.ckpt_retries")
    res.set_fault_spec("seed=2;ckpt_write_fail@n=1")
    p = str(tmp_path / "m.pdparams")
    paddle.save({"w": paddle.to_tensor(np.ones(2, np.float32))}, p)
    assert paddle.framework.io.verify(p)
    assert _metric("resilience.ckpt_retries") >= before + 1


def test_write_failure_exhausts_retries(tmp_path):
    res.set_fault_spec("seed=2;ckpt_write_fail@p=1.0:times=99")
    with pytest.raises(res.InjectedFault):
        paddle.save({"w": paddle.to_tensor(np.ones(2, np.float32))},
                    str(tmp_path / "m.pdparams"), retries=2, backoff=0.0)


def test_dist_checkpoint_corrupt_shard_falls_back(tmp_path):
    from paddle_tpu.distributed.checkpoint import (load_state_dict,
                                                   save_state_dict,
                                                   verify_checkpoint)
    good = str(tmp_path / "ck1")
    bad = str(tmp_path / "ck2")
    save_state_dict({"w": paddle.to_tensor(np.full(4, 7.0, np.float32))},
                    good)
    save_state_dict({"w": paddle.to_tensor(np.full(4, 9.0, np.float32))},
                    bad)
    # flip bytes in ck2's shard
    shard = [f for f in os.listdir(bad) if f.endswith(".npy")][0]
    with open(os.path.join(bad, shard), "r+b") as f:
        f.seek(-4, os.SEEK_END)
        f.write(b"\x00\x01\x02\x03")
    assert verify_checkpoint(good)
    assert not verify_checkpoint(bad)
    before = _metric("resilience.ckpt_fallbacks")
    target = {"w": paddle.to_tensor(np.zeros(4, np.float32))}
    with pytest.warns(RuntimeWarning):
        load_state_dict(target, bad, fallback_paths=(good,))
    np.testing.assert_allclose(target["w"].numpy(), np.full(4, 7.0))
    assert _metric("resilience.ckpt_fallbacks") >= before + 1


# ---------------------------------------------------------------------------
# trainer guards
# ---------------------------------------------------------------------------
class ToyDataset(Dataset):
    def __init__(self, n=64, seed=0):
        rng = np.random.RandomState(seed)
        self.x = rng.randn(n, 8).astype(np.float32)
        w = rng.randn(8, 2).astype(np.float32)
        self.y = self.x @ w

    def __len__(self):
        return len(self.x)

    def __getitem__(self, i):
        return self.x[i], self.y[i]


class Net(nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc = nn.Linear(8, 2)

    def forward(self, x, y=None):
        out = self.fc(x)
        if y is not None:
            return ((out - y) ** 2).mean(), out
        return out


def _args(tmp_path, **kw):
    base = dict(output_dir=str(tmp_path), per_device_train_batch_size=8,
                learning_rate=5e-2, logging_steps=2, max_steps=10,
                warmup_steps=2, seed=7)
    base.update(kw)
    return TrainingArguments(**base)


def test_nan_grad_skip_policy(tmp_path):
    res.set_fault_spec("seed=1;nan_grad@step=3")
    t = Trainer(model=Net(), args=_args(tmp_path, bad_step_policy="skip"),
                train_dataset=ToyDataset())
    state = t.train()
    assert state["global_step"] == 10       # budget still reached
    assert state["skipped_steps"] == 1
    assert any(e.get("bad_step") == "non_finite_grad"
               for e in state["log_history"])
    # the skipped grads never reached the weights
    assert np.isfinite(t.model.fc.weight.numpy()).all()


def test_nan_loss_rollback_policy(tmp_path):
    res.set_fault_spec("seed=1;nan_loss@step=4")
    t = Trainer(model=Net(),
                args=_args(tmp_path, bad_step_policy="rollback",
                           snapshot_steps=2),
                train_dataset=ToyDataset())
    state = t.train()
    assert state["global_step"] == 10
    assert state["rollbacks"] == 1
    entry = next(e for e in state["log_history"] if "bad_step" in e)
    assert entry["restored_step"] <= 4
    assert np.isfinite(t.model.fc.weight.numpy()).all()


def test_loss_spike_detected_by_ewma(tmp_path):
    res.set_fault_spec("seed=1;spike_loss@step=8:scale=1e6")
    t = Trainer(model=Net(),
                args=_args(tmp_path, bad_step_policy="skip",
                           loss_spike_factor=10.0),
                train_dataset=ToyDataset())
    state = t.train()
    assert state["skipped_steps"] == 1
    assert any(e.get("bad_step") == "loss_spike"
               for e in state["log_history"])


def test_persistent_failure_raises_after_max_bad_steps(tmp_path):
    res.set_fault_spec("seed=1;nan_loss@p=1.0:times=1000")
    t = Trainer(model=Net(),
                args=_args(tmp_path, bad_step_policy="skip",
                           max_bad_steps=3),
                train_dataset=ToyDataset())
    with pytest.raises(RuntimeError, match="max_bad_steps"):
        t.train()


def test_resume_missing_dir_lists_available(tmp_path):
    args = _args(tmp_path, save_steps=5)
    t = Trainer(model=Net(), args=args, train_dataset=ToyDataset())
    t.train()
    t2 = Trainer(model=Net(), args=args, train_dataset=ToyDataset())
    with pytest.raises(FileNotFoundError) as ei:
        t2.train(resume_from_checkpoint=str(tmp_path / "checkpoint-999"))
    msg = str(ei.value)
    assert "checkpoint-5" in msg and "checkpoint-10" in msg


# ---------------------------------------------------------------------------
# dataloader crash recovery
# ---------------------------------------------------------------------------
class HostDS(Dataset):
    def __len__(self):
        return 16

    def __getitem__(self, i):
        return np.full((4,), i, dtype=np.float32)


def test_loader_raise_recovered_thread_mode():
    res.set_fault_spec("seed=5;loader_raise@n=2")
    before = _metric("resilience.loader_retries")
    dl = DataLoader(HostDS(), batch_size=4, num_workers=2,
                    max_batch_retries=2)
    assert len(list(dl)) == 4
    assert _metric("resilience.loader_retries") >= before + 1


def test_loader_raise_propagates_without_budget():
    res.set_fault_spec("seed=5;loader_raise@n=1")
    with pytest.raises(res.InjectedFault):
        list(DataLoader(HostDS(), batch_size=4, num_workers=1))


def test_loader_worker_crash_recovered_process_mode():
    res.set_fault_spec("seed=5;loader_raise@worker=0")
    dl = DataLoader(HostDS(), batch_size=4, num_workers=2,
                    worker_mode="process", max_batch_retries=1)
    batches = list(dl)
    assert len(batches) == 4
    # order and content survive the inline re-fetch
    got = sorted(float(b[0][0]) for b in batches)
    assert got == [0.0, 4.0, 8.0, 12.0]


# ---------------------------------------------------------------------------
# serving degradation: deadlines + admission
# ---------------------------------------------------------------------------
class TinyLM(nn.Layer):
    def __init__(self, V=17, H=8):
        super().__init__()
        self.emb = nn.Embedding(V, H)
        self.fc = nn.Linear(H, V)

    def forward(self, ids):
        return self.fc(self.emb(ids))


def test_generate_deadline_returns_typed_timeout():
    from paddle_tpu.generation import generate
    before = _metric("resilience.deadline_misses")
    r = generate(TinyLM(), np.zeros((2, 3), np.int32), max_new_tokens=5,
                 decode_strategy="greedy_search", deadline_s=1e-9)
    assert isinstance(r, res.TimeoutResult) and not r
    assert r.kind == "generate" and r.completed == 0
    # partial rides along, padded to the contract width
    assert tuple(r.partial[0].shape) == (2, 5)
    assert _metric("resilience.deadline_misses") >= before + 1


def test_generate_within_deadline_is_normal():
    from paddle_tpu.generation import generate
    out = generate(TinyLM(), np.zeros((2, 3), np.int32), max_new_tokens=4,
                   decode_strategy="greedy_search", deadline_s=120.0)
    assert not isinstance(out, res.TimeoutResult)
    gen, _ = out
    assert tuple(gen.shape) == (2, 4)


def test_admission_gate_backpressure():
    gate = res.AdmissionGate(max_inflight=1, queue_timeout_s=0.01)
    before = _metric("resilience.admission_rejects")
    assert gate.try_acquire()
    with pytest.raises(res.Overloaded):
        with gate.admit():
            pass
    gate.release()
    with gate.admit():                       # slot free again
        pass
    assert _metric("resilience.admission_rejects") >= before + 1


def test_collective_fault_injection():
    from paddle_tpu.distributed import collective as coll
    res.set_fault_spec("seed=9;collective_error@collective=all_reduce")
    with pytest.raises(res.InjectedFault):
        coll.all_reduce(paddle.to_tensor(np.ones(4, np.float32)))
    # other collectives unaffected
    coll.barrier()


# ---------------------------------------------------------------------------
# end-to-end chaos run (acceptance criterion)
# ---------------------------------------------------------------------------
def test_chaos_pretrain_completes_and_resumes(tmp_path):
    # fault-free reference
    t_ref = Trainer(model=Net(), args=_args(tmp_path / "ref"),
                    train_dataset=ToyDataset())
    ref_state = t_ref.train()
    assert ref_state["global_step"] == 10

    # chaos: one NaN grad (skipped), one checkpoint write failure
    # (retried), one preemption at step 6 (emergency ckpt + clean stop)
    res.set_fault_spec(
        "seed=3;nan_grad@step=3;ckpt_write_fail@n=1;preempt@step=6")
    out = tmp_path / "chaos"
    args = _args(out, bad_step_policy="skip", save_steps=4)
    t = Trainer(model=Net(), args=args, train_dataset=ToyDataset())
    state = t.train()
    assert state["global_step"] == 6          # stopped by preemption
    assert state["skipped_steps"] == 1
    emergency = out / "checkpoint-6"
    assert emergency.is_dir()
    # integrity metadata rode along with every pickle
    assert (emergency / "model_state.pdparams.meta.json").exists()

    # resume from the emergency checkpoint -> same final step count as
    # the fault-free run, with the skipped step accounted in state
    t2 = Trainer(model=Net(), args=args, train_dataset=ToyDataset())
    state2 = t2.train(resume_from_checkpoint=str(emergency))
    assert state2["global_step"] == ref_state["global_step"] == 10
    assert state2["skipped_steps"] == 1       # carried through the resume

    # every recovery path visible in the metrics snapshot
    snap = res.metrics()
    fired = {s["labels"]["kind"]: s["value"]
             for s in snap["resilience.faults_injected"]["series"]}
    assert fired.get("nan_grad", 0) >= 1
    assert fired.get("ckpt_write_fail", 0) >= 1
    assert fired.get("preempt", 0) >= 1
    assert _metric("resilience.steps_skipped") >= 1
    assert _metric("resilience.ckpt_retries") >= 1
    assert _metric("resilience.emergency_checkpoints") >= 1


# ---------------------------------------------------------------------------
# fleet drain / re-admit (ISSUE 15: disaggregated serving resilience)
# ---------------------------------------------------------------------------
def _fleet_model():
    from paddle_tpu.models.llama import (LlamaForCausalLM,
                                         llama_tiny_config)
    paddle.seed(0)
    c = llama_tiny_config(num_hidden_layers=1)
    m = LlamaForCausalLM(c)
    m.eval()
    return m, c.vocab_size


def test_fleet_drain_on_collective_timeout_loses_nothing():
    """Acceptance: killing a replica mid-stream loses zero requests —
    running decodes move pages-intact (no re-prefill) to the survivor
    and every output stays bit-identical to the healthy-fleet run."""
    from paddle_tpu.distributed.watchdog import CollectiveTimeout
    from paddle_tpu.serving import FleetRouter, ServingEngine
    m, V = _fleet_model()
    rng = np.random.RandomState(3)
    reqs = [(rng.randint(0, V, rng.randint(3, 9)).astype(np.int32),
             int(rng.randint(3, 7))) for _ in range(5)]
    kw = dict(max_slots=2, page_size=4, prefill_chunk=4)

    def run(inject):
        a, b = ServingEngine(m, **kw), ServingEngine(m, **kw)
        router = FleetRouter({"a": a, "b": b})
        for i, (p, mn) in enumerate(reqs):
            router.submit(p, mn, request_id=i)
        if inject:
            orig, calls = b.step, [0]

            def flaky():
                calls[0] += 1
                if calls[0] == 3:   # mid-stream: b already holds work
                    raise CollectiveTimeout("injected", op="all_reduce")
                return orig()
            b.step = flaky
        return router.run_to_completion(), router

    healthy, _ = run(inject=False)
    faulted, router = run(inject=True)
    assert set(faulted) == set(healthy) == set(range(len(reqs)))
    for rid in healthy:
        np.testing.assert_array_equal(faulted[rid], healthy[rid])
    assert router.stats()["down"] == ["b"]


def test_fleet_elastic_drain_and_readmit():
    """The router's ElasticManager view: a replica whose node stops
    heartbeating is drained; when the heartbeat returns it re-enters
    rotation and serves again."""
    import time
    from paddle_tpu.native import TCPStore
    from paddle_tpu.distributed.launch import ElasticManager
    from paddle_tpu.serving import FleetRouter, ServingEngine
    m, V = _fleet_model()
    kw = dict(max_slots=2, page_size=4, prefill_chunk=4)
    s = TCPStore(is_master=True, world_size=2)
    try:
        m0 = ElasticManager(s, node_rank=0, ttl=0.2)
        m1 = ElasticManager(s, node_rank=1, ttl=0.2)
        watcher = ElasticManager(s, node_rank=0, ttl=0.2)
        router = FleetRouter(
            {"a": ServingEngine(m, **kw), "b": ServingEngine(m, **kw)},
            elastic=watcher, node_ranks={"a": 0, "b": 1})
        m0.heartbeat()
        m1.heartbeat()
        router.poll_elastic()
        assert router.live_replicas() == ["a", "b"]
        time.sleep(0.3)
        m0.heartbeat()           # node 1 went silent past its ttl
        router.poll_elastic()
        assert router.live_replicas() == ["a"]
        # the healed node heartbeats again -> back in rotation
        m1.heartbeat()
        router.poll_elastic()
        assert router.live_replicas() == ["a", "b"]
        prompt = np.arange(1, 6, dtype=np.int32)
        router.submit(prompt, 3, request_id="after")
        out = router.run_to_completion()
        assert list(out) == ["after"] and len(out["after"]) == 3
    finally:
        s.close()
