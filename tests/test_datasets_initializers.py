"""DatasetFolder/ImageFolder + Orthogonal/Dirac initializers +
profiler.load_profiler_result (long-tail parity rows)."""

import os

import numpy as np
import pytest

import paddle_tpu as paddle


def _mk_tree(tmp_path, classes=("cat", "dog"), per_class=3):
    for c in classes:
        d = tmp_path / c
        d.mkdir()
        for i in range(per_class):
            np.save(str(d / f"{i}.npy"),
                    np.full((4, 4, 3), ord(c[0]) + i, np.uint8))
    return str(tmp_path)


class TestFolders:
    def test_dataset_folder_classes_and_samples(self, tmp_path):
        from paddle_tpu.vision.datasets import DatasetFolder
        root = _mk_tree(tmp_path)
        ds = DatasetFolder(root)
        assert ds.classes == ["cat", "dog"]
        assert len(ds) == 6
        img, label = ds[0]
        assert img.shape == (4, 4, 3)
        assert label == 0
        img5, label5 = ds[5]
        assert label5 == 1

    def test_image_folder_flat(self, tmp_path):
        from paddle_tpu.vision.datasets import ImageFolder
        root = _mk_tree(tmp_path, classes=("a",), per_class=4)
        ds = ImageFolder(root)
        assert len(ds) == 4
        (img,) = ds[1]
        assert img.shape == (4, 4, 3)

    def test_transform_and_loader(self, tmp_path):
        from paddle_tpu.vision.datasets import DatasetFolder
        root = _mk_tree(tmp_path)
        ds = DatasetFolder(root, transform=lambda x: x.astype(np.float32)
                           / 255.0)
        img, _ = ds[0]
        assert img.dtype == np.float32 and img.max() <= 1.0

    def test_empty_raises(self, tmp_path):
        from paddle_tpu.vision.datasets import DatasetFolder
        (tmp_path / "empty").mkdir()
        with pytest.raises(RuntimeError):
            DatasetFolder(str(tmp_path))


class TestInitializers:
    def test_orthogonal_rows_orthonormal(self):
        from paddle_tpu.nn.initializer import Orthogonal
        paddle.seed(0)
        w = np.asarray(Orthogonal()( [4, 16], "float32"))
        np.testing.assert_allclose(w @ w.T, np.eye(4), atol=1e-5)
        # tall case: columns orthonormal
        w2 = np.asarray(Orthogonal(gain=2.0)([16, 4], "float32"))
        np.testing.assert_allclose(w2.T @ w2, 4.0 * np.eye(4), atol=1e-4)

    def test_dirac_identity_conv(self):
        import torch
        from paddle_tpu.nn.initializer import Dirac
        w = np.asarray(Dirac()([3, 3, 3, 3], "float32"))
        x = np.random.RandomState(0).randn(1, 3, 8, 8).astype(np.float32)
        y = torch.nn.functional.conv2d(torch.tensor(x), torch.tensor(w),
                                       padding=1).numpy()
        np.testing.assert_allclose(y, x, rtol=1e-5, atol=1e-6)


def test_load_profiler_result(tmp_path):
    import json
    from paddle_tpu.profiler import load_profiler_result
    f = tmp_path / "trace.json"
    f.write_text(json.dumps({"traceEvents": [
        {"name": "op1", "ph": "X", "ts": 0, "dur": 5}]}))
    ev = load_profiler_result(str(f))
    assert ev[0]["name"] == "op1"


class TestReviewRegressions:
    def test_legacy_array_trace(self, tmp_path):
        import json
        from paddle_tpu.profiler import load_profiler_result
        f = tmp_path / "legacy.json"
        f.write_text(json.dumps([{"name": "op2", "ph": "X"}]))
        ev = load_profiler_result(str(f))
        assert ev[0]["name"] == "op2"

    def test_ppm_loader_comments_and_16bit(self, tmp_path):
        from paddle_tpu.vision.datasets import _default_image_loader
        p8 = tmp_path / "img.pgm"
        payload = bytes(range(6))
        p8.write_bytes(b"P5\n# a comment\n3 2\n255\n" + payload)
        img = _default_image_loader(str(p8))
        assert img.shape == (2, 3) and img[0, 1] == 1
        p16 = tmp_path / "img16.pgm"
        data16 = np.arange(6, dtype=">u2").tobytes()
        p16.write_bytes(b"P5 3 2 65535\n" + data16)
        img16 = _default_image_loader(str(p16))
        assert img16.shape == (2, 3) and int(img16[1, 2]) == 5

    def test_png_via_pil(self, tmp_path):
        # PIL ships in this image: the standard-format path must work
        from PIL import Image
        from paddle_tpu.vision.datasets import DatasetFolder
        d = tmp_path / "cls"
        d.mkdir()
        Image.fromarray(np.zeros((4, 4, 3), np.uint8)).save(
            str(d / "a.png"))
        ds = DatasetFolder(str(tmp_path))
        img, label = ds[0]
        assert img.shape == (4, 4, 3) and label == 0
