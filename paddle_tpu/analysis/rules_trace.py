"""Trace-time rules: PT001 tracer-leak, PT002 retrace-hazard, PT005
FLAGS-mutation-at-trace-time.

PT001 runs an interprocedural taint analysis over the traced region: the
parameters of every trace root (a function decorated with / passed to
``jit``/``shard_map``/``pallas_call``/...) start tainted, and taint flows
through resolved call edges **per argument** — a callee parameter is only
tainted when some traced call site actually passes it a tainted value.
That keeps shape-helper functions (``_largest_dividing_block(S)`` called
with ``S = q.shape[1]``) out of the findings: shapes are concrete at
trace time and ``.shape``/``.ndim``/``.dtype``/``len()``/``isinstance()``
break taint.

Reported as PT001 (error): ``float()/int()/bool()/np.asarray`` over a
tainted value, ``.item()/.tolist()/.numpy()`` on a tainted receiver, and
Python ``if``/``while`` tests that depend on a tainted value — each of
these forces a concrete value out of a tracer and raises (or silently
constant-folds) at trace time.
"""

from __future__ import annotations

import ast
from collections import defaultdict
from typing import Dict, List, Optional, Set, Tuple

from .callgraph import (JIT_CONSTRUCTORS, PackageIndex, FunctionInfo,
                        _last_name, _dotted, walk_shallow)
from .model import Config, Finding, register_rule

register_rule("PT001", "tracer leak: host conversion or Python control "
                       "flow on a traced value", severity="error", module=__name__)
register_rule("PT002", "retrace hazard: jit construction in a loop, "
                       "unhashable static args, shape-dependent branch",
              severity="warning", module=__name__)
register_rule("PT005", "FLAGS mutation at trace time (set_flags/"
                       "flags_guard/define_flag inside a traced body)",
              severity="error", module=__name__)

# attribute reads that yield concrete (non-tracer) values at trace time
_BREAKER_ATTRS = {"shape", "ndim", "dtype", "size", "sharding", "device",
                  "aval", "weak_type", "itemsize", "nbytes"}
# calls whose result is concrete regardless of argument taint
_BREAKER_FUNCS = {"len", "isinstance", "type", "hasattr", "callable", "id",
                  "repr", "str", "format", "getattr_static", "issubclass",
                  "eval_shape", "ShapeDtypeStruct"}
_HOST_CONVERTERS = {"float", "int", "bool", "complex"}
_HOST_METHODS = {"item", "tolist", "numpy", "block_until_ready"}
_NP_CONVERTERS = {"np.asarray", "np.array", "numpy.asarray", "numpy.array",
                  "onp.asarray", "onp.array"}
_STATIC_COMPARE_OPS = (ast.Is, ast.IsNot, ast.In, ast.NotIn)

_FLAGS_MUTATORS = {"set_flags", "flags_guard", "define_flag"}


def _unparse(node: ast.AST, limit: int = 60) -> str:
    try:
        s = ast.unparse(node)
    except Exception:  # pragma: no cover - py<3.9 or exotic node
        s = type(node).__name__
    s = " ".join(s.split())
    return s if len(s) <= limit else s[: limit - 3] + "..."


def _isinstance_guarded(fi: FunctionInfo) -> Set[str]:
    """Names checked with isinstance() anywhere in the function: by
    contract they are static Python values (the ``isinstance(start, int)``
    idiom in generation step bodies), so they never carry taint."""
    out: Set[str] = set()
    for node in walk_shallow(fi.node):
        if isinstance(node, ast.Call) and _last_name(node.func) == \
                "isinstance" and node.args \
                and isinstance(node.args[0], ast.Name):
            out.add(node.args[0].id)
    return out


class _Ctx:
    """Interprocedural context for taint queries: ``callmap`` resolves a
    Call node (by identity) to candidate callee keys, ``returns_tainted``
    is the current return-taint fixpoint state. A call whose every
    resolved callee provably returns an untainted value (shape math,
    routing strings, eligibility bools) does not taint — this is what
    keeps `path = sdpa_path(q, k, ...); if path == "flash"` clean."""
    __slots__ = ("callmap", "returns_tainted")

    def __init__(self, callmap, returns_tainted):
        self.callmap = callmap
        self.returns_tainted = returns_tainted


def _expr_tainted(node: ast.AST, tainted: Set[str],
                  ctx: Optional[_Ctx] = None) -> bool:
    if isinstance(node, ast.Name):
        return node.id in tainted
    if isinstance(node, ast.Attribute):
        if node.attr in _BREAKER_ATTRS:
            return False
        return _expr_tainted(node.value, tainted, ctx)
    if isinstance(node, ast.Call):
        if _last_name(node.func) in _BREAKER_FUNCS:
            return False
        if ctx is not None:
            keys = ctx.callmap.get(id(node))
            if keys and all(k in ctx.returns_tainted
                            and not ctx.returns_tainted[k] for k in keys):
                return False
        if isinstance(node.func, ast.Attribute) \
                and _expr_tainted(node.func.value, tainted, ctx):
            return True
        return any(_expr_tainted(a, tainted, ctx) for a in node.args) or \
            any(_expr_tainted(kw.value, tainted, ctx)
                for kw in node.keywords)
    if isinstance(node, ast.Compare):
        if all(isinstance(op, _STATIC_COMPARE_OPS) for op in node.ops):
            return False
        return _expr_tainted(node.left, tainted, ctx) or \
            any(_expr_tainted(c, tainted, ctx) for c in node.comparators)
    if isinstance(node, (ast.Lambda, ast.Constant)):
        return False
    return any(_expr_tainted(c, tainted, ctx)
               for c in ast.iter_child_nodes(node)
               if isinstance(c, ast.expr))


def _assign_targets(node: ast.AST) -> List[str]:
    out: List[str] = []
    if isinstance(node, ast.Name):
        out.append(node.id)
    elif isinstance(node, (ast.Tuple, ast.List)):
        for elt in node.elts:
            out.extend(_assign_targets(elt))
    elif isinstance(node, ast.Starred):
        out.extend(_assign_targets(node.value))
    return out


def _decorator_static_specs(node) -> Tuple[Set[int], Set[str]]:
    """Positions/names pinned static by a decorator: ``static_argnums``,
    ``static_argnames``, and custom_vjp/custom_jvp ``nondiff_argnums``
    (nondiff args are concrete Python values through the vjp machinery in
    this codebase's usage — eps, block sizes, causal switches)."""
    pos: Set[int] = set()
    names: Set[str] = set()
    for dec in getattr(node, "decorator_list", []):
        if not isinstance(dec, ast.Call):
            continue
        for kw in dec.keywords:
            if kw.arg not in ("static_argnums", "static_argnames",
                             "nondiff_argnums"):
                continue
            try:
                val = ast.literal_eval(kw.value)
            except (ValueError, SyntaxError):
                continue
            if isinstance(val, (int, str)):
                val = (val,)
            if isinstance(val, (tuple, list)):
                for v in val:
                    if isinstance(v, int):
                        pos.add(v)
                    elif isinstance(v, str):
                        names.add(v)
    return pos, names


def _root_taint_params(fi: FunctionInfo) -> Set[str]:
    """Trace-root parameters assumed to carry tracers: everything except
    self/cls, parameters with a constant scalar default (static config
    knobs like ``causal=True``), and parameters pinned static by
    static_argnums/static_argnames/nondiff_argnums decorators."""
    node = fi.node
    a = node.args
    defaults: Dict[str, ast.AST] = {}
    pos = ([p.arg for p in getattr(a, "posonlyargs", [])] +
           [p.arg for p in a.args])
    for name, d in zip(reversed(pos), reversed(a.defaults)):
        defaults[name] = d
    for p, d in zip(a.kwonlyargs, a.kw_defaults):
        if d is not None:
            defaults[p.arg] = d
    static_pos, static_names = _decorator_static_specs(node)
    static_by_pos = {pos[i] for i in static_pos if i < len(pos)}
    out: Set[str] = set()
    for p in fi.params:
        if p in ("self", "cls"):
            continue
        if p in static_names or p in static_by_pos:
            continue
        d = defaults.get(p)
        if d is not None and isinstance(d, ast.Constant) \
                and isinstance(d.value, (bool, int, float, str, type(None))):
            continue
        out.add(p)
    return out


def _local_taint(fi: FunctionInfo, seed: Set[str],
                 ctx: Optional[_Ctx] = None) -> Set[str]:
    """Gen-only fixpoint of name taint inside one function body (kills are
    ignored — fine for a linter, keeps the walk flow-insensitive)."""
    tainted = set(seed) - _isinstance_guarded(fi)
    if isinstance(fi.node, ast.Lambda):
        return tainted
    changed = True
    while changed:
        changed = False
        for node in walk_shallow(fi.node):
            targets: List[str] = []
            value: Optional[ast.AST] = None
            if isinstance(node, ast.Assign):
                value = node.value
                for t in node.targets:
                    targets.extend(_assign_targets(t))
            elif isinstance(node, ast.AugAssign):
                value = node.value
                targets.extend(_assign_targets(node.target))
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                value = node.value
                targets.extend(_assign_targets(node.target))
            elif isinstance(node, ast.For):
                value = node.iter
                targets.extend(_assign_targets(node.target))
            elif isinstance(node, ast.NamedExpr):
                value = node.value
                targets.extend(_assign_targets(node.target))
            if value is None or not targets:
                continue
            if _expr_tainted(value, tainted, ctx):
                for t in targets:
                    if t not in tainted:
                        tainted.add(t)
                        changed = True
    return tainted


def _returns_tainted(fi: FunctionInfo, local: Set[str],
                     ctx: _Ctx) -> bool:
    if isinstance(fi.node, ast.Lambda):
        return _expr_tainted(fi.node.body, local, ctx)
    for node in walk_shallow(fi.node):
        if isinstance(node, ast.Return) and node.value is not None \
                and _expr_tainted(node.value, local, ctx):
            return True
    return False


def _propagate_taint(index: PackageIndex):
    """Optimistic whole-region fixpoint: per-function tainted params
    (monotone growing from trace roots, flowing through call arguments)
    and per-function return taint (monotone False -> True). Converges in
    a handful of sweeps on this codebase."""
    taint: Dict[str, Set[str]] = defaultdict(set)
    for key in index.traced_roots:
        fi = index.functions.get(key)
        if fi is not None:
            taint[key] = _root_taint_params(fi)
    rt: Dict[str, bool] = {key: False for key in index.traced}
    callmaps: Dict[str, Dict[int, Set[str]]] = {}
    for key in index.traced:
        fi = index.functions.get(key)
        if fi is not None:
            callmaps[key] = {id(call): keys for keys, _, call in fi.calls
                             if keys}
    order = sorted(index.traced)
    changed = True
    sweeps = 0
    while changed and sweeps < 50:
        changed = False
        sweeps += 1
        for key in order:
            fi = index.functions.get(key)
            if fi is None:
                continue
            ctx = _Ctx(callmaps.get(key, {}), rt)
            local = _local_taint(fi, taint[key], ctx)
            if not rt[key] and _returns_tainted(fi, local, ctx):
                rt[key] = True
                changed = True
            for keys, _, call in fi.calls:
                for ck in keys:
                    cfi = index.functions.get(ck)
                    if cfi is None or ck not in index.traced:
                        continue
                    new = set()
                    params = [p for p in cfi.params
                              if p not in ("self", "cls")]
                    for i, arg in enumerate(call.args):
                        if i < len(params) and _expr_tainted(arg, local,
                                                            ctx):
                            new.add(params[i])
                    for kw in call.keywords:
                        if kw.arg in params \
                                and _expr_tainted(kw.value, local, ctx):
                            new.add(kw.arg)
                    if new - taint[ck]:
                        taint[ck] |= new
                        changed = True
    return taint, rt, callmaps


def _check_traced_function(fi: FunctionInfo, mi, tainted: Set[str],
                           findings: List[Finding], cfg: Config,
                           ctx: Optional[_Ctx] = None) -> None:
    if isinstance(fi.node, ast.Lambda):
        body_nodes = list(ast.walk(fi.node.body))
    else:
        body_nodes = list(walk_shallow(fi.node))
    for node in body_nodes:
        if cfg.wants("PT001") and isinstance(node, (ast.If, ast.While)):
            if _expr_tainted(node.test, tainted, ctx):
                findings.append(Finding(
                    "PT001", "error", mi.rel, node.test.lineno,
                    node.test.col_offset, fi.qualname,
                    f"Python `{'while' if isinstance(node, ast.While) else 'if'}` "
                    f"on a traced value: `{_unparse(node.test)}`",
                    hint="use lax.cond/jnp.where, or hoist the decision to "
                         "a static argument",
                    detail=f"branch:{_unparse(node.test, 48)}"))
        if not isinstance(node, ast.Call):
            continue
        name = _last_name(node.func)
        dotted = _dotted(node.func) or ""
        if cfg.wants("PT001"):
            if name in _HOST_CONVERTERS and isinstance(node.func, ast.Name) \
                    and any(_expr_tainted(a, tainted, ctx)
                            for a in node.args):
                findings.append(Finding(
                    "PT001", "error", mi.rel, node.lineno, node.col_offset,
                    fi.qualname,
                    f"`{name}()` forces a traced value to host at trace "
                    f"time: `{_unparse(node)}`",
                    hint="keep the value on device (jnp ops) or mark the "
                         "argument static",
                    detail=f"host:{name}:{_unparse(node, 40)}"))
            elif dotted in _NP_CONVERTERS \
                    and any(_expr_tainted(a, tainted, ctx)
                            for a in node.args):
                findings.append(Finding(
                    "PT001", "error", mi.rel, node.lineno, node.col_offset,
                    fi.qualname,
                    f"`{dotted}()` materializes a traced value as a numpy "
                    f"array at trace time",
                    hint="use jnp.asarray, or compute on host before "
                         "entering the traced function",
                    detail=f"host:{dotted}:{_unparse(node, 40)}"))
            elif name in _HOST_METHODS \
                    and isinstance(node.func, ast.Attribute) \
                    and _expr_tainted(node.func.value, tainted, ctx):
                findings.append(Finding(
                    "PT001", "error", mi.rel, node.lineno, node.col_offset,
                    fi.qualname,
                    f"`.{name}()` on a traced value inside a traced body",
                    hint="return the array and convert outside the jitted "
                         "function",
                    detail=f"host:.{name}:{_unparse(node, 40)}"))
        if cfg.wants("PT005") and (name in _FLAGS_MUTATORS):
            findings.append(Finding(
                "PT005", "warning", mi.rel, node.lineno, node.col_offset,
                fi.qualname,
                f"`{name}()` mutates the FLAGS registry inside a traced "
                f"body — the write happens once at trace time, not per "
                f"call, and is invisible to retraces",
                hint="set flags before tracing, or pass the knob as a "
                     "static argument",
                detail=f"flags:{name}"))


def _check_shape_branches(fi: FunctionInfo, mi,
                          findings: List[Finding]) -> None:
    """PT002 (info): Python branches on `.shape`-derived values inside
    traced bodies are legal (shapes are static) but bake the decision into
    the compiled program — every new shape recompiles. Often deliberate;
    surfaced only under --strict."""
    def mentions_shape(node: ast.AST) -> bool:
        for n in ast.walk(node):
            if isinstance(n, ast.Attribute) and n.attr in ("shape", "ndim"):
                return True
            if isinstance(n, ast.Call) and _last_name(n.func) == "len":
                return True
        return False

    if isinstance(fi.node, ast.Lambda):
        return
    for node in walk_shallow(fi.node):
        if isinstance(node, (ast.If, ast.While)) \
                and mentions_shape(node.test):
            findings.append(Finding(
                "PT002", "info", mi.rel, node.test.lineno,
                node.test.col_offset, fi.qualname,
                f"shape-dependent Python branch in a traced body: "
                f"`{_unparse(node.test)}` — compiled per shape bucket",
                hint="fine if the shape set is bounded; otherwise pad to "
                     "buckets or use lax.cond",
                detail=f"shape-branch:{_unparse(node.test, 48)}"))


def _check_retrace(index: PackageIndex, findings: List[Finding]) -> None:
    """PT002: jit/pjit constructed under a loop (a fresh jit object has an
    empty compile cache — constructing one per iteration retraces every
    call), and unhashable static_argnums/static_argnames containers."""
    for mi in index.modules.values():
        # loop-nesting walk per function and at module level
        scopes = [(fi.qualname, fi.node) for fi in mi.functions.values()
                  if not isinstance(fi.node, ast.Lambda)]
        scopes.append(("<module>", mi.tree))

        def visit(node, qual: str, loop_depth: int) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                      ast.Lambda, ast.ClassDef)):
                    continue  # separate scope
                inc = isinstance(child, (ast.For, ast.While))
                if isinstance(child, ast.Call):
                    name = _last_name(child.func)
                    if name in JIT_CONSTRUCTORS:
                        if loop_depth > 0:
                            findings.append(Finding(
                                "PT002", "warning", mi.rel, child.lineno,
                                child.col_offset, qual,
                                f"`{name}(...)` constructed inside a loop — "
                                f"each iteration builds a fresh compile "
                                f"cache and retraces",
                                hint="hoist the jit() out of the loop (or "
                                     "cache it on self/module scope)",
                                detail=f"jit-in-loop:{_unparse(child, 40)}"))
                        for kw in child.keywords:
                            if kw.arg in ("static_argnums",
                                          "static_argnames") \
                                    and isinstance(kw.value,
                                                   (ast.Dict, ast.Set)):
                                findings.append(Finding(
                                    "PT002", "warning", mi.rel,
                                    kw.value.lineno, kw.value.col_offset,
                                    qual,
                                    f"unhashable `{kw.arg}` container "
                                    f"passed to `{name}` — jit requires "
                                    f"hashable static specs",
                                    hint="use a tuple of ints/names",
                                    detail=f"static-args:{kw.arg}"))
                visit(child, qual, loop_depth + (1 if inc else 0))

        for qual, scope in scopes:
            visit(scope, qual, 0)


def run(index: PackageIndex, cfg: Config) -> List[Finding]:
    findings: List[Finding] = []
    if cfg.wants("PT001") or cfg.wants("PT005"):
        taint, rt, callmaps = _propagate_taint(index)
        for key in sorted(index.traced):
            fi = index.functions.get(key)
            if fi is None:
                continue
            mi = index.modules[fi.modname]
            ctx = _Ctx(callmaps.get(key, {}), rt)
            local = _local_taint(fi, taint.get(key, set()), ctx)
            _check_traced_function(fi, mi, local, findings, cfg, ctx)
    if cfg.wants("PT002"):
        _check_retrace(index, findings)
        for key in sorted(index.traced):
            fi = index.functions.get(key)
            if fi is None:
                continue
            _check_shape_branches(fi, index.modules[fi.modname], findings)
    return findings
