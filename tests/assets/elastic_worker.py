"""Worker for the IN-LAUNCHER elastic scale-up test: writes a marker for
its (generation, rank, world) then — while the world is still below 3
nodes — runs until the controller's elastic relaunch SIGTERMs it. At a
3-node world it exits 0 so the whole job completes. No jax import: the
test exercises the launcher's membership/generation machinery, not the
compute path (the train path is covered by TestMultiHostTrain)."""
import os
import sys
import time

gen = os.environ.get("PADDLE_ELASTIC_GEN", "0")
rank = os.environ["PADDLE_TRAINER_ID"]
n = os.environ["PADDLE_TRAINERS_NUM"]
out = os.environ["MH_OUT"]
with open(os.path.join(out, f"g{gen}.{rank}of{n}"), "w") as f:
    f.write("ok")
print(f"elastic worker g{gen} rank {rank}/{n}", flush=True)
if int(n) >= 3:
    sys.exit(0)
for _ in range(1200):   # ~5 min ceiling; the relaunch kills us first
    time.sleep(0.25)
sys.exit(0)
