"""Trainer (PaddleNLP paddlenlp/trainer parity — SURVEY §2.4): grad
accumulation, LR schedule with warmup, logging, checkpoint/resume with
optimizer + RNG state, evaluation with metrics."""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.io import Dataset
from paddle_tpu.trainer.trainer import Trainer, TrainingArguments


class ToyDataset(Dataset):
    """y = Wx regression with fixed W."""
    def __init__(self, n=64, seed=0):
        rng = np.random.RandomState(seed)
        self.x = rng.randn(n, 8).astype(np.float32)
        w = rng.randn(8, 2).astype(np.float32)
        self.y = self.x @ w

    def __len__(self):
        return len(self.x)

    def __getitem__(self, i):
        return self.x[i], self.y[i]


class Net(nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc = nn.Linear(8, 2)

    def forward(self, x, y=None):
        out = self.fc(x)
        if y is not None:
            return ((out - y) ** 2).mean(), out
        return out


def _args(tmp_path, **kw):
    base = dict(output_dir=str(tmp_path), per_device_train_batch_size=8,
                learning_rate=5e-2, logging_steps=2, max_steps=10,
                warmup_steps=2, seed=7)
    base.update(kw)
    return TrainingArguments(**base)


def test_train_reduces_loss_and_logs(tmp_path):
    t = Trainer(model=Net(), args=_args(tmp_path),
                train_dataset=ToyDataset())
    state = t.train()
    assert state["global_step"] == 10
    logs = [e for e in state["log_history"] if "loss" in e]
    assert len(logs) >= 3
    assert logs[-1]["loss"] < logs[0]["loss"]
    assert "samples_per_sec" in logs[-1]
    # warmup then decay
    lrs = [e["lr"] for e in logs]
    assert lrs[-1] < max(lrs) + 1e-12


def test_grad_accumulation_equivalence(tmp_path):
    """accum=2 with bs=4 must match accum=1 with bs=8 step-for-step
    (same data order, same LR schedule)."""
    def run(accum, bs):
        paddle.seed(123)
        net = Net()
        t = Trainer(model=net,
                    args=_args(tmp_path, gradient_accumulation_steps=accum,
                               per_device_train_batch_size=bs, max_steps=4,
                               warmup_steps=0, logging_steps=1),
                    train_dataset=ToyDataset(n=32))
        # deterministic order
        t.get_train_dataloader = lambda: paddle.io.DataLoader(
            t.train_dataset, batch_size=bs, shuffle=False, drop_last=True)
        t.train()
        return {k: v.numpy().copy() for k, v in net.state_dict().items()}

    w1 = run(1, 8)
    w2 = run(2, 4)
    for k in w1:
        np.testing.assert_allclose(w2[k], w1[k], rtol=2e-4, atol=2e-5)


def test_checkpoint_resume_bitwise(tmp_path):
    """Stop at step 5, resume, and match an uninterrupted 10-step run."""
    data = ToyDataset(n=64, seed=3)

    def fresh(max_steps):
        paddle.seed(99)
        net = Net()
        t = Trainer(model=net, args=_args(tmp_path / "a", max_steps=max_steps,
                                          warmup_steps=0, save_steps=5,
                                          logging_steps=0),
                    train_dataset=data)
        t.get_train_dataloader = lambda: paddle.io.DataLoader(
            data, batch_size=8, shuffle=False, drop_last=True)
        return net, t

    net_full, t_full = fresh(10)
    t_full.train()

    net_half, t_half = fresh(5)
    # an interrupted run shares the FULL run's schedule horizon (the crash
    # is external; max_steps stays 10) — build the 10-step schedule first
    t_half.create_optimizer_and_scheduler(10)
    t_half.train()
    ckpt = t_half.save_checkpoint()

    paddle.seed(1234)  # resume must restore RNG, not depend on ambient seed
    net_res, t_res = fresh(10)
    t_res.train(resume_from_checkpoint=ckpt)
    assert t_res.state["global_step"] == 10

    for k, v in net_full.state_dict().items():
        np.testing.assert_allclose(net_res.state_dict()[k].numpy(),
                                   v.numpy(), rtol=1e-5, atol=1e-6)


def test_evaluate_with_metrics(tmp_path):
    def acc(preds, labels):
        return {"mse": float(((preds - labels) ** 2).mean())}
    t = Trainer(model=Net(), args=_args(tmp_path, max_steps=5),
                train_dataset=ToyDataset(), eval_dataset=ToyDataset(seed=5),
                compute_metrics=acc)
    t.train()
    m = t.evaluate()
    assert "mse" in m and np.isfinite(m["mse"])


def test_bf16_autocast_path(tmp_path):
    t = Trainer(model=Net(), args=_args(tmp_path, bf16=True, max_steps=4),
                train_dataset=ToyDataset())
    state = t.train()
    assert state["global_step"] == 4


def test_preemption_sigterm_saves_emergency_checkpoint(tmp_path):
    """SIGTERM mid-training saves a consistent checkpoint at the next step
    boundary and exits the loop (SURVEY §5.3 preemption story)."""
    import os
    import signal

    class PreemptingNet(Net):
        def forward(self, x, y=None):
            # deliver SIGTERM during step 3's forward
            if getattr(self, "_steps", 0) == 3 and not getattr(
                    self, "_sent", False):
                self._sent = True
                os.kill(os.getpid(), signal.SIGTERM)
            self._steps = getattr(self, "_steps", 0) + 1
            return super().forward(x, y)

    t = Trainer(model=PreemptingNet(),
                args=_args(tmp_path, max_steps=50, logging_steps=0),
                train_dataset=ToyDataset())
    state = t.train()
    assert state["global_step"] < 50  # stopped early
    pre = [e for e in state["log_history"] if "preempted_checkpoint" in e]
    assert len(pre) == 1
    ckpt = pre[0]["preempted_checkpoint"]
    assert os.path.exists(os.path.join(ckpt, "model_state.pdparams"))
    # and the checkpoint resumes
    t2 = Trainer(model=Net(), args=_args(tmp_path, max_steps=state[
        "global_step"] + 2, logging_steps=0), train_dataset=ToyDataset())
    t2.create_optimizer_and_scheduler(50)
    t2.train(resume_from_checkpoint=ckpt)
    assert t2.state["global_step"] == state["global_step"] + 2
