"""ONNX export seam (ref: python/paddle/onnx/export.py → paddle2onnx).

The paddle2onnx converter and the onnx package are not in this
environment (zero-egress build); the durable serialization path here is
`paddle_tpu.static.save_inference_model` (jax.export / StableHLO), which
plays the same deployment role. `export` raises with that pointer unless
an `onnx` module is importable, in which case a minimal converter would
be pluggable via `register_exporter`."""

from __future__ import annotations

__all__ = ["export"]

_exporter = None


def register_exporter(fn) -> None:
    global _exporter
    _exporter = fn


def export(layer, path: str, input_spec=None, opset_version: int = 9,
           **configs):
    if _exporter is not None:
        return _exporter(layer, path, input_spec=input_spec,
                         opset_version=opset_version, **configs)
    raise NotImplementedError(
        "ONNX export requires the paddle2onnx/onnx packages (absent in "
        "this build). Use paddle_tpu.static.save_inference_model "
        "(StableHLO via jax.export) for deployable serialization, or "
        "register_exporter() to plug a converter.")
