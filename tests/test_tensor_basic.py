"""Core Tensor semantics: creation, math, manipulation, async host transfer."""

import numpy as np
import pytest

import paddle_tpu as paddle


def test_to_tensor_roundtrip():
    x = paddle.to_tensor([[1.0, 2.0], [3.0, 4.0]])
    assert x.shape == [2, 2]
    assert str(x.dtype) == "float32"
    np.testing.assert_allclose(x.numpy(), [[1, 2], [3, 4]])


def test_default_dtype():
    paddle.set_default_dtype("float32")
    assert paddle.to_tensor(1.5).dtype == np.float32
    assert paddle.to_tensor(np.array([1.0, 2.0])).dtype == np.float32  # f64 demote
    assert paddle.to_tensor([1, 2]).dtype in (np.int32, np.int64)


def test_arith_dunder_and_broadcast():
    x = paddle.to_tensor([[1.0, 2.0], [3.0, 4.0]])
    y = paddle.to_tensor([10.0, 20.0])
    z = x * 2 + y / 2 - 1
    np.testing.assert_allclose(z.numpy(), np.array([[1, 2], [3, 4]]) * 2
                               + np.array([10, 20]) / 2 - 1)
    np.testing.assert_allclose((x @ x.T).numpy(),
                               np.array([[1., 2], [3, 4]]) @ np.array([[1., 3], [2, 4]]))


def test_reductions_and_axis():
    x = paddle.arange(24, dtype="float32").reshape([2, 3, 4])
    np.testing.assert_allclose(x.sum(axis=[1, 2]).numpy(),
                               np.arange(24, dtype=np.float32).reshape(2, 3, 4).sum((1, 2)))
    assert x.mean().item() == pytest.approx(11.5)
    assert x.max(axis=0, keepdim=True).shape == [1, 3, 4]


def test_manipulation():
    x = paddle.arange(12).reshape([3, 4])
    assert paddle.transpose(x, [1, 0]).shape == [4, 3]
    parts = paddle.split(x, 2, axis=1)
    assert [p.shape for p in parts] == [[3, 2], [3, 2]]
    parts = paddle.split(x, [1, -1], axis=0)
    assert [p.shape for p in parts] == [[1, 4], [2, 4]]
    s = paddle.stack([x, x], axis=0)
    assert s.shape == [2, 3, 4]
    c = paddle.concat([x, x], axis=1)
    assert c.shape == [3, 8]
    assert paddle.flatten(x).shape == [12]
    assert x.unsqueeze([0, 2]).shape == [1, 3, 1, 4]


def test_indexing_and_setitem():
    x = paddle.arange(12, dtype="float32").reshape([3, 4])
    np.testing.assert_allclose(x[1].numpy(), [4, 5, 6, 7])
    np.testing.assert_allclose(x[:, 1:3].numpy(),
                               np.arange(12.).reshape(3, 4)[:, 1:3])
    x[0, 0] = 100.0
    assert x.numpy()[0, 0] == 100.0
    idx = paddle.to_tensor([0, 2])
    g = paddle.gather(x, idx, axis=0)
    assert g.shape == [2, 4]


def test_gather_scatter_take_along():
    x = paddle.to_tensor([[1.0, 2.0], [3.0, 4.0], [5.0, 6.0]])
    idx = paddle.to_tensor([[0], [1], [0]])
    t = paddle.take_along_axis(x, idx, axis=1)
    np.testing.assert_allclose(t.numpy(), [[1], [4], [5]])
    s = paddle.scatter(paddle.zeros([3, 2]), paddle.to_tensor([0, 2]),
                       paddle.ones([2, 2]))
    np.testing.assert_allclose(s.numpy(), [[1, 1], [0, 0], [1, 1]])


def test_where_and_compare():
    x = paddle.to_tensor([1.0, -2.0, 3.0])
    m = x > 0
    assert m.dtype == np.bool_
    w = paddle.where(m, x, paddle.zeros_like(x))
    np.testing.assert_allclose(w.numpy(), [1, 0, 3])


def test_dynamic_ops_eager_only():
    x = paddle.to_tensor([1.0, 0.0, 2.0])
    nz = paddle.nonzero(x)
    assert nz.shape == [2, 1]
    ms = paddle.masked_select(x, x > 0)
    np.testing.assert_allclose(ms.numpy(), [1, 2])


def test_sort_topk():
    x = paddle.to_tensor([[3.0, 1.0, 2.0], [9.0, 7.0, 8.0]])
    v, i = paddle.topk(x, 2)
    np.testing.assert_allclose(v.numpy(), [[3, 2], [9, 8]])
    np.testing.assert_allclose(i.numpy(), [[0, 2], [0, 2]])
    s = paddle.sort(x, descending=True)
    np.testing.assert_allclose(s.numpy(), [[3, 2, 1], [9, 8, 7]])


def test_einsum_linalg():
    a = paddle.rand([4, 5])
    b = paddle.rand([5, 6])
    np.testing.assert_allclose(paddle.einsum("ij,jk->ik", a, b).numpy(),
                               a.numpy() @ b.numpy(), rtol=1e-5)
    m = paddle.to_tensor([[4.0, 1.0], [1.0, 3.0]])
    l = paddle.linalg.cholesky(m)
    np.testing.assert_allclose((l @ l.T).numpy(), m.numpy(), rtol=1e-5)


def test_cast_astype():
    x = paddle.to_tensor([1.5, 2.5])
    y = x.astype("int32")
    assert y.dtype == np.int32
    z = x.astype(paddle.bfloat16)
    assert str(z.dtype) == "bfloat16"


def test_random_determinism():
    paddle.seed(42)
    a = paddle.rand([4])
    paddle.seed(42)
    b = paddle.rand([4])
    np.testing.assert_allclose(a.numpy(), b.numpy())
    c = paddle.rand([4])
    assert not np.allclose(b.numpy(), c.numpy())


def test_save_load(tmp_path):
    sd = {"w": paddle.rand([3, 3]), "step": 7,
          "nested": [paddle.ones([2]), "tag"]}
    p = str(tmp_path / "model.pdparams")
    paddle.save(sd, p)
    back = paddle.load(p)
    np.testing.assert_allclose(back["w"].numpy(), sd["w"].numpy())
    assert back["step"] == 7
    np.testing.assert_allclose(back["nested"][0].numpy(), [1, 1])


def test_bf16_save_load(tmp_path):
    x = paddle.ones([4], dtype="bfloat16")
    p = str(tmp_path / "bf16.pdparams")
    paddle.save({"x": x}, p)
    back = paddle.load(p)
    assert str(back["x"].dtype) == "bfloat16"
