"""paddle.Model high-level API (ref: python/paddle/hapi/ — SURVEY §2.2).

Model.prepare/fit/evaluate/predict with a callback system (checkpoint,
early-stop, LR scheduling, logging), plus `summary` and a FLOPs counter.
"""

from .model import Model  # noqa: F401
from .callbacks import (Callback, EarlyStopping, LRScheduler,  # noqa: F401
                        ModelCheckpoint, ProgBarLogger)
from .summary import flops, summary  # noqa: F401
