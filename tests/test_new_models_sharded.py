"""New model families under the hybrid mesh (SURVEY §4.2 build lesson:
N-way-sharded step == single-device step on the simulated 8-device CPU
mesh). Covers GPT / Qwen2 / DeepSeek-V2-MLA — the TP specs these models
attach must actually materialize and train under fleet.distributed_model."""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.distributed import fleet


def _strategy(dp=4, mp=2):
    s = fleet.DistributedStrategy()
    s.hybrid_configs = {"dp_degree": dp, "mp_degree": mp, "pp_degree": 1,
                        "sharding_degree": 1, "sep_degree": 1}
    return s


def _train_two_steps(model_fn, ids_np):
    from paddle_tpu.optimizer import AdamW
    model = model_fn()
    model.train()
    opt = AdamW(learning_rate=1e-2, parameters=model.parameters())
    losses = []
    for _ in range(2):
        loss, _ = model(paddle.to_tensor(ids_np), labels=paddle.to_tensor(ids_np))
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss.numpy()))
    return losses


@pytest.mark.parametrize("family", ["gpt", "qwen2", "deepseek"])
def test_tp_sharded_step_matches_single_device(family):
    if family == "gpt":
        from paddle_tpu.models.gpt import GPTForCausalLM, gpt_tiny_config
        cfg = gpt_tiny_config(num_hidden_layers=1)
        model_fn = lambda: GPTForCausalLM(cfg)  # noqa: E731
        V = cfg.vocab_size
    elif family == "qwen2":
        from paddle_tpu.models.qwen2 import (Qwen2ForCausalLM,
                                             qwen2_tiny_config)
        cfg = qwen2_tiny_config(num_hidden_layers=1)
        model_fn = lambda: Qwen2ForCausalLM(cfg)  # noqa: E731
        V = cfg.vocab_size
    else:
        from paddle_tpu.models.deepseek import (DeepSeekV2ForCausalLM,
                                                deepseek_v2_tiny_config)
        cfg = deepseek_v2_tiny_config(num_hidden_layers=1,
                                      first_k_dense_replace=1)
        model_fn = lambda: DeepSeekV2ForCausalLM(cfg)  # noqa: E731
        V = cfg.vocab_size

    rng = np.random.RandomState(0)
    ids = rng.randint(0, V, (4, 16)).astype(np.int32)

    paddle.seed(0)
    ref = _train_two_steps(model_fn, ids)

    paddle.seed(0)
    fleet.init(is_collective=True, strategy=_strategy())
    from paddle_tpu.optimizer import AdamW
    model = model_fn()
    model = fleet.distributed_model(model)
    model.train()
    opt = AdamW(learning_rate=1e-2, parameters=model.parameters())
    got = []
    for _ in range(2):
        loss, _ = model(paddle.to_tensor(ids), labels=paddle.to_tensor(ids))
        loss.backward()
        opt.step()
        opt.clear_grad()
        got.append(float(loss.numpy()))

    np.testing.assert_allclose(got, ref, rtol=2e-3, atol=2e-4)
