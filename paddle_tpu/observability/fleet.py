"""paddle_tpu.observability.fleet — the fleet observability plane
(ISSUE 16): cross-replica trace stitching, metric federation, and the
fleet-scope SLO histograms the `FleetRouter` measures.

PR 15 made serving a fleet (prefill/decode roles, `KVPageHandoff`,
`FleetRouter`); this module makes the fleet observable as ONE system:

  - **Trace stitching** — `stitch_chrome_trace` joins per-replica
    `TraceRecorder` rings into one chrome trace with one process lane
    (pid) per replica. A request that travelled routed → prefill →
    handoff export → import → decode renders as ONE logical timeline
    whose lifetime spans sit in the lane of the replica that ran each
    leg, tied together by a flow/arrow event (`ph:"s"`/`ph:"f"`) from
    `handoff_export` to `handoff_import`. Lane attribution comes from
    the ``replica=`` meta the recorder attaches to every stamp taken
    under `TraceRecorder.set_replica_context` (the engine sets it at
    the top of every stamping method).
  - **Fleet SLO histograms** — ``serving.fleet.ttft_seconds`` /
    ``e2e_seconds`` / ``handoff_latency_seconds`` observed by the
    ROUTER (submit → first token / completion seen from outside the
    replicas, the latency a client of the fleet actually experiences)
    plus ``serving.fleet.phase_seconds{phase=router_queue|prefill|
    handoff|decode}``, the per-phase attribution of each finished
    request's e2e derived from its stitched trace.
  - **Metric federation** — `federate` merges per-replica registry
    snapshots (`ServingEngine.scrape()`) into one fleet rollup
    registry: counters summed across replicas per label key, gauges
    and histograms re-labeled with ``replica=<name>``. The rollup is a
    plain `Registry`, so the existing exporters (`to_prometheus`,
    `snapshot`) and `slo_summary` work on it unchanged —
    `FleetRouter.scrape()` is the entry point.

Overhead contract (same as the metrics/tracing layers): every observe_*
entry point checks the cached ``FLAGS_metrics`` flag object FIRST, and
the stitcher only reads recorder state that `FLAGS_request_tracing`
gates at stamp time — gated at <5% disabled overhead alongside the
other paths in tests/test_observability.py::TestOverhead.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Mapping, Optional, Sequence, Tuple, Union

from .. import flags as _flags
from . import DEFAULT_BUCKETS, Registry, registry
from .tracing import RequestTrace, TraceRecorder, slo_summary

__all__ = ["FLEET_SLO_METRICS", "FLEET_PHASES", "observe_ttft",
           "observe_e2e", "observe_handoff", "observe_phases",
           "phase_attribution", "federate", "stitch_chrome_trace",
           "fleet_slo_summary"]

_MFLAG = _flags._registry["FLAGS_metrics"]

#: router-measured fleet-scope SLO histograms (unlabeled; slo_summary
#: renders the standard table over them)
FLEET_SLO_METRICS: Tuple[str, ...] = (
    "serving.fleet.ttft_seconds",
    "serving.fleet.e2e_seconds",
    "serving.fleet.handoff_latency_seconds",
)
#: per-phase attribution label values on serving.fleet.phase_seconds
FLEET_PHASES: Tuple[str, ...] = ("router_queue", "prefill", "handoff",
                                 "decode")

_H_TTFT = registry().histogram(
    "serving.fleet.ttft_seconds",
    "router submit -> first token, fleet-wide (measured by the router, "
    "drains and handoffs included)", buckets=DEFAULT_BUCKETS)
_H_E2E = registry().histogram(
    "serving.fleet.e2e_seconds",
    "router submit -> completed result, fleet-wide", buckets=DEFAULT_BUCKETS)
_H_HANDOFF = registry().histogram(
    "serving.fleet.handoff_latency_seconds",
    "KV-page handoff export -> successful import, router-measured",
    buckets=DEFAULT_BUCKETS)
_H_PHASE = registry().histogram(
    "serving.fleet.phase_seconds",
    "per-request e2e attribution by phase (router queue / prefill / "
    "handoff / decode), derived from the stitched trace",
    labels=("phase",), buckets=DEFAULT_BUCKETS)


def observe_ttft(seconds: float) -> None:
    if not _MFLAG.value:
        return
    _H_TTFT.observe(seconds)


def observe_e2e(seconds: float) -> None:
    if not _MFLAG.value:
        return
    _H_E2E.observe(seconds)


def observe_handoff(seconds: float) -> None:
    if not _MFLAG.value:
        return
    _H_HANDOFF.observe(seconds)


def phase_attribution(tr: Optional[RequestTrace]) -> Dict[str, float]:
    """Split one request's wall time into the four fleet phases from its
    (stitched) timeline: router_queue = enqueue → admit, prefill =
    admit → handoff_ready (or first token when colocated), handoff =
    Σ(handoff_export → next handoff_import), decode = first token →
    last event minus the handoff windows. Phases whose events are
    missing are omitted (pure derivation — no flag, no mutation)."""
    if tr is None:
        return {}
    evs = tr.timeline()
    if not evs:
        return {}
    out: Dict[str, float] = {}
    enq, adm = tr.first("enqueue"), tr.first("admit")
    tok1 = tr.first("token")
    if enq is not None and adm is not None and adm.t_us >= enq.t_us:
        out["router_queue"] = (adm.t_us - enq.t_us) / 1e6
    pf_end = tr.first("handoff_ready") or tok1
    if adm is not None and pf_end is not None \
            and pf_end.t_us >= adm.t_us:
        out["prefill"] = (pf_end.t_us - adm.t_us) / 1e6
    handoff = 0.0
    t_exp: Optional[int] = None
    for e in evs:
        if e.name == "handoff_export":
            t_exp = e.t_us
        elif e.name == "handoff_import" and t_exp is not None:
            handoff += (e.t_us - t_exp) / 1e6
            t_exp = None
    if handoff > 0.0:
        out["handoff"] = handoff
    if tok1 is not None and evs[-1].t_us >= tok1.t_us:
        out["decode"] = max(
            (evs[-1].t_us - tok1.t_us) / 1e6 - handoff, 0.0)
    return out


def observe_phases(tr: Optional[RequestTrace]) -> None:
    """Observe a finished request's phase attribution into
    ``serving.fleet.phase_seconds{phase=...}`` (router calls this when a
    result is collected; no-op with metrics off or no trace)."""
    if not _MFLAG.value:
        return
    for phase, seconds in phase_attribution(tr).items():
        _H_PHASE.labels(phase=phase).observe(seconds)


def fleet_slo_summary(reg=None, qs: Sequence[float] = (50, 90, 99)
                      ) -> Dict[str, Any]:
    """{metric: {count, mean, p50, p90, p99}} over the fleet SLO
    histograms (default registry, or a `FleetRouter.scrape()` rollup)."""
    return slo_summary(FLEET_SLO_METRICS, reg=reg, qs=qs)


# ---------------------------------------------------------------------------
# metric federation
# ---------------------------------------------------------------------------

def federate(snapshots: Mapping[str, Mapping[str, Any]]) -> Registry:
    """Merge per-replica registry snapshots ({replica_name:
    reg.snapshot()}) into one fleet rollup `Registry`:

      - **counters** are summed across replicas per label key (the fleet
        total — the per-replica split, when it matters, is already a
        ``replica`` label on the source family);
      - **gauges and histograms** gain a leading ``replica`` label, one
        child per (replica, original labels) — summing a queue-depth
        gauge or a latency histogram across replicas would destroy the
        signal operators page on. Families that already carry a
        ``replica`` label keep their label set (the value is overridden
        with the scraping replica's name).

    The result is a plain registry: `obs.to_prometheus(rollup)`,
    `rollup.snapshot()` and `tracing.slo_summary(..., reg=rollup)` all
    work unchanged. Pure transformation of its inputs — flag gating
    lives at the scrape() entry points that produce them."""
    reg = Registry()
    for replica in sorted(snapshots):
        snap = snapshots[replica]
        for name in sorted(snap):
            e = snap[name]
            kind, labels = e["kind"], tuple(e["labels"])
            if kind == "counter":
                m = reg.counter(name, e.get("help", ""), labels)
                for s in e["series"]:
                    tgt = m.labels(**s["labels"]) if labels else m
                    tgt._value += float(s["value"])
                continue
            relabel = "replica" not in labels
            out_labels = (("replica",) + labels) if relabel else labels
            if kind == "gauge":
                m = reg.gauge(name, e.get("help", ""), out_labels)
                for s in e["series"]:
                    lbl = dict(s["labels"])
                    lbl["replica"] = replica
                    m.labels(**lbl)._value = float(s["value"])
            elif kind == "histogram":
                m = reg.histogram(name, e.get("help", ""), out_labels,
                                  buckets=e["buckets"])
                for s in e["series"]:
                    lbl = dict(s["labels"])
                    lbl["replica"] = replica
                    tgt = m.labels(**lbl)
                    counts = list(s["counts"])
                    tgt._counts = [a + b for a, b
                                   in zip(tgt._counts, counts)] \
                        if tgt._count else counts
                    tgt._sum += float(s["sum"])
                    tgt._count += int(s["count"])
            else:
                raise ValueError(f"unknown metric kind {kind!r} "
                                 f"for {name!r}")
    return reg


# ---------------------------------------------------------------------------
# cross-replica trace stitching
# ---------------------------------------------------------------------------

def _collect_traces(recorders, include_live: bool
                    ) -> List[Tuple[str, RequestTrace]]:
    if isinstance(recorders, TraceRecorder):
        recorders = {"fleet": recorders}
    seen: set = set()
    out: List[Tuple[str, RequestTrace]] = []
    for rec_name in recorders:
        rec = recorders[rec_name]
        traces = rec.finished() + (rec.live() if include_live else [])
        for tr in traces:
            if id(tr) in seen:     # one recorder shared by N replicas
                continue
            seen.add(id(tr))
            out.append((rec_name, tr))
    return out


def _event_lanes(tr: RequestTrace, fallback: str) -> List[str]:
    """Per-event lane names: the stamp's ``replica`` meta, carried
    forward over untagged events; events before the first tagged one
    back-fill from it (the enqueue raced the engine setting its
    context). Fully untagged traces stay in the `fallback` lane."""
    evs = tr.timeline()
    lanes: List[Optional[str]] = []
    cur: Optional[str] = None
    for e in evs:
        tag = (e.meta or {}).get("replica")
        if tag:
            cur = str(tag)
        lanes.append(cur)
    first = next((x for x in lanes if x is not None), None)
    return [x if x is not None else (first or fallback) for x in lanes]


def stitch_chrome_trace(path: str,
                        recorders: Union[TraceRecorder,
                                         Mapping[str, TraceRecorder],
                                         None] = None,
                        include_live: bool = True) -> int:
    """Join per-replica `TraceRecorder` rings into ONE chrome trace with
    one process lane per replica.

    `recorders` maps replica/recorder name → `TraceRecorder`; an
    in-process fleet (tier-1) passes the shared singleton (or nothing —
    the default recorder is used) and lanes come entirely from the
    per-stamp ``replica=`` meta. Each request renders as:

      - one lifetime span (``<kind>:<id>[span=<span_id>]``) per
        contiguous run of events on the same replica, in that replica's
        pid lane, all sharing the request's span id;
      - an instant event per stamp, in the lane the stamp was taken on;
      - a flow event (``ph:"s"`` at ``handoff_export`` →
        ``ph:"f"``/``bp:"e"`` at ``handoff_import``) drawing the
        arrow across the two lanes for every handoff the request paid.

    Counter tracks from every recorder land in a shared ``fleet`` lane
    (pid 0). Returns the event count; the file opens in Perfetto."""
    if recorders is None:
        from .tracing import recorder as _default
        recorders = _default()
    pairs = _collect_traces(recorders, include_live)
    # lane -> pid, assigned in first-appearance-then-sorted order so the
    # output is deterministic for seeded runs
    lane_events: Dict[str, List[Tuple[RequestTrace, List[int]]]] = {}
    per_trace: List[Tuple[RequestTrace, List[str]]] = []
    for rec_name, tr in pairs:
        if not tr.timeline():
            continue
        lanes = _event_lanes(tr, rec_name)
        per_trace.append((tr, lanes))
    lane_names = sorted({ln for _, lanes in per_trace for ln in lanes})
    pid_of = {ln: i + 1 for i, ln in enumerate(lane_names)}
    events: List[Dict[str, Any]] = []
    for ln in lane_names:
        events.append({"ph": "M", "name": "process_name",
                       "pid": pid_of[ln],
                       "args": {"name": f"replica:{ln}"}})
    events.append({"ph": "M", "name": "process_name", "pid": 0,
                   "args": {"name": "fleet"}})
    # one tid per request within each lane, stable across lanes so the
    # same request sits at the same row index in every replica's lane
    tid_of: Dict[Any, int] = {}
    for tr, _ in per_trace:
        tid_of.setdefault(tr.request_id, len(tid_of) + 1)
    n_flows = 0
    for tr, lanes in per_trace:
        evs = tr.timeline()
        tid = tid_of[tr.request_id]
        args = {"span_id": tr.span_id, "outcome": tr.outcome}
        args.update(tr.meta)
        # contiguous same-lane segments -> lifetime spans per lane
        seg_start = 0
        for i in range(1, len(evs) + 1):
            if i < len(evs) and lanes[i] == lanes[seg_start]:
                continue
            seg = evs[seg_start:i]
            pid = pid_of[lanes[seg_start]]
            events.append({
                "name": f"{tr.kind}:{tr.request_id}"
                        f"[span={tr.span_id}]",
                "ph": "X", "pid": pid, "tid": tid,
                "ts": seg[0].t_us,
                "dur": max(seg[-1].t_us - seg[0].t_us, 1),
                "cat": tr.kind, "args": dict(args)})
            seg_start = i
        for e, ln in zip(evs, lanes):
            rec = {"name": e.name, "ph": "i", "pid": pid_of[ln],
                   "tid": tid, "ts": e.t_us, "s": "t", "cat": "event"}
            if e.meta:
                rec["args"] = dict(e.meta)
            events.append(rec)
        # handoff flow arrows: export on one lane -> import on the next
        pending: Optional[Tuple[int, str]] = None
        for e, ln in zip(evs, lanes):
            if e.name == "handoff_export":
                pending = (e.t_us, ln)
            elif e.name == "handoff_import" and pending is not None:
                n_flows += 1
                fid = f"handoff:{tr.request_id}:{n_flows}"
                t_exp, ln_exp = pending
                events.append({"name": "kv_handoff", "ph": "s",
                               "id": fid, "pid": pid_of[ln_exp],
                               "tid": tid, "ts": t_exp,
                               "cat": "handoff"})
                events.append({"name": "kv_handoff", "ph": "f",
                               "bp": "e", "id": fid, "pid": pid_of[ln],
                               "tid": tid, "ts": e.t_us,
                               "cat": "handoff"})
                pending = None
    if isinstance(recorders, TraceRecorder):
        recorders = {"fleet": recorders}
    seen_rec: set = set()
    for rec_name in recorders:
        rec = recorders[rec_name]
        if id(rec) in seen_rec:
            continue
        seen_rec.add(id(rec))
        for name, series in sorted(rec.counters().items()):
            for t, v in series:
                events.append({"name": name, "ph": "C", "pid": 0,
                               "ts": t, "cat": "counter",
                               "args": {"value": v}})
    parent = os.path.dirname(os.path.abspath(path))
    os.makedirs(parent, exist_ok=True)
    with open(path, "w", encoding="utf-8") as f:
        json.dump({"traceEvents": events}, f)
    return len(events)
