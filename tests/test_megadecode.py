"""Mega-kernel decode back half (ops/pallas_megadecode.py, ISSUE 14).

Interpret-mode parity of the two fused launches against their XLA
oracles (ops/references.py) across the four family geometries — fp
(bitwise), int8 and packed int4 (split-contraction reordering only) —
plus the engine-level contracts: megadecode vs split-chain exactness,
the eligibility gate's TPU tiling rules, int4-MoE end-to-end, and the
costmodel launch accounting (5 launches/layer with both mega halves,
8 with either alone, 11 split; 2 pallas_calls after attention)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.generation import generate_cached
from paddle_tpu.ops.pallas_megadecode import (fused_ffn, fused_oproj_norm,
                                              megadecode_eligible)
from paddle_tpu.ops.quant import weight_quantize
from paddle_tpu.ops.references import (megadecode_ffn_reference,
                                       oproj_norm_reference)
from paddle_tpu.serving import ServingEngine


def _rand(rng, *shape):
    return jnp.asarray(rng.standard_normal(shape), jnp.float32)


def _q(rng, K, N, algo):
    w = _rand(rng, K, N)
    qw, s = weight_quantize(w, algo=algo)
    return qw, s.astype(jnp.float32)


class TestOprojNormParity:
    """fused_oproj_norm vs oproj_norm_reference (the registered
    oracle): o-proj + bias + residual + rms/layer norm, both outputs."""

    # fp parity is ULP-level, not bitwise: the kernel body is one jitted
    # computation where XLA emits FMAs; the eager oracle runs op-by-op
    def _check(self, got, want, exact=True, atol=1e-4):
        for g, w in zip(got, want):
            if exact:
                np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                           atol=2e-6, rtol=2e-6)
            else:
                np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                           atol=atol, rtol=1e-5)

    # family geometries: (T, Ko, H) — llama-like (Ko == H), mla-like
    # (Ko = nh*dv != H), plus a non-128-multiple lane width (interpret
    # mode carries no lane constraint; TPU gates via megadecode_eligible)
    @pytest.mark.parametrize("T,Ko,H", [(8, 64, 64), (8, 48, 40),
                                        (16, 136, 24)])
    def test_fp_rms_exact(self, T, Ko, H):
        rng = np.random.default_rng(0)
        o, x = _rand(rng, T, Ko), _rand(rng, T, H)
        w, nw = _rand(rng, Ko, H), _rand(rng, H)
        got = fused_oproj_norm(o, x, w, norm_weight=nw, eps=1e-6)
        want = oproj_norm_reference(o, x, w, norm_weight=nw, eps=1e-6)
        self._check(got, want)

    def test_fp_layer_norm_bias_exact(self):
        # gpt geometry: o-proj bias + layer norm with weight AND bias
        rng = np.random.default_rng(1)
        T, Ko, H = 8, 64, 32
        o, x = _rand(rng, T, Ko), _rand(rng, T, H)
        w = _rand(rng, Ko, H)
        b, nw, nb = (_rand(rng, H) for _ in range(3))
        got = fused_oproj_norm(o, x, w, bias=b, norm_weight=nw,
                               norm_bias=nb, eps=1e-5, norm="layer")
        want = oproj_norm_reference(o, x, w, bias=b, norm_weight=nw,
                                    norm_bias=nb, eps=1e-5, norm="layer")
        self._check(got, want)

    @pytest.mark.parametrize("algo", ["weight_only_int8",
                                      "weight_only_int4"])
    def test_quantized_tracks_oracle(self, algo):
        rng = np.random.default_rng(2)
        T, Ko, H = 8, 64, 32
        o, x = _rand(rng, T, Ko), _rand(rng, T, H)
        qw, s = _q(rng, Ko, H, algo)
        nw = _rand(rng, H)
        got = fused_oproj_norm(o, x, qw, s, norm_weight=nw, algo=algo)
        want = oproj_norm_reference(o, x, qw, s, norm_weight=nw,
                                    algo=algo)
        # int4 contracts even/odd planes separately — summation-order
        # noise only vs the whole-dequant oracle
        self._check(got, want, exact=False)

    def test_batched_shape_roundtrip(self):
        # engine calls with flat [T, ...]; the public API also accepts
        # the [1, T, H] cached-body layout and returns it unchanged
        rng = np.random.default_rng(3)
        o, x = _rand(rng, 1, 8, 64), _rand(rng, 1, 8, 32)
        w, nw = _rand(rng, 64, 32), _rand(rng, 32)
        xn, h = fused_oproj_norm(o, x, w, norm_weight=nw)
        assert xn.shape == x.shape and h.shape == x.shape

    def test_zero_sentinel_rows_finite(self):
        # idle ragged slots feed all-zero rows (trash-page attention
        # output on a zeroed residual): the norm's eps must keep both
        # outputs finite and equal to the oracle's
        rng = np.random.default_rng(4)
        T, Ko, H = 8, 64, 32
        o, x = _rand(rng, T, Ko), _rand(rng, T, H)
        o = o.at[3:].set(0.0)
        x = x.at[3:].set(0.0)
        w, nw = _rand(rng, Ko, H), _rand(rng, H)
        got = fused_oproj_norm(o, x, w, norm_weight=nw)
        want = oproj_norm_reference(o, x, w, norm_weight=nw)
        assert all(bool(jnp.isfinite(g).all()) for g in got)
        self._check(got, want)

    def test_row_count_not_multiple_of_block(self):
        # T=5 falls through the whole block ladder to bt=1
        rng = np.random.default_rng(5)
        o, x = _rand(rng, 5, 16), _rand(rng, 5, 8)
        w, nw = _rand(rng, 16, 8), _rand(rng, 8)
        self._check(fused_oproj_norm(o, x, w, norm_weight=nw),
                    oproj_norm_reference(o, x, w, norm_weight=nw))


class TestFfnParity:
    """fused_ffn vs megadecode_ffn_reference: gate/up + activation +
    down-proj + residual in one launch."""

    # same ULP-level bar as TestOprojNormParity (FMA fusion drift only)
    def _check(self, got, want, exact=True, atol=1e-4):
        if exact:
            np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                       atol=2e-6, rtol=2e-6)
        else:
            np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                       atol=atol, rtol=1e-5)

    # llama/mla swiglu geometry plus a non-128-multiple (even) ffn dim
    @pytest.mark.parametrize("T,H,I", [(8, 32, 64), (8, 40, 136)])
    def test_swiglu_fp_exact(self, T, H, I):
        rng = np.random.default_rng(10)
        h, x = _rand(rng, T, H), _rand(rng, T, H)
        wg, wu, wd = (_rand(rng, H, I), _rand(rng, H, I),
                      _rand(rng, I, H))
        got = fused_ffn(h, x, wg, None, wu, None, wd, None)
        want = megadecode_ffn_reference(h, x, wg, None, wu, None,
                                        wd, None)
        self._check(got, want)

    def test_gelu_bias_fp_exact(self):
        # gpt geometry: gelu(h @ wi + bi) @ wf + bf, both biases live
        rng = np.random.default_rng(11)
        T, H, I = 8, 32, 64
        h, x = _rand(rng, T, H), _rand(rng, T, H)
        wi, wf = _rand(rng, H, I), _rand(rng, I, H)
        bi, bf = _rand(rng, I), _rand(rng, H)
        got = fused_ffn(h, x, wi, None, None, None, wf, None, bi, bf,
                        act="gelu")
        want = megadecode_ffn_reference(h, x, wi, None, None, None,
                                        wf, None, bi, bf, act="gelu")
        self._check(got, want)

    @pytest.mark.parametrize("algo", ["weight_only_int8",
                                      "weight_only_int4"])
    def test_quantized_swiglu_tracks_oracle(self, algo):
        rng = np.random.default_rng(12)
        T, H, I = 8, 32, 64
        h, x = _rand(rng, T, H), _rand(rng, T, H)
        qg, sg = _q(rng, H, I, algo)
        qu, su = _q(rng, H, I, algo)
        qd, sd = _q(rng, I, H, algo)
        got = fused_ffn(h, x, qg, sg, qu, su, qd, sd, algo=algo)
        want = megadecode_ffn_reference(h, x, qg, sg, qu, su, qd, sd,
                                        algo=algo)
        self._check(got, want, exact=False)

    def test_int4_non_128_multiple_even_dims(self):
        # the packed layouts only need EVEN contraction dims off-TPU;
        # I=136 exercises the in-kernel scratch split at a non-128 lane
        rng = np.random.default_rng(13)
        T, H, I = 8, 24, 136
        h, x = _rand(rng, T, H), _rand(rng, T, H)
        qg, sg = _q(rng, H, I, "weight_only_int4")
        qu, su = _q(rng, H, I, "weight_only_int4")
        qd, sd = _q(rng, I, H, "weight_only_int4")
        got = fused_ffn(h, x, qg, sg, qu, su, qd, sd,
                        algo="weight_only_int4")
        want = megadecode_ffn_reference(h, x, qg, sg, qu, su, qd, sd,
                                        algo="weight_only_int4")
        self._check(got, want, exact=False)

    def test_int4_gelu_unsupported(self):
        rng = np.random.default_rng(14)
        h, x = _rand(rng, 8, 16), _rand(rng, 8, 16)
        qg, sg = _q(rng, 16, 32, "weight_only_int4")
        qd, sd = _q(rng, 32, 16, "weight_only_int4")
        with pytest.raises(NotImplementedError, match="swiglu"):
            fused_ffn(h, x, qg, sg, None, None, qd, sd,
                      algo="weight_only_int4", act="gelu")


class TestEligibility:
    """megadecode_eligible: always True in interpret mode; on TPU the
    128-lane / even-dim / VMEM-budget rules decide the fallback."""

    def test_interpret_mode_always_eligible(self):
        assert megadecode_eligible(24, 136, 40)

    def test_tpu_rules(self, monkeypatch):
        import paddle_tpu.ops.pallas_megadecode as md
        monkeypatch.setattr(md, "_interpret", lambda: False)
        # the llama3_8b 8-way shard geometry (SERVING_BENCH) tiles
        assert md.megadecode_eligible(512, 1792, 512)
        assert md.megadecode_eligible(512, 1792, 512, int4=True)
        # non-128 lane dims fall back
        assert not md.megadecode_eligible(520, 1792, 512)
        assert not md.megadecode_eligible(512, 1800, 512)
        assert not md.megadecode_eligible(512, 1792, 520)
        # unsharded llama3-8B blows the VMEM weight budget
        assert not md.megadecode_eligible(4096, 14336, 4096)


class TestEngineMegadecode:
    """Engine wiring: default-on fused back half, split-chain fallback
    parity, int4-MoE end-to-end, launch accounting."""

    @pytest.fixture(scope="class")
    def model(self):
        from paddle_tpu.models.llama import (LlamaForCausalLM,
                                             llama_tiny_config)
        paddle.seed(0)
        m = LlamaForCausalLM(llama_tiny_config(num_hidden_layers=2))
        m.eval()
        return m

    def _run(self, model, prompts, max_new=4, **kw):
        eng = ServingEngine(model, max_slots=2, page_size=4,
                            prefill_chunk=4, **kw)
        for i, p in enumerate(prompts):
            eng.add_request(p, max_new_tokens=max_new, request_id=i)
        return eng.run_to_completion(), eng

    def test_default_on_and_back_half_launches(self, model):
        eng = ServingEngine(model, max_slots=2, page_size=4)
        assert eng.megadecode
        assert eng.back_half_launches == 2
        off = ServingEngine(model, max_slots=2, page_size=4,
                            megadecode=False)
        assert not off.megadecode
        assert off.back_half_launches == 6

    def test_megadecode_matches_split_chain(self, model):
        V = model.config.vocab_size
        rng = np.random.RandomState(21)
        prompts = [rng.randint(0, V, rng.randint(3, 9)).astype(np.int32)
                   for _ in range(3)]
        on, e1 = self._run(model, prompts)
        off, e2 = self._run(model, prompts, megadecode=False)
        assert e1.megadecode and not e2.megadecode
        assert set(on) == set(off)
        for i in on:
            np.testing.assert_array_equal(on[i], off[i])
        # and both match solo generate_cached (greedy exactness)
        for i, p in enumerate(prompts):
            want, _ = generate_cached(model, paddle.to_tensor(p[None]),
                                      max_new_tokens=4,
                                      decode_strategy="greedy_search")
            np.testing.assert_array_equal(on[i], want.numpy()[0])

    def test_moe_int4_seeded_trace(self):
        # ISSUE 14 tentpole tail: int4 end-to-end through the fused
        # back half INCLUDING the 3-D packed expert stacks — engine
        # greedy tokens equal the solo int4 run exactly
        from paddle_tpu.models.moe_llm import (MoEForCausalLM,
                                               qwen2_moe_tiny_config)
        paddle.seed(0)
        c = qwen2_moe_tiny_config(moe_dropless=True,
                                  first_k_dense_replace=1,
                                  max_position_embeddings=64)
        m = MoEForCausalLM(c)
        m.eval()
        rng = np.random.RandomState(22)
        prompts = [rng.randint(0, c.vocab_size, rng.randint(3, 9))
                   .astype(np.int32) for _ in range(3)]
        out, eng = self._run(m, prompts, weight_only_quant="int4")
        assert eng.megadecode
        for i, p in enumerate(prompts):
            want, _ = generate_cached(m, paddle.to_tensor(p[None]),
                                      max_new_tokens=4,
                                      decode_strategy="greedy_search",
                                      weight_only_quant="int4")
            np.testing.assert_array_equal(out[i], want.numpy()[0])

    def test_gpt_megadecode_matches_split(self):
        from paddle_tpu.models.gpt import GPTForCausalLM, gpt_tiny_config
        paddle.seed(0)
        c = gpt_tiny_config(max_position_embeddings=64)
        m = GPTForCausalLM(c)
        m.eval()
        rng = np.random.RandomState(23)
        prompts = [rng.randint(0, c.vocab_size, rng.randint(3, 7))
                   .astype(np.int32) for _ in range(2)]
        on, e1 = self._run(m, prompts)
        off, e2 = self._run(m, prompts, megadecode=False)
        assert e1.megadecode and not e2.megadecode
        for i in on:
            np.testing.assert_array_equal(on[i], off[i])


class TestLaunchAccounting:
    """costmodel.decode_layer_kernels fused modes: 5 launches per layer
    with both mega halves (the ISSUE 20 default), 8 with either half
    alone, 11 for the fully split chain — and the dual-ledger claim:
    the fused path's modeled HBM bytes are strictly below the split
    chain's at identical weights."""

    KW = dict(batch=8, context=256, hidden=512, heads=4, kv_heads=1,
              head_dim=128, intermediate=1792, page_size=32,
              weight_bytes_per_layer=8_000_000)

    @staticmethod
    def _total_bytes(decomp):
        return sum(n * (c.bytes_read + c.bytes_written)
                   for n, c in decomp["kernels"].values())

    def test_launch_counts(self):
        from paddle_tpu.observability import costmodel as cm
        both = cm.decode_layer_kernels(**self.KW)
        back = cm.decode_layer_kernels(megafront=False, **self.KW)
        front = cm.decode_layer_kernels(megadecode=False, **self.KW)
        old = cm.decode_layer_kernels(megadecode=False, megafront=False,
                                      **self.KW)
        # both halves: rms 1 + qkv_rope_append 1 + ragged 1 +
        # oproj_norm 1 + ffn 1 = 5; back only: rms 1 + qkv 3 + rope 1
        # + ragged 1 + oproj_norm 1 + ffn 1 = 8; front only: rms 2 +
        # qkv_rope_append 1 + ragged 1 + swiglu 1 + three back mats =
        # 8; split chain: rms 2 + six projections + rope 1 + ragged 1
        # + swiglu 1 = 11
        assert both["launches_per_layer"] == 5
        assert back["launches_per_layer"] == 8
        assert front["launches_per_layer"] == 8
        assert old["launches_per_layer"] == 11
        fused = {k: n for k, (n, _) in both["kernels"].items()
                 if k in ("fused_qkv_rope_append", "fused_oproj_norm",
                          "fused_ffn")}
        assert fused == {"fused_qkv_rope_append": 1,
                         "fused_oproj_norm": 1, "fused_ffn": 1}
        assert "swiglu" not in both["kernels"]
        assert "fused_rope_append" not in both["kernels"]
        assert "fused_rope_append" in back["kernels"]

    def test_fused_path_removes_intermediate_bytes(self):
        from paddle_tpu.observability import costmodel as cm
        both = cm.decode_layer_kernels(**self.KW)
        back = cm.decode_layer_kernels(megafront=False, **self.KW)
        old = cm.decode_layer_kernels(megadecode=False, megafront=False,
                                      **self.KW)
        # same real weight total crosses in every mode (the fused slabs
        # are carved out of weight_bytes_per_layer, not double-counted);
        # everything saved is intermediate activation traffic
        assert self._total_bytes(both) < self._total_bytes(back)
        assert self._total_bytes(back) < self._total_bytes(old)

    def test_quant_algo_shrinks_fused_weight_read(self):
        from paddle_tpu.observability import costmodel as cm
        kw = dict(self.KW)
        fp = cm.decode_layer_kernels(**kw)
        i4 = cm.decode_layer_kernels(quant_algo="weight_only_int4", **kw)
        wf = fp["kernels"]["fused_ffn"][1].breakdown["weights"]
        w4 = i4["kernels"]["fused_ffn"][1].breakdown["weights"]
        assert w4 < wf / 3       # packed nibbles: ~quarter of bf16
