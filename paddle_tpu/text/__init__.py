"""Text/tokenizer utilities (ecosystem parity — SURVEY §2.4: "the build
needs a tokenizer-compatible data pipeline" for the BERT/ERNIE/Llama
configs; reference lives in PaddleNLP paddlenlp/transformers/*tokenizer*).

Native WordPiece (BERT/ERNIE family) and byte-level BPE skeleton (Llama
family loads real merges when files are available); both expose the
encode/decode + __call__ padding/truncation surface the Trainer consumes.
"""

from __future__ import annotations

import json
import os
import re
import unicodedata
from typing import Dict, List, Optional, Sequence

import numpy as np

__all__ = ["WordPieceTokenizer", "BasicTokenizer", "Vocab",
           "pad_sequences"]


class Vocab:
    def __init__(self, token_to_id: Dict[str, int]):
        self.token_to_id = dict(token_to_id)
        self.id_to_token = {i: t for t, i in self.token_to_id.items()}

    @classmethod
    def from_file(cls, path: str) -> "Vocab":
        tok2id = {}
        with open(path, encoding="utf-8") as f:
            for i, line in enumerate(f):
                tok2id[line.rstrip("\n")] = i
        return cls(tok2id)

    @classmethod
    def build(cls, texts: Sequence[str], max_size: int = 30000,
              specials=("[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]")):
        """Frequency vocab with wordpiece continuation pieces."""
        from collections import Counter
        counter = Counter()
        basic = BasicTokenizer()
        for t in texts:
            for w in basic.tokenize(t):
                counter[w] += 1
        tok2id = {s: i for i, s in enumerate(specials)}
        # whole words + char pieces
        chars = set()
        for w in counter:
            for ch in w.lstrip("#"):
                chars.add(ch)
        for w, _ in counter.most_common(max_size - len(tok2id)):
            if w not in tok2id:
                tok2id[w] = len(tok2id)
        for ch in sorted(chars):
            for piece in (ch, "##" + ch):
                if piece not in tok2id and len(tok2id) < max_size:
                    tok2id[piece] = len(tok2id)
        return cls(tok2id)

    def __len__(self):
        return len(self.token_to_id)

    def __getitem__(self, tok):
        return self.token_to_id[tok]

    def get(self, tok, default=None):
        return self.token_to_id.get(tok, default)


class BasicTokenizer:
    """Whitespace + punctuation split, lowercasing, accent stripping
    (BERT basic tokenizer semantics)."""

    def __init__(self, do_lower_case: bool = True):
        self.do_lower_case = do_lower_case

    def tokenize(self, text: str) -> List[str]:
        if self.do_lower_case:
            text = text.lower()
            text = unicodedata.normalize("NFD", text)
            text = "".join(c for c in text
                           if unicodedata.category(c) != "Mn")
        out = []
        for chunk in text.split():
            out.extend(t for t in re.split(r"([^\w]+)", chunk)
                       if t and not t.isspace())
        return out


class WordPieceTokenizer:
    """Greedy longest-match-first wordpiece (BERT/ERNIE tokenizer)."""

    def __init__(self, vocab: Vocab, unk_token: str = "[UNK]",
                 cls_token: str = "[CLS]", sep_token: str = "[SEP]",
                 pad_token: str = "[PAD]", mask_token: str = "[MASK]",
                 do_lower_case: bool = True,
                 max_input_chars_per_word: int = 100):
        self.vocab = vocab
        self.basic = BasicTokenizer(do_lower_case)
        self.unk_token = unk_token
        self.cls_token = cls_token
        self.sep_token = sep_token
        self.pad_token = pad_token
        self.mask_token = mask_token
        self.max_chars = max_input_chars_per_word

    @classmethod
    def from_pretrained(cls, path: str, **kw) -> "WordPieceTokenizer":
        vf = os.path.join(path, "vocab.txt") if os.path.isdir(path) else path
        return cls(Vocab.from_file(vf), **kw)

    # -- core ----------------------------------------------------------------
    def _wordpiece(self, word: str) -> List[str]:
        if len(word) > self.max_chars:
            return [self.unk_token]
        pieces, start = [], 0
        while start < len(word):
            end = len(word)
            cur = None
            while start < end:
                sub = word[start:end]
                if start > 0:
                    sub = "##" + sub
                if self.vocab.get(sub) is not None:
                    cur = sub
                    break
                end -= 1
            if cur is None:
                return [self.unk_token]
            pieces.append(cur)
            start = end
        return pieces

    def tokenize(self, text: str) -> List[str]:
        out = []
        for w in self.basic.tokenize(text):
            out.extend(self._wordpiece(w))
        return out

    def convert_tokens_to_ids(self, tokens: List[str]) -> List[int]:
        unk = self.vocab.get(self.unk_token, 0)
        return [self.vocab.get(t, unk) for t in tokens]

    def convert_ids_to_tokens(self, ids: List[int]) -> List[str]:
        return [self.vocab.id_to_token.get(int(i), self.unk_token)
                for i in ids]

    def encode(self, text: str, text_pair: Optional[str] = None,
               max_length: Optional[int] = None) -> Dict[str, List[int]]:
        toks = [self.cls_token] + self.tokenize(text) + [self.sep_token]
        type_ids = [0] * len(toks)
        if text_pair is not None:
            pair = self.tokenize(text_pair) + [self.sep_token]
            toks += pair
            type_ids += [1] * len(pair)
        if max_length is not None and len(toks) > max_length:
            toks = toks[:max_length - 1] + [self.sep_token]
            type_ids = type_ids[:max_length]
        ids = self.convert_tokens_to_ids(toks)
        return {"input_ids": ids, "token_type_ids": type_ids,
                "attention_mask": [1] * len(ids)}

    def decode(self, ids) -> str:
        toks = self.convert_ids_to_tokens(list(np.asarray(ids).tolist()))
        out = []
        for t in toks:
            if t in (self.cls_token, self.sep_token, self.pad_token):
                continue
            if t.startswith("##") and out:
                out[-1] += t[2:]
            else:
                out.append(t)
        return " ".join(out)

    def __call__(self, texts, text_pairs=None, max_length: int = 128,
                 padding: bool = True, truncation: bool = True,
                 return_attention_mask: bool = True):
        """Batched encode -> padded numpy arrays (Trainer feed format)."""
        if isinstance(texts, str):
            texts = [texts]
        pairs = text_pairs if text_pairs is not None else [None] * len(texts)
        encs = [self.encode(t, p, max_length if truncation else None)
                for t, p in zip(texts, pairs)]
        pad_id = self.vocab.get(self.pad_token, 0)
        L = max(len(e["input_ids"]) for e in encs)
        if padding:
            L = max_length if truncation else L
        out = {"input_ids": pad_sequences(
            [e["input_ids"] for e in encs], L, pad_id),
            "token_type_ids": pad_sequences(
                [e["token_type_ids"] for e in encs], L, 0)}
        if return_attention_mask:
            out["attention_mask"] = pad_sequences(
                [e["attention_mask"] for e in encs], L, 0)
        return out


def pad_sequences(seqs: Sequence[List[int]], length: int,
                  pad_value: int) -> np.ndarray:
    out = np.full((len(seqs), length), pad_value, np.int32)
    for i, s in enumerate(seqs):
        out[i, :min(len(s), length)] = s[:length]
    return out


# ---------------------------------------------------------------------------
# Byte-level BPE (GPT-2/Llama-family tokenizer; ref: PaddleNLP
# paddlenlp/transformers/gpt/tokenizer.py — GPTTokenizer's byte-level BPE)
# ---------------------------------------------------------------------------
def _bytes_to_unicode() -> Dict[int, str]:
    """GPT-2's reversible byte→printable-char table (avoids raw control
    chars in the vocab)."""
    bs = (list(range(ord("!"), ord("~") + 1))
          + list(range(ord("¡"), ord("¬") + 1))
          + list(range(ord("®"), ord("ÿ") + 1)))
    cs = bs[:]
    n = 0
    for b in range(256):
        if b not in bs:
            bs.append(b)
            cs.append(256 + n)
            n += 1
    return dict(zip(bs, [chr(c) for c in cs]))


def _gpt2_pretokenize_pattern():
    """GPT-2's pre-tokenizer: contractions, space-prefixed word/number
    runs, symbol runs, whitespace. The space ATTACHES to the following
    word (" world" is one piece) — required for pretrained vocab/merges
    compatibility. Uses the `regex` module's \\p classes when available
    (the reference pattern), else an ASCII-equivalent re fallback."""
    try:
        import regex as _rx
        return _rx.compile(
            r"'s|'t|'re|'ve|'m|'ll|'d| ?\p{L}+| ?\p{N}+"
            r"| ?[^\s\p{L}\p{N}]+|\s+(?!\S)|\s+")
    except ImportError:
        return re.compile(
            r"'s|'t|'re|'ve|'m|'ll|'d| ?[A-Za-z]+| ?[0-9]+"
            r"| ?[^\sA-Za-z0-9]+|\s+(?!\S)|\s+")


def train_bpe(corpus: Sequence[str], vocab_size: int,
              special_tokens: Sequence[str] = ("<|endoftext|>",)):
    """Learn byte-level BPE merges from a corpus (offline-trainable stand-in
    for loading pretrained merges.txt). Returns (vocab: Dict[str, int],
    merges: List[Tuple[str, str]])."""
    byte_enc = _bytes_to_unicode()
    words: Dict[tuple, int] = {}
    pat = _gpt2_pretokenize_pattern()
    for text in corpus:
        for piece in pat.findall(text):
            sym = tuple(byte_enc[b] for b in piece.encode("utf-8"))
            if sym:
                words[sym] = words.get(sym, 0) + 1
    vocab = {tok: i for i, tok in enumerate(special_tokens)}
    for ch in sorted(set(byte_enc.values())):
        vocab.setdefault(ch, len(vocab))
    merges: List[tuple] = []
    while len(vocab) < vocab_size:
        pairs: Dict[tuple, int] = {}
        for sym, cnt in words.items():
            for a, b in zip(sym, sym[1:]):
                pairs[(a, b)] = pairs.get((a, b), 0) + cnt
        if not pairs:
            break
        best = max(pairs, key=lambda p: (pairs[p], p))
        merged = best[0] + best[1]
        # a collision with an existing entry (two merge paths to the same
        # string) still records the merge RULE; only vocab growth is skipped
        if merged not in vocab:
            vocab[merged] = len(vocab)
        merges.append(best)
        new_words = {}
        for sym, cnt in words.items():
            out, i = [], 0
            while i < len(sym):
                if i + 1 < len(sym) and (sym[i], sym[i + 1]) == best:
                    out.append(merged)
                    i += 2
                else:
                    out.append(sym[i])
                    i += 1
            new_words[tuple(out)] = new_words.get(tuple(out), 0) + cnt
        words = new_words
    return vocab, merges


class BPETokenizer:
    """Byte-level BPE encode/decode (GPT/Llama tokenizer mechanism).

    Construct from (vocab, merges) — learned via train_bpe or loaded from
    pretrained vocab.json/merges.txt files via from_pretrained.
    """

    def __init__(self, vocab: Dict[str, int], merges,
                 unk_token: str = "<|endoftext|>",
                 eos_token: str = "<|endoftext|>", pad_token=None):
        self.vocab = dict(vocab)
        self.id_to_token = {i: t for t, i in self.vocab.items()}
        self.ranks = {tuple(m): i for i, m in enumerate(merges)}
        self.byte_enc = _bytes_to_unicode()
        self.byte_dec = {v: k for k, v in self.byte_enc.items()}
        self.unk_token = unk_token
        self.eos_token = eos_token
        self.pad_token = pad_token if pad_token is not None else eos_token
        self._cache: Dict[str, List[str]] = {}
        self._id_cache: Dict[str, List[int]] = {}
        self._pat = _gpt2_pretokenize_pattern()
        # the merge loop runs in C++ when the native runtime is built
        # (ref: PaddleNLP fast_tokenizer); falls back to the python loop
        self._native = None
        try:
            from ..native import NativeBPE, available
            if available():
                self._native = NativeBPE(
                    self.vocab, merges,
                    unk_id=self.vocab.get(unk_token, 0))
        except Exception:
            self._native = None

    @classmethod
    def from_pretrained(cls, path: str, **kw) -> "BPETokenizer":
        vf = os.path.join(path, "vocab.json")
        mf = os.path.join(path, "merges.txt")
        with open(vf, encoding="utf-8") as f:
            vocab = json.load(f)
        merges = []
        with open(mf, encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line or line.startswith("#"):
                    continue
                merges.append(tuple(line.split()))
        return cls(vocab, merges, **kw)

    def _bpe(self, token: str) -> List[str]:
        if token in self._cache:
            return self._cache[token]
        sym = list(token)
        while len(sym) > 1:
            best, rank = None, None
            for pair in zip(sym, sym[1:]):
                r = self.ranks.get(pair)
                if r is not None and (rank is None or r < rank):
                    best, rank = pair, r
            if best is None:
                break
            out, i = [], 0
            while i < len(sym):
                if i + 1 < len(sym) and (sym[i], sym[i + 1]) == best:
                    out.append(sym[i] + sym[i + 1])
                    i += 2
                else:
                    out.append(sym[i])
                    i += 1
            sym = out
        self._cache[token] = sym
        return sym

    def tokenize(self, text: str) -> List[str]:
        out = []
        for piece in self._pat.findall(text):
            mapped = "".join(self.byte_enc[b] for b in piece.encode("utf-8"))
            out.extend(self._bpe(mapped))
        return out

    def encode(self, text: str) -> List[int]:
        if self._native is not None:
            # python-side memo in front of the C call: repeated pieces
            # skip the ctypes boundary entirely
            memo = self._id_cache
            out: List[int] = []
            for piece in self._pat.findall(text):
                ids = memo.get(piece)
                if ids is None:
                    mapped = "".join(self.byte_enc[b]
                                     for b in piece.encode("utf-8"))
                    ids = self._native.encode_piece(mapped)
                    memo[piece] = ids
                out.extend(ids)
            return out
        unk = self.vocab.get(self.unk_token, 0)
        return [self.vocab.get(t, unk) for t in self.tokenize(text)]

    def decode(self, ids) -> str:
        toks = [self.id_to_token.get(int(i), "") for i in
                np.asarray(ids).tolist()]
        chars = "".join(t for t in toks
                        if t not in (self.eos_token, self.pad_token))
        raw = bytes(self.byte_dec[c] for c in chars if c in self.byte_dec)
        return raw.decode("utf-8", errors="replace")

    def __call__(self, texts, max_length: int = 128, padding: bool = True,
                 truncation: bool = True):
        if isinstance(texts, str):
            texts = [texts]
        encs = [self.encode(t) for t in texts]
        if truncation:
            encs = [e[:max_length] for e in encs]
        pad_id = self.vocab.get(self.pad_token, 0)
        # truncation off: L grows to the longest sequence (never chop)
        L = max(len(e) for e in encs)
        if padding and truncation:
            L = max_length
        return {"input_ids": pad_sequences(encs, L, pad_id),
                "attention_mask": pad_sequences(
                    [[1] * len(e) for e in encs], L, 0)}


__all__ += ["BPETokenizer", "train_bpe"]


def _bpe_getstate(self):
    """Pickle/deepcopy support: the native handle and caches are process-
    local and rebuilt lazily on restore."""
    state = self.__dict__.copy()
    state["_native"] = None
    state["_pat"] = None
    state["_cache"] = {}
    state["_id_cache"] = {}
    return state


def _bpe_setstate(self, state):
    state.pop("_merges_for_restore", None)  # legacy pickles carried this
    self.__dict__.update(state)
    # merges are derivable from the pickled ranks — no duplicate payload
    merges = sorted(self.ranks, key=self.ranks.get)
    self._pat = _gpt2_pretokenize_pattern()
    try:
        from ..native import NativeBPE, available
        if available():
            self._native = NativeBPE(
                self.vocab, merges, unk_id=self.vocab.get(self.unk_token, 0))
    except Exception:
        self._native = None


BPETokenizer.__getstate__ = _bpe_getstate
BPETokenizer.__setstate__ = _bpe_setstate
