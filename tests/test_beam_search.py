"""Beam search (generation.beam_search / beam_search_cached) vs an
independent NumPy reference implementation, plus KV-cache-path
equivalence (ref: PaddleNLP GenerationMixin beam/group-beam with length
and repetition penalties; VERDICT r1 item 7)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.generation import beam_search, beam_search_cached, generate

V = 16


class MarkovModel:
    """logits[:, t] = W[ids[:, t]] — deterministic, position-free."""

    def __init__(self, seed=0):
        self.W = np.random.RandomState(seed).standard_normal(
            (V, V)).astype(np.float32) * 2.0
        self.training = False

    def __call__(self, ids):
        arr = np.asarray(ids._data)
        return Tensor(jnp.asarray(self.W[arr]))


def np_beam_search(W, prompt, max_new, nb, ngroups=1, diversity=0.0,
                   length_penalty=0.0, rep_penalty=1.0, eos=None, pad=0,
                   nrs=1):
    """Independent reference with the documented semantics."""
    B, S0 = prompt.shape
    gs = nb // ngroups
    seqs = np.repeat(prompt[:, None, :], nb, 1)       # [B, nb, S0+L]
    scores = np.full((B, nb), -1e9, np.float64)
    scores[:, 0::gs] = 0.0
    finished = np.zeros((B, nb), bool)
    gen = np.zeros((B, nb, 0), np.int64)
    for step in range(max_new):
        last = seqs[:, :, -1]
        logits = W[last].astype(np.float64)           # [B, nb, V]
        if rep_penalty != 1.0:
            # CTRL penalty on raw logits (multiply negatives, divide
            # positives), then softmax — scores stay normalized log-probs
            for b in range(B):
                for n in range(nb):
                    seen = np.unique(seqs[b, n])
                    lv = logits[b, n, seen]
                    logits[b, n, seen] = np.where(
                        lv < 0, lv * rep_penalty, lv / rep_penalty)
        logp = logits - np.log(np.exp(
            logits - logits.max(-1, keepdims=True)).sum(
                -1, keepdims=True)) - logits.max(-1, keepdims=True)
        frozen = np.full((V,), -np.inf)
        frozen[pad] = 0.0
        logp = np.where(finished[..., None], frozen[None, None], logp)
        new_scores = np.empty((B, nb))
        new_tok = np.empty((B, nb), np.int64)
        new_src = np.empty((B, nb), np.int64)
        chosen = np.zeros((B, V))
        for g in range(ngroups):
            cand = (scores[:, g * gs:(g + 1) * gs, None]
                    + logp[:, g * gs:(g + 1) * gs])
            if g > 0 and diversity:
                cand = cand - diversity * chosen[:, None, :]
            flat = cand.reshape(B, gs * V)
            idx = np.argsort(-flat, axis=1, kind="stable")[:, :gs]
            for b in range(B):
                for r in range(gs):
                    i = idx[b, r]
                    new_scores[b, g * gs + r] = flat[b, i]
                    new_src[b, g * gs + r] = i // V + g * gs
                    new_tok[b, g * gs + r] = i % V
            if ngroups > 1:
                for b in range(B):
                    for r in range(gs):
                        chosen[b, new_tok[b, g * gs + r]] += 1
        # reorder
        bidx = np.arange(B)[:, None]
        seqs = seqs[bidx, new_src]
        gen = gen[bidx, new_src]
        finished = finished[bidx, new_src]
        scores = new_scores
        seqs = np.concatenate([seqs, new_tok[..., None]], -1)
        gen = np.concatenate([gen, new_tok[..., None]], -1)
        if eos is not None:
            finished = finished | (new_tok == eos)
            if finished.all():
                break
    L = gen.shape[-1]
    if eos is not None:
        lengths = np.full((B, nb), L, np.float64)
        for b in range(B):
            for n in range(nb):
                w = np.where(gen[b, n] == eos)[0]
                if len(w):
                    lengths[b, n] = w[0] + 1
                    gen[b, n, w[0] + 1:] = pad
    else:
        lengths = np.full((B, nb), L, np.float64)
    final = scores / (lengths ** length_penalty) if length_penalty \
        else scores
    out_g = np.zeros((B, nrs, max_new), np.int64)
    out_s = np.zeros((B, nrs))
    for b in range(B):
        order = np.argsort(-final[b], kind="stable")[:nrs]
        out_g[b, :, :L] = gen[b, order]
        out_s[b] = final[b, order]
    return out_g.reshape(B * nrs, max_new), out_s.reshape(-1)


PROMPT = np.array([[3, 7], [1, 4]], np.int64)


@pytest.mark.parametrize("kw", [
    dict(),
    dict(eos=5),
    dict(length_penalty=1.2, eos=5),
    dict(rep_penalty=1.5),
    dict(ngroups=2, diversity=1.0),
    dict(ngroups=2, diversity=0.5, length_penalty=0.8, eos=5),
])
def test_matches_numpy_reference(kw):
    m = MarkovModel(0)
    nb, max_new = 4, 6
    ref_g, ref_s = np_beam_search(m.W.astype(np.float64), PROMPT, max_new,
                                  nb, kw.get("ngroups", 1),
                                  kw.get("diversity", 0.0),
                                  kw.get("length_penalty", 0.0),
                                  kw.get("rep_penalty", 1.0),
                                  kw.get("eos"), 0, 1)
    got_g, got_s = beam_search(
        m, paddle.to_tensor(PROMPT.astype(np.int32)),
        max_new_tokens=max_new, num_beams=nb,
        num_beam_groups=kw.get("ngroups", 1),
        diversity_rate=kw.get("diversity", 0.0),
        length_penalty=kw.get("length_penalty", 0.0),
        repetition_penalty=kw.get("rep_penalty", 1.0),
        eos_token_id=kw.get("eos"), pad_token_id=0)
    np.testing.assert_array_equal(np.asarray(got_g.numpy()), ref_g,
                                  err_msg=str(kw))
    np.testing.assert_allclose(np.asarray(got_s.numpy()), ref_s,
                               rtol=1e-4, atol=1e-4, err_msg=str(kw))


def test_num_return_sequences():
    m = MarkovModel(1)
    ref_g, ref_s = np_beam_search(m.W.astype(np.float64), PROMPT, 5, 4,
                                  nrs=3)
    got_g, got_s = beam_search(m, paddle.to_tensor(PROMPT.astype(np.int32)),
                               max_new_tokens=5, num_beams=4,
                               num_return_sequences=3)
    assert got_g.shape == [6, 5]
    np.testing.assert_array_equal(np.asarray(got_g.numpy()), ref_g)


def test_single_beam_equals_greedy():
    m = MarkovModel(2)
    g1, _ = beam_search(m, paddle.to_tensor(PROMPT.astype(np.int32)),
                        max_new_tokens=6, num_beams=1)
    g2, _ = generate(m, paddle.to_tensor(PROMPT.astype(np.int32)),
                     max_new_tokens=6, decode_strategy="greedy_search")
    np.testing.assert_array_equal(np.asarray(g1.numpy()),
                                  np.asarray(g2.numpy()))


def test_kv_cache_path_equivalence():
    """beam_search (full-buffer forwards) and beam_search_cached (KV
    cache + per-step beam reorder of the cache) must produce identical
    sequences on an f32 Llama."""
    from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny_config
    paddle.seed(11)
    cfg = llama_tiny_config(num_hidden_layers=2, vocab_size=64,
                            max_position_embeddings=64,
                            sequence_parallel=False)
    model = LlamaForCausalLM(cfg)
    prompt = paddle.to_tensor(
        np.random.RandomState(0).randint(1, 64, (2, 4)).astype(np.int32))
    g_buf, s_buf = beam_search(model, prompt, max_new_tokens=6,
                               num_beams=3, length_penalty=0.6,
                               eos_token_id=2)
    g_cac, s_cac = beam_search_cached(model, prompt, max_new_tokens=6,
                                      num_beams=3, length_penalty=0.6,
                                      eos_token_id=2)
    np.testing.assert_array_equal(np.asarray(g_buf.numpy()),
                                  np.asarray(g_cac.numpy()))
    np.testing.assert_allclose(np.asarray(s_buf.numpy()),
                               np.asarray(s_cac.numpy()),
                               rtol=1e-4, atol=1e-4)
