"""Comparison / logical / bitwise ops (ref: python/paddle/tensor/logic.py)."""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..core.dispatch import apply
from ..core.tensor import Tensor

__all__ = [
    "equal", "not_equal", "greater_than", "greater_equal", "less_than",
    "less_equal", "logical_and", "logical_or", "logical_xor", "logical_not",
    "bitwise_and", "bitwise_or", "bitwise_xor", "bitwise_not", "equal_all",
    "allclose", "isclose", "all", "any", "is_tensor",
]


def _cmp(opname, jfn):
    def op(x, y, name=None):
        xv = x._data if isinstance(x, Tensor) else jnp.asarray(x)
        yv = y._data if isinstance(y, Tensor) else jnp.asarray(y)
        return Tensor(jfn(xv, yv))
    op.__name__ = opname
    return op


equal = _cmp("equal", jnp.equal)
not_equal = _cmp("not_equal", jnp.not_equal)
greater_than = _cmp("greater_than", jnp.greater)
greater_equal = _cmp("greater_equal", jnp.greater_equal)
less_than = _cmp("less_than", jnp.less)
less_equal = _cmp("less_equal", jnp.less_equal)
logical_and = _cmp("logical_and", jnp.logical_and)
logical_or = _cmp("logical_or", jnp.logical_or)
logical_xor = _cmp("logical_xor", jnp.logical_xor)
bitwise_and = _cmp("bitwise_and", jnp.bitwise_and)
bitwise_or = _cmp("bitwise_or", jnp.bitwise_or)
bitwise_xor = _cmp("bitwise_xor", jnp.bitwise_xor)


def logical_not(x, name=None) -> Tensor:
    return Tensor(jnp.logical_not(x._data))


def bitwise_not(x, name=None) -> Tensor:
    return Tensor(jnp.bitwise_not(x._data))


def equal_all(x, y, name=None) -> Tensor:
    return Tensor(jnp.array_equal(x._data, y._data))


def allclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None) -> Tensor:
    return Tensor(jnp.allclose(x._data, y._data, rtol=rtol, atol=atol,
                               equal_nan=equal_nan))


def isclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None) -> Tensor:
    return Tensor(jnp.isclose(x._data, y._data, rtol=rtol, atol=atol,
                              equal_nan=equal_nan))


def all(x, axis=None, keepdim=False, name=None) -> Tensor:
    ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis
    return Tensor(jnp.all(x._data, axis=ax, keepdims=keepdim))


def any(x, axis=None, keepdim=False, name=None) -> Tensor:
    ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis
    return Tensor(jnp.any(x._data, axis=ax, keepdims=keepdim))


def is_tensor(x) -> bool:
    return isinstance(x, Tensor)
