"""Baseline file handling.

The baseline is a checked-in JSON list of accepted pre-existing findings,
keyed by :attr:`Finding.baseline_key` (``rule|path|qualname|detail`` — no
line numbers, so entries survive unrelated edits). Every entry carries a
one-line justification; ``--write-baseline`` refuses to invent them and
stamps ``TODO: justify`` so review catches unexplained acceptances.
"""

from __future__ import annotations

import json
from typing import Dict, List, Tuple

from .model import Finding

VERSION = 1


def load(path: str) -> Dict[str, str]:
    """-> baseline_key -> justification."""
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    if data.get("version") != VERSION:
        raise ValueError(f"unsupported baseline version: "
                         f"{data.get('version')!r}")
    return {e["key"]: e.get("justification", "")
            for e in data.get("entries", [])}


def save(path: str, findings: List[Finding],
         justifications: Dict[str, str]) -> None:
    entries = []
    seen = set()
    for f in sorted(findings, key=lambda x: (x.path, x.rule, x.qualname)):
        k = f.baseline_key
        if k in seen:
            continue
        seen.add(k)
        entries.append({
            "key": k,
            "justification": justifications.get(k, "TODO: justify"),
        })
    with open(path, "w", encoding="utf-8") as f:
        json.dump({"version": VERSION, "entries": entries}, f, indent=2)
        f.write("\n")


def split(findings: List[Finding], baseline: Dict[str, str]
          ) -> Tuple[List[Finding], List[str]]:
    """-> (non-baselined findings, stale baseline keys no longer hit)."""
    hit = set()
    fresh = []
    for f in findings:
        if f.baseline_key in baseline:
            hit.add(f.baseline_key)
        else:
            fresh.append(f)
    stale = sorted(set(baseline) - hit)
    return fresh, stale
