"""Build-config queries (ref: python/paddle/sysconfig.py)."""

from __future__ import annotations

import os

__all__ = ["get_include", "get_lib"]

_pkg_dir = os.path.dirname(os.path.abspath(__file__))


def get_include() -> str:
    """Directory of this package's C headers (csrc/)."""
    return os.path.join(_pkg_dir, "csrc")


def get_lib() -> str:
    """Directory holding the built native shared objects."""
    return os.path.join(_pkg_dir, "native")
