"""Prefix-cache-locality fleet router over N serving replicas.

`FleetRouter` spreads requests across engine replicas (ROADMAP item 2)
using the radix-trie prefix overlap as the placement signal: each
prefill-capable replica is scored by

    locality_weight * match_length(prompt)          (trie overlap, tokens)
  - queue_cost_tokens * (inflight + waiting)        (queue depth penalty)

with free pages then submission order as deterministic tiebreaks — a
cold prompt degenerates to least-loaded placement. The same
`PrefixCache.match_length` tokens feed the per-replica
`serving.prefix_cache.replica_hit_tokens` counters, so the router's
score is computed from the numbers operators already see.

Disaggregation: prefill-role replicas stage completed prefills on
`engine.handoff_ready`; after each fleet step the router exports them
(`KVPageHandoff`) and imports into the least-loaded decode-capable
replica. An import refused with `Overloaded` (pool or admission gate)
parks the handoff on a pending queue and retries next step — the
export pins keep the protocol window consistent however long that
takes.

Resilience: a replica whose `step()` raises
`distributed.watchdog.CollectiveTimeout` (or any fault the caller
reports via `drain()`) is taken out of rotation. Every in-flight
request with complete KV — running decodes, staged handoffs,
preempted waiters — is exported pages-intact and requeued on the
survivors (no re-prefill, the PR-10 resume path); mid-prefill and
still-waiting requests are resubmitted fresh (chunked prefill replays
deterministically). `readmit()` puts a healed replica back, and
`poll_elastic()` drives both transitions from an `ElasticManager`'s
heartbeat view when one is attached.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from .. import observability as _obs
from .. import resilience as _res
from ..distributed.watchdog import CollectiveTimeout
from ..observability import fleet as _fleet
from ..observability import tracing as _tracing
from .engine import ServingEngine
from .handoff import KVPageHandoff
from .scheduler import DECODE, PREFILL, Request

__all__ = ["FleetRouter"]

_PLACED = _obs.registry().counter(
    "serving.router.placements",
    "requests placed, by replica and placement signal",
    labels=("replica", "signal"))
_ROUTED_HANDOFFS = _obs.registry().counter(
    "serving.router.handoffs", "prefill→decode handoffs routed")
_DRAINS = _obs.registry().counter(
    "serving.router.drains", "replicas drained on fault",
    labels=("replica",))
_REQUEUED = _obs.registry().counter(
    "serving.router.requeued",
    "in-flight requests moved pages-intact off a drained replica")
_RESUBMITTED = _obs.registry().counter(
    "serving.router.resubmitted",
    "waiting/mid-prefill requests restarted off a drained replica")
_READMITS = _obs.registry().counter(
    "serving.router.readmits", "healed replicas re-admitted",
    labels=("replica",))
_UP = _obs.registry().gauge(
    "serving.router.replicas_up", "replicas in rotation")
_TRACE = _tracing.recorder()


class FleetRouter:
    """Route requests across N `ServingEngine` replicas by prefix-cache
    locality; drive their steps; broker prefill→decode handoffs; drain
    and re-admit replicas on faults.

    Typical loop::

        router = FleetRouter({"pf0": prefill_eng, "dec0": decode_eng})
        router.submit(prompt_ids, max_new_tokens=32)
        results = router.run_to_completion()

    Replicas may be any role mix: `prefill`/`colocated` replicas take
    fresh prompts, `decode`/`colocated` replicas take handoffs. All
    replicas must share model weights, family, and page_size for the
    exactness contract to hold.
    """

    def __init__(self, replicas: Dict[str, ServingEngine],
                 locality_weight: float = 1.0,
                 queue_cost_tokens: float = 32.0,
                 elastic=None,
                 node_ranks: Optional[Dict[str, int]] = None,
                 readmit_warmup: float = 0.5,
                 warmup_load: float = 2.0,
                 weight_recovery: float = 0.25):
        if not replicas:
            raise ValueError("FleetRouter needs at least one replica")
        self.replicas = dict(replicas)
        self.locality_weight = float(locality_weight)
        self.queue_cost_tokens = float(queue_cost_tokens)
        for name, eng in self.replicas.items():
            if eng.replica is None:
                eng.set_replica(name)
        self._order = list(self.replicas)     # deterministic tiebreak
        self._down: set = set()
        self._pending: List[KVPageHandoff] = []
        self._export_t: Dict[object, float] = {}
        self._results: Dict[object, object] = {}
        self.handoff_count = 0
        self.handoff_seconds = 0.0
        # placement weights (ISSUE 18): [0, 1] per replica. A weight
        # below 1 scales down the locality signal and charges
        # `warmup_load` phantom queue entries, so a just-readmitted
        # replica is neither dogpiled (its empty queue looks loaded)
        # nor starved (the weight ramps back by `weight_recovery` per
        # fleet step). `readmit()` seeds the weight from the last
        # federated scrape when one was taken, else `readmit_warmup`.
        self.placement_weight: Dict[str, float] = \
            {name: 1.0 for name in self._order}
        self.readmit_warmup = float(readmit_warmup)
        self.warmup_load = float(warmup_load)
        self.weight_recovery = float(weight_recovery)
        self._last_scrape: Dict[str, Dict[str, float]] = {}
        # optional fleet-scope SLO autopilot (serving.controller):
        # attach_controller wires on_step / on_capacity_loss
        self.controller = None
        # fleet-scope SLO tracking, router-measured: request_id ->
        # [submit_t, first_token_seen, Request, submit_step].
        # Drain-resubmits keep the ORIGINAL submit time/step, so fleet
        # TTFT/e2e include the retry cost a client of the fleet
        # actually pays. Step-indexed latencies (ttft_steps/e2e_steps,
        # in router steps) are kept unconditionally — they are the
        # deterministic SLO signal seeded CI replays bit-exactly.
        self._slo: Dict[object, list] = {}
        self._step_idx = 0
        self.ttft_steps: Dict[object, int] = {}
        self.e2e_steps: Dict[object, int] = {}
        # optional ElasticManager heartbeat view: replica name -> node
        # rank (defaults to listing order)
        self._elastic = elastic
        self._ranks = dict(node_ranks) if node_ranks else \
            {name: i for i, name in enumerate(self._order)}
        if _obs.enabled():
            _UP.set(len(self._live()))

    # ------------------------------------------------------------- queries
    def _live(self) -> List[Tuple[str, ServingEngine]]:
        return [(n, self.replicas[n]) for n in self._order
                if n not in self._down]

    def live_replicas(self) -> List[str]:
        return [n for n, _ in self._live()]

    def has_work(self) -> bool:
        return bool(self._pending) or any(
            eng.has_work() or eng.handoff_ready for _, eng in self._live())

    def stats(self) -> Dict[str, object]:
        return {
            "replicas": len(self.replicas),
            "up": len(self._live()),
            "down": sorted(self._down),
            "pending_handoffs": len(self._pending),
            "handoffs": self.handoff_count,
            "handoff_latency_s": (self.handoff_seconds
                                  / self.handoff_count
                                  if self.handoff_count else 0.0),
        }

    def attach_controller(self, controller) -> None:
        """Wire a `FleetController`: `step()` calls its `on_step` and
        `drain()` its `on_capacity_loss`."""
        self.controller = controller

    # ----------------------------------------------------------- placement
    def _weight(self, name: Optional[str]) -> float:
        return self.placement_weight.get(name or "", 1.0)

    def _score(self, eng: ServingEngine, prompt) -> Tuple[float, int]:
        hit = eng.prefix_cache.match_length(prompt) \
            if eng.prefix_cache is not None else 0
        load = eng.scheduler.inflight + len(eng.scheduler.waiting)
        w = self._weight(eng.replica)
        return (self.locality_weight * hit * w
                - self.queue_cost_tokens
                * (load + (1.0 - w) * self.warmup_load), hit)

    def submit(self, prompt, max_new_tokens: int = 20, **kw) -> Request:
        """Place one fresh request on the best prefill-capable replica:
        highest locality-vs-load score, free pages then listing order as
        tiebreaks, falling back down the ranking when a replica refuses
        with `Overloaded`. Raises `Overloaded` only when every live
        prefill-capable replica refused."""
        targets = [(n, e) for n, e in self._live()
                   if e.role in ("prefill", "colocated")]
        if not targets:
            raise _res.Overloaded("no prefill-capable replica in rotation")
        ranked = []
        for idx, (name, eng) in enumerate(targets):
            score, hit = self._score(eng, prompt)
            ranked.append((-score, -eng.allocator.available_pages, idx,
                           name, eng, hit))
        ranked.sort(key=lambda t: t[:3])
        err: Optional[Exception] = None
        for _, _, _, name, eng, hit in ranked:
            try:
                req = eng.add_request(prompt, max_new_tokens, **kw)
            except _res.Overloaded as e:
                err = e
                continue
            ent = self._slo.get(req.request_id)
            if ent is None:
                self._slo[req.request_id] = [time.monotonic(), False,
                                             req, self._step_idx]
            else:
                ent[2] = req     # drain-resubmit: keep original t0/step
            if _obs.enabled():
                _PLACED.labels(replica=name,
                               signal="prefix" if hit else "load").inc()
            _TRACE.stamp(req.request_id, "routed", replica=name,
                         hit_tokens=hit)
            return req
        raise err if err is not None else _res.Overloaded(
            "all prefill-capable replicas refused")

    def place_of(self, request_id) -> Optional[str]:
        """Replica currently holding `request_id` (None if unknown/done)."""
        for name, eng in self._live():
            if any(r.request_id == request_id
                   for r in eng.handoff_ready):
                return name
            if any(r.request_id == request_id
                   for r in eng.scheduler.waiting):
                return name
            if any(r is not None and r.request_id == request_id
                   for r in eng.scheduler.slots):
                return name
        return None

    # ------------------------------------------------------------ stepping
    def step(self) -> Dict[str, int]:
        """One fleet iteration: step every live replica (a
        `CollectiveTimeout` drains it instead of propagating), export
        freshly completed prefills, then try to place pending handoffs
        on decode-capable replicas."""
        out = {"admitted": 0, "prefill_tokens": 0, "decoded": 0,
               "finished": 0, "handoffs": 0}
        self._step_idx += 1
        for name in list(self._order):
            if name in self._down:
                continue
            eng = self.replicas[name]
            try:
                st = eng.step()
            except CollectiveTimeout as err:
                self.drain(name, err)
                continue
            for k in ("admitted", "prefill_tokens", "decoded",
                      "finished"):
                out[k] += st.get(k, 0)
            self._observe_first_tokens()
            for req in list(eng.handoff_ready):
                self._export(eng, req)
            self._absorb(eng.collect())
        pending, self._pending = self._pending, []
        for handoff in pending:
            out["handoffs"] += self._import(handoff)
        # warmup ramp: discounted replicas recover toward full weight
        for name in self._order:
            if name not in self._down:
                w = self.placement_weight[name]
                if w < 1.0:
                    self.placement_weight[name] = \
                        min(1.0, w + self.weight_recovery)
        if self.controller is not None:
            self.controller.on_step(out)
        return out

    def collect(self) -> Dict[object, object]:
        """Results finished anywhere in the fleet since last collect."""
        for _, eng in self._live():
            self._absorb(eng.collect())
        done, self._results = self._results, {}
        return done

    def run_to_completion(self, max_steps: int = 100000) \
            -> Dict[object, object]:
        """Step until the fleet is idle; collect everything."""
        results: Dict[object, object] = {}
        steps = 0
        while self.has_work():
            if steps >= max_steps:
                raise RuntimeError(
                    f"fleet did not drain in {max_steps} steps "
                    f"({self.stats()})")
            self.step()
            results.update(self.collect())
            steps += 1
        results.update(self.collect())
        return results

    # ------------------------------------------------------- fleet SLOs
    def _observe_first_tokens(self) -> None:
        """Fleet TTFT, measured from OUTSIDE the replicas: scanned right
        after each engine step so the router sees a first token at the
        earliest moment a fleet client could (within one step of the
        trace's own token stamp)."""
        if not self._slo:
            return
        now = time.monotonic()
        for rid, ent in self._slo.items():
            if not ent[1] and ent[2] is not None and ent[2].tokens:
                ent[1] = True
                self.ttft_steps[rid] = self._step_idx - ent[3]
                if _obs.enabled():
                    _fleet.observe_ttft(now - ent[0])

    def _absorb(self, done: Dict[object, object]) -> None:
        """Fold one engine's collected results into the fleet result
        set, observing fleet e2e + per-phase attribution for every
        request that completed with tokens."""
        self._results.update(done)
        if not self._slo or not done:
            return
        now = time.monotonic()
        finished = None
        for rid, res in done.items():
            ent = self._slo.pop(rid, None)
            if ent is None or not isinstance(res, np.ndarray):
                continue
            self.e2e_steps[rid] = self._step_idx - ent[3]
            if not _obs.enabled():
                continue
            _fleet.observe_e2e(now - ent[0])
            if finished is None:
                finished = {t.request_id: t for t in _TRACE.finished()}
            _fleet.observe_phases(finished.get(rid))

    def scrape(self) -> _obs.Registry:
        """Fleet metric federation: collect every live replica's
        `ServingEngine.scrape()` snapshot into one rollup registry
        (counters summed, gauges/histograms re-labeled with
        ``replica=...``) plus the router-measured ``serving.fleet.*``
        SLO histograms — ready for `obs.to_prometheus(rollup)` /
        `rollup.snapshot()`. Returns an empty registry with metrics
        disabled."""
        snaps = {n: e.scrape() for n, e in self._live()}
        # remember each replica's scraped queue view: `readmit()` seeds
        # a healed replica's placement weight from its LAST known load
        # instead of treating it as a brand-new cold replica
        for n, e in self._live():
            self._last_scrape[n] = {
                "waiting": float(len(e.scheduler.waiting)),
                "inflight": float(e.scheduler.inflight),
                "utilization": float(
                    e.allocator.stats()["utilization"]),
            }
        rollup = _fleet.federate(
            {n: s for n, s in snaps.items() if s})
        snap = _obs.snapshot()
        for name in sorted(snap):
            if not name.startswith("serving.fleet."):
                continue
            e = snap[name]
            if e.get("kind") != "histogram":
                continue    # serving.fleet.controller.* counters/gauges
            m = rollup.histogram(name, e["help"], tuple(e["labels"]),
                                 buckets=tuple(e["buckets"]))
            for s in e["series"]:
                tgt = m.labels(**s["labels"]) if e["labels"] else m
                tgt._counts = list(s["counts"])
                tgt._sum = float(s["sum"])
                tgt._count = int(s["count"])
        return rollup

    def slo_summary(self, qs=(50, 90, 99)) -> Dict[str, object]:
        """Fleet-scope SLO table ({metric: {count, mean, pXX}}) over the
        router-measured serving.fleet.* histograms."""
        return _fleet.fleet_slo_summary(qs=qs)

    @staticmethod
    def _step_pct(vals: List[int], q: int) -> Optional[int]:
        """Nearest-rank percentile over integer step counts —
        deterministic on a seeded replay (no interpolation)."""
        if not vals:
            return None
        s = sorted(vals)
        return s[max(0, -(-q * len(s) // 100) - 1)]

    def step_slo_summary(self, qs=(50, 90, 99)) -> Dict[str, object]:
        """Step-indexed fleet SLOs: TTFT / e2e measured in ROUTER STEPS
        from original submission (drain-resubmits keep their first
        step). Wall-clock percentiles are machine-dependent; these
        replay bit-exactly from a seed, so `SLOTargets.*_steps` targets
        can be asserted in CI."""
        out: Dict[str, object] = {}
        for key, d in (("ttft", self.ttft_steps),
                       ("e2e", self.e2e_steps)):
            vals = list(d.values())
            for q in qs:
                out[f"{key}_p{q}_steps"] = self._step_pct(vals, q)
        return out

    # ------------------------------------------------------------- handoff
    def _export(self, eng: ServingEngine, req: Request) -> None:
        self._export_t[req.request_id] = time.monotonic()
        self._pending.append(eng.export_request(req))

    def _import(self, handoff: KVPageHandoff) -> int:
        """Place one handoff on the least-loaded decode-capable replica
        (free pages, then listing order). Refused everywhere → back on
        the pending queue for the next step."""
        ranked = []
        for idx, (name, eng) in enumerate(self._live()):
            if eng.role not in ("decode", "colocated"):
                continue
            w = self._weight(name)
            load = (eng.scheduler.inflight + len(eng.scheduler.waiting)
                    + (1.0 - w) * self.warmup_load)
            ranked.append((load, -eng.allocator.available_pages, idx,
                           name, eng))
        ranked.sort(key=lambda t: t[:3])
        for _, _, _, name, eng in ranked:
            try:
                req = eng.import_request(handoff)
            except _res.Overloaded:
                continue
            ent = self._slo.get(handoff.request_id)
            if ent is not None:
                ent[2] = req    # the importer's Request is live now
            t0 = self._export_t.pop(handoff.request_id, None)
            if t0 is not None:
                dt = time.monotonic() - t0
                self.handoff_seconds += dt
                _fleet.observe_handoff(dt)
            self.handoff_count += 1
            if _obs.enabled():
                _ROUTED_HANDOFFS.inc()
            return 1
        self._pending.append(handoff)
        return 0

    # ---------------------------------------------------------- resilience
    def drain(self, name: str, err: Optional[BaseException] = None,
              notify: bool = True) -> int:
        """Take `name` out of rotation and move its work to survivors:
        requests with complete KV (running decodes, staged handoffs,
        preempted waiters) are exported pages-intact onto the pending
        handoff queue — they resume elsewhere WITHOUT re-prefill;
        waiting/mid-prefill requests are resubmitted fresh. Returns how
        many requests were moved or resubmitted."""
        if name in self._down:
            return 0
        eng = self.replicas[name]
        self._down.add(name)
        if _obs.enabled():
            _DRAINS.labels(replica=name).inc()
            _UP.set(len(self._live()))
        # results finished before the fault survive the drain
        self._absorb(eng.collect())
        moved = resubmitted = 0
        for req in list(eng.handoff_ready):
            self._export(eng, req)
            moved += 1
        for _, req in list(eng.scheduler.active(DECODE)):
            self._export(eng, req)
            moved += 1
        fresh: List[Request] = []
        for _, req in list(eng.scheduler.active(PREFILL)):
            # partial prefill is discarded: chunked prefill replays
            # deterministically on the new replica
            if req in eng._prefill_fifo:
                eng._prefill_fifo.remove(req)
            eng.scheduler.detach(req)
            if eng.allocator.has_seq(req.request_id):
                eng.allocator.free(req.request_id)
            fresh.append(req)
        for req in list(eng.scheduler.waiting):
            if req.preempted and eng.allocator.has_seq(req.request_id):
                self._export(eng, req)
                moved += 1
            else:
                eng.scheduler.waiting.remove(req)
                fresh.append(req)
        for req in fresh:
            self.submit(req.prompt, req.max_new_tokens,
                        eos_token_id=req.eos_token_id,
                        pad_token_id=req.pad_token_id,
                        deadline_s=req.deadline_s,
                        request_id=req.request_id,
                        priority=req.priority, tenant=req.tenant)
            resubmitted += 1
        if _obs.enabled():
            _REQUEUED.inc(moved)
            _RESUBMITTED.inc(resubmitted)
        _TRACE.stamp(f"drain:{name}", "drain", moved=moved,
                     resubmitted=resubmitted,
                     reason=type(err).__name__ if err else "manual")
        if notify and self.controller is not None:
            # capacity-loss event: the fleet controller pre-emptively
            # tightens the survivors' admission instead of waiting for
            # their queues to cross the SLO threshold
            self.controller.on_capacity_loss(name)
        return moved + resubmitted

    def readmit(self, name: str,
                weight: Optional[float] = None) -> None:
        """Put a healed replica back in rotation (its pool is empty —
        drain exported or resubmitted everything). Its locality and
        queue stats are COLD, so the placement weight is seeded below
        1.0 — from the last federated scrape when one was taken (the
        more loaded it went down, the deeper the discount), else the
        `readmit_warmup` default — and ramps back to full weight by
        `weight_recovery` per fleet step. That keeps the router from
        dogpiling an empty-looking replica or starving a healed one."""
        if name not in self.replicas:
            raise KeyError(name)
        if name in self._down:
            self._down.discard(name)
            if weight is None:
                last = self._last_scrape.get(name)
                if last is None:
                    weight = self.readmit_warmup
                else:
                    gone_load = last.get("waiting", 0.0) \
                        + last.get("inflight", 0.0)
                    weight = self.readmit_warmup / (1.0 + gone_load)
            self.placement_weight[name] = max(0.1, min(1.0, weight))
            if _obs.enabled():
                _READMITS.labels(replica=name).inc()
                _UP.set(len(self._live()))

    def set_role(self, name: str, role: str) -> None:
        """Shift `name` between prefill/decode duty through the PR-15
        drain/handoff path: in-flight work leaves pages-intact (or is
        resubmitted fresh), the role flips, and the replica re-enters
        rotation at FULL weight — it was repurposed, not unhealthy.
        Callers must leave at least one replica of each needed role
        (the FleetController guards this)."""
        if role not in ("prefill", "decode", "colocated"):
            raise ValueError(
                f"role must be prefill/decode/colocated, got {role!r}")
        eng = self.replicas[name]
        if eng.role == role:
            return
        was_down = name in self._down
        if not was_down:
            # not a capacity loss: survivors need no guard tightening
            self.drain(name, notify=False)
        eng.role = role
        if not was_down:
            self.readmit(name, weight=1.0)

    def poll_elastic(self) -> None:
        """Reconcile rotation with an `ElasticManager` membership view:
        replicas whose node stopped heartbeating are drained; nodes
        alive again are re-admitted."""
        if self._elastic is None:
            return
        alive = set(self._elastic.alive_nodes(len(self.replicas)))
        for name, rank in self._ranks.items():
            if rank in alive:
                self.readmit(name)
            elif name not in self._down:
                self.drain(name)
