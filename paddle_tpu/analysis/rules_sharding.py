"""PS301–PS306: sharding verification over the static mesh/PartitionSpec
model (``meshmodel.py``; docs/ANALYSIS.md, sharding-verification section).

A wrong axis name or a non-divisible sharded dimension surfaces at
runtime as an opaque XLA error on chip — or worse, as a silent
full-replication slowdown. These rules check, entirely at the AST level,
that the specs and axis names threaded through ``shard_map`` /
``NamedSharding`` / collectives are mutually consistent. Like the kernel
rules, every check opts out when the model could not resolve the piece
it needs — an unknown mesh or a helper-built spec is never guessed at.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .callgraph import PackageIndex, _last_name, walk_shallow
from .meshmodel import (MeshModel, OrderedEnv, ShardMapSite, SpecModel,
                        _str_const, build_mesh_model, literal_rank,
                        literal_shape)
from .model import Config, Finding, register_rule

register_rule("PS301", "collective axis name not bound by an enclosing "
                       "mesh/shard_map axis environment",
              severity="error", module=__name__)
register_rule("PS302", "in_specs/out_specs arity mismatch vs the wrapped "
                       "function's signature or call arguments",
              severity="error", module=__name__)
register_rule("PS303", "PartitionSpec rank exceeds the sharded array's "
                       "rank, or the same mesh axis appears twice",
              severity="error", module=__name__)
register_rule("PS304", "statically-known dimension not divisible by the "
                       "product of the mesh axis sizes sharding it",
              severity="warning", module=__name__)
register_rule("PS305", "axis-name shadowing across nested shard_map/"
                       "vmap(axis_name=) scopes",
              severity="warning", module=__name__)
register_rule("PS306", "unsanitized layer-declared spec reaches "
                       "NamedSharding under a configurable mesh",
              severity="warning", module=__name__)


def _spec_dup_axes(spec: SpecModel) -> List[str]:
    """Axis names appearing in more than one dim entry (or twice inside
    one nested-tuple entry) of a fully-literal spec."""
    if spec.entries is None:
        return []
    seen: Dict[str, int] = {}
    for e in spec.entries:
        names = (e,) if isinstance(e, str) else e if isinstance(e, tuple) \
            else ()
        for n in names:
            seen[n] = seen.get(n, 0) + 1
    return sorted(n for n, c in seen.items() if c > 1)


def _site_axes(site: ShardMapSite) -> Set[str]:
    """Every axis name the site is *known* to bind (possibly a subset of
    the true environment when the mesh is partially symbolic)."""
    out: Set[str] = set(site.manual_axes or ())
    if site.env is not None:
        out |= set(site.env.axes)
    return out


# ---------------------------------------------------------------------------
# PS301 — collective axis vs environment
# ---------------------------------------------------------------------------

def _check_ps301(model: MeshModel, findings: List[Finding]) -> None:
    reported: Set[Tuple[int, str]] = set()
    for site in model.shard_map_sites:
        bound = site.bound_axes()
        if bound is None or not site.body_keys:
            continue
        region = model.region_of(site.body_keys)
        allowed = set(bound) | model.region_vmap_axes(region)
        for key in sorted(region):
            for use in model.collectives.get(key, []):
                if use.axes is None:
                    continue
                for axis in use.axes:
                    if axis in allowed or (id(use.call), axis) in reported:
                        continue
                    reported.add((id(use.call), axis))
                    findings.append(Finding(
                        "PS301", "error", use.mi.rel, use.call.lineno,
                        use.call.col_offset, use.fi.qualname,
                        f"collective `{use.name}` names axis '{axis}' "
                        f"but the shard_map environment reaching it binds "
                        f"only {sorted(allowed)} "
                        f"(site {site.mi.rel}:{site.qualname})",
                        hint="pass the axis the mesh actually has, or "
                             "thread the axis name from the shard_map "
                             "site instead of hard-coding it",
                        detail=f"unbound-axis:{use.name}:{axis}"))


# ---------------------------------------------------------------------------
# PS302 — spec arity vs signature
# ---------------------------------------------------------------------------

def _return_tuple_len(site: ShardMapSite) -> Optional[int]:
    """Common literal-tuple length of every return of the body, or None
    when returns are absent / non-tuple / of mixed length."""
    fi = site.body_fi
    if fi is None:
        return None
    if isinstance(fi.node, ast.Lambda):
        body = fi.node.body
        return len(body.elts) if isinstance(body, ast.Tuple) else None
    lens: Set[int] = set()
    for node in walk_shallow(fi.node):
        if isinstance(node, ast.Return) and node.value is not None:
            if not isinstance(node.value, ast.Tuple):
                return None
            lens.add(len(node.value.elts))
    return lens.pop() if len(lens) == 1 else None


def _check_ps302(site: ShardMapSite, findings: List[Finding]) -> None:
    def report(what: str, detail: str) -> None:
        findings.append(Finding(
            "PS302", "error", site.mi.rel, site.line,
            site.call.col_offset, site.qualname, what,
            hint="one spec per leaf of the argument/output tree — a "
                 "single spec (no tuple) broadcasts as a pytree prefix",
            detail=detail))

    if site.in_specs is not None and site.in_specs_seq:
        n_specs = len(site.in_specs)
        n_params = site.body_positional()
        if n_params is not None and n_specs != n_params:
            report(f"in_specs has {n_specs} spec(s) but the wrapped "
                   f"function takes {n_params} positional argument(s)",
                   f"in-specs-arity:{n_specs}:{n_params}")
        if site.arg_exprs is not None and len(site.arg_exprs) != n_specs:
            report(f"in_specs has {n_specs} spec(s) but the shard_map "
                   f"is invoked with {len(site.arg_exprs)} argument(s)",
                   f"in-specs-args:{n_specs}:{len(site.arg_exprs)}")
    if site.out_specs is not None and site.out_specs_seq:
        n_ret = _return_tuple_len(site)
        if n_ret is not None and n_ret != len(site.out_specs):
            report(f"out_specs has {len(site.out_specs)} spec(s) but the "
                   f"wrapped function returns a {n_ret}-tuple",
                   f"out-specs-arity:{len(site.out_specs)}:{n_ret}")


# ---------------------------------------------------------------------------
# PS303 — rank excess / duplicate axis
# ---------------------------------------------------------------------------

def _check_ps303_spec(mi_rel: str, qualname: str, spec: SpecModel,
                      findings: List[Finding],
                      reported: Set[int]) -> None:
    if id(spec.node) in reported:
        return
    dups = _spec_dup_axes(spec)
    if dups:
        reported.add(id(spec.node))
        findings.append(Finding(
            "PS303", "error", mi_rel, spec.node.lineno,
            spec.node.col_offset, qualname,
            f"mesh axis{'es' if len(dups) > 1 else ''} "
            f"{', '.join(repr(d) for d in dups)} used twice in "
            f"{spec.text()} — an axis may shard at most one dim",
            hint="each mesh axis may appear once per spec; merge dims "
                 "with a nested tuple entry instead",
            detail=f"dup-axis:{':'.join(dups)}"))


def _check_ps303_rank(mi_rel: str, qualname: str, spec: SpecModel,
                      rank: Optional[int], what: str,
                      findings: List[Finding],
                      reported: Set[int]) -> None:
    min_rank = spec.min_rank
    if rank is None or min_rank is None or min_rank <= rank \
            or id(spec.node) in reported:
        return
    reported.add(id(spec.node))
    findings.append(Finding(
        "PS303", "error", mi_rel, spec.node.lineno, spec.node.col_offset,
        qualname,
        f"spec {spec.text()} names {min_rank} dim(s) but {what} has "
        f"rank {rank}",
        hint="drop the excess entries — a spec may be shorter than the "
             "array rank (trailing dims replicate) but never longer",
        detail=f"rank-excess:{min_rank}:{rank}"))


# ---------------------------------------------------------------------------
# PS304 — divisibility
# ---------------------------------------------------------------------------

def _axis_product(site_env, axes: Tuple[str, ...]) -> Optional[int]:
    prod = 1
    for a in axes:
        s = site_env.size(a)
        if s is None:
            return None
        prod *= s
    return prod


def _check_ps304_pair(mi_rel: str, qualname: str, env, spec: SpecModel,
                      shape: Optional[List[Optional[int]]],
                      findings: List[Finding],
                      reported: Set[Tuple[int, int]]) -> None:
    if spec.entries is None or env is None or not env.sizes:
        return
    for d in range(len(spec.entries)):
        axes = spec.entry_axes(d)
        if not axes:
            continue
        prod = _axis_product(env, axes)
        if prod is None or prod <= 1:
            continue
        dim = shape[d] if shape is not None and d < len(shape) else None
        key = (id(spec.node), d)
        if key in reported:
            continue
        if dim is None:
            reported.add(key)
            findings.append(Finding(
                "PS304", "info", mi_rel, spec.node.lineno,
                spec.node.col_offset, qualname,
                f"dim {d} sharded by {list(axes)} (product {prod}) has a "
                f"statically-unknown size — divisibility not verified",
                hint="advisory only: verify the dim is a multiple of "
                     f"{prod} for every configuration that reaches here",
                detail=f"indivisible-unverified:{d}:{prod}"))
        elif dim % prod != 0:
            reported.add(key)
            findings.append(Finding(
                "PS304", "warning", mi_rel, spec.node.lineno,
                spec.node.col_offset, qualname,
                f"dim {d} of size {dim} is not divisible by the mesh "
                f"axis product {prod} ({list(axes)}) — XLA pads or "
                f"rejects the sharding",
                hint="pad the dim, pick a divisible degree, or replicate "
                     "this dim (None entry) instead",
                detail=f"indivisible:{d}:{dim}:{prod}"))


# ---------------------------------------------------------------------------
# PS305 — axis shadowing
# ---------------------------------------------------------------------------

def _check_ps305(model: MeshModel, findings: List[Finding]) -> None:
    site_by_call = {id(s.call): s for s in model.shard_map_sites}
    reported: Set[Tuple[int, str]] = set()

    def scan_region(outer_axes: Set[str], body_keys: Set[str],
                    outer_desc: str) -> None:
        if not outer_axes or not body_keys:
            return
        region = model.region_of(body_keys)
        for key in sorted(region):
            fi = model.index.functions.get(key)
            if fi is None:
                continue
            mi = model.index.modules[fi.modname]
            for _, bare, call in fi.calls:
                rebound: List[str] = []
                if bare in ("vmap", "pmap"):
                    env = OrderedEnv(mi, fi)
                    for kw in call.keywords:
                        if kw.arg == "axis_name":
                            s = _str_const(model.index, mi, env, kw.value)
                            if s is not None and s in outer_axes:
                                rebound.append(s)
                elif bare == "shard_map":
                    inner = site_by_call.get(id(call))
                    if inner is not None:
                        rebound = sorted(_site_axes(inner) & outer_axes)
                for axis in rebound:
                    if (id(call), axis) in reported:
                        continue
                    reported.add((id(call), axis))
                    findings.append(Finding(
                        "PS305", "warning", mi.rel, call.lineno,
                        call.col_offset, fi.qualname,
                        f"axis '{axis}' rebound by nested `{bare}` inside "
                        f"a scope that already binds it ({outer_desc}) — "
                        f"collectives over '{axis}' silently target the "
                        f"innermost binding",
                        hint="rename the inner axis_name, or lift the "
                             "nested mapping out of the shard_map body",
                        detail=f"axis-shadow:{bare}:{axis}"))

    for site in model.shard_map_sites:
        scan_region(_site_axes(site), site.body_keys,
                    f"shard_map at {site.mi.rel}:{site.qualname}")
    for v in model.vmap_sites:
        scan_region({v.axis_name}, v.body_keys,
                    f"vmap at {v.mi.rel}:{v.qualname}")


# ---------------------------------------------------------------------------
# PS306 — unsanitized spec under a configurable mesh
# ---------------------------------------------------------------------------

def _check_ps306(model: MeshModel, findings: List[Finding]) -> None:
    for site in model.sharding_sites:
        spec = site.spec
        if spec is None or spec.sanitized:
            continue
        env = site.env
        configurable = env is not None and env.ambient
        mesh_known = env is not None and env.complete and not env.ambient
        if spec.layer_declared and (configurable or env is None):
            findings.append(Finding(
                "PS306", "warning", site.mi.rel, site.line,
                site.call.col_offset, site.qualname,
                "layer-declared `_sharding_spec` reaches NamedSharding "
                "without sanitize_spec — under a mesh missing one of its "
                "axes this raises at placement time",
                hint="wrap the spec: sanitize_spec(mesh, spec) drops "
                     "axis names the mesh does not have",
                detail="unsanitized-layer-spec"))
        elif spec.axes and configurable:
            findings.append(Finding(
                "PS306", "warning", site.mi.rel, site.line,
                site.call.col_offset, site.qualname,
                f"spec {spec.text()} names axes {sorted(spec.axes)} but "
                f"the mesh comes from runtime configuration "
                f"({env.source}) — a configured mesh lacking one of "
                f"them fails at placement time",
                hint="sanitize_spec(mesh, spec) before placing, or "
                     "construct the mesh this spec assumes",
                detail=f"unsanitized-spec:{':'.join(sorted(spec.axes))}"))
        elif spec.axes and mesh_known:
            missing = sorted(spec.axes - set(env.axes))
            if missing:
                findings.append(Finding(
                    "PS306", "warning", site.mi.rel, site.line,
                    site.call.col_offset, site.qualname,
                    f"spec {spec.text()} names ax"
                    f"{'es' if len(missing) > 1 else 'is'} "
                    f"{', '.join(repr(m) for m in missing)} that the "
                    f"{env.source} mesh ({list(env.axes)}) does not have",
                    hint="fix the axis name or sanitize_spec() the spec "
                         "for this mesh",
                    detail=f"missing-axis:{':'.join(missing)}"))


# ---------------------------------------------------------------------------
# entry point
# ---------------------------------------------------------------------------

def run(index: PackageIndex, cfg: Config) -> List[Finding]:
    wanted = [r for r in ("PS301", "PS302", "PS303", "PS304", "PS305",
                          "PS306") if cfg.wants(r)]
    if not wanted:
        return []
    model = build_mesh_model(index)
    findings: List[Finding] = []

    if cfg.wants("PS301"):
        _check_ps301(model, findings)
    if cfg.wants("PS302"):
        for site in model.shard_map_sites:
            _check_ps302(site, findings)
    if cfg.wants("PS303"):
        reported: Set[int] = set()
        for mi, qualname, spec in model.spec_literals:
            _check_ps303_spec(mi.rel, qualname, spec, findings, reported)
        for site in model.shard_map_sites:
            if site.in_specs and site.in_specs_seq \
                    and site.arg_exprs is not None:
                env = OrderedEnv(site.mi, site.fi)
                for i, spec in enumerate(site.in_specs):
                    if i < len(site.arg_exprs) and spec.resolved:
                        rank = literal_rank(index, site.mi, env,
                                            site.arg_exprs[i])
                        _check_ps303_rank(site.mi.rel, site.qualname, spec,
                                          rank, f"argument {i}", findings,
                                          reported)
        for ssite in model.sharding_sites:
            if ssite.spec is not None and ssite.placed_expr is not None:
                env = OrderedEnv(ssite.mi, ssite.fi)
                rank = literal_rank(index, ssite.mi, env, ssite.placed_expr)
                _check_ps303_rank(ssite.mi.rel, ssite.qualname, ssite.spec,
                                  rank, "the placed array", findings,
                                  reported)
    if cfg.wants("PS304"):
        reported_div: Set[Tuple[int, int]] = set()
        for site in model.shard_map_sites:
            if site.in_specs and site.in_specs_seq and site.env is not None:
                env = OrderedEnv(site.mi, site.fi)
                for i, spec in enumerate(site.in_specs):
                    if not spec.resolved:
                        continue
                    shape = None
                    if site.arg_exprs is not None \
                            and i < len(site.arg_exprs):
                        shape = literal_shape(index, site.mi, env,
                                              site.arg_exprs[i])
                    _check_ps304_pair(site.mi.rel, site.qualname,
                                      site.env, spec, shape, findings,
                                      reported_div)
        for ssite in model.sharding_sites:
            if ssite.spec is not None and ssite.env is not None:
                env = OrderedEnv(ssite.mi, ssite.fi)
                shape = literal_shape(index, ssite.mi, env,
                                      ssite.placed_expr) \
                    if ssite.placed_expr is not None else None
                _check_ps304_pair(ssite.mi.rel, ssite.qualname, ssite.env,
                                  ssite.spec, shape, findings, reported_div)
    if cfg.wants("PS305"):
        _check_ps305(model, findings)
    if cfg.wants("PS306"):
        _check_ps306(model, findings)
    return findings
