"""Worker for the executed multi-host test (SURVEY §3.1 / §5.8 DCN half):
launched by python -m paddle_tpu.distributed.launch on 2 simulated hosts;
each process owns 4 virtual CPU devices, init_parallel_env (via
mh_bootstrap) bridges the TCPStore rendezvous into
jax.distributed.initialize, and a psum runs across all 8 global devices."""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import mh_bootstrap  # noqa: F401  (env + jax.distributed init, pre-jax)

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jax.experimental.shard_map import shard_map

pid = jax.process_index()
mesh = Mesh(jax.devices(), ("x",))
data = jnp.arange(4.0) + 10 * pid
g = jax.make_array_from_process_local_data(
    NamedSharding(mesh, P("x")), data)
out = shard_map(lambda v: jax.lax.psum(v, "x"), mesh=mesh,
                in_specs=P("x"), out_specs=P())(g)
val = float(out[0])
expected = sum(float(i + 10 * p) for p in range(jax.process_count())
               for i in range(4))
assert val == expected, (val, expected)

with open(os.path.join(os.environ["MH_OUT"],
                       f"ok.{os.environ['PADDLE_TRAINER_ID']}"), "w") as f:
    f.write(f"{val}")
print("PSUM OK", val, flush=True)
