"""paddlelint (paddle_tpu.analysis): per-rule true-positive/negative
fixtures, suppression comments, baseline round-trip, the whole-repo CI
gate, and seeded-defect detection in scratch copies of real modules.

The fixtures are the rule contract: each PTxxx has at least one snippet
the rule MUST flag and one structurally-similar snippet it must NOT flag
(the negative encodes the false-positive class the analyzer was tuned
against — shape branches, split-then-use keys, lock-guarded writes)."""

import json
import os
import shutil
import textwrap

import pytest

from paddle_tpu.analysis import (Config, analyze_paths, analyze_source,
                                 load_baseline, save_baseline,
                                 split_baseline)
from paddle_tpu.analysis.cli import main as lint_main

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _lint(src, **cfg_kw):
    return analyze_source(textwrap.dedent(src), Config(**cfg_kw))


def _rules(findings):
    return sorted({f.rule for f in findings})


# ---------------------------------------------------------------- PT001

class TestPT001TracerLeak:
    def test_branch_on_traced_value(self):
        fs = _lint("""
            import jax

            @jax.jit
            def f(x):
                if x > 0:
                    return x
                return x * 2
        """)
        assert _rules(fs) == ["PT001"]
        assert fs[0].severity == "error"
        assert "branch" in fs[0].detail

    def test_host_conversion_of_traced_value(self):
        fs = _lint("""
            import jax

            @jax.jit
            def f(x):
                return float(x) * 2
        """)
        assert _rules(fs) == ["PT001"]
        assert "float" in fs[0].detail

    def test_item_on_traced_value(self):
        fs = _lint("""
            import jax

            @jax.jit
            def f(x):
                y = x + 1
                return y.item()
        """)
        assert _rules(fs) == ["PT001"]

    def test_taint_propagates_through_local_call(self):
        # interprocedural: leak is in a helper only reachable with a
        # traced argument
        fs = _lint("""
            import jax

            def helper(v):
                if v > 0:
                    return v
                return -v

            @jax.jit
            def f(x):
                return helper(x * 2)
        """)
        assert "PT001" in _rules(fs)
        assert any(f.qualname == "helper" for f in fs)

    def test_shape_branch_is_not_a_leak(self):
        # .shape / .ndim / len() are static under trace
        fs = _lint("""
            import jax

            @jax.jit
            def f(x):
                if x.shape[0] > 1 and x.ndim == 2:
                    return x * 2
                return x
        """)
        assert "PT001" not in _rules(fs)

    def test_static_argnums_param_exempt(self):
        fs = _lint("""
            import functools
            import jax

            @functools.partial(jax.jit, static_argnums=(1,))
            def f(x, mode):
                if mode == "fast":
                    return x * 2
                return x
        """)
        assert "PT001" not in _rules(fs)

    def test_isinstance_guard_exempts_name(self):
        fs = _lint("""
            import jax

            @jax.jit
            def f(x, s=None):
                if isinstance(s, int) and s == 0:
                    return x
                return x * 2
        """)
        assert "PT001" not in _rules(fs)


# ---------------------------------------------------------------- PT002

class TestPT002RetraceHazard:
    def test_jit_inside_loop(self):
        fs = _lint("""
            import jax

            def build(fns):
                outs = []
                for fn in fns:
                    outs.append(jax.jit(fn))
                return outs
        """)
        assert _rules(fs) == ["PT002"]
        assert "jit-in-loop" in fs[0].detail

    def test_unhashable_static_argnums(self):
        fs = _lint("""
            import jax

            def build(fn):
                return jax.jit(fn, static_argnums={1, 2})
        """)
        assert _rules(fs) == ["PT002"]
        assert "static-args" in fs[0].detail

    def test_module_level_jit_ok(self):
        fs = _lint("""
            import jax

            def step(x):
                return x * 2

            jitted = jax.jit(step)
        """)
        assert "PT002" not in _rules(fs)

    def test_shape_branch_reported_only_under_strict(self):
        src = """
            import jax

            @jax.jit
            def f(x):
                if x.shape[0] > 1:
                    return x * 2
                return x
        """
        assert "PT002" not in _rules(_lint(src))
        strict = [f for f in _lint(src, strict=True) if f.rule == "PT002"]
        assert strict and strict[0].severity == "info"


# ---------------------------------------------------------------- PT003

class TestPT003HostSync:
    def test_sync_in_hot_entry(self):
        fs = _lint("""
            class Trainer:
                def training_step(self, batch):
                    loss = self.step(batch)
                    return loss.item()
        """)
        assert _rules(fs) == ["PT003"]
        assert "sync" in fs[0].detail

    def test_sync_reachable_from_hot_entry(self):
        fs = _lint("""
            def _log(loss):
                return float(loss.numpy())

            def training_step(batch):
                loss = batch * 2
                return _log(loss)
        """)
        assert "PT003" in _rules(fs)
        assert any(f.qualname == "_log" for f in fs)

    def test_sync_outside_hot_region_ok(self):
        fs = _lint("""
            def summarize(loss):
                return loss.item()

            def unrelated(batch):
                return summarize(batch)
        """)
        assert "PT003" not in _rules(fs)


# ---------------------------------------------------------------- PT004

class TestPT004RngHygiene:
    def test_key_reuse(self):
        fs = _lint("""
            import jax

            def sample(key):
                a = jax.random.normal(key, (2,))
                b = jax.random.uniform(key, (2,))
                return a + b
        """)
        assert _rules(fs) == ["PT004"]
        assert "key-reuse" in fs[0].detail

    def test_split_then_use_ok(self):
        fs = _lint("""
            import jax

            def sample(key):
                key, sub = jax.random.split(key)
                a = jax.random.normal(sub, (2,))
                key, sub = jax.random.split(key)
                b = jax.random.uniform(sub, (2,))
                return a + b
        """)
        assert "PT004" not in _rules(fs)

    def test_host_rng_in_traced_code(self):
        fs = _lint("""
            import jax
            import numpy as np

            @jax.jit
            def f(x):
                noise = np.random.randn(4)
                return x + noise
        """)
        assert "PT004" in _rules(fs)
        assert any("host-rng" in f.detail for f in fs)

    def test_host_rng_outside_trace_ok(self):
        fs = _lint("""
            import numpy as np

            def make_batch(n):
                return np.random.randn(n, 4)
        """)
        assert "PT004" not in _rules(fs)


# ---------------------------------------------------------------- PT005

class TestPT005FlagsAtTraceTime:
    def test_flags_guard_in_traced_function(self):
        fs = _lint("""
            import jax
            from paddle_tpu.flags import flags_guard

            @jax.jit
            def f(x):
                with flags_guard(flash_impl="composite"):
                    return x * 2
        """)
        assert _rules(fs) == ["PT005"]
        assert "flags" in fs[0].detail

    def test_set_flags_in_traced_function(self):
        fs = _lint("""
            import jax
            import paddle_tpu

            @jax.jit
            def f(x):
                paddle_tpu.set_flags({"FLAGS_flash_impl": "intree"})
                return x * 2
        """)
        assert _rules(fs) == ["PT005"]

    def test_flags_outside_trace_ok(self):
        fs = _lint("""
            import paddle_tpu

            def configure():
                paddle_tpu.set_flags({"FLAGS_flash_impl": "intree"})
        """)
        assert "PT005" not in _rules(fs)


# ---------------------------------------------------------------- PT006

class TestPT006SharedState:
    def test_unguarded_global_write_from_thread(self):
        fs = _lint("""
            import threading

            _events = []
            _count = 0

            def _worker():
                global _count
                _count += 1
                _events.append("tick")

            def start():
                threading.Thread(target=_worker, daemon=True).start()
        """)
        assert _rules(fs) == ["PT006"]
        assert {f.detail for f in fs} == {"write:_count", "write:_events"}

    def test_lock_guarded_write_ok(self):
        fs = _lint("""
            import threading

            _lock = threading.Lock()
            _count = 0

            def _worker():
                global _count
                with _lock:
                    _count += 1

            def start():
                threading.Thread(target=_worker, daemon=True).start()
        """)
        assert "PT006" not in _rules(fs)

    def test_local_rebind_ok(self):
        # a local that shadows a module global is not shared state
        fs = _lint("""
            import threading

            _count = 0

            def _worker():
                _count = 1
                return _count

            def start():
                threading.Thread(target=_worker, daemon=True).start()
        """)
        assert "PT006" not in _rules(fs)

    def test_same_write_outside_thread_region_ok(self):
        fs = _lint("""
            _events = []

            def record(e):
                _events.append(e)
        """)
        assert "PT006" not in _rules(fs)

    def test_trace_ring_exporter_unguarded_flagged(self):
        # the observability.tracing background-exporter shape with the
        # lock REMOVED: flush thread drains a module-level ring — PT006
        fs = _lint("""
            import threading

            _ring = []

            def _flush_loop():
                while _ring:
                    _ring.pop()

            def start_exporter():
                threading.Thread(target=_flush_loop,
                                 daemon=True).start()
        """)
        assert "PT006" in _rules(fs)
        assert any(f.detail == "write:_ring" for f in fs)

    def test_trace_ring_exporter_lock_guarded_ok(self):
        # the shipped recorder discipline: every ring access from the
        # flush thread sits under the one module lock
        fs = _lint("""
            import threading

            _lock = threading.Lock()
            _ring = []

            def _flush_loop():
                with _lock:
                    while _ring:
                        _ring.pop()

            def start_exporter():
                threading.Thread(target=_flush_loop,
                                 daemon=True).start()
        """)
        assert "PT006" not in _rules(fs)


# ----------------------------------------------------------- suppression

class TestSuppression:
    LEAKY = """
        import jax

        @jax.jit
        def f(x):
            if x > 0:{comment}
                return x
            return x * 2
    """

    def test_line_suppression(self):
        src = self.LEAKY.format(comment="  # paddlelint: disable=PT001")
        assert _lint(src) == []

    def test_wrong_rule_does_not_suppress(self):
        src = self.LEAKY.format(comment="  # paddlelint: disable=PT003")
        assert _rules(_lint(src)) == ["PT001"]

    def test_file_wide_suppression(self):
        src = ("# paddlelint: disable-file=PT001\n"
               + textwrap.dedent(self.LEAKY.format(comment="")))
        assert analyze_source(src, Config()) == []

    def test_disable_all(self):
        src = self.LEAKY.format(comment="  # paddlelint: disable=all")
        assert _lint(src) == []


# -------------------------------------------------------------- baseline

class TestBaseline:
    def _findings(self):
        return _lint("""
            import jax

            @jax.jit
            def f(x):
                return float(x)
        """)

    def test_round_trip(self, tmp_path):
        fs = self._findings()
        path = str(tmp_path / "baseline.json")
        save_baseline(path, fs, {fs[0].baseline_key: "accepted: legacy"})
        loaded = load_baseline(path)
        assert loaded == {fs[0].baseline_key: "accepted: legacy"}
        fresh, stale = split_baseline(fs, loaded)
        assert fresh == [] and stale == []

    def test_key_is_line_number_free(self):
        a = self._findings()[0]
        b = _lint("""
            import jax

            # shifted down by a comment block: the baseline key must
            # not move with the line number
            @jax.jit
            def f(x):
                return float(x)
        """)[0]
        assert a.line != b.line
        assert a.baseline_key == b.baseline_key

    def test_split_reports_fresh_and_stale(self, tmp_path):
        fs = self._findings()
        fresh, stale = split_baseline(fs, {"PT999|gone.py|f|x": "old"})
        assert [f.rule for f in fresh] == ["PT001"]
        assert stale == ["PT999|gone.py|f|x"]

    def test_missing_justification_stamped(self, tmp_path):
        fs = self._findings()
        path = str(tmp_path / "baseline.json")
        save_baseline(path, fs, {})
        with open(path) as f:
            data = json.load(f)
        assert data["entries"][0]["justification"] == "TODO: justify"


# ------------------------------------------------------------------ CLI

class TestCli:
    def _write(self, tmp_path, src):
        p = tmp_path / "mod.py"
        p.write_text(textwrap.dedent(src))
        return str(p)

    LEAKY = """
        import jax

        @jax.jit
        def f(x):
            return float(x)
    """

    def test_exit_one_on_findings(self, tmp_path, capsys):
        assert lint_main([self._write(tmp_path, self.LEAKY)]) == 1
        out = capsys.readouterr().out
        assert "PT001" in out and "1 finding(s)" in out

    def test_exit_zero_when_clean(self, tmp_path, capsys):
        assert lint_main([self._write(tmp_path, "x = 1\n")]) == 0

    def test_json_output(self, tmp_path, capsys):
        assert lint_main(["--json", self._write(tmp_path, self.LEAKY)]) == 1
        data = json.loads(capsys.readouterr().out)
        assert data["findings"][0]["rule"] == "PT001"
        assert "PT001" in data["rules"]

    def test_baseline_gates_to_zero(self, tmp_path, capsys):
        mod = self._write(tmp_path, self.LEAKY)
        base = str(tmp_path / "base.json")
        assert lint_main([mod, "--baseline", base,
                          "--write-baseline"]) == 0
        capsys.readouterr()
        assert lint_main([mod, "--baseline", base]) == 0
        assert "1 baselined" in capsys.readouterr().out

    def test_stale_baseline_reported(self, tmp_path, capsys):
        mod = self._write(tmp_path, self.LEAKY)
        base = str(tmp_path / "base.json")
        assert lint_main([mod, "--baseline", base,
                          "--write-baseline"]) == 0
        clean = self._write(tmp_path, "x = 1\n")
        capsys.readouterr()
        assert lint_main([clean, "--baseline", base]) == 0
        assert "stale baseline" in capsys.readouterr().out
        assert lint_main([clean, "--baseline", base,
                          "--fail-stale"]) == 1

    def test_rules_subset(self, tmp_path):
        mod = self._write(tmp_path, self.LEAKY)
        assert lint_main(["--rules", "PT006", mod]) == 0
        assert lint_main(["--rules", "PT001", mod]) == 1

    def test_unknown_rule_is_usage_error(self, tmp_path):
        assert lint_main(["--rules", "PT999",
                          self._write(tmp_path, "x = 1\n")]) == 2


# ------------------------------------------------- whole-repo CI gate

class TestRepoGate:
    def test_package_clean_against_baseline(self, capsys):
        """The tier-1 gate: paddlelint over paddle_tpu/ must produce zero
        non-baselined findings (same invocation as tools/paddlelint.py)."""
        rc = lint_main([os.path.join(REPO, "paddle_tpu"), "--baseline",
                        os.path.join(REPO, "tools",
                                     "paddlelint_baseline.json")])
        out = capsys.readouterr().out
        assert rc == 0, f"paddlelint gate failed:\n{out}"
        assert "0 finding(s)" in out

    def test_baseline_entries_are_justified(self):
        base = load_baseline(os.path.join(
            REPO, "tools", "paddlelint_baseline.json"))
        for key, justification in base.items():
            assert justification and "TODO" not in justification, key


# ------------------------------------------- seeded-defect detection

class TestSeededDefects:
    """Acceptance check: the analyzer must catch a tracer leak and an
    unguarded shared-state write seeded into scratch copies of the real
    modules it is meant to police."""

    def _scratch(self, tmp_path, rel, appended):
        dst = tmp_path / os.path.basename(rel)
        shutil.copy(os.path.join(REPO, rel), dst)
        with open(dst, "a") as f:
            f.write(textwrap.dedent(appended))
        return str(dst)

    def test_seeded_tracer_leak_in_trainer(self, tmp_path):
        clean = analyze_paths(
            [self._scratch(tmp_path, "paddle_tpu/trainer/trainer.py", "")])
        seeded = analyze_paths([self._scratch(
            tmp_path, "paddle_tpu/trainer/trainer.py", """

            import jax as _seeded_jax

            @_seeded_jax.jit
            def _seeded_step(loss):
                if loss > 0:
                    return loss
                return float(loss)
            """)])
        new = {f.baseline_key for f in seeded} - {f.baseline_key
                                                  for f in clean}
        hits = [f for f in seeded if f.baseline_key in new
                and f.rule == "PT001" and f.qualname == "_seeded_step"]
        assert len(hits) == 2  # the branch AND the float()

    def test_seeded_unguarded_write_in_watchdog(self, tmp_path):
        clean = analyze_paths([self._scratch(
            tmp_path, "paddle_tpu/distributed/watchdog.py", "")])
        assert not [f for f in clean if f.rule == "PT006"]
        seeded = analyze_paths([self._scratch(
            tmp_path, "paddle_tpu/distributed/watchdog.py", """

            _seeded_flight_log = []

            def _seeded_recorder_loop():
                _seeded_flight_log.append("tick")

            def _seeded_start_recorder():
                threading.Thread(target=_seeded_recorder_loop,
                                 daemon=True).start()
            """)])
        hits = [f for f in seeded if f.rule == "PT006"
                and f.qualname == "_seeded_recorder_loop"]
        assert len(hits) == 1
        assert hits[0].detail == "write:_seeded_flight_log"
