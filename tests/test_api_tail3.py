"""Flat-namespace batch 3 (VERDICT r2 item 5): framework compat
(iinfo/finfo/places/ParamAttr/create_parameter/LazyGuard), tensor tail3
ops + in-place family, regularizer, DataParallel passthrough, and the
checklist generator's count invariants."""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core.tensor import Tensor


class TestDtypeInfo:
    def test_iinfo(self):
        ii = paddle.iinfo(paddle.int32)
        assert ii.max == 2**31 - 1 and ii.min == -2**31 and ii.bits == 32
        assert paddle.iinfo("int8").max == 127

    def test_finfo(self):
        fi = paddle.finfo(paddle.bfloat16)
        assert fi.bits == 16 and fi.eps == pytest.approx(0.0078125)
        f32 = paddle.finfo("float32")
        assert f32.max == pytest.approx(3.4028235e38, rel=1e-6)

    def test_dtype_class(self):
        assert isinstance(paddle.float32, paddle.dtype)
        assert isinstance(paddle.bool, paddle.dtype)


class TestPlaces:
    def test_place_identity(self):
        assert paddle.CPUPlace() == paddle.CPUPlace()
        assert paddle.CUDAPlace(0) == paddle.CUDAPlace(0)
        assert paddle.CUDAPlace(0) != paddle.CUDAPlace(1)
        assert paddle.CPUPlace() != paddle.CUDAPlace(0)
        assert paddle.CustomPlace("tpu", 0).get_device_type() == "tpu"
        assert "gpu:1" in repr(paddle.CUDAPlace(1))

    def test_compile_info(self):
        assert paddle.is_compiled_with_cuda() is False
        assert paddle.is_compiled_with_custom_device("tpu") is True
        assert paddle.is_compiled_with_distribute() is True


class TestParamAttr:
    def test_create_parameter_with_attr(self):
        init = paddle.nn.initializer.Constant(3.0)
        p = paddle.create_parameter(
            [2, 4], attr=paddle.ParamAttr(initializer=init,
                                          learning_rate=0.5,
                                          trainable=True))
        np.testing.assert_allclose(np.asarray(p.numpy()), 3.0)
        assert p.optimize_attr["learning_rate"] == 0.5
        assert not p.stop_gradient

    def test_attr_polymorphism(self):
        from paddle_tpu.framework.param_attr import ParamAttr
        assert ParamAttr._to_attr(None) is None
        assert ParamAttr._to_attr(False) is None
        assert ParamAttr._to_attr("w0").name == "w0"
        a = ParamAttr(name="x")
        assert ParamAttr._to_attr(a) is a

    def test_is_bias_default_zero(self):
        p = paddle.create_parameter([4], is_bias=True)
        np.testing.assert_allclose(np.asarray(p.numpy()), 0.0)


class TestLazyGuard:
    def test_lazy_then_materialize(self):
        import jax
        from paddle_tpu.framework.lazy import materialize
        paddle.seed(7)
        with paddle.LazyGuard():
            net = paddle.nn.Sequential(
                paddle.nn.Linear(8, 16), paddle.nn.Linear(16, 4))
        for _, p in net.named_parameters():
            assert isinstance(p._data, jax.ShapeDtypeStruct)
        materialize(net)
        for _, p in net.named_parameters():
            assert isinstance(p._data, jax.Array)
        w = np.asarray(net[0].weight.numpy())
        assert w.std() > 0  # initializer actually ran
        # and the materialized net runs
        y = net(paddle.ones([2, 8]))
        assert y.shape == [2, 4]

    def test_materialize_with_shard_fn(self):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P
        from paddle_tpu.framework.lazy import materialize
        from paddle_tpu.distributed.mesh import build_hybrid_mesh
        mesh = build_hybrid_mesh(dp_degree=8)
        with paddle.LazyGuard():
            lin = paddle.nn.Linear(16, 8)

        def shard_fn(name, p):
            if "weight" in name and p._data.shape[0] % 8 == 0:
                return NamedSharding(mesh, P("dp", None))
            return None
        materialize(lin, shard_fn=shard_fn)
        assert not lin.weight._data.sharding.is_fully_replicated
        assert lin.bias._data.sharding.is_fully_replicated

    def test_direct_bind_wins_over_lazy(self):
        import jax.numpy as jnp
        from paddle_tpu.framework.lazy import materialize
        with paddle.LazyGuard():
            lin = paddle.nn.Linear(3, 3)
        lin.weight._data = jnp.full((3, 3), 7.0)  # explicit init
        materialize(lin)
        np.testing.assert_allclose(np.asarray(lin.weight.numpy())[0, 0], 7.0)


class TestTail3Ops:
    def test_reduce_as(self):
        x = paddle.to_tensor(np.arange(12, dtype=np.float32).reshape(3, 4))
        t = paddle.ones([4])
        np.testing.assert_allclose(np.asarray(paddle.reduce_as(x, t).numpy()),
                                   np.arange(12.).reshape(3, 4).sum(0))
        t2 = paddle.ones([3, 1])
        np.testing.assert_allclose(
            np.asarray(paddle.reduce_as(x, t2).numpy()),
            np.arange(12.).reshape(3, 4).sum(1, keepdims=True))

    def test_reduce_as_grad(self):
        x = paddle.to_tensor(np.ones((2, 3), np.float32))
        x.stop_gradient = False
        y = paddle.reduce_as(x, paddle.ones([3]))
        y.sum().backward()
        np.testing.assert_allclose(np.asarray(x.grad.numpy()), 1.0)

    def test_binomial(self):
        paddle.seed(3)
        n = paddle.to_tensor(np.full((2000,), 20, np.float32))
        p = paddle.to_tensor(np.full((2000,), 0.25, np.float32))
        s = np.asarray(paddle.binomial(n, p).numpy())
        assert s.min() >= 0 and s.max() <= 20
        assert abs(s.mean() - 5.0) < 0.35

    def test_log_normal(self):
        paddle.seed(4)
        s = np.asarray(paddle.log_normal(
            mean=0.0, std=0.25, shape=[4000]).numpy())
        assert (s > 0).all()
        assert abs(np.log(s).mean()) < 0.05

    def test_inplace_comparison_and_logical(self):
        x = paddle.to_tensor(np.array([1.0, 2.0, 3.0], np.float32))
        y = paddle.to_tensor(np.array([1.0, 9.0, 3.0], np.float32))
        out = paddle.equal_(x, y)
        assert out is x
        np.testing.assert_array_equal(np.asarray(x.numpy()),
                                      [True, False, True])
        a = paddle.to_tensor(np.array([True, False]))
        paddle.logical_or_(a, paddle.to_tensor(np.array([False, True])))
        np.testing.assert_array_equal(np.asarray(a.numpy()), [True, True])

    def test_inplace_math_batch3(self):
        x = paddle.to_tensor(np.array([4.0, 9.0], np.float32))
        paddle.square_(x)
        np.testing.assert_allclose(np.asarray(x.numpy()), [16., 81.])
        z = paddle.to_tensor(np.array([[1., 2.], [3., 4.]], np.float32))
        paddle.t_(z)
        np.testing.assert_allclose(np.asarray(z.numpy()),
                                   [[1., 3.], [2., 4.]])
        w = paddle.to_tensor(np.array([1.0, -1.0], np.float32))
        paddle.where_(paddle.to_tensor(np.array([True, False])), w,
                      paddle.zeros([2]))
        np.testing.assert_allclose(np.asarray(w.numpy()), [1.0, 0.0])

    def test_addmm_(self):
        inp = paddle.ones([2, 2])
        paddle.addmm_(inp, paddle.ones([2, 3]), paddle.ones([3, 2]),
                      beta=2.0, alpha=1.0)
        np.testing.assert_allclose(np.asarray(inp.numpy()), 5.0)

    def test_inplace_refuses_grad(self):
        x = paddle.ones([3])
        x.stop_gradient = False
        with pytest.raises(RuntimeError, match="in-place"):
            paddle.square_(x)

    def test_bernoulli_(self):
        paddle.seed(5)
        x = paddle.zeros([1000])
        paddle.bernoulli_(x, p=0.3)
        m = float(np.asarray(x.numpy()).mean())
        assert 0.2 < m < 0.4

    def test_tensor_apply(self):
        x = paddle.to_tensor(np.array([1.0, 2.0], np.float32))
        y = x.apply(lambda t: t * 3)
        np.testing.assert_allclose(np.asarray(y.numpy()), [3., 6.])
        x.apply_(lambda t: t + 1)
        np.testing.assert_allclose(np.asarray(x.numpy()), [2., 3.])


class TestReviewRegressions:
    """Round-3 code-review findings, pinned."""

    def test_finfo_float8(self):
        fi = paddle.finfo(paddle.float8_e4m3fn)
        assert fi.bits == 8 and fi.max == pytest.approx(448.0)
        assert paddle.finfo(paddle.float8_e5m2).bits == 8

    def test_optimizer_honors_l2decay_object(self):
        w0 = np.full((2,), 10.0, np.float32)
        outs = {}
        for wd in (0.1, paddle.regularizer.L2Decay(0.1)):
            p = paddle.create_parameter(
                [2], attr=paddle.ParamAttr(
                    initializer=paddle.nn.initializer.Constant(10.0)))
            p._grad = paddle.zeros([2])
            opt = paddle.optimizer.SGD(learning_rate=1.0, parameters=[p],
                                       weight_decay=wd)
            opt.step()
            outs[str(wd)] = np.asarray(p.numpy())
        # both forms: w - lr * wd * w = 10 - 1*0.1*10 = 9
        for k, v in outs.items():
            np.testing.assert_allclose(v, 9.0, rtol=1e-6, err_msg=k)

    def test_param_regularizer_overrides_optimizer_wd(self):
        p = paddle.create_parameter(
            [2], attr=paddle.ParamAttr(
                initializer=paddle.nn.initializer.Constant(10.0),
                regularizer=paddle.regularizer.L1Decay(0.5)))
        p._grad = paddle.zeros([2])
        opt = paddle.optimizer.SGD(learning_rate=1.0, parameters=[p],
                                   weight_decay=0.3)
        opt.step()
        # L1 term only: w - lr * 0.5 * sign(w) = 9.5 (0.3 L2 skipped)
        np.testing.assert_allclose(np.asarray(p.numpy()), 9.5, rtol=1e-6)

    def test_param_lr_multiplier(self):
        p = paddle.create_parameter(
            [2], attr=paddle.ParamAttr(
                initializer=paddle.nn.initializer.Constant(1.0),
                learning_rate=0.1))
        p._grad = paddle.ones([2])
        opt = paddle.optimizer.SGD(learning_rate=1.0, parameters=[p])
        opt.step()
        np.testing.assert_allclose(np.asarray(p.numpy()), 0.9, rtol=1e-6)

    def test_need_clip_false_excluded_from_global_norm(self):
        from paddle_tpu.nn.clip import ClipGradByGlobalNorm
        a = paddle.create_parameter([1], attr=paddle.ParamAttr(
            initializer=paddle.nn.initializer.Constant(0.0)))
        b = paddle.create_parameter([1], attr=paddle.ParamAttr(
            initializer=paddle.nn.initializer.Constant(0.0),
            need_clip=False))
        ga = paddle.to_tensor(np.array([3.0], np.float32))
        gb = paddle.to_tensor(np.array([4.0], np.float32))
        out = ClipGradByGlobalNorm(1.0)([(a, ga), (b, gb)])
        # norm counts only a's grad (3.0): a scaled to 1.0, b untouched
        np.testing.assert_allclose(np.asarray(out[0][1].numpy()), [1.0],
                                   rtol=1e-5)
        np.testing.assert_allclose(np.asarray(out[1][1].numpy()), [4.0])

    def test_lazy_access_clear_error(self):
        with paddle.LazyGuard():
            lin = paddle.nn.Linear(3, 3)
        assert "uninitialized" in repr(lin.weight)
        with pytest.raises(RuntimeError, match="materialize"):
            lin.weight.numpy()
        with pytest.raises(RuntimeError, match="materialize"):
            _ = lin.weight.place

    def test_bitwise_invert_method(self):
        x = paddle.to_tensor(np.array([0, 1], np.int32))
        x.bitwise_invert_()
        np.testing.assert_array_equal(np.asarray(x.numpy()), [-1, -2])

    def test_apply_refuses_grad(self):
        x = paddle.ones([2])
        x.stop_gradient = False
        with pytest.raises(RuntimeError, match="apply"):
            x.apply(lambda t: t * 2)

    def test_adamw_honors_l2decay_object_and_param_override(self):
        # AdamW(weight_decay=L2Decay(c)) must decay with coeff c...
        p1 = paddle.create_parameter([2], attr=paddle.ParamAttr(
            initializer=paddle.nn.initializer.Constant(10.0)))
        p1._grad = paddle.zeros([2])
        opt1 = paddle.optimizer.AdamW(
            learning_rate=1.0, parameters=[p1],
            weight_decay=paddle.regularizer.L2Decay(0.1))
        opt1.step()
        np.testing.assert_allclose(np.asarray(p1.numpy()), 9.0, rtol=1e-5)
        # ...and a param-level regularizer OVERRIDES the decoupled decay
        # (L2Decay(0.0) = "no decay on this param", the paddle idiom)
        p2 = paddle.create_parameter([2], attr=paddle.ParamAttr(
            initializer=paddle.nn.initializer.Constant(10.0),
            regularizer=paddle.regularizer.L2Decay(0.0)))
        p2._grad = paddle.zeros([2])
        opt2 = paddle.optimizer.AdamW(learning_rate=1.0, parameters=[p2],
                                      weight_decay=0.1)
        opt2.step()
        np.testing.assert_allclose(np.asarray(p2.numpy()), 10.0,
                                   rtol=1e-6)

    def test_layer_weight_attr_fields_bound(self):
        lin = paddle.nn.Linear(
            4, 2, weight_attr=paddle.ParamAttr(
                learning_rate=0.5, need_clip=False,
                regularizer=paddle.regularizer.L2Decay(1e-3)))
        w = lin.weight
        assert w.need_clip is False
        assert w.optimize_attr["learning_rate"] == 0.5
        assert isinstance(w.regularizer, paddle.regularizer.L2Decay)

    def test_sp_suppression_is_thread_local(self):
        import threading
        from paddle_tpu.distributed.parallel_layers import (
            _sp_state, suppress_sequence_parallel_annotations)
        seen = {}

        def other_thread():
            seen["off"] = getattr(_sp_state, "off", False)
        with suppress_sequence_parallel_annotations():
            t = threading.Thread(target=other_thread)
            t.start()
            t.join()
        assert seen["off"] is False


class TestRegularizer:
    def test_l1_l2_terms(self):
        import jax.numpy as jnp
        w = jnp.asarray([[1.0, -2.0], [3.0, -4.0]])
        l1 = paddle.regularizer.L1Decay(0.1)
        l2 = paddle.regularizer.L2Decay(0.1)
        assert float(l1.loss_term(w)) == pytest.approx(1.0)
        assert float(l2.loss_term(w)) == pytest.approx(1.5)
        np.testing.assert_allclose(np.asarray(l1.grad_term(w)),
                                   0.1 * np.sign(np.asarray(w)))
        np.testing.assert_allclose(np.asarray(l2.grad_term(w)),
                                   0.1 * np.asarray(w))


class TestDataParallel:
    def test_wrap_forward_and_state(self):
        net = paddle.nn.Linear(4, 2)
        dp = paddle.DataParallel(net)
        x = paddle.ones([3, 4])
        np.testing.assert_allclose(np.asarray(dp(x).numpy()),
                                   np.asarray(net(x).numpy()))
        assert set(dp.state_dict().keys()) == set(net.state_dict().keys())
        assert len(list(dp.parameters())) == len(list(net.parameters()))

    def test_scale_loss_on_mesh(self):
        from paddle_tpu.distributed.mesh import (build_hybrid_mesh,
                                                 mesh_context)
        net = paddle.nn.Linear(2, 2)
        dp = paddle.DataParallel(net)
        mesh = build_hybrid_mesh(dp_degree=8)
        with mesh_context(mesh):
            loss = paddle.to_tensor(np.float32(8.0))
            assert float(dp.scale_loss(loss).numpy()) == pytest.approx(1.0)


class TestMiscFramework:
    def test_batch_reader(self):
        def reader():
            yield from range(7)
        batches = list(paddle.batch(reader, batch_size=3)())
        assert batches == [[0, 1, 2], [3, 4, 5], [6]]
        batches = list(paddle.batch(reader, 3, drop_last=True)())
        assert batches == [[0, 1, 2], [3, 4, 5]]

    def test_cuda_rng_state_aliases(self):
        st = paddle.get_cuda_rng_state()
        paddle.set_cuda_rng_state(st)

    def test_version_module(self):
        assert paddle.version.full_version == paddle.__version__
        assert paddle.version.cuda() == "False"

    def test_sysconfig(self):
        import os
        assert os.path.isdir(paddle.sysconfig.get_include())

    def test_onnx_export_raises_with_pointer(self):
        with pytest.raises(NotImplementedError, match="save_inference_model"):
            paddle.onnx.export(paddle.nn.Linear(2, 2), "/tmp/x.onnx")

    def test_hub_local(self, tmp_path):
        (tmp_path / "hubconf.py").write_text(
            "def toy(scale=2):\n"
            "    'a toy entrypoint'\n"
            "    return scale * 21\n")
        assert paddle.hub.list(str(tmp_path)) == ["toy"]
        assert "toy entrypoint" in paddle.hub.help(str(tmp_path), "toy")
        assert paddle.hub.load(str(tmp_path), "toy", scale=2) == 42
        with pytest.raises(NotImplementedError):
            paddle.hub.load("github.com/x/y", "toy", source="github")

    def test_float8_dtypes(self):
        import jax.numpy as jnp
        assert paddle.float8_e4m3fn is jnp.float8_e4m3fn
        x = paddle.ones([2]).astype("float8_e5m2")
        assert "float8_e5m2" in str(x.dtype)

    def test_checklist_generator_runs(self, tmp_path):
        import subprocess, sys, os
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        r = subprocess.run([sys.executable, "tools/api_checklist.py"],
                           capture_output=True, text=True, cwd="/root/repo",
                           env=env, timeout=300)
        assert r.returncode == 0, r.stderr[-500:]
        assert "parity" in r.stdout
        n = int(r.stdout.split("wrote docs/API_CHECKLIST.md: ")[1]
                .split(" parity")[0])
        assert n >= 500, f"flat parity surface regressed to {n}"


class TestRound4Stragglers:
    """Round-4 additions: the last missing inplace variants + index_copy."""

    def test_new_inplace_variants(self):
        import numpy as np
        y = paddle.to_tensor(np.array([2.0, -3.0], np.float32))
        paddle.sign_(y)
        np.testing.assert_allclose(y.numpy(), [1.0, -1.0])
        z = paddle.to_tensor(np.array([180.0], np.float32))
        paddle.deg2rad_(z)
        np.testing.assert_allclose(z.numpy(), [np.pi], rtol=1e-6)
        w = paddle.to_tensor(np.array([np.pi], np.float32))
        paddle.rad2deg_(w)
        np.testing.assert_allclose(w.numpy(), [180.0], rtol=1e-6)
        a = paddle.to_tensor(np.array([1.0, 2.0], np.float32))
        paddle.atan2_(a, paddle.to_tensor(np.array([1.0, 1.0], np.float32)))
        np.testing.assert_allclose(a.numpy(), [np.arctan2(1, 1),
                                               np.arctan2(2, 1)], rtol=1e-6)
        s = paddle.to_tensor(np.array([0.5], np.float32))
        paddle.stanh_(s)
        b = paddle.to_tensor(np.array([1, 2], np.int32))
        paddle.bitwise_left_shift_(b, paddle.to_tensor(
            np.array([1, 2], np.int32)))
        np.testing.assert_array_equal(b.numpy(), [2, 8])
        c = paddle.to_tensor(np.array([8, 8], np.int32))
        paddle.bitwise_right_shift_(c, paddle.to_tensor(
            np.array([1, 2], np.int32)))
        np.testing.assert_array_equal(c.numpy(), [4, 2])
        n = paddle.to_tensor(np.array([1.0], np.float32))
        paddle.nextafter_(n, paddle.to_tensor(np.array([2.0], np.float32)))
        assert float(n.numpy()[0]) > 1.0

    def test_index_copy(self):
        import numpy as np
        x = paddle.to_tensor(np.zeros((4, 3), np.float32))
        v = paddle.to_tensor(np.full((2, 3), 5.0, np.float32))
        out = paddle.index_copy(x, paddle.to_tensor([0, 2]), 0, v)
        expect = np.zeros((4, 3), np.float32)
        expect[[0, 2]] = 5.0
        np.testing.assert_array_equal(out.numpy(), expect)
        # axis=1
        x2 = paddle.to_tensor(np.zeros((2, 4), np.float32))
        v2 = paddle.to_tensor(np.full((2, 1), 7.0, np.float32))
        out2 = paddle.index_copy(x2, paddle.to_tensor([3]), 1, v2)
        assert out2.numpy()[0, 3] == 7.0 and out2.numpy()[0, 0] == 0.0
        # inplace twin
        paddle.index_copy_(x, paddle.to_tensor([1]), 0,
                           paddle.to_tensor(np.full((1, 3), 9.0,
                                                    np.float32)))
        assert x.numpy()[1, 0] == 9.0

    def test_index_copy_gradients(self):
        import numpy as np
        x = paddle.to_tensor(np.ones((3, 2), np.float32))
        x.stop_gradient = False
        v = paddle.to_tensor(np.full((1, 2), 4.0, np.float32))
        v.stop_gradient = False
        out = paddle.index_copy(x, paddle.to_tensor([1]), 0, v)
        out.sum().backward()
        # overwritten row contributes no grad to x; v gets full grad
        np.testing.assert_array_equal(x.grad.numpy(),
                                      [[1, 1], [0, 0], [1, 1]])
        np.testing.assert_array_equal(v.grad.numpy(), [[1, 1]])
