"""Fused Layer classes (ref: python/paddle/incubate/nn/layer/
fused_transformer.py — FusedMultiHeadAttention/FusedFeedForward/
FusedTransformerEncoderLayer/FusedLinear)."""

import numpy as np

import paddle_tpu as paddle
from paddle_tpu.incubate.nn import (FusedFeedForward, FusedLinear,
                                    FusedMultiHeadAttention,
                                    FusedTransformerEncoderLayer)


def _x(B=2, S=8, E=16, seed=0):
    return paddle.to_tensor(
        np.random.RandomState(seed).randn(B, S, E).astype(np.float32))


def test_fused_linear():
    paddle.seed(0)
    lin = FusedLinear(16, 8)
    out = lin(_x())
    assert out.shape == [2, 8, 8]
    assert len(lin.parameters()) == 2


def test_fused_mha_shapes_and_residual_ln():
    paddle.seed(0)
    attn = FusedMultiHeadAttention(16, 4, dropout_rate=0.0,
                                   attn_dropout_rate=0.0)
    attn.eval()
    x = _x()
    out = attn(x)
    assert out.shape == [2, 8, 16]
    # post-LN output is normalized over the feature dim
    o = out.numpy()
    np.testing.assert_allclose(o.mean(-1), 0.0, atol=1e-4)
    np.testing.assert_allclose(o.std(-1), 1.0, atol=1e-2)


def test_fused_ffn_pre_vs_post_norm():
    paddle.seed(0)
    ffn_post = FusedFeedForward(16, 32, dropout_rate=0.0)
    ffn_post.eval()
    out = ffn_post(_x())
    np.testing.assert_allclose(out.numpy().mean(-1), 0.0, atol=1e-4)
    ffn_pre = FusedFeedForward(16, 32, dropout_rate=0.0,
                               normalize_before=True)
    ffn_pre.eval()
    out2 = ffn_pre(_x())
    assert out2.shape == [2, 8, 16]


def test_fused_encoder_layer_trains():
    paddle.seed(0)
    layer = FusedTransformerEncoderLayer(16, 4, 32, dropout_rate=0.0)
    layer.train()
    from paddle_tpu.optimizer import AdamW
    opt = AdamW(learning_rate=1e-2, parameters=layer.parameters())
    x = _x(seed=1)
    losses = []
    for _ in range(4):
        out = layer(x)
        loss = (out - 0.1).pow(2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss.numpy()))
    assert losses[-1] < losses[0]
    assert len(layer.parameters()) >= 14  # all fused params registered


class TestReviewRegressions:
    def test_bias_attr_false_disables_biases(self):
        ffn = FusedFeedForward(16, 32, linear1_bias_attr=False,
                               linear2_bias_attr=False)
        assert ffn.linear1_bias is None and ffn.linear2_bias is None
        ffn.eval()
        assert ffn(_x()).shape == [2, 8, 16]

    def test_self_attention_contract(self):
        import pytest
        with pytest.raises(ValueError, match="kdim"):
            FusedMultiHeadAttention(16, 4, kdim=8)
        with pytest.raises(ValueError, match="need_weights"):
            FusedMultiHeadAttention(16, 4, need_weights=True)
        attn = FusedMultiHeadAttention(16, 4, dropout_rate=0.0,
                                       attn_dropout_rate=0.0)
        x, other = _x(), _x(seed=9)
        with pytest.raises(ValueError, match="self-attention"):
            attn(x, key=other)
        with pytest.raises(ValueError, match="divide"):
            FusedMultiHeadAttention(15, 4)
