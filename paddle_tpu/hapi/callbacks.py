"""hapi callbacks (ref: python/paddle/hapi/callbacks.py)."""

from __future__ import annotations

import os
from typing import Optional

__all__ = ["Callback", "ProgBarLogger", "ModelCheckpoint", "EarlyStopping",
           "LRScheduler"]


class Callback:
    def set_model(self, model):
        self.model = model

    def set_params(self, params):
        self.params = params

    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_train_batch_begin(self, step, logs=None):
        pass

    def on_train_batch_end(self, step, logs=None):
        pass

    def on_eval_begin(self, logs=None):
        pass

    def on_eval_end(self, logs=None):
        pass


class ProgBarLogger(Callback):
    def __init__(self, log_freq: int = 10, verbose: int = 1):
        self.log_freq = log_freq
        self.verbose = verbose

    def on_train_batch_end(self, step, logs=None):
        if self.verbose and logs and step % self.log_freq == 0:
            items = " - ".join(f"{k}: {v:.4f}" if isinstance(v, float)
                               else f"{k}: {v}" for k, v in logs.items())
            print(f"step {step}: {items}")


class ModelCheckpoint(Callback):
    def __init__(self, save_freq: int = 1, save_dir: Optional[str] = None):
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.save_dir and epoch % self.save_freq == 0:
            self.model.save(os.path.join(self.save_dir, str(epoch)))


class EarlyStopping(Callback):
    def __init__(self, monitor: str = "loss", mode: str = "min",
                 patience: int = 0, min_delta: float = 0.0,
                 baseline=None, save_best_model: bool = True):
        self.monitor = monitor
        self.mode = mode
        self.patience = patience
        self.min_delta = min_delta
        self.best = baseline
        self.wait = 0
        self.stopped_epoch = -1

    def on_epoch_end(self, epoch, logs=None):
        val = (logs or {}).get(self.monitor)
        if val is None:
            return
        better = (self.best is None or
                  (self.mode == "min" and val < self.best - self.min_delta) or
                  (self.mode == "max" and val > self.best + self.min_delta))
        if better:
            self.best = val
            self.wait = 0
        else:
            self.wait += 1
            if self.wait > self.patience:
                self.stopped_epoch = epoch
                self.model.stop_training = True


class LRScheduler(Callback):
    """Steps the optimizer's LRScheduler each epoch/step (ref parity)."""

    def __init__(self, by_step: bool = False, by_epoch: bool = True):
        self.by_step = by_step
        self.by_epoch = by_epoch

    def _sched(self):
        lr = getattr(self.model._optimizer, "_lr", None)
        return lr if hasattr(lr, "step") else None

    def on_train_batch_end(self, step, logs=None):
        s = self._sched()
        if self.by_step and s is not None:
            s.step()

    def on_epoch_end(self, epoch, logs=None):
        s = self._sched()
        if self.by_epoch and s is not None:
            s.step()
