"""Fused MLA decode kernel (ops/pallas_mla.py) — r5 roofline-residual
work. The absorbed-latent decode is structurally multi-query attention;
the kernel streams each latent-cache byte once (score + weighted sum from
the same VMEM tile) where the XLA path reads it twice across the softmax
barrier. Kernel-level parity vs the einsum composite, then end-to-end
decode parity with FLAGS_mla_decode_impl pinned both ways (ref
capability: PaddleNLP deepseek_v2 absorbed decode, SURVEY §2.4 row 5)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.flags import flags_guard
from paddle_tpu.ops.pallas_mla import mla_decode_attention, mla_kernel_eligible


def _ref(qe, qp, cl, cp, lens, scale):
    T = cl.shape[1]
    s = (jnp.einsum("bnr,btr->bnt", qe, cl)
         + jnp.einsum("bnd,btd->bnt", qp, cp)) * scale
    mask = jnp.arange(T)[None, None] < lens[:, None, None]
    s = jnp.where(mask, s.astype(jnp.float32), -1e30)
    aw = jax.nn.softmax(s, -1).astype(cl.dtype)
    return jnp.einsum("bnt,btr->bnr", aw, cl)


def _rand(shape, dtype, seed):
    rng = np.random.RandomState(seed)
    return jnp.asarray(rng.randn(*shape), dtype)


class TestKernelParity:
    @pytest.mark.parametrize("dtype,atol", [(jnp.float32, 2e-5),
                                            (jnp.bfloat16, 3e-2)])
    def test_matches_einsum_composite(self, dtype, atol):
        B, nh, r, dr, T = 3, 4, 128, 16, 96
        qe = _rand((B, nh, r), dtype, 0)
        qp = _rand((B, nh, dr), dtype, 1)
        cl = _rand((B, T, r), dtype, 2)
        cp = _rand((B, T, dr), dtype, 3)
        lens = jnp.asarray([96, 1, 37], jnp.int32)
        scale = 1.0 / float(np.sqrt(144))
        out = mla_decode_attention(qe, qp, cl, cp, lens,
                                   scale=scale, block_t=32)
        exp = _ref(qe, qp, cl, cp, lens, scale)
        np.testing.assert_allclose(np.asarray(out, np.float32),
                                   np.asarray(exp, np.float32), atol=atol)

    def test_block_not_dividing_T(self):
        # T=80 with block 32 -> 3 blocks, last one half out-of-bounds;
        # the position mask must cover pallas's padded tail rows
        B, nh, r, dr, T = 2, 8, 128, 8, 80
        qe = _rand((B, nh, r), jnp.float32, 4)
        qp = _rand((B, nh, dr), jnp.float32, 5)
        cl = _rand((B, T, r), jnp.float32, 6)
        cp = _rand((B, T, dr), jnp.float32, 7)
        lens = jnp.asarray([80, 50], jnp.int32)
        out = mla_decode_attention(qe, qp, cl, cp, lens,
                                   scale=0.1, block_t=32)
        exp = _ref(qe, qp, cl, cp, lens, 0.1)
        np.testing.assert_allclose(np.asarray(out), np.asarray(exp),
                                   atol=2e-5)

    def test_length_one_and_dead_blocks(self):
        # lens=1: every block but the first is dead (clamped DMA +
        # skipped compute); output must be exactly cl[:, 0] per head
        B, nh, r, dr, T = 1, 4, 128, 8, 256
        qe = _rand((B, nh, r), jnp.float32, 8)
        qp = _rand((B, nh, dr), jnp.float32, 9)
        cl = _rand((B, T, r), jnp.float32, 10)
        cp = _rand((B, T, dr), jnp.float32, 11)
        lens = jnp.asarray([1], jnp.int32)
        out = mla_decode_attention(qe, qp, cl, cp, lens,
                                   scale=0.1, block_t=64)
        exp = jnp.broadcast_to(cl[:, 0][:, None], (B, nh, r))
        np.testing.assert_allclose(np.asarray(out), np.asarray(exp),
                                   atol=2e-5)

    def test_eligibility_gate(self):
        assert mla_kernel_eligible(16, 512, 64)
        assert mla_kernel_eligible(4, 128, 8)
        assert not mla_kernel_eligible(4, 16, 8)    # tiny-config rank
        assert not mla_kernel_eligible(4, 192, 6)


class TestDecodeIntegration:
    """End-to-end: an MLA model whose latent rank IS lane-aligned decodes
    identically (greedy tokens) through the fused kernel and the pinned
    einsum path."""

    @pytest.fixture(scope="class")
    def model(self):
        from paddle_tpu.models.deepseek import (DeepSeekV2ForCausalLM,
                                                deepseek_v2_tiny_config)
        paddle.seed(31)
        cfg = deepseek_v2_tiny_config(kv_lora_rank=128, qk_rope_head_dim=8,
                                      moe_dropless=True,
                                      max_position_embeddings=64)
        m = DeepSeekV2ForCausalLM(cfg)
        m.eval()
        return m

    def test_fused_matches_xla_tokens(self, model):
        from paddle_tpu.generation import generate_cached
        rng = np.random.RandomState(3)
        ids = paddle.to_tensor(
            rng.randint(1, model.config.vocab_size, (2, 6)).astype("int32"))
        with flags_guard(mla_decode_impl="xla"):
            ref, ref_sc = generate_cached(model, ids, max_new_tokens=6,
                                          decode_strategy="greedy_search")
        with flags_guard(mla_decode_impl="fused"):
            got, got_sc = generate_cached(model, ids, max_new_tokens=6,
                                          decode_strategy="greedy_search")
        np.testing.assert_array_equal(got.numpy(), ref.numpy())
        np.testing.assert_allclose(got_sc.numpy(), ref_sc.numpy(),
                                   rtol=1e-3, atol=1e-4)

    def test_compiled_loop_cache_keys_on_impl_flag(self, model):
        # review r5: _DECODE_LOOP_CACHE ignored the trace-time impl flag,
        # so flipping it returned the OTHER impl's compiled program — an
        # A/B that compared a program to itself
        from paddle_tpu.generation import (_DECODE_LOOP_CACHE,
                                           _decode_params,
                                           _make_decode_loop)
        _DECODE_LOOP_CACHE.clear()
        p = _decode_params(model)
        with flags_guard(mla_decode_impl="fused"):
            _make_decode_loop(p, 4, 2, "greedy_search", None, None,
                              1.0, None, 0)
        with flags_guard(mla_decode_impl="xla"):
            _make_decode_loop(p, 4, 2, "greedy_search", None, None,
                              1.0, None, 0)
        assert len(_DECODE_LOOP_CACHE) == 2, \
            "flag flip must be a program-cache MISS"
        # the flash-impl flag shapes the prefill program the same way
        with flags_guard(mla_decode_impl="xla", flash_impl="composite"):
            _make_decode_loop(p, 4, 2, "greedy_search", None, None,
                              1.0, None, 0)
        assert len(_DECODE_LOOP_CACHE) == 3, \
            "flash-impl flip must be a program-cache MISS"

    def test_compiled_fused_matches_xla_tokens(self, model):
        from paddle_tpu.generation import generate_compiled
        rng = np.random.RandomState(7)
        ids = paddle.to_tensor(
            rng.randint(1, model.config.vocab_size, (2, 5)).astype("int32"))
        with flags_guard(mla_decode_impl="xla"):
            ref, _ = generate_compiled(model, ids, max_new_tokens=5,
                                       decode_strategy="greedy_search")
        with flags_guard(mla_decode_impl="fused"):
            got, _ = generate_compiled(model, ids, max_new_tokens=5,
                                       decode_strategy="greedy_search")
        np.testing.assert_array_equal(got.numpy(), ref.numpy())

    def test_auto_routes_fused_when_eligible(self, model, monkeypatch):
        # token equality cannot distinguish impls (parity is exact here):
        # observe the KERNEL CALL itself — 'auto' at an eligible rank must
        # invoke mla_decode_attention, and an ineligible tiny rank must not
        from paddle_tpu.generation import generate_cached
        from paddle_tpu.ops import pallas_mla
        calls = []
        orig = pallas_mla.mla_decode_attention
        monkeypatch.setattr(
            pallas_mla, "mla_decode_attention",
            lambda *a, **k: (calls.append(1), orig(*a, **k))[1])
        rng = np.random.RandomState(5)
        ids = paddle.to_tensor(
            rng.randint(1, model.config.vocab_size, (1, 4)).astype("int32"))
        with flags_guard(mla_decode_impl="auto"):
            generate_cached(model, ids, max_new_tokens=4,
                            decode_strategy="greedy_search")
        assert calls, "auto must route the fused kernel for eligible ranks"

        from paddle_tpu.models.deepseek import (DeepSeekV2ForCausalLM,
                                                deepseek_v2_tiny_config)
        paddle.seed(3)
        tiny = DeepSeekV2ForCausalLM(deepseek_v2_tiny_config(
            moe_dropless=True, max_position_embeddings=16))
        tiny.eval()
        calls.clear()
        ids2 = paddle.to_tensor(
            rng.randint(1, 512, (1, 3)).astype("int32"))
        with flags_guard(mla_decode_impl="auto"):
            generate_cached(tiny, ids2, max_new_tokens=3,
                            decode_strategy="greedy_search")
        assert not calls, "rank 16 is not lane-eligible; auto must fall back"
