"""The eager Tensor: a paddle-parity imperative handle over a jax.Array.

Rework of the reference's eager tensor (ref: paddle/fluid/pybind/eager.cc,
eager_method.cc; value type paddle/phi/core/dense_tensor.cc). The device
buffer is an async PJRT `jax.Array` — dispatch returns immediately and only
`.numpy()` / `.item()` / python bool fence the device, mirroring the
stream-async semantics of the reference's GPU path.

Tensor is registered as a jax pytree node, so eager code is directly traceable
by `jax.jit` — this is what makes `to_static` a thin bridge instead of a
bytecode interpreter.
"""

from __future__ import annotations

from typing import Any, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from . import autograd
from .dtypes import convert_dtype, get_default_dtype

__all__ = ["Tensor", "to_tensor"]


class Tensor:
    __slots__ = ("_data", "stop_gradient", "_grad", "_node", "name",
                 "persistable", "_retain_grad", "_hooks", "trainable",
                 "__weakref__", "__dict__")

    def __init__(self, data, dtype=None, stop_gradient: bool = True,
                 name: Optional[str] = None):
        if isinstance(data, Tensor):
            data = data._data
        if not isinstance(data, (jax.Array, jax.core.Tracer)):
            dt = convert_dtype(dtype)
            if dt is None and isinstance(data, (float, list)) \
                    and _is_float_data(data):
                dt = get_default_dtype()
            data = jnp.asarray(data, dtype=dt)
        elif dtype is not None and np.dtype(convert_dtype(dtype)) != data.dtype:
            data = data.astype(convert_dtype(dtype))
        self._data = data
        self.stop_gradient = stop_gradient
        self._grad: Optional[Tensor] = None
        self._node: Optional[autograd.GradNode] = None
        self.name = name
        self.persistable = False
        self._retain_grad = False
        self._hooks: List[Any] = []
        self.trainable = not stop_gradient

    # -- metadata ----------------------------------------------------------
    @property
    def shape(self) -> List[int]:
        return list(self._data.shape)

    @property
    def ndim(self) -> int:
        return self._data.ndim

    @property
    def dtype(self):
        return self._data.dtype

    @property
    def size(self) -> int:
        return int(np.prod(self._data.shape)) if self._data.shape else 1

    @property
    def place(self) -> str:
        if isinstance(self._data, jax.core.Tracer):
            return "traced"
        d = list(self._data.devices())[0]
        return f"{d.platform}:{d.id}"

    def is_leaf(self) -> bool:
        return self._node is None

    # -- grad --------------------------------------------------------------
    @property
    def grad(self) -> Optional["Tensor"]:
        return self._grad

    @grad.setter
    def grad(self, value):
        self._grad = None if value is None else (
            value if isinstance(value, Tensor) else Tensor(value))

    def backward(self, grad_tensor=None, retain_graph: bool = False) -> None:
        autograd.backward(self, grad_tensor, retain_graph)

    def clear_grad(self) -> None:
        self._grad = None

    def clear_gradient(self) -> None:  # paddle alias
        self._grad = None

    def retain_grads(self) -> None:
        self._retain_grad = True

    def register_hook(self, hook):
        self._hooks.append(hook)

        class _Handle:
            def remove(_self):
                try:
                    self._hooks.remove(hook)
                except ValueError:
                    pass
        return _Handle()

    def detach(self) -> "Tensor":
        t = Tensor(self._data, stop_gradient=True)
        t.name = self.name
        return t

    def clone(self) -> "Tensor":
        from . import dispatch
        return dispatch.apply("clone", lambda x: x + jnp.zeros((), x.dtype), [self])

    # -- host transfer (these FENCE the async device stream) ---------------
    def numpy(self) -> np.ndarray:
        return np.asarray(self._data)

    def item(self):
        return self._data.item()

    def tolist(self):
        return np.asarray(self._data).tolist()

    def __array__(self, dtype=None):
        a = np.asarray(self._data)
        return a.astype(dtype) if dtype is not None else a

    def __jax_array__(self):
        return self._data

    def __float__(self):
        return float(self._data)

    def __int__(self):
        return int(self._data)

    def __index__(self):
        # lets a concrete 0-d integer Tensor drive range()/slicing, matching
        # the reference Tensor's __index__ (dygraph scalar protocol)
        import numpy as _np
        if not _np.issubdtype(self._data.dtype, _np.integer):
            raise TypeError(
                f"only integer Tensors can be used as an index, got "
                f"{self._data.dtype}")
        return int(self._data)

    def __bool__(self):
        return bool(self._data)

    def __len__(self):
        if self.ndim == 0:
            raise TypeError("len() of a 0-d tensor")
        return self._data.shape[0]

    def __iter__(self):
        """Iterate rows of the leading axis (paddle/torch parity). Without
        this, python's legacy __getitem__ iteration never terminates:
        XLA's gather clamps out-of-range indices, so t[i] past the end
        silently returns the last row instead of raising IndexError. The
        leading dim is static even under trace, so this also makes plain
        `for row in t` unroll correctly inside jit."""
        if self.ndim == 0:
            raise TypeError("iteration over a 0-d tensor")
        for i in range(self._data.shape[0]):
            yield self[i]

    def __hash__(self):
        return id(self)

    # -- in-place-style mutation (functional underneath) -------------------
    def set_value(self, value) -> None:
        arr = value._data if isinstance(value, Tensor) else jnp.asarray(value)
        if tuple(arr.shape) != tuple(self._data.shape):
            raise ValueError(
                f"set_value shape mismatch: {arr.shape} vs {self._data.shape}")
        self._data = arr.astype(self._data.dtype)

    def copy_(self, other) -> "Tensor":
        self.set_value(other)
        return self

    def zero_(self) -> "Tensor":
        self._data = jnp.zeros_like(self._data)
        return self

    def _snapshot(self) -> "Tensor":
        """Freeze the current value/graph-position into a fresh Tensor so this
        one can be mutated in place: the producing node's out_ref is repointed
        to the snapshot, which becomes the autograd parent of the new value."""
        import weakref
        t = Tensor.__new__(Tensor)
        t._data = self._data
        t.stop_gradient = self.stop_gradient
        t._grad = None
        t._node = self._node
        t.name = self.name
        t.persistable = False
        t._retain_grad = False
        t._hooks = []
        t.trainable = self.trainable
        if t._node is not None:
            for i, ref in enumerate(t._node.out_refs):
                if ref() is self:
                    t._node.out_refs[i] = weakref.ref(t)
                    break
        return t

    def _inplace_from(self, result: "Tensor") -> "Tensor":
        """Adopt ``result`` as this tensor's new value, keeping autograd intact:
        the producing GradNode's output slot is repointed from ``result`` to
        ``self`` so cotangents land here during backward."""
        import weakref
        self._data = result._data
        self.stop_gradient = result.stop_gradient
        node = result._node
        self._node = node
        if node is not None:
            for i, ref in enumerate(node.out_refs):
                if ref() is result:
                    node.out_refs[i] = weakref.ref(self)
                    break
        return self

    def __repr__(self):
        sg = self.stop_gradient
        if isinstance(self._data, jax.core.Tracer):
            return (f"Tensor(shape={self.shape}, dtype={self.dtype}, traced, "
                    f"stop_gradient={sg})")
        return (f"Tensor(shape={self.shape}, dtype={self.dtype}, "
                f"place={self.place}, stop_gradient={sg},\n"
                f"       {np.asarray(self._data)!r})")


def _is_float_data(data) -> bool:
    if isinstance(data, float):
        return True
    if isinstance(data, (list, tuple)):
        return any(_is_float_data(x) for x in data)
    return False


def to_tensor(data, dtype=None, place=None, stop_gradient: bool = True) -> Tensor:
    """paddle.to_tensor parity. ``place`` accepted for API compatibility;
    device placement on TPU is owned by shardings (see paddle_tpu.distributed)."""
    if isinstance(data, Tensor):
        t = Tensor(data._data if dtype is None else data._data.astype(convert_dtype(dtype)),
                   stop_gradient=stop_gradient)
        return t
    if isinstance(data, np.ndarray) and data.dtype == np.float64 and dtype is None:
        # numpy float defaults to f64; paddle/tpu default is f32-family
        data = data.astype(get_default_dtype())
    return Tensor(data, dtype=dtype, stop_gradient=stop_gradient)


# -- pytree registration: lets jax.jit/vmap/grad consume Tensors directly ---
def _tensor_flatten(t: Tensor):
    return (t._data,), (t.stop_gradient,)


def _tensor_unflatten(aux, children):
    t = Tensor.__new__(Tensor)
    t._data = children[0]
    t.stop_gradient = aux[0]
    t._grad = None
    t._node = None
    t.name = None
    t.persistable = False
    t._retain_grad = False
    t._hooks = []
    t.trainable = not aux[0]
    return t


jax.tree_util.register_pytree_node(Tensor, _tensor_flatten, _tensor_unflatten)
