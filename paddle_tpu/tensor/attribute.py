"""Tensor attribute helpers (ref: python/paddle/tensor/attribute.py)."""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..core.dispatch import apply
from ..core.tensor import Tensor

__all__ = ["rank", "shape", "real", "imag", "is_complex", "is_integer",
           "is_floating_point"]


def rank(x) -> Tensor:
    return Tensor(jnp.asarray(x.ndim, jnp.int32))


def shape(x) -> Tensor:
    return Tensor(jnp.asarray(x.shape, jnp.int32))


def real(x, name=None) -> Tensor:
    return apply("real", jnp.real, [x])


def imag(x, name=None) -> Tensor:
    return apply("imag", jnp.imag, [x])


def is_complex(x) -> bool:
    return np.issubdtype(x.dtype, np.complexfloating)


def is_integer(x) -> bool:
    return np.issubdtype(x.dtype, np.integer)


def is_floating_point(x) -> bool:
    return np.issubdtype(x.dtype, np.floating) or x.dtype == jnp.bfloat16
