"""Continuous-batching serving engine over the paged KV cache.

`ServingEngine.add_request/step/collect` drives FIXED-SHAPE jitted
device programs (static `max_slots` batch, per-slot active masking
through the page tables) with the per-family math of the generation.py
cached step bodies. Requests join mid-decode (chunked prefill),
leave the instant they hit EOS/max-tokens (their pages return to the
pool immediately), and never retrace — one compile per (model-config,
slot-count) pair, checked by the PT002-gated tests.

Two dispatch paths:

- **ragged (default)**: ONE unified launch per step. Every decode
  slot's token and the oldest prefill request's chunk ride a single
  flat token buffer through a fused per-layer body
  (fused_rms_norm → fused_qkv_rope_append → ragged_paged_attention →
  fused_oproj_norm → fused_ffn, ≤5 launches), so a step that has both
  prefill and decode work issues ONE device program instead of two
  (`serving.engine.launches` counts the difference). Per-sequence row
  tables (seq_start / num_tokens / kv_lengths / page table) make joins
  and leaves pure data changes. The front half rides the ISSUE-20
  mega-kernel — qkv projection (with in-kernel int4/int8 dequant),
  rope and the paged K/V append in one pallas_call — when
  `megafront_eligible` holds for the family geometry (`megafront=False`
  or an ineligible tiling falls back to the split
  qkv→fused_rope_append front, 5 launches instead of 2; MLA with
  q-lora or int4 always splits). The back half rides the ISSUE-14
  mega-kernels — o-proj + residual + norm in one pallas_call, the
  whole FFN in a second — when `megadecode_eligible` holds
  (`megadecode=False` or an ineligible tiling falls back to the split
  o-proj/norm/ffn chain; routed MoE layers always keep the
  `_ffn_apply` combine — data-dependent routing can't fuse — but
  still take the fused o-proj+norm kernel).
- **split (legacy, `ragged=False`)**: the PR-5 alternating
  `_prefill_chunk` / `_decode` dispatches over
  `paged_attention`/`append_to_cache`. Kept as the reference path and
  the fallback when the ragged kernel's tiling constraints don't hold
  on TPU (`ragged_kernel_eligible`).

Inactive slots point their whole page table at the allocator's trash
page 0 with length/num_tokens 0: both paths write their (garbage) K/V
into the trash page and their logits are ignored on the host.

Greedy decoding only: the exactness contract (engine tokens ==
solo `generate_cached` tokens per request, the acceptance test) is a
greedy property; sampling strategies belong to the batch APIs.
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import observability as _obs
from .. import resilience as _res
from ..observability import costmodel as _costmodel
from ..observability import tracing as _tracing
from ..generation import (_decode_params, _dq, _ffn_apply, _llama_weights,
                          _mm_w)
from ..ops.fused import (fused_append_rows, fused_layer_norm,
                         fused_rms_norm, fused_rope_append)
from ..ops.paged_attention import append_to_cache, paged_attention
from ..ops.pallas_megadecode import (fused_ffn, fused_oproj_norm,
                                     megadecode_eligible)
from ..ops.pallas_megafront import (fused_qkv_rope_append,
                                    megafront_eligible)
from ..ops.pallas_ragged import (ragged_kernel_eligible,
                                 ragged_paged_attention)
from .block_allocator import PageBlockAllocator
from .handoff import (HANDOFF_BYTES, HANDOFF_PAGES, HANDOFFS,
                      KVPageHandoff)
from .prefix_cache import PrefixCache
from .scheduler import DECODE, PREFILL, Request, Scheduler
from .spec_decode import accept_length, ngram_draft, record_verify

__all__ = ["ServingEngine"]

_REQS = _obs.registry().counter(
    "serving.engine.requests", "engine requests by outcome",
    labels=("outcome",))
_STEPS = _obs.registry().counter(
    "serving.engine.steps", "device steps launched", labels=("phase",))
_LAUNCHES = _obs.registry().counter(
    "serving.engine.launches", "device program launches by dispatch path",
    labels=("path",))
_TOKENS = _obs.registry().counter(
    "serving.engine.tokens", "tokens processed", labels=("phase",))
_ACTIVE = _obs.registry().gauge(
    "serving.engine.active_slots", "slots holding an in-flight request")
_WAITING = _obs.registry().gauge(
    "serving.engine.waiting", "requests queued for admission")
_REBUILDS = _obs.registry().counter(
    "serving.controller.rebuilds",
    "jit program rebuilds triggered by chunk/spec-k actuation "
    "(ServingEngine.reconfigure)", labels=("replica",))
_PREEMPTIONS = _obs.registry().counter(
    "serving.engine.preemptions",
    "low-priority decodes re-queued (pages intact) for a higher-"
    "priority arrival")
_G_HBM_WEIGHTS = _obs.registry().gauge(
    "serving.engine.hbm_weights_bytes",
    "resident decode weight-tree bytes (costmodel.tree_bytes)")
_G_HBM_POOL = _obs.registry().gauge(
    "serving.engine.hbm_page_pool_bytes",
    "resident KV page-pool bytes: layers x planes x kv_heads x "
    "num_pages x page_size x head_dim x itemsize")
_G_HBM_DRAFT = _obs.registry().gauge(
    "serving.engine.hbm_draft_bytes",
    "spec-decode draft state staged this step: draft + verify token "
    "ids for every extra row of the unified launch")
_G_BPT_MODEL = _obs.registry().gauge(
    "serving.engine.bytes_per_token_model",
    "cumulative costmodel.decode_step_budget bytes (evaluated at each "
    "step's batch and mean live context) / tokens processed")
_G_BPT_MEASURED = _obs.registry().gauge(
    "serving.engine.bytes_per_token_measured",
    "cumulative launch ledger / tokens processed: weight tree once "
    "per device launch + page-granular cache reads at actual lengths")
_TRACE = _tracing.recorder()

#: gauges sampled onto the chrome-trace counter tracks after each step
_COUNTER_GAUGES = (
    "serving.engine.active_slots", "serving.engine.waiting",
    "serving.engine.pages_used", "serving.engine.pages_free",
    "serving.engine.page_utilization",
    "serving.engine.page_fragmentation",
    "serving.engine.hbm_weights_bytes",
    "serving.engine.hbm_page_pool_bytes",
    "serving.engine.hbm_draft_bytes",
    "serving.engine.bytes_per_token_model",
    "serving.engine.bytes_per_token_measured",
)


def _walgo(L, key):
    """Static quant algo of a deploy-layout weight leaf. Kept separate
    from _wq2 (string literal out, never a tracer) so the fused-kernel
    dispatchers branch on a host value."""
    if key + "_q4" in L:
        return "weight_only_int4"
    if key + "_q" in L:
        return "weight_only_int8"
    return None


def _wq2(L, key):
    """(payload, scale) of a deploy-layout weight leaf — the three
    layouts fused_oproj_norm / fused_ffn read natively (fp, int8 + f32
    scale, packed int4 + f32 scale)."""
    if key + "_q4" in L:
        return L[key + "_q4"], L[key + "_s"]
    if key + "_q" in L:
        return L[key + "_q"], L[key + "_s"]
    return L[key], None


def _lcp(a: np.ndarray, b: np.ndarray) -> int:
    n = min(a.size, b.size)
    if n == 0:
        return 0
    neq = np.nonzero(a[:n] != b[:n])[0]
    return int(neq[0]) if neq.size else n


class ServingEngine:
    """Continuous-batching engine for llama/moe, gpt and mla families.

    Typical loop::

        eng = ServingEngine(model, max_slots=4, page_size=16)
        eng.add_request(prompt_ids, max_new_tokens=32, eos_token_id=2)
        while eng.has_work():
            eng.step()
        results = eng.collect()   # {request_id: np.int32[max_new]}

    `config` (inference.Config) carries serving policy: `set_admission`
    bounds in-flight requests (Overloaded backpressure), `set_deadline`
    sets the default per-request budget (falsy TimeoutResult partials),
    `set_prefix_cache` toggles the global radix prefix cache.

    Multi-tenant fast path (all greedy-exact — engine output always
    matches solo `generate_cached`):

      - `enable_prefix_cache` (default on): prompt pages are cached in
        a global radix trie after prefill; a request whose prompt
        extends a cached prefix skips prefilling the shared pages;
      - `add_request(priority=, tenant=)` + `tenant_budgets`: priority
        classes with per-tenant in-flight token budgets; `preemption`
        lets a higher-priority arrival re-queue a low-priority decode
        with its pages intact (resume without re-prefill);
      - `spec_decode=k`: n-gram self-drafting speculative decoding —
        up to k drafted tokens per slot verified in the SAME unified
        ragged launch, greedy accept/rollback.
    """

    def __init__(self, model, max_slots: int = 4, page_size: int = 16,
                 num_pages: Optional[int] = None,
                 max_context: Optional[int] = None,
                 prefill_chunk: int = 32,
                 weight_only_int8: bool = False,
                 weight_only_quant=None,
                 config=None,
                 prefix_sharing: bool = True,
                 ragged: Optional[bool] = None,
                 enable_prefix_cache: Optional[bool] = None,
                 spec_decode: int = 0,
                 preemption: bool = True,
                 tenant_budgets: Optional[dict] = None,
                 megadecode: Optional[bool] = None,
                 megafront: Optional[bool] = None,
                 role: str = "colocated",
                 replica: Optional[str] = None,
                 prefix_cache_admit: bool = True,
                 slo_targets=None):
        if role not in ("prefill", "decode", "colocated"):
            raise ValueError(
                f"role must be prefill/decode/colocated, got {role!r}")
        # disaggregated serving (ROADMAP item 2): a prefill replica runs
        # chunked prefill into its own pool, then stages the request on
        # `handoff_ready` for export (KVPageHandoff) instead of decoding
        # it; a decode replica refuses add_request — `import_request` is
        # its intake — and resumes imported requests straight into
        # DECODE via the PR-10 preemption/resume path. colocated keeps
        # the single-replica behavior and can play either side.
        self.role = role
        self.replica = replica
        self.handoff_ready: List[Request] = []
        # engine-local handoff totals for scrape(): in-process fleets
        # share ONE default registry, so per-replica truth must come
        # from engine state, not the shared counters
        self._handoff_counts = {"export": 0, "import": 0}
        p = _decode_params(model, weight_only_int8, weight_only_quant)
        cfg = p["cfg"]
        self._p = p
        self._w = _llama_weights(p)
        self._family = p["family"]
        self.max_slots = int(max_slots)
        self.page_size = int(page_size)
        self.max_context = int(max_context or cfg.max_position_embeddings)
        if self.max_context > cfg.max_position_embeddings:
            raise ValueError(
                f"max_context {self.max_context} exceeds "
                f"max_position_embeddings {cfg.max_position_embeddings}")
        self.prefill_chunk = int(prefill_chunk)
        if self.prefill_chunk < 1:
            raise ValueError("prefill_chunk must be >= 1")
        self.pages_per_seq = -(-self.max_context // self.page_size)
        if num_pages is None:
            num_pages = self.max_slots * self.pages_per_seq + 1
        self.num_pages = int(num_pages)
        self.prefix_sharing = bool(prefix_sharing)
        self.allocator = PageBlockAllocator(
            self.num_pages, self.page_size, self.pages_per_seq)
        admission = getattr(config, "_admission", None)
        self._default_deadline_s = getattr(config, "_deadline_s", None)
        self.scheduler = Scheduler(
            self.max_slots,
            max_inflight=admission[0] if admission else None,
            queue_timeout_s=admission[1] if admission else 0.0,
            tenant_budgets=tenant_budgets)
        self._prefill_fifo: List[Request] = []
        # global radix prefix cache: engine kwarg wins, then the
        # inference.Config knob (set_prefix_cache), default on
        if enable_prefix_cache is None:
            enable_prefix_cache = getattr(config, "_prefix_cache", None)
        self.prefix_cache = PrefixCache(self.allocator, replica=replica) \
            if enable_prefix_cache in (None, True) else None
        # prefix-cache INSERT admission (the autopilot's thrash lever):
        # False stops new prompts entering the trie — lookups and
        # adopts stay live, so a warm tenant's pinned prefix survives a
        # never-repeating adversary instead of being churned out
        self.prefix_cache_admit = bool(prefix_cache_admit)
        self.preemption = bool(preemption)

        # family geometry + device page pools
        dt = p["embed"].dtype
        n_layers = len(p["layers"])
        if self._family == "gpt":
            kv, d = cfg.num_attention_heads, cfg.head_dim
        elif self._family == "mla":
            kv, d = 1, cfg.kv_lora_rank + cfg.qk_rope_head_dim
        else:
            kv, d = cfg.num_key_value_heads, cfg.head_dim
        shape = (kv, self.num_pages, self.page_size, d)
        if self._family == "mla":
            # one pool per layer: each row is [latent | rope-key], read
            # as both K and V by the concat-dot absorbed decode
            self._pools = [jnp.zeros(shape, dt) for _ in range(n_layers)]
        else:
            self._pools = [(jnp.zeros(shape, dt), jnp.zeros(shape, dt))
                           for _ in range(n_layers)]

        # dispatch path: the unified ragged launch by default, unless
        # the ragged kernel's tiling constraints don't hold on a real
        # TPU (interpret mode has none) or the caller pins the path
        if ragged is None:
            ragged = (jax.default_backend() != "tpu"
                      or ragged_kernel_eligible(
                          cfg.num_attention_heads, kv, d, self.page_size))
        self.ragged = bool(ragged)
        if spec_decode < 0:
            raise ValueError("spec_decode must be >= 0")
        # speculative decoding: each decode slot owns 1 + spec_k flat
        # rows of the unified step (n-gram drafts verified in the SAME
        # ragged launch). The split path has no multi-row slots, so
        # spec decoding rides the ragged path only.
        self.spec_k = int(spec_decode) if self.ragged else 0
        # mega-kernel back half (ISSUE 14): o-proj -> residual -> norm
        # and the whole FFN collapse to TWO pallas_calls per layer when
        # the family geometry tiles; default on, per-family fallback to
        # the split chain via the megadecode_eligible gate (routed MoE
        # layers keep the _ffn_apply combine either way — routing is
        # data-dependent — but still take the fused o-proj+norm kernel)
        ow = (cfg.num_attention_heads * cfg.v_head_dim
              if self._family == "mla"
              else cfg.num_attention_heads * cfg.head_dim)
        int4 = any(k.endswith("_q4") for L in p["layers"] for k in L)
        self.megadecode = bool(
            (True if megadecode is None else megadecode)
            and self.ragged
            and megadecode_eligible(cfg.hidden_size,
                                    cfg.intermediate_size, ow,
                                    int4=int4))
        #: pallas launches after attention, per layer per decode step —
        #: the bench A/B row reads this (2 fused vs the 6-stage chain)
        self.back_half_launches = 2 if self.megadecode else 6
        # mega-kernel front half (ISSUE 20): the qkv projection matmuls,
        # rope and the paged K/V append collapse to ONE pallas_call
        # after the norm, so the decode layer body is <=5 launches with
        # both mega flags on.  Default on, per-family fallback via the
        # megafront_eligible tiling gate; MLA's two-stage q-lora
        # projection and the (unpacked) MLA int4 layout keep the split
        # front.  The gate rewrites the weight tree (per-projection
        # slabs -> one concatenated slab per layer), so it must run
        # BEFORE tree_bytes below.
        self.megafront = bool(
            (True if megafront is None else megafront)
            and self.ragged
            and self._megafront_family_ok(cfg, int4))
        if self.megafront:
            self._concat_qkv_weights()
        #: pallas/XLA launches before attention, per layer per decode
        #: step — the bench A/B row reads this (2 fused vs the split
        #: norm / projection dots / rope-append front)
        self.front_half_launches = 2 if self.megafront \
            else self._split_front_launches()
        self.launches = 0      # device program launches by THIS engine

        # live HBM accounting (ISSUE 11): static residency is published
        # once; a cumulative analytical ledger turns each launch into
        # measured bytes, divided by tokens processed for the
        # bytes-per-token gauge the observatory checks against the
        # costmodel budget
        self._kv_geom = (kv, d)
        self._kv_itemsize = int(jnp.dtype(dt).itemsize)
        planes = 1 if self._family == "mla" else 2
        self._hbm_weights_bytes = _costmodel.tree_bytes(self._w)
        self._hbm_pool_bytes = (n_layers * planes * kv * self.num_pages
                                * self.page_size * d * self._kv_itemsize)
        self._ledger_bytes = 0.0
        self._ledger_model_bytes = 0.0
        self._ledger_tokens = 0
        self._ledger_launches = 0   # self.launches at the last account
        if _obs.enabled():
            _G_HBM_WEIGHTS.set(self._hbm_weights_bytes)
            _G_HBM_POOL.set(self._hbm_pool_bytes)
            _G_HBM_DRAFT.set(0)

        # the fixed-shape programs: built ONCE here, never in the step
        # loop (paddlelint PT002)
        self._build_programs()
        self.rebuilds = 0   # reconfigure()-triggered program rebuilds

        # engine-local speculative-decode totals: the process-wide
        # serving.spec_decode.* counters are shared by every in-process
        # replica, so the controller's per-engine acceptance signal
        # must come from here
        self.spec_drafted = 0
        self.spec_accepted = 0
        # SLO autopilot (ISSUE 18): declaring targets attaches a
        # feedback controller stepped from the tail of step()
        if slo_targets is not None:
            from .controller import EngineController
            self.controller = EngineController(self, slo_targets)
        else:
            self.controller = None

    def _megafront_family_ok(self, cfg, int4: bool) -> bool:
        """Per-family tiling/layout gate for the fused front half."""
        if self._family == "gpt":
            # wqkv ships concatenated already; identity trig
            return megafront_eligible(
                cfg.hidden_size,
                3 * cfg.num_attention_heads * cfg.head_dim,
                cfg.head_dim)
        if self._family == "mla":
            if int4:
                return False    # no packed-int4 MLA front site
            if any("wqa" in L or "wqa_q" in L or "wqa_q4" in L
                   for L in self._p["layers"]):
                return False    # two-stage q-lora can't ride one slab
            dh = cfg.qk_nope_head_dim + cfg.qk_rope_head_dim
            n = (cfg.num_attention_heads * dh
                 + cfg.kv_lora_rank + cfg.qk_rope_head_dim)
            return megafront_eligible(cfg.hidden_size, n, dh)
        n = (cfg.num_attention_heads
             + 2 * cfg.num_key_value_heads) * cfg.head_dim
        return megafront_eligible(cfg.hidden_size, n, cfg.head_dim,
                                  int4=int4)

    def _split_front_launches(self) -> int:
        """Launches before attention on the SPLIT front path, per layer
        (norm + projection dots + the rope/append kernel)."""
        if self._family == "gpt":
            return 3            # norm + wqkv dot + rope-append
        if self._family == "mla":
            qlora = any("wqa" in L or "wqa_q" in L or "wqa_q4" in L
                        for L in self._p["layers"])
            # norm + q dot(s, + q-lora norm) + kv_a dot + latent norm
            # + row append
            return 7 if qlora else 5
        return 5                # norm + q/k/v dots + rope-append

    def _concat_qkv_weights(self) -> None:
        """Deploy-layout transform behind the megafront gate: replace
        each layer's per-projection slabs with ONE concatenated
        out-channel slab — the layout `fused_qkv_rope_append` reads.
        Column-wise identical math (every output column depends only on
        its own weight column; int4 packs along the contraction axis,
        so out-channel concat is layout-safe), applied to payloads,
        scales and biases alike.  llama/moe: wq|wk|wv -> wqkv; MLA:
        wq|wkva -> wqkva; GPT already ships wqkv.  Consumed leaves are
        popped so `tree_bytes` stays the honest residency total (concat
        preserves bytes), which is safe because ragged engines only
        ever build the unified program."""
        if self._family == "gpt":
            return
        mla = self._family == "mla"
        keys = ("wq", "wkva") if mla else ("wq", "wk", "wv")
        new = "wqkva" if mla else "wqkv"
        layers = []
        for L in self._p["layers"]:
            L = dict(L)
            suffix = {"weight_only_int4": "_q4",
                      "weight_only_int8": "_q"}.get(
                          _walgo(L, keys[0]), "")
            L[new + suffix] = jnp.concatenate(
                [L.pop(k + suffix) for k in keys], axis=-1)
            if suffix:
                L[new + "_s"] = jnp.concatenate(
                    [L.pop(k + "_s") for k in keys], axis=-1)
            if "bq" in L:
                L["bqkv"] = jnp.concatenate(
                    [L.pop("bq"), L.pop("bk"), L.pop("bv")], axis=-1)
            layers.append(L)
        self._p = dict(self._p, layers=layers)
        self._w = dict(self._w, layers=layers)

    def _build_programs(self) -> None:
        """(Re)build the fixed-shape jitted programs for the CURRENT
        max_slots/prefill_chunk/spec_k. Called once from __init__ and
        again from `reconfigure()` — fresh `jax.jit` objects each time,
        so `program_cache_sizes()` stays at 1 per program (PT002)."""
        if self.ragged:
            self._jit_unified = jax.jit(self._make_unified_body())
            self._programs = {"unified": self._jit_unified}
        else:
            self._jit_decode = jax.jit(self._make_decode_body())
            self._jit_prefill = jax.jit(self._make_prefill_body())
            self._programs = {"decode": self._jit_decode,
                              "prefill": self._jit_prefill}

    def reconfigure(self, prefill_chunk: Optional[int] = None,
                    spec_decode: Optional[int] = None) -> bool:
        """Retune the shape-baked serving knobs on a LIVE engine — the
        autopilot's chunk/spec-k actuator. Greedy-exactness is
        preserved: chunk size only changes how many prompt tokens ride
        each launch, and spec decoding is accept/rollback-exact at any
        k, so in-flight requests continue bit-identically. Returns True
        when the jitted programs were rebuilt (a recompile on next
        step), False for a no-op."""
        new_chunk = self.prefill_chunk if prefill_chunk is None \
            else int(prefill_chunk)
        if new_chunk < 1:
            raise ValueError("prefill_chunk must be >= 1")
        new_k = self.spec_k if spec_decode is None else int(spec_decode)
        if new_k < 0:
            raise ValueError("spec_decode must be >= 0")
        if not self.ragged:
            new_k = 0   # the split path has no multi-row slots
        if (new_chunk, new_k) == (self.prefill_chunk, self.spec_k):
            return False
        self.prefill_chunk = new_chunk
        self.spec_k = new_k
        self._build_programs()
        self.rebuilds += 1
        if _obs.enabled():
            _REBUILDS.labels(replica=self.replica or "solo").inc()
        return True

    # ------------------------------------------------------------- public
    def add_request(self, prompt, max_new_tokens: int = 20,
                    eos_token_id: Optional[int] = None,
                    pad_token_id: int = 0,
                    deadline_s: Optional[float] = None,
                    request_id=None,
                    priority: int = 0,
                    tenant: Optional[str] = None) -> Request:
        """Enqueue a request (FCFS within its priority class). Raises
        resilience.Overloaded when admission backpressure refuses it at
        the door."""
        if self.role == "decode":
            raise ValueError(
                "decode-role replica does not prefill: route fresh "
                "requests to a prefill/colocated replica "
                "(import_request is this engine's intake)")
        _TRACE.set_replica_context(self.replica)
        req = Request(prompt, max_new_tokens, eos_token_id=eos_token_id,
                      pad_token_id=pad_token_id,
                      deadline_s=(deadline_s if deadline_s is not None
                                  else self._default_deadline_s),
                      request_id=request_id,
                      priority=priority, tenant=tenant)
        if req.total_tokens > self.max_context:
            raise ValueError(
                f"prompt+max_new_tokens = {req.total_tokens} exceeds "
                f"max_context {self.max_context}")
        try:
            self.scheduler.submit(req)
        except _res.Shed:
            if _obs.enabled():
                _REQS.labels(outcome="shed").inc()
            raise
        except _res.Overloaded:
            if _obs.enabled():
                _REQS.labels(outcome="overloaded").inc()
            raise
        if _obs.enabled():
            _REQS.labels(outcome="submitted").inc()
        return req

    def has_work(self) -> bool:
        return self.scheduler.has_work()

    def step(self) -> Dict[str, int]:
        """One engine iteration: cull expired requests, admit waiting
        ones into free slots, then run the step's device work — ONE
        unified ragged launch carrying every decode slot's token plus
        one prefill chunk (ragged path), or the legacy alternating
        prefill-chunk / decode-step pair (split path). Returns counts
        for observability/benching."""
        out = {"admitted": 0, "prefill_tokens": 0, "decoded": 0,
               "finished": 0}
        _TRACE.set_replica_context(self.replica)
        for req in self.scheduler.expire_waiting():
            # a PREEMPTED request expiring in the queue still owns its
            # allocator sequence (pages kept for the resume that never
            # came) — free it here or the pool leaks
            if self.allocator.has_seq(req.request_id):
                self.allocator.free(req.request_id)
            if _obs.enabled():
                _REQS.labels(outcome="overloaded"
                             if isinstance(req.result, _res.Overloaded)
                             else "timeout").inc()
            out["finished"] += 1
        # deadline sweep over in-flight requests: partial result, pages
        # freed immediately
        for _, req in list(self.scheduler.active()):
            if req.deadline_expired():
                self._finish(req)
                out["finished"] += 1
        out["admitted"] = self._admit()
        if self.ragged:
            pf, dec, fin = self._unified_step()
            out["prefill_tokens"] = pf
            out["decoded"] = dec
            out["finished"] += fin
        else:
            out["prefill_tokens"], fin = self._prefill_chunk()
            out["finished"] += fin
            out["decoded"], fin = self._decode()
            out["finished"] += fin
        if _obs.enabled():
            _ACTIVE.set(self.scheduler.inflight)
            _WAITING.set(len(self.scheduler.waiting))
            self._account_step(out)
        self.allocator.publish_gauges()
        if _obs.enabled():
            # counter tracks move in lockstep with the step spans
            _TRACE.sample_gauges(_COUNTER_GAUGES)
        if self.controller is not None:
            self.controller.on_step(out)
        return out

    # ------------------------------------------------- HBM accounting
    def _account_step(self, out: Dict[str, int]) -> None:
        """Fold this step's launches into the measured bytes-per-token
        ledger and refresh the costmodel budget gauge (ISSUE 11).

        Measured = analytical bytes at the step's ACTUAL geometry: the
        weight tree once per device launch plus page-granular cache
        reads at each live slot's current length (what the paged/ragged
        kernels really transfer), cumulative over the engine's life.
        Model = `decode_step_budget` at the same batch and the MEAN
        context.  The two agree up to page rounding and prefill chunks
        riding the unified launch — the slack the observatory's 25%
        gate allows."""
        kv, d = self._kv_geom
        n_layers = len(self._p["layers"])
        per_tok = _costmodel.kv_bytes_per_token_layer(
            self._family, kv_heads=kv, head_dim=d,
            kv_latent_dim=(d if self._family == "mla" else 0),
            kv_dtype_bytes=self._kv_itemsize)
        lens = [self.allocator.seq_length(req.request_id)
                for _, req in self.scheduler.active()
                if self.allocator.has_seq(req.request_id)]
        spec_rows = 1 + self.spec_k
        dl = self.launches - self._ledger_launches
        self._ledger_launches = self.launches
        self._ledger_tokens += (int(out["decoded"])
                                + int(out["prefill_tokens"]))
        if dl:
            pages = sum(-(-ln // self.page_size) for ln in lens)
            self._ledger_bytes += (
                dl * self._hbm_weights_bytes
                + dl * pages * self.page_size * per_tok * n_layers
                * spec_rows)
            if lens:
                # the budget's view of the SAME step: one weight pass +
                # every live cache byte at the mean context
                budget = _costmodel.decode_step_budget(
                    self._family, batch=len(lens),
                    context=sum(lens) / len(lens), layers=n_layers,
                    weight_bytes=self._hbm_weights_bytes,
                    kv_heads=kv, head_dim=d,
                    kv_latent_dim=(d if self._family == "mla" else 0),
                    kv_dtype_bytes=self._kv_itemsize,
                    page_size=self.page_size, spec_rows=spec_rows)
                self._ledger_model_bytes += budget["bytes_per_step"]
        if self._ledger_tokens:
            _G_BPT_MEASURED.set(self._ledger_bytes
                                / self._ledger_tokens)
            _G_BPT_MODEL.set(self._ledger_model_bytes
                             / self._ledger_tokens)
        _G_HBM_DRAFT.set(len(lens) * self.spec_k * 2 * 4)

    def hbm_accounting(self) -> Dict[str, float]:
        """Live HBM/bandwidth ledger snapshot for the observatory:
        static residency (weights, page pool, draft state) plus the
        measured and model bytes-per-token the 25% acceptance check
        compares."""
        return {
            "weights_bytes": float(self._hbm_weights_bytes),
            "page_pool_bytes": float(self._hbm_pool_bytes),
            "draft_bytes": float(_G_HBM_DRAFT.value),
            "ledger_bytes": float(self._ledger_bytes),
            "ledger_tokens": int(self._ledger_tokens),
            "bytes_per_token_measured": (
                self._ledger_bytes / self._ledger_tokens
                if self._ledger_tokens else 0.0),
            "bytes_per_token_model": (
                self._ledger_model_bytes / self._ledger_tokens
                if self._ledger_tokens else 0.0),
            # launch decomposition of one decode layer body — the live
            # A/B the bench reads.  The byte ledger above is fusion-
            # INVARIANT by construction (weights cross once per launch
            # and cache reads are page-granular on both paths; the
            # fused front elides only intermediate activation
            # crossings, which the ledger never counted), so the
            # front-half win shows up here and in tokens/s, not as a
            # measured-bytes discontinuity.
            "front_half_launches": int(self.front_half_launches),
            "back_half_launches": int(self.back_half_launches),
            "layer_body_launches": int(self.front_half_launches + 1
                                       + self.back_half_launches),
        }

    def program_cache_sizes(self) -> Dict[str, int]:
        """{program name: compiled-variant count} for this engine's
        jitted programs — the PT002 no-retrace guard's hook. Ragged
        engines expose {"unified": n}; split engines {"decode": n,
        "prefill": n}. Every count must stay at 1 after any join/leave
        pattern."""
        return {name: fn._cache_size()
                for name, fn in self._programs.items()}

    def collect(self) -> Dict[object, object]:
        """Results of every request finished since the last collect():
        {request_id: np.int32[max_new_tokens] | TimeoutResult |
        Overloaded}."""
        return {r.request_id: r.result
                for r in self.scheduler.drain_finished()}

    def scrape(self) -> Dict[str, object]:
        """This replica's registry snapshot for fleet federation
        (`FleetRouter.scrape()` → `observability.fleet.federate`).

        In-process fleets share ONE default registry, so the per-replica
        families here (``serving.replica.*``) are built from engine-local
        state — slots, queue, allocator, trie, launch and handoff totals
        — into a fresh registry and returned in `Registry.snapshot()`
        format. Returns {} with metrics disabled (the federation
        mutation entry point honors `FLAGS_metrics`)."""
        if not _obs.enabled():
            return {}
        reg = _obs.Registry()
        reg.gauge("serving.replica.info",
                  "replica role marker (value always 1)",
                  labels=("role",)).labels(role=self.role).set(1)
        reg.gauge("serving.replica.active_slots",
                  "requests holding a slot").set(self.scheduler.inflight)
        reg.gauge("serving.replica.waiting",
                  "requests queued for admission").set(
                      len(self.scheduler.waiting))
        st = self.allocator.stats()
        reg.gauge("serving.replica.kv_pages_used",
                  "KV pool pages in use").set(st["pages_used"])
        reg.gauge("serving.replica.kv_pages_free",
                  "KV pool pages free").set(st["pages_free"])
        reg.gauge("serving.replica.kv_utilization",
                  "KV pool utilization [0,1]").set(st["utilization"])
        if self.prefix_cache is not None:
            reg.gauge("serving.replica.prefix_pages",
                      "radix-trie pages pinned on this replica").set(
                          self.prefix_cache.pages)
        reg.counter("serving.replica.launches",
                    "device program launches").inc(self.launches)
        reg.gauge("serving.replica.front_half_launches",
                  "per-layer launches before attention "
                  "(2 = fused megafront)").set(self.front_half_launches)
        reg.gauge("serving.replica.back_half_launches",
                  "per-layer launches after attention "
                  "(2 = fused megadecode)").set(self.back_half_launches)
        hc = reg.counter("serving.replica.handoffs",
                         "KV-page handoffs by direction",
                         labels=("direction",))
        for direction, n in self._handoff_counts.items():
            hc.labels(direction=direction).inc(n)
        return reg.snapshot()

    def run_to_completion(self) -> Dict[object, object]:
        """Step until idle; collect everything."""
        results: Dict[object, object] = {}
        while self.has_work():
            self.step()
            results.update(self.collect())
        results.update(self.collect())
        return results

    # ------------------------------------------------------------ handoff
    def set_replica(self, name: str) -> None:
        """Name this replica for routing/metrics (the FleetRouter calls
        this for replicas constructed without `replica=`)."""
        self.replica = name
        if self.prefix_cache is not None:
            self.prefix_cache.set_replica(name)

    def _stage_handoff(self, req: Request) -> None:
        """Prefill-role completion: give up the slot and queue the
        request for export — a decode replica resumes it without
        re-prefill. Called right after the first token was emitted, so
        the KV-length invariant (length == prompt.size, pending ==
        tokens[-1]) holds."""
        self.scheduler.detach(req)
        self.handoff_ready.append(req)
        _TRACE.stamp(req.request_id, "handoff_ready",
                     kv_tokens=self.allocator.seq_length(req.request_id))

    def export_request(self, req: Request) -> KVPageHandoff:
        """Export an in-flight request as a `KVPageHandoff`: pin its
        pages, snapshot (page table, block payload, sampler state),
        and remove it from this replica. Works for staged prefill
        completions, running decodes, and preempted-waiting requests —
        any request whose prefill is complete (the drain path exports
        mid-stream decodes pages-intact). The export pins keep the
        pages readable until the importer's `release()`, and trie pins
        keep shared prompt pages warm on this replica regardless."""
        rid = req.request_id
        _TRACE.set_replica_context(self.replica)
        if req.pending is None or req.prefill_pos < int(req.prompt.size):
            raise ValueError(
                f"request {rid} is not exportable mid-prefill "
                f"({req.prefill_pos}/{int(req.prompt.size)} tokens)")
        if req in self.handoff_ready:
            self.handoff_ready.remove(req)
        else:
            self.scheduler.detach(req)
        exp = self.allocator.export_seq(rid)
        pages = np.asarray(exp["pages"], np.int32)
        if self._family == "mla":
            blocks = [np.asarray(pool[:, pages]) for pool in self._pools]
        else:
            blocks = [(np.asarray(kp[:, pages]), np.asarray(vp[:, pages]))
                      for kp, vp in self._pools]
        # remaining deadline travels with the request (the importer's
        # submit() restarts the clock)
        dl = req.deadline_s
        if req._deadline is not None:
            dl = max(1e-6, req._deadline.budget_s
                     - req._deadline.elapsed_s)
        # the sequence leaves this replica the moment the payload is
        # snapshotted; the export pins (dropped by release()) keep the
        # protocol window consistent even so
        self.allocator.free(rid)
        alloc = self.allocator
        handoff = KVPageHandoff(
            request_id=rid, prompt=req.prompt,
            max_new_tokens=req.max_new_tokens,
            eos_token_id=req.eos_token_id, pad_token_id=req.pad_token_id,
            priority=req.priority, tenant=req.tenant, deadline_s=dl,
            tokens=list(req.tokens), pending=int(req.pending),
            shared_tokens=req.shared_tokens,
            kv_length=int(exp["length"]), blocks=blocks,
            page_size=self.page_size, family=self._family,
            source=self.replica or "", _release=lambda:
            alloc.release_export(exp))
        self._handoff_counts["export"] += 1
        if _obs.enabled():
            HANDOFFS.labels(direction="export").inc()
            HANDOFF_PAGES.inc(len(exp["pages"]))
            HANDOFF_BYTES.inc(handoff.payload_bytes)
        _TRACE.stamp(rid, "handoff_export", pages=len(exp["pages"]),
                     kv_tokens=handoff.kv_length)
        # the trace context travels WITH the KV pages: the importer
        # adopts it so the request keeps one timeline across replicas
        handoff.trace = _TRACE.export_context(rid)
        return handoff

    def import_request(self, handoff: KVPageHandoff) -> Request:
        """Receive side of the handoff: allocate destination pages,
        copy the block payload into this replica's pools, and submit
        the rebuilt request with `preempted=True` so the scheduler
        resumes it straight into DECODE (the PR-10 resume path) — no
        re-prefill. Raises `resilience.Overloaded` (allocator or
        admission gate) with this replica unchanged, so the router can
        retry the same handoff elsewhere."""
        if self.role == "prefill":
            raise ValueError("prefill-role replica cannot decode an "
                             "imported request")
        if handoff.family != self._family:
            raise ValueError(
                f"family mismatch: handoff {handoff.family} vs engine "
                f"{self._family}")
        if handoff.page_size != self.page_size:
            raise ValueError(
                f"page_size mismatch: handoff {handoff.page_size} vs "
                f"engine {self.page_size}")
        _TRACE.set_replica_context(self.replica)
        _TRACE.adopt(handoff.request_id, handoff.trace)
        req = Request(handoff.prompt, handoff.max_new_tokens,
                      eos_token_id=handoff.eos_token_id,
                      pad_token_id=handoff.pad_token_id,
                      deadline_s=handoff.deadline_s,
                      request_id=handoff.request_id,
                      priority=handoff.priority, tenant=handoff.tenant)
        pages = self.allocator.import_seq(
            req.request_id, handoff.kv_length, req.total_tokens)
        dst = np.asarray(pages, np.int32)
        if self._family == "mla":
            self._pools = [pool.at[:, dst].set(jnp.asarray(blk))
                           for pool, blk in zip(self._pools,
                                                handoff.blocks)]
        else:
            self._pools = [(kp.at[:, dst].set(jnp.asarray(kb)),
                            vp.at[:, dst].set(jnp.asarray(vb)))
                           for (kp, vp), (kb, vb)
                           in zip(self._pools, handoff.blocks)]
        req.tokens = list(handoff.tokens)
        req.pending = handoff.pending
        req.prefill_pos = int(req.prompt.size)
        req.shared_tokens = handoff.shared_tokens
        req.preempted = True
        try:
            self.scheduler.submit(req)
        except _res.Overloaded:
            self.allocator.free(req.request_id)
            raise
        # warm THIS replica's trie with the prompt pages so the router's
        # locality score sends the tenant's next request here. The
        # inserted full prompt pages are never rewritten: decode writes
        # land at positions >= kv_length >= prompt.size, past them.
        if self.prefix_cache is not None and self.prefix_cache_admit:
            self.prefix_cache.insert(req.prompt, pages)
        self._handoff_counts["import"] += 1
        if _obs.enabled():
            HANDOFFS.labels(direction="import").inc()
            _REQS.labels(outcome="imported").inc()
        _TRACE.stamp(req.request_id, "handoff_import",
                     source=handoff.source, replica=self.replica or "",
                     pages=len(pages))
        handoff.release()
        return req

    # ---------------------------------------------------------- admission
    def _admit(self) -> int:
        admitted = 0
        while True:
            req = self.scheduler.next_admittable()
            if req is None:
                req = self._preempt_for_waiting()
                if req is None:
                    break
                continue   # the freed slot re-enters next_admittable
            if req.preempted:
                # resume: the allocator sequence — pages, length,
                # pending token — survived preemption untouched, so the
                # request goes straight back to DECODE. No re-prefill.
                self.scheduler.admit(req)
                admitted += 1
                continue
            if not self._reserve_pages(req):
                break   # head-of-class waits for pages; no skip
            self.scheduler.admit(req)
            if req.shared_tokens > 0:
                _TRACE.stamp(req.request_id,
                             "prefix_hit" if req._share_source == "cache"
                             else "prefix_share",
                             tokens=req.shared_tokens,
                             **req._share_meta)
            self._prefill_fifo.append(req)
            admitted += 1
        return admitted

    def _reserve_pages(self, req: Request) -> bool:
        """Reserve the request's pages, sharing the longest available
        prefix — a live donor's prefilled prompt (token-granular fork)
        or the global prefix cache (page-granular adopt), whichever is
        longer. Under pool pressure, cold trie pages are evicted and
        the reservation retried ONCE. Every failure path releases the
        lookup's pins (no leaked refcounts); returns False so the
        request keeps waiting."""
        share, donor = 0, None
        if self.prefix_sharing:
            for _, cand in self.scheduler.active():
                # only the donor's PREFILLED prompt tokens are
                # reusable; cap at len(prompt)-1 so the last prompt
                # token is always re-run for this request's logits
                s = min(_lcp(req.prompt, cand.prompt),
                        cand.prefill_pos, int(req.prompt.size) - 1)
                if s > share:
                    share, donor = s, cand
        match = self.prefix_cache.lookup(req.prompt) \
            if self.prefix_cache is not None else None
        use_cache = match is not None and match.tokens > share

        def take() -> None:
            if use_cache:
                self.allocator.adopt(req.request_id, match.pages,
                                     match.tokens, req.total_tokens)
            elif share > 0:
                self.allocator.fork(donor.request_id, req.request_id,
                                    share, req.total_tokens)
            else:
                self.allocator.allocate(req.request_id, req.total_tokens)

        try:
            try:
                take()
            except _res.Overloaded:
                if self.prefix_cache is None:
                    raise
                eff = match.tokens if use_cache else share
                need = self.allocator.pages_needed(req.total_tokens, eff)
                if self.prefix_cache.evict(
                        need - self.allocator.available_pages) <= 0:
                    raise
                take()
        except _res.Overloaded:
            if match is not None:
                match.release()
            return False
        if use_cache:
            self.prefix_cache.note_adopted(match.tokens)
            req._share_source = "cache"
            req._share_meta = {"pages": len(match.pages)}
            req.prefill_pos = req.shared_tokens = match.tokens
        elif share > 0:
            req._share_source = "donor"
            req._share_meta = {"donor": donor.request_id}
            req.prefill_pos = req.shared_tokens = share
        else:
            req._share_source = None
            req._share_meta = {}
            req.prefill_pos = req.shared_tokens = 0
        if match is not None:
            match.release()   # adopt holds its own refcounts by now
        return True

    def _preempt_for_waiting(self) -> Optional[Request]:
        """Make room for the highest-priority waiting request by
        re-queueing a strictly lower-priority DECODE victim with its
        pages intact. Only fires when the candidate's pages would
        actually fit (the victim keeps its pages, so preempting for a
        pool-blocked candidate would just thrash)."""
        if not self.preemption:
            return None
        cand = self.scheduler.next_candidate()
        if cand is None:
            return None
        victim = self.scheduler.pick_victim(cand.priority)
        if victim is None:
            return None
        if not cand.preempted:
            share = self.prefix_cache.match_length(cand.prompt) \
                if self.prefix_cache is not None else 0
            need = self.allocator.pages_needed(cand.total_tokens, share)
            spare = self.allocator.available_pages + (
                self.prefix_cache.evictable_pages()
                if self.prefix_cache is not None else 0)
            if need > spare:
                return None
        self.scheduler.preempt(victim)
        if _obs.enabled():
            _PREEMPTIONS.inc()
        return cand

    # ------------------------------------------------------------ prefill
    def _prefill_chunk(self) -> Tuple[int, int]:
        """One chunk of prompt prefill for the OLDEST prefilling request
        — bounded work between decode steps so long prompts never stall
        the in-flight batch."""
        while self._prefill_fifo and \
                self._prefill_fifo[0].state != PREFILL:
            self._prefill_fifo.pop(0)
        if not self._prefill_fifo:
            return 0, 0
        req = self._prefill_fifo[0]
        n = min(self.prefill_chunk, int(req.prompt.size) - req.prefill_pos)
        start = req.prefill_pos
        self._apply_copies(self.allocator.extend(req.request_id, n), req)
        ids = np.zeros((1, self.prefill_chunk), np.int32)
        ids[0, :n] = req.prompt[start:start + n]
        table = self.allocator.table(req.request_id)[None]
        if _tracing.enabled():
            # the host span's id rides along on every stamp taken inside
            # this launch, so request timelines join the profiler trace
            with _obs.span("serving.engine.prefill_chunk") as sp:
                logits, self._pools = self._jit_prefill(
                    self._w, jnp.asarray(ids), self._pools,
                    jnp.asarray(table), np.int32(start), np.int32(n))
            _TRACE.set_host_span(sp.span_id)
            _TRACE.stamp(req.request_id, "prefill_chunk", tokens=n,
                         start=start)
        else:
            logits, self._pools = self._jit_prefill(
                self._w, jnp.asarray(ids), self._pools, jnp.asarray(table),
                np.int32(start), np.int32(n))
        req.prefill_pos += n
        self.launches += 1
        if _obs.enabled():
            _LAUNCHES.labels(path="split").inc()
            _STEPS.labels(phase="prefill").inc()
            _TOKENS.labels(phase="prefill").inc(n)
        finished = 0
        if req.prefill_pos == int(req.prompt.size):
            self._prefill_fifo.pop(0)
            req.state = DECODE
            # cache the full prompt pages BEFORE _emit can finish the
            # request and return its pages — trie pins keep them warm
            if self.prefix_cache is not None and self.prefix_cache_admit:
                self.prefix_cache.insert(
                    req.prompt, self.allocator.seq_pages(req.request_id))
            tok = int(np.argmax(np.asarray(logits[0])))
            fin = self._emit(req, tok)
            finished += fin
            if not fin and self.role == "prefill":
                self._stage_handoff(req)
        _TRACE.set_host_span(None)
        return n, finished

    # ------------------------------------------------------------- decode
    def _decode(self) -> Tuple[int, int]:
        active = self.scheduler.active(DECODE)
        if not active:
            return 0, 0
        B = self.max_slots
        tok = np.zeros(B, np.int32)
        lengths = np.zeros(B, np.int32)
        tables = np.zeros((B, self.pages_per_seq), np.int32)
        for slot, req in active:
            tok[slot] = req.pending
            lengths[slot] = self.allocator.seq_length(req.request_id)
            self._apply_copies(self.allocator.extend(req.request_id, 1),
                               req)
            tables[slot] = self.allocator.table(req.request_id)
        if _tracing.enabled():
            with _obs.span("serving.engine.decode_step") as sp:
                logits, self._pools = self._jit_decode(
                    self._w, jnp.asarray(tok), self._pools,
                    jnp.asarray(lengths), jnp.asarray(tables))
            _TRACE.set_host_span(sp.span_id)
        else:
            logits, self._pools = self._jit_decode(
                self._w, jnp.asarray(tok), self._pools,
                jnp.asarray(lengths), jnp.asarray(tables))
        logits = np.asarray(logits)
        self.launches += 1
        if _obs.enabled():
            _LAUNCHES.labels(path="split").inc()
            _STEPS.labels(phase="decode").inc()
            _TOKENS.labels(phase="decode").inc(len(active))
        finished = 0
        for slot, req in active:
            finished += self._emit(req, int(np.argmax(logits[slot])))
        _TRACE.set_host_span(None)
        return len(active), finished

    # ------------------------------------------------------------ unified
    def _unified_step(self) -> Tuple[int, int, int]:
        """ONE ragged launch for the whole step: decode slot `s` owns
        flat rows [s*R, s*R + 1 + k) with R = 1 + spec_k — its pending
        token plus k n-gram-drafted tokens verified in the SAME launch
        — and the oldest prefilling request's chunk rides rows
        [max_slots*R, max_slots*R + n). Row tables (num_tokens /
        kv_lengths / page tables, seq_start baked into the jitted body)
        tell the ragged kernel who owns which rows; idle rows write to
        the trash page and emit garbage logits the host never reads.
        Returns (prefill_tokens, decoded, finished).

        Speculative accept/rollback is greedy-exact: position j's argmax
        depends only on rows 0..j of the slot (per-row causality), so
        drafted tokens are accepted while they match the argmax chain
        and the KV length is shrunk past the first mismatch — engine
        output is bit-identical to plain decode, just fewer launches.

        Vs the split path: a request that completes its prefill emits
        its first token from THIS launch and takes its first decode
        step in the NEXT one (the split path decodes it the same
        engine step) — per-request token sequences are identical, the
        step count shifts by at most one."""
        while self._prefill_fifo and \
                self._prefill_fifo[0].state != PREFILL:
            self._prefill_fifo.pop(0)
        preq = self._prefill_fifo[0] if self._prefill_fifo else None
        active = self.scheduler.active(DECODE)
        if preq is None and not active:
            return 0, 0, 0
        B, C, K = self.max_slots, self.prefill_chunk, self.spec_k
        R = 1 + K
        base = B * R
        T, S = base + C, B + 1
        ps, nj = self.page_size, self.pages_per_seq
        tok = np.zeros(T, np.int32)
        positions = np.zeros(T, np.int32)
        num_tokens = np.zeros(S, np.int32)
        kv_lengths = np.zeros(S, np.int32)
        tables = np.zeros((S, nj), np.int32)   # idle -> trash page 0
        tok_page = np.zeros(T, np.int32)
        tok_off = np.zeros(T, np.int32)
        drafts: Dict[int, List[int]] = {}
        for slot, req in active:
            ln = self.allocator.seq_length(req.request_id)
            d: List[int] = []
            if K:
                # never draft past max_new - 1: the verify step itself
                # emits up to k+1 tokens
                cap = req.max_new_tokens - len(req.tokens) - 1
                if cap > 0:
                    d = ngram_draft(
                        np.concatenate([req.prompt, req.tokens]),
                        min(K, cap))
            drafts[slot] = d
            nt = 1 + len(d)
            self._apply_copies(self.allocator.extend(req.request_id, nt),
                               req)
            tbl = self.allocator.table(req.request_id)
            r0 = slot * R
            pos = ln + np.arange(nt)
            tok[r0:r0 + nt] = [req.pending] + d
            positions[r0:r0 + nt] = pos
            num_tokens[slot] = nt
            kv_lengths[slot] = ln + nt
            tables[slot] = tbl
            tok_page[r0:r0 + nt] = tbl[pos // ps]
            tok_off[r0:r0 + nt] = pos % ps
            if d:
                _TRACE.stamp(req.request_id, "draft", tokens=len(d))
        n, start = 0, 0
        if preq is not None:
            start = preq.prefill_pos
            n = min(C, int(preq.prompt.size) - start)
            self._apply_copies(self.allocator.extend(preq.request_id, n),
                               preq)
            tbl = self.allocator.table(preq.request_id)
            rows = np.arange(n)
            tok[base:base + n] = preq.prompt[start:start + n]
            positions[base:base + n] = start + rows
            num_tokens[S - 1] = n
            kv_lengths[S - 1] = start + n
            tables[S - 1] = tbl
            tok_page[base:base + n] = tbl[(start + rows) // ps]
            tok_off[base:base + n] = (start + rows) % ps
        args = (self._w, jnp.asarray(tok), self._pools,
                jnp.asarray(positions), jnp.asarray(num_tokens),
                jnp.asarray(kv_lengths), jnp.asarray(tables),
                jnp.asarray(tok_page), jnp.asarray(tok_off))
        if _tracing.enabled():
            with _obs.span("serving.engine.unified_step") as sp:
                logits, self._pools = self._jit_unified(*args)
            _TRACE.set_host_span(sp.span_id)
            if preq is not None:
                _TRACE.stamp(preq.request_id, "prefill_chunk", tokens=n,
                             start=start)
        else:
            logits, self._pools = self._jit_unified(*args)
        logits = np.asarray(logits)         # [S, vocab]; [T, vocab] K>0
        self.launches += 1
        if _obs.enabled():
            _LAUNCHES.labels(
                path="unified_megafront" if self.megafront
                else "unified").inc()
            _STEPS.labels(phase="unified").inc()
            if n:
                _TOKENS.labels(phase="prefill").inc(n)
        finished = 0
        if preq is not None:
            preq.prefill_pos += n
            if preq.prefill_pos == int(preq.prompt.size):
                self._prefill_fifo.pop(0)
                preq.state = DECODE
                # cache the full prompt pages BEFORE _emit can finish
                # the request and return its pages — trie pins keep
                # them warm for the next tenant
                if self.prefix_cache is not None \
                        and self.prefix_cache_admit:
                    self.prefix_cache.insert(
                        preq.prompt,
                        self.allocator.seq_pages(preq.request_id))
                row = logits[base + n - 1] if K else logits[S - 1]
                fin = self._emit(preq, int(np.argmax(row)))
                finished += fin
                if not fin and self.role == "prefill":
                    self._stage_handoff(preq)
        decoded = 0
        for slot, req in active:
            d = drafts[slot]
            if not d:
                row = logits[slot * R] if K else logits[slot]
                finished += self._emit(req, int(np.argmax(row)))
                decoded += 1
                continue
            r0 = slot * R
            greedy = [int(np.argmax(logits[r0 + j]))
                      for j in range(len(d) + 1)]
            m = accept_length(d, greedy)
            fin = 0
            for j in range(m + 1):
                decoded += 1
                fin = self._emit(req, greedy[j])
                if fin:
                    break   # EOS/max_new: _finish already freed the seq
            finished += fin
            if not fin:
                # reject the tail: pure length rollback — stale KV past
                # the new length is never readable (kv_lengths caps the
                # attention window) and is overwritten by later tokens
                self.allocator.shrink(req.request_id, len(d) - m)
            record_verify(len(d), m)
            self.spec_drafted += len(d)
            self.spec_accepted += m
            _TRACE.stamp(req.request_id, "verify_accept",
                         drafted=len(d), accepted=m)
        if _obs.enabled() and decoded:
            _TOKENS.labels(phase="decode").inc(decoded)
        _TRACE.set_host_span(None)
        return n, decoded, finished

    def _emit(self, req: Request, tok: int) -> int:
        """Record one sampled token; finish on EOS/max-tokens (pages
        freed the same step), else stage it for the next decode step."""
        req.tokens.append(tok)
        _TRACE.stamp(req.request_id, "token", index=len(req.tokens) - 1)
        done = (req.eos_token_id is not None and tok == req.eos_token_id) \
            or len(req.tokens) >= req.max_new_tokens
        if done:
            self._finish(req)
            return 1
        req.pending = tok
        return 0

    def _finish(self, req: Request) -> None:
        req.finalize()
        self.allocator.free(req.request_id)
        self.scheduler.release(req)
        timeout = isinstance(req.result, _res.TimeoutResult)
        _TRACE.finish(req.request_id, "timeout" if timeout else "finish",
                      tokens=len(req.tokens))
        if _obs.enabled():
            _REQS.labels(outcome="timeout" if timeout
                         else "completed").inc()

    def _apply_copies(self, copies, req: Optional[Request] = None) -> None:
        """Apply the allocator's copy-on-write page copies to the device
        pools before the write that triggered them."""
        if not copies:
            return
        if req is not None:
            _TRACE.stamp(req.request_id, "cow", pages=len(copies))
        src = np.asarray([c[0] for c in copies])
        dst = np.asarray([c[1] for c in copies])
        if self._family == "mla":
            self._pools = [pool.at[:, dst].set(pool[:, src])
                           for pool in self._pools]
        else:
            self._pools = [(kp.at[:, dst].set(kp[:, src]),
                            vp.at[:, dst].set(vp[:, src]))
                           for kp, vp in self._pools]

    # ----------------------------------------------------- jitted bodies
    def _make_decode_body(self):
        if self._family == "gpt":
            return self._gpt_decode_body()
        if self._family == "mla":
            return self._mla_decode_body()
        return self._llama_decode_body()

    def _make_prefill_body(self):
        if self._family == "gpt":
            return self._gpt_prefill_body()
        if self._family == "mla":
            return self._mla_prefill_body()
        return self._llama_prefill_body()

    def _make_unified_body(self):
        if self._family == "gpt":
            return self._gpt_unified_body()
        if self._family == "mla":
            return self._mla_unified_body()
        return self._llama_unified_body()

    # -- unified ragged step -------------------------------------------
    # One fused launch per engine step: T = max_slots + prefill_chunk
    # flat token rows, S = max_slots + 1 sequences with BAKED seq_start
    # [0..B-1, B] (decode slot i owns row i; the prefill chunk owns rows
    # B..B+n-1). The per-layer body is the fused decode chain:
    # fused_rms_norm -> fused_qkv_rope_append (the ISSUE-20 mega-kernel
    # front half: qkv projection with in-kernel dequant, rope, and the
    # paged K/V scatter in one launch; `self.megafront` False falls
    # back to the split qkv -> fused_rope_append front, same math) ->
    # ragged_paged_attention -> fused_oproj_norm -> fused_ffn (the
    # ISSUE-14 mega-kernel back half: o-proj + residual + norm emit
    # from one f32 VMEM accumulator, the whole FFN from a second —
    # `self.megadecode` False falls back to the split o-proj/norm/ffn
    # chain, same math, more HBM round-trips).
    # No flags_guard: nothing in the chain is flag-routed.

    def _llama_unified_body(self):
        cfg = self._p["cfg"]
        Hh, KV, D = (cfg.num_attention_heads, cfg.num_key_value_heads,
                     cfg.head_dim)
        eps = cfg.rms_norm_eps
        moe_static = self._p.get("moe_static")
        mega = self.megadecode
        megafront = self.megafront
        B, C, K = self.max_slots, self.prefill_chunk, self.spec_k
        R = 1 + K
        T = B * R + C
        # decode slot s owns rows [s*R, (s+1)*R); the prefill chunk owns
        # rows [B*R, B*R+C). R == 1 reduces to arange(B + 1).
        seq_start = jnp.concatenate(
            [jnp.arange(B, dtype=jnp.int32) * R,
             jnp.asarray([B * R], jnp.int32)])

        def step(w, tok, pools, positions, num_tokens, kv_lengths,
                 tables, tok_page, tok_off):
            x = w["embed"][tok][None]                    # [1, T, H]
            c = w["cos"][positions]                      # [T, D/2]
            s = w["sin"][positions]
            new_pools = []
            sts = moe_static or (None,) * len(w["layers"])
            for L, (kp, vp), st in zip(w["layers"], pools, sts):
                h = fused_rms_norm(x, L["ln1"], eps)
                if megafront:
                    # ISSUE 20 front half: qkv projection (in-kernel
                    # dequant of the concatenated deploy slab), rope
                    # and the paged K/V scatter in ONE launch
                    wp, ws = _wq2(L, "wqkv")
                    q, kp, vp = fused_qkv_rope_append(
                        h[0], wp, ws, L.get("bqkv"), c, s, kp, vp,
                        tok_page, tok_off, heads=Hh, kv_heads=KV,
                        head_dim=D, algo=_walgo(L, "wqkv"))
                else:
                    q, k, v = (_mm_w(h, L, "wq"), _mm_w(h, L, "wk"),
                               _mm_w(h, L, "wv"))
                    if "bq" in L:
                        q, k, v = q + L["bq"], k + L["bk"], v + L["bv"]
                    q, kp, vp = fused_rope_append(
                        q.reshape(T, Hh, D), k.reshape(T, KV, D),
                        v.reshape(T, KV, D), c, s, kp, vp, tok_page,
                        tok_off)
                new_pools.append((kp, vp))
                o = ragged_paged_attention(q, kp, vp, seq_start,
                                           num_tokens, kv_lengths,
                                           tables, scale=D ** -0.5)
                if mega:
                    wp, ws = _wq2(L, "wo")
                    xn, h2 = fused_oproj_norm(
                        o.reshape(T, Hh * D), x[0], wp, ws, None,
                        L["ln2"], None, eps=eps, algo=_walgo(L, "wo"))
                    if "moe" in L:
                        x = xn[None] + _ffn_apply(L, h2[None], st)
                    else:
                        gp, gs = _wq2(L, "wg")
                        up, us = _wq2(L, "wu")
                        dp, ds = _wq2(L, "wd")
                        x = fused_ffn(h2, xn, gp, gs, up, us, dp, ds,
                                      algo=_walgo(L, "wg"))[None]
                else:
                    x = x + _mm_w(o.reshape(1, T, Hh * D), L, "wo")
                    h2 = fused_rms_norm(x, L["ln2"], eps)
                    x = x + _ffn_apply(L, h2, st)
            x = fused_rms_norm(x, w["norm"], eps)
            # each sequence's logits come from its LAST flat row; idle
            # slots (num_tokens 0) index garbage the host ignores. With
            # spec decoding every row's logits come back — each drafted
            # position is a verify point.
            if K:
                last = x[0]
            else:
                last = x[0, jnp.clip(seq_start + num_tokens - 1,
                                     0, T - 1)]
            if "head_q" in w or "head_q4" in w:
                logits = _mm_w(last, w, "head")
            else:
                logits = last @ (w["head"] if w["head"] is not None
                                 else w["embed"].T)
            return logits, new_pools

        return step

    def _gpt_unified_body(self):
        cfg = self._p["cfg"]
        nh, hd = cfg.num_attention_heads, cfg.head_dim
        eps = cfg.layer_norm_eps
        mega = self.megadecode
        megafront = self.megafront
        B, C, K = self.max_slots, self.prefill_chunk, self.spec_k
        R = 1 + K
        T = B * R + C
        # decode slot s owns rows [s*R, (s+1)*R); the prefill chunk owns
        # rows [B*R, B*R+C). R == 1 reduces to arange(B + 1).
        seq_start = jnp.concatenate(
            [jnp.arange(B, dtype=jnp.int32) * R,
             jnp.asarray([B * R], jnp.int32)])

        def step(w, tok, pools, positions, num_tokens, kv_lengths,
                 tables, tok_page, tok_off):
            x = (w["embed"][tok] + w["pos"][positions])[None]
            # identity rope (cos=1, sin=0): fused_rope_append becomes a
            # pure fused K/V append, bitwise-exact on q/k
            c = jnp.ones((T, hd // 2), x.dtype)
            s = jnp.zeros((T, hd // 2), x.dtype)
            new_pools = []
            for L, (kp, vp) in zip(w["layers"], pools):
                h = fused_layer_norm(x, L["ln1w"], L["ln1b"], eps)
                if megafront:
                    # the deploy wqkv slab is already the fused
                    # kernel's [q | k | v] column layout; identity
                    # trig makes rope a no-op on q/k
                    q, kp, vp = fused_qkv_rope_append(
                        h[0], L["wqkv"], None, L["bqkv"], c, s, kp,
                        vp, tok_page, tok_off, heads=nh, kv_heads=nh,
                        head_dim=hd)
                else:
                    qkv = h @ L["wqkv"] + L["bqkv"]
                    q, k, v = jnp.split(qkv, 3, axis=-1)
                    q, kp, vp = fused_rope_append(
                        q.reshape(T, nh, hd), k.reshape(T, nh, hd),
                        v.reshape(T, nh, hd), c, s, kp, vp,
                        tok_page, tok_off)
                new_pools.append((kp, vp))
                o = ragged_paged_attention(q, kp, vp, seq_start,
                                           num_tokens, kv_lengths,
                                           tables, scale=hd ** -0.5)
                if mega:
                    # GPT family is fp (no quantized leaves): biases and
                    # the layer norm ride the same two mega-kernels
                    xn, h2 = fused_oproj_norm(
                        o.reshape(T, nh * hd), x[0], L["wo"], None,
                        L["bo"], L["ln2w"], L["ln2b"], eps=eps,
                        norm="layer")
                    x = fused_ffn(h2, xn, L["wi"], None, None, None,
                                  L["wf"], None, L["bi"], L["bf"],
                                  act="gelu")[None]
                else:
                    x = x + (o.reshape(1, T, nh * hd) @ L["wo"]
                             + L["bo"])
                    h2 = fused_layer_norm(x, L["ln2w"], L["ln2b"], eps)
                    x = x + (jax.nn.gelu(h2 @ L["wi"] + L["bi"],
                                         approximate=True) @ L["wf"]
                             + L["bf"])
            x = fused_layer_norm(x, w["normw"], w["normb"], eps)
            if K:
                last = x[0]
            else:
                last = x[0, jnp.clip(seq_start + num_tokens - 1,
                                     0, T - 1)]
            logits = last @ (w["head"] if w["head"] is not None
                             else w["embed"].T)
            return logits, new_pools

        return step

    def _mla_unified_body(self):
        cfg = self._p["cfg"]
        nh = cfg.num_attention_heads
        dn, dr, dv = (cfg.qk_nope_head_dim, cfg.qk_rope_head_dim,
                      cfg.v_head_dim)
        r = cfg.kv_lora_rank
        eps = cfg.rms_norm_eps
        scale = 1.0 / float(math.sqrt(dn + dr))
        moe_static = self._p.get("moe_static")
        mega = self.megadecode
        megafront = self.megafront
        B, C, K = self.max_slots, self.prefill_chunk, self.spec_k
        R = 1 + K
        T = B * R + C
        # decode slot s owns rows [s*R, (s+1)*R); the prefill chunk owns
        # rows [B*R, B*R+C). R == 1 reduces to arange(B + 1).
        seq_start = jnp.concatenate(
            [jnp.arange(B, dtype=jnp.int32) * R,
             jnp.asarray([B * R], jnp.int32)])

        def step(w, tok, pools, positions, num_tokens, kv_lengths,
                 tables, tok_page, tok_off):
            x = w["embed"][tok][None]                    # [1, T, H]
            c = w["cos"][positions]                      # [T, dr/2]
            s = w["sin"][positions]

            def rope(t):                                 # [1, T, h, dr]
                d2 = t.shape[-1] // 2
                t1, t2 = t[..., :d2], t[..., d2:]
                cc = c[None, :, None, :].astype(t.dtype)
                ss = s[None, :, None, :].astype(t.dtype)
                return jnp.concatenate(
                    [t1 * cc - t2 * ss, t2 * cc + t1 * ss], -1)

            new_pools = []
            sts = moe_static or (None,) * len(w["layers"])
            for L, pool, st in zip(w["layers"], pools, sts):
                h = fused_rms_norm(x, L["ln1"], eps)
                wkb = _dq(L, "wkvb", x.dtype).reshape(r, nh, dn + dv)
                w_k, w_v = wkb[..., :dn], wkb[..., dn:]
                if megafront:
                    # ISSUE 20 front half: the [q | kv_a] slab
                    # projects, the q tail and k_pe rope, the latent
                    # rms-norms and the [latent | rope-key] pool row
                    # lands — one launch, q already at the attention
                    # granularity [T, nh, dn+dr]
                    wp, ws = _wq2(L, "wqkva")
                    q, pool = fused_qkv_rope_append(
                        h[0], wp, ws, None, c, s, pool, None,
                        tok_page, tok_off, heads=nh,
                        algo=_walgo(L, "wqkva"), norm_weight=L["gkv"],
                        eps=eps, nope_dim=dn, rope_dim=dr,
                        lora_rank=r)
                    q_eff = jnp.einsum("tnd,rnd->tnr", q[..., :dn],
                                       w_k)
                    q_cat = jnp.concatenate([q_eff, q[..., dn:]], -1)
                else:
                    if "wqa" in L or "wqa_q" in L or "wqa_q4" in L:
                        q = _mm_w(fused_rms_norm(_mm_w(h, L, "wqa"),
                                                 L["gq"], eps),
                                  L, "wqb")
                    else:
                        q = _mm_w(h, L, "wq")
                    q = q.reshape(1, T, nh, dn + dr)
                    q_nope, q_pe = q[..., :dn], q[..., dn:]
                    # rope runs on the split q_pe/k_pe shapes (not
                    # D-halved cache rows), so the append is the
                    # row-scatter kernel
                    q_pe = rope(q_pe)
                    kv_a = _mm_w(h, L, "wkva")           # [1, T, r+dr]
                    lat = fused_rms_norm(kv_a[..., :r], L["gkv"], eps)
                    k_pe = rope(kv_a[..., r:][:, :, None, :])[:, :, 0]
                    rows = jnp.concatenate([lat, k_pe], -1)[0][:, None]
                    pool = fused_append_rows(pool, rows, tok_page,
                                             tok_off)
                    q_eff = jnp.einsum("bsnd,rnd->bsnr", q_nope, w_k)
                    q_cat = jnp.concatenate([q_eff, q_pe], -1)[0]
                new_pools.append(pool)
                o_cat = ragged_paged_attention(q_cat, pool, pool,
                                               seq_start, num_tokens,
                                               kv_lengths, tables,
                                               scale=scale)
                o = jnp.einsum("tnr,rnv->tnv", o_cat[..., :r], w_v)
                if mega:
                    wp, ws = _wq2(L, "wo")
                    xn, h2 = fused_oproj_norm(
                        o.reshape(T, nh * dv), x[0], wp, ws, None,
                        L["ln2"], None, eps=eps, algo=_walgo(L, "wo"))
                    if "moe" in L:
                        x = xn[None] + _ffn_apply(L, h2[None], st)
                    else:
                        gp, gs = _wq2(L, "wg")
                        up, us = _wq2(L, "wu")
                        dp, ds = _wq2(L, "wd")
                        x = fused_ffn(h2, xn, gp, gs, up, us, dp, ds,
                                      algo=_walgo(L, "wg"))[None]
                else:
                    x = x + _mm_w(o.reshape(1, T, nh * dv), L, "wo")
                    h2 = fused_rms_norm(x, L["ln2"], eps)
                    x = x + _ffn_apply(L, h2, st)
            x = fused_rms_norm(x, w["norm"], eps)
            if K:
                last = x[0]
            else:
                last = x[0, jnp.clip(seq_start + num_tokens - 1,
                                     0, T - 1)]
            if "head_q" in w or "head_q4" in w:
                logits = _mm_w(last, w, "head")
            else:
                logits = last @ (w["head"] if w["head"] is not None
                                 else w["embed"].T)
            return logits, new_pools

        return step

    # -- llama / moe ---------------------------------------------------
    def _llama_decode_body(self):
        cfg = self._p["cfg"]
        Hh, KV, D = (cfg.num_attention_heads, cfg.num_key_value_heads,
                     cfg.head_dim)
        eps = cfg.rms_norm_eps
        moe_static = self._p.get("moe_static")
        from ..flags import flag, flags_guard
        # pinned at engine construction like the cached bodies' flash
        # pin: the jit traces lazily and must compile the impl this
        # engine was built under
        paged_impl = flag("FLAGS_paged_impl")

        def rms(h, wt):
            # routed through the fused Pallas kernel — same op order as
            # the inline form (ulp-level), one HBM round-trip
            return fused_rms_norm(h, wt, eps)

        def step(w, tok, pools, lengths, tables):
            B = tok.shape[0]
            x = w["embed"][tok][:, None]                 # [B, 1, H]
            c = w["cos"][lengths]                        # [B, D/2]
            s = w["sin"][lengths]

            def rope(t):                                 # [B, 1, h, D]
                d2 = t.shape[-1] // 2
                t1, t2 = t[..., :d2], t[..., d2:]
                cc = c[:, None, None, :].astype(t.dtype)
                ss = s[:, None, None, :].astype(t.dtype)
                return jnp.concatenate(
                    [t1 * cc - t2 * ss, t2 * cc + t1 * ss], -1)

            new_pools = []
            sts = moe_static or (None,) * len(w["layers"])
            with flags_guard(paged_impl=paged_impl):  # paddlelint: disable=PT005
                for L, (kp, vp), st in zip(w["layers"], pools, sts):
                    h = rms(x, L["ln1"])
                    q, k, v = (_mm_w(h, L, "wq"), _mm_w(h, L, "wk"),
                               _mm_w(h, L, "wv"))
                    if "bq" in L:
                        q, k, v = q + L["bq"], k + L["bk"], v + L["bv"]
                    q = rope(q.reshape(B, 1, Hh, D))
                    k = rope(k.reshape(B, 1, KV, D))
                    v = v.reshape(B, 1, KV, D)
                    kp, vp, _ = append_to_cache(kp, vp, k[:, 0], v[:, 0],
                                                lengths, tables)
                    new_pools.append((kp, vp))
                    o = paged_attention(q[:, 0], kp, vp, lengths + 1,
                                        tables, scale=D ** -0.5)
                    x = x + _mm_w(o.reshape(B, 1, Hh * D), L, "wo")
                    h2 = rms(x, L["ln2"])
                    x = x + _ffn_apply(L, h2, st)
            x = rms(x, w["norm"])
            last = x[:, -1]
            if "head_q" in w or "head_q4" in w:
                logits = _mm_w(last, w, "head")
            else:
                logits = last @ (w["head"] if w["head"] is not None
                                 else w["embed"].T)
            return logits, new_pools

        return step

    def _llama_prefill_body(self):
        cfg = self._p["cfg"]
        Hh, KV, D = (cfg.num_attention_heads, cfg.num_key_value_heads,
                     cfg.head_dim)
        eps = cfg.rms_norm_eps
        rep = Hh // KV
        moe_static = self._p.get("moe_static")
        C = self.prefill_chunk
        ps, nj = self.page_size, self.pages_per_seq
        T = nj * ps

        def rms(h, wt):
            # routed through the fused Pallas kernel — same op order as
            # the inline form (ulp-level), one HBM round-trip
            return fused_rms_norm(h, wt, eps)

        def prefill(w, ids, pools, table, start, n_valid):
            x = w["embed"][ids]                          # [1, C, H]
            pos = start + jnp.arange(C)
            posc = jnp.clip(pos, 0, w["cos"].shape[0] - 1)
            c, s = w["cos"][posc], w["sin"][posc]        # [C, D/2]

            def rope(t):                                 # [1, C, h, D]
                d2 = t.shape[-1] // 2
                t1, t2 = t[..., :d2], t[..., d2:]
                cc = c[None, :, None, :].astype(t.dtype)
                ss = s[None, :, None, :].astype(t.dtype)
                return jnp.concatenate(
                    [t1 * cc - t2 * ss, t2 * cc + t1 * ss], -1)

            valid = jnp.arange(C) < n_valid
            # pad positions write to the trash page; real positions to
            # this sequence's pages
            pg = jnp.where(valid, table[0, jnp.clip(pos // ps, 0, nj - 1)],
                           0)
            off = jnp.where(valid, pos % ps, 0)
            pos_t = jnp.arange(T)
            vis = pos_t[None, :] <= pos[:, None]         # [C, T]

            def write(pages, new):                       # new [C, kv, D]
                def body(pages, i):
                    return pages.at[:, pg[i], off[i], :].set(new[i]), None
                pages, _ = jax.lax.scan(body, pages, jnp.arange(C))
                return pages

            new_pools = []
            sts = moe_static or (None,) * len(w["layers"])
            for L, (kp, vp), st in zip(w["layers"], pools, sts):
                h = rms(x, L["ln1"])
                q, k, v = (_mm_w(h, L, "wq"), _mm_w(h, L, "wk"),
                           _mm_w(h, L, "wv"))
                if "bq" in L:
                    q, k, v = q + L["bq"], k + L["bk"], v + L["bv"]
                q = rope(q.reshape(1, C, Hh, D))
                k = rope(k.reshape(1, C, KV, D))
                v = v.reshape(1, C, KV, D)
                kp = write(kp, k[0])
                vp = write(vp, v[0])
                new_pools.append((kp, vp))
                ks = kp[:, table[0]].reshape(KV, T, D)
                vs = vp[:, table[0]].reshape(KV, T, D)
                qg = q.reshape(1, C, KV, rep, D)
                scores = jnp.einsum("bsgrd,gtd->bgrst", qg, ks) \
                    * (D ** -0.5)
                scores = jnp.where(vis[None, None, None],
                                   scores.astype(jnp.float32), -1e30)
                aw = jax.nn.softmax(scores, axis=-1).astype(vs.dtype)
                o = jnp.einsum("bgrst,gtd->bsgrd", aw, vs).reshape(
                    1, C, Hh * D)
                x = x + _mm_w(o, L, "wo")
                h2 = rms(x, L["ln2"])
                x = x + _ffn_apply(L, h2, st)
            x = rms(x, w["norm"])
            last = jax.lax.dynamic_index_in_dim(x[0], n_valid - 1, 0,
                                                keepdims=False)[None]
            if "head_q" in w or "head_q4" in w:
                logits = _mm_w(last, w, "head")
            else:
                logits = last @ (w["head"] if w["head"] is not None
                                 else w["embed"].T)
            return logits, new_pools

        return prefill

    # -- gpt -----------------------------------------------------------
    def _gpt_decode_body(self):
        cfg = self._p["cfg"]
        nh, hd = cfg.num_attention_heads, cfg.head_dim
        eps = cfg.layer_norm_eps
        from ..flags import flag, flags_guard
        paged_impl = flag("FLAGS_paged_impl")

        def ln(h, wt, b):
            # routed through the fused Pallas kernel — same op order as
            # the inline form (ulp-level), one HBM round-trip
            return fused_layer_norm(h, wt, b, eps)

        def step(w, tok, pools, lengths, tables):
            B = tok.shape[0]
            x = w["embed"][tok][:, None] + w["pos"][lengths][:, None]
            new_pools = []
            with flags_guard(paged_impl=paged_impl):  # paddlelint: disable=PT005
                for L, (kp, vp) in zip(w["layers"], pools):
                    h = ln(x, L["ln1w"], L["ln1b"])
                    qkv = h @ L["wqkv"] + L["bqkv"]
                    q, k, v = jnp.split(qkv, 3, axis=-1)
                    q = q.reshape(B, 1, nh, hd)
                    k = k.reshape(B, 1, nh, hd)
                    v = v.reshape(B, 1, nh, hd)
                    kp, vp, _ = append_to_cache(kp, vp, k[:, 0], v[:, 0],
                                                lengths, tables)
                    new_pools.append((kp, vp))
                    o = paged_attention(q[:, 0], kp, vp, lengths + 1,
                                        tables, scale=hd ** -0.5)
                    x = x + (o.reshape(B, 1, nh * hd) @ L["wo"] + L["bo"])
                    h2 = ln(x, L["ln2w"], L["ln2b"])
                    x = x + (jax.nn.gelu(h2 @ L["wi"] + L["bi"],
                                         approximate=True) @ L["wf"]
                             + L["bf"])
            x = ln(x, w["normw"], w["normb"])
            last = x[:, -1]
            logits = last @ (w["head"] if w["head"] is not None
                             else w["embed"].T)
            return logits, new_pools

        return step

    def _gpt_prefill_body(self):
        cfg = self._p["cfg"]
        nh, hd = cfg.num_attention_heads, cfg.head_dim
        eps = cfg.layer_norm_eps
        C = self.prefill_chunk
        ps, nj = self.page_size, self.pages_per_seq
        T = nj * ps

        def ln(h, wt, b):
            # routed through the fused Pallas kernel — same op order as
            # the inline form (ulp-level), one HBM round-trip
            return fused_layer_norm(h, wt, b, eps)

        def prefill(w, ids, pools, table, start, n_valid):
            pos = start + jnp.arange(C)
            posc = jnp.clip(pos, 0, w["pos"].shape[0] - 1)
            x = w["embed"][ids] + w["pos"][posc][None]
            valid = jnp.arange(C) < n_valid
            pg = jnp.where(valid, table[0, jnp.clip(pos // ps, 0, nj - 1)],
                           0)
            off = jnp.where(valid, pos % ps, 0)
            pos_t = jnp.arange(T)
            vis = pos_t[None, :] <= pos[:, None]

            def write(pages, new):
                def body(pages, i):
                    return pages.at[:, pg[i], off[i], :].set(new[i]), None
                pages, _ = jax.lax.scan(body, pages, jnp.arange(C))
                return pages

            new_pools = []
            for L, (kp, vp) in zip(w["layers"], pools):
                h = ln(x, L["ln1w"], L["ln1b"])
                qkv = h @ L["wqkv"] + L["bqkv"]
                q, k, v = jnp.split(qkv, 3, axis=-1)
                q = q.reshape(1, C, nh, hd)
                k = k.reshape(1, C, nh, hd)
                v = v.reshape(1, C, nh, hd)
                kp = write(kp, k[0])
                vp = write(vp, v[0])
                new_pools.append((kp, vp))
                ks = kp[:, table[0]].reshape(nh, T, hd)
                vs = vp[:, table[0]].reshape(nh, T, hd)
                scores = jnp.einsum("bshd,htd->bhst", q, ks) \
                    * (hd ** -0.5)
                scores = jnp.where(vis[None, None],
                                   scores.astype(jnp.float32), -1e30)
                aw = jax.nn.softmax(scores, axis=-1).astype(vs.dtype)
                o = jnp.einsum("bhst,htd->bshd", aw, vs).reshape(
                    1, C, nh * hd)
                x = x + (o @ L["wo"] + L["bo"])
                h2 = ln(x, L["ln2w"], L["ln2b"])
                x = x + (jax.nn.gelu(h2 @ L["wi"] + L["bi"],
                                     approximate=True) @ L["wf"]
                         + L["bf"])
            x = ln(x, w["normw"], w["normb"])
            last = jax.lax.dynamic_index_in_dim(x[0], n_valid - 1, 0,
                                                keepdims=False)[None]
            logits = last @ (w["head"] if w["head"] is not None
                             else w["embed"].T)
            return logits, new_pools

        return prefill

    # -- mla -----------------------------------------------------------
    def _mla_decode_body(self):
        cfg = self._p["cfg"]
        nh = cfg.num_attention_heads
        dn, dr, dv = (cfg.qk_nope_head_dim, cfg.qk_rope_head_dim,
                      cfg.v_head_dim)
        r = cfg.kv_lora_rank
        eps = cfg.rms_norm_eps
        scale = 1.0 / float(math.sqrt(dn + dr))
        moe_static = self._p.get("moe_static")
        from ..flags import flag, flags_guard
        paged_impl = flag("FLAGS_paged_impl")

        def rms(h, wt):
            # routed through the fused Pallas kernel — same op order as
            # the inline form (ulp-level), one HBM round-trip
            return fused_rms_norm(h, wt, eps)

        def step(w, tok, pools, lengths, tables):
            B = tok.shape[0]
            x = w["embed"][tok][:, None]
            c = w["cos"][lengths]                        # [B, dr/2]
            s = w["sin"][lengths]

            def rope(t):                                 # [B, 1, h, dr]
                d2 = t.shape[-1] // 2
                t1, t2 = t[..., :d2], t[..., d2:]
                cc = c[:, None, None, :].astype(t.dtype)
                ss = s[:, None, None, :].astype(t.dtype)
                return jnp.concatenate(
                    [t1 * cc - t2 * ss, t2 * cc + t1 * ss], -1)

            new_pools = []
            sts = moe_static or (None,) * len(w["layers"])
            with flags_guard(paged_impl=paged_impl):  # paddlelint: disable=PT005
                for L, pool, st in zip(w["layers"], pools, sts):
                    h = rms(x, L["ln1"])
                    if "wqa" in L or "wqa_q" in L or "wqa_q4" in L:
                        q = _mm_w(rms(_mm_w(h, L, "wqa"), L["gq"]),
                                  L, "wqb")
                    else:
                        q = _mm_w(h, L, "wq")
                    q = q.reshape(B, 1, nh, dn + dr)
                    q_nope, q_pe = q[..., :dn], q[..., dn:]
                    q_pe = rope(q_pe)
                    kv_a = _mm_w(h, L, "wkva")           # [B, 1, r+dr]
                    lat = rms(kv_a[..., :r], L["gkv"])
                    k_pe = rope(kv_a[..., r:][:, :, None, :])[:, :, 0]
                    row = jnp.concatenate([lat, k_pe], -1)[:, 0]
                    pool = append_to_cache(pool, pool, row[:, None],
                                           row[:, None], lengths,
                                           tables)[0]
                    new_pools.append(pool)
                    wkb = _dq(L, "wkvb", x.dtype).reshape(r, nh, dn + dv)
                    w_k, w_v = wkb[..., :dn], wkb[..., dn:]
                    # absorbed concat-dot: softmax((q_eff|q_pe)·row) over
                    # rows [lat|k_pe]; the weighted row sum sliced to the
                    # latent part IS the latent attention output
                    q_eff = jnp.einsum("bsnd,rnd->bsnr", q_nope, w_k)
                    q_cat = jnp.concatenate([q_eff, q_pe], -1)[:, 0]
                    o_cat = paged_attention(q_cat, pool, pool,
                                            lengths + 1, tables,
                                            scale=scale)
                    o = jnp.einsum("bnr,rnv->bnv", o_cat[..., :r], w_v)
                    x = x + _mm_w(o.reshape(B, 1, nh * dv), L, "wo")
                    h2 = rms(x, L["ln2"])
                    x = x + _ffn_apply(L, h2, st)
            x = rms(x, w["norm"])
            last = x[:, -1]
            if "head_q" in w or "head_q4" in w:
                logits = _mm_w(last, w, "head")
            else:
                logits = last @ (w["head"] if w["head"] is not None
                                 else w["embed"].T)
            return logits, new_pools

        return step

    def _mla_prefill_body(self):
        cfg = self._p["cfg"]
        nh = cfg.num_attention_heads
        dn, dr, dv = (cfg.qk_nope_head_dim, cfg.qk_rope_head_dim,
                      cfg.v_head_dim)
        r = cfg.kv_lora_rank
        eps = cfg.rms_norm_eps
        scale = 1.0 / float(math.sqrt(dn + dr))
        moe_static = self._p.get("moe_static")
        C = self.prefill_chunk
        ps, nj = self.page_size, self.pages_per_seq
        T = nj * ps

        def rms(h, wt):
            # routed through the fused Pallas kernel — same op order as
            # the inline form (ulp-level), one HBM round-trip
            return fused_rms_norm(h, wt, eps)

        def prefill(w, ids, pools, table, start, n_valid):
            x = w["embed"][ids]
            pos = start + jnp.arange(C)
            posc = jnp.clip(pos, 0, w["cos"].shape[0] - 1)
            c, s = w["cos"][posc], w["sin"][posc]

            def rope(t):                                 # [1, C, h, dr]
                d2 = t.shape[-1] // 2
                t1, t2 = t[..., :d2], t[..., d2:]
                cc = c[None, :, None, :].astype(t.dtype)
                ss = s[None, :, None, :].astype(t.dtype)
                return jnp.concatenate(
                    [t1 * cc - t2 * ss, t2 * cc + t1 * ss], -1)

            valid = jnp.arange(C) < n_valid
            pg = jnp.where(valid, table[0, jnp.clip(pos // ps, 0, nj - 1)],
                           0)
            off = jnp.where(valid, pos % ps, 0)
            pos_t = jnp.arange(T)
            vis = pos_t[None, :] <= pos[:, None]

            def write(pages, new):                       # new [C, 1, Dc]
                def body(pages, i):
                    return pages.at[:, pg[i], off[i], :].set(new[i]), None
                pages, _ = jax.lax.scan(body, pages, jnp.arange(C))
                return pages

            new_pools = []
            sts = moe_static or (None,) * len(w["layers"])
            for L, pool, st in zip(w["layers"], pools, sts):
                h = rms(x, L["ln1"])
                if "wqa" in L or "wqa_q" in L or "wqa_q4" in L:
                    q = _mm_w(rms(_mm_w(h, L, "wqa"), L["gq"]), L, "wqb")
                else:
                    q = _mm_w(h, L, "wq")
                q = q.reshape(1, C, nh, dn + dr)
                q_nope, q_pe = q[..., :dn], q[..., dn:]
                q_pe = rope(q_pe)
                kv_a = _mm_w(h, L, "wkva")               # [1, C, r+dr]
                lat = rms(kv_a[..., :r], L["gkv"])
                k_pe = rope(kv_a[..., r:][:, :, None, :])[:, :, 0]
                rows_new = jnp.concatenate([lat, k_pe], -1)  # [1, C, Dc]
                pool = write(pool, rows_new[0][:, None])
                new_pools.append(pool)
                wkb = _dq(L, "wkvb", x.dtype).reshape(r, nh, dn + dv)
                w_k, w_v = wkb[..., :dn], wkb[..., dn:]
                q_eff = jnp.einsum("bsnd,rnd->bsnr", q_nope, w_k)
                q_cat = jnp.concatenate([q_eff, q_pe], -1)  # [1,C,nh,Dc]
                rows = pool[0, table[0]].reshape(T, r + dr)
                scores = jnp.einsum("bsnd,td->bnst", q_cat, rows) * scale
                scores = jnp.where(vis[None, None],
                                   scores.astype(jnp.float32), -1e30)
                aw = jax.nn.softmax(scores, axis=-1).astype(rows.dtype)
                o_cat = jnp.einsum("bnst,td->bsnd", aw, rows)
                o = jnp.einsum("bsnr,rnv->bsnv", o_cat[..., :r], w_v)
                x = x + _mm_w(o.reshape(1, C, nh * dv), L, "wo")
                h2 = rms(x, L["ln2"])
                x = x + _ffn_apply(L, h2, st)
            x = rms(x, w["norm"])
            last = jax.lax.dynamic_index_in_dim(x[0], n_valid - 1, 0,
                                                keepdims=False)[None]
            if "head_q" in w or "head_q4" in w:
                logits = _mm_w(last, w, "head")
            else:
                logits = last @ (w["head"] if w["head"] is not None
                                 else w["embed"].T)
            return logits, new_pools

        return prefill
