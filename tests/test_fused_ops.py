"""Pallas fused kernels vs XLA references (SURVEY §4.1 OpTest triangle:
output parity + gradient parity; kernels run in interpret mode on CPU)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from paddle_tpu.ops.fused import (fused_layer_norm, fused_rms_norm,
                                  fused_rope, swiglu)
from paddle_tpu.ops.quant import (weight_only_linear, weight_quantize,
                                  weight_dequantize)
from paddle_tpu.ops.paged_attention import (append_to_cache,
                                            paged_attention,
                                            paged_attention_reference)


def _r(*shape, seed=0, scale=1.0):
    return jnp.asarray(
        np.random.RandomState(seed).randn(*shape).astype(np.float32) * scale)


class TestRmsNorm:
    def test_matches_reference(self):
        x = _r(4, 16, 64, seed=1)
        w = _r(64, seed=2) * 0.1 + 1.0
        out = fused_rms_norm(x, w, eps=1e-6)
        xf = x.astype(jnp.float32)
        ref = xf * jax.lax.rsqrt(jnp.mean(xf * xf, -1, keepdims=True) + 1e-6) * w
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-6)

    def test_grad_matches_autodiff_reference(self):
        x = _r(8, 32, seed=3)
        w = _r(32, seed=4) * 0.1 + 1.0

        def f_fused(x, w):
            return jnp.sum(fused_rms_norm(x, w) ** 2)

        def f_ref(x, w):
            y = x * jax.lax.rsqrt(jnp.mean(x * x, -1, keepdims=True) + 1e-6) * w
            return jnp.sum(y ** 2)
        gx1, gw1 = jax.grad(f_fused, argnums=(0, 1))(x, w)
        gx2, gw2 = jax.grad(f_ref, argnums=(0, 1))(x, w)
        np.testing.assert_allclose(np.asarray(gx1), np.asarray(gx2),
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(np.asarray(gw1), np.asarray(gw2),
                                   rtol=1e-4, atol=1e-5)

    def test_ulp_equal_to_inline_f32(self):
        # the serving engine's bodies route their inline rms through
        # this kernel: same op order (x * rsqrt(mean(x^2) + eps) * w),
        # so any difference is last-ulp reduction/FMA reassociation —
        # the engine-vs-solo exactness contract (greedy TOKEN equality)
        # is checked end-to-end in test_serving_engine.py
        x = _r(3, 7, 48, seed=5)
        w = _r(48, seed=6) * 0.1 + 1.0
        eps = 1e-6
        var = jnp.mean(jnp.square(x.astype(jnp.float32)), -1,
                       keepdims=True)
        inline = (x * jax.lax.rsqrt(var + eps).astype(x.dtype)) * w
        np.testing.assert_allclose(
            np.asarray(fused_rms_norm(x, w, eps)), np.asarray(inline),
            rtol=1e-6, atol=1e-6)


class TestLayerNorm:
    def test_matches_reference(self):
        x = _r(6, 48, seed=5)
        w = _r(48, seed=6) * 0.1 + 1.0
        b = _r(48, seed=7) * 0.1
        out = fused_layer_norm(x, w, b)
        mu = jnp.mean(x, -1, keepdims=True)
        var = jnp.mean((x - mu) ** 2, -1, keepdims=True)
        ref = (x - mu) * jax.lax.rsqrt(var + 1e-5) * w + b
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)

    def test_ulp_equal_to_inline_f32(self):
        # the serving engine's GPT bodies route their inline ln through
        # this kernel: same op order, so differences are last-ulp only
        # (greedy token-level exactness checked in test_serving_engine)
        x = _r(2, 5, 32, seed=8)
        w = _r(32, seed=9) * 0.1 + 1.0
        b = _r(32, seed=10) * 0.1
        eps = 1e-5
        h32 = x.astype(jnp.float32)
        mu = jnp.mean(h32, -1, keepdims=True)
        var = jnp.var(h32, -1, keepdims=True)
        inline = (((h32 - mu) * jax.lax.rsqrt(var + eps))
                  .astype(x.dtype) * w + b)
        np.testing.assert_allclose(
            np.asarray(fused_layer_norm(x, w, b, eps)),
            np.asarray(inline), rtol=1e-6, atol=1e-6)


class TestRope:
    def test_matches_model_reference(self):
        from paddle_tpu.models.llama import apply_rope, precompute_rope
        B, S, H, D = 2, 16, 4, 32
        q, k = _r(B, S, H, D, seed=8), _r(B, S, H, D, seed=9)
        cos, sin = precompute_rope(D, S, 10000.0)
        q2, k2 = fused_rope(q, k, cos, sin)
        np.testing.assert_allclose(np.asarray(q2),
                                   np.asarray(apply_rope(q, cos, sin)),
                                   rtol=1e-5, atol=1e-5)
        np.testing.assert_allclose(np.asarray(k2),
                                   np.asarray(apply_rope(k, cos, sin)),
                                   rtol=1e-5, atol=1e-5)

    def test_grad_is_inverse_rotation(self):
        from paddle_tpu.models.llama import precompute_rope
        B, S, H, D = 1, 8, 2, 16
        q = _r(B, S, H, D, seed=10)
        cos, sin = precompute_rope(D, S, 10000.0)

        def f(q):
            out, _ = fused_rope(q, q, cos, sin)
            return jnp.sum(out ** 2)
        g = jax.grad(f)(q)
        assert np.isfinite(np.asarray(g)).all()
        # rotation is orthogonal: |grad| == |2*rope(q)|
        out, _ = fused_rope(q, q, cos, sin)
        np.testing.assert_allclose(float(jnp.linalg.norm(g)),
                                   float(jnp.linalg.norm(2 * out)),
                                   rtol=1e-4)


class TestSwiglu:
    def test_matches_reference_both_signatures(self):
        g, u = _r(4, 32, seed=11), _r(4, 32, seed=12)
        ref = jax.nn.silu(g) * u
        np.testing.assert_allclose(np.asarray(swiglu(g, u)), np.asarray(ref),
                                   rtol=1e-5, atol=1e-6)
        packed = jnp.concatenate([g, u], axis=-1)
        np.testing.assert_allclose(np.asarray(swiglu(packed)),
                                   np.asarray(ref), rtol=1e-5, atol=1e-6)

    def test_grad_matches(self):
        g, u = _r(4, 16, seed=13), _r(4, 16, seed=14)
        g1 = jax.grad(lambda a, b: jnp.sum(swiglu(a, b) ** 2),
                      argnums=(0, 1))(g, u)
        g2 = jax.grad(lambda a, b: jnp.sum((jax.nn.silu(a) * b) ** 2),
                      argnums=(0, 1))(g, u)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-5)


class TestWeightOnly:
    def test_int8_quant_roundtrip_small_error(self):
        w = _r(64, 32, seed=15)
        qw, scale = weight_quantize(w, "weight_only_int8")
        assert qw.dtype == jnp.int8
        deq = weight_dequantize(qw, scale, "weight_only_int8")
        err = float(jnp.abs(deq - w).max() / jnp.abs(w).max())
        assert err < 0.01

    def test_int8_linear_close_to_fp(self):
        x = _r(8, 64, seed=16, scale=0.5)
        w = _r(64, 32, seed=17, scale=0.5)
        b = _r(32, seed=18, scale=0.1)
        qw, scale = weight_quantize(w, "weight_only_int8")
        out = weight_only_linear(x, qw, scale, bias=b)
        ref = x @ w + b
        rel = float(jnp.abs(out - ref).max() /
                    (jnp.abs(ref).max() + 1e-6))
        assert rel < 0.02, rel

    def test_int4_linear_runs(self):
        x = _r(4, 16, seed=19, scale=0.5)
        w = _r(16, 8, seed=20, scale=0.5)
        qw, scale = weight_quantize(w, "weight_only_int4")
        assert qw.shape == (8, 8)  # packed
        out = weight_only_linear(x, qw, scale, algo="weight_only_int4")
        ref = x @ w
        rel = float(jnp.abs(out - ref).max() / (jnp.abs(ref).max() + 1e-6))
        assert rel < 0.2  # int4 tolerance


class TestPagedAttention:
    def _setup(self, B=2, H=4, KV=2, D=16, page_size=4, pages_per_seq=3,
               seed=21):
        rng = np.random.RandomState(seed)
        total_pages = B * pages_per_seq
        kp = jnp.asarray(rng.randn(KV, total_pages, page_size, D)
                         .astype(np.float32) * 0.3)
        vp = jnp.asarray(rng.randn(KV, total_pages, page_size, D)
                         .astype(np.float32) * 0.3)
        pi = jnp.asarray(
            rng.permutation(total_pages).reshape(B, pages_per_seq)
            .astype(np.int32))
        lengths = jnp.asarray([7, 10], jnp.int32)
        q = jnp.asarray(rng.randn(B, H, D).astype(np.float32) * 0.3)
        return q, kp, vp, lengths, pi

    def test_reference_matches_dense(self):
        q, kp, vp, lengths, pi = self._setup()
        out = paged_attention_reference(q, kp, vp, lengths, pi)
        # dense check for sequence 0
        B, H, D = q.shape
        KV, _, psize, _ = kp.shape
        L = int(lengths[0])
        k_seq = np.concatenate([np.asarray(kp[:, int(p)]) for p in pi[0]],
                               axis=1)[:, :L]     # [KV, L, D]
        v_seq = np.concatenate([np.asarray(vp[:, int(p)]) for p in pi[0]],
                               axis=1)[:, :L]
        rep = H // KV
        k_seq = np.repeat(k_seq, rep, axis=0)
        v_seq = np.repeat(v_seq, rep, axis=0)
        s = np.einsum("hd,hkd->hk", np.asarray(q[0]), k_seq) * D ** -0.5
        p = np.exp(s - s.max(-1, keepdims=True))
        p /= p.sum(-1, keepdims=True)
        ref0 = np.einsum("hk,hkd->hd", p, v_seq)
        np.testing.assert_allclose(np.asarray(out[0]), ref0, rtol=1e-4,
                                   atol=1e-5)

    def test_public_entry_runs(self):
        q, kp, vp, lengths, pi = self._setup(seed=22)
        out = paged_attention(q, kp, vp, lengths, pi)
        assert out.shape == q.shape
        assert np.isfinite(np.asarray(out)).all()

    def test_append_to_cache(self):
        q, kp, vp, lengths, pi = self._setup(seed=23)
        B = q.shape[0]
        KV, D = kp.shape[0], kp.shape[-1]
        k_new = jnp.ones((B, KV, D), jnp.float32)
        v_new = 2 * jnp.ones((B, KV, D), jnp.float32)
        kp2, vp2, l2 = append_to_cache(kp, vp, k_new, v_new, lengths, pi)
        assert list(np.asarray(l2)) == [8, 11]
        # the written slot holds the new value
        b = 0
        slot = int(lengths[b])
        page = int(pi[b, slot // kp.shape[2]])
        off = slot % kp.shape[2]
        np.testing.assert_allclose(np.asarray(kp2[:, page, off]), 1.0)
        np.testing.assert_allclose(np.asarray(vp2[:, page, off]), 2.0)
