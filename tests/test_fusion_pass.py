"""CINN-parity fusion pass (SURVEY §2.1 'CINN fusion compiler' row):
jaxpr pattern matching + fused-kernel substitution, flag-gated like
FLAGS_use_cinn."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.jit.fusion import fuse, match_sdpa_patterns

R = np.random.RandomState(0)
B, H, S, D = 2, 2, 16, 8


def naive_sdpa(q, k, v):
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k) * (D ** -0.5)
    p = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v)


def _qkv(dtype=np.float32):
    return tuple(jnp.asarray(R.randn(B, H, S, D).astype(np.float32) * 0.3)
                 .astype(dtype) for _ in range(3))


def test_matcher_finds_sdpa_chain():
    q, k, v = _qkv()
    closed = jax.make_jaxpr(naive_sdpa)(q, k, v)
    ms = match_sdpa_patterns(closed.jaxpr)
    assert len(ms) == 1
    assert ms[0]["scale"] == pytest.approx(D ** -0.5)
    assert len(ms[0]["chain"]) >= 8  # interior softmax chain eliminated


def test_matcher_finds_bf16_chain_through_converts():
    q, k, v = _qkv(jnp.bfloat16)
    closed = jax.make_jaxpr(naive_sdpa)(q, k, v)
    assert len(match_sdpa_patterns(closed.jaxpr)) == 1


def test_matcher_ignores_non_sdpa():
    def plain(a, b):
        return jax.nn.softmax(a @ b, axis=-1).sum()
    a = jnp.zeros((4, 4))
    closed = jax.make_jaxpr(plain)(a, a)
    assert match_sdpa_patterns(closed.jaxpr) == []


def test_externally_used_interiors_disable_fusion():
    """If the probs are ALSO returned, the whole chain must execute anyway
    — fusing would only ADD work, so the matcher declines (no
    pessimization) and outputs stay exact."""
    def sdpa_and_probs(q, k, v):
        logits = jnp.einsum("bhqd,bhkd->bhqk", q, k) * 0.5
        p = jax.nn.softmax(logits, axis=-1)
        return jnp.einsum("bhqk,bhkd->bhqd", p, v), p
    q, k, v = _qkv()
    closed = jax.make_jaxpr(sdpa_and_probs)(q, k, v)
    assert match_sdpa_patterns(closed.jaxpr) == []
    out, probs = fuse(sdpa_and_probs)(q, k, v)
    ref_out, ref_p = sdpa_and_probs(q, k, v)
    np.testing.assert_allclose(np.asarray(probs), np.asarray(ref_p),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref_out),
                               rtol=1e-4, atol=1e-5)


def test_fused_matches_naive_numerics():
    q, k, v = _qkv()
    ref = naive_sdpa(q, k, v)
    out = fuse(naive_sdpa)(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-5)


def test_fused_under_jit_and_grad():
    q, k, v = _qkv()
    out = jax.jit(fuse(naive_sdpa))(q, k, v)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(naive_sdpa(q, k, v)),
                               rtol=1e-4, atol=1e-5)
    g = jax.grad(lambda q: fuse(naive_sdpa)(q, k, v).sum())(q)
    gref = jax.grad(lambda q: naive_sdpa(q, k, v).sum())(q)
    np.testing.assert_allclose(np.asarray(g), np.asarray(gref),
                               rtol=1e-3, atol=1e-4)


def test_surrounding_ops_preserved():
    """The pass must only touch the matched region."""
    def model(x, q, k, v):
        h = jnp.tanh(x)
        a = naive_sdpa(q, k, v)
        return (h.sum() + a.sum()) * 2.0
    q, k, v = _qkv()
    x = jnp.asarray(R.randn(3, 3).astype(np.float32))
    np.testing.assert_allclose(float(fuse(model)(x, q, k, v)),
                               float(model(x, q, k, v)), rtol=1e-5)


def test_flag_gated_in_to_static():
    """FLAGS_use_fusion_compiler routes to_static through the pass
    (FLAGS_use_cinn parity) without changing results."""
    from paddle_tpu import jit, nn

    class Attn(nn.Layer):
        def forward(self, q, k, v):
            return paddle.Tensor(naive_sdpa(q._data, k._data, v._data))

    q, k, v = (paddle.to_tensor(np.asarray(t)) for t in _qkv())
    ref = Attn()(q, k, v).numpy()
    paddle.set_flags({"FLAGS_use_fusion_compiler": True})
    try:
        m = jit.to_static(Attn())
        out = m(q, k, v).numpy()
    finally:
        paddle.set_flags({"FLAGS_use_fusion_compiler": False})
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)
