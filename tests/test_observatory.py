"""tools/observatory.py: the FLAGSHIP residual step-breakdown table is
GENERATED from `attribution.train_step_attribution` over the recorded
stats (byte-identical to the committed markdown — the hand-math era is
over), the in-place splice is idempotent, and the seeded serving
observatory reproduces the committed docs/OBSERVATORY.json artifact and
its 25% measured-vs-model acceptance gate."""

import json
import os
import shutil
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

import observatory  # noqa: E402

STATS = os.path.join(REPO, "docs", "FLAGSHIP_trace_stats.json")
FLAGSHIP = os.path.join(REPO, "docs", "FLAGSHIP.md")


class TestTrainMode:
    def test_recorded_stats_regenerate_committed_table(self):
        d, table = observatory.run_train(STATS)
        assert d["steps"] == 8
        assert d["wall_ms_per_step"] == pytest.approx(135.1)
        assert d["unattributed_ms_per_step"] == pytest.approx(2.5)
        # the regenerated markdown block is byte-identical to what
        # FLAGSHIP.md commits — the table is generated output
        with open(FLAGSHIP, encoding="utf-8") as f:
            assert table in f.read()

    def test_splice_is_idempotent_and_updates(self, tmp_path):
        md = str(tmp_path / "FLAGSHIP.md")
        shutil.copy(FLAGSHIP, md)
        _, table = observatory.run_train(STATS)
        assert observatory.splice_flagship_table(table, path=md) is False
        doctored = table.replace("**135.1**", "**999.9**")
        assert observatory.splice_flagship_table(doctored, path=md) is True
        with open(md, encoding="utf-8") as f:
            text = f.read()
        assert "**999.9**" in text and "**135.1**" not in text
        # and back again
        assert observatory.splice_flagship_table(table, path=md) is True

    def test_splice_without_table_raises(self, tmp_path):
        md = str(tmp_path / "no_table.md")
        with open(md, "w", encoding="utf-8") as f:
            f.write("# nothing here\n")
        _, table = observatory.run_train(STATS)
        with pytest.raises(SystemExit):
            observatory.splice_flagship_table(table, path=md)


@pytest.mark.slow
class TestServingMode:
    def test_seeded_run_reproduces_committed_artifact(self, tmp_path):
        out = str(tmp_path / "OBSERVATORY.json")
        assert observatory.main(["--out", out]) == 0
        with open(out, encoding="utf-8") as f:
            art = json.load(f)
        s = art["serving"]
        # the acceptance gate: measured bytes/token within 25% of the
        # costmodel budget on CPU interpret mode
        assert 0.75 <= s["measured_over_model"] <= 1.25
        # deterministic seed -> the analytical rows match the committed
        # artifact exactly (this is what perf_gate bands)
        with open(os.path.join(REPO, "docs", "OBSERVATORY.json"),
                  encoding="utf-8") as f:
            committed = json.load(f)
        mine = {(k["kernel"], k["launches"], k["bytes"])
                for k in art["kernels"]}
        theirs = {(k["kernel"], k["launches"], k["bytes"])
                  for k in committed["kernels"]}
        assert mine == theirs
        assert s["hbm_weights_bytes"] \
            == committed["serving"]["hbm_weights_bytes"]
        # and the fresh artifact round-trips through the perf gate
        import perf_gate
        assert perf_gate.main(["--repo", REPO, "--check", out]) == 0

    def test_train_mode_fresh_trace(self):
        # a fresh seeded 2-step tiny train loop attributes cleanly: all
        # four phases present, residual non-negative, wall > 0
        d, table = observatory.run_train(None, steps=2)
        assert d["steps"] >= 1
        assert d["wall_ms_per_step"] > 0
        assert [p["phase"] for p in d["phases"]] == ["data", "fwd",
                                                     "bwd", "opt"]
        assert d["unattributed_ms_per_step"] >= 0
        assert "| Phase | ms/step | % of wall |" in table
