"""Weight regularizers (ref: python/paddle/regularizer.py — L1Decay /
L2Decay attached per-param via ParamAttr.regularizer or passed to the
optimizer's weight_decay argument)."""

from __future__ import annotations

import jax.numpy as jnp

__all__ = ["L1Decay", "L2Decay"]


class _Regularizer:
    def __init__(self, coeff: float = 0.0):
        self._coeff = float(coeff)

    @property
    def coeff(self) -> float:
        return self._coeff

    def __repr__(self):
        return f"{type(self).__name__}(coeff={self._coeff})"


class L1Decay(_Regularizer):
    """loss += coeff * sum(|w|); grad contribution coeff * sign(w)."""

    def grad_term(self, param_data):
        return self._coeff * jnp.sign(param_data)

    def loss_term(self, param_data):
        return self._coeff * jnp.abs(param_data).sum()


class L2Decay(_Regularizer):
    """loss += coeff * 0.5 * sum(w^2); grad contribution coeff * w
    (the reference's L2DecayRegularizer; equivalent to decoupled weight
    decay only when lr-coupled — the optimizers' weight_decay argument
    implements the AdamW-style decoupled form)."""

    def grad_term(self, param_data):
        return self._coeff * param_data

    def loss_term(self, param_data):
        return self._coeff * 0.5 * jnp.square(param_data).sum()
