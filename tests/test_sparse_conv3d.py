"""Sparse conv3d tests (ref: paddle.sparse.nn.Conv3D/SubmConv3D,
paddle/phi/kernels/sparse/ conv kernels — SURVEY §2.1 sparse row).

Oracle: torch.nn.functional.conv3d on the densified voxel grid.
"""

import numpy as np
import torch

import paddle_tpu as paddle
from paddle_tpu import sparse


def _random_cloud(N, D, H, W, C, nnz, seed=0):
    rng = np.random.RandomState(seed)
    # unique voxel sites
    keys = rng.choice(N * D * H * W, size=nnz, replace=False)
    b = keys // (D * H * W)
    d = (keys // (H * W)) % D
    h = (keys // W) % H
    w = keys % W
    idx = np.stack([b, d, h, w]).astype(np.int64)  # [4, nnz]
    vals = rng.randn(nnz, C).astype(np.float32)
    st = sparse.sparse_coo_tensor(idx, vals, shape=(N, D, H, W, C))
    dense = np.zeros((N, D, H, W, C), np.float32)
    dense[b, d, h, w] = vals
    return st, dense


def _torch_conv(dense_ndhwc, weight, stride, padding):
    x = torch.tensor(dense_ndhwc).permute(0, 4, 1, 2, 3)  # NCDHW
    w = torch.tensor(weight).permute(4, 3, 0, 1, 2)       # [oc,ic,kd,kh,kw]
    y = torch.nn.functional.conv3d(x, w, stride=stride, padding=padding)
    return y.permute(0, 2, 3, 4, 1).numpy()               # NDHWC


def test_subm_conv3d_matches_dense_oracle_at_input_sites():
    paddle.seed(0)
    st, dense = _random_cloud(2, 6, 7, 5, 3, nnz=40)
    rng = np.random.RandomState(1)
    w = (rng.randn(3, 3, 3, 3, 4) * 0.2).astype(np.float32)
    out = sparse.subm_conv3d(st, paddle.to_tensor(w))
    ref = _torch_conv(dense, w, stride=1, padding=1)
    oi = np.asarray(out._bcoo.indices)
    ov = np.asarray(out._bcoo.data)
    # same active sites as the input (submanifold property)
    ii = np.asarray(st._bcoo.indices)
    assert sorted(map(tuple, oi.tolist())) == sorted(map(tuple, ii.tolist()))
    for (b, d, h, wd), v in zip(oi.tolist(), ov):
        np.testing.assert_allclose(v, ref[b, d, h, wd], rtol=1e-4,
                                   atol=1e-5)


def test_conv3d_matches_dense_oracle_everywhere():
    paddle.seed(0)
    st, dense = _random_cloud(1, 6, 6, 6, 2, nnz=20, seed=3)
    rng = np.random.RandomState(2)
    w = (rng.randn(3, 3, 3, 2, 5) * 0.3).astype(np.float32)
    out = sparse.conv3d(st, paddle.to_tensor(w), stride=1, padding=1)
    ref = _torch_conv(dense, w, stride=1, padding=1)
    assert out.shape == (1, 6, 6, 6, 5)
    oi = np.asarray(out._bcoo.indices)
    ov = np.asarray(out._bcoo.data)
    seen = np.zeros(ref.shape[:-1], bool)
    for (b, d, h, wd), v in zip(oi.tolist(), ov):
        np.testing.assert_allclose(v, ref[b, d, h, wd], rtol=1e-4,
                                   atol=1e-5)
        seen[b, d, h, wd] = True
    # every site the dense conv leaves nonzero is covered by the sparse out
    nonzero = np.abs(ref).max(-1) > 1e-6
    assert not np.any(nonzero & ~seen)


def test_conv3d_stride2():
    paddle.seed(0)
    st, dense = _random_cloud(1, 8, 8, 8, 2, nnz=30, seed=5)
    rng = np.random.RandomState(4)
    w = (rng.randn(3, 3, 3, 2, 3) * 0.3).astype(np.float32)
    out = sparse.conv3d(st, paddle.to_tensor(w), stride=2, padding=1)
    ref = _torch_conv(dense, w, stride=2, padding=1)
    assert out.shape == (1, 4, 4, 4, 3)
    oi = np.asarray(out._bcoo.indices)
    ov = np.asarray(out._bcoo.data)
    for (b, d, h, wd), v in zip(oi.tolist(), ov):
        np.testing.assert_allclose(v, ref[b, d, h, wd], rtol=1e-4,
                                   atol=1e-5)


def test_subm_layer_trains():
    """Gradient flows to weight/bias through the gather-matmul rulebook."""
    paddle.seed(0)
    st, _ = _random_cloud(1, 5, 5, 5, 3, nnz=15, seed=7)
    layer = sparse.nn.SubmConv3D(3, 4, kernel_size=3)
    out = layer(st)
    loss = out.values().pow(2).mean()
    loss.backward()
    g = layer.weight.grad
    assert g is not None
    assert float(np.abs(g.numpy()).max()) > 0
    assert layer.bias.grad is not None


def test_conv_layer_api():
    paddle.seed(0)
    st, _ = _random_cloud(1, 6, 6, 6, 2, nnz=12, seed=9)
    layer = sparse.nn.Conv3D(2, 4, kernel_size=3, stride=2, padding=1,
                             bias_attr=False)
    out = layer(st)
    assert out.shape == (1, 3, 3, 3, 4)
    assert layer.bias is None


def test_stacked_subm_convs_all_layers_train():
    """Review regression: grads must flow through CHAINED sparse convs (the
    values() tape-tensor path), not just the last layer."""
    paddle.seed(0)
    st, _ = _random_cloud(1, 5, 5, 5, 3, nnz=15, seed=11)
    l1 = sparse.nn.SubmConv3D(3, 4, kernel_size=3)
    l2 = sparse.nn.SubmConv3D(4, 2, kernel_size=3)
    out = l2(l1(st))
    loss = out.values().pow(2).mean()
    loss.backward()
    assert l1.weight.grad is not None
    assert float(np.abs(l1.weight.grad.numpy()).max()) > 0
    assert l2.weight.grad is not None


def test_conv_relu_conv_chain_trains():
    """Review regression: value-map ops (relu) between convs must carry the
    tape, not rebuild raw values."""
    paddle.seed(0)
    st, _ = _random_cloud(1, 5, 5, 5, 3, nnz=15, seed=13)
    l1 = sparse.nn.SubmConv3D(3, 4, kernel_size=3)
    l2 = sparse.nn.SubmConv3D(4, 2, kernel_size=3)
    out = l2(sparse.relu(l1(st)))
    loss = out.to_dense().pow(2).mean()  # dense head path also on the tape
    loss.backward()
    assert l1.weight.grad is not None
    assert float(np.abs(l1.weight.grad.numpy()).max()) > 0


def test_sparse_convs_are_layers():
    """Review regression: enclosing nn.Layer models must see conv params."""
    import paddle_tpu.nn as nn

    class Backbone(nn.Layer):
        def __init__(self):
            super().__init__()
            self.c1 = sparse.nn.SubmConv3D(3, 4, kernel_size=3)
            self.c2 = sparse.nn.Conv3D(4, 2, kernel_size=3, stride=2,
                                       padding=1)

        def forward(self, x):
            return self.c2(self.c1(x))

    m = Backbone()
    params = m.parameters()
    assert len(params) == 4  # 2 weights + 2 biases
    sd = m.state_dict()
    assert any("c1" in k for k in sd)


def test_huge_grid_key_overflow_raises():
    idx = np.zeros((4, 2), np.int64)
    idx[:, 1] = 1
    st = sparse.sparse_coo_tensor(idx, np.ones((2, 1), np.float32),
                                  shape=(2, 1300, 1300, 1300, 1))
    w = np.zeros((3, 3, 3, 1, 1), np.float32)
    import pytest
    with pytest.raises(ValueError, match="int32"):
        sparse.subm_conv3d(st, w)


def test_csr_value_map_to_dense_keeps_tape():
    """Review regression: CSR relu -> to_dense must keep the autograd tape
    (the COO fix's CSR sibling)."""
    import paddle_tpu.nn  # noqa: F401
    dense_w = paddle.to_tensor(np.ones((2, 2), np.float32))
    dense_w.stop_gradient = False
    csr = sparse.sparse_csr_tensor([0, 1, 2], [0, 1],
                                   np.array([2.0, -3.0], np.float32), (2, 2))
    # build values that depend on a differentiable tensor
    from paddle_tpu.core.dispatch import apply as _apply
    vals = _apply("mk_vals", lambda w: w.reshape(-1)[:2], [dense_w])
    csr._values_tensor = vals
    csr._values = vals._data
    out = sparse.relu(csr)
    dense = out.to_dense()
    loss = dense.pow(2).mean()
    loss.backward()
    assert dense_w.grad is not None
    assert float(np.abs(dense_w.grad.numpy()).max()) > 0
