"""paddle.geometric parity (ref: python/paddle/geometric/ — graph segment
ops + message passing; SURVEY §2.2 misc numerics). XLA segment primitives
replace the CUDA scatter kernels."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.dispatch import apply
from ..core.tensor import Tensor

__all__ = ["segment_sum", "segment_mean", "segment_max", "segment_min",
           "send_u_recv", "send_ue_recv"]


def _arr(x):
    return x._data if isinstance(x, Tensor) else jnp.asarray(x)


def _segment(name, reducer, x, segment_ids, num_segments=None):
    ids = _arr(segment_ids).astype(jnp.int32)
    n = int(num_segments) if num_segments is not None else \
        int(jnp.max(ids)) + 1

    def impl(a):
        return reducer(a, ids, n)
    return apply(name, impl, [x])


def segment_sum(data, segment_ids, name=None):
    return _segment("segment_sum", lambda a, i, n:
                    jax.ops.segment_sum(a, i, n), data, segment_ids)


def segment_mean(data, segment_ids, name=None):
    def red(a, i, n):
        s = jax.ops.segment_sum(a, i, n)
        c = jax.ops.segment_sum(jnp.ones((a.shape[0],) + (1,) * (a.ndim - 1),
                                         a.dtype), i, n)
        return s / jnp.maximum(c, 1)
    return _segment("segment_mean", red, data, segment_ids)


def segment_max(data, segment_ids, name=None):
    return _segment("segment_max", lambda a, i, n:
                    jax.ops.segment_max(a, i, n), data, segment_ids)


def segment_min(data, segment_ids, name=None):
    return _segment("segment_min", lambda a, i, n:
                    jax.ops.segment_min(a, i, n), data, segment_ids)


def send_u_recv(x, src_index, dst_index, reduce_op: str = "sum",
                out_size=None, name=None):
    """Graph message passing (ref: paddle.geometric.send_u_recv): gather
    x[src], segment-reduce onto dst."""
    src = _arr(src_index).astype(jnp.int32)
    dst = _arr(dst_index).astype(jnp.int32)
    xa = _arr(x)
    n = int(out_size) if out_size is not None else xa.shape[0]
    red = {"sum": jax.ops.segment_sum, "max": jax.ops.segment_max,
           "min": jax.ops.segment_min}.get(reduce_op)

    def impl(a):
        msgs = a[src]
        if reduce_op == "mean":
            s = jax.ops.segment_sum(msgs, dst, n)
            c = jax.ops.segment_sum(
                jnp.ones((msgs.shape[0],) + (1,) * (msgs.ndim - 1),
                         msgs.dtype), dst, n)
            return s / jnp.maximum(c, 1)
        return red(msgs, dst, n)
    return apply("send_u_recv", impl, [x])


def send_ue_recv(x, y, src_index, dst_index, message_op: str = "add",
                 reduce_op: str = "sum", out_size=None, name=None):
    """Messages combine node features x[src] with edge features y."""
    src = _arr(src_index).astype(jnp.int32)
    dst = _arr(dst_index).astype(jnp.int32)
    xa = _arr(x)
    n = int(out_size) if out_size is not None else xa.shape[0]
    red = {"sum": jax.ops.segment_sum, "max": jax.ops.segment_max,
           "min": jax.ops.segment_min}[reduce_op]

    def impl(a, e):
        m = a[src]
        m = m + e if message_op == "add" else m * e
        return red(m, dst, n)
    return apply("send_ue_recv", impl, [x, y])
